"""Loader shard I/O pipeline: read-ahead prefetch + generation-keyed
read-through shard cache + decode-ahead over the storage backend.

The loader's shard order is fully deterministic before the epoch starts
(seeded world shuffle -> dp-group stride -> worker stride, see
:class:`..loader.datasets.ParquetDataset`), so read-ahead is EXACT —
never speculative. This module exploits that three ways:

1. **Prefetch** — a small pool of fetcher threads walks the worker's
   shard list depth-K ahead of the consumer, pulling raw shard bytes
   through ``resilience.io.read_shard_bytes`` (the StorageBackend seam).
   Fetch indices are claimed from a shared counter but DELIVERED
   strictly in file order, so batch bytes are independent of thread
   scheduling. The depth bounds in-flight + undelivered shards, which
   bounds memory.
2. **Shard cache** — a process-wide read-through LRU over raw shard
   bytes, keyed ``(path, version)`` where the version is the object's
   commit generation on the mock store (the ETag) or a (size, mtime_ns)
   stat pair on POSIX. Every lookup starts with a cheap
   ``object_head`` version probe, so after ``maybe_refresh`` picks up a
   new generation a pre-advance cache entry can never be served — the
   key mismatch reads as a miss and refetches.
3. **Decode-ahead** — one decode thread turns fetched bytes into Arrow
   tables through a depth-1 queue (the preprocess sink's double buffer,
   inverse direction), so parquet decode of shard N+1 overlaps
   consumption of shard N.

Byte identity: shards are consumed in exactly the order the synchronous
path reads them and the bytes come from the same backend reads, so the
sample stream is identical with the pipeline on or off (pinned by
tests/test_shardcache.py and benchmarks/cache_smoke.py).

Env knobs (resolved once per stream, BEFORE any worker thread spawns)::

    LDDL_TPU_LOADER_PREFETCH_SHARDS  read-ahead depth K (default 4;
                                     0 disables the threaded pipeline —
                                     shards are read synchronously, and
                                     on the local backend that path is
                                     the pre-pipeline ``read_table``
                                     code verbatim)
    LDDL_TPU_LOADER_CACHE_BYTES      shard-cache budget in bytes
                                     (default 256 MiB; 0 disables
                                     caching)

Telemetry (all inert on batch bytes, gated by ``observability.enabled``):
``loader_shard_cache_{hits,misses,evictions}_total``,
``loader_shard_cache_bytes`` (gauge),
``loader_prefetch_shard_wait_seconds_total`` (consumer blocked waiting
for a prefetched shard), and the ``shard_fetch`` attribution stage
(fetch self-time on the prefetcher threads).
"""

import collections
import os
import queue
import threading

from .. import observability as obs
from ..resilience import io as rio

DEFAULT_PREFETCH_SHARDS = 4
DEFAULT_CACHE_BYTES = 256 << 20
# Concurrent backend fetches per stream: enough to overlap several
# round trips of per-op latency, few enough that K streams (elastic
# workers) don't swamp the box — cpus.loader_io_threads() folds this
# into pool-sizing budgets.
MAX_FETCH_THREADS = 4

WAIT_METRIC = "loader_prefetch_shard_wait_seconds_total"


def _env_int(name, default):
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        return default


def pipeline_config():
    """(prefetch_depth, cache_budget_bytes) resolved from the env ONCE —
    callers resolve before spawning any thread, so the analyzer's
    env-read-after-spawn rule holds by construction."""
    depth = max(0, _env_int("LDDL_TPU_LOADER_PREFETCH_SHARDS",
                            DEFAULT_PREFETCH_SHARDS))
    budget = max(0, _env_int("LDDL_TPU_LOADER_CACHE_BYTES",
                             DEFAULT_CACHE_BYTES))
    return depth, budget


def io_thread_count(depth=None):
    """Threads ONE loader stream adds at ``depth`` (default: the env
    knob): the fetcher pool plus the decode-ahead thread; 0 when the
    pipeline is disabled. Pool-sizing call sites subtract this so
    elastic workers x loader threads never oversubscribe the affinity
    mask."""
    if depth is None:
        depth = pipeline_config()[0]
    if depth <= 0:
        return 0
    return min(depth, MAX_FETCH_THREADS) + 1


class ShardCache:
    """Process-wide read-through LRU over raw shard bytes, keyed
    ``(path, version)``.

    ``get`` starts with a version probe (``object_head`` — a commit-
    record read on the mock store, a stat on POSIX; never data bytes),
    so a republished object (new generation / changed stat) always
    misses and refetches: generation-following can never be served a
    stale shard. Fetches run OUTSIDE the lock — concurrent prefetch
    threads fetch distinct shards in parallel — and insert-side
    eviction keeps total bytes within the budget."""

    def __init__(self, budget_bytes):
        self._lock = threading.Lock()
        self._entries = collections.OrderedDict()  # (path, version) -> bytes
        self._bytes = 0
        self._budget = int(budget_bytes)

    @property
    def budget_bytes(self):
        return self._budget

    def cached_bytes(self):
        with self._lock:
            return self._bytes

    def __len__(self):
        with self._lock:
            return len(self._entries)

    def get(self, path):
        """The CURRENT version of ``path``'s bytes, from cache when the
        live version matches a cached key, from the backend otherwise."""
        _, version = rio.object_head(path)
        key = (path, version)
        with self._lock:
            data = self._entries.get(key)
            if data is not None:
                self._entries.move_to_end(key)
        if data is not None:
            if obs.enabled():
                obs.inc("loader_shard_cache_hits_total")
            return data
        data, fetched_version = rio.read_shard_bytes(path)
        self._insert(path, fetched_version, data)
        if obs.enabled():
            obs.inc("loader_shard_cache_misses_total")
        return data

    def _insert(self, path, version, data):
        evicted = 0
        with self._lock:
            key = (path, version)
            # An over-budget single shard is served but never cached; a
            # racing duplicate fetch keeps the first copy.
            if key not in self._entries and len(data) <= self._budget:
                self._entries[key] = data
                self._bytes += len(data)
                while self._bytes > self._budget and self._entries:
                    _, old = self._entries.popitem(last=False)
                    self._bytes -= len(old)
                    evicted += 1
            size = self._bytes
        if obs.enabled():
            if evicted:
                obs.inc("loader_shard_cache_evictions_total", evicted)
            obs.set_gauge("loader_shard_cache_bytes", size)


# Process-wide cache singleton, shared by every stream (thread-mode
# workers, warm epochs) and rebuilt when the budget knob changes (tests
# flip it). Same recognized guarded-singleton shape as
# backend._instances.
_cache = None
_cache_lock = threading.Lock()


def shared_cache(budget_bytes):
    global _cache
    with _cache_lock:
        if _cache is None or _cache.budget_bytes != budget_bytes:
            _cache = ShardCache(budget_bytes)
        return _cache


class _ShardStream:
    """Depth-K ordered shard fetch + decode-ahead for one worker's file
    list. Up to :data:`MAX_FETCH_THREADS` backend reads run concurrently
    (one thread cannot hide per-op latency: sequential round trips
    serialize), but results are handed to the single decode thread
    strictly in file order, and the decode thread feeds the consumer
    through a depth-1 queue."""

    def __init__(self, files, depth, cache):
        self._files = list(files)
        self._depth = max(1, int(depth))
        self._cache = cache
        self._stop = threading.Event()
        # One permit per undelivered in-flight shard: acquired before a
        # fetch index is claimed, released when the decode thread takes
        # delivery — bounds fetched-but-unconsumed bytes to depth shards.
        self._slots = threading.Semaphore(self._depth)
        self._cond = threading.Condition()
        self._next_index = 0
        self._results = {}
        self._obs_on = obs.enabled()
        self._stage = None
        self._wait_counter = None
        if self._obs_on:
            from ..observability import attribution
            self._stage = attribution.stage_counter()
            self._wait_counter = obs.registry().counter(
                WAIT_METRIC,
                help="consumer wall seconds blocked waiting for a "
                     "prefetched shard")
        nthreads = min(self._depth, MAX_FETCH_THREADS,
                       max(1, len(self._files)))
        self._fetchers = [
            threading.Thread(target=self._fetch_loop, daemon=True,
                             name="lddl-shard-fetch-{}".format(i))
            for i in range(nthreads)]
        self._tables = queue.Queue(maxsize=1)
        self._decoder = threading.Thread(target=self._decode_loop,
                                         daemon=True,
                                         name="lddl-shard-decode")

    # ------------------------------------------------------------ fetch

    def _fetch_one(self, path):
        if self._cache is not None:
            return self._cache.get(path)
        data, _ = rio.read_shard_bytes(path)
        return data

    def _fetch_loop(self):
        import time as _time
        pc = _time.perf_counter
        while not self._stop.is_set():
            # Bounded acquire so an abandoned stream (consumer closed the
            # generator early) never leaves a thread parked forever.
            if not self._slots.acquire(timeout=0.1):
                continue
            with self._cond:
                i = self._next_index
                if i >= len(self._files):
                    self._slots.release()
                    return
                self._next_index += 1
            try:
                t0 = pc() if self._obs_on else 0.0
                out = ("ok", self._fetch_one(self._files[i].path))
                if self._obs_on:
                    self._stage.inc(pc() - t0, stage="shard_fetch")
            except BaseException as e:  # noqa: BLE001 - forwarded below
                out = ("error", e)
            with self._cond:
                self._results[i] = out
                self._cond.notify_all()

    def _take_fetched(self, i):
        """Decode-thread side of ordered delivery: block for index
        ``i``, release its depth slot, re-raise forwarded errors."""
        with self._cond:
            while i not in self._results:
                self._cond.wait(timeout=0.1)
                if self._stop.is_set() and i not in self._results:
                    raise RuntimeError("shard pipeline stopped")
            out = self._results.pop(i)
        self._slots.release()
        if out[0] == "error":
            raise out[1]
        return out[1]

    # ----------------------------------------------------------- decode

    def _decode_loop(self):
        import pyarrow as pa
        import pyarrow.parquet as pq

        def put(item):
            while not self._stop.is_set():
                try:
                    self._tables.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        try:
            for i, f in enumerate(self._files):
                data = self._take_fetched(i)
                table = pq.read_table(pa.BufferReader(data))
                if not put(("table", f, table)):
                    return
            put(("end", None, None))
        except BaseException as e:  # noqa: BLE001 - forwarded to consumer
            put(("error", None, e))

    # ---------------------------------------------------------- consume

    def __iter__(self):
        import time as _time
        pc = _time.perf_counter
        for t in self._fetchers:
            t.start()
        self._decoder.start()
        try:
            while True:
                t0 = pc() if self._obs_on else 0.0
                kind, f, payload = self._tables.get()
                if self._obs_on:
                    dt = pc() - t0
                    # The residual consumer-side blocking wait — what is
                    # left of shard_read once fetch+decode run ahead.
                    self._stage.inc(dt, stage="shard_read")
                    self._wait_counter.inc(dt)
                if kind == "error":
                    raise payload
                if kind == "end":
                    return
                yield f, payload
        finally:
            self._stop.set()
            self._decoder.join(timeout=5)
            for t in self._fetchers:
                t.join(timeout=5)


def _sync_tables(files, cache, logger):
    """The pipeline-off path. Local backend + no cache is the
    pre-pipeline ``read_table`` code verbatim (byte- and
    syscall-identical); a non-local backend (or an armed cache) routes
    the synchronous read through the versioned backend primitive so
    every loader shard byte still crosses the StorageBackend seam."""
    import time as _time
    obs_on = obs.enabled()
    stage = None
    pc = _time.perf_counter
    if obs_on:
        from ..observability import attribution
        stage = attribution.stage_counter()
    use_backend = cache is not None or rio.backend_if_nonlocal() is not None
    if use_backend:
        import pyarrow as pa
        import pyarrow.parquet as pq
    for f in files:
        if logger is not None:
            logger.to("worker").info("Reading {}".format(f.path))
        t0 = pc() if obs_on else 0.0
        if use_backend:
            data = (cache.get(f.path) if cache is not None
                    else rio.read_shard_bytes(f.path)[0])
            table = pq.read_table(pa.BufferReader(data))
        else:
            # Resilient shard read: transient EIO/ESTALE retries with
            # backoff instead of killing the epoch (resilience.io).
            table = rio.read_table(f.path)
        if obs_on:
            stage.inc(pc() - t0, stage="shard_read")
        yield f, table


def shard_tables(files, logger=None):
    """Iterate ``(file, pyarrow.Table)`` over ``files`` in order through
    the shard I/O pipeline — the loader's one shard-acquisition seam
    (ShuffleBuffer consumes this). Pipeline knobs are resolved here,
    before any thread spawns."""
    depth, budget = pipeline_config()
    cache = shared_cache(budget) if budget > 0 else None
    if depth <= 0 or not files:
        for item in _sync_tables(files, cache, logger):
            yield item
        return
    stream = iter(_ShardStream(files, depth, cache))
    try:
        for f, table in stream:
            if logger is not None:
                logger.to("worker").info("Reading {}".format(f.path))
            yield f, table
    finally:
        # Deterministic teardown on early consumer exit (ShuffleBuffer
        # returns mid-epoch once its yield quota is met): closing the
        # inner generator runs _ShardStream's stop/join finally NOW, not
        # at GC time.
        stream.close()
