"""Queue serialization for process-mode workers.

Batches cross the worker->consumer mp.Queue as ONE framed bytes payload
built with pickle protocol 5 **out-of-band buffers**: the pickle stream
carries only the object skeleton, while every numpy array body (batch
dict values, schema-v2 raw-sample id views) is appended as a raw buffer
frame — no re-pickle of a dict-of-lists, no per-array copy into the
pickler's growing buffer. On the consumer side the frame is decoded
**zero-copy**: arrays are reconstructed as views into one writable
bytearray, so the only consumer-side copy is the single
bytes->bytearray transfer of the frame itself.

Frame layout (little-endian)::

    u32 part_count
    u64 part_len * part_count      (part 0 = pickle payload, 1.. = buffers)
    part bytes, concatenated

Used by loader.dataloader._stream_one_epoch / _iter_process; thread mode
never serializes (batches are shared memory). Both modes produce
byte-identical batches (tests/test_schema_v2.py).
"""

import pickle
import struct


def encode(obj):
    """Object -> one framed bytes payload (pickle-5 out-of-band)."""
    buffers = []
    payload = pickle.dumps(obj, protocol=5, buffer_callback=buffers.append)
    parts = [payload] + [b.raw() for b in buffers]
    header = [struct.pack("<I", len(parts))]
    header += [struct.pack("<Q", p.nbytes if isinstance(p, memoryview)
                           else len(p)) for p in parts]
    return b"".join(header + parts)


def decode(data):
    """Framed bytes -> object, arrays reconstructed as writable views
    into one backing bytearray (single copy of the frame, none per
    array)."""
    mv = memoryview(bytearray(data))
    (count,) = struct.unpack_from("<I", mv, 0)
    offset = 4 + 8 * count
    lens = struct.unpack_from("<{}Q".format(count), mv, 4)
    parts = []
    for length in lens:
        parts.append(mv[offset:offset + length])
        offset += length
    return pickle.loads(parts[0], buffers=parts[1:])
