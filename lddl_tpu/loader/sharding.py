"""Mesh-aware batch placement: host-local numpy -> globally-sharded jax.Array.

This is the TPU-native generalization of the reference's dp_rank contract
(lddl/torch_mp/): instead of the user wiring up process groups, everything
derives from the device mesh —

- ``process_dp_info(mesh)``: which data-parallel group does *this process*
  feed, and how many groups are there? Processes whose addressable devices
  cover the same batch blocks are TP/PP/SP peers: they get the same
  dp_rank, hence identical host batches.
- ``to_device_batch(batch, mesh)``: assemble each host's identical-or-
  distinct local batch into one global jax.Array sharded over the mesh's
  data axes (replicated over model axes) via
  ``jax.make_array_from_process_local_data``.
"""

import numpy as np

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from ..parallel.mesh import data_axes_of, mesh_data_axes


def _batch_block_of_device(device_shape, axis_names, coords, data_axes):
    """Index of the batch block a device at ``coords`` consumes, i.e. its
    position along the flattened data axes."""
    block = 0
    for axis in data_axes:
        axis_idx = axis_names.index(axis)
        block = block * device_shape[axis_idx] + coords[axis_idx]
    return block


def dp_info_of_process(device_array, axis_names, process_index):
    """Core grouping rule of ``process_dp_info`` over a plain ndarray of
    device-like objects (anything with a ``process_index`` attribute) —
    callable with synthetic devices to validate a mesh layout without a
    real multi-process runtime.

    Grouping rule: two processes belong to the same data-parallel group iff
    their devices cover exactly the same set of batch blocks. Groups are
    ordered by their smallest block so dp_rank is stable and identical on
    every process.
    """
    axis_names = tuple(axis_names)
    data_axes = data_axes_of(axis_names)
    if not data_axes:
        return 0, 1
    blocks_by_process = {}
    for coords in np.ndindex(*device_array.shape):
        device = device_array[coords]
        block = _batch_block_of_device(device_array.shape, axis_names,
                                       coords, data_axes)
        blocks_by_process.setdefault(device.process_index, set()).add(block)

    groups = {}
    for proc, blocks in blocks_by_process.items():
        groups.setdefault(frozenset(blocks), []).append(proc)
    ordered = sorted(groups.keys(), key=min)
    # Sanity: block sets must tile the batch without overlap.
    seen = set()
    for blocks in ordered:
        if seen & blocks:
            raise ValueError(
                "mesh layout maps one batch block to multiple process "
                "groups; choose a mesh whose data axes align with hosts")
        seen |= blocks

    for dp_rank, blocks in enumerate(ordered):
        if process_index in groups[blocks]:
            return dp_rank, len(ordered)
    raise RuntimeError(
        "process {} owns no devices in the mesh".format(process_index))


def process_dp_info(mesh):
    """(dp_rank, num_dp_groups) of the calling process for ``mesh``.

    See ``dp_info_of_process`` for the grouping rule; this binds it to the
    real mesh and ``jax.process_index()``.
    """
    return dp_info_of_process(mesh.devices, mesh.axis_names,
                              jax.process_index())


def batch_sharding(mesh, rank=2):
    """NamedSharding for a [batch, ...] array: dim 0 over the data axes,
    everything else replicated."""
    data_axes = mesh_data_axes(mesh)
    spec = P(data_axes if data_axes else None, *([None] * (rank - 1)))
    return NamedSharding(mesh, spec)


def to_device_batch(batch, mesh):
    """Host-local numpy batch dict -> dict of global jax.Arrays sharded
    over the mesh's data axes.

    Every process passes the batch for its own dp group (identical within
    a group); the result is the concatenated global batch of size
    ``local_batch * num_dp_groups``, device-sharded without any gather.

    IMPORTANT (multi-host): every process must supply arrays of identical
    non-batch shape. Batch-max padding varies with each dp group's data, so
    multi-group meshes must use the loader's ``fixed_seq_lengths`` (which
    you want on TPU anyway — bounded XLA compilation count).
    """
    out = {}
    for key, value in batch.items():
        value = np.asarray(value)
        sharding = batch_sharding(mesh, rank=value.ndim)
        global_shape = None  # infer: local batch extends dim 0 per process
        out[key] = jax.make_array_from_process_local_data(
            sharding, value, global_shape)
    return out


def to_device_step_batches(batches, mesh):
    """Stacked host-local batches ``{k: [n_steps, local_batch, ...]}`` ->
    global jax.Arrays for models.make_sharded_multi_step: dim 0 (steps)
    replicated, dim 1 (batch) sharded over the mesh's data axes. Same
    per-process contract as to_device_batch, shifted one axis right."""
    data_axes = mesh_data_axes(mesh)
    out = {}
    for key, value in batches.items():
        value = np.asarray(value)
        spec = P(None, data_axes if data_axes else None,
                 *([None] * (value.ndim - 2)))
        out[key] = jax.make_array_from_process_local_data(
            NamedSharding(mesh, spec), value, None)
    return out
