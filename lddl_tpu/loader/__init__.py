from .datasets import ShuffleBuffer, ParquetDataset
from .dataloader import DataLoader, Binned, prefetch_to_device
from .bert import (get_bert_pretrain_data_loader, BertPretrainBinned,
                   BertPackedCollate, PackedBertLoader)
from .bart import get_bart_pretrain_data_loader, BartCollate
from .sharding import (dp_info_of_process, process_dp_info, to_device_batch,
                       to_device_step_batches)

__all__ = [
    "ShuffleBuffer",
    "ParquetDataset",
    "DataLoader",
    "Binned",
    "get_bert_pretrain_data_loader",
    "get_bart_pretrain_data_loader",
    "BartCollate",
    "BertPretrainBinned",
    "BertPackedCollate",
    "PackedBertLoader",
    "dp_info_of_process",
    "prefetch_to_device",
    "process_dp_info",
    "to_device_batch",
    "to_device_step_batches",
]
