"""BART denoising data loader: text infilling + sentence permutation.

The reference preprocesses BART chunks but ships NO BART loader in any
framework (SURVEY.md §2.5: "no BART loader"); noising is left to the
training side. lddl_tpu completes the path: this loader consumes the
``{sentences}`` shards (lddl_tpu.preprocess.bart), applies the BART
pretraining noise at load time on deterministic per-(epoch, dp group,
worker) streams, and emits encoder/decoder numpy batches for a
seq2seq trainer.

Noising (Lewis et al. 2019, BART):
- sentence permutation: the chunk's sentences are shuffled;
- text infilling: token spans with Poisson(lambda=3) lengths are each
  replaced by a single [MASK] until ~``mask_ratio`` of tokens are covered
  (0-length spans insert a lone [MASK]).

Batch keys: input_ids (noised), attention_mask, decoder_input_ids
(shift-right of the clean sequence), labels (clean ids, ignore_index on
padding).
"""

import numpy as np

from ..ops.packing import round_up
from ..preprocess.sentences import split_sentences
from ..utils import rng as lrng
from ..utils.fs import get_all_parquets_under
from ..utils.logging import DatasetLogger
from .dataloader import DataLoader
from .datasets import (ParquetDataset, annotate_quarantine,
                       verified_shard_paths)


def decode_record_batch(b):
    """Schema-v2 BART shards (``sentence_ids`` present) decode to
    ``(flat_ids, sent_lens)`` int32 ndarray-view pairs — the precomputed
    per-sentence tokenization the collate otherwise derives from the chunk
    text every epoch. Schema-v1 decodes to the original chunk strings;
    selection is per shard."""
    from .. import observability as obs
    from .bert import _list_views
    names = b.schema.names
    if "sentence_ids" in names:
        if obs.enabled():
            obs.inc("loader_decode_columnar_batches_total")
        flat, off = _list_views(b.column("sentence_ids"))
        lens_v, lens_off = _list_views(b.column("sentence_lens"))
        for i in range(len(off) - 1):
            yield (flat[off[i]:off[i + 1]],
                   lens_v[lens_off[i]:lens_off[i + 1]])
        return
    if obs.enabled():
        obs.inc("loader_decode_legacy_batches_total")
    # Legacy v1 text path: per-row Python strings are the shard format.
    for s in b.column("sentences").to_pylist():  # lddl: disable=python-hot-loop
        yield s


class BartCollate:

    needs_rng = True

    def __init__(self, tokenizer, max_seq_length=128, mask_ratio=0.3,
                 poisson_lambda=3.0, permute_sentences=True,
                 sequence_length_alignment=8, fixed_seq_length=None,
                 ignore_index=-1, decoder_start_token_id=None):
        self._tokenizer = tokenizer
        self._max_seq_length = max_seq_length
        self._mask_ratio = mask_ratio
        self._poisson_lambda = poisson_lambda
        self._permute_sentences = permute_sentences
        self._align = sequence_length_alignment
        self._fixed_seq_length = fixed_seq_length
        self._ignore_index = ignore_index
        vocab = tokenizer.get_vocab()
        self._mask_id = vocab["[MASK]"] if "[MASK]" in vocab else \
            tokenizer.mask_token_id
        self._cls_id = tokenizer.cls_token_id
        self._sep_id = tokenizer.sep_token_id
        self._pad_id = tokenizer.pad_token_id or 0
        self._decoder_start = (decoder_start_token_id
                               if decoder_start_token_id is not None
                               else self._cls_id)

    def _noise_ids(self, ids, g):
        """Text infilling over one id list; returns the noised list."""
        n = len(ids)
        if n == 0:
            return list(ids)
        budget = int(round(n * self._mask_ratio))
        out = list(ids)
        # Sample span starts/lengths until the mask budget is spent.
        # Inserts (0-length spans) sit at gap positions 0..n; a replacement
        # span (s, e) owns tokens s..e-1 and interior gaps s+1..e-1. Keeping
        # spans and inserts off each other's territory guarantees the
        # right-to-left application below never swallows an inserted [MASK]
        # (and the spent budget always equals the masked token count).
        covered = np.zeros(n, dtype=bool)
        gap_covered = np.zeros(n + 1, dtype=bool)   # gaps interior to a span
        insert_at = np.zeros(n + 1, dtype=bool)     # gaps holding an insert
        spans = []
        tries = 0
        while budget > 0 and tries < 4 * n:
            tries += 1
            length = int(g.poisson(self._poisson_lambda))
            start = int(g.integers(0, n))
            if length == 0:
                if gap_covered[start]:
                    continue
                insert_at[start] = True
                spans.append((start, 0))
                budget -= 1
                continue
            end = min(n, start + length)
            if covered[start:end].any() or insert_at[start + 1:end].any():
                continue
            covered[start:end] = True
            gap_covered[start + 1:end] = True
            spans.append((start, end - start))
            budget -= (end - start)
        # Apply right-to-left so indices stay valid. At equal start, the
        # replacement (longer) sorts after the insert and thus applies
        # first, so boundary inserts survive too.
        for start, length in sorted(spans, reverse=True):
            out[start:start + length] = [self._mask_id]
        return out

    def __call__(self, samples, g=None):
        if g is None:
            raise ValueError("BART noising needs a worker RNG")
        tok = self._tokenizer
        limit = self._max_seq_length - 2

        # Per-sample per-sentence token-id lists. Schema-v2 samples carry
        # them precomputed ((flat_ids, sent_lens) ndarray views, sliced
        # here with numpy only); v1 chunk strings are sentence-split and
        # tokenized (one batched call across the whole batch), every
        # epoch. Sentence permutation then happens in TOKEN space on
        # exactly the clean window: truncate first, then permute/infill —
        # encoder input and labels always cover the same tokens.
        per_sample_enc = [None] * len(samples)
        strings = []
        for i, c in enumerate(samples):
            if isinstance(c, str):
                strings.append(i)
                continue
            flat_ids, sent_lens = c
            ends = np.cumsum(sent_lens)
            per_sample_enc[i] = [flat_ids[e - l:e]
                                 for l, e in zip(sent_lens, ends)]
        if strings:
            per_sent = [split_sentences(samples[i]) for i in strings]
            flat = [s for sents in per_sent for s in sents]
            enc = tok(flat, add_special_tokens=False,
                      return_attention_mask=False)["input_ids"] if flat \
                else []
            k = 0
            for i, sents in zip(strings, per_sent):
                per_sample_enc[i] = enc[k:k + len(sents)]
                k += len(sents)
        clean, noisy = [], []
        for sample_enc in per_sample_enc:
            sent_ids = []
            budget = limit
            for ids in sample_enc:
                if budget <= 0:
                    break
                ids = ids[:budget]
                if len(ids):
                    sent_ids.append(ids)
                    budget -= len(ids)
            clean.append([i for s in sent_ids for i in s])
            if self._permute_sentences and len(sent_ids) > 1:
                lrng.shuffle(g, sent_ids)
            permuted = [i for s in sent_ids for i in s]
            # Infilling can grow the sequence via 0-length inserts; clamp
            # back to the window so fixed shapes always hold.
            noisy.append(self._noise_ids(permuted, g)[:limit])

        n = len(samples)
        enc_lens = [len(x) + 2 for x in noisy]
        dec_lens = [len(x) + 2 for x in clean]
        longest = max(max(enc_lens), max(dec_lens))
        if self._fixed_seq_length is not None:
            if longest > self._fixed_seq_length:
                raise ValueError(
                    "sample of {} tokens exceeds fixed_seq_length {}".format(
                        longest, self._fixed_seq_length))
            L = self._fixed_seq_length
        else:
            L = round_up(longest, self._align)

        input_ids = np.full((n, L), self._pad_id, dtype=np.int32)
        attention_mask = np.zeros((n, L), dtype=np.int32)
        decoder_input_ids = np.full((n, L), self._pad_id, dtype=np.int32)
        labels = np.full((n, L), self._ignore_index, dtype=np.int32)
        for i, (nz, cl) in enumerate(zip(noisy, clean)):
            e = [self._cls_id] + nz + [self._sep_id]
            d = [self._cls_id] + cl + [self._sep_id]
            input_ids[i, :len(e)] = e
            attention_mask[i, :len(e)] = 1
            # Teacher forcing: decoder sees shift-right of the clean seq.
            decoder_input_ids[i, 0] = self._decoder_start
            decoder_input_ids[i, 1:len(d)] = d[:-1]
            labels[i, :len(d)] = d
        return {
            "input_ids": input_ids,
            "attention_mask": attention_mask,
            "decoder_input_ids": decoder_input_ids,
            "labels": labels,
        }


def get_bart_pretrain_data_loader(
    path,
    dp_rank=0,
    num_dp_groups=1,
    batch_size=64,
    num_workers=1,
    shuffle_buffer_size=16384,
    shuffle_buffer_warmup_factor=16,
    tokenizer=None,
    vocab_file=None,
    tokenizer_name=None,
    max_seq_length=128,
    mask_ratio=0.3,
    poisson_lambda=3.0,
    permute_sentences=True,
    sequence_length_alignment=8,
    fixed_seq_length=None,
    ignore_index=-1,
    base_seed=12345,
    start_epoch=0,
    log_dir=None,
    log_level=None,
    return_raw_samples=False,
    prefetch=2,
    comm=None,
    worker_mode="thread",
    on_corrupt=None,
):
    """BART denoising loader over ``{sentences}`` shards at ``path``.
    ``on_corrupt``: startup shard-integrity policy, see
    get_bert_pretrain_data_loader. Shard bytes arrive through the same
    shard I/O pipeline as the BERT loader (loader/shardcache.py:
    StorageBackend-routed reads, prefetch + generation-keyed cache +
    decode-ahead; byte-identical with the pipeline on or off)."""
    import logging
    if tokenizer is None:
        from ..preprocess.tokenizer import get_tokenizer
        tokenizer = get_tokenizer(vocab_file=vocab_file,
                                  pretrained_model_name=tokenizer_name)
    logger = DatasetLogger(
        log_dir=log_dir,
        log_level=log_level if log_level is not None else logging.WARNING,
        rank=dp_rank,
    )
    file_paths = get_all_parquets_under(path)
    if not file_paths:
        raise ValueError("no parquet shards under {}".format(path))
    n_before = len(file_paths)
    file_paths = verified_shard_paths(path, file_paths,
                                      on_corrupt=on_corrupt, logger=logger,
                                      comm=comm)
    n_quarantined = n_before - len(file_paths)
    try:
        dataset = ParquetDataset(
            file_paths,
            base_seed=base_seed,
            start_epoch=start_epoch,
            dp_rank=dp_rank,
            num_dp_groups=num_dp_groups,
            num_workers=num_workers,
            shuffle_buffer_size=shuffle_buffer_size,
            shuffle_buffer_warmup_factor=shuffle_buffer_warmup_factor,
            decode_record_batch=decode_record_batch,
            comm=comm,
            logger=logger,
        )
    except ValueError as e:
        if n_quarantined:
            raise annotate_quarantine(e, n_quarantined) from e
        raise
    collate = None if return_raw_samples else BartCollate(
        tokenizer,
        max_seq_length=max_seq_length,
        mask_ratio=mask_ratio,
        poisson_lambda=poisson_lambda,
        permute_sentences=permute_sentences,
        sequence_length_alignment=sequence_length_alignment,
        fixed_seq_length=fixed_seq_length,
        ignore_index=ignore_index,
    )
    return DataLoader(dataset, batch_size, collate_fn=collate,
                      prefetch=prefetch, worker_mode=worker_mode)
