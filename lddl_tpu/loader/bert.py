"""BERT pretraining data loader: decode, collation, dynamic masking, factory.

Reference parity: lddl/torch/bert.py (and the torch_mp variant's loss-mask
output). Differences by design, for TPU:

- Batches are numpy (int32) dicts; the training step moves them to devices
  as globally-sharded jax.Arrays via loader/sharding.py.
- ``fixed_seq_lengths`` pads every batch of a bin to that bin's boundary
  instead of the batch max: a *bounded set of static shapes* means a
  bounded number of XLA compilations (the TPU version of the reference's
  Tensor-Core alignment trick, lddl/torch/bert.py:91-96 — which we also
  keep for the unbinned path via ``sequence_length_alignment``).
- Dynamic masking is vectorized numpy on deterministic per-(epoch, dp
  group, worker) streams — identical across TP/PP peers, like everything
  else in the loader.
"""

import numpy as np

from ..utils.fs import (
    deserialize_np_array,
    get_all_bin_ids,
    get_all_parquets_under,
    get_file_paths_for_bin_id,
    get_generation_of_path,
)
from ..utils.logging import DatasetLogger
from .dataloader import Binned, DataLoader
from .datasets import (ParquetDataset, annotate_quarantine,
                       verified_shard_paths)


def generation_gate_filter(root, paths):
    """Apply the generation pickup gate: the root ``.manifest.json``'s
    ``__meta__`` generation is the LAST thing the ingest publisher
    writes, so shards under gen dirs newer than it are excluded even if
    their files already exist (a generation mid-publish is never
    visible). A directory with no generation meta (classic offline
    output) gates nothing and follows whatever is on disk. Returns
    (filtered_paths, gate). The startup file list and every epoch-
    boundary refresh go through this one filter."""
    from ..resilience.integrity import read_manifest
    manifest = read_manifest(root)
    meta = manifest.get("__meta__") if manifest else None
    gate = meta.get("generation") if isinstance(meta, dict) else None
    if gate is not None:
        paths = [p for p in paths
                 if get_generation_of_path(root, p) <= gate]
    return paths, gate


def packed_shape_of_dir(path, file_paths=None):
    """(pack_seq_length, pack_max_per_row) of an offline-packed shard
    directory, or None. The root ``.manifest.json``'s ``__meta__.packed``
    entry (written by build_manifest off every shard's footer) is
    authoritative; manifest-less or meta-less directories sniff one
    shard's footer metadata instead — detection must work for raw
    preprocess output too, not only published datasets."""
    from ..resilience.integrity import read_manifest
    manifest = read_manifest(path)
    meta = (manifest.get("__meta__") if manifest else None) or {}
    packed = meta.get("packed")
    if isinstance(packed, dict):
        try:
            return (int(packed["pack_seq_length"]),
                    int(packed["pack_max_per_row"]))
        except (KeyError, TypeError, ValueError):
            return None
    if file_paths is None:
        from ..utils.fs import get_all_parquets_under
        file_paths = get_all_parquets_under(path)
    if file_paths:
        from ..preprocess.packing import pack_shape_of_parquet
        return pack_shape_of_parquet(sorted(file_paths)[0])
    return None


class GenerationSnapshot:
    """One gate + directory-listing read shared by every bin's follower
    within one epoch boundary (keyed by the boundary's epoch number), so
    a generation publish landing between two bins' refreshes cannot give
    a single epoch a generation-mixed view — a fresh loader started at
    that epoch index must reproduce its batches exactly."""

    def __init__(self, root):
        self.root = root
        self._key = None
        self._value = None

    def get(self, key):
        if key is None or key != self._key:
            self._value = generation_gate_filter(
                self.root, get_all_parquets_under(self.root))
            self._key = key
        return self._value


class GenerationFollower:
    """Picklable refresh callable for generation-aware loading: returns
    the currently-published, verified shard list for one dataset (one bin
    or the unbinned whole; see generation_gate_filter for the visibility
    rule)."""

    def __init__(self, root, bin_id=None, on_corrupt=None, snapshot=None):
        self.root = root
        self.bin_id = bin_id
        self.on_corrupt = on_corrupt
        self.snapshot = snapshot or GenerationSnapshot(root)
        self.last_gate = None
        self._epoch_key = None
        self._last = None  # (gated bin paths, verified result)

    def set_epoch_key(self, key):
        """Called by the dataset right before the refresh with the epoch
        boundary's number — the snapshot cache key every bin shares."""
        self._epoch_key = key

    def __call__(self):
        paths, gate = self.snapshot.get(self._epoch_key)
        self.last_gate = gate
        # Bin-filter BEFORE verifying (bin_id=None serves the unbinned
        # dataset, not "all bins"), and serve an unchanged set from the
        # memo: integrity verification is a startup/pickup contract, not
        # a per-epoch CRC re-scan of the whole directory.
        paths = get_file_paths_for_bin_id(paths, self.bin_id)
        if self._last is not None and self._last[0] == paths:
            return list(self._last[1])
        verified = verified_shard_paths(self.root, paths,
                                        on_corrupt=self.on_corrupt)
        self._last = (paths, verified)
        return list(verified)


def _list_views(col):
    """(values, offsets) numpy views of an Arrow ``list<int32>`` column —
    the values buffer is referenced zero-copy, so per-row slices are views
    into the shard's decoded Arrow memory, never per-row Python objects."""
    lens = col.value_lengths().to_numpy(zero_copy_only=False)
    values = col.flatten().to_numpy(zero_copy_only=True)
    offsets = np.zeros(len(lens) + 1, dtype=np.int64)
    np.cumsum(lens, out=offsets[1:])
    return values, offsets


def _decode_columnar(b, names):
    """Schema-v2 fast path: one zero-copy buffer grab per column, then
    per-row ndarray views (no string materialization, no per-token work —
    the collate consumes the id views directly)."""
    flat_a, off_a = _list_views(b.column("A_ids"))
    flat_b, off_b = _list_views(b.column("B_ids"))
    rn = b.column("is_random_next").to_numpy(zero_copy_only=False)
    n = len(rn)
    if "masked_lm_positions_ids" in names:
        pos_v, pos_off = _list_views(b.column("masked_lm_positions_ids"))
        lab_v, lab_off = _list_views(b.column("masked_lm_label_ids"))
        for i in range(n):
            yield (flat_a[off_a[i]:off_a[i + 1]],
                   flat_b[off_b[i]:off_b[i + 1]], rn[i],
                   pos_v[pos_off[i]:pos_off[i + 1]],
                   lab_v[lab_off[i]:lab_off[i + 1]])
    else:
        for i in range(n):
            yield (flat_a[off_a[i]:off_a[i + 1]],
                   flat_b[off_b[i]:off_b[i + 1]], rn[i])


class PackedRow(tuple):
    """One offline-packed shard row decoded to views:
    ``(ids, tok3, content, samp, mlm2)``. ``ids`` is the stored
    fully-interleaved row content ([CLS]/[SEP] baked in at pack time);
    ``tok3`` stacks the boundary-derived per-token arrays
    ``[segments, position_ids, token_type]`` and ``samp`` the per-sample
    arrays ``[a_lens, b_lens, off, nsp(, mask_lens)]`` — both
    precomputed ONCE per decode chunk, stacked so a row is FIVE slices
    and a batch is four axis-1 concatenates; ``content`` is the
    content-vs-special bool mask (dynamic masking), ``mlm2`` stacks
    ``[positions, labels]`` (ROW-relative positions as stored; None for
    dynamic-masking corpora). The named properties unpack the stacks. A
    distinct type (not a bare tuple) so collates can assert they were
    wired to the decode path they expect."""

    __slots__ = ()

    ids = property(lambda s: s[0])
    seg = property(lambda s: s[1][0])
    pos = property(lambda s: s[1][1])
    typ = property(lambda s: s[1][2])
    content = property(lambda s: s[2])
    a_lens = property(lambda s: s[3][0])
    b_lens = property(lambda s: s[3][1])
    off = property(lambda s: s[3][2])
    nsp = property(lambda s: s[3][3])
    mask_lens = property(lambda s: s[3][4] if len(s[3]) > 4 else None)
    mlm_pos = property(lambda s: s[4][0] if s[4] is not None else None)
    mlm_labels = property(lambda s: s[4][1] if s[4] is not None else None)


# Decode-chunk token budget: the boundary-derived per-token arrays are
# materialized per chunk (not per shard-sized record batch — row groups
# can span a whole shard), so buffered rows keep at most
# ~shuffle_buffer_size + chunk rows' worth of derived arrays alive.
_DECODE_CHUNK_TOKENS = 1 << 20


def _decode_prepacked(b, names):
    """Offline-packed fast path: one zero-copy buffer grab per column,
    the boundary-derived per-token arrays computed ONCE per chunk of
    rows (vectorized over thousands of rows, amortizing numpy dispatch),
    then per-ROW views — each yielded sample is one already-packed
    training row; no repacking and no per-sample work ever happens at
    load time."""
    ids_v, ids_off = _list_views(b.column("input_ids"))
    al_v, al_off = _list_views(b.column("pack_a_lens"))
    nsp_v, _ = _list_views(b.column("pack_nsp"))
    bl_v, _ = _list_views(b.column("pack_b_lens"))
    static = "pack_mask_lens" in names
    if static:
        pos_v, pos_off = _list_views(b.column("masked_lm_positions_ids"))
        lab_v, _ = _list_views(b.column("masked_lm_label_ids"))
        ml_v, _ = _list_views(b.column("pack_mask_lens"))
    n = b.num_rows
    row = 0
    aranges = BertCollate._concat_aranges
    while row < n:
        end = row + 1
        while end < n and ids_off[end + 1] - ids_off[row] \
                <= _DECODE_CHUNK_TOKENS:
            end += 1
        # Sample-flat slices for rows [row, end); all derived arrays are
        # chunk-relative, computed in one vectorized pass, and STACKED
        # (per-token x3, per-sample x4|5, mlm x2) so each row is five
        # slices and a batch is four axis-1 concatenates.
        s0, s1 = int(al_off[row]), int(al_off[end])
        al = al_v[s0:s1].astype(np.int64)
        bl = bl_v[s0:s1].astype(np.int64)
        spr = (al_off[row:end + 1] - s0).astype(np.int64)
        samples_per_row = np.diff(spr)
        tot = al + bl + 3
        slot = aranges(samples_per_row)
        pos64 = aranges(tot)
        tok3 = np.empty((3, len(pos64)), dtype=np.int32)
        tok3[0] = np.repeat(slot + 1, tot)                  # segments
        tok3[1] = pos64                                     # position_ids
        tok3[2] = pos64 >= np.repeat(2 + al, tot)           # token_type
        content = ((pos64 != 0)
                   & (pos64 != np.repeat(1 + al, tot))
                   & (pos64 != np.repeat(tot - 1, tot)))
        cum = np.cumsum(tot) - tot              # token start per sample
        samp = np.empty((5 if static else 4, len(al)), dtype=np.int32)
        samp[0] = al_v[s0:s1]
        samp[1] = bl_v[s0:s1]
        samp[2] = cum - np.repeat(cum[spr[:-1]], samples_per_row)
        samp[3] = nsp_v[s0:s1]
        if static:
            samp[4] = ml_v[s0:s1]
            m0 = int(pos_off[row])
            mlm2 = np.empty((2, int(pos_off[end]) - m0), dtype=np.int32)
            mlm2[0] = pos_v[m0:int(pos_off[end])]
            mlm2[1] = lab_v[m0:int(pos_off[end])]
        # Slice bounds as plain ints, materialized once per chunk (a few
        # per ROW, never per token): numpy scalar extraction inside the
        # per-row loop costs ~10x a list index.
        idsb = ids_off[row:end + 1].tolist()  # lddl: disable=python-hot-loop
        trow = (ids_off[row:end + 1] - ids_off[row]).tolist()  # lddl: disable=python-hot-loop
        sprl = spr.tolist()  # lddl: disable=python-hot-loop
        if static:
            mb = (pos_off[row:end + 1] - m0).tolist()  # lddl: disable=python-hot-loop
        for i in range(end - row):
            mlm = mlm2[:, mb[i]:mb[i + 1]] if static else None
            yield PackedRow((
                ids_v[idsb[i]:idsb[i + 1]],
                tok3[:, trow[i]:trow[i + 1]], content[trow[i]:trow[i + 1]],
                samp[:, sprl[i]:sprl[i + 1]], mlm))
        row = end


def decode_record_batch(b):
    """Yield sample tuples from a parquet RecordBatch:
    (A, B, is_random_next[, masked_lm_positions, masked_lm_labels]).

    Schema v2 shards (``A_ids`` present) decode to int32 ndarray views
    over the batch's flat token-id buffers; schema v1 decodes to the
    original Python strings. Offline-packed shards (``pack_a_lens``
    present) decode one PackedRow of views per parquet row. Selection is
    per-shard, so directories mixing both schemas stream correctly (and
    byte-identically — the collate normalizes)."""
    from .. import observability as obs
    names = b.schema.names
    if "pack_a_lens" in names:
        if obs.enabled():
            obs.inc("loader_decode_packed_batches_total")
        yield from _decode_prepacked(b, names)
        return
    if "A_ids" in names:
        if obs.enabled():
            obs.inc("loader_decode_columnar_batches_total")
        yield from _decode_columnar(b, names)
        return
    if obs.enabled():
        obs.inc("loader_decode_legacy_batches_total")
    static = "masked_lm_positions" in names
    # Legacy v1 text path: per-row Python strings are the shard format.
    b = b.to_pydict()  # lddl: disable=python-hot-loop
    if static:
        for row in zip(b["A"], b["B"], b["is_random_next"],
                       b["masked_lm_positions"], b["masked_lm_labels"]):
            yield row
    else:
        for row in zip(b["A"], b["B"], b["is_random_next"]):
            yield row


class BertCollate:
    """samples -> encoded numpy batch dict.

    Static masking (5-tuples): emits ``labels`` from the stored positions.
    Dynamic masking (3-tuples): masks on the fly with the worker stream.
    Output keys: input_ids, token_type_ids, attention_mask,
    next_sentence_labels, labels, masked_lm_positions-mask (``loss_mask``,
    the torch_mp extra output for Megatron-style loss,
    ref lddl/torch_mp/bert.py:103-105).
    """

    needs_rng = True

    def __init__(self, tokenizer, sequence_length_alignment=8,
                 fixed_seq_length=None, ignore_index=-1, mlm_prob=0.15,
                 emit_loss_mask=False):
        self._tokenizer = tokenizer
        self._align = sequence_length_alignment
        self._fixed_seq_length = fixed_seq_length
        self._ignore_index = ignore_index
        self._mlm_prob = mlm_prob
        self._emit_loss_mask = emit_loss_mask
        self._mask_id = tokenizer.convert_tokens_to_ids("[MASK]")
        self._cls_id = tokenizer.convert_tokens_to_ids("[CLS]")
        self._sep_id = tokenizer.convert_tokens_to_ids("[SEP]")
        self._vocab_size = len(tokenizer)
        # One dict lookup per token beats a per-sample HF call; shards store
        # tokens this tokenizer produced, so misses (-> unk) are impossible
        # in practice but keep convert_tokens_to_ids semantics anyway.
        self._vocab = dict(tokenizer.get_vocab())
        self._unk_id = tokenizer.convert_tokens_to_ids(
            tokenizer.unk_token or "[UNK]")

    def _batch_seq_len(self, lens):
        longest = max(lens)
        if self._fixed_seq_length is not None:
            if longest > self._fixed_seq_length:
                raise ValueError(
                    "sample of {} tokens exceeds fixed_seq_length {}".format(
                        longest, self._fixed_seq_length))
            return self._fixed_seq_length
        from ..ops.packing import round_up
        return round_up(longest, self._align)

    def _token_ids_and_lens(self, seqs):
        """One flat id array + per-item lengths. Items are int32 token-id
        ndarrays (schema-v2 shards: used as-is, zero per-token work) or
        space-joined token strings (schema-v1: split + vocab lookup),
        freely mixed when a directory holds shards of both schemas."""
        n_str = sum(isinstance(s, str) for s in seqs)
        if n_str == 0:
            # Columnar fast path: lengths off the views, ONE C-level
            # concatenation for the flat batch buffer.
            lens = np.fromiter(map(len, seqs), dtype=np.int64,
                               count=len(seqs))
            flat = (np.concatenate(seqs) if len(seqs)
                    else np.zeros(0, dtype=np.int32))
            return np.ascontiguousarray(flat, dtype=np.int32), lens
        vocab_get = self._vocab.get
        unk = self._unk_id
        if n_str == len(seqs):
            # Pure v1: single bulk pass, dict lookups only. Per-token
            # Python iteration is inherent to the text schema (baselined).
            token_lists = [t.split() for t in seqs]
            lens = np.fromiter((len(t) for t in token_lists),
                               dtype=np.int64, count=len(token_lists))
            flat = np.fromiter(
                (vocab_get(t, unk) for ts in token_lists for t in ts),
                dtype=np.int32, count=int(lens.sum()))
            return flat, lens
        # Mixed v1/v2 batch (shards of both schemas in one directory):
        # normalize the strings, then concatenate like the fast path.
        arrs = [s if not isinstance(s, str) else
                np.fromiter((vocab_get(t, unk) for t in s.split()),
                            dtype=np.int32)
                for s in seqs]
        lens = np.fromiter(map(len, arrs), dtype=np.int64, count=len(arrs))
        return np.concatenate(arrs).astype(np.int32, copy=False), lens

    @staticmethod
    def _positions_and_lens(samples):
        """Flat masked-lm positions + per-sample counts, as ONE batched
        decode: schema-v2 rows carry int32 ndarray views (already sliced
        from one Arrow buffer), schema-v1 rows carry serialize_np_array
        bytes (decoded per row — the v1 format is row-serialized)."""
        pos_list = [s[3] if not isinstance(s[3], (bytes, bytearray))
                    else deserialize_np_array(s[3])
                    for s in samples]
        lens = np.fromiter(map(len, pos_list), dtype=np.int64,
                           count=len(pos_list))
        flat = (np.concatenate(pos_list).astype(np.int64, copy=False)
                if pos_list else np.zeros(0, dtype=np.int64))
        return flat, lens

    @staticmethod
    def _concat_aranges(lens):
        """[arange(l) for l in lens] concatenated, without a Python loop."""
        total = int(lens.sum())
        if total == 0:
            return np.zeros(0, dtype=np.int64)
        starts = np.cumsum(lens) - lens
        return np.arange(total, dtype=np.int64) - np.repeat(starts, lens)

    def __call__(self, samples, g=None):
        n = len(samples)
        static = len(samples[0]) == 5
        flat_a, lens_a = self._token_ids_and_lens([s[0] for s in samples])
        flat_b, lens_b = self._token_ids_and_lens([s[1] for s in samples])
        ends = lens_a + lens_b + 3
        seq_len = self._batch_seq_len([int(ends.max())])

        rows = np.arange(n, dtype=np.int64)
        col = np.arange(seq_len, dtype=np.int64)[None, :]
        # Flat scatter of the A and B segments: row offsets repeated per
        # token + a concatenated per-row arange gives every target slot.
        idx_a = (np.repeat(rows, lens_a) * seq_len
                 + 1 + self._concat_aranges(lens_a))
        idx_b = (np.repeat(rows * seq_len + 2 + lens_a, lens_b)
                 + self._concat_aranges(lens_b))

        input_ids = np.zeros((n, seq_len), dtype=np.int32)
        input_ids[:, 0] = self._cls_id
        input_ids.flat[idx_a] = flat_a
        input_ids.flat[idx_b] = flat_b
        input_ids[rows, 1 + lens_a] = self._sep_id
        input_ids[rows, ends - 1] = self._sep_id

        token_type_ids = ((col >= (2 + lens_a)[:, None])
                          & (col < ends[:, None])).astype(np.int32)
        attention_mask = (col < ends[:, None]).astype(np.int32)

        labels = np.full((n, seq_len), self._ignore_index, dtype=np.int32)
        if static:
            flat_pos, pos_lens = self._positions_and_lens(samples)
            flat_labels, lens_m = self._token_ids_and_lens(
                [s[4] for s in samples])
            if not np.array_equal(pos_lens, lens_m):
                raise ValueError(
                    "masked_lm_positions/masked_lm_labels length mismatch "
                    "in sample(s) {}".format(
                        # error path only -- lddl: disable=python-hot-loop
                        np.flatnonzero(pos_lens != lens_m).tolist()))
            labels[np.repeat(rows, lens_m), flat_pos] = flat_labels
        else:
            if g is None:
                raise ValueError("dynamic masking needs a worker RNG")
            # Non-special positions eligible for masking.
            special_tokens_mask = np.ones((n, seq_len), dtype=bool)
            special_tokens_mask.flat[idx_a] = False
            special_tokens_mask.flat[idx_b] = False
            input_ids, labels = self._mask_tokens(
                input_ids, special_tokens_mask, g)

        batch = {
            "input_ids": input_ids,
            "token_type_ids": token_type_ids,
            "attention_mask": attention_mask,
            "next_sentence_labels": np.asarray(
                [int(s[2]) for s in samples], dtype=np.int32),
            "labels": labels,
        }
        if self._emit_loss_mask:
            batch["loss_mask"] = (labels != self._ignore_index).astype(np.int32)
        return batch

    def _mask_tokens(self, input_ids, special_tokens_mask, g):
        """Vectorized HF-style dynamic masking: select ~mlm_prob of
        non-special tokens; of those 80% -> [MASK], 10% -> random token,
        10% -> unchanged. (ref: lddl/torch/bert.py:152-196)"""
        shape = input_ids.shape
        masked = (g.random(shape) < self._mlm_prob) & ~special_tokens_mask
        labels = np.where(masked, input_ids, self._ignore_index).astype(np.int32)
        r = g.random(shape)
        out = input_ids.copy()
        out[masked & (r < 0.8)] = self._mask_id
        random_sel = masked & (r >= 0.8) & (r < 0.9)
        random_words = g.integers(0, self._vocab_size, shape, dtype=np.int32)
        out[random_sel] = random_words[random_sel]
        return out, labels


class BertPackedCollate(BertCollate):
    """samples + packed layout -> encoded packed batch (sequence packing,
    ops/packing.py): several samples per fixed-length row, block-diagonal
    attention via per-token ``segments``, per-sample ``position_ids``
    restart, per-sample [CLS] columns in ``cls_positions`` and NSP labels
    [R, P] padded with ignore_index. Rows are always exactly
    ``pack_seq_length`` wide — ONE static shape for the whole run."""

    def __init__(self, tokenizer, pack_seq_length, pack_rows, pack_max_per_row,
                 ignore_index=-1, mlm_prob=0.15, emit_loss_mask=False):
        super().__init__(tokenizer, fixed_seq_length=pack_seq_length,
                         ignore_index=ignore_index, mlm_prob=mlm_prob,
                         emit_loss_mask=emit_loss_mask)
        self._rows = pack_rows
        self._max_per_row = pack_max_per_row

    def __call__(self, layout_rows, samples, g=None):
        return self._encode_packed(layout_rows, samples, g, self._rows)

    def _encode_packed(self, layout_rows, samples, g, R):
        """The packed scatter encode for ``R`` output rows — shared by the
        load-time packer (R = configured pack_rows) and the offline
        prepacked collate (R = rows in this batch). ``R`` is a parameter,
        not instance state: collates are shared across worker threads."""
        from ..ops.packing import packed_layout_arrays
        L, P = self._fixed_seq_length, self._max_per_row
        n = len(samples)
        static = len(samples[0]) == 5
        layout = packed_layout_arrays(layout_rows, L, P)
        if layout["n_rows"] > R or n != len(layout["row_of"]):
            raise ValueError("layout/sample mismatch: {} rows > {} or "
                             "{} != {}".format(layout["n_rows"], R, n,
                                               len(layout["row_of"])))

        flat_a, lens_a = self._token_ids_and_lens([s[0] for s in samples])
        flat_b, lens_b = self._token_ids_and_lens([s[1] for s in samples])
        totals = lens_a + lens_b + 3
        row_of, offset_of = layout["row_of"], layout["offset_of"]
        slot_of = layout["slot_of"]

        base = row_of * L + offset_of                   # flat start per sample
        idx_a = np.repeat(base + 1, lens_a) + self._concat_aranges(lens_a)
        idx_b = (np.repeat(base + 2 + lens_a, lens_b)
                 + self._concat_aranges(lens_b))
        idx_all = np.repeat(base, totals) + self._concat_aranges(totals)

        input_ids = np.zeros((R, L), dtype=np.int32)
        input_ids.flat[idx_a] = flat_a
        input_ids.flat[idx_b] = flat_b
        input_ids.flat[base] = self._cls_id
        input_ids.flat[base + 1 + lens_a] = self._sep_id
        input_ids.flat[base + totals - 1] = self._sep_id

        token_type_ids = np.zeros((R, L), dtype=np.int32)
        # type 1 spans B plus its trailing [SEP], like the unpacked collate.
        idx_b_ext = (np.repeat(base + 2 + lens_a, lens_b + 1)
                     + self._concat_aranges(lens_b + 1))
        token_type_ids.flat[idx_b_ext] = 1

        attention_mask = np.zeros((R, L), dtype=np.int32)
        attention_mask.flat[idx_all] = 1
        segments = np.zeros((R, L), dtype=np.int32)
        segments.flat[idx_all] = np.repeat(slot_of + 1, totals)
        position_ids = np.zeros((R, L), dtype=np.int32)
        position_ids.flat[idx_all] = self._concat_aranges(totals)

        cls_positions = np.zeros((R, P), dtype=np.int32)
        nsp = np.full((R, P), self._ignore_index, dtype=np.int32)
        cls_positions[row_of, slot_of] = offset_of
        nsp[row_of, slot_of] = np.asarray([int(s[2]) for s in samples],
                                          dtype=np.int32)

        labels = np.full((R, L), self._ignore_index, dtype=np.int32)
        if static:
            flat_pos, _ = self._positions_and_lens(samples)
            flat_labels, lens_m = self._token_ids_and_lens(
                [s[4] for s in samples])
            labels.flat[np.repeat(base, lens_m) + flat_pos] = flat_labels
        else:
            if g is None:
                raise ValueError("dynamic masking needs a worker RNG")
            special = np.ones((R, L), dtype=bool)
            special.flat[idx_a] = False
            special.flat[idx_b] = False
            input_ids, labels = self._mask_tokens(input_ids, special, g)

        batch = {
            "input_ids": input_ids,
            "token_type_ids": token_type_ids,
            "attention_mask": attention_mask,
            "segments": segments,
            "position_ids": position_ids,
            "cls_positions": cls_positions,
            "next_sentence_labels": nsp,
            "labels": labels,
        }
        if self._emit_loss_mask:
            batch["loss_mask"] = (labels != self._ignore_index).astype(
                np.int32)
        stats = {"pad_tokens": int(layout["pad_tokens"]
                                   + (R - layout["n_rows"]) * L),
                 "total_tokens": R * L, "n_samples": n}
        return batch, stats


class BertPrepackedCollate(BertPackedCollate):
    """Collate for OFFLINE-packed shards: each input sample is one
    pre-packed row (a PackedRow of zero-copy views from
    decode_record_batch). The FFD packing happened at preprocess time,
    so this encode is FULLY vectorized: the per-row flat buffers
    concatenate once per column (R arrays, not one per sample) and every
    layout quantity — row/slot/offset per sample — derives from the
    stored boundary columns with numpy arithmetic; no per-sample Python
    exists anywhere on the path. Batches are exactly
    ``len(rows) x pack_seq_length``; a full batch always has the one
    static shape, like the load-time packer's output."""

    def __init__(self, tokenizer, pack_seq_length, pack_max_per_row,
                 ignore_index=-1, mlm_prob=0.15, emit_loss_mask=False):
        super().__init__(tokenizer, pack_seq_length, pack_rows=1,
                         pack_max_per_row=pack_max_per_row,
                         ignore_index=ignore_index, mlm_prob=mlm_prob,
                         emit_loss_mask=emit_loss_mask)

    def __call__(self, rows, g=None):
        if not rows or not isinstance(rows[0], PackedRow):
            raise TypeError(
                "BertPrepackedCollate consumes PackedRow samples from "
                "offline-packed shards; got {} — is this directory "
                "actually packed?".format(type(rows[0]).__name__
                                          if rows else "an empty batch"))
        static = rows[0][4] is not None
        L, P, R = self._fixed_seq_length, self._max_per_row, len(rows)

        # Four axis-1 concatenates over R precomputed row views, one
        # index build, then one flat scatter per output array.
        ids_rows = [r[0] for r in rows]
        used = np.fromiter(map(len, ids_rows), dtype=np.int64, count=R)
        bases = np.arange(R, dtype=np.int64) * L
        idx_all = np.repeat(bases, used) + self._concat_aranges(used)
        tok3 = np.concatenate([r[1] for r in rows], axis=1)
        samp = np.concatenate([r[3] for r in rows], axis=1)

        input_ids = np.zeros((R, L), dtype=np.int32)
        input_ids.flat[idx_all] = np.concatenate(ids_rows)
        # The three per-token planes land in ONE fancy-index assignment
        # (their batch dict entries are views of one backing array), and
        # the attention mask needs no scatter at all: packed rows fill a
        # PREFIX of each row, so it is a broadcast compare against the
        # per-row used count.
        out3 = np.zeros((3, R, L), dtype=np.int32)
        out3.reshape(3, R * L)[:, idx_all] = tok3
        segments, position_ids, token_type_ids = out3
        attention_mask = (np.arange(L, dtype=np.int64)[None, :]
                          < used[:, None]).astype(np.int32)

        samples_per_row = np.fromiter(
            (r[3].shape[1] for r in rows), dtype=np.int64, count=R)
        row_of = np.repeat(np.arange(R, dtype=np.int64), samples_per_row)
        slot_of = self._concat_aranges(samples_per_row)
        cls_positions = np.zeros((R, P), dtype=np.int32)
        nsp = np.full((R, P), self._ignore_index, dtype=np.int32)
        cls_positions[row_of, slot_of] = samp[2]
        nsp[row_of, slot_of] = samp[3]

        labels = np.full((R, L), self._ignore_index, dtype=np.int32)
        if static:
            mlm2 = np.concatenate([r[4] for r in rows], axis=1)
            mask_counts = np.fromiter(
                (r[4].shape[1] for r in rows), dtype=np.int64, count=R)
            labels.flat[np.repeat(bases, mask_counts) + mlm2[0]] = mlm2[1]
        else:
            if g is None:
                raise ValueError("dynamic masking needs a worker RNG")
            special = np.ones((R, L), dtype=bool)
            special.flat[idx_all] = ~np.concatenate(
                [r[2] for r in rows])
            input_ids, labels = self._mask_tokens(input_ids, special, g)

        batch = {
            "input_ids": input_ids,
            "token_type_ids": token_type_ids,
            "attention_mask": attention_mask,
            "segments": segments,
            "position_ids": position_ids,
            "cls_positions": cls_positions,
            "next_sentence_labels": nsp,
            "labels": labels,
        }
        if self._emit_loss_mask:
            batch["loss_mask"] = (labels != self._ignore_index).astype(
                np.int32)
        return batch


class PackedBertLoader:
    """Streams raw samples from an inner DataLoader through a
    StreamPacker, emitting packed batches of exactly ``pack_rows`` x
    ``pack_seq_length``. Packing is deterministic (first-fit in stream
    order) and carries leftover samples across batch boundaries, so no
    sample is dropped; the final partial batch pads with empty rows."""

    _PACK_RNG_TAG = 0xACED  # dynamic-masking stream domain for packed mode

    def __init__(self, inner, collate, pack_seq_length, pack_rows,
                 pack_max_per_row, pack_horizon=None):
        from ..ops.packing import StreamPacker
        self._inner = inner
        self._collate = collate
        self._L = pack_seq_length
        self._R = pack_rows
        self._P = pack_max_per_row
        self._horizon = pack_horizon
        self._StreamPacker = StreamPacker
        # Cumulative packing efficiency (reset each epoch): pad_ratio =
        # pad_tokens / total_tokens over the emitted batches.
        self.pad_tokens = 0
        self.total_tokens = 0
        self.n_samples = 0

    @property
    def pad_ratio(self):
        return self.pad_tokens / max(self.total_tokens, 1)

    # Collate encodes run on a small thread pool (numpy scatter work,
    # largely GIL-releasing): layout assignment stays serial-deterministic,
    # encode order is preserved by yielding futures FIFO.
    _COLLATE_THREADS = 2

    def __iter__(self):
        import collections
        import concurrent.futures as cf

        from ..utils import rng as lrng
        ds = self._inner.dataset
        inner_it = iter(self._inner)   # advances the epoch
        packer = self._StreamPacker(self._L, self._R, self._P,
                                    horizon=self._horizon)
        store = {}                     # global ordinal -> sample
        self.pad_tokens = self.total_tokens = self.n_samples = 0
        pool = cf.ThreadPoolExecutor(max_workers=self._COLLATE_THREADS)
        inflight = collections.deque()
        batch_idx = 0

        def submit(rows):
            nonlocal batch_idx
            if not rows:
                return
            # Relabel global ordinals to batch-local 0..n-1 (collate
            # contract) in stream order, and pull their samples.
            ordinals = sorted(o for row in rows for o, _ in row)
            local = {o: i for i, o in enumerate(ordinals)}
            rows_local = [[(local[o], length) for o, length in row]
                          for row in rows]
            samples = [store.pop(o) for o in ordinals]
            # Per-BATCH masking stream (not one shared generator): collates
            # run concurrently on the pool, and interleaved draws from a
            # shared stream would be schedule-dependent.
            g = lrng.sample_rng(ds.base_seed, self._PACK_RNG_TAG, ds.epoch,
                                ds.dp_rank, batch_idx)
            batch_idx += 1
            inflight.append(pool.submit(self._collate, rows_local, samples,
                                        g=g))

        from .. import observability as obs
        obs_on = obs.enabled()

        def drain(block):
            while inflight and (block
                                or len(inflight) > self._COLLATE_THREADS):
                batch, stats = inflight.popleft().result()
                self.pad_tokens += stats["pad_tokens"]
                self.total_tokens += stats["total_tokens"]
                self.n_samples += stats["n_samples"]
                if obs_on:
                    # Packed batches bypass DataLoader's collate metering;
                    # account the paper's padding-efficiency quantity here
                    # from the packer's own layout stats.
                    obs.inc("loader_real_tokens_total",
                            stats["total_tokens"] - stats["pad_tokens"])
                    obs.inc("loader_padded_slots_total",
                            stats["total_tokens"])
                    obs.set_gauge("loader_padding_efficiency",
                                  1.0 - self.pad_ratio)
                yield batch

        def seg_len(v):
            # v2 samples carry id ndarrays (len = token count directly);
            # v1 carries space-joined token strings.
            return len(v) if not isinstance(v, str) else len(v.split())

        def sample_len(s):
            return seg_len(s[0]) + seg_len(s[1]) + 3

        try:
            for raw_batch in inner_it:
                # (ds.epoch advanced at the iterator's first yield, before
                # any submit can run)
                for sample in raw_batch:
                    length = sample_len(sample)
                    ordinal = packer.add(length)
                    if ordinal is None:
                        submit(packer.emit_fullest())
                        yield from drain(block=False)
                        ordinal = packer.add(length)
                        assert ordinal is not None
                    store[ordinal] = sample
            while packer.open_rows:
                submit(packer.emit_fullest())
            yield from drain(block=True)
            assert not store
        finally:
            pool.shutdown(wait=False, cancel_futures=True)


class BertPretrainBinned(Binned):

    def _get_batch_size(self, batch):
        # Encoded batches are dicts; return_raw_samples batches are lists.
        if isinstance(batch, dict):
            return len(batch["input_ids"])
        return len(batch)


def get_bert_pretrain_data_loader(
    path,
    dp_rank=0,
    num_dp_groups=1,
    batch_size=64,
    num_workers=1,
    shuffle_buffer_size=16384,
    shuffle_buffer_warmup_factor=16,
    tokenizer=None,
    vocab_file=None,
    tokenizer_name=None,
    sequence_length_alignment=8,
    fixed_seq_lengths=None,
    ignore_index=-1,
    mlm_prob=0.15,
    emit_loss_mask=False,
    base_seed=12345,
    start_epoch=0,
    log_dir=None,
    log_level=None,
    return_raw_samples=False,
    prefetch=2,
    comm=None,
    pack_seq_length=None,
    pack_rows=None,
    pack_max_per_row=8,
    pack_horizon=None,
    pack_allow_uneven_epochs=False,
    worker_mode="thread",
    on_corrupt=None,
    follow_generations=False,
):
    """Build the BERT pretraining loader over balanced shards at ``path``.

    ``follow_generations=True`` serves a streaming-ingestion directory as
    a growing dataset: at every epoch boundary the loader re-reads the
    root manifest's generation gate and picks up newly published
    ``gen-<NNNN>/`` shards without a restart (mid-epoch publishes wait
    for the boundary; see ParquetDataset.maybe_refresh). Off by default —
    classic directories behave exactly as before.

    ``on_corrupt`` sets the startup shard-integrity policy ("fail" |
    "quarantine"; None defers to LDDL_TPU_ON_CORRUPT then "fail") — shards
    are checked against the ``.manifest.json`` their producer published;
    quarantine excludes corrupt shards loudly and continues on the rest.

    Auto-detects binned vs unbinned from the shard filenames and static vs
    dynamic masking from the parquet schema
    (ref: lddl/torch/bert.py:199-413). For TPU static shapes pass
    ``fixed_seq_lengths``: an int (unbinned) or a list with one padded
    length per bin.

    Sequence packing (``pack_seq_length`` + ``pack_rows``): several short
    samples share each fixed-length row with block-diagonal attention —
    batches gain ``segments``/``position_ids``/``cls_positions`` keys, NSP
    labels become [rows, pack_max_per_row], and the consumer is
    models.BertForPreTrainingPacked. Packing subsumes binning (every row
    is exactly pack_seq_length wide), so it requires unbinned shards.

    **Offline-packed directories** (preprocessed with
    ``pack_seq_length=...`` — see preprocess/packing.py) are detected
    automatically from the manifest's ``__meta__.packed`` entry (or a
    footer sniff) and stream their pre-packed rows zero-copy: no load-
    time packing runs at all, the stored row shape is authoritative
    (``pack_seq_length``, if passed, must match; ``pack_rows`` — default
    ``batch_size`` — sets rows per batch), and the batch contract is the
    packed one above. The greedy load-time packer remains the fallback
    for unpacked directories.

    ``dp_rank``/``num_dp_groups`` name the data-parallel group of this
    process — derive them from a device mesh with
    ``lddl_tpu.loader.process_dp_info(mesh)``. All processes in the same
    group receive identical batches (ref: lddl/torch_mp/bert.py:203-211).

    Shard I/O: worker streams acquire shards through the loader shard
    I/O pipeline (loader/shardcache.py) — StorageBackend-routed reads
    with depth-K read-ahead prefetch (``LDDL_TPU_LOADER_PREFETCH_SHARDS``),
    a generation-keyed read-through shard cache
    (``LDDL_TPU_LOADER_CACHE_BYTES``), and decode-ahead. Batch bytes are
    identical with the pipeline on or off; set both knobs to 0 for the
    fully synchronous pre-pipeline path.
    """
    import logging
    if tokenizer is None:
        from ..preprocess.tokenizer import get_tokenizer
        tokenizer = get_tokenizer(vocab_file=vocab_file,
                                  pretrained_model_name=tokenizer_name)
    logger = DatasetLogger(
        log_dir=log_dir,
        log_level=log_level if log_level is not None else logging.WARNING,
        rank=dp_rank,
    )
    file_paths = get_all_parquets_under(path)
    if not file_paths:
        raise ValueError("no parquet shards under {}".format(path))
    if follow_generations:
        # The initial set obeys the same pickup gate a refresh does, so
        # a generation mid-publish at startup is excluded consistently.
        file_paths, _ = generation_gate_filter(path, file_paths)
    n_before = len(file_paths)
    file_paths = verified_shard_paths(path, file_paths,
                                      on_corrupt=on_corrupt, logger=logger,
                                      comm=comm)
    n_quarantined = n_before - len(file_paths)
    try:
        bin_ids = get_all_bin_ids(file_paths)
    except ValueError as e:
        if n_quarantined:
            # Quarantine swallowed a whole bin: point the operator at the
            # corrupt shards just logged, not at the preprocessor.
            raise annotate_quarantine(e, n_quarantined) from e
        raise

    packed_shape = packed_shape_of_dir(path, file_paths)
    if packed_shape is not None:
        # OFFLINE-packed directory: every parquet row is an already-
        # packed training row, so the loader is a plain zero-copy row
        # stream + scatter encode — the greedy load-time pack loop below
        # never runs (it stays only as the fallback for unpacked dirs).
        L, P = packed_shape
        if pack_seq_length is not None and int(pack_seq_length) != L:
            raise ValueError(
                "shards under {} were packed offline at pack_seq_length="
                "{}, which the stored rows fix; requested {}".format(
                    path, L, pack_seq_length))
        if bin_ids:
            raise ValueError("offline-packed shards cannot be binned")
        if return_raw_samples:
            raise ValueError(
                "return_raw_samples over offline-packed shards is not "
                "supported (rows are packed training rows, not samples)")
        if fixed_seq_lengths is not None:
            raise ValueError(
                "offline-packed shards fix the row width at {}; "
                "fixed_seq_lengths does not apply".format(L))
        # Rows per batch: pack_rows when given (API parity with the
        # load-time packer), else the ordinary batch_size. Unlike the
        # stream packer, batch COUNTS are row-arithmetic (balanced ±1
        # shards), so multi-dp-group epochs stay lockstep — no
        # pack_allow_uneven_epochs needed.
        rows = int(pack_rows) if pack_rows is not None else int(batch_size)
        gen_snapshot = GenerationSnapshot(path) if follow_generations \
            else None
        try:
            dataset = ParquetDataset(
                file_paths,
                base_seed=base_seed,
                start_epoch=start_epoch,
                dp_rank=dp_rank,
                num_dp_groups=num_dp_groups,
                num_workers=num_workers,
                shuffle_buffer_size=shuffle_buffer_size,
                shuffle_buffer_warmup_factor=shuffle_buffer_warmup_factor,
                decode_record_batch=decode_record_batch,
                comm=comm,
                logger=logger,
                refresh=(GenerationFollower(path, on_corrupt=on_corrupt,
                                            snapshot=gen_snapshot)
                         if follow_generations else None),
            )
        except ValueError as e:
            if n_quarantined:
                raise annotate_quarantine(e, n_quarantined) from e
            raise
        return DataLoader(
            dataset,
            rows,
            collate_fn=BertPrepackedCollate(
                tokenizer, L, P, ignore_index=ignore_index,
                mlm_prob=mlm_prob, emit_loss_mask=emit_loss_mask),
            prefetch=prefetch,
            worker_mode=worker_mode,
        )

    packing = pack_seq_length is not None or pack_rows is not None
    if packing:
        if pack_seq_length is None or pack_rows is None:
            raise ValueError("packing needs BOTH pack_seq_length and "
                             "pack_rows")
        if num_dp_groups > 1 and not pack_allow_uneven_epochs:
            # Packed batch boundaries depend on each group's length mix,
            # so per-epoch batch COUNTS can differ by a few across dp
            # groups — a lockstep loop would deadlock in a collective at
            # the shortest group's end. Until a synchronized packed epoch
            # exists, the caller must bound steps itself (e.g.
            # itertools.islice to an allreduce-min of batch counts) and
            # acknowledge that with the override flag.
            raise ValueError(
                "sequence packing with num_dp_groups > 1 yields uneven "
                "per-group batch counts; pass "
                "pack_allow_uneven_epochs=True and bound your step loop "
                "(e.g. islice to the min batch count across groups)")
        if bin_ids:
            raise ValueError(
                "packing requires unbinned shards (rows are always exactly "
                "pack_seq_length wide, which subsumes binning); preprocess "
                "without --bin-size")
        if return_raw_samples:
            raise ValueError("return_raw_samples and packing are exclusive")

    # One snapshot for the whole loader: every bin's follower reads the
    # gate + listing from the same per-epoch-keyed cache.
    gen_snapshot = GenerationSnapshot(path) if follow_generations else None

    def make_dataset(paths, transform=None, bin_id=None):
        try:
            return ParquetDataset(
                paths,
                base_seed=base_seed,
                start_epoch=start_epoch,
                dp_rank=dp_rank,
                num_dp_groups=num_dp_groups,
                num_workers=num_workers,
                shuffle_buffer_size=shuffle_buffer_size,
                shuffle_buffer_warmup_factor=shuffle_buffer_warmup_factor,
                decode_record_batch=decode_record_batch,
                transform=transform,
                comm=comm,
                logger=logger,
                refresh=(GenerationFollower(path, bin_id=bin_id,
                                            on_corrupt=on_corrupt,
                                            snapshot=gen_snapshot)
                         if follow_generations else None),
            )
        except ValueError as e:
            # Divisibility/balance errors after a quarantine must name
            # the quarantine, not (only) the shard/dp-group arithmetic.
            if n_quarantined:
                raise annotate_quarantine(e, n_quarantined) from e
            raise

    def make_collate(fixed_seq_length):
        if return_raw_samples:
            return None
        return BertCollate(
            tokenizer,
            sequence_length_alignment=sequence_length_alignment,
            fixed_seq_length=fixed_seq_length,
            ignore_index=ignore_index,
            mlm_prob=mlm_prob,
            emit_loss_mask=emit_loss_mask,
        )

    if bin_ids:
        if fixed_seq_lengths is not None:
            if len(fixed_seq_lengths) != len(bin_ids):
                raise ValueError(
                    "fixed_seq_lengths has {} entries for {} bins".format(
                        len(fixed_seq_lengths), len(bin_ids)))
        else:
            fixed_seq_lengths = [None] * len(bin_ids)
        loaders = [
            DataLoader(
                make_dataset(get_file_paths_for_bin_id(file_paths, b),
                             bin_id=b),
                batch_size,
                collate_fn=make_collate(fixed_seq_lengths[b]),
                prefetch=prefetch,
                worker_mode=worker_mode,
            ) for b in bin_ids
        ]
        return BertPretrainBinned(loaders,
                                  base_seed=base_seed,
                                  start_epoch=start_epoch,
                                  logger=logger)
    if packing:
        inner = DataLoader(make_dataset(file_paths), batch_size,
                           collate_fn=None, prefetch=prefetch,
                           worker_mode=worker_mode)
        return PackedBertLoader(
            inner,
            BertPackedCollate(tokenizer, pack_seq_length, pack_rows,
                              pack_max_per_row, ignore_index=ignore_index,
                              mlm_prob=mlm_prob,
                              emit_loss_mask=emit_loss_mask),
            pack_seq_length, pack_rows, pack_max_per_row,
            pack_horizon=pack_horizon)
    fixed = fixed_seq_lengths
    if isinstance(fixed, (list, tuple)):
        if len(fixed) != 1:
            raise ValueError("unbinned data takes a single fixed_seq_length")
        fixed = fixed[0]
    return DataLoader(
        make_dataset(file_paths),
        batch_size,
        collate_fn=make_collate(fixed),
        prefetch=prefetch,
        worker_mode=worker_mode,
    )
