"""Batch iteration: worker-threaded or worker-process DataLoader +
synchronized Binned wrapper.

Reference parity: lddl/torch/dataloader.py. The reference rides
torch.utils.data.DataLoader worker *processes*
(lddl/torch/bert.py:386, persistent_workers=True); we offer both:

- ``worker_mode="thread"`` (default): the hot per-sample work (pyarrow
  decode, numpy collate) releases the GIL, threads share the batch memory
  with the consumer (no pickle copy), and determinism is trivial.
- ``worker_mode="process"``: one spawned process per worker, rebuilt each
  epoch from the dataset's pure (seed, epoch, dp, worker) stream
  definition — no state handoff. Batches cross the process boundary
  pickled, so this wins only when collate cost dominates the copy
  (GIL-bound tokenize-heavy transforms on many-core hosts). Both modes
  produce identical batches in identical order (tested).
"""

import queue
import threading

from ..utils import rng as lrng
from ..utils.logging import DatasetLogger


def _process_worker_main(dataset, worker_idx, epoch, batch_size, collate_fn,
                         rng_spec, out_q):
    """Top-level so spawn can import it; rebuilds the worker's stream and
    streams collated batches into the queue.

    Batches are pickled HERE (bytes on the queue), not by mp.Queue's
    feeder thread: a feeder-thread pickling error would silently drop the
    batch and still deliver a clean 'end' — pickling in this try block
    turns it into a forwarded error instead."""
    import pickle

    try:
        if rng_spec is not None:
            g = lrng.sample_rng(*rng_spec)
            collate = lambda b: collate_fn(b, g=g)  # noqa: E731
        else:
            collate = collate_fn or (lambda b: b)

        def put_batch(b):
            out_q.put(("batch", pickle.dumps(collate(b), protocol=-1)))

        batch = []
        for sample in dataset.worker_stream(epoch, worker_idx):
            batch.append(sample)
            if len(batch) == batch_size:
                put_batch(batch)
                batch = []
        if batch:
            put_batch(batch)
        out_q.put(("end", None))
    except BaseException:  # noqa: BLE001 - forwarded to consumer
        import traceback
        out_q.put(("error", traceback.format_exc()))


class DataLoader:
    """Iterates a ParquetDataset in batches.

    Epoch advance happens on ``__iter__`` (via dataset.start_epoch), like
    the reference's IterableDataset. Worker w collates its own stream into
    batches; the loader serves worker batches round-robin, so batch order
    is a pure function of (base_seed, epoch).
    """

    def __init__(self, dataset, batch_size, collate_fn=None, prefetch=2,
                 worker_mode="thread"):
        if batch_size < 1:
            raise ValueError("batch_size must be >= 1")
        if worker_mode not in ("thread", "process"):
            raise ValueError("worker_mode must be thread|process")
        self.dataset = dataset
        self.batch_size = batch_size
        self._user_collate = collate_fn  # None = raw samples (picklable)
        self._collate_fn = collate_fn or (lambda samples: samples)
        self._prefetch = max(1, prefetch)
        self._worker_mode = worker_mode

    @property
    def num_batches_per_worker(self):
        num_files_per_worker = (self.dataset.num_files_per_group //
                                self.dataset.num_workers)
        samples_per_worker = (self.dataset.num_samples_per_file *
                              num_files_per_worker)
        return (samples_per_worker - 1) // self.batch_size + 1

    def __len__(self):
        """Batches per epoch, accounting for each worker's final partial
        batch. (ref: lddl/torch/dataloader.py:96-105)"""
        return self.num_batches_per_worker * self.dataset.num_workers

    # Domain tag for per-worker collation RNG streams (dynamic masking) —
    # distinct from the shuffle-buffer streams so the two never correlate.
    _COLLATE_RNG_TAG = 0xC011

    def _worker_loop(self, stream, out_q, stop, collate):
        def put(item):
            # Bounded-queue put that gives up if the consumer abandoned the
            # epoch (e.g. partial iteration) so threads never leak blocked.
            while not stop.is_set():
                try:
                    out_q.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        try:
            batch = []
            for sample in stream:
                batch.append(sample)
                if len(batch) == self.batch_size:
                    if not put(("batch", collate(batch))):
                        return
                    batch = []
            if batch:
                if not put(("batch", collate(batch))):
                    return
            put(("end", None))
        except BaseException as e:  # noqa: BLE001 - forwarded to consumer
            put(("error", e))

    def _bind_collate(self, worker_idx):
        """Bind a per-(epoch, dp group, worker) RNG stream into the collate
        when it asks for one (dynamic masking)."""
        if not getattr(self._collate_fn, "needs_rng", False):
            return self._collate_fn
        ds = self.dataset
        g = lrng.sample_rng(ds.base_seed, self._COLLATE_RNG_TAG, ds.epoch,
                            ds.dp_rank, worker_idx)
        return lambda batch: self._collate_fn(batch, g=g)

    def _iter_process(self):
        import multiprocessing
        ctx = multiprocessing.get_context("spawn")
        ds = self.dataset
        epoch = ds.advance_epoch()
        n = ds.num_workers
        queues = [ctx.Queue(maxsize=self._prefetch) for _ in range(n)]
        rng = getattr(self._collate_fn, "needs_rng", False)
        import pickle
        procs = [
            ctx.Process(
                target=_process_worker_main,
                args=(ds, w, epoch, self.batch_size, self._user_collate,
                      ((ds.base_seed, self._COLLATE_RNG_TAG, epoch,
                        ds.dp_rank, w) if rng else None),
                      queues[w]),
                daemon=True)
            for w in range(n)
        ]
        live = list(range(n))
        try:
            # Inside the try: a start() failure (unpicklable dataset or
            # collate) must still terminate the workers already running.
            for p in procs:
                p.start()
            while live:
                for w in list(live):
                    while True:
                        # Timed get + liveness check: a worker killed
                        # without enqueueing (OOM killer, segfault in
                        # native code) must raise here, not hang the
                        # training loop forever.
                        try:
                            kind, payload = queues[w].get(timeout=5.0)
                            break
                        except queue.Empty:
                            p = procs[w]
                            if not p.is_alive():
                                raise RuntimeError(
                                    "loader worker {} died (exit code {}) "
                                    "without reporting".format(
                                        w, p.exitcode))
                    if kind == "error":
                        raise RuntimeError(
                            "loader worker {} failed:\n{}".format(w, payload))
                    if kind == "end":
                        live.remove(w)
                        continue
                    yield pickle.loads(payload)
        finally:
            for p in procs:
                if p.is_alive():
                    p.terminate()
            for p in procs:
                if p.pid is not None:  # join() on a never-started Process
                    p.join(timeout=5)  # raises

    def __iter__(self):
        if self._worker_mode == "process":
            yield from self._iter_process()
            return
        streams = self.dataset.start_epoch()
        stop = threading.Event()
        queues = [queue.Queue(maxsize=self._prefetch) for _ in streams]
        threads = [
            threading.Thread(target=self._worker_loop,
                             args=(s, q, stop, self._bind_collate(w)),
                             daemon=True)
            for w, (s, q) in enumerate(zip(streams, queues))
        ]
        for t in threads:
            t.start()
        live = list(range(len(queues)))
        try:
            while live:
                for w in list(live):
                    kind, payload = queues[w].get()
                    if kind == "error":
                        raise payload
                    if kind == "end":
                        live.remove(w)
                        continue
                    yield payload
        finally:
            stop.set()
            for t in threads:
                t.join(timeout=5)


class Binned:
    """One DataLoader per sequence-length bin; every iteration all ranks
    draw the same bin from the world RNG stream, weighted by remaining
    samples — identical choice with zero communication.
    (ref: lddl/torch/dataloader.py:32-91)
    """

    def __init__(self, dataloaders, base_seed=12345, start_epoch=0,
                 logger=None):
        self._dataloaders = dataloaders
        self._base_seed = base_seed
        self._epoch = start_epoch - 1
        self._logger = logger or DatasetLogger()

    def __len__(self):
        return sum(len(dl) for dl in self._dataloaders)

    @property
    def epoch(self):
        return self._epoch

    def _get_batch_size(self, batch):
        raise NotImplementedError("Binned is abstract: use a subclass that "
                                  "knows the batch structure")

    def __iter__(self):
        self._epoch += 1
        world_g = lrng.world_rng(self._base_seed, self._epoch)
        remaining = [len(dl.dataset) for dl in self._dataloaders]
        iters = [iter(dl) for dl in self._dataloaders]
        for i in range(len(self)):
            bin_id = lrng.choices(world_g,
                                  list(range(len(iters))),
                                  weights=remaining)[0]
            self._logger.to("rank").info(
                "iteration {} selects bin {}".format(i, bin_id))
            assert remaining[bin_id] > 0
            batch = next(iters[bin_id])
            remaining[bin_id] -= self._get_batch_size(batch)
            yield batch
        assert sum(remaining) == 0, (
            "bin bookkeeping out of sync: {} samples unaccounted".format(
                sum(remaining)))
