"""Batch iteration: worker-threaded or worker-process DataLoader +
synchronized Binned wrapper.

Reference parity: lddl/torch/dataloader.py. The reference rides
torch.utils.data.DataLoader worker *processes*
(lddl/torch/bert.py:386, persistent_workers=True); we offer both:

- ``worker_mode="thread"`` (default): the hot per-sample work (pyarrow
  decode, numpy collate) releases the GIL, threads share the batch memory
  with the consumer (no pickle copy), and determinism is trivial.
- ``worker_mode="process"``: PERSISTENT spawned workers (the reference's
  persistent_workers=True) — spawned once, each epoch is a command; the
  worker rebuilds its stream from the dataset's pure (seed, epoch, dp,
  worker) definition, no state handoff. Batches cross the process
  boundary pickled, so this wins only when collate cost dominates the
  copy (GIL-bound tokenize-heavy transforms on many-core hosts). Both
  modes produce identical batches in identical order (tested).
"""

import queue
import threading

from .. import observability as obs
from ..resilience import faults
from ..utils import rng as lrng
from ..utils.logging import DatasetLogger


class _EpochObserver:
    """Per-batch telemetry with the registry handles resolved ONCE per
    epoch (each ``obs.inc(name)`` is a registry dict lookup under a lock;
    at tens of thousands of batches/s that was the telemetry hot-path
    cost): the per-batch work is cached-handle increments only, and the
    padding-efficiency gauge (real tokens / padded slots, the paper's
    headline quantity) is folded into the end-of-epoch summary instead of
    being recomputed per batch. Read-only on the batch; constructed only
    when telemetry is enabled."""

    __slots__ = ("_latency", "_batches", "_samples", "_real", "_padded",
                 "_gauge")

    def __init__(self):
        reg = obs.registry()
        self._latency = reg.histogram("loader_batch_latency_seconds")
        self._batches = reg.counter("loader_batches_total")
        self._samples = reg.counter("loader_samples_total")
        self._real = reg.counter("loader_real_tokens_total")
        self._padded = reg.counter("loader_padded_slots_total")
        self._gauge = reg.gauge("loader_padding_efficiency")

    def batch(self, batch, dt_s):
        self._latency.observe(dt_s)
        self._batches.inc()
        if isinstance(batch, dict) and "attention_mask" in batch:
            mask = batch["attention_mask"]
            self._samples.inc(len(mask))
            self._real.inc(int(mask.sum()))
            self._padded.inc(int(mask.size))
        elif isinstance(batch, (list, tuple)):
            self._samples.inc(len(batch))

    def finish(self):
        """End-of-epoch gauge update from the process-cumulative totals —
        the same value the per-batch recomputation converged to."""
        padded = self._padded.total()
        if padded:
            self._gauge.set(self._real.total() / padded)


def _timed_collate(collate):
    """Wrap a collate once per epoch with the ``collate`` stage
    accumulator (attribution). One wrap, zero per-batch branches; the
    wrapped function is semantically identical, so batch bytes are
    untouched. Only called when telemetry is enabled."""
    import time
    from ..observability import attribution
    stage = attribution.stage_counter()
    pc = time.perf_counter

    def timed(batch, _c=collate, _s=stage, _pc=pc):
        t0 = _pc()
        out = _c(batch)
        _s.inc(_pc() - t0, stage="collate")
        return out

    return timed


def _stream_one_epoch(dataset, worker_idx, epoch, batch_size, collate_fn,
                      rng_spec, out_q):
    """Stream one epoch's collated batches into the queue.

    Batches are serialized HERE (one framed bytes payload per batch via
    qserde: pickle protocol 5 with out-of-band numpy buffers), not by
    mp.Queue's feeder thread — a feeder-thread pickling error would
    silently drop the batch and still deliver a clean 'end'; serializing
    in this try block turns it into a forwarded error instead."""
    from . import qserde

    try:
        if rng_spec is not None:
            g = lrng.sample_rng(*rng_spec)
            collate = lambda b: collate_fn(b, g=g)  # noqa: E731
        else:
            collate = collate_fn or (lambda b: b)
        if obs.enabled():
            # Spawned workers inherit LDDL_TPU_METRICS_DIR: their collate
            # stage seconds land in the child registry and reach the
            # fleet rollup via the colocated per-pid exports.
            collate = _timed_collate(collate)

        def put_batch(b):
            # Chaos-harness site: a "worker:kill" fault SIGKILLs this
            # worker here, before the batch is enqueued (supervision in
            # DataLoader._iter_process restarts + replays it).
            faults.fault_point("worker", "w{}".format(worker_idx))
            out_q.put(("batch", qserde.encode(collate(b))))

        batch = []
        for sample in dataset.worker_stream(epoch, worker_idx):
            batch.append(sample)
            if len(batch) == batch_size:
                put_batch(batch)
                batch = []
        if batch:
            put_batch(batch)
        out_q.put(("end", None))
    except BaseException:  # noqa: BLE001 - forwarded to consumer
        import traceback
        out_q.put(("error", traceback.format_exc()))


def _persistent_worker_main(dataset, worker_idx, batch_size, collate_fn,
                            cmd_q, out_q):
    """Persistent process-worker loop (the reference's
    persistent_workers=True, lddl/torch/bert.py:386): spawn once, then
    serve ("epoch", n, rng_spec) commands until ("stop",). The worker's
    pickled dataset copy never advances its epoch counter — every stream
    is the pure function dataset.worker_stream(epoch, w)."""
    while True:
        cmd = cmd_q.get()
        if cmd[0] == "stop":
            return
        _, epoch, rng_spec = cmd
        _stream_one_epoch(dataset, worker_idx, epoch, batch_size,
                          collate_fn, rng_spec, out_q)


class DataLoader:
    """Iterates a ParquetDataset in batches.

    Epoch advance happens on ``__iter__`` (via dataset.start_epoch), like
    the reference's IterableDataset. Worker w collates its own stream into
    batches; the loader serves worker batches round-robin, so batch order
    is a pure function of (base_seed, epoch).
    """

    def __init__(self, dataset, batch_size, collate_fn=None, prefetch=2,
                 worker_mode="thread"):
        if batch_size < 1:
            raise ValueError("batch_size must be >= 1")
        if worker_mode not in ("thread", "process"):
            raise ValueError("worker_mode must be thread|process")
        # A loader process armed ONLY through the env (LDDL_TPU_FLEET_DIR,
        # the documented equivalent of --fleet-telemetry) never calls
        # configure() or record(), so nothing would start the heartbeat or
        # point the metrics spool — and every obs.enabled() gate below
        # would read False. Kick the fleet once here, before any gate.
        try:
            from ..observability import fleet
            fleet.ensure_started()
        except Exception:  # noqa: BLE001 - telemetry must stay inert
            pass
        if worker_mode == "process":
            worker_mode = self._check_process_mode(dataset)
        self.dataset = dataset
        self.batch_size = batch_size
        self._user_collate = collate_fn  # None = raw samples (picklable)
        self._collate_fn = collate_fn or (lambda samples: samples)
        self._prefetch = max(1, prefetch)
        self._worker_mode = worker_mode
        self._procs = self._cmd_qs = self._out_qs = None
        self._local_qs = self._pump_stops = None
        self._finalizer = None
        self._pool_gen = 0
        self._epoch_active = False
        # Generation-aware datasets bump files_version when an epoch
        # boundary picks up newly published shards; persistent process
        # workers hold a PICKLED dataset copy, so a version change forces
        # a pool respawn (workers re-pickle the refreshed dataset).
        self._seen_files_version = getattr(dataset, "files_version", 0)
        # Cumulative process-mode IPC cost: framed qserde bytes and
        # batches received over this loader's lifetime (benchmarks read
        # these to report pickle-bytes/batch; always 0 in thread mode).
        self.queue_bytes = 0
        self.queue_batches = 0

    @staticmethod
    def _check_process_mode(dataset):
        """Process workers only pay off when spare cores exist: on a
        single-core host every recorded measurement shows them losing
        badly to thread mode (LOADER_BENCH.json w4proc rows: 66-415
        samples/s vs ~16k — spawn, pickle and queue costs with zero
        parallel upside), so fall back to threads with a warning instead
        of silently running a known-pathological configuration."""
        import os
        if os.environ.get("LDDL_TPU_FORCE_PROCESS_WORKERS"):
            return "process"  # tests / benchmarks of the mode itself
        from ..utils.cpus import loader_io_threads, pool_cpu_budget
        # Each worker process runs its own shard fetch/decode-ahead
        # threads (loader/shardcache.py); budget for them so "spare
        # cores" means cores actually left over, not the raw count.
        io_threads = loader_io_threads()
        budget = pool_cpu_budget(reserve=io_threads)
        if budget < 2:
            logger = getattr(dataset, "logger", None)
            msg = ("worker_mode='process' with a {}-CPU budget (usable "
                   "cores minus {} shard-I/O thread(s) per stream): "
                   "falling back to thread mode (process workers "
                   "measured 40-240x slower without spare cores — "
                   "LOADER_BENCH.json)".format(budget, io_threads))
            if logger is not None:
                try:
                    logger.to("rank").warning(msg)
                except Exception:
                    pass
            import warnings
            warnings.warn(msg, stacklevel=4)
            return "thread"
        return "process"

    @property
    def num_batches_per_worker(self):
        num_files_per_worker = (self.dataset.num_files_per_group //
                                self.dataset.num_workers)
        samples_per_worker = (self.dataset.num_samples_per_file *
                              num_files_per_worker)
        return (samples_per_worker - 1) // self.batch_size + 1

    def __len__(self):
        """Batches per epoch, accounting for each worker's final partial
        batch. (ref: lddl/torch/dataloader.py:96-105)"""
        return self.num_batches_per_worker * self.dataset.num_workers

    # Domain tag for per-worker collation RNG streams (dynamic masking) —
    # distinct from the shuffle-buffer streams so the two never correlate.
    _COLLATE_RNG_TAG = 0xC011

    def _worker_loop(self, stream, out_q, stop, collate):
        def put(item):
            # Bounded-queue put that gives up if the consumer abandoned the
            # epoch (e.g. partial iteration) so threads never leak blocked.
            while not stop.is_set():
                try:
                    out_q.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        if obs.enabled():
            collate = _timed_collate(collate)
        try:
            batch = []
            for sample in stream:
                batch.append(sample)
                if len(batch) == self.batch_size:
                    if not put(("batch", collate(batch))):
                        return
                    batch = []
            if batch:
                if not put(("batch", collate(batch))):
                    return
            put(("end", None))
        except BaseException as e:  # noqa: BLE001 - forwarded to consumer
            put(("error", e))

    def _bind_collate(self, worker_idx):
        """Bind a per-(epoch, dp group, worker) RNG stream into the collate
        when it asks for one (dynamic masking)."""
        if not getattr(self._collate_fn, "needs_rng", False):
            return self._collate_fn
        ds = self.dataset
        g = lrng.sample_rng(ds.base_seed, self._COLLATE_RNG_TAG, ds.epoch,
                            ds.dp_rank, worker_idx)
        return lambda batch: self._collate_fn(batch, g=g)

    def _ensure_worker_pool(self):
        """Spawn the persistent worker pool once (reference:
        persistent_workers=True); respawned automatically after a failed
        or abandoned epoch tears it down, or when a worker died while
        idle between epochs (OOM killer etc.)."""
        if self._procs is not None:
            if all(p.is_alive() for p in self._procs):
                return
            self.shutdown_workers()
        import multiprocessing
        import weakref
        ctx = multiprocessing.get_context("spawn")
        ds = self.dataset
        n = ds.num_workers
        self._cmd_qs = [ctx.Queue() for _ in range(n)]
        self._out_qs = [ctx.Queue(maxsize=self._prefetch) for _ in range(n)]
        procs = [
            ctx.Process(
                target=_persistent_worker_main,
                args=(ds, w, self.batch_size, self._user_collate,
                      self._cmd_qs[w], self._out_qs[w]),
                daemon=True)
            for w in range(n)
        ]
        try:
            for p in procs:
                p.start()
        except BaseException:
            for p in procs:
                if p.is_alive():
                    p.terminate()
            raise
        self._procs = procs
        self._local_qs = [None] * n
        self._pump_stops = [None] * n
        for w in range(n):
            self._start_pump(w)
        self._pool_gen += 1
        # GC safety net: daemon workers die with the interpreter anyway,
        # but a finalizer releases them as soon as the loader is dropped.
        self._finalizer = weakref.finalize(
            self, DataLoader._shutdown_procs, procs)

    @staticmethod
    def _shutdown_procs(procs, grace_s=0):
        if grace_s:
            for p in procs:
                if p.pid is not None:
                    p.join(timeout=grace_s)
        for p in procs:
            if p.is_alive():
                p.terminate()
        for p in procs:
            if p.pid is not None:
                p.join(timeout=5)

    def shutdown_workers(self):
        """Stop persistent process workers (no-op in thread mode):
        graceful ("stop",) command with a short grace period, then
        terminate stragglers."""
        if self._procs is None:
            return
        for q in self._cmd_qs:
            try:
                q.put(("stop",))
            except Exception:  # noqa: BLE001 - queue may be broken
                pass
        self._shutdown_procs(self._procs, grace_s=2)
        if self._pump_stops is not None:
            for stop in self._pump_stops:
                if stop is not None:
                    stop.set()
        if self._finalizer is not None:
            self._finalizer.detach()
        self._procs = self._cmd_qs = self._out_qs = None
        self._local_qs = self._pump_stops = None
        self._finalizer = None

    # A dead process worker (OOM killer, preemption, segfault in native
    # code) is restarted at most this many times per worker per epoch;
    # the second death of the same worker fails fast with a named error.
    _MAX_WORKER_RESTARTS = 1
    # How long a queue get waits before re-checking worker liveness.
    _POLL_TIMEOUT_S = 5.0

    @staticmethod
    def _pump_worker_queue(mp_q, local_q, stop):
        """Forward worker output from the mp queue onto an in-process
        queue from a SACRIFICIAL daemon thread. mp.Queue.get's timeout
        covers only the initial poll — once a frame header arrives, the
        payload read blocks until complete, and a frame torn by a SIGKILL
        mid-put never completes (the parent holds a write end, so no EOF
        either). The training loop therefore must never read the pipe
        directly: if this thread wedges on a torn frame, the supervisor
        simply abandons it with the dead worker's queues. The local queue
        is size-1 so the mp queue's prefetch bound still backpressures
        the worker."""
        while not stop.is_set():
            try:
                item = mp_q.get(timeout=0.5)
            except queue.Empty:
                continue
            except Exception:  # noqa: BLE001 - torn pipe / unpickling
                item = ("pump_torn", None)
            while not stop.is_set():
                try:
                    local_q.put(item, timeout=0.5)
                    break
                except queue.Full:
                    continue
            if item[0] == "pump_torn":
                return

    def _start_pump(self, w):
        stop = threading.Event()
        local_q = queue.Queue(maxsize=1)
        t = threading.Thread(target=self._pump_worker_queue,
                             args=(self._out_qs[w], local_q, stop),
                             daemon=True)
        t.start()
        self._local_qs[w] = local_q
        self._pump_stops[w] = stop

    def _restart_worker(self, w):
        """Replace dead worker ``w`` with a fresh spawn on FRESH queues
        and a fresh pump thread (a SIGKILL mid-``put`` can leave a torn
        frame in the old queue — possibly with the old pump wedged on it —
        so both are abandoned wholesale). The pool lists mutate in place
        so the GC finalizer and any local aliases track the replacement."""
        import multiprocessing
        ctx = multiprocessing.get_context("spawn")
        self._pump_stops[w].set()
        for q in (self._cmd_qs[w], self._out_qs[w]):
            try:
                q.close()
                q.cancel_join_thread()
            except Exception:  # noqa: BLE001 - queue may already be broken
                pass
        self._cmd_qs[w] = ctx.Queue()
        self._out_qs[w] = ctx.Queue(maxsize=self._prefetch)
        p = ctx.Process(
            target=_persistent_worker_main,
            args=(self.dataset, w, self.batch_size, self._user_collate,
                  self._cmd_qs[w], self._out_qs[w]),
            daemon=True)
        p.start()
        old = self._procs[w]
        self._procs[w] = p
        self._start_pump(w)
        try:
            old.join(timeout=1)
        except Exception:  # noqa: BLE001
            pass

    def _handle_worker_death(self, w, epoch, rng_spec, restarts, served,
                             skip):
        """Supervision policy: restart a dead worker once and replay its
        pure (seed, epoch, dp, worker) stream deterministically — the
        first ``served[w]`` batches are discarded unopened, so the
        consumer-visible batch sequence is unchanged. A second death of
        the same worker raises a named error instead of looping."""
        import warnings
        code = self._procs[w].exitcode
        restarts[w] += 1
        obs.inc("loader_worker_deaths_total", worker=w)
        if restarts[w] > self._MAX_WORKER_RESTARTS:
            obs.event("loader.worker_failed", worker=w, exit_code=code)
            raise RuntimeError(
                "loader worker {} died again after a restart (last exit "
                "code {}); failing fast — a worker that keeps dying needs "
                "a human, not another retry".format(w, code))
        obs.inc("loader_worker_restarts_total", worker=w)
        obs.event("loader.worker_restart", worker=w, exit_code=code,
                  replayed_batches=served[w])
        warnings.warn(
            "loader worker {} died (exit code {}); restarting it once and "
            "replaying its deterministic stream (discarding {} already-"
            "served batch(es))".format(w, code, served[w]), stacklevel=3)
        self._restart_worker(w)
        self._cmd_qs[w].put(("epoch", epoch, rng_spec))
        skip[w] = served[w]

    def _iter_process(self):
        from . import qserde
        ds = self.dataset
        epoch = ds.advance_epoch()
        version = getattr(ds, "files_version", 0)
        if version != self._seen_files_version:
            # The dataset picked up a new generation at this boundary:
            # the workers' pickled copies are stale — respawn the pool so
            # every worker re-pickles the refreshed file list.
            self._seen_files_version = version
            self.shutdown_workers()
        rng = getattr(self._collate_fn, "needs_rng", False)
        if self._epoch_active:
            # A previous epoch's iterator is still mid-stream on the
            # shared queues (partially consumed and kept alive): its
            # leftovers would masquerade as this epoch's data. Tear down
            # and respawn for a clean slate.
            self.shutdown_workers()
            self._epoch_active = False
        self._ensure_worker_pool()
        gen = self._pool_gen
        self._epoch_active = True
        procs, local_qs = self._procs, self._local_qs
        n = len(procs)

        def rng_spec(w):
            return ((ds.base_seed, self._COLLATE_RNG_TAG, epoch, ds.dp_rank,
                     w) if rng else None)

        for w in range(n):
            self._cmd_qs[w].put(("epoch", epoch, rng_spec(w)))
        live = list(range(n))
        served = [0] * n    # batches yielded to the consumer, per worker
        restarts = [0] * n  # deaths survived this epoch, per worker
        skip = [0] * n      # replayed batches to discard after a restart
        obs_on = obs.enabled()
        if obs_on:
            import time as _time
            from ..observability import attribution
            stage, pc = attribution.stage_counter(), _time.perf_counter
        try:
            while live:
                for w in list(live):
                    payload = None
                    while True:
                        # Timed get + liveness check against the PUMPED
                        # in-process queue (never the mp pipe itself — see
                        # _pump_worker_queue): a worker killed without
                        # enqueueing (OOM killer, segfault in native code)
                        # must be detected here, not hang the training
                        # loop forever. Batches already pumped when the
                        # worker died are a valid stream prefix and are
                        # consumed normally first.
                        try:
                            kind, payload = local_qs[w].get(
                                timeout=self._POLL_TIMEOUT_S)
                        except queue.Empty:
                            if procs[w].is_alive():
                                continue
                            self._handle_worker_death(
                                w, epoch, rng_spec(w), restarts, served,
                                skip)
                            continue
                        if kind == "pump_torn":
                            # A SIGKILL mid-put tore the queue pipe; only
                            # a dead worker excuses that.
                            if procs[w].is_alive():
                                raise RuntimeError(
                                    "loader worker {} output queue broke "
                                    "while the worker is alive".format(w))
                            self._handle_worker_death(
                                w, epoch, rng_spec(w), restarts, served,
                                skip)
                            continue
                        if kind == "batch" and skip[w] > 0:
                            skip[w] -= 1  # replayed duplicate: drop unopened
                            continue
                        break
                    if kind == "error":
                        raise RuntimeError(
                            "loader worker {} failed:\n{}".format(w, payload))
                    if kind == "end":
                        if skip[w] > 0:
                            raise RuntimeError(
                                "loader worker {} replay ended {} batch(es) "
                                "early; its stream is not reproducing "
                                "deterministically".format(w, skip[w]))
                        live.remove(w)
                        continue
                    served[w] += 1
                    self.queue_bytes += len(payload)
                    self.queue_batches += 1
                    if obs_on:
                        # IPC stage: the cross-process payload decode the
                        # thread mode never pays (queue WAIT is already
                        # covered by the batch_wait boundary upstream).
                        t0 = pc()
                        decoded = qserde.decode(payload)
                        stage.inc(pc() - t0, stage="ipc")
                        yield decoded
                    else:
                        yield qserde.decode(payload)
        finally:
            if live:
                # Failed or abandoned mid-epoch: workers are mid-stream
                # with no way to fast-forward — tear the pool down (next
                # epoch respawns it), UNLESS a newer epoch already
                # replaced this pool (a stale abandoned iterator being
                # GC'd must not kill the successor's workers).
                if self._pool_gen == gen and self._procs is not None:
                    self.shutdown_workers()
            if self._pool_gen == gen:
                self._epoch_active = False

    def __iter__(self):
        inner = (self._iter_process() if self._worker_mode == "process"
                 else self._iter_thread())
        if not obs.enabled():
            # Telemetry off: the raw iterator, zero per-batch overhead
            # (the no-op-mode guard in tests/test_observability.py holds
            # the whole loader hot path to this).
            yield from inner
            return
        yield from self._iter_instrumented(inner)

    def _iter_instrumented(self, inner):
        """Top-level loader span + per-batch latency/padding accounting.
        Wall time between consumer next() calls is the batch latency the
        training loop actually experiences (prefetch included). The same
        two timestamps also feed the attribution boundary pair:
        ``batch_wait`` (consumer blocked in next()) and ``step_gap``
        (consumer away between batches) partition the epoch wall exactly
        — the input-share those yield is what the bound verdict reads."""
        import time
        from ..observability import attribution
        watcher = _EpochObserver()
        stage = attribution.stage_counter()
        try:
            with obs.span("loader.epoch", mode=self._worker_mode,
                          batch_size=self.batch_size):
                t0 = time.perf_counter()
                for batch in inner:
                    t_ready = time.perf_counter()
                    watcher.batch(batch, t_ready - t0)
                    stage.inc(t_ready - t0, stage="batch_wait")
                    yield batch
                    t0 = time.perf_counter()
                    stage.inc(t0 - t_ready, stage="step_gap")
        finally:
            # Abandoned epochs still summarize what they served.
            watcher.finish()

    def attribution_snapshot(self):
        """Critical-path attribution accumulated so far in this process:
        per-stage self-time seconds, per-stage shares of the observed
        wall, and the bound verdict (input-bound / compute-bound /
        balanced). None when telemetry is off or nothing has iterated.
        Registry-wide by design — stages recorded by worker threads and
        the device prefetcher all land in the same accumulator."""
        from ..observability import attribution
        return attribution.snapshot()

    def _iter_thread(self):
        streams = self.dataset.start_epoch()
        stop = threading.Event()
        queues = [queue.Queue(maxsize=self._prefetch) for _ in streams]
        threads = [
            threading.Thread(target=self._worker_loop,
                             args=(s, q, stop, self._bind_collate(w)),
                             daemon=True)
            for w, (s, q) in enumerate(zip(streams, queues))
        ]
        for t in threads:
            t.start()
        live = list(range(len(queues)))
        try:
            while live:
                for w in list(live):
                    kind, payload = queues[w].get()
                    if kind == "error":
                        raise payload
                    if kind == "end":
                        live.remove(w)
                        continue
                    yield payload
        finally:
            stop.set()
            for t in threads:
                t.join(timeout=5)


class _DevicePrefetcher:
    """Iterable produced by :func:`prefetch_to_device` (re-iterable: each
    ``iter()`` runs one epoch of the wrapped loader, like DataLoader)."""

    def __init__(self, loader, device_put, depth):
        self._loader = loader
        self._device_put = device_put
        self._depth = depth

    def __len__(self):
        return len(self._loader)

    def __iter__(self):
        stop = threading.Event()
        q = queue.Queue(maxsize=self._depth)

        def put(item):
            # Stop-aware bounded put (terminal markers included): an
            # abandoned consumer must never leave this thread blocked on
            # a full queue forever.
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        obs_on = obs.enabled()
        if obs_on:
            from ..observability import attribution
            reg = obs.registry()
            batches = reg.counter("loader_prefetch_batches_total")
            wait = reg.histogram("loader_prefetch_wait_seconds")
            stage = attribution.stage_counter()
        device_put = self._device_put
        if obs_on:
            import time as _time

            def device_put(b, _d=self._device_put, _s=stage,
                           _pc=_time.perf_counter):
                # h2d stage: the dispatch cost of the transfer (the
                # transfer itself is asynchronous — overlap is the point).
                t0 = _pc()
                out = _d(b)
                _s.inc(_pc() - t0, stage="h2d")
                return out

        def produce():
            try:
                for batch in self._loader:
                    # device_put dispatches the H2D transfer
                    # asynchronously; the consumer's current step overlaps
                    # with the NEXT batch's host collate + transfer.
                    if not put(("batch", device_put(batch))):
                        return
                put(("end", None))
            except BaseException as e:  # noqa: BLE001 - forwarded
                put(("error", e))

        t = threading.Thread(target=produce, daemon=True)
        t.start()
        try:
            import time
            t_yield = None
            while True:
                t0 = time.perf_counter() if obs_on else 0.0
                if obs_on and t_yield is not None:
                    # The consumer was away running its step: that gap is
                    # the compute side of the outermost boundary pair.
                    stage.inc(t0 - t_yield, stage="prefetch_gap")
                kind, payload = q.get()
                if kind == "error":
                    raise payload
                if kind == "end":
                    return
                if obs_on:
                    dt = time.perf_counter() - t0
                    batches.inc()
                    wait.observe(dt)
                    stage.inc(dt, stage="prefetch_wait")
                    t_yield = time.perf_counter()
                yield payload
        finally:
            stop.set()
            t.join(timeout=5)


def prefetch_to_device(loader, device_put=None, depth=2):
    """Double-buffered host->device pipeline: a background thread drains
    ``loader`` and runs ``device_put`` (default ``jax.device_put``, which
    dispatches transfers asynchronously) up to ``depth`` batches ahead of
    the consumer, so host collate + H2D transfer overlap with the running
    train step instead of serializing with it.

    Pass ``device_put=lambda b: to_device_batch(b, mesh)`` to land
    globally-sharded batches on a device mesh (benchmarks/mock_train.py
    --with-model does). The wrapper is re-iterable — each ``iter()``
    advances the wrapped loader one epoch — and order-preserving, so the
    determinism contract is untouched. Telemetry (when armed):
    ``loader_prefetch_batches_total`` and ``loader_prefetch_wait_seconds``
    (time the consumer actually blocked on the queue — near zero when the
    pipeline keeps up)."""
    if device_put is None:
        import jax
        device_put = jax.device_put
    return _DevicePrefetcher(loader, device_put, max(1, depth))


class Binned:
    """One DataLoader per sequence-length bin; every iteration all ranks
    draw the same bin from the world RNG stream, weighted by remaining
    samples — identical choice with zero communication.
    (ref: lddl/torch/dataloader.py:32-91)
    """

    def __init__(self, dataloaders, base_seed=12345, start_epoch=0,
                 logger=None):
        self._dataloaders = dataloaders
        self._base_seed = base_seed
        self._epoch = start_epoch - 1
        self._logger = logger or DatasetLogger()

    def __len__(self):
        return sum(len(dl) for dl in self._dataloaders)

    @property
    def epoch(self):
        return self._epoch

    def _get_batch_size(self, batch):
        raise NotImplementedError("Binned is abstract: use a subclass that "
                                  "knows the batch structure")

    def shutdown_workers(self):
        """Stop every bin loader's persistent process workers (no-op in
        thread mode)."""
        for dl in self._dataloaders:
            dl.shutdown_workers()

    def attribution_snapshot(self):
        """Critical-path attribution + bound verdict across every bin
        (the stage accumulator is registry-wide; see
        DataLoader.attribution_snapshot)."""
        from ..observability import attribution
        return attribution.snapshot()

    def __iter__(self):
        self._epoch += 1
        # Refresh every bin dataset BEFORE sizing the epoch: the
        # remaining-sample bookkeeping below and each bin's own epoch
        # advance must agree on one file set (maybe_refresh is once per
        # epoch, so the advance inside iter(dl) will not refresh again).
        for dl in self._dataloaders:
            refresh = getattr(dl.dataset, "maybe_refresh", None)
            if refresh is not None:
                refresh()
        world_g = lrng.world_rng(self._base_seed, self._epoch)
        remaining = [len(dl.dataset) for dl in self._dataloaders]
        iters = [iter(dl) for dl in self._dataloaders]
        bin_ids = list(range(len(iters)))  # allocation-free hot loop
        obs_on = obs.enabled()
        for i in range(len(self)):
            bin_id = lrng.choices(world_g, bin_ids, weights=remaining)[0]
            self._logger.to("rank").info(
                "iteration {} selects bin {}".format(i, bin_id))
            if obs_on:
                obs.inc("loader_bin_choice_total", bin=bin_id)
            assert remaining[bin_id] > 0
            batch = next(iters[bin_id])
            remaining[bin_id] -= self._get_batch_size(batch)
            yield batch
        assert sum(remaining) == 0, (
            "bin bookkeeping out of sync: {} samples unaccounted".format(
                sum(remaining)))
        # Let each bin iterator finish NATURALLY (consume its end-of-epoch
        # marker): count-based iteration leaves generators suspended on
        # their last yield, and closing a suspended process-mode iterator
        # looks like mid-epoch abandonment — tearing down the persistent
        # worker pools every epoch.
        for it in iters:
            leftover = next(it, None)
            assert leftover is None, "bin served a batch past its count"
