"""Streaming shard datasets with deterministic epoch-seeded shuffling.

Reference parity: lddl/torch/datasets.py and the model-parallel
generalization lddl/torch_mp/datasets.py. One implementation covers both:
sharding is always by *data-parallel group* (``dp_rank``), which equals the
global rank in plain DP and the Megatron dp_rank in 3D-parallel layouts —
on TPU both fall out of the device mesh (see loader/sharding.py).

Determinism contract (ref: lddl/torch/datasets.py:227-286):
- Epoch k derives every random choice from (base_seed, epoch):
  a world-identical file shuffle, then per-(dp_rank, worker) streams for
  the shuffle buffer. Restarting with ``start_epoch=k`` reproduces epoch k
  exactly — resume is recomputation by seeding, no state files.
- All ranks of one dp group draw identical files, buffers, batches.
"""

import os

from ..parallel.distributed import LocalCommunicator
from ..utils import rng as lrng
from ..utils.fs import (
    get_num_samples_of_parquet,
    read_num_samples_cache,
    trusted_num_samples_entries,
)
from ..utils.logging import DatasetLogger
from ..utils.types import File


def verified_shard_paths(path, file_paths, on_corrupt=None, logger=None,
                         comm=None):
    """Startup integrity gate shared by the loader factories: verify the
    shards against their directories' ``.manifest.json`` (written by the
    preprocessor/balancer; absent manifests are trusted as-is, e.g. for
    pre-manifest data).

    ``on_corrupt`` is ``"fail"`` (default, raise naming every corrupt
    shard) or ``"quarantine"`` (exclude corrupt shards, log each exclusion
    loudly, and return the survivors — downstream count/divisibility
    checks then account for the exclusion explicitly). ``None`` defers to
    ``LDDL_TPU_ON_CORRUPT`` then ``"fail"``. Raises if quarantine leaves
    no shard at all."""
    from ..resilience.integrity import verify_shards
    if on_corrupt is None:
        on_corrupt = os.environ.get("LDDL_TPU_ON_CORRUPT", "fail")
    log = None
    if logger is not None:
        log = lambda msg: logger.to("rank").warning(msg)  # noqa: E731
    good, excluded = verify_shards(file_paths, on_corrupt=on_corrupt,
                                   log=log, comm=comm)
    if not good:
        raise ValueError(
            "every parquet shard under {} was quarantined as corrupt; "
            "re-run the producing stage".format(path))
    return good


def annotate_quarantine(exc, n_quarantined):
    """Re-raise a downstream shard-set error (bin contiguity, dp-group
    divisibility, balance) with the quarantine called out: the operator
    must be pointed at the corrupt shards just logged, not at their
    shard/worker configuration."""
    return ValueError(
        "{} (note: {} corrupt shard(s) were quarantined at startup, which "
        "changed the shard set — re-run the producing stage to restore "
        "them, or adjust num_dp_groups/num_workers to the surviving "
        "count)".format(exc, n_quarantined))


class ShuffleBuffer:
    """Streaming shuffle: warmup fills the buffer at ``warmup_factor``:1,
    then each new sample swap-replaces a random buffered sample, which is
    yielded; the tail is shuffled and drained.
    (ref: lddl/torch/datasets.py:46-109)
    """

    def __init__(self, files, max_num_samples_to_yield, decode_record_batch,
                 size, warmup_factor, g, logger=None):
        num_wasted = sum(f.num_samples for f in files) - max_num_samples_to_yield
        assert 0 <= num_wasted <= len(files)
        self._files = files
        self._max_num_samples_to_yield = max_num_samples_to_yield
        self._decode_record_batch = decode_record_batch
        self._size = size
        self._warmup_factor = warmup_factor
        self._g = g
        self._logger = logger

    @property
    def num_samples(self):
        return sum(f.num_samples for f in self._files)

    def __iter__(self):
        from .. import observability as obs
        buffer = []
        num_to_yield = min(self._max_num_samples_to_yield, self.num_samples)
        remaining = num_to_yield
        # Telemetry is hoisted out of the per-sample loop: enabled() is
        # checked once per epoch, and the fill gauge samples every 1024
        # yields (a gauge is a level, not a rate — sampling loses nothing).
        obs_on = obs.enabled()
        gauge = obs.registry().gauge(
            "loader_shuffle_buffer_fill",
            help="shuffle-buffer occupancy / configured size") if obs_on \
            else None
        decode = self._decode_record_batch
        if obs_on:
            # Attribution stages, hoisted like the gauge: shard_read is
            # the blocking parquet read, decode is the time spent inside
            # the sample generator (timed per resume — two perf_counter
            # reads per sample, same budget as the swap-replace itself).
            import time as _time
            from ..observability import attribution
            stage = attribution.stage_counter()
            pc = _time.perf_counter

            def decode(rb, _d=self._decode_record_batch, _s=stage, _pc=pc):
                it = iter(_d(rb))
                while True:
                    t0 = _pc()
                    try:
                        sample = next(it)
                    except StopIteration:
                        _s.inc(_pc() - t0, stage="decode")
                        return
                    _s.inc(_pc() - t0, stage="decode")
                    yield sample

        # Shard acquisition goes through the shard I/O pipeline
        # (shardcache.shard_tables): StorageBackend-routed reads,
        # read-ahead prefetch + generation-keyed cache + decode-ahead
        # when enabled, the verbatim synchronous read_table path when
        # not. Either way shards arrive in exactly self._files order,
        # so the sample stream is byte-identical.
        from .shardcache import shard_tables
        for f, table in shard_tables(self._files, logger=self._logger):
            for record_batch in table.to_batches():
                for sample in decode(record_batch):
                    if remaining <= 0:
                        return
                    warmup_cap = (num_to_yield - remaining + 1) * self._warmup_factor
                    if len(buffer) >= min(self._size, warmup_cap):
                        idx = int(self._g.integers(0, len(buffer)))
                        yield buffer[idx]
                        buffer[idx] = sample
                        remaining -= 1
                        if gauge is not None and remaining % 1024 == 0:
                            gauge.set(len(buffer) / max(self._size, 1))
                    else:
                        buffer.append(sample)
        lrng.shuffle(self._g, buffer)
        for sample in buffer:
            if remaining <= 0:
                return
            yield sample
            remaining -= 1


class ParquetDataset:
    """Balanced parquet shards -> per-(dp_rank, worker) sample streams.

    ``file_paths`` must be the balanced output of lddl_tpu.balance (all
    counts equal ±1); files are truncated to the min count so every dp
    group sees exactly the same number of samples per epoch.
    """

    def __init__(
        self,
        file_paths,
        base_seed=12345,
        start_epoch=0,
        dp_rank=0,
        num_dp_groups=1,
        num_workers=1,
        shuffle_buffer_size=16384,
        shuffle_buffer_warmup_factor=16,
        decode_record_batch=None,
        transform=None,
        comm=None,
        logger=None,
        refresh=None,
    ):
        if decode_record_batch is None:
            raise ValueError("decode_record_batch is required")
        if not file_paths:
            raise ValueError("no input shard files")
        num_workers = max(1, num_workers)
        if len(file_paths) % num_dp_groups != 0:
            raise ValueError(
                "{} files not divisible by {} data-parallel groups".format(
                    len(file_paths), num_dp_groups))
        if (len(file_paths) // num_dp_groups) % num_workers != 0:
            raise ValueError(
                "{} files per dp group not divisible by {} workers".format(
                    len(file_paths) // num_dp_groups, num_workers))
        self._base_seed = base_seed
        self._epoch = start_epoch - 1
        self._dp_rank = dp_rank
        self._num_dp_groups = num_dp_groups
        self._num_workers = num_workers
        self._shuffle_buffer_size = shuffle_buffer_size
        self._shuffle_buffer_warmup_factor = shuffle_buffer_warmup_factor
        self._decode_record_batch = decode_record_batch
        self._transform = transform
        self._logger = logger or DatasetLogger()
        # ``refresh``: optional picklable callable returning the CURRENT
        # verified file list for a growing (multi-generation) directory;
        # checked once per epoch boundary (see maybe_refresh). The comm is
        # kept for cross-rank agreement but never pickled — process-mode
        # workers receive refreshed file lists via a pool respawn, they
        # never refresh themselves.
        self._refresh = refresh
        self._comm = comm
        self._files_version = 0
        self._refreshed_for = None
        self._files = self._census(sorted(file_paths),
                                   comm or LocalCommunicator())
        self._num_samples_per_file = self._validate_counts(self._files)

    def _validate_counts(self, files):
        """The ±1 balance checks every file set must pass; returns the
        per-file (min) count every file is truncated to."""
        counts = [f.num_samples for f in files]
        lo, hi = min(counts), max(counts)
        if not (lo == hi or lo + 1 == hi):
            raise ValueError(
                "input shards not balanced (counts range {}..{}); run "
                "lddl_tpu.balance first".format(lo, hi))
        if lo == 0:
            raise ValueError("input shards contain empty files")
        # Truncate to the min count so every file contributes equally.
        lost = sum(counts) - lo * len(files)
        if lost:
            self._logger.to("rank").warning(
                "dropping {} sample(s) to equalize shard counts".format(lost))
        return lo

    def __getstate__(self):
        state = self.__dict__.copy()
        # Communicators do not pickle, and pickled copies (process-mode
        # workers) must never refresh independently: their file list is
        # whatever the parent held at spawn, replaced wholesale by a pool
        # respawn when the parent picks up a new generation.
        state["_comm"] = None
        state["_refresh"] = None
        return state

    def _census(self, file_paths, comm, on_error="raise"):
        """Per-file counts from the .num_samples.json cache; strided footer
        reads + allreduce only for entries the cache cannot vouch for.
        (ref: lddl/torch/datasets.py:161-195)

        Trust is per entry (utils.fs.trusted_num_samples_entries): sized
        caches — the ingest service records each shard's byte length —
        validate entry-by-entry, so appending a generation or flushing a
        tail recounts only the shards that actually changed. Legacy
        caches keep the all-or-nothing key-set check; a distrusted cache
        is logged so the fallback is visible.

        The collective is shape-invariant: every rank always allreduces a
        full-length vector, with each index contributed by exactly its
        stride owner (cache value if it trusts the entry, footer read
        otherwise). Trust is a LOCAL judgement — a stale NFS attribute
        cache can make ranks disagree — so neither collective
        participation nor the length of the vector may depend on it, and
        every rank ends up using the identical, owner-decided counts."""
        dir_counts = {}
        for d in sorted({os.path.dirname(p) for p in file_paths}):
            cached = read_num_samples_cache(d)
            trusted, untrusted = trusted_num_samples_entries(d, cached)
            if cached is not None and untrusted:
                self._logger.to("rank").warning(
                    ".num_samples.json in {} cannot vouch for {} shard(s); "
                    "recomputing those counts from parquet footers".format(
                        d, len(untrusted)))
            for name, n in trusted.items():
                dir_counts[os.path.join(d, name)] = n
        counts = [0] * len(file_paths)
        for i in range(comm.rank, len(file_paths), comm.world_size):
            p = file_paths[i]
            n = dir_counts.get(p)
            if n:
                counts[i] = int(n)
            elif on_error == "raise":
                counts[i] = get_num_samples_of_parquet(p)
            else:
                # Sentinel mode (epoch-boundary refresh): a failed footer
                # read must not abandon the collective other ranks are
                # already waiting in — poison the count instead; the
                # allreduce spreads it so every rank defers identically.
                try:
                    counts[i] = get_num_samples_of_parquet(p)
                except Exception:  # noqa: BLE001  lddl: disable=swallowed-error
                    counts[i] = -(1 << 40)
        counts = comm.allreduce_sum(counts)
        return [File(p, int(n)) for p, n in zip(file_paths, counts)]

    @property
    def base_seed(self):
        return self._base_seed

    @property
    def dp_rank(self):
        return self._dp_rank

    @property
    def num_dp_groups(self):
        return self._num_dp_groups

    @property
    def num_files_per_group(self):
        return len(self._files) // self._num_dp_groups

    @property
    def num_samples_per_file(self):
        return self._num_samples_per_file

    @property
    def num_workers(self):
        return self._num_workers

    def __len__(self):
        """Samples one dp group sees per epoch."""
        return self._num_samples_per_file * self.num_files_per_group

    @property
    def epoch(self):
        return self._epoch

    @property
    def files_version(self):
        """Bumped whenever maybe_refresh changes the file set — consumers
        holding derived state (process-worker pools with pickled dataset
        copies) watch this to know when to rebuild."""
        return self._files_version

    def maybe_refresh(self):
        """Pick up newly published generations at an epoch boundary.

        No-op without a ``refresh`` callable (classic frozen datasets),
        when the published file set is unchanged, or when this epoch
        already refreshed (Binned refreshes all bins up front so its
        remaining-sample bookkeeping and the per-bin epoch advance agree
        on one file set). A new set must pass the same balance and
        divisibility checks as construction — a violation defers the
        pickup with a warning instead of killing a running service (the
        next publish usually heals it). Returns True when the file set
        changed. Never called mid-epoch: streams built by start_epoch /
        worker_stream keep their file list until the next boundary."""
        if self._refresh is None:
            return False
        if self._refreshed_for == self._epoch + 1:
            return False
        self._refreshed_for = self._epoch + 1
        warn = self._logger.to("rank").warning
        refresh = self._refresh
        if hasattr(refresh, "set_epoch_key"):
            # GenerationFollower: one shared snapshot read per epoch
            # boundary across every bin (see loader.bert), so a publish
            # landing between two bins' refreshes cannot give one epoch
            # a generation-mixed view.
            refresh.set_epoch_key(self._epoch + 1)
        try:
            new_paths = sorted(refresh())
        except Exception as e:  # noqa: BLE001 - a service must not die
            warn("generation refresh failed ({}: {}); keeping the current "
                 "file set".format(type(e).__name__, e))
            new_paths = None
        comm = self._comm or LocalCommunicator()
        if comm.world_size > 1:
            # The agreement collective runs UNCONDITIONALLY once per
            # boundary: participation must never depend on locally-judged
            # state (a failed refresh, an unchanged-looking set) or the
            # ranks' collectives desync — the same contract _census
            # documents. From here on, every decision is a pure function
            # of the agreed set, so verdicts stay rank-identical.
            if not self._ranks_agree(comm, new_paths):
                warn("generation refresh deferred: ranks observed "
                     "different published file sets (a publish raced the "
                     "epoch boundary); retrying next epoch")
                return False
        if new_paths is None:
            return False
        current = [f.path for f in self._files]
        if new_paths == current:
            return False
        if len(new_paths) % self._num_dp_groups != 0 or (
                len(new_paths) // self._num_dp_groups) % self._num_workers:
            warn("generation refresh deferred: {} files not divisible by "
                 "{} dp group(s) x {} worker(s); keeping the current "
                 "set".format(len(new_paths), self._num_dp_groups,
                              self._num_workers))
            return False
        files = self._census(new_paths, comm, on_error="sentinel")
        if any(f.num_samples < 0 for f in files):
            # A footer read failed on the stride owner; the sentinel rode
            # the allreduce, so EVERY rank sees it and defers together.
            warn("generation refresh deferred (unreadable shard footer); "
                 "keeping the current file set")
            return False
        try:
            per_file = self._validate_counts(files)
        except ValueError as e:
            # Pure function of the allreduced counts: rank-identical.
            warn("generation refresh deferred ({}); keeping the current "
                 "file set".format(e))
            return False
        self._files = files
        self._num_samples_per_file = per_file
        self._files_version += 1
        from .. import observability as obs
        if obs.enabled() or obs.fleet.enabled():
            obs.inc("loader_generation_refreshes_total")
            loaded, lag = None, None
            root = getattr(self._refresh, "root", None)
            if root is not None:
                from ..utils.fs import get_generation_of_path
                loaded = max(get_generation_of_path(root, f.path)
                             for f in self._files)
                obs.set_gauge("loader_generations_loaded", loaded + 1)
                gate = getattr(self._refresh, "last_gate", None)
                if gate is not None:
                    lag = gate - loaded
                    obs.set_gauge("loader_generation_lag", lag)
            obs.fleet.record("generation.pickup", files=len(self._files),
                             epoch=self._epoch + 1, loaded=loaded, lag=lag)
        self._logger.to("rank").info(
            "picked up new generation(s): {} -> {} files".format(
                len(current), len(self._files)))
        return True

    @staticmethod
    def _ranks_agree(comm, new_paths):
        """All-rank agreement on the refreshed file set with the one
        collective available (sum): every rank contributes a digest of
        its set; agreement iff the digest variance is zero
        (world * sum(d^2) == (sum d)^2). Both sides are built from the
        allreduced totals only, so EVERY rank computes the identical
        verdict — a rank-divergent refresh decision would desync the
        SPMD epoch, which is exactly what this check exists to prevent."""
        import zlib
        # 28-bit digest keeps digest^2 summed over any realistic world
        # size inside the collective's int64 contract. A failed refresh
        # (new_paths None) contributes a sentinel OUTSIDE the digest
        # range: all-failed still agrees (every rank then defers on the
        # None), mixed failure disagrees, and either way every rank ran
        # the collective.
        digest = (1 << 28) if new_paths is None else (
            zlib.crc32("\n".join(new_paths).encode()) & 0xFFFFFFF)
        s1, s2 = comm.allreduce_sum([digest, digest * digest])
        return int(s2) * comm.world_size == int(s1) * int(s1)

    def advance_epoch(self):
        """Advance the epoch counter (no streams built); returns it.
        Generation pickup happens here — the epoch boundary — so a
        mid-epoch publish never changes a stream in flight."""
        self.maybe_refresh()
        self._epoch += 1
        return self._epoch

    def start_epoch(self):
        """Advance to the next epoch; returns per-worker sample streams.

        The file shuffle uses the world stream — identical on every rank —
        then this dp group takes ``files[dp_rank::num_dp_groups]`` and
        worker w takes every num_workers-th of those.
        """
        self.advance_epoch()
        group_files = self._epoch_group_files(self._epoch)  # shuffle once
        return [self.worker_stream(self._epoch, w, _group_files=group_files)
                for w in range(self._num_workers)]

    def _epoch_group_files(self, epoch):
        world_g = lrng.world_rng(self._base_seed, epoch)
        files = list(self._files)
        lrng.shuffle(world_g, files)
        return files[self._dp_rank::self._num_dp_groups]

    def worker_stream(self, epoch, w, _group_files=None):
        """Worker ``w``'s sample stream for ``epoch`` — a pure function of
        (files, base_seed, epoch, dp group, worker), so process-mode
        workers rebuild their own stream after a pickle round-trip without
        any state handoff. (start_epoch passes the epoch file shuffle in
        to avoid repeating it per worker.)"""
        group_files = (_group_files if _group_files is not None
                       else self._epoch_group_files(epoch))
        worker_files = group_files[w::self._num_workers]
        worker_g = lrng.worker_rng(self._base_seed, epoch,
                                   self._dp_rank, self._num_dp_groups, w,
                                   self._num_workers)
        buf = ShuffleBuffer(
            worker_files,
            self._num_samples_per_file * len(worker_files),
            self._decode_record_batch,
            self._shuffle_buffer_size,
            self._shuffle_buffer_warmup_factor,
            worker_g,
            logger=self._logger,
        )
        return self._transformed(buf)

    def _transformed(self, stream):
        if self._transform is None:
            return iter(stream)
        return (self._transform(s) for s in stream)
