"""Streaming shard datasets with deterministic epoch-seeded shuffling.

Reference parity: lddl/torch/datasets.py and the model-parallel
generalization lddl/torch_mp/datasets.py. One implementation covers both:
sharding is always by *data-parallel group* (``dp_rank``), which equals the
global rank in plain DP and the Megatron dp_rank in 3D-parallel layouts —
on TPU both fall out of the device mesh (see loader/sharding.py).

Determinism contract (ref: lddl/torch/datasets.py:227-286):
- Epoch k derives every random choice from (base_seed, epoch):
  a world-identical file shuffle, then per-(dp_rank, worker) streams for
  the shuffle buffer. Restarting with ``start_epoch=k`` reproduces epoch k
  exactly — resume is recomputation by seeding, no state files.
- All ranks of one dp group draw identical files, buffers, batches.
"""

import os

from ..parallel.distributed import LocalCommunicator
from ..resilience.io import read_table
from ..utils import rng as lrng
from ..utils.fs import (
    get_num_samples_of_parquet,
    num_samples_cache_is_stale,
    read_num_samples_cache,
)
from ..utils.logging import DatasetLogger
from ..utils.types import File


def verified_shard_paths(path, file_paths, on_corrupt=None, logger=None,
                         comm=None):
    """Startup integrity gate shared by the loader factories: verify the
    shards against their directories' ``.manifest.json`` (written by the
    preprocessor/balancer; absent manifests are trusted as-is, e.g. for
    pre-manifest data).

    ``on_corrupt`` is ``"fail"`` (default, raise naming every corrupt
    shard) or ``"quarantine"`` (exclude corrupt shards, log each exclusion
    loudly, and return the survivors — downstream count/divisibility
    checks then account for the exclusion explicitly). ``None`` defers to
    ``LDDL_TPU_ON_CORRUPT`` then ``"fail"``. Raises if quarantine leaves
    no shard at all."""
    from ..resilience.integrity import verify_shards
    if on_corrupt is None:
        on_corrupt = os.environ.get("LDDL_TPU_ON_CORRUPT", "fail")
    log = None
    if logger is not None:
        log = lambda msg: logger.to("rank").warning(msg)  # noqa: E731
    good, excluded = verify_shards(file_paths, on_corrupt=on_corrupt,
                                   log=log, comm=comm)
    if not good:
        raise ValueError(
            "every parquet shard under {} was quarantined as corrupt; "
            "re-run the producing stage".format(path))
    return good


def annotate_quarantine(exc, n_quarantined):
    """Re-raise a downstream shard-set error (bin contiguity, dp-group
    divisibility, balance) with the quarantine called out: the operator
    must be pointed at the corrupt shards just logged, not at their
    shard/worker configuration."""
    return ValueError(
        "{} (note: {} corrupt shard(s) were quarantined at startup, which "
        "changed the shard set — re-run the producing stage to restore "
        "them, or adjust num_dp_groups/num_workers to the surviving "
        "count)".format(exc, n_quarantined))


class ShuffleBuffer:
    """Streaming shuffle: warmup fills the buffer at ``warmup_factor``:1,
    then each new sample swap-replaces a random buffered sample, which is
    yielded; the tail is shuffled and drained.
    (ref: lddl/torch/datasets.py:46-109)
    """

    def __init__(self, files, max_num_samples_to_yield, decode_record_batch,
                 size, warmup_factor, g, logger=None):
        num_wasted = sum(f.num_samples for f in files) - max_num_samples_to_yield
        assert 0 <= num_wasted <= len(files)
        self._files = files
        self._max_num_samples_to_yield = max_num_samples_to_yield
        self._decode_record_batch = decode_record_batch
        self._size = size
        self._warmup_factor = warmup_factor
        self._g = g
        self._logger = logger

    @property
    def num_samples(self):
        return sum(f.num_samples for f in self._files)

    def __iter__(self):
        from .. import observability as obs
        buffer = []
        num_to_yield = min(self._max_num_samples_to_yield, self.num_samples)
        remaining = num_to_yield
        # Telemetry is hoisted out of the per-sample loop: enabled() is
        # checked once per epoch, and the fill gauge samples every 1024
        # yields (a gauge is a level, not a rate — sampling loses nothing).
        obs_on = obs.enabled()
        gauge = obs.registry().gauge(
            "loader_shuffle_buffer_fill",
            help="shuffle-buffer occupancy / configured size") if obs_on \
            else None

        for f in self._files:
            if self._logger is not None:
                self._logger.to("worker").info("Reading {}".format(f.path))
            # Resilient shard read: transient EIO/ESTALE retries with
            # backoff instead of killing the epoch (resilience.io).
            for record_batch in read_table(f.path).to_batches():
                for sample in self._decode_record_batch(record_batch):
                    if remaining <= 0:
                        return
                    warmup_cap = (num_to_yield - remaining + 1) * self._warmup_factor
                    if len(buffer) >= min(self._size, warmup_cap):
                        idx = int(self._g.integers(0, len(buffer)))
                        yield buffer[idx]
                        buffer[idx] = sample
                        remaining -= 1
                        if gauge is not None and remaining % 1024 == 0:
                            gauge.set(len(buffer) / max(self._size, 1))
                    else:
                        buffer.append(sample)
        lrng.shuffle(self._g, buffer)
        for sample in buffer:
            if remaining <= 0:
                return
            yield sample
            remaining -= 1


class ParquetDataset:
    """Balanced parquet shards -> per-(dp_rank, worker) sample streams.

    ``file_paths`` must be the balanced output of lddl_tpu.balance (all
    counts equal ±1); files are truncated to the min count so every dp
    group sees exactly the same number of samples per epoch.
    """

    def __init__(
        self,
        file_paths,
        base_seed=12345,
        start_epoch=0,
        dp_rank=0,
        num_dp_groups=1,
        num_workers=1,
        shuffle_buffer_size=16384,
        shuffle_buffer_warmup_factor=16,
        decode_record_batch=None,
        transform=None,
        comm=None,
        logger=None,
    ):
        if decode_record_batch is None:
            raise ValueError("decode_record_batch is required")
        if not file_paths:
            raise ValueError("no input shard files")
        num_workers = max(1, num_workers)
        if len(file_paths) % num_dp_groups != 0:
            raise ValueError(
                "{} files not divisible by {} data-parallel groups".format(
                    len(file_paths), num_dp_groups))
        if (len(file_paths) // num_dp_groups) % num_workers != 0:
            raise ValueError(
                "{} files per dp group not divisible by {} workers".format(
                    len(file_paths) // num_dp_groups, num_workers))
        self._base_seed = base_seed
        self._epoch = start_epoch - 1
        self._dp_rank = dp_rank
        self._num_dp_groups = num_dp_groups
        self._num_workers = num_workers
        self._shuffle_buffer_size = shuffle_buffer_size
        self._shuffle_buffer_warmup_factor = shuffle_buffer_warmup_factor
        self._decode_record_batch = decode_record_batch
        self._transform = transform
        self._logger = logger or DatasetLogger()
        self._files = self._census(sorted(file_paths),
                                   comm or LocalCommunicator())

        counts = [f.num_samples for f in self._files]
        lo, hi = min(counts), max(counts)
        if not (lo == hi or lo + 1 == hi):
            raise ValueError(
                "input shards not balanced (counts range {}..{}); run "
                "lddl_tpu.balance first".format(lo, hi))
        if lo == 0:
            raise ValueError("input shards contain empty files")
        # Truncate to the min count so every file contributes equally.
        self._num_samples_per_file = lo
        lost = sum(counts) - lo * len(self._files)
        if lost:
            self._logger.to("rank").warning(
                "dropping {} sample(s) to equalize shard counts".format(lost))

    def _census(self, file_paths, comm):
        """Per-file counts from the .num_samples.json cache; strided footer
        reads + allreduce when the cache is missing/incomplete.
        (ref: lddl/torch/datasets.py:161-195)

        A cache whose key set mismatches the parquet basenames actually on
        disk is STALE (e.g. a crash published it for a different shard
        set, or shards were added/removed since): it is ignored and the
        counts recomputed from footers, logged so the fallback is
        visible."""
        dir_counts = {}
        for d in {os.path.dirname(p) for p in file_paths}:
            cached = read_num_samples_cache(d)
            if cached is None:
                continue
            if num_samples_cache_is_stale(d, cached):
                self._logger.to("rank").warning(
                    ".num_samples.json in {} does not match the shards on "
                    "disk; ignoring it and recomputing counts from parquet "
                    "footers".format(d))
                continue
            for name, n in cached.items():
                dir_counts[os.path.join(d, name)] = n
        if all(p in dir_counts for p in file_paths):
            return [File(p, int(dir_counts[p])) for p in file_paths]
        counts = [0] * len(file_paths)
        for i in range(comm.rank, len(file_paths), comm.world_size):
            counts[i] = get_num_samples_of_parquet(file_paths[i])
        counts = comm.allreduce_sum(counts)
        return [File(p, int(n)) for p, n in zip(file_paths, counts)]

    @property
    def base_seed(self):
        return self._base_seed

    @property
    def dp_rank(self):
        return self._dp_rank

    @property
    def num_dp_groups(self):
        return self._num_dp_groups

    @property
    def num_files_per_group(self):
        return len(self._files) // self._num_dp_groups

    @property
    def num_samples_per_file(self):
        return self._num_samples_per_file

    @property
    def num_workers(self):
        return self._num_workers

    def __len__(self):
        """Samples one dp group sees per epoch."""
        return self._num_samples_per_file * self.num_files_per_group

    @property
    def epoch(self):
        return self._epoch

    def advance_epoch(self):
        """Advance the epoch counter (no streams built); returns it."""
        self._epoch += 1
        return self._epoch

    def start_epoch(self):
        """Advance to the next epoch; returns per-worker sample streams.

        The file shuffle uses the world stream — identical on every rank —
        then this dp group takes ``files[dp_rank::num_dp_groups]`` and
        worker w takes every num_workers-th of those.
        """
        self.advance_epoch()
        group_files = self._epoch_group_files(self._epoch)  # shuffle once
        return [self.worker_stream(self._epoch, w, _group_files=group_files)
                for w in range(self._num_workers)]

    def _epoch_group_files(self, epoch):
        world_g = lrng.world_rng(self._base_seed, epoch)
        files = list(self._files)
        lrng.shuffle(world_g, files)
        return files[self._dp_rank::self._num_dp_groups]

    def worker_stream(self, epoch, w, _group_files=None):
        """Worker ``w``'s sample stream for ``epoch`` — a pure function of
        (files, base_seed, epoch, dp group, worker), so process-mode
        workers rebuild their own stream after a pickle round-trip without
        any state handoff. (start_epoch passes the epoch file shuffle in
        to avoid repeating it per worker.)"""
        group_files = (_group_files if _group_files is not None
                       else self._epoch_group_files(epoch))
        worker_files = group_files[w::self._num_workers]
        worker_g = lrng.worker_rng(self._base_seed, epoch,
                                   self._dp_rank, self._num_dp_groups, w,
                                   self._num_workers)
        buf = ShuffleBuffer(
            worker_files,
            self._num_samples_per_file * len(worker_files),
            self._decode_record_batch,
            self._shuffle_buffer_size,
            self._shuffle_buffer_warmup_factor,
            worker_g,
            logger=self._logger,
        )
        return self._transformed(buf)

    def _transformed(self, stream):
        if self._transform is None:
            return iter(stream)
        return (self._transform(s) for s in stream)
