"""Metric exporters: periodic JSONL snapshots, Prometheus textfile,
end-of-run summary.

All exports land in the metrics directory (LDDL_TPU_METRICS_DIR), one
file per (rank, pid) so concurrent worker processes never contend:

    metrics-rank<r>-pid<p>.jsonl   one full registry snapshot per line
                                   (append-only time series)
    metrics-rank<r>-pid<p>.prom    Prometheus textfile-collector format,
                                   rewritten in place on every export
    summary-rank<r>-pid<p>.json    final registry snapshot + derived
                                   headline numbers (padding efficiency,
                                   resilience activity)

Export writes are plain file I/O on purpose: they must not ride
``resilience.io`` (whose fault-injection points could then raise *inside*
telemetry and change pipeline behavior — the exact inversion of the
inertness contract), and a torn metrics file is an acceptable loss where
a torn shard is not. Every write is wrapped so failures drop the export
rather than the run.
"""

import json
import os
import threading
import time

from . import tracing
from .registry import ENV_DIR, ENV_RANK, metrics_dir, rank, registry

_EXPORT_INTERVAL_ENV = "LDDL_TPU_METRICS_INTERVAL_S"

_thread_lock = threading.Lock()
_exporter = {"thread": None, "stop": None}


def _file_tag():
    return "rank{}-pid{}".format(rank(), os.getpid())


def configure(dir=None, rank=None, periodic=False):  # noqa: A002
    """Arm telemetry in this process AND future child processes (the env
    var is the source of truth, like resilience.faults). ``periodic=True``
    also starts the background snapshot thread (interval from
    ``LDDL_TPU_METRICS_INTERVAL_S``, default 30s)."""
    if dir is not None:
        os.makedirs(dir, exist_ok=True)
        os.environ[ENV_DIR] = dir
    if rank is not None:
        os.environ[ENV_RANK] = str(int(rank))
    if periodic:
        start_periodic_export()
    return metrics_dir()


def disable():
    """Disarm telemetry (this process and future children). Recorded
    metrics stay in the registry; call ``registry().reset()`` to drop."""
    stop_periodic_export()
    os.environ.pop(ENV_DIR, None)
    os.environ.pop(ENV_RANK, None)


def snapshot_line():
    """One JSON-serializable snapshot object with a wall-clock stamp."""
    return {"time": time.time(), "rank": rank(), "pid": os.getpid(),
            "metrics": registry().snapshot()}


def export_jsonl():
    """Append one registry snapshot line to the per-process JSONL file."""
    d = metrics_dir()
    if d is None:
        return None
    path = os.path.join(d, "metrics-{}.jsonl".format(_file_tag()))
    try:
        os.makedirs(d, exist_ok=True)
        with open(path, "a", encoding="utf-8") as f:
            f.write(json.dumps(snapshot_line()) + "\n")
    except Exception:  # noqa: BLE001 - telemetry must stay inert
        return None
    return path


def _prom_name(name):
    return "".join(c if c.isalnum() or c == "_" else "_" for c in name)


def _prom_labels(label_str, extra=None):
    pairs = []
    if label_str:
        for part in label_str.split(","):
            k, _, v = part.partition("=")
            pairs.append((k, v))
    if extra:
        pairs.extend(extra)
    if not pairs:
        return ""
    return "{" + ",".join(
        '{}="{}"'.format(k, str(v).replace('"', r'\"')) for k, v in pairs
    ) + "}"


def export_prom():
    """Rewrite the Prometheus textfile for this process (node-exporter
    textfile-collector format). Histograms export ``_count``/``_sum`` plus
    cumulative ``_bucket{le=...}`` series from the log buckets."""
    d = metrics_dir()
    if d is None:
        return None
    path = os.path.join(d, "metrics-{}.prom".format(_file_tag()))
    lines = []
    snap = registry().snapshot()
    for name, data in snap.items():
        pname = _prom_name(name)
        kind = data["type"]
        lines.append("# TYPE {} {}".format(
            pname, "histogram" if kind == "histogram" else kind))
        if kind in ("counter", "gauge"):
            for label_str, v in sorted(data["values"].items()):
                lines.append("{}{} {}".format(
                    pname, _prom_labels(label_str), _num(v)))
        else:
            for label_str, st in sorted(data["values"].items()):
                cum = 0

                def le_of(bucket):
                    le = bucket[3:] if bucket.startswith("le_") else bucket
                    try:
                        return float(le), le
                    except ValueError:
                        return float("inf"), le

                for _, le, n in sorted(
                        (le_of(b) + (n,)) for b, n in st["buckets"].items()):
                    cum += n
                    lines.append("{}_bucket{} {}".format(
                        pname, _prom_labels(label_str, [("le", le)]), cum))
                lines.append("{}_bucket{} {}".format(
                    pname, _prom_labels(label_str, [("le", "+Inf")]), cum))
                lines.append("{}_sum{} {}".format(
                    pname, _prom_labels(label_str), _num(st["sum"])))
                lines.append("{}_count{} {}".format(
                    pname, _prom_labels(label_str), st["count"]))
    try:
        os.makedirs(d, exist_ok=True)
        # Plain truncate-write: a torn .prom file is re-written next tick
        # (and os.replace is reserved for resilience.io by lint).
        with open(path, "w", encoding="utf-8") as f:
            f.write("\n".join(lines) + "\n")
    except Exception:  # noqa: BLE001 - telemetry must stay inert
        return None
    return path


def _num(v):
    if isinstance(v, float) and v.is_integer():
        return int(v)
    return v


def summary():
    """End-of-run summary dict: the full snapshot plus derived headline
    numbers every stage report cares about."""
    snap = registry().snapshot()

    def counter_total(name):
        data = snap.get(name)
        if not data or data["type"] != "counter":
            return 0
        return sum(data["values"].values())

    real = counter_total("loader_real_tokens_total")
    padded = counter_total("loader_padded_slots_total")
    out = {
        "padding_efficiency": (real / padded) if padded else None,
        "real_tokens": real,
        "padded_slots": padded,
        "retries": counter_total("resilience_retry_attempts_total"),
        "faults_injected": counter_total("resilience_faults_injected_total"),
        "worker_restarts": counter_total("loader_worker_restarts_total"),
        "quarantined_shards": counter_total(
            "resilience_quarantined_shards_total"),
        "metrics": snap,
    }
    # Critical-path attribution rides the summary so downstream readers
    # (step_profile --attribution, the fleet rollup) never re-derive it.
    try:
        from . import attribution
        out["loader_attribution"] = attribution.from_stage_seconds(
            attribution.stage_seconds())
    except Exception:  # noqa: BLE001 - telemetry must stay inert
        out["loader_attribution"] = None
    return out


def write_summary():
    """Write ``summary()`` (plus flush traces) to the metrics dir."""
    d = metrics_dir()
    if d is None:
        return None
    tracing.flush()
    path = os.path.join(d, "summary-{}.json".format(_file_tag()))
    try:
        os.makedirs(d, exist_ok=True)
        with open(path, "w", encoding="utf-8") as f:
            json.dump(summary(), f, indent=2, sort_keys=True, default=str)
    except Exception:  # noqa: BLE001 - telemetry must stay inert
        return None
    return path


def _export_once():
    export_jsonl()
    export_prom()
    tracing.flush()


_signal_installed = []


def install_signal_flush():
    """Flush telemetry from a SIGTERM handler (idempotent; main thread
    only — installing elsewhere raises ValueError and is skipped).

    atexit covers normal interpreter exit, but a polite kill (k8s pod
    eviction, timeout(1), a supervisor's TERM before KILL) used to drop
    every buffered trace event, unexported counter, and fleet lifecycle
    event recorded since the last flush — precisely the telemetry an
    operator needs to diagnose WHY the process was killed. The handler
    chains any previously-installed Python handler; when the prior
    disposition was the default (terminate), it re-raises SIGTERM after
    flushing so the process still dies with the conventional -TERM
    status; a prior SIG_IGN (or an unknown C-level handler, getsignal()
    -> None) is preserved — flush only, never turn an ignored signal
    into a death. The telemetry locks on the flush path are reentrant
    (see tracing._lock), so a TERM landing while the interrupted frame
    holds one cannot deadlock the dying process."""
    if _signal_installed:
        return
    import signal

    try:
        prev = signal.getsignal(signal.SIGTERM)

        def _handler(signum, frame):
            try:
                final_flush(reason="sigterm")
            except Exception:  # noqa: BLE001 - dying anyway
                pass
            if callable(prev):
                prev(signum, frame)
            elif prev == signal.SIG_DFL:
                signal.signal(signal.SIGTERM, signal.SIG_DFL)
                os.kill(os.getpid(), signal.SIGTERM)
            # SIG_IGN / None (C-level handler we cannot re-invoke):
            # keep the process alive, exactly as before installation.

        signal.signal(signal.SIGTERM, _handler)
        _signal_installed.append(True)
    except ValueError:
        # Not the main thread: the atexit path still covers clean exits.
        return


def final_flush(reason=None):
    """One last telemetry publish: registry snapshot exports, trace
    buffer flush, and the fleet spool (snapshot marked closed). Shared by
    the atexit and SIGTERM paths; safe to call repeatedly."""
    if metrics_dir() is not None:
        export_jsonl()
        export_prom()
        tracing.flush()
    from . import fleet
    if fleet.enabled():
        fleet.heartbeat(closed=True, reason=reason or "atexit")


def start_periodic_export(interval_s=None):
    """Start the daemon exporter thread (idempotent). Interval defaults to
    ``LDDL_TPU_METRICS_INTERVAL_S`` (30s)."""
    if metrics_dir() is None:
        return None
    if interval_s is None:
        try:
            interval_s = float(os.environ.get(_EXPORT_INTERVAL_ENV, "30"))
        except ValueError:
            interval_s = 30.0
    with _thread_lock:
        if _exporter["thread"] is not None and _exporter["thread"].is_alive():
            return _exporter["thread"]
        stop = threading.Event()

        def loop():
            while not stop.wait(interval_s):
                if metrics_dir() is None:
                    return
                try:
                    _export_once()
                except Exception:  # noqa: BLE001 - keep exporting
                    pass

        t = threading.Thread(target=loop, name="lddl-metrics-exporter",
                             daemon=True)
        t.start()
        _exporter["thread"] = t
        _exporter["stop"] = stop
        return t


def stop_periodic_export():
    with _thread_lock:
        if _exporter["stop"] is not None:
            _exporter["stop"].set()
        _exporter["thread"] = None
        _exporter["stop"] = None
