"""Time-series telemetry: bounded history over the metrics registry.

PR 2's registry and PR 10's fleet spools expose *lifetime* counters and
*last-snapshot* gauges: ``pipeline_status`` can say "1.2M docs so far"
but not "throughput halved eight minutes ago". This module adds the
missing axis — a bounded ring-buffer history sampled off the registry
and persisted into the per-host spool — so the aggregator can compute
windowed rates, gauge trends, and histogram percentiles over time.

Sampling model: each ``sample()`` diffs the current registry snapshot
against the previous one and emits one compact **point**::

    {"wall": w, "mono": m, "pid": p,
     "d": {"name" or "name{k=v,...}": counter_delta, ...},
     "g": {"key": gauge_value, ...},
     "h": {"key": {"n": dcount, "s": dsum, "b": {"le_x": dn}}, ...}}

Only nonzero counter/histogram deltas are written (quiet metrics cost
nothing); gauges are sampled absolutely. Points ride the in-memory ring
(bounded, like tracing's buffer) and are appended to
``series-pid<p>*.jsonl`` segments in the spool on every fleet heartbeat
and on the same atexit/SIGTERM/kill-fault flush paths as snapshots — a
SIGKILLed host leaves at most one torn trailing line, which readers
treat as end-of-stream (``fleet.read_jsonl`` discipline).

Segments rotate at a size bound (``LDDL_TPU_FLEET_ROTATE_BYTES``) into
``series-pid<p>.seg<k>.jsonl`` files; ``fleet.gc_spool`` drops old
segments by total-size/age. Readers glob the shared prefix, so rotated
and live segments merge seamlessly.

Inertness contract (same as registry/tracing/fleet): disabled, every
hook is one env lookup; enabled, nothing here raises into the pipeline,
touches an RNG stream, or writes outside the spool. Wall-clock reads are
confined to this module (observability is allowlisted for them).
"""

import collections
import json
import logging
import os
import threading
import time

from .registry import registry

ENV_RING = "LDDL_TPU_SERIES_RING"

SEGMENT_PREFIX = "series-pid"

DEFAULT_RING = 720  # at the 10s heartbeat default: two hours of history

_log = logging.getLogger("lddl_tpu.observability.series")

# RLock like tracing/fleet: the SIGTERM flush may interrupt a frame that
# holds it on the main thread and must re-enter, not deadlock.
_lock = threading.RLock()
_last_snapshot = [None]      # previous registry snapshot, for deltas
_ring = [None]               # deque of recent points (bounded)
_unflushed = []              # points not yet appended to the segment
_segment = {"path": None}    # current on-disk segment for this pid


def _ring_size():
    try:
        return max(int(os.environ.get(ENV_RING, DEFAULT_RING)), 16)
    except ValueError:
        return DEFAULT_RING


def _flat(name, label_str):
    """One series key per (metric, label set): ``name`` for the unlabelled
    series, ``name{k=v,...}`` otherwise (the Prometheus spelling, so the
    README's stable metric names read verbatim off a segment)."""
    if not label_str:
        return name
    return "{}{{{}}}".format(name, label_str)


def split_key(key):
    """Inverse of ``_flat``: ``(metric_name, label_str)``."""
    if key.endswith("}") and "{" in key:
        name, _, rest = key.partition("{")
        return name, rest[:-1]
    return key, ""


def _diff_point(prev, snap, wall, mono):
    """The compact delta point between two registry snapshots. Counter
    and histogram deltas clamp negative (a registry reset mid-run reads
    as a fresh start, not a negative rate)."""
    point = {"wall": wall, "mono": mono, "pid": os.getpid()}
    d, g, h = {}, {}, {}
    prev = prev or {}
    for name, data in snap.items():
        kind = data.get("type")
        pvals = (prev.get(name) or {}).get("values", {})
        if kind == "counter":
            for label_str, v in data.get("values", {}).items():
                delta = v - pvals.get(label_str, 0)
                if delta > 0:
                    d[_flat(name, label_str)] = delta
        elif kind == "gauge":
            for label_str, v in data.get("values", {}).items():
                if isinstance(v, (int, float)) and v == v:  # drop NaN
                    g[_flat(name, label_str)] = v
        elif kind == "histogram":
            for label_str, st in data.get("values", {}).items():
                pst = pvals.get(label_str) or {}
                dn = st.get("count", 0) - pst.get("count", 0)
                if dn <= 0:
                    continue
                db = {}
                pbuckets = pst.get("buckets", {})
                for b, n in st.get("buckets", {}).items():
                    bn = n - pbuckets.get(b, 0)
                    if bn > 0:
                        db[b] = bn
                h[_flat(name, label_str)] = {
                    "n": dn, "s": st.get("sum", 0.0) - pst.get("sum", 0.0),
                    "b": db,
                }
    if d:
        point["d"] = d
    if g:
        point["g"] = g
    if h:
        point["h"] = h
    return point


def sample():
    """Take one point: diff the registry against the previous sample and
    push the delta onto the ring + flush queue. Returns the point, or
    None when it could not be taken. Never raises."""
    try:
        snap = registry().snapshot()
        wall, mono = time.time(), time.monotonic()
        with _lock:
            point = _diff_point(_last_snapshot[0], snap, wall, mono)
            _last_snapshot[0] = snap
            if _ring[0] is None or _ring[0].maxlen != _ring_size():
                _ring[0] = collections.deque(_ring[0] or (),
                                             maxlen=_ring_size())
            _ring[0].append(point)
            if len(_unflushed) < _ring_size():
                _unflushed.append(point)
        return point
    except Exception:  # noqa: BLE001 - telemetry must stay inert
        return None


def recent(window_s=None):
    """Points currently in the in-memory ring, oldest first; with
    ``window_s``, only those inside the trailing window."""
    with _lock:
        points = list(_ring[0] or ())
    if window_s is None or not points:
        return points
    cutoff = points[-1].get("wall", 0.0) - float(window_s)
    return [p for p in points if p.get("wall", 0.0) >= cutoff]


def _segment_paths(d, pid=None):
    """All series segments in one spool dir (rotated + live), sorted so
    rotation order is read order."""
    try:
        names = sorted(os.listdir(d))
    except OSError:
        return []
    tag = SEGMENT_PREFIX if pid is None \
        else "{}{}".format(SEGMENT_PREFIX, pid)
    out = []
    for name in names:
        if not (name.startswith(SEGMENT_PREFIX) and
                name.endswith(".jsonl")):
            continue
        if pid is not None and not (
                name == tag + ".jsonl" or name.startswith(tag + ".seg")):
            continue
        out.append(os.path.join(d, name))
    return out


def flush():
    """Append unflushed points to this pid's current segment (rotating at
    the size bound). Called from ``fleet.heartbeat`` — i.e. the periodic
    beat, atexit, SIGTERM, and the injector's pre-kill flush. A no-op
    when fleet telemetry is off."""
    from . import fleet
    d = fleet.spool_dir()
    if d is None:
        return None
    with _lock:
        if not _unflushed:
            return _segment["path"]
        batch, _unflushed[:] = list(_unflushed), []
    try:
        from ..resilience import io as rio
        os.makedirs(d, exist_ok=True)
        # rotating_path mutates the shared segment dict, and both the
        # heartbeat sampler and the SIGTERM/atexit flush reach here.
        with _lock:
            path = fleet.rotating_path(d, SEGMENT_PREFIX, _segment)
        payload = "".join(json.dumps(p, sort_keys=True) + "\n"
                          for p in batch)
        with rio.open_append(path) as f:
            f.write(payload.encode("utf-8"))
        return path
    except Exception:  # noqa: BLE001 - drop the batch, never the pipeline
        return None


def sample_and_flush():
    """One heartbeat's worth of history: sample, then persist."""
    sample()
    return flush()


def read_series(root, holder_name, warn=None):
    """Every point one holder's spool recorded, wall-ordered, merged
    across pids and rotated segments. Torn-tolerant via
    ``fleet.read_jsonl``. Returns ``(points, torn_line_count)``."""
    from . import fleet
    d = fleet.spool_dir(root, holder_name)
    points, torn = [], 0
    for path in _segment_paths(d) if d else []:
        recs, t = fleet.read_jsonl(path, warn)
        points.extend(recs)
        torn += t
    points.sort(key=lambda p: p.get("wall", 0.0))
    return points, torn


def percentile_from_buckets(buckets, q):
    """Percentile estimate off log-bucket counts ({"le_2.0": n, ...}):
    the upper bound of the bucket where the cumulative count crosses
    ``q``. Within a factor of 2 of the true value — the resolution the
    frexp buckets buy, plenty for trend/alerting use."""
    def le_of(bucket):
        raw = bucket[3:] if bucket.startswith("le_") else bucket
        try:
            return float(raw)
        except ValueError:
            return float("inf")
    total = sum(buckets.values())
    if total <= 0:
        return None
    target = q * total
    cum = 0
    for le, n in sorted((le_of(b), n) for b, n in buckets.items()):
        cum += n
        if cum >= target:
            return le
    return None


def window_rollup(points, window_s, now=None):
    """Windowed statistics over a point stream: per-key counter rates
    (and the per-point delta series, for sparklines), gauge trends
    (first/last/min/max inside the window), and histogram percentiles
    from the summed bucket deltas. Pure function of the points."""
    if now is None:
        now = max((p.get("wall", 0.0) for p in points), default=0.0)
    window_s = float(window_s)
    cutoff = now - window_s
    inside = [p for p in points if p.get("wall", 0.0) >= cutoff]
    if not inside:
        return {"window_s": window_s, "points": 0, "span_s": 0.0,
                "rates": {}, "deltas": {}, "gauges": {}, "histograms": {}}
    walls = [p.get("wall", 0.0) for p in inside]
    # Rate denominator: the observed span, floored at one heartbeat-ish
    # second so a single point doesn't divide by ~zero; capped at the
    # requested window so long-idle spools don't dilute.
    span = min(max(max(walls) - min(walls), 1.0), window_s)
    rates, deltas = {}, {}
    for p in inside:
        for key, dv in (p.get("d") or {}).items():
            deltas.setdefault(key, []).append((p.get("wall", 0.0), dv))
    for key, seq in deltas.items():
        rates[key] = sum(dv for _, dv in seq) / span
    gauges = {}
    for p in inside:
        for key, v in (p.get("g") or {}).items():
            st = gauges.get(key)
            if st is None:
                gauges[key] = {"first": v, "last": v, "min": v, "max": v}
            else:
                st["last"] = v
                st["min"] = min(st["min"], v)
                st["max"] = max(st["max"], v)
    for st in gauges.values():
        st["trend"] = st["last"] - st["first"]
    hists = {}
    for p in inside:
        for key, hd in (p.get("h") or {}).items():
            st = hists.setdefault(key, {"n": 0, "s": 0.0, "b": {}})
            st["n"] += hd.get("n", 0)
            st["s"] += hd.get("s", 0.0)
            for b, n in (hd.get("b") or {}).items():
                st["b"][b] = st["b"].get(b, 0) + n
    histograms = {}
    for key, st in hists.items():
        histograms[key] = {
            "count": st["n"],
            "mean": (st["s"] / st["n"]) if st["n"] else None,
            "p50": percentile_from_buckets(st["b"], 0.50),
            "p90": percentile_from_buckets(st["b"], 0.90),
            "p99": percentile_from_buckets(st["b"], 0.99),
        }
    return {"window_s": window_s, "points": len(inside), "span_s": span,
            "rates": rates, "deltas": deltas, "gauges": gauges,
            "histograms": histograms}


def _reset_for_tests():
    with _lock:
        _last_snapshot[0] = None
        _ring[0] = None
        _unflushed[:] = []
        _segment["path"] = None
