"""Span tracing: Chrome-trace-format JSONL per process, Perfetto-openable.

``span("preprocess.scatter", shard=3)`` is a context manager that records
one complete ("ph": "X") Trace Event with microsecond start/duration;
nested spans on the same thread render as a span tree in Perfetto
(https://ui.perfetto.dev — open the ``trace-*.jsonl`` file directly; the
JSON trace importer accepts newline-delimited events without the
enclosing ``[]``). ``event(...)`` records an instant ("ph": "i") event
for point occurrences (a retry, a fault injection, a worker restart).

Inertness contract (same as registry.py): disabled spans are a shared
reusable null context manager (no allocation, one env lookup), enabled
spans never raise into the caller and never touch any RNG. Events buffer
in memory and append to ``<metrics_dir>/trace-rank<r>-pid<p>.jsonl`` on
``flush()`` — called by the exporter thread, at interpreter exit, and by
``mock_train``'s end-of-run report. Worker *processes* inherit the env
var and write their own per-pid file, which is what makes the
scatter/gather span tree span process boundaries.
"""

import json
import os
import threading
import time

from .registry import metrics_dir, rank

# RLock, not Lock: the SIGTERM flush handler (exporters.install_signal_
# flush) runs on the main thread between bytecodes and may interrupt a
# frame that already holds this lock mid-_push; a non-reentrant lock
# would deadlock the dying process instead of flushing it. Re-entry is
# safe: flush() only swaps the buffer out, and the interrupted append
# lands in the fresh buffer.
_lock = threading.RLock()
_buffer = []          # pending trace event dicts
_emitted_meta = set()  # pids that already wrote their process_name event
_MAX_BUFFER = 50000    # hard cap: a runaway loop must not eat the heap
_atexit_registered = []


def _now_us():
    # Wall clock so events from different PROCESSES (pool workers, loader
    # workers) land on one comparable timeline in Perfetto; durations use
    # the monotonic perf counter so a clock step cannot produce negative
    # or inflated span widths.
    return time.time() * 1e6


class Span:
    """One timed section. Use via ``span(...)``; re-entrant use of a
    single instance is not supported (make a new span instead)."""

    __slots__ = ("name", "args", "_t0", "_p0")

    def __init__(self, name, args):
        self.name = name
        self.args = args
        self._t0 = 0.0
        self._p0 = 0.0

    def __enter__(self):
        self._t0 = _now_us()
        self._p0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb):
        dur = (time.perf_counter() - self._p0) * 1e6
        record = {
            "name": self.name,
            "ph": "X",
            "ts": self._t0,
            "dur": dur,
            "pid": os.getpid(),
            "tid": threading.get_ident() & 0x7FFFFFFF,
        }
        if self.args:
            record["args"] = self.args
        if exc_type is not None:
            record.setdefault("args", {})["error"] = exc_type.__name__
        _push(record)
        return False  # never swallow pipeline exceptions


class _NullSpan:
    """Shared disabled-mode span: zero state, reusable, nestable."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        return False


_NULL_SPAN = _NullSpan()


def span(name, **args):
    """Context manager timing one section; a shared no-op when disabled."""
    if metrics_dir() is None:
        return _NULL_SPAN
    return Span(name, args)


def event(name, **args):
    """Record an instant event (a point in time, not a duration)."""
    if metrics_dir() is None:
        return
    record = {
        "name": name,
        "ph": "i",
        "ts": _now_us(),
        "pid": os.getpid(),
        "tid": threading.get_ident() & 0x7FFFFFFF,
        "s": "t",
    }
    if args:
        record["args"] = args
    _push(record)


def _push(record):
    try:
        with _lock:
            if len(_buffer) >= _MAX_BUFFER:
                return
            pid = record["pid"]
            if pid not in _emitted_meta:
                _emitted_meta.add(pid)
                _buffer.append({
                    "name": "process_name", "ph": "M", "pid": pid,
                    "args": {"name": "rank{} pid{}".format(rank(), pid)},
                })
            _buffer.append(record)
            if not _atexit_registered:
                _atexit_registered.append(True)
                import atexit
                atexit.register(flush)
    except Exception:  # noqa: BLE001 - telemetry must stay inert
        pass


def trace_path():
    """This process's trace file path, or None when disabled."""
    d = metrics_dir()
    if d is None:
        return None
    return os.path.join(
        d, "trace-rank{}-pid{}.jsonl".format(rank(), os.getpid()))


def flush():
    """Append buffered events to the per-process trace file. Safe to call
    any time from any thread; failures (unwritable dir, disk full) drop
    the batch rather than disturb the pipeline."""
    path = trace_path()
    with _lock:
        if not _buffer:
            return path
        batch, _buffer[:] = list(_buffer), []
    if path is None:
        return None
    try:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        with open(path, "a", encoding="utf-8") as f:
            for record in batch:
                f.write(json.dumps(record) + "\n")
    except Exception:  # noqa: BLE001 - telemetry must stay inert
        pass
    return path


def pending_events():
    """Number of buffered (unflushed) events — tests and debugging."""
    with _lock:
        return len(_buffer)


def _reset_for_tests():
    with _lock:
        _buffer[:] = []
        _emitted_meta.clear()
