"""Loader critical-path attribution: where does batch wall time go?

The question an operator actually asks — "is my step input-bound or
compute-bound, and if input-bound, which loader stage is on the critical
path?" — cannot be answered from lifetime counters alone. This module
defines the stage vocabulary, the accumulation metric, and the verdict
rule; the instrumentation sites live in ``loader/dataloader.py`` and
``loader/datasets.py`` (guarded by ``registry.enabled()`` like every
other hook, so the disabled hot path stays one env lookup).

Stage vocabulary (``loader_stage_seconds_total{stage=...}``):

============== =========================================================
self-time stages (pipeline work, mostly overlapped by worker threads)
--------------------------------------------------------------------------
``shard_read``  consumer-side blocking shard acquisition: the synchronous
                ``read_table`` when the shard I/O pipeline is off, or the
                residual wait for the next prefetched+decoded table when
                it is on (loader/shardcache.py)
``shard_fetch`` backend shard-byte fetch self-time on the prefetcher
                threads (mostly overlapped; large vs small ``shard_read``
                is the prefetch-working/not-working signal)
``decode``      Arrow record-batch -> sample dict decode
``collate``     sample list -> padded/packed batch assembly
``ipc``         process-mode queue wait + payload decode (qserde)
``h2d``         device_put / host-to-device transfer in the prefetcher
-------------- ---------------------------------------------------------
boundary stages (partition the consumer-observed wall exactly)
--------------------------------------------------------------------------
``batch_wait``  consumer blocked in ``__next__`` waiting for a batch
``step_gap``    consumer away between batches (its compute step)
``prefetch_wait``/``prefetch_gap``
                the same pair measured at the device-prefetch boundary
                (preferred when present: it is the outermost iterator)
============== =========================================================

Verdict rule: with ``wall = wait + gap`` at the outermost boundary,
``input_share = wait / wall``. ``input-bound`` when input_share >= 0.40,
``compute-bound`` when <= 0.15, ``balanced`` between. Shares reported
per stage partition the wall exactly: the gap is ``consumer_step`` and
the wait is split across the self-time stages proportionally to their
accumulated seconds (``queue_wait`` absorbs it when no self-time was
observed, e.g. all stages ran in unobserved worker processes).

Everything here is pure arithmetic over counters — no clocks (the
instrumentation sites use ``perf_counter`` intervals), no RNG, nothing
that can raise into the pipeline.
"""

from .registry import enabled, registry, set_gauge

STAGE_METRIC = "loader_stage_seconds_total"
VERDICT_GAUGE = "loader_bound_verdict"
INPUT_SHARE_GAUGE = "loader_input_share"

# Self-time stages, in the order the batch path visits them
# (shard_fetch runs on the prefetcher threads, logically ahead of the
# consumer's shard_read wait).
STAGES = ("shard_fetch", "shard_read", "decode", "collate", "ipc", "h2d")

INPUT_BOUND_SHARE = 0.40
COMPUTE_BOUND_SHARE = 0.15

# Gauge encoding of the verdict (exported through fleet snapshots):
# +1 input-bound, 0 balanced, -1 compute-bound.
VERDICT_VALUE = {"input-bound": 1.0, "balanced": 0.0, "compute-bound": -1.0}


def stage_counter():
    """The shared per-stage accumulator (instrumentation sites cache the
    handle per epoch and ``inc(dt, stage=...)`` into it)."""
    return registry().counter(
        STAGE_METRIC, help="accumulated loader self-time per stage (s)")


def stage_seconds():
    """{stage: seconds} accumulated so far in this process's registry."""
    m = registry().get(STAGE_METRIC)
    if m is None or m.kind != "counter":
        return {}
    out = {}
    for label_str, v in m.snapshot().get("values", {}).items():
        for part in label_str.split(","):
            k, _, stage = part.partition("=")
            if k == "stage" and stage:
                out[stage] = out.get(stage, 0.0) + v
    return out


def from_stage_seconds(stages):
    """The attribution report for accumulated ``{stage: seconds}``, or
    None when no boundary pair was observed (nothing iterated). Pure
    function — the fleet aggregator calls this on spool bytes alone."""
    try:
        wait = float(stages.get("prefetch_wait", 0.0))
        gap = float(stages.get("prefetch_gap", 0.0))
        boundary = "prefetch"
        if wait + gap <= 0.0:
            wait = float(stages.get("batch_wait", 0.0))
            gap = float(stages.get("step_gap", 0.0))
            boundary = "loader"
        wall = wait + gap
        if wall <= 0.0:
            return None
        input_share = wait / wall
        if input_share >= INPUT_BOUND_SHARE:
            verdict = "input-bound"
        elif input_share <= COMPUTE_BOUND_SHARE:
            verdict = "compute-bound"
        else:
            verdict = "balanced"
        self_times = {s: float(stages.get(s, 0.0)) for s in STAGES
                      if float(stages.get(s, 0.0)) > 0.0}
        self_total = sum(self_times.values())
        shares = {"consumer_step": gap / wall}
        if self_total > 0.0:
            for s, v in self_times.items():
                shares[s] = input_share * (v / self_total)
        elif wait > 0.0:
            shares["queue_wait"] = input_share
        top = max(((s, sh) for s, sh in shares.items()
                   if s != "consumer_step"),
                  key=lambda kv: kv[1], default=(None, 0.0))
        return {
            "verdict": verdict,
            "input_share": input_share,
            "wall_seconds": wall,
            "boundary": boundary,
            "stages_seconds": {s: float(v) for s, v in stages.items()
                               if float(v) > 0.0},
            "shares": shares,
            "top_stage": {"stage": top[0], "share": top[1]},
        }
    except (TypeError, ValueError):
        return None


def snapshot():
    """Attribution off the live registry. Also publishes the verdict and
    input-share gauges, so fleet snapshots (and therefore the rollup)
    carry them without re-deriving. None when telemetry is off or the
    loader has not iterated."""
    if not enabled():
        return None
    report = from_stage_seconds(stage_seconds())
    if report is None:
        return None
    set_gauge(VERDICT_GAUGE, VERDICT_VALUE[report["verdict"]])
    set_gauge(INPUT_SHARE_GAUGE, report["input_share"])
    return report


def format_report(report, indent=""):
    """Human-readable attribution block (mock_train's final report)."""
    if not report:
        return indent + "loader attribution: no batches observed"
    lines = [indent + "loader bound verdict: {} (input share {:.1%} of "
             "{:.2f}s observed wall, {} boundary)".format(
                 report["verdict"], report["input_share"],
                 report["wall_seconds"], report["boundary"])]
    top = report.get("top_stage") or {}
    if top.get("stage"):
        lines.append(indent + "top contributing stage: {} ({:.1%})"
                     .format(top["stage"], top["share"]))
    for stage, share in sorted(report["shares"].items(),
                               key=lambda kv: -kv[1]):
        lines.append(indent + "  {:<14s} {:6.1%}  ({:.3f}s)".format(
            stage, share,
            report["stages_seconds"].get(
                stage if stage != "consumer_step" else
                ("prefetch_gap" if report["boundary"] == "prefetch"
                 else "step_gap"), 0.0)))
    return "\n".join(lines)
