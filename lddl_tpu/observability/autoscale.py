"""Telemetry-driven autoscaling for the elastic preprocessing fleet.

PR 10's fleet aggregator produces backlog gauges and stall/wedge health
verdicts that, until now, nothing consumed. This module closes the loop:
an :class:`Autoscaler` reads ``fleet.aggregate``'s report and spawns or
retires **local helper host processes** (callables supplied by the
caller — ``ingest_watch --autoscale`` wires them to subprocesses that
join the pending generation's elastic claim loop) to hold an ingest
backlog SLO.

Decision policy (deliberately boring — a thermostat, not a controller):

- **scale up** one helper per observation while the max backlog gauge is
  at/above ``backlog_slo_docs`` — or the service is WEDGED (live hosts,
  pending work, no progress: a stuck claim loop wants more claimants) —
  and fewer than ``max_helpers`` run;
- **scale down** one helper per observation after ``drain_rounds``
  consecutive calm observations (no backlog, not wedged, no pending
  work) with more than ``min_helpers`` running.

Every decision is journaled as a fleet lifecycle event
(``autoscale.scale_up`` / ``autoscale.scale_down`` — they surface in
``pipeline_status``'s event table automatically) and counted in
``autoscale_decisions_total{action=...}``.

This module is intentionally **clock-free**: decisions derive only from
the aggregate report and observation counting — pacing belongs to the
caller's loop, and the analyzer's wall-clock rules check this file (it
is excluded from the observability allowlist on purpose). All wall-clock
reads stay inside ``fleet.aggregate``.
"""

import logging

from . import fleet
from . import inc as obs_inc

_log = logging.getLogger("lddl_tpu.observability.autoscale")


def backlog_of(report):
    """The fleet's worst ingest backlog (docs): the max of every host's
    ``ingest_backlog_docs`` gauge — max, not sum, because hosts observe
    the same landing directory (the gauge is a fleet-wide fact each host
    reports, not a per-host share)."""
    worst = 0
    for st in report.get("hosts", {}).values():
        v = st.get("gauges", {}).get("ingest_backlog_docs")
        if v is not None:
            worst = max(worst, int(v))
    return worst


class Autoscaler(object):
    """Spawn/retire helper processes to hold a backlog SLO.

    ``spawn()`` must start one helper and return an opaque handle;
    ``retire(handle)`` must stop it. Handles are retired LIFO (the most
    recently added helper leaves first). The autoscaler never inspects a
    handle — process management stays with the caller."""

    def __init__(self, root, spawn, retire, *, backlog_slo_docs,
                 max_helpers, min_helpers=0, drain_rounds=3,
                 stall_ttl=None, wedge_window=None, log=None):
        if backlog_slo_docs <= 0:
            raise ValueError("backlog_slo_docs must be > 0, got {}".format(
                backlog_slo_docs))
        if max_helpers < min_helpers:
            raise ValueError("max_helpers {} < min_helpers {}".format(
                max_helpers, min_helpers))
        self.root = root
        self._spawn = spawn
        self._retire = retire
        self.backlog_slo_docs = int(backlog_slo_docs)
        self.max_helpers = int(max_helpers)
        self.min_helpers = int(min_helpers)
        self.drain_rounds = max(1, int(drain_rounds))
        self.stall_ttl = stall_ttl
        self.wedge_window = wedge_window
        self._log_fn = log or (lambda msg: _log.info("%s", msg))
        self._helpers = []
        self._calm = 0
        self.decisions = []  # (action, reason) history, for callers/tests

    @property
    def helper_count(self):
        return len(self._helpers)

    def step(self):
        """One control round: aggregate the fleet spools, then decide.
        Returns the observation dict (see :meth:`observe`)."""
        report = fleet.aggregate(self.root, stall_ttl=self.stall_ttl,
                                 wedge_window=self.wedge_window)
        return self.observe(report)

    def observe(self, report):
        """Decide from one aggregate report. Split from :meth:`step` so
        tests (and other controllers) can feed synthetic reports."""
        backlog = backlog_of(report)
        health = report.get("health", {})
        wedged = bool(health.get("wedged"))
        pending = report.get("pending_work")
        obs = {"backlog_docs": backlog, "wedged": wedged,
               "pending_work": pending, "helpers": len(self._helpers),
               "decision": None}
        if (backlog >= self.backlog_slo_docs or wedged) \
                and len(self._helpers) < self.max_helpers:
            reason = ("wedged" if wedged and backlog < self.backlog_slo_docs
                      else "backlog {} >= slo {}".format(
                          backlog, self.backlog_slo_docs))
            self._calm = 0
            obs["decision"] = self._scale_up(reason, backlog)
        elif backlog == 0 and not wedged and pending is None:
            self._calm += 1
            if self._calm >= self.drain_rounds \
                    and len(self._helpers) > self.min_helpers:
                obs["decision"] = self._scale_down(
                    "drained for {} round(s)".format(self._calm), backlog)
        else:
            self._calm = 0
        obs["helpers"] = len(self._helpers)
        return obs

    def _scale_up(self, reason, backlog):
        handle = self._spawn()
        self._helpers.append(handle)
        self._journal("scale_up", reason, backlog)
        return "scale_up"

    def _scale_down(self, reason, backlog):
        handle = self._helpers.pop()
        try:
            self._retire(handle)
        finally:
            self._journal("scale_down", reason, backlog)
        return "scale_down"

    def _journal(self, action, reason, backlog):
        self.decisions.append((action, reason))
        obs_inc("autoscale_decisions_total", action=action)
        fleet.record("autoscale.{}".format(action), reason=reason,
                     backlog_docs=backlog, helpers=len(self._helpers),
                     slo_docs=self.backlog_slo_docs)
        self._log_fn("autoscale: {} ({}); {} helper(s) now running".format(
            action, reason, len(self._helpers)))

    def shutdown(self):
        """Retire every helper (service stopping). Each retirement is
        journaled like a drain-driven scale-down."""
        while self._helpers:
            self._scale_down("service shutdown", 0)
