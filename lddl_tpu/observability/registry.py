"""Process-wide metrics registry: labelled counters, gauges, histograms.

Telemetry backbone for every pipeline stage (preprocess, balance, loader,
resilience). Design constraints, in priority order:

1. **Inert**: instrumentation may never change pipeline behavior. No
   metric op raises into the caller, nothing here touches any RNG stream
   (backoff jitter, shuffle streams, masking draws are all out of reach —
   this module never imports ``random``/``numpy.random``), and exports go
   to a separate metrics directory, never into a shard directory.
2. **Near-zero when disabled**: the loader's per-batch hot path calls the
   module-level helpers below; when telemetry is off each call is one
   env-dict lookup + an early return (same trick as resilience.faults).
   ``enabled()`` lets per-sample loops hoist even that.
3. **Thread-safe**: loader worker threads and the exporter thread update
   metrics concurrently; every mutation holds the registry lock (the
   critical sections are a dict update — nanoseconds).

Enablement is ENV-VAR based (``LDDL_TPU_METRICS_DIR``) so spawned pool /
loader worker processes inherit it automatically; ``configure()`` is the
in-process convenience that sets the env var and (optionally) the rank
label used in export filenames.

Metric names are **stable API** (the README table documents them); spell
them ``<stage>_<what>_<unit-suffix>`` like Prometheus conventions.
"""

import math
import os
import threading

ENV_DIR = "LDDL_TPU_METRICS_DIR"
ENV_RANK = "LDDL_TPU_METRICS_RANK"

_lock = threading.RLock()
# Cached enablement: (raw env value, metrics_dir or None). Re-checked on
# every call so faults.arm()-style env flips take effect immediately.
_cached = {"raw": object(), "dir": None}


def metrics_dir():
    """The active metrics directory, or None when telemetry is disabled.
    One env-dict lookup on the cached path."""
    raw = os.environ.get(ENV_DIR)
    if raw != _cached["raw"]:
        with _lock:
            _cached["raw"] = raw
            _cached["dir"] = raw or None
    return _cached["dir"]


def enabled():
    """True when telemetry is armed (LDDL_TPU_METRICS_DIR set)."""
    return metrics_dir() is not None


def rank():
    """The rank tag used in export filenames (0 unless configured)."""
    try:
        return int(os.environ.get(ENV_RANK, "0"))
    except ValueError:
        return 0


def _labels_key(labels):
    if not labels:
        return ()
    return tuple(sorted(labels.items()))


class _Metric:
    """Shared storage: {labels_key: value-ish} guarded by the registry
    lock. Subclasses define the value semantics."""

    kind = "untyped"

    def __init__(self, name, help=""):
        self.name = name
        self.help = help
        self._values = {}

    def _items(self):
        with _lock:
            return list(self._values.items())


class Counter(_Metric):
    """Monotonic counter. ``inc`` clamps negative deltas to zero (a
    counter that can go down is a gauge; refusing keeps exports honest)."""

    kind = "counter"

    def inc(self, value=1, **labels):
        if value < 0:
            value = 0
        key = _labels_key(labels)
        with _lock:
            self._values[key] = self._values.get(key, 0) + value

    def value(self, **labels):
        with _lock:
            return self._values.get(_labels_key(labels), 0)

    def total(self):
        with _lock:
            return sum(self._values.values())

    def snapshot(self):
        return {"type": "counter",
                "values": {_fmt_labels(k): v for k, v in self._items()}}


class Gauge(_Metric):
    kind = "gauge"

    def set(self, value, **labels):
        with _lock:
            self._values[_labels_key(labels)] = value

    def value(self, **labels):
        with _lock:
            return self._values.get(_labels_key(labels))

    def snapshot(self):
        return {"type": "gauge",
                "values": {_fmt_labels(k): v for k, v in self._items()}}


class Histogram(_Metric):
    """Log-bucketed histogram: observations land in power-of-two buckets
    keyed by their binary exponent (``math.frexp``), so any dynamic range
    costs O(64) buckets and zero configuration. Tracks sum/count/min/max
    per label set for exact means alongside the shape."""

    kind = "histogram"

    def observe(self, value, **labels):
        key = _labels_key(labels)
        v = float(value)
        if v > 0 and not math.isinf(v):
            b = math.frexp(v)[1]  # v in (2**(b-1), 2**b]
        else:
            b = None  # <=0 / non-finite: one catch-all underflow bucket
        with _lock:
            st = self._values.get(key)
            if st is None:
                st = {"count": 0, "sum": 0.0, "min": v, "max": v,
                      "buckets": {}}
                self._values[key] = st
            st["count"] += 1
            st["sum"] += v
            if v < st["min"]:
                st["min"] = v
            if v > st["max"]:
                st["max"] = v
            st["buckets"][b] = st["buckets"].get(b, 0) + 1

    def stats(self, **labels):
        with _lock:
            st = self._values.get(_labels_key(labels))
            if st is None:
                return None
            out = dict(st)
            out["buckets"] = dict(st["buckets"])
            return out

    def snapshot(self):
        out = {}
        for key, st in self._items():
            with _lock:
                buckets = {
                    ("le_" + repr(2.0 ** b) if b is not None else "le_0"): n
                    for b, n in sorted(
                        st["buckets"].items(),
                        key=lambda kv: (kv[0] is None, kv[0]))
                }
                out[_fmt_labels(key)] = {
                    "count": st["count"], "sum": st["sum"],
                    "min": st["min"], "max": st["max"],
                    "mean": st["sum"] / st["count"] if st["count"] else 0.0,
                    "buckets": buckets,
                }
        return {"type": "histogram", "values": out}


def _fmt_labels(key):
    if not key:
        return ""
    return ",".join("{}={}".format(k, v) for k, v in key)


_final_export_registered = []


def _ensure_final_export():
    """Register a best-effort end-of-process export (once). Without this,
    metrics recorded by short-lived processes — spawn-pool preprocess
    workers, an env-armed CLI run that never calls write_summary() — die
    with the process and the documented metrics-*.jsonl/.prom files never
    appear; tracing already flushes at exit, counters must too."""
    if _final_export_registered:
        return
    _final_export_registered.append(True)
    import atexit

    def _final_export():
        try:
            if metrics_dir() is None:
                return
            from . import exporters
            exporters.final_flush()
        except Exception:  # noqa: BLE001 - telemetry must stay inert
            pass

    atexit.register(_final_export)
    try:
        from . import exporters
        # SIGTERM must flush the same set atexit does (see
        # exporters.install_signal_flush); a polite kill used to lose
        # everything since the last periodic export.
        exporters.install_signal_flush()
    except Exception:  # noqa: BLE001 - telemetry must stay inert
        pass


class Registry:
    """Name -> metric map. ``counter``/``gauge``/``histogram`` create on
    first use and return the existing metric thereafter; asking for an
    existing name with a different type raises (a true bug at the
    instrumentation site — the one failure this layer should not
    swallow, and it cannot fire from a disabled run)."""

    def __init__(self):
        self._metrics = {}

    def _get(self, cls, name, help):
        with _lock:
            m = self._metrics.get(name)
            if m is None:
                m = cls(name, help=help)
                self._metrics[name] = m
                if metrics_dir() is not None:
                    _ensure_final_export()
            elif not isinstance(m, cls):
                raise TypeError(
                    "metric {!r} already registered as {} (wanted {})"
                    .format(name, type(m).__name__, cls.__name__))
            return m

    def counter(self, name, help=""):
        return self._get(Counter, name, help)

    def gauge(self, name, help=""):
        return self._get(Gauge, name, help)

    def histogram(self, name, help=""):
        return self._get(Histogram, name, help)

    def names(self):
        with _lock:
            return sorted(self._metrics)

    def get(self, name):
        with _lock:
            return self._metrics.get(name)

    def snapshot(self):
        """{name: {"type": ..., "values"/...}} for every metric — the
        exporters' single source."""
        with _lock:
            items = list(self._metrics.items())
        return {name: m.snapshot() for name, m in sorted(items)}

    def reset(self):
        """Drop every metric (tests and fresh benchmark runs)."""
        with _lock:
            self._metrics.clear()


_REGISTRY = Registry()


def registry():
    return _REGISTRY


# ---------------------------------------------------------------- helpers
# Module-level instrumentation points. Each is a no-op after one cheap
# enabled() check when telemetry is off, and never raises when it is on.

def inc(name, value=1, **labels):
    if metrics_dir() is None:
        return
    try:
        _REGISTRY.counter(name).inc(value, **labels)
    except TypeError:
        raise
    except Exception:  # noqa: BLE001 - telemetry must stay inert
        pass


def set_gauge(name, value, **labels):
    if metrics_dir() is None:
        return
    try:
        _REGISTRY.gauge(name).set(value, **labels)
    except TypeError:
        raise
    except Exception:  # noqa: BLE001 - telemetry must stay inert
        pass


def observe(name, value, **labels):
    if metrics_dir() is None:
        return
    try:
        _REGISTRY.histogram(name).observe(value, **labels)
    except TypeError:
        raise
    except Exception:  # noqa: BLE001 - telemetry must stay inert
        pass
