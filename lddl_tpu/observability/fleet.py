"""Fleet telemetry: cross-host spools + aggregation over the shared FS.

PR 2's observability is strictly per-process: one registry, one trace
file per rank+pid, all under one host's metrics dir. The elastic
work-stealing preprocess and the streaming-ingest service run as N
independent host processes sharing nothing but the output directory —
so this module extends telemetry to the same deployment assumption the
lease protocol uses: **no RPC, no daemons, just files on the shared
filesystem**.

Publisher side (each host, armed via ``LDDL_TPU_FLEET_DIR``):

    <fleet_dir>/.telemetry/<holder>/
        snapshot-pid<p>.json    latest registry snapshot + clock pair +
                                liveness flag, atomically republished
                                every heartbeat (resilience.io path)
        events-pid<p>.jsonl     append-only structured event log: unit
                                lifecycle (claimed -> renewed -> stolen/
                                fenced -> journaled) and generation
                                lifecycle (intake -> preprocess ->
                                delta-balance -> gate-advance ->
                                committed); every record carries a
                                (wall, mono) clock pair
        metrics-*.jsonl / trace-*.jsonl / ...
                                the PR 2 per-process exports, colocated
                                when ``configure()`` arms the metrics
                                dir into the spool

Events buffer in memory and flush on the heartbeat interval AND from the
atexit/SIGTERM handlers (exporters.install_signal_flush), so a dying
host leaves a parseable tail; a SIGKILLed host may leave one torn final
line, which every reader here treats as end-of-stream with a warning —
mirroring torn-lease handling (resilience.leases.read_lease). The
injector's ``kill`` fault flushes the fleet spool pre-kill for the same
reason it flushes metrics: a crash the telemetry exists to expose must
not also destroy the telemetry.

Aggregator side (``aggregate()`` / ``merge_traces()``, consumed by
``tools/pipeline_status.py`` and ``tools/trace_summary.py --merge``):
merges all host spools into cluster rollups (units/s and MB/s per host
and total, steal/fence/retry/quarantine counts, heartbeat ages, ingest
backlog and generation lag, padding efficiency) and renders health
verdicts — a host is **stalled** when its heartbeat age exceeds the
stall TTL without a clean-shutdown marker, the service is **wedged**
when live hosts exist but the journal/ledger shows no progress inside
the wedge window. ``merge_traces`` re-bases every host's Chrome-trace
events through its published (wall, mono) clock samples — a wall-clock
step mid-run is detected as an offset jump and corrected back onto the
host's monotonic timeline — and assigns per-host Perfetto lanes, so one
merged trace spans the whole fleet.

Inertness contract (same as registry.py/tracing.py): disabled, every
hook is one env-dict lookup; enabled, nothing here raises into the
pipeline, touches an RNG stream, or writes outside ``.telemetry/``.
Wall-clock reads are confined to this module (observability is
allowlisted for them), so the status CLI in tools/ stays clock-free.
"""

import json
import logging
import os
import re
import socket
import threading
import time

from . import tracing
from .registry import ENV_DIR as ENV_METRICS_DIR
from .registry import metrics_dir, rank, registry

ENV_FLEET_DIR = "LDDL_TPU_FLEET_DIR"
ENV_HOLDER = "LDDL_TPU_FLEET_HOLDER"
ENV_INTERVAL = "LDDL_TPU_FLEET_INTERVAL_S"
ENV_TTL = "LDDL_TPU_FLEET_TTL_S"
ENV_ROTATE_BYTES = "LDDL_TPU_FLEET_ROTATE_BYTES"
ENV_RETAIN_BYTES = "LDDL_TPU_FLEET_RETAIN_BYTES"
ENV_RETAIN_AGE_S = "LDDL_TPU_FLEET_RETAIN_AGE_S"

TELEMETRY_DIR = ".telemetry"
DEFAULT_INTERVAL_S = 10.0
DEFAULT_TTL_S = 30.0
# Spool retention: append segments (events/series) freeze at the rotate
# bound and start a .segNNNN successor; gc_spool drops frozen segments
# and closed foreign snapshots past the total-size/age budget — the same
# bounded-accumulation discipline the mock store's generation GC has.
DEFAULT_ROTATE_BYTES = 4 << 20
DEFAULT_RETAIN_BYTES = 64 << 20
DEFAULT_RETAIN_AGE_S = 7 * 24 * 3600.0

# A (wall - mono) offset drifting more than this from its first sample is
# a wall-clock STEP (NTP slew stays far under it); merge_traces re-anchors
# later events onto the host's monotonic timeline.
CLOCK_STEP_S = 0.5

# Event kinds that constitute pipeline PROGRESS for the wedge verdict
# (scheduling chatter like renewals deliberately does not count).
PROGRESS_EVENTS = frozenset({
    "unit.journaled", "generation.committed", "generation.gate_advance",
    "generation.pickup",
})

_MAX_BUFFER = 50000  # hard cap, like tracing: runaway loops must not OOM

_log = logging.getLogger("lddl_tpu.observability.fleet")

_SAFE_RE = re.compile(r"[^A-Za-z0-9_.-]+")

# RLock for the same reason as tracing._lock: the SIGTERM flush handler
# may interrupt a frame holding this lock on the main thread, and must
# re-enter rather than deadlock the dying process.
_lock = threading.RLock()
_events = []
_started = []          # [True] once the heartbeat/exit hooks are live
_hb = {"thread": None, "stop": None, "beats": 0}
_cached = {"raw": object(), "dir": None}
_ev_segment = {"path": None}   # this pid's current events append segment
_started_wall = time.time()


# ------------------------------------------------------------- enablement


def fleet_dir():
    """The fleet root (spools live under ``<dir>/.telemetry/``), or None
    when fleet telemetry is disabled. One env lookup on the cached path."""
    raw = os.environ.get(ENV_FLEET_DIR)
    if raw != _cached["raw"]:
        with _lock:
            _cached["raw"] = raw
            _cached["dir"] = raw or None
    return _cached["dir"]


def enabled():
    return fleet_dir() is not None


def sanitize_holder(holder):
    safe = _SAFE_RE.sub("-", str(holder)).strip("-")
    return safe or "host"


def holder():
    """This process's spool name: the env-pinned holder (inherited by
    worker processes) or a per-process hostname-pid default."""
    h = os.environ.get(ENV_HOLDER)
    if h:
        return sanitize_holder(h)
    return sanitize_holder("{}-pid{}".format(socket.gethostname(),
                                             os.getpid()))


def spool_dir(root=None, for_holder=None):
    root = root if root is not None else fleet_dir()
    if root is None:
        return None
    return os.path.join(root, TELEMETRY_DIR, for_holder or holder())


def _env_float(name, default):
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default


def configure(dir, holder_id=None, ttl=None, interval=None,  # noqa: A002
              arm_metrics=True):
    """Arm fleet telemetry in this process AND future children (env vars
    are the source of truth, like registry.configure). Pins the holder
    into the env so spawned pool/loader workers publish into the SAME
    spool (per-pid files never contend). ``arm_metrics=True`` (default)
    also points ``LDDL_TPU_METRICS_DIR`` at the spool when metrics are
    not armed elsewhere, colocating the PR 2 per-process exports with the
    fleet spool — which is what lets the aggregator compute counter
    rollups and merge traces for hosts that died mid-run."""
    os.environ[ENV_FLEET_DIR] = dir
    os.environ[ENV_HOLDER] = sanitize_holder(holder_id) if holder_id \
        else holder()
    if ttl is not None:
        os.environ[ENV_TTL] = str(float(ttl))
    if interval is not None:
        os.environ[ENV_INTERVAL] = str(float(interval))
    spool = spool_dir()
    if arm_metrics and metrics_dir() is None:
        os.environ[ENV_METRICS_DIR] = spool
    ensure_started()
    return spool


def adopt_holder(holder_id, ttl=None):
    """Pin ``holder_id`` as this process tree's spool name if the env has
    not already chosen one (the elastic runner calls this so spool names
    match lease-file holder ids — 'which host is stalled' and 'who stole
    unit 7' then name the same thing), and advertise ``ttl`` as the stall
    threshold hint when none was configured (a heartbeat older than the
    lease TTL is exactly when survivors may steal the host's units). A
    no-op when fleet is disabled."""
    if not enabled():
        return
    if not os.environ.get(ENV_HOLDER):
        os.environ[ENV_HOLDER] = sanitize_holder(holder_id)
    if ttl is not None and not os.environ.get(ENV_TTL):
        os.environ[ENV_TTL] = str(float(ttl))
    ensure_started()


# ------------------------------------------------------------- publishing


def record(kind, **fields):
    """Append one lifecycle event to the in-memory buffer (flushed on the
    heartbeat and at exit). A no-op costing one env lookup when disabled;
    enabled, it never raises into the caller."""
    if fleet_dir() is None:
        return
    try:
        ev = {"kind": str(kind), "wall": time.time(),
              "mono": time.monotonic(), "pid": os.getpid()}
        if fields:
            ev["args"] = {k: _jsonable(v) for k, v in fields.items()}
        with _lock:
            if len(_events) >= _MAX_BUFFER:
                return
            _events.append(ev)
        ensure_started()
    except Exception:  # noqa: BLE001 - telemetry must stay inert
        pass


def _jsonable(v):
    if isinstance(v, (str, int, float, bool)) or v is None:
        return v
    return str(v)


def rotating_path(d, prefix, state):
    """The current append segment for this pid under ``d``: the base
    ``<prefix><pid>.jsonl`` until it reaches the rotation bound, then
    ``<prefix><pid>.segNNNN.jsonl`` successors. Rotation never renames
    (os.replace is reserved for the resilience.io publish path) — a full
    segment simply freezes and appends move to the next name, which the
    readers' shared-prefix glob merges seamlessly. ``state`` is a
    per-writer dict carrying the cached current path."""
    base = os.path.join(d, "{}{}".format(prefix, os.getpid()))
    path = state.get("path") or base + ".jsonl"
    cap = _env_float(ENV_ROTATE_BYTES, DEFAULT_ROTATE_BYTES)
    try:
        size = os.path.getsize(path)
    except OSError:
        size = 0
    if size >= cap:
        seq = state.get("seq", 0) + 1
        # A restart that reuses the pid must not append to a frozen
        # segment from the previous life: skip to the first free name.
        while os.path.exists("{}.seg{:04d}.jsonl".format(base, seq)):
            seq += 1
        state["seq"] = seq
        path = "{}.seg{:04d}.jsonl".format(base, seq)
    state["path"] = path
    return path


def gc_spool(d=None, now=None):
    """Size/age-bounded retention for one spool dir. Candidates are
    frozen (rotated) event/series segments that are not this process's
    current append target, and closed snapshots left by OTHER pids
    (generations and restarts otherwise accumulate them forever). A
    candidate is dropped when it is older than the retention age, or
    oldest-first while the spool exceeds the byte budget. Live segments
    and open snapshots are never touched, so a host's current telemetry
    survives any GC pass. Returns the number of files removed."""
    d = d if d is not None else spool_dir()
    if d is None or not os.path.isdir(d):
        return 0
    now = time.time() if now is None else float(now)
    retain_bytes = _env_float(ENV_RETAIN_BYTES, DEFAULT_RETAIN_BYTES)
    retain_age = _env_float(ENV_RETAIN_AGE_S, DEFAULT_RETAIN_AGE_S)
    with _lock:
        keep = {_ev_segment.get("path")}
    try:
        from . import series
        with series._lock:
            keep.add(series._segment.get("path"))
    except Exception:  # noqa: BLE001 - best-effort; GC still runs
        pass
    total, candidates = 0, []
    try:
        names = sorted(os.listdir(d))
    except OSError:
        return 0
    for name in names:
        path = os.path.join(d, name)
        try:
            st = os.stat(path)
        except OSError:
            continue
        total += st.st_size
        if path in keep:
            continue
        frozen = (".seg" in name and name.endswith(".jsonl") and
                  (name.startswith("events-pid") or
                   name.startswith("series-pid")))
        stale_snap = False
        if name.startswith("snapshot-pid") and name.endswith(".json"):
            snap = _read_json(path, warn=lambda *a: None)
            stale_snap = bool(snap) and bool(snap.get("closed")) \
                and int(snap.get("pid", -1)) != os.getpid()
        if frozen or stale_snap:
            candidates.append((st.st_mtime, st.st_size, path))
    candidates.sort()  # oldest first
    removed = 0
    for mtime, size, path in candidates:
        if (now - mtime) <= retain_age and total <= retain_bytes:
            continue
        try:
            os.remove(path)
        except OSError:
            continue
        total -= size
        removed += 1
    return removed


def _maybe_gc(every=6):
    """Run retention every Nth heartbeat (the spool is small between
    passes; a listdir per beat would be pure overhead)."""
    try:
        with _lock:
            _hb["beats"] = _hb.get("beats", 0) + 1
            if _hb["beats"] % every != 1:
                return
        gc_spool()
    except Exception:  # noqa: BLE001 - telemetry must stay inert
        pass


def _snapshot_path():
    d = spool_dir()
    if d is None:
        return None
    return os.path.join(d, "snapshot-pid{}.json".format(os.getpid()))


def flush_events():
    """Append buffered events to this process's spool event log (current
    rotation segment). Each line is written complete; only a mid-write
    crash can tear the final line, which readers degrade to
    end-of-stream."""
    d = spool_dir()
    with _lock:
        path = _ev_segment.get("path")
        if not _events:
            if path is None and d is not None:
                path = os.path.join(
                    d, "events-pid{}.jsonl".format(os.getpid()))
            return path
        batch, _events[:] = list(_events), []
    if d is None:
        return None
    try:
        from ..resilience import io as rio
        os.makedirs(d, exist_ok=True)
        # rotating_path mutates the shared segment dict, and both the
        # heartbeat thread and the SIGTERM/atexit flush reach here.
        with _lock:
            path = rotating_path(d, "events-pid", _ev_segment)
        payload = "".join(json.dumps(ev, sort_keys=True) + "\n"
                          for ev in batch)
        with rio.open_append(path) as f:
            f.write(payload.encode("utf-8"))
    except Exception:  # noqa: BLE001 - drop the batch, never the pipeline
        pass
    return path


def publish_snapshot(closed=False, reason=None):
    """Atomically (re)publish this process's registry snapshot + clock
    pair + liveness flag, via the resilience.io publish path — the same
    tmp+fsync+replace dance shards ride, so a reader never sees a torn
    snapshot. ``closed=True`` marks a clean shutdown: the aggregator only
    stall-flags hosts that went silent WITHOUT it."""
    path = _snapshot_path()
    if path is None:
        return None
    try:
        from ..resilience import io as rio
        snap = {
            "holder": holder(),
            "pid": os.getpid(),
            "rank": rank(),
            "hostname": socket.gethostname(),
            "wall": time.time(),
            "mono": time.monotonic(),
            "started_wall": _started_wall,
            "interval_s": _env_float(ENV_INTERVAL, DEFAULT_INTERVAL_S),
            "ttl_s": _env_float(ENV_TTL, DEFAULT_TTL_S),
            "closed": bool(closed),
            "metrics": registry().snapshot(),
        }
        if reason:
            snap["closed_reason"] = str(reason)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        rio.atomic_write(path, json.dumps(snap, sort_keys=True, default=str))
    except Exception:  # noqa: BLE001 - drop the export, never the pipeline
        return None
    return path


def heartbeat(closed=False, reason=None):
    """One publish cycle: event-log flush + snapshot republish (+ the
    colocated PR 2 exports when the metrics dir lives in the spool).
    Called by the heartbeat thread, the exit hooks, and the fault
    injector's pre-kill flush."""
    if not enabled():
        return None
    flush_events()
    path = publish_snapshot(closed=closed, reason=reason)
    try:
        # Series history rides the same beat (and therefore the same
        # atexit/SIGTERM/pre-kill flush paths) as the snapshot: a crash
        # loses at most one interval of points plus maybe a torn line.
        from . import series
        series.sample_and_flush()
    except Exception:  # noqa: BLE001 - best-effort history
        pass
    try:
        tracing.flush()
        d = metrics_dir()
        if d is not None and os.path.abspath(d) == os.path.abspath(
                spool_dir() or d):
            from . import exporters
            exporters.export_jsonl()
    except Exception:  # noqa: BLE001 - best-effort colocated exports
        pass
    _maybe_gc()
    return path


def ensure_started(interval=None):
    """Start the heartbeat thread + exit hooks once (idempotent, no-op
    when disabled). Every ``record()`` calls this, so arming the env var
    is the only configuration a host needs — including the metrics side:
    if no metrics dir is armed, one is pointed at the spool here, so an
    env-only arming (documented as equivalent to ``--fleet-telemetry``)
    still publishes non-empty registry snapshots instead of silently
    reporting every counter as zero."""
    if not enabled() or _started:
        return
    with _lock:
        if _started:
            return
        _started.append(True)
    if metrics_dir() is None:
        spool = spool_dir()
        if spool is not None:
            os.environ[ENV_METRICS_DIR] = spool
    import atexit
    atexit.register(_final_flush)
    from . import exporters
    exporters.install_signal_flush()
    # Arm-time stamp: a host that dies between configure() and the first
    # heartbeat used to leave an EMPTY spool dir, indistinguishable from
    # one that never started — and with no started_wall, the aggregator
    # could not even age it into a STALLED verdict. Publish immediately
    # so every armed process leaves at least a start stamp.
    try:
        publish_snapshot()
    except Exception:  # noqa: BLE001 - telemetry must stay inert
        pass
    if interval is None:
        interval = _env_float(ENV_INTERVAL, DEFAULT_INTERVAL_S)
    stop = threading.Event()

    def loop():
        while not stop.wait(interval):
            if not enabled():
                return
            try:
                heartbeat()
            except Exception:  # noqa: BLE001 - keep beating
                pass

    t = threading.Thread(target=loop, name="lddl-fleet-heartbeat",
                         daemon=True)
    t.start()
    # The heartbeat thread writes _hb["beats"] under _lock; publish the
    # thread/stop handles under the same lock.
    with _lock:
        _hb["thread"] = t
        _hb["stop"] = stop


def _final_flush():
    try:
        heartbeat(closed=True, reason="atexit")
    except Exception:  # noqa: BLE001 - exiting anyway
        pass


def _reset_for_tests():
    with _lock:
        _events[:] = []
        _started[:] = []
        _ev_segment.clear()
        _ev_segment["path"] = None
        _hb["beats"] = 0
        stop = _hb["stop"]
        _hb["thread"] = None
        _hb["stop"] = None
    if stop is not None:
        stop.set()
    from . import series
    series._reset_for_tests()


# ------------------------------------------------------------ spool reads


def read_jsonl(path, warn=None):
    """All parseable records of one spool JSONL file, torn-tolerant:
    a torn TRAILING line (a writer died mid-append) reads as end-of-
    stream with a warning; a torn interior line (storage misbehaviour)
    is skipped with a warning. Never raises on content. Streams line by
    line (long-running hosts grow spools without bound — never hold the
    whole file), with one unparsed line of lookahead to tell trailing
    from interior. Returns ``(records, torn_line_count)``."""
    warn = warn or _log.warning
    records, torn = [], 0
    pending = None  # line number of the last unparsed line, pending EOF
    try:
        with open(path, "rb") as f:
            for i, line in enumerate(f):
                line = line.strip()
                if not line:
                    continue
                if pending is not None:
                    warn("torn interior line %d in %s; skipping",
                         pending + 1, path)
                    pending = None
                try:
                    rec = json.loads(line)
                except ValueError:
                    torn += 1
                    pending = i
                    continue
                if isinstance(rec, dict):
                    records.append(rec)
    except OSError as e:
        warn("unreadable telemetry file %s (%s); skipping", path, e)
        return [], 0
    if pending is not None:
        warn("torn trailing line in %s (writer died mid-append?); "
             "treating as end-of-stream", path)
    return records, torn


def _read_json(path, warn=None):
    warn = warn or _log.warning
    try:
        with open(path, "rb") as f:
            raw = f.read()
    except OSError as e:
        warn("unreadable telemetry file %s (%s); skipping", path, e)
        return None
    try:
        rec = json.loads(raw)
    except ValueError:
        warn("torn telemetry snapshot %s; skipping", path)
        return None
    return rec if isinstance(rec, dict) else None


def telemetry_root(root):
    return os.path.join(root, TELEMETRY_DIR)


def list_holders(root):
    d = telemetry_root(root)
    if not os.path.isdir(d):
        return []
    return [n for n in sorted(os.listdir(d))
            if os.path.isdir(os.path.join(d, n))]


def load_spool(root, holder_name, warn=None):
    """One holder's spool, parsed: latest snapshot per pid, the full
    event stream (wall-ordered), and torn-line accounting."""
    d = spool_dir(root, holder_name)
    snapshots, events, torn = {}, [], 0
    for name in sorted(os.listdir(d)) if os.path.isdir(d) else []:
        path = os.path.join(d, name)
        if name.startswith("snapshot-pid") and name.endswith(".json"):
            snap = _read_json(path, warn)
            if snap is not None:
                snapshots[int(snap.get("pid", 0))] = snap
        elif name.startswith("events-pid") and name.endswith(".jsonl"):
            recs, t = read_jsonl(path, warn)
            events.extend(recs)
            torn += t
    events.sort(key=lambda ev: ev.get("wall", 0.0))
    return {"holder": holder_name, "dir": d, "snapshots": snapshots,
            "events": events, "torn_lines": torn}


# ------------------------------------------------------------- aggregator

# Registry counters the rollup surfaces per host and in the totals row
# (report key -> metric name; counts are summed over the holder's pids).
ROLLUP_COUNTERS = (
    ("units_completed", "elastic_units_completed_total"),
    ("steals", "lease_steals_total"),
    ("fence_rejects", "lease_fence_rejects_total"),
    ("renews", "lease_renews_total"),
    ("retries", "resilience_retry_attempts_total"),
    ("retry_exhausted", "resilience_retry_exhausted_total"),
    ("faults_injected", "resilience_faults_injected_total"),
    ("quarantined_shards", "resilience_quarantined_shards_total"),
    ("docs", "preprocess_docs_total"),
    ("doc_bytes", "preprocess_doc_bytes_total"),
    ("samples", "preprocess_samples_total"),
    ("pack_tokens_placed", "preprocess_pack_tokens_total"),
    ("pack_slot_tokens", "preprocess_pack_slot_tokens_total"),
    ("ingest_docs", "ingest_docs_total"),
    ("generations_published", "ingest_generations_published_total"),
    ("loader_batches", "loader_batches_total"),
    ("backend_ops", "backend_ops_total"),
    ("backend_cas_conflicts", "backend_cas_conflicts_total"),
    ("alerts_fired", "alerts_fired_total"),
)

# Labelled counters surfaced per host WITH their label breakdown (the
# flat ROLLUP_COUNTERS sum above collapses labels; these keep them).
ROLLUP_LABELLED = (
    ("backend_ops", "backend_ops_total"),
    ("loader_stage_seconds", "loader_stage_seconds_total"),
    ("alerts_fired", "alerts_fired_total"),
)

# Histograms surfaced per host as merged count/sum/mean/max per label set
# (per-{backend,op} storage op latency is the headline consumer).
ROLLUP_HISTOGRAMS = (
    ("backend_op_latency", "backend_op_latency_seconds"),
)

# Gauges reported at host level when present (latest snapshot wins).
ROLLUP_GAUGES = (
    ("padding_efficiency", "loader_padding_efficiency"),
    ("generation_lag", "loader_generation_lag"),
    ("generations_loaded", "loader_generations_loaded"),
    ("ingest_generation", "ingest_generation"),
    ("ingest_backlog_docs", "ingest_backlog_docs"),
    ("ingest_carry_rows", "ingest_carry_rows"),
    ("samples_per_second", "preprocess_samples_per_second"),
    ("pack_fill_ratio", "preprocess_pack_fill_ratio"),
)


def _counter_total(snap_metrics, name):
    data = (snap_metrics or {}).get(name)
    if not data or data.get("type") != "counter":
        return 0
    return sum(data.get("values", {}).values())


def _gauge_value(snap_metrics, name):
    data = (snap_metrics or {}).get(name)
    if not data or data.get("type") != "gauge":
        return None
    values = data.get("values", {})
    if not values:
        return None
    # Unlabelled gauge is the common case; otherwise take the max label.
    return values.get("", max(values.values()))


def _labelled_totals(snaps, metric):
    """{label_str: value} for one counter, summed over a holder's pids."""
    agg = {}
    for s in snaps:
        data = (s.get("metrics") or {}).get(metric)
        if not data or data.get("type") != "counter":
            continue
        for label_str, v in data.get("values", {}).items():
            agg[label_str] = agg.get(label_str, 0) + v
    return agg


def _histogram_stats(snaps, metric):
    """{label_str: {count, sum, mean, max}} for one histogram, merged
    over a holder's pids (log buckets are dropped here — the windowed
    series path carries percentiles; the rollup carries the moments)."""
    agg = {}
    for s in snaps:
        data = (s.get("metrics") or {}).get(metric)
        if not data or data.get("type") != "histogram":
            continue
        for label_str, st in data.get("values", {}).items():
            cur = agg.setdefault(label_str,
                                 {"count": 0, "sum": 0.0, "max": 0.0})
            cur["count"] += st.get("count", 0)
            cur["sum"] += st.get("sum", 0.0)
            cur["max"] = max(cur["max"], st.get("max", 0.0) or 0.0)
    for cur in agg.values():
        cur["mean"] = (cur["sum"] / cur["count"]) if cur["count"] else None
    return agg


def _stage_seconds_of(labelled):
    """{stage: seconds} off a ``loader_stage_seconds`` label breakdown."""
    out = {}
    for label_str, v in (labelled or {}).items():
        for part in label_str.split(","):
            k, _, stage = part.partition("=")
            if k == "stage" and stage:
                out[stage] = out.get(stage, 0.0) + v
    return out


def _host_rollup(spool, now, stall_ttl):
    snaps = list(spool["snapshots"].values())
    counters = {key: sum(_counter_total(s.get("metrics"), metric)
                         for s in snaps)
                for key, metric in ROLLUP_COUNTERS}
    gauges = {}
    for key, metric in ROLLUP_GAUGES:
        vals = [v for v in (_gauge_value(s.get("metrics"), metric)
                            for s in snaps) if v is not None]
        if vals:
            gauges[key] = max(vals)
    if counters["pack_slot_tokens"]:
        # Recompute the host's pack fill from its counter totals (summed
        # over pids) so the host row and the per-pid gauge agree even
        # when several worker processes each packed a slice.
        gauges["pack_fill_ratio"] = (counters["pack_tokens_placed"]
                                     / counters["pack_slot_tokens"])
    stamps = [s.get("wall", 0.0) for s in snaps]
    stamps.extend(ev.get("wall", 0.0) for ev in spool["events"][-1:])
    last_wall = max(stamps) if stamps else None
    started = min((s.get("started_wall", s.get("wall", now))
                   for s in snaps), default=None)
    ttl = max((s.get("ttl_s", DEFAULT_TTL_S) for s in snaps),
              default=DEFAULT_TTL_S)
    if stall_ttl is not None:
        ttl = stall_ttl
    closed = bool(snaps) and all(s.get("closed") for s in snaps)
    age = (now - last_wall) if last_wall is not None else None
    elapsed = None
    if last_wall is not None and started is not None \
            and last_wall > started:
        elapsed = last_wall - started
    rates = {}
    if elapsed:
        rates["units_per_s"] = counters["units_completed"] / elapsed
        rates["mb_per_s"] = counters["doc_bytes"] / 1e6 / elapsed
        rates["samples_per_s"] = counters["samples"] / elapsed
    event_counts = {}
    for ev in spool["events"]:
        k = ev.get("kind", "?")
        event_counts[k] = event_counts.get(k, 0) + 1
    progress = [ev.get("wall", 0.0) for ev in spool["events"]
                if ev.get("kind") in PROGRESS_EVENTS]
    labelled = {}
    for key, metric in ROLLUP_LABELLED:
        vals = _labelled_totals(snaps, metric)
        if vals:
            labelled[key] = vals
    histograms = {}
    for key, metric in ROLLUP_HISTOGRAMS:
        vals = _histogram_stats(snaps, metric)
        if vals:
            histograms[key] = vals
    attribution_report = None
    stage_s = _stage_seconds_of(labelled.get("loader_stage_seconds"))
    if stage_s:
        try:
            from . import attribution
            attribution_report = attribution.from_stage_seconds(stage_s)
        except Exception:  # noqa: BLE001 - rollup survives a bad snapshot
            attribution_report = None
    return {
        "holder": spool["holder"],
        "pids": sorted(spool["snapshots"]),
        "started_wall": started,
        "last_heartbeat_wall": last_wall,
        "heartbeat_age_s": age,
        "closed": closed,
        "stall_ttl_s": ttl,
        "stalled": (not closed and age is not None and age > ttl),
        "counters": counters,
        "gauges": gauges,
        "rates": rates,
        "labelled": labelled,
        "histograms": histograms,
        "attribution": attribution_report,
        "events_total": len(spool["events"]),
        "event_counts": event_counts,
        "torn_lines": spool["torn_lines"],
        "last_progress_wall": max(progress) if progress else None,
    }


def _fs_progress_stamps(root):
    """Latest mtimes of the on-disk ground truth the wedge verdict also
    trusts: preprocess ledger records and ingest journal segments. File
    mtimes come from the shared FS's clock — same budget the lease
    deadlines already live on."""
    stamps = []
    for d in (os.path.join(root, "_done"),
              os.path.join(root, ".ingest", "journal")):
        if not os.path.isdir(d):
            continue
        for name in sorted(os.listdir(d)):
            try:
                stamps.append(os.stat(os.path.join(d, name)).st_mtime)
            except OSError:
                continue
    return stamps


def _pending_work(root, hosts):
    """Evidence that the pipeline has UNFINISHED work — the wedge verdict
    requires it (an idle-but-alive watch service with nothing to ingest
    is healthy, not wedged): a nonzero ingest backlog gauge on any host,
    an in-flight ingest generation (work dir present), or a preprocess
    run mid-flight (unretired unit ledger)."""
    for st in hosts.values():
        if st["gauges"].get("ingest_backlog_docs"):
            return "ingest backlog"
    wdir = os.path.join(root, ".ingest", "work")
    if os.path.isdir(wdir) and sorted(os.listdir(wdir)):
        return "in-flight ingest generation"
    if os.path.isdir(os.path.join(root, "_done")):
        return "unretired preprocess ledger"
    return None


def _journal_state(root):
    """The ingest journal's latest generation, read off the segment file
    names (cheap, no segment parse)."""
    d = os.path.join(root, ".ingest", "journal")
    if not os.path.isdir(d):
        return None
    gens = []
    for name in sorted(os.listdir(d)):
        m = re.match(r"gen-(\d+)\.json$", name)
        if m:
            gens.append(int(m.group(1)))
    return max(gens) if gens else None


def aggregate(root, now=None, stall_ttl=None, wedge_window=None, warn=None,
              window=None):
    """Merge every host spool under ``<root>/.telemetry/`` into one
    cluster report with health verdicts. Pure function of the spool
    bytes, ``now`` (defaults to this process's wall clock — the one
    clock read the status CLI delegates here) and the thresholds.
    ``window`` (seconds) additionally loads each holder's series
    segments and attaches windowed rates/trends/percentiles per host
    plus a cluster ``window`` block (rates summed across hosts)."""
    now = time.time() if now is None else float(now)
    from . import series as series_mod
    hosts = {}
    for h in list_holders(root):
        hosts[h] = _host_rollup(load_spool(root, h, warn), now, stall_ttl)
        if window:
            points, torn = series_mod.read_series(root, h, warn)
            hosts[h]["window"] = series_mod.window_rollup(
                points, window, now)
            hosts[h]["torn_lines"] += torn
    totals = {key: sum(h["counters"][key] for h in hosts.values())
              for key, _ in ROLLUP_COUNTERS}
    if totals.get("pack_slot_tokens"):
        # Cluster-wide offline-pack fill: recomputed from the summed
        # counters (a mean of per-host ratios would weight hosts, not
        # tokens).
        totals["pack_fill_ratio"] = (totals["pack_tokens_placed"]
                                     / totals["pack_slot_tokens"])
    total_rates = {}
    for key in ("units_per_s", "mb_per_s", "samples_per_s"):
        vals = [h["rates"].get(key) for h in hosts.values()
                if h["rates"].get(key) is not None]
        if vals:
            total_rates[key] = sum(vals)
    stalled = sorted(h for h, st in hosts.items() if st["stalled"])
    live = sorted(h for h, st in hosts.items()
                  if not st["closed"] and not st["stalled"])
    progress = [st["last_progress_wall"] for st in hosts.values()
                if st["last_progress_wall"] is not None]
    progress.extend(_fs_progress_stamps(root))
    last_progress = max(progress) if progress else None
    ttl = stall_ttl if stall_ttl is not None else max(
        (st["stall_ttl_s"] for st in hosts.values()), default=DEFAULT_TTL_S)
    wedge_win = wedge_window if wedge_window is not None \
        else max(4.0 * ttl, 120.0)
    pending = _pending_work(root, hosts)
    # "No progress EVER" must not instant-wedge a freshly started run
    # (the first generation/unit legitimately takes a while to land):
    # the baseline the window counts from is the last progress stamp, or
    # the earliest host start when none exists yet.
    started = [st["started_wall"] for st in hosts.values()
               if st["started_wall"] is not None]
    baseline = last_progress if last_progress is not None \
        else (min(started) if started else None)
    wedged = bool(live) and pending is not None and (
        baseline is not None and (now - baseline) > wedge_win)
    verdicts = []
    for h in stalled:
        verdicts.append(
            "host {} STALLED: last heartbeat {:.1f}s ago exceeds the "
            "{:.1f}s stall TTL with no clean-shutdown marker".format(
                h, hosts[h]["heartbeat_age_s"], hosts[h]["stall_ttl_s"]))
    if wedged:
        age = "never" if last_progress is None \
            else "{:.1f}s ago".format(now - last_progress)
        verdicts.append(
            "service WEDGED: {} live host(s) with {} but last "
            "journal/ledger progress was {} (window {:.1f}s)".format(
                len(live), pending, age, wedge_win))
    for h, st in sorted(hosts.items()):
        if st["torn_lines"]:
            verdicts.append(
                "host {}: {} torn spool line(s) tolerated (host died "
                "mid-append?)".format(h, st["torn_lines"]))
    # Cluster storage-backend view: op counts and merged latency moments
    # per {backend,op,outcome} (pipeline_status --json surfaces these so
    # mock-vs-local op cost is visible from telemetry alone).
    backend_ops, backend_latency = {}, {}
    for st in hosts.values():
        for label_str, v in st["labelled"].get("backend_ops", {}).items():
            backend_ops[label_str] = backend_ops.get(label_str, 0) + v
        for label_str, h_ in st["histograms"].get(
                "backend_op_latency", {}).items():
            cur = backend_latency.setdefault(
                label_str, {"count": 0, "sum": 0.0, "max": 0.0})
            cur["count"] += h_.get("count", 0)
            cur["sum"] += h_.get("sum", 0.0)
            cur["max"] = max(cur["max"], h_.get("max", 0.0) or 0.0)
    for cur in backend_latency.values():
        cur["mean"] = (cur["sum"] / cur["count"]) if cur["count"] else None
    # Cluster attribution: stage seconds summed across hosts, then one
    # fleet-wide bound verdict (a mean of verdicts would weight hosts,
    # not wall time — same reasoning as the pack-fill recompute above).
    cluster_stages = {}
    for st in hosts.values():
        for stage, v in _stage_seconds_of(
                st["labelled"].get("loader_stage_seconds")).items():
            cluster_stages[stage] = cluster_stages.get(stage, 0.0) + v
    cluster_attr = None
    if cluster_stages:
        try:
            from . import attribution
            cluster_attr = attribution.from_stage_seconds(cluster_stages)
        except Exception:  # noqa: BLE001 - report survives bad metrics
            cluster_attr = None
    report_window = None
    if window:
        wrates = {}
        for st in hosts.values():
            for key, r in st.get("window", {}).get("rates", {}).items():
                wrates[key] = wrates.get(key, 0.0) + r
        report_window = {"window_s": float(window), "rates": wrates}
    return {
        "root": os.path.abspath(root),
        "generated_wall": now,
        "hosts": hosts,
        "totals": {"counters": totals, "rates": total_rates},
        "backend": {"ops": backend_ops, "latency": backend_latency},
        "attribution": cluster_attr,
        "window": report_window,
        "journal_generation": _journal_state(root),
        "pending_work": pending,
        "last_progress_wall": last_progress,
        "health": {
            "ok": not stalled and not wedged,
            "stalled_hosts": stalled,
            "live_hosts": live,
            "closed_hosts": sorted(h for h, st in hosts.items()
                                   if st["closed"]),
            "wedged": wedged,
            "stall_ttl_s": ttl,
            "wedge_window_s": wedge_win,
            "verdicts": verdicts,
        },
    }


# ------------------------------------------------------------ trace merge


def _clock_samples(spool):
    """Per-pid (wall, wall-mono) samples from every spool record that
    carries the clock pair, mono-ordered."""
    by_pid = {}
    for ev in spool["events"]:
        if "wall" in ev and "mono" in ev:
            by_pid.setdefault(int(ev.get("pid", 0)), []).append(
                (float(ev["mono"]), float(ev["wall"])))
    for pid, snap in spool["snapshots"].items():
        if "wall" in snap and "mono" in snap:
            by_pid.setdefault(int(pid), []).append(
                (float(snap["mono"]), float(snap["wall"])))
    return {pid: sorted(samples) for pid, samples in by_pid.items()}


def _step_corrections(samples):
    """Wall-clock-step corrections for one pid: segments of
    ``(wall_from, delta_s)`` meaning events stamped at/after ``wall_from``
    were recorded ``delta_s`` off the process's original wall<->mono
    anchor and must be shifted back by ``delta_s``. Empty when the clock
    behaved (the overwhelmingly common case)."""
    if len(samples) < 2:
        return []
    base = samples[0][1] - samples[0][0]  # first wall - mono offset
    segments = []
    current = 0.0
    for mono, wall in samples[1:]:
        delta = (wall - mono) - base
        if abs(delta - current) > CLOCK_STEP_S:
            segments.append((wall, delta))
            current = delta
    return segments


def _corrected_ts(ts_us, segments):
    delta = 0.0
    for wall_from, d in segments:
        if ts_us >= wall_from * 1e6:
            delta = d
    return ts_us - delta * 1e6


def merge_traces(root, warn=None):
    """Merge every host spool's Chrome-trace files into ONE event list
    spanning the fleet: per-(holder, pid) Perfetto lanes (synthetic lane
    pids with ``process_name``/``process_sort_index`` metadata naming the
    real holder+pid), and per-pid wall-clock-step correction from the
    spool's clock samples so a stepped host still lines up. Returns
    ``(events, lanes)`` where lanes is ``[(lane_pid, holder, real_pid)]``;
    the caller writes the JSON (Perfetto accepts a plain JSON array)."""
    events, lanes = [], []
    lane_of = {}
    for h in list_holders(root):
        spool = load_spool(root, h, warn)
        corrections = {pid: _step_corrections(samples)
                       for pid, samples in _clock_samples(spool).items()}
        d = spool["dir"]
        names = [n for n in sorted(os.listdir(d))
                 if n.startswith("trace-") and n.endswith(".jsonl")] \
            if os.path.isdir(d) else []
        for name in names:
            recs, _ = read_jsonl(os.path.join(d, name), warn)
            for rec in recs:
                if rec.get("ph") == "M":
                    continue  # re-emitted per lane below
                real_pid = int(rec.get("pid", 0))
                key = (h, real_pid)
                if key not in lane_of:
                    lane_of[key] = len(lane_of) + 1
                    lanes.append((lane_of[key], h, real_pid))
                out = dict(rec)
                out["pid"] = lane_of[key]
                segs = corrections.get(real_pid)
                if segs and "ts" in out:
                    out["ts"] = _corrected_ts(float(out["ts"]), segs)
                events.append(out)
    meta = []
    for lane, h, real_pid in lanes:
        meta.append({"name": "process_name", "ph": "M", "pid": lane,
                     "args": {"name": "{} pid{}".format(h, real_pid)}})
        meta.append({"name": "process_sort_index", "ph": "M", "pid": lane,
                     "args": {"sort_index": lane}})
    events.sort(key=lambda ev: ev.get("ts", 0.0))
    return meta + events, lanes
