"""Declarative alert rules over telemetry: threshold / rate / absence.

The autoscaler (PR 15) is a thermostat for exactly one quantity; this
module is its general-purpose sibling: a small rule vocabulary evaluated
against the fleet rollup and the registry metrics in the spools, so
operators declare SLOs ("backlog above 10k docs", "CAS-conflict rate
above 5/s over 60s", "no loader heartbeat at all") in a JSON/TOML file
instead of writing watchers.

Rules file (JSON shown; TOML with ``[[rules]]`` tables works when the
interpreter ships ``tomllib``)::

    {"rules": [
      {"name": "backlog-slo", "type": "threshold",
       "metric": "ingest_backlog_docs", "op": ">", "value": 10000},
      {"name": "cas-storm", "type": "rate",
       "metric": "backend_cas_conflicts_total", "window_s": 60,
       "op": ">", "value": 5},
      {"name": "no-loader", "type": "absence",
       "metric": "loader_batches_total", "window_s": 120}
    ]}

``metric`` resolves, in order:

1. a dotted **report path** into the ``fleet.aggregate`` rollup when it
   contains a dot (``totals.counters.fence_rejects``,
   ``health.wedged`` — booleans read as 0/1; a ``*`` segment fans out
   over dict values and takes the numeric max, so
   ``hosts.*.heartbeat_age_s`` is "the worst heartbeat age");
2. a **registry metric name** merged across every holder's latest
   snapshots — counters sum, gauges max, histograms read their mean; an
   optional ``{label=value,...}`` suffix selects one label set.

Rule semantics:

- ``threshold``: fire while ``value <op> threshold`` holds now.
- ``rate``: fire while the windowed per-second rate (computed from the
  series segments, summed across hosts) satisfies ``op``/``value``.
- ``absence``: fire while the metric resolves to nothing — no snapshot
  carries it, and (when ``window_s`` is set) no series point inside the
  window recorded it either. The "is anything alive at all" rule.

Firing/resolving transitions are journaled to
``<root>/.telemetry/alerts-events.jsonl`` in the fleet event-line format
(``alert.fired`` / ``alert.resolved``, torn-tail-tolerant on read), the
engine state persists in ``alerts-state.json`` next to it (so one-shot
``pipeline_status`` invocations detect transitions across runs), and
``alerts_fired_total{rule}`` counts fires when metrics are armed.
Evaluation never raises: a malformed rule reports as an ``error`` entry
and counts as not-firing. Wall-clock reads stay in this module
(observability is allowlisted; the status CLI delegates here).
"""

import json
import logging
import os
import time

from .registry import inc as obs_inc

STATE_FILE = "alerts-state.json"
EVENTS_FILE = "alerts-events.jsonl"

FIRED_COUNTER = "alerts_fired_total"

DEFAULT_WINDOW_S = 60.0

_OPS = {
    ">": lambda a, b: a > b,
    ">=": lambda a, b: a >= b,
    "<": lambda a, b: a < b,
    "<=": lambda a, b: a <= b,
    "==": lambda a, b: a == b,
    "!=": lambda a, b: a != b,
}

_log = logging.getLogger("lddl_tpu.observability.alerts")


def load_rules(path):
    """Parse a rules file (JSON, or TOML when the stdlib has tomllib).
    Returns the normalized rule list; raises ValueError on a file that
    cannot express rules (bad syntax, duplicate names, unknown type) —
    a rules file the operator points at explicitly SHOULD fail loudly,
    unlike the inert telemetry hooks."""
    with open(path, "rb") as f:
        raw = f.read()
    if path.endswith(".toml"):
        try:
            import tomllib
        except ImportError as e:
            raise ValueError(
                "TOML rules need python>=3.11 (tomllib); use JSON") from e
        doc = tomllib.loads(raw.decode("utf-8"))
    else:
        doc = json.loads(raw.decode("utf-8"))
    rules = doc.get("rules", doc) if isinstance(doc, dict) else doc
    if not isinstance(rules, list):
        raise ValueError("rules file must hold a list under 'rules'")
    seen = set()
    out = []
    for i, rule in enumerate(rules):
        if not isinstance(rule, dict):
            raise ValueError("rule #{} is not a table/object".format(i))
        name = str(rule.get("name") or "").strip()
        if not name:
            raise ValueError("rule #{} has no name".format(i))
        if name in seen:
            raise ValueError("duplicate rule name {!r}".format(name))
        seen.add(name)
        rtype = rule.get("type", "threshold")
        if rtype not in ("threshold", "rate", "absence"):
            raise ValueError("rule {!r}: unknown type {!r}".format(
                name, rtype))
        if not rule.get("metric"):
            raise ValueError("rule {!r} has no metric".format(name))
        op = rule.get("op", ">")
        if op not in _OPS:
            raise ValueError("rule {!r}: unknown op {!r}".format(name, op))
        if rtype != "absence" and not isinstance(
                rule.get("value"), (int, float)):
            raise ValueError("rule {!r} needs a numeric value".format(name))
        out.append(dict(rule, name=name, type=rtype, op=op))
    return out


def _split_selector(metric):
    """``name{k=v,...}`` -> (name, {k: v}); plain names pass through."""
    if metric.endswith("}") and "{" in metric:
        name, _, rest = metric.partition("{")
        sel = {}
        for part in rest[:-1].split(","):
            k, _, v = part.partition("=")
            if k:
                sel[k.strip()] = v.strip()
        return name, sel
    return metric, None


def _label_match(label_str, sel):
    if sel is None:
        return True
    have = {}
    for part in label_str.split(","):
        k, _, v = part.partition("=")
        if k:
            have[k] = v
    return all(have.get(k) == v for k, v in sel.items())


def _as_number(v):
    if isinstance(v, bool):
        return 1.0 if v else 0.0
    if isinstance(v, (int, float)):
        return float(v)
    return None


def _report_path(report, path):
    """Resolve a dotted path into the rollup; a ``*`` segment fans out
    over dict values and the numeric max wins (absent -> None)."""
    nodes = [report]
    for seg in path.split("."):
        nxt = []
        for node in nodes:
            if not isinstance(node, dict):
                continue
            if seg == "*":
                nxt.extend(node.values())
            elif seg in node:
                nxt.append(node[seg])
        nodes = nxt
        if not nodes:
            return None
    vals = [n for n in (_as_number(v) for v in nodes) if n is not None]
    return max(vals) if vals else None


def _merged_snapshot_metrics(root, warn=None):
    """{metric_name: {"type", "values": {label_str: merged}}} across every
    holder's latest per-pid snapshots: counters sum, gauges max,
    histograms keep (count, sum, max) for mean-reads."""
    from . import fleet
    merged = {}
    for h in fleet.list_holders(root):
        spool = fleet.load_spool(root, h, warn)
        for snap in spool["snapshots"].values():
            for name, data in (snap.get("metrics") or {}).items():
                kind = data.get("type")
                slot = merged.setdefault(name, {"type": kind, "values": {}})
                if slot["type"] != kind:
                    continue
                for label_str, v in (data.get("values") or {}).items():
                    cur = slot["values"].get(label_str)
                    if kind == "counter":
                        slot["values"][label_str] = (cur or 0) + v
                    elif kind == "gauge":
                        num = _as_number(v)
                        if num is not None:
                            slot["values"][label_str] = num if cur is None \
                                else max(cur, num)
                    elif kind == "histogram" and isinstance(v, dict):
                        if cur is None:
                            cur = {"count": 0, "sum": 0.0}
                            slot["values"][label_str] = cur
                        cur["count"] += v.get("count", 0)
                        cur["sum"] += v.get("sum", 0.0)
    return merged


def _snapshot_value(metrics, metric):
    name, sel = _split_selector(metric)
    data = metrics.get(name)
    if not data:
        return None
    kind, values = data.get("type"), data.get("values", {})
    picked = [(ls, v) for ls, v in values.items() if _label_match(ls, sel)]
    if not picked:
        return None
    if kind == "counter":
        return float(sum(v for _, v in picked))
    if kind == "gauge":
        vals = [n for n in (_as_number(v) for _, v in picked)
                if n is not None]
        return max(vals) if vals else None
    if kind == "histogram":
        count = sum(v.get("count", 0) for _, v in picked)
        total = sum(v.get("sum", 0.0) for _, v in picked)
        return (total / count) if count else None
    return None


def _series_stats(root, metric, window_s, now, warn=None):
    """(windowed_rate_per_s, points_seen) for one metric across every
    holder's series segments; rate sums over hosts, labels merge unless
    a {label=...} selector narrows them."""
    from . import fleet, series
    name, sel = _split_selector(metric)
    rate, points = 0.0, 0
    for h in fleet.list_holders(root):
        pts, _ = series.read_series(root, h, warn)
        roll = series.window_rollup(pts, window_s, now)
        for key, r in roll["rates"].items():
            kname, klabels = series.split_key(key)
            if kname == name and _label_match(klabels, sel):
                rate += r
                points += len(roll["deltas"].get(key, ()))
    return rate, points


class AlertEngine:
    """Evaluates a rule list against one telemetry root, tracking
    firing state across evaluations (in memory, and persisted under
    ``.telemetry/`` so one-shot status runs see transitions too)."""

    def __init__(self, rules, root):
        self.rules = rules
        self.root = root
        self._tdir = os.path.join(root, ".telemetry")
        self._state_path = os.path.join(self._tdir, STATE_FILE)
        self._events_path = os.path.join(self._tdir, EVENTS_FILE)
        self._state = self._load_state()

    def _load_state(self):
        try:
            with open(self._state_path, "rb") as f:
                doc = json.loads(f.read())
            return doc if isinstance(doc, dict) else {}
        except (OSError, ValueError):
            return {}

    def _save_state(self):
        try:
            from ..resilience import io as rio
            os.makedirs(self._tdir, exist_ok=True)
            rio.atomic_write(self._state_path,
                             json.dumps(self._state, sort_keys=True))
        except Exception:  # noqa: BLE001 - state loss degrades to re-fire
            _log.warning("could not persist alert state to %s",
                         self._state_path)

    def _evaluate_rule(self, rule, report, now, warn):
        metric = str(rule["metric"])
        rtype = rule["type"]
        out = {"name": rule["name"], "type": rtype, "metric": metric,
               "firing": False, "value": None}
        try:
            if rtype == "rate":
                window = float(rule.get("window_s", DEFAULT_WINDOW_S))
                rate, _ = _series_stats(self.root, metric, window, now,
                                        warn)
                out["value"] = rate
                out["window_s"] = window
                out["firing"] = _OPS[rule["op"]](rate, rule["value"])
                out["threshold"] = rule["value"]
                return out
            value = None
            if "." in metric:
                value = _report_path(report, metric)
            if value is None:
                value = _snapshot_value(self._metrics_cache(warn), metric)
            if rtype == "absence":
                window = rule.get("window_s")
                if window is not None:
                    # Freshness flavor: the metric must have moved inside
                    # the window — a stale lifetime snapshot doesn't count.
                    _, pts = _series_stats(self.root, metric,
                                           float(window), now, warn)
                    absent = pts == 0
                else:
                    absent = value is None
                out["firing"] = absent
                out["value"] = value
                return out
            out["value"] = value
            out["threshold"] = rule["value"]
            out["firing"] = value is not None and _OPS[rule["op"]](
                value, rule["value"])
            return out
        except Exception as e:  # noqa: BLE001 - one bad rule != no alerts
            out["error"] = str(e)
            out["firing"] = False
            return out

    def _metrics_cache(self, warn):
        if not hasattr(self, "_metrics"):
            self._metrics = _merged_snapshot_metrics(self.root, warn)
        return self._metrics

    def evaluate(self, report=None, now=None, warn=None):
        """One evaluation pass. Returns ``{"alerts": [...], "firing":
        [names], "transitions": [...]}``; transitions (vs the persisted
        state) are appended to the alert event log and counted."""
        now = time.time() if now is None else float(now)
        if report is None:
            from . import fleet
            report = fleet.aggregate(self.root, now=now, warn=warn)
        if hasattr(self, "_metrics"):
            del self._metrics  # re-read snapshots every pass
        alerts, transitions = [], []
        for rule in self.rules:
            res = self._evaluate_rule(rule, report, now, warn)
            prev = self._state.get(res["name"], {})
            was_firing = bool(prev.get("firing"))
            if res["firing"] and not was_firing:
                transitions.append({"kind": "alert.fired",
                                    "rule": res["name"],
                                    "value": res["value"], "wall": now})
                self._state[res["name"]] = {"firing": True,
                                            "since_wall": now}
                obs_inc(FIRED_COUNTER, rule=res["name"])
            elif not res["firing"] and was_firing:
                transitions.append({"kind": "alert.resolved",
                                    "rule": res["name"],
                                    "value": res["value"], "wall": now})
                self._state[res["name"]] = {"firing": False,
                                            "resolved_wall": now}
            if res["firing"]:
                res["since_wall"] = self._state[res["name"]].get(
                    "since_wall", now)
            alerts.append(res)
        if transitions:
            self._append_transitions(transitions)
        self._save_state()
        return {"now": now, "alerts": alerts,
                "firing": [a["name"] for a in alerts if a["firing"]],
                "transitions": transitions}

    def _append_transitions(self, transitions):
        """Append fired/resolved records to the alert event log — fleet
        event-line format (kind + clock pair + args), same torn-tail
        discipline on read."""
        try:
            from ..resilience import io as rio
            os.makedirs(self._tdir, exist_ok=True)
            mono = time.monotonic()
            payload = "".join(
                json.dumps({"kind": t["kind"], "wall": t["wall"],
                            "mono": mono, "pid": os.getpid(),
                            "args": {"rule": t["rule"],
                                     "value": t["value"]}},
                           sort_keys=True) + "\n"
                for t in transitions)
            with rio.open_append(self._events_path) as f:
                f.write(payload.encode("utf-8"))
        except Exception:  # noqa: BLE001 - alerting must not crash status
            _log.warning("could not append alert transitions to %s",
                         self._events_path)


def read_alert_events(root, warn=None):
    """All alert.fired/alert.resolved records under one telemetry root
    (torn-tolerant). Returns ``(records, torn_count)``."""
    from . import fleet
    path = os.path.join(root, ".telemetry", EVENTS_FILE)
    if not os.path.exists(path):
        return [], 0
    return fleet.read_jsonl(path, warn)


def evaluate_file(root, rules_path, report=None, now=None, warn=None):
    """Convenience one-shot: load rules, evaluate, return the result
    (the pipeline_status integration point)."""
    engine = AlertEngine(load_rules(rules_path), root)
    return engine.evaluate(report=report, now=now, warn=warn)
