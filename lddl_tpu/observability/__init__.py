"""Pipeline-wide telemetry: metrics registry, span tracing, exporters.

The observability layer every stage (preprocess, balance, loader,
resilience) reports into. It is **inert by contract**: instrumentation
never raises into the pipeline, never touches any RNG stream, and never
writes into a shard directory — and when disabled (the default) every
hook is a single env-dict lookup (see registry.py / tracing.py).

Arm it with ``LDDL_TPU_METRICS_DIR=/path`` (inherited by worker
processes) or ``observability.configure(dir=...)``; drive a run with
``benchmarks/mock_train.py --metrics-dir``. Metric names are stable API —
the README "Observability" section is the catalog.

Quick tour::

    from lddl_tpu import observability as obs

    obs.configure(dir="/tmp/metrics", periodic=True)
    with obs.span("preprocess.scatter", shard=3):
        ...
    obs.inc("preprocess_docs_total", 128)
    obs.observe("loader_batch_latency_seconds", 0.004)
    obs.set_gauge("loader_padding_efficiency", 0.87)
    print(obs.summary()["padding_efficiency"])
    obs.write_summary()          # summary-*.json + trace flush
"""

from . import alerts, attribution, fleet, series
from .exporters import (
    configure,
    disable,
    export_jsonl,
    export_prom,
    install_signal_flush,
    start_periodic_export,
    stop_periodic_export,
    summary,
    write_summary,
)
from .registry import (
    Counter,
    Gauge,
    Histogram,
    Registry,
    enabled,
    inc,
    metrics_dir,
    observe,
    registry,
    set_gauge,
)
from .tracing import event, flush, span, trace_path

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "Registry",
    "alerts",
    "attribution",
    "configure",
    "disable",
    "enabled",
    "event",
    "export_jsonl",
    "export_prom",
    "fleet",
    "flush",
    "install_signal_flush",
    "inc",
    "metrics_dir",
    "observe",
    "registry",
    "series",
    "set_gauge",
    "span",
    "start_periodic_export",
    "stop_periodic_export",
    "summary",
    "trace_path",
    "write_summary",
]
