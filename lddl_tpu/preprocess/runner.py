"""Distributed preprocessing runner.

Reference parity: the execution layer that lddl delegates to Dask + dask-mpi
(lddl/dask/bert/pretrain.py:573-581) plus the global shuffle it performs as
a dask bag->dataframe all-to-all (pretrain.py:100-111).

TPU-native redesign (SURVEY.md §7.4): the task graph of this workload is
embarrassingly parallel per block, so we replace the dynamic scheduler with
*static deterministic scheduling*: every host plans the identical block
list, takes blocks by rank striding, and synchronizes only at phase
barriers via the Communicator (jax.distributed on pods; no MPI). The global
document shuffle is a two-pass, shared-filesystem all-to-all:

    phase 1 (scatter):  each worker reads its input blocks and appends every
                        document to a hash-assigned bucket spool file
                        (_shuffle/bucket-<k>/block-<b>.txt) — the bucket is a
                        deterministic hash of (seed, doc position), so the
                        assignment is a true random permutation independent
                        of input order.
    phase 2 (gather):   each worker owns buckets by striding, reads a
                        bucket's spool files, shuffles in-bucket, tokenizes,
                        builds pairs, and writes part.<k>.parquet[_<bin>].

TPU pods always mount shared storage (GCS/NFS) for their shards, so the
spool rides the same medium the output does.
"""

import hashlib
import os
import shutil
import time

from ..parallel.distributed import LocalCommunicator
from ..utils import rng as lrng
from .bert import (
    BertPretrainConfig,
    TokenizerInfo,
    instances_from_texts,
    materialize_columns,
    materialize_rows,
)
from .readers import discover_source_files, plan_blocks, read_documents
from . import binning as binning_mod

_SPOOL_DIR = "_shuffle"


def _bucket_of(seed, block_id, doc_ordinal, nbuckets):
    digest = hashlib.blake2b(
        "{}:{}:{}".format(seed, block_id, doc_ordinal).encode(),
        digest_size=8).digest()
    return int.from_bytes(digest, "little") % nbuckets


def vocab_words_of(tokenizer):
    """Vocab tokens ordered by id, specials excluded. Kept for the
    per-sequence masking helper; the pipeline itself uses TokenizerInfo."""
    specials = set(tokenizer.all_special_tokens)
    vocab = tokenizer.get_vocab()
    return [t for t, _ in sorted(vocab.items(), key=lambda kv: kv[1])
            if t not in specials]


def _spool_one_block(block, out_dir, seed, sample_ratio, nbuckets):
    """Scatter one input block: append every doc to its hash bucket's spool
    file. Each block writes its own per-bucket files, so blocks can spool
    concurrently (across ranks and across pool workers) without locking."""
    spool_root = os.path.join(out_dir, _SPOOL_DIR)
    sinks = {}
    try:
        for ordinal, (doc_id, text) in enumerate(
                read_documents(block, sample_ratio=sample_ratio,
                               base_seed=seed)):
            b = _bucket_of(seed, block.block_id, ordinal, nbuckets)
            sink = sinks.get(b)
            if sink is None:
                bucket_dir = os.path.join(spool_root, "bucket-{}".format(b))
                os.makedirs(bucket_dir, exist_ok=True)
                sink = open(
                    os.path.join(bucket_dir,
                                 "block-{}.txt".format(block.block_id)),
                    "w", encoding="utf-8")
                sinks[b] = sink
            sink.write(doc_id + " " + text + "\n")
    finally:
        for sink in sinks.values():
            sink.close()


def _read_bucket_docs(out_dir, bucket):
    bucket_dir = os.path.join(out_dir, _SPOOL_DIR, "bucket-{}".format(bucket))
    texts = []
    if not os.path.isdir(bucket_dir):
        return texts
    for name in sorted(os.listdir(bucket_dir)):
        with open(os.path.join(bucket_dir, name), encoding="utf-8") as f:
            for line in f:
                line = line.rstrip("\n")
                if line.strip():
                    # Strip the doc id; pair creation is id-agnostic.
                    parts = line.split(None, 1)
                    if len(parts) == 2 and parts[1].strip():
                        texts.append(parts[1])
    return texts


class BertBucketProcessor:
    """Picklable per-bucket BERT pipeline stage: shuffle -> instances ->
    materialize -> shard sink. Pickles the HF tokenizer (fast tokenizers
    serialize to their JSON form); the TokenizerInfo tables and native
    engine are rebuilt lazily once per process."""

    def __init__(self, tokenizer, config, seed, out_dir, bin_size,
                 output_format):
        self.tokenizer = tokenizer
        self.config = config
        self.seed = seed
        self.out_dir = out_dir
        self.bin_size = bin_size
        self.output_format = output_format
        self._tok_info = None

    def __getstate__(self):
        state = self.__dict__.copy()
        state["_tok_info"] = None  # rebuilt per process
        return state

    def __setstate__(self, state):
        self.__dict__.update(state)

    @property
    def tok_info(self):
        if self._tok_info is None:
            self._tok_info = TokenizerInfo(self.tokenizer)
        return self._tok_info

    def __call__(self, texts, bucket):
        config, seed = self.config, self.seed
        g = lrng.sample_rng(seed, 0x9A1A, bucket)
        lrng.shuffle(g, texts)
        batch = instances_from_texts(texts, self.tok_info, config, seed,
                                     bucket)
        if self.output_format == "txt":
            rows = materialize_rows(batch, config, self.tok_info, seed,
                                    (0x3A5C, bucket))
            return _write_txt_shard(rows, self.out_dir, bucket,
                                    config.masking, self.bin_size,
                                    config.max_seq_length)
        columns, n = materialize_columns(batch, config, self.tok_info, seed,
                                         (0x3A5C, bucket))
        return binning_mod.write_shard_columns(
            columns, n, self.out_dir, bucket, masking=config.masking,
            bin_size=self.bin_size,
            target_seq_length=config.max_seq_length)


def _write_txt_shard(rows, out_dir, part_id, masking, bin_size,
                     target_seq_length):
    """Human-readable debug sink (ref: pretrain.py:501-532 _save_txt)."""
    from ..utils.fs import deserialize_np_array
    os.makedirs(out_dir, exist_ok=True)

    def fmt(r):
        if masking:
            return ("is_random_next: {} - [CLS] {} [SEP] {} [SEP] - "
                    "masked_lm_positions: {} - masked_lm_labels: {} - {}".format(
                        r["is_random_next"], r["A"], r["B"],
                        deserialize_np_array(r["masked_lm_positions"]).tolist(),
                        r["masked_lm_labels"], r["num_tokens"]))
        return "is_random_next: {} - [CLS] {} [SEP] {} [SEP] - {}".format(
            r["is_random_next"], r["A"], r["B"], r["num_tokens"])

    written = {}
    if bin_size is None:
        path = os.path.join(out_dir, "{}.txt".format(part_id))
        with open(path, "w", encoding="utf-8") as f:
            for r in rows:
                f.write(fmt(r) + "\n")
        written[path] = len(rows)
        return written
    nbins = binning_mod.num_bins(target_seq_length, bin_size)
    by_bin = {}
    for r in rows:
        b = binning_mod.bin_id_of_num_tokens(r["num_tokens"], bin_size, nbins)
        by_bin.setdefault(b, []).append(r)
    for b, bin_rows in sorted(by_bin.items()):
        path = os.path.join(out_dir, "{}.txt_{}".format(part_id, b))
        with open(path, "w", encoding="utf-8") as f:
            for r in bin_rows:
                f.write(fmt(r) + "\n")
        written[path] = len(bin_rows)
    return written


# Worker-process globals for the intra-host pool (set by _pool_init).
_POOL = {}


def _pool_init(process_bucket, spec):
    _POOL["process_bucket"] = process_bucket
    _POOL["spec"] = spec


def _bucket_texts(spec, bucket):
    """Load one bucket's documents inside a worker (texts never cross the
    process boundary; workers re-read from the spool / re-plan blocks
    deterministically)."""
    if spec["global_shuffle"]:
        return _read_bucket_docs(spec["out_dir"], bucket)
    input_files = discover_source_files(spec["corpus_paths"])
    blocks = plan_blocks(input_files, spec["num_blocks"])
    return [text for _, text in read_documents(
        blocks[bucket], sample_ratio=spec["sample_ratio"],
        base_seed=spec["seed"])]


def _pool_run_bucket(bucket):
    texts = _bucket_texts(_POOL["spec"], bucket)
    return _POOL["process_bucket"](texts, bucket)


def _pool_scatter_block(block_id):
    spec = _POOL["spec"]
    input_files = discover_source_files(spec["corpus_paths"])
    blocks = plan_blocks(input_files, spec["num_blocks"])
    _spool_one_block(blocks[block_id], spec["out_dir"], spec["seed"],
                     spec["sample_ratio"], len(blocks))
    return block_id


def run_sharded_pipeline(
    corpus_paths,
    out_dir,
    process_bucket,
    num_blocks=64,
    sample_ratio=0.9,
    seed=12345,
    global_shuffle=True,
    comm=None,
    log=None,
    num_workers=1,
):
    """Generic SPMD scaffolding shared by every preprocessor: dirty-dir
    guard -> block planning -> (optional) scatter shuffle -> strided bucket
    processing via ``process_bucket(texts, bucket) -> {path: n}`` ->
    cleanup + reduced totals.

    Returns {path: num_rows} for the shards written by THIS rank (ranks
    own disjoint buckets; the balancer performs the global census).
    SPMD: call on every host with the same arguments; hosts split the work
    by ``comm`` rank and meet at barriers.
    """
    comm = comm or LocalCommunicator()
    log = log or (lambda msg: None)

    # Refuse a dirty output dir: stale part files from a previous run with a
    # different block count would silently survive next to fresh ones and
    # duplicate data downstream.
    if os.path.isdir(out_dir):
        stale = [
            n for n in os.listdir(out_dir)
            if ".parquet" in n or (".txt" in n and not n.startswith("."))
            or n == _SPOOL_DIR
        ]
        if stale:
            raise ValueError(
                "output dir {} already contains {} shard files (e.g. {}); "
                "remove them or choose a fresh directory".format(
                    out_dir, len(stale), stale[0]))
    # No rank may start writing before every rank has passed the guard.
    comm.barrier()

    t0 = time.time()
    input_files = discover_source_files(corpus_paths)
    blocks = plan_blocks(input_files, num_blocks)
    nbuckets = len(blocks)
    log("{} input files -> {} blocks".format(len(input_files), len(blocks)))

    # Intra-host fan-out (the reference runs ~128 MPI ranks per node,
    # slurm_example.sub:72; our equivalent is one Communicator rank per
    # host times a local spawn pool). Workers re-read inputs themselves —
    # only bucket ids cross the process boundary.
    my_buckets = list(range(comm.rank, nbuckets, comm.world_size))
    workers = max(1, int(num_workers or 1))
    pool = None
    if workers > 1 and len(my_buckets) > 1:
        import concurrent.futures
        import multiprocessing
        spec = {
            "global_shuffle": global_shuffle,
            "out_dir": out_dir,
            "corpus_paths": corpus_paths,
            "num_blocks": num_blocks,
            "sample_ratio": sample_ratio,
            "seed": seed,
        }
        pool = concurrent.futures.ProcessPoolExecutor(
            max_workers=min(workers, len(my_buckets)),
            mp_context=multiprocessing.get_context("spawn"),
            initializer=_pool_init,
            initargs=(process_bucket, spec))

    try:
        if global_shuffle:
            my_blocks = list(range(comm.rank, len(blocks), comm.world_size))
            if pool is not None:
                list(pool.map(_pool_scatter_block, my_blocks))
            else:
                for b in my_blocks:
                    _spool_one_block(blocks[b], out_dir, seed, sample_ratio,
                                     nbuckets)
            log("rank {}: scatter phase done".format(comm.rank))
            comm.barrier()

        written = {}
        if pool is not None:
            for res in pool.map(_pool_run_bucket, my_buckets):
                written.update(res)
        else:
            for bucket in my_buckets:
                if global_shuffle:
                    texts = _read_bucket_docs(out_dir, bucket)
                else:
                    texts = [
                        text for _, text in read_documents(
                            blocks[bucket], sample_ratio=sample_ratio,
                            base_seed=seed)
                    ]
                written.update(process_bucket(texts, bucket))
    finally:
        if pool is not None:
            pool.shutdown()
    comm.barrier()

    if global_shuffle and comm.rank == 0:
        shutil.rmtree(os.path.join(out_dir, _SPOOL_DIR), ignore_errors=True)
    totals = comm.allreduce_sum([len(written), sum(written.values())])
    log("preprocess done in {:.1f}s, {} shards, {} samples".format(
        time.time() - t0, int(totals[0]), int(totals[1])))
    return written


def run_bert_preprocess(
    corpus_paths,
    out_dir,
    tokenizer,
    config=None,
    num_blocks=64,
    sample_ratio=0.9,
    seed=12345,
    bin_size=None,
    global_shuffle=True,
    output_format="parquet",
    comm=None,
    log=None,
    num_workers=1,
):
    """Run the full BERT preprocessing pipeline (see run_sharded_pipeline
    for the SPMD execution contract). ``num_workers`` > 1 fans the bucket
    work out over a local process pool per host."""
    config = config or BertPretrainConfig()
    if output_format not in ("parquet", "txt"):
        raise ValueError("output_format must be parquet|txt")
    if bin_size is not None:
        binning_mod.num_bins(config.max_seq_length, bin_size)  # validate

    return run_sharded_pipeline(
        corpus_paths,
        out_dir,
        BertBucketProcessor(tokenizer, config, seed, out_dir, bin_size,
                            output_format),
        num_blocks=num_blocks,
        sample_ratio=sample_ratio,
        seed=seed,
        global_shuffle=global_shuffle,
        comm=comm,
        log=log,
        num_workers=num_workers,
    )
