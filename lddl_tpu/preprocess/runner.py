"""Distributed preprocessing runner.

Reference parity: the execution layer that lddl delegates to Dask + dask-mpi
(lddl/dask/bert/pretrain.py:573-581) plus the global shuffle it performs as
a dask bag->dataframe all-to-all (pretrain.py:100-111).

TPU-native redesign (SURVEY.md §7.4): the task graph of this workload is
embarrassingly parallel per block, so we replace the dynamic scheduler with
*static deterministic scheduling*: every host plans the identical block
list, takes blocks by rank striding, and synchronizes only at phase
barriers via the Communicator (jax.distributed on pods; no MPI). The global
document shuffle is a two-pass, shared-filesystem all-to-all over a
two-level radix:

    phase 1 (scatter):  each writer (one per rank, or per pool worker)
                        reads its input blocks; every document goes to a
                        hash-assigned fine bucket (a deterministic hash of
                        (seed, doc position) — a true random permutation
                        independent of input order), and is appended,
                        tagged with "<bucket> <block>", to the COARSE
                        group spool file this writer exclusively owns:
                        _shuffle/group-<bucket %% G>/w<writer>.txt.
    phase 2 (gather):   workers own coarse groups by striding; each reads
                        its group's spool files once, splits per fine
                        bucket, restores the canonical per-bucket order
                        (block-id lex order — byte-stable vs any writer
                        layout), shuffles in-bucket, tokenizes, builds
                        pairs, writes part.<k>.parquet[_<bin>].

Spool file count is G x writers = O(sqrt-ish of blocks x writers), NOT
O(blocks^2) like a per-(bucket, block) layout — at the 12.5 GB north-star
(4096 blocks, 8 hosts x 16 workers) that is ~66k files instead of 16.7M.
Every spool file has exactly ONE writer process for its whole life, so
plain O_APPEND is safe even on NFS (no cross-client append races). TPU
pods always mount shared storage (GCS-fuse/NFS) for their shards, so the
spool rides the same medium the output does.
"""

import hashlib
import json
import logging
import os
import shutil
import time

from .. import observability as obs
from ..parallel.distributed import LocalCommunicator
from ..resilience import io as rio
from ..resilience.integrity import build_manifest
from ..utils import rng as lrng
from .bert import (
    BertPretrainConfig,
    TokenizerInfo,
    instances_from_texts,
    masked_instances_from_texts,
    materialize_columns,
    materialize_rows,
)
from .readers import discover_source_files, plan_blocks, read_documents
from . import binning as binning_mod
from . import sink as sink_mod

_SPOOL_DIR = "_shuffle"
_LEDGER_DIR = "_done"
_SCATTER_MARKER = ".scatter_done"

_log = logging.getLogger("lddl_tpu.preprocess.runner")


class _Progress:
    """Throttled phase progress lines with ETA (VERDICT r2: a multi-hour
    pod run must not be a black box between barriers; replaces the
    reference's implicit Dask/bokeh dashboard, /root/reference/setup.py:52)."""

    def __init__(self, log, phase, total, interval_s=5.0):
        self.log = log
        self.phase = phase
        self.total = total
        self.interval_s = interval_s
        self.done = 0
        self.samples = 0
        # Log-only rate/ETA meter; never reaches shard bytes or order.
        self.t0 = time.time()  # lddl: disable=wall-clock
        self._last = 0.0

    def tick(self, samples=0, force=False):
        self.done += 1
        self.samples += samples
        now = time.time()  # lddl: disable=wall-clock (log-only ETA)
        if not force and now - self._last < self.interval_s \
                and self.done < self.total:
            return
        self._last = now
        elapsed = now - self.t0
        rate = self.done / elapsed if elapsed > 0 else 0.0
        eta = (self.total - self.done) / rate if rate > 0 else float("inf")
        msg = "{}: {}/{} units in {:.0f}s (eta {:.0f}s)".format(
            self.phase, self.done, self.total, elapsed, eta)
        if self.samples:
            msg += ", {} samples".format(self.samples)
        self.log(msg)


def _run_units(fn, units, pool_factory, log, phase, retry_deaths=True,
               max_rounds=3, progress_interval=5.0, on_result=None,
               writer=None):
    """Run ``fn(unit) -> result`` over all units, serially or on a process
    pool, with per-unit fault isolation: a unit whose task raises is
    recorded as failed (others continue). A worker process dying (OOM
    killer, preemption) breaks the whole pool; when ``retry_deaths``, the
    pool is rebuilt and every unfinished unit resubmitted — a break names
    no culprit, so collateral units are NOT charged an attempt. After
    ``max_rounds`` pool-wide rounds the survivors run one-by-one in fresh
    single-worker pools (exact attribution: a unit that breaks its solo
    pool is the culprit and fails; innocents complete). ``on_result`` is
    called as each unit finishes (journal hook — survives a later crash).

    ``writer`` (serial path only): a :class:`sink.ShardWriter` the unit
    functions defer their durable writes to. A unit returning
    ``sink.DeferredUnit`` completes asynchronously — its result (or
    failure) is collected from the writer at the next unit boundary and
    at the final drain, and ``on_result`` (the ledger journal) fires only
    then, i.e. only after that unit's writes actually hit stable storage.
    This is the cross-unit double buffer: unit N's parquet encode + fsync
    + publish overlap unit N+1's read/tokenize/mask.
    Returns ({unit: result}, {unit: error_string})."""
    import concurrent.futures as cf
    from concurrent.futures.process import BrokenProcessPool

    progress = _Progress(log, phase, len(units), interval_s=progress_interval)
    results, failures = {}, {}

    def record(u, res):
        results[u] = res
        if on_result is not None:
            on_result(u, res)
        progress.tick(sum(res.values()) if isinstance(res, dict) else 0)

    def record_failure(u, msg):
        failures[u] = msg
        progress.tick()

    if pool_factory is None:
        def safe_record(u, res):
            # Per-unit isolation extends to the journal hook itself: an
            # on_result failure (e.g. persistent EIO on the ledger dir)
            # fails THAT unit, never the whole phase.
            try:
                record(u, res)
            except Exception as e:  # noqa: BLE001 - isolate per unit
                record_failure(u, "{}: {}".format(type(e).__name__, e))

        for u in units:
            if writer is not None:
                # Collect (and journal) units whose deferred writes have
                # finished while this thread was computing later units.
                sink_mod.collect_into(writer.completed(), safe_record,
                                      record_failure)
            try:
                res = fn(u)
                if writer is not None \
                        and isinstance(res, sink_mod.DeferredUnit):
                    continue  # completes at a later collect / final drain
                record(u, res)
            except Exception as e:  # noqa: BLE001 - isolate per unit
                record_failure(u, "{}: {}".format(type(e).__name__, e))
        if writer is not None:
            sink_mod.collect_into(writer.drain(), safe_record,
                                  record_failure)
        return results, failures

    pending = list(units)
    rounds = 0
    pool = pool_factory()
    try:
        while pending and rounds < max_rounds:
            rounds += 1
            futures = {pool.submit(fn, u): u for u in pending}
            pending = []
            broken = False
            for fut in cf.as_completed(futures):
                u = futures[fut]
                try:
                    record(u, fut.result())
                except BrokenProcessPool:
                    broken = True
                    if retry_deaths:
                        pending.append(u)
                    else:
                        record_failure(u, "worker process died")
                except Exception as e:  # noqa: BLE001
                    record_failure(u, "{}: {}".format(type(e).__name__, e))
            if broken and pending:
                log("{}: worker died; rebuilding pool, retrying {} "
                    "unit(s)".format(phase, len(pending)))
                pool.shutdown(wait=False)
                pool = pool_factory()
        if pending:  # repeated breaks: exact attribution, one unit at a time
            log("{}: repeated worker deaths; isolating {} unit(s)".format(
                phase, len(pending)))
            pool.shutdown(wait=False)
            pool = None
            for u in pending:
                solo = pool_factory(max_workers=1)
                try:
                    record(u, solo.submit(fn, u).result())
                except BrokenProcessPool:
                    record_failure(u, "worker process died (isolated)")
                except Exception as e:  # noqa: BLE001
                    record_failure(u, "{}: {}".format(type(e).__name__, e))
                finally:
                    solo.shutdown(wait=False)
    finally:
        if pool is not None:
            pool.shutdown()
    return results, failures


def _ledger_path(out_dir, group):
    return os.path.join(out_dir, _LEDGER_DIR, "group-{}.json".format(group))


def _check_resume_manifest(out_dir, fingerprint, resume, rank):
    """Stamp the run arguments that define unit identity into the ledger
    dir; a resume with a different fingerprint would silently mix units
    from two incompatible plans (ledger ids denote different bucket sets,
    stale part files survive the skipped dirty-dir guard), so refuse."""
    path = os.path.join(out_dir, _LEDGER_DIR, "manifest.json")
    if resume and os.path.exists(path):
        with open(path) as f:
            prior = json.load(f)
        if prior != fingerprint:
            raise ValueError(
                "resume fingerprint mismatch: this run was started with "
                "{} but resume got {}; re-run with the original arguments "
                "or start a fresh output dir".format(prior, fingerprint))
    elif rank == 0:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        rio.atomic_write(path, json.dumps(fingerprint))


def _ledger_write(out_dir, group, written):
    """Durable atomic per-group completion record (resilience.io): a crash
    between part-file writes and the ledger write just redoes the group,
    and a crash right after the write can never durably publish a torn
    ledger that a resume would half-trust."""
    path = _ledger_path(out_dir, group)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    rio.atomic_write(path, json.dumps(written))


def _ledger_read(out_dir, group):
    """One group's completion record, or None when the unit is not done.

    Reads ride ``resilience.io.read_bytes`` (transient EIO/ESTALE on
    NFS-like mounts retry with backoff instead of silently reading as
    "not done" and redoing a finished unit). A torn/empty record — a
    crash can only leave none-or-whole files through atomic_write, but
    flaky storage can still serve torn bytes — degrades to "unit not
    done" with a warning rather than crashing the resume."""
    path = _ledger_path(out_dir, group)
    rec, status = rio.read_json(path)
    if status == "torn":
        _log.warning("torn/unparseable ledger record %s (%d bytes); "
                     "treating unit as not done (it will be redone)",
                     path, len(rec))
        return None
    return rec


def _bucket_of(seed, block_id, doc_ordinal, nbuckets):
    digest = hashlib.blake2b(
        "{}:{}:{}".format(seed, block_id, doc_ordinal).encode(),
        digest_size=8).digest()
    return int.from_bytes(digest, "little") % nbuckets


def vocab_words_of(tokenizer):
    """Vocab tokens ordered by id, specials excluded. Kept for the
    per-sequence masking helper; the pipeline itself uses TokenizerInfo."""
    specials = set(tokenizer.all_special_tokens)
    vocab = tokenizer.get_vocab()
    return [t for t, _ in sorted(vocab.items(), key=lambda kv: kv[1])
            if t not in specials]


def _canonical_paths(corpus_paths):
    """``discover_source_files``'s {name: path} dict with every path
    absolutized (normpath+abspath, NO symlink resolution: realpath would
    diverge across hosts whose automounters resolve the same logical
    path differently, spuriously refusing a multi-host resume).
    Explicit file lists (the ingest service's form) canonicalize as the
    sorted absolutized list."""
    def canon(v):
        if isinstance(v, str):
            return os.path.abspath(v)
        if isinstance(v, (list, tuple)):
            return sorted(os.path.abspath(str(p)) for p in v)
        return str(v)

    return {k: canon(v) for k, v in sorted(corpus_paths.items())}


def splitter_digest(splitter_params):
    """One digest rule for learned splitter params in resume fingerprints
    (BERT and BART must invalidate identically on a splitter change)."""
    if splitter_params is None:
        return "none"
    return hashlib.sha256(splitter_params.serialize()).hexdigest()[:16]


def processor_fingerprint(*fields):
    """Shared digest skeleton for processor resume fingerprints: joins the
    stringified fields (dataclass configs serialize as sorted json) and
    hashes. One implementation so BERT/BART digests cannot drift."""
    import dataclasses

    def canon(f):
        if dataclasses.is_dataclass(f) and not isinstance(f, type):
            return json.dumps(dataclasses.asdict(f), sort_keys=True,
                              default=str)
        return str(f)

    return hashlib.sha256(
        "|".join(canon(f) for f in fields).encode()).hexdigest()[:16]


def _num_spool_groups(nbuckets):
    """Default coarse-group count: enough groups for gather parallelism,
    few enough that spool files stay O(groups x writers)."""
    return min(nbuckets, max(64, nbuckets // 8))


def _group_of_bucket(bucket, ngroups):
    return bucket % ngroups


def _buckets_of_group(group, nbuckets, ngroups):
    return range(group, nbuckets, ngroups)


def _spool_one_block(block, out_dir, seed, sample_ratio, nbuckets, ngroups,
                     spool_name):
    """Scatter one input block: buffer every doc per (coarse group, fine
    bucket) — a block is a bounded slice of the corpus, ~corpus/nblocks
    bytes — then append each group's lines to THIS writer's exclusive
    spool file ``spool_name`` (``w<rank>-<pid>.txt`` for the static
    scheduler; the elastic scheduler names files per claim attempt,
    ``s<slice>.e<epoch>.<holder>.txt``, so a reclaimed unit's debris is
    sweepable and a zombie's late appends are fenced out by name). A
    "#B <block> <bucket>" header line precedes each run of document lines
    (written as " " + text), so the gather pays no per-line field parsing
    and the scatter never copies text bytes into a tagged string (the
    round-3 per-line "<bucket> <block> <doc_id> <text>" format cost ~8%
    of end-to-end preprocess throughput — VERDICT.md round 3, item 1)."""
    with obs.span("preprocess.scatter_block", block=block.block_id):
        _spool_one_block_inner(block, out_dir, seed, sample_ratio, nbuckets,
                               ngroups, spool_name)


_WS_TABLE = None  # ASCII whitespace membership (bytes.split(None) set)


def _scan_block_documents(block, sample_ratio, base_seed):
    """Vectorized replay of ``readers.read_documents`` for the scatter:
    returns (buffer, text_starts, text_ends) where document i's text bytes
    are ``buffer[text_starts[i]:text_ends[i]]`` — same documents, same
    order, same per-line sample draws (one bulk ``g.random(n)`` consumes
    the stream exactly like n scalar draws), but the line split, the
    blank-line filter and the '<doc id> <text>' parse all run as numpy
    scans instead of per-line Python."""
    import numpy as np
    global _WS_TABLE
    if _WS_TABLE is None:
        table = np.zeros(256, dtype=bool)
        table[[9, 10, 11, 12, 13, 32]] = True  # bytes.strip()/split(None)
        _WS_TABLE = table
    with open(block.path, "rb") as f:
        if block.start == 0:
            f.seek(0)
        else:
            f.seek(block.start - 1)
            # If the previous byte is not a newline, our start is
            # mid-line: that line belongs to the previous block.
            prev = f.read(1)
            if prev != b"\n":
                f.readline()
        pos0 = f.tell()
        if pos0 >= block.end:
            z = np.zeros(0, dtype=np.int64)
            return b"", z, z
        data = f.read(block.end - pos0)
        # A line that STARTS inside the block is owned whole: complete a
        # truncated tail line from beyond the block boundary.
        if data and not data.endswith(b"\n"):
            data += f.readline()
    if not data:
        z = np.zeros(0, dtype=np.int64)
        return b"", z, z
    arr = np.frombuffer(data, dtype=np.uint8)
    n = len(arr)
    is_ws = _WS_TABLE[arr]
    # ONE nonzero pass: newlines are whitespace (0x0A is in _WS_TABLE),
    # so the line scan is a cheap sub-select of the word scan instead of
    # a second full-buffer np.nonzero (this pair was a profile-top-5
    # hotspot: two O(n) scans per block where one suffices).
    ws_pos = np.flatnonzero(is_ws)  # ~one per word; cheap to search
    nl = ws_pos[arr[ws_pos] == 0x0A]
    nlines = len(nl) + (0 if (len(nl) and nl[-1] == n - 1) else 1)
    line_starts = np.zeros(nlines, dtype=np.int64)
    line_starts[1:] = nl[:nlines - 1] + 1
    line_ends = np.empty(nlines, dtype=np.int64)
    line_ends[:len(nl)] = nl[:nlines]
    if nlines > len(nl):
        line_ends[-1] = n
    # id_start: first non-ws byte of the line. Fast path — the line
    # starts with its doc id (no leading whitespace); the rare
    # leading-ws/blank lines walk forward in Python.
    id_start = line_starts.copy()
    odd = np.flatnonzero(is_ws[np.minimum(line_starts, n - 1)]
                         | (line_starts >= line_ends))
    blank = np.zeros(nlines, dtype=bool)
    for li in odd:
        j = int(line_starts[li])
        e = int(line_ends[li])
        while j < e and is_ws[j]:
            j += 1
        if j >= e:
            blank[li] = True  # `not line.strip()`
        else:
            id_start[li] = j
    id_start = id_start[~blank]
    nb_ends = line_ends[~blank]
    # Per-line sample draw (only non-blank lines draw, as in the scalar
    # path; a kept draw may still yield no document — one bulk
    # ``g.random(n)`` consumes the stream exactly like n scalar draws).
    if sample_ratio < 1.0:
        g = lrng.sample_rng(base_seed, block.block_id)
        kept = g.random(len(id_start)) < sample_ratio
        id_start = id_start[kept]
        nb_ends = nb_ends[kept]
    # '<doc id> <text...>': text starts at the first non-ws after the
    # first ws-run following the id token; lines with no text drop.
    # First ws at/after id_start via ONE searchsorted over ws positions.
    if len(ws_pos):
        j = np.searchsorted(ws_pos, id_start)
        ws_after = np.where(
            j < len(ws_pos), ws_pos[np.minimum(j, len(ws_pos) - 1)], n)
    else:
        ws_after = np.full(len(id_start), n, dtype=np.int64)
    has_sep = ws_after < nb_ends
    # Fast path: a single separator byte (text at ws_after + 1); rare
    # multi-ws separators walk forward in Python.
    probe = np.minimum(ws_after + 1, n - 1)
    multi = np.flatnonzero(has_sep & is_ws[probe])
    text_start = np.where(has_sep, np.minimum(ws_after + 1, n), nb_ends)
    for li in multi:
        j2 = int(text_start[li])
        e = int(nb_ends[li])
        while j2 < e and is_ws[j2]:
            j2 += 1
        text_start[li] = j2
    has_text = has_sep & (text_start < nb_ends)
    return data, text_start[has_text], nb_ends[has_text]


def _spool_one_block_inner(block, out_dir, seed, sample_ratio, nbuckets,
                           ngroups, spool_name):
    import numpy as np
    buf, text_starts, text_ends = _scan_block_documents(
        block, sample_ratio, seed)
    n = len(text_starts)
    obs.inc("preprocess_docs_total", n)
    obs.inc("preprocess_doc_bytes_total",
            int((text_ends - text_starts).sum()))
    if not n:
        return
    # Bucket assignment replays the frozen per-ordinal digest stream:
    # blake2b("{seed}:{block_id}:{ordinal}") == one hasher fed the common
    # prefix, copied per ordinal (hash streaming equivalence) — bytes
    # identical to the scalar _bucket_of, prefix hashed once.
    base = hashlib.blake2b(
        "{}:{}:".format(seed, block.block_id).encode(), digest_size=8)
    buckets = np.empty(n, dtype=np.int64)
    for o in range(n):
        h = base.copy()
        h.update(str(o).encode())
        buckets[o] = int.from_bytes(h.digest(), "little") % nbuckets
    # Vectorized scatter (was ~6.5% of single-worker wall as a per-doc
    # Python loop — the serial floor once the kernel is threaded): one
    # stable lexsort reproduces the nested sorted-dict walk (group asc,
    # bucket asc, ordinal asc), then each group's spool bytes are built
    # with one gather/scatter over the block buffer. Raw bytes end to end
    # (see readers.read_block_lines): document bytes are copied exactly
    # as read, never decoded.
    from .arrowcols import concat_aranges
    groups = buckets % ngroups
    order = np.lexsort((buckets, groups))
    src = np.frombuffer(buf, dtype=np.uint8)
    g_sorted = groups[order]
    g_bounds = np.flatnonzero(np.diff(g_sorted)) + 1
    spool_root = os.path.join(out_dir, _SPOOL_DIR)
    for g_lo, g_hi in zip(np.r_[0, g_bounds],
                          np.r_[g_bounds, len(order)]):
        sel = order[g_lo:g_hi]
        group_dir = os.path.join(
            spool_root, "group-{}".format(int(g_sorted[g_lo])))
        os.makedirs(group_dir, exist_ok=True)
        b_sel = buckets[sel]
        run_starts = np.r_[0, np.flatnonzero(np.diff(b_sel)) + 1]
        headers = ["#B {} {}\n".format(block.block_id,
                                       int(b_sel[s])).encode()
                   for s in run_starts]
        dlen = (text_ends[sel] - text_starts[sel]).astype(np.int64)
        rec = dlen + 2  # b" " + doc + b"\n"
        extra = np.zeros(len(sel), dtype=np.int64)
        extra[run_starts] = [len(h) for h in headers]
        rec_start = np.cumsum(extra + rec) - rec  # the space byte
        out = np.empty(int(rec_start[-1] + rec[-1]), dtype=np.uint8)
        for hb, s in zip(headers, run_starts):
            p = int(rec_start[s]) - len(hb)
            out[p:p + len(hb)] = np.frombuffer(hb, dtype=np.uint8)
        out[rec_start] = 0x20
        out[rec_start + 1 + dlen] = 0x0A
        dst = np.repeat(rec_start + 1, dlen) + concat_aranges(dlen)
        gat = np.repeat(text_starts[sel], dlen) + concat_aranges(dlen)
        out[dst] = src[gat]
        # Guarded append (fault site "open"): spool files are O_APPEND
        # streams, so only the OPEN retries on transient errors — a
        # half-applied write is handled at the unit level (the unmarked
        # spool is wiped and redone on resume).
        with rio.open_append(os.path.join(group_dir, spool_name)) as f:
            f.write(memoryview(out))


def _read_group_texts(out_dir, group, nbuckets, ngroups, accept=None):
    """Read one coarse spool group once; return {bucket: DocSpans} — a
    ZERO-COPY view per bucket over the group's merged spool bytes (each
    document is a (start, end) range; the native engine reads the buffer
    in place and the fallback engines materialize bytes lazily). Each
    bucket's documents come in canonical order: blocks sorted by block id
    as a STRING. (Lex order over digit strings matches the round-2
    layout's sorted-"block-<b>.txt"-filename order, keeping shard bytes
    identical — pinned by tests/golden_spool.json.) Within a block,
    scatter wrote lines in document order under one "#B" header in one
    writer's file, so collecting per (bucket, block) and walking blocks in
    sorted order preserves it regardless of how blocks were dealt to
    writers.

    The line parse is vectorized: newline offsets come from one numpy
    scan, per-line Python happens only at "#B" headers (one per
    (block, bucket) run, not per document) — this was the 'other_python'
    sink in PROFILE_PREPROCESS.json before PR 9.

    ``accept``: optional collection of exact file names to read — the
    elastic scheduler's epoch fence: only the spool files named by each
    scatter unit's completion record (the winning (epoch, holder) attempt)
    are trusted; a fenced-off zombie's late appends land in files this
    set never names."""
    import numpy as np
    from .readers import DocSpans
    group_dir = os.path.join(out_dir, _SPOOL_DIR, "group-{}".format(group))
    empty = np.zeros(0, dtype=np.int64)
    by_bucket = {b: {} for b in _buckets_of_group(group, nbuckets, ngroups)}
    if not os.path.isdir(group_dir):
        return {b: DocSpans(b"", empty, empty) for b in by_bucket}
    # Merge the group's spool files into ONE buffer (guarded reads:
    # transient EIO/ESTALE on the shared spool retries). Every writer
    # terminates every line, but a crashed writer may leave a torn tail —
    # reinsert the newline so file boundaries never fuse lines.
    datas = []
    for name in sorted(os.listdir(group_dir)):
        if accept is not None and name not in accept:
            continue
        data = rio.read_bytes(os.path.join(group_dir, name))
        if data and not data.endswith(b"\n"):
            data += b"\n"
        datas.append(data)
    blob = b"".join(datas)
    del datas
    if not blob:
        return {b: DocSpans(blob, empty, empty) for b in by_bucket}
    arr = np.frombuffer(blob, dtype=np.uint8)
    nl = np.flatnonzero(arr == 0x0A)
    if not len(nl):  # unreachable (files are newline-terminated above)
        return {b: DocSpans(blob, empty, empty) for b in by_bucket}
    line_starts = np.empty(len(nl), dtype=np.int64)
    line_starts[0] = 0
    line_starts[1:] = nl[:-1] + 1
    line_ends = nl.astype(np.int64)  # exclusive of the newline
    # Header lines start with '#'; documents were written as b" " + text.
    # Only an exact b"#B " prefix is a header (anything else starting '#'
    # is document text, as in the per-line parser this replaces).
    hdr_idx = np.flatnonzero(arr[line_starts] == 0x23)
    runs = []  # (bucket, block_key, first_doc_line, end_doc_line)
    for pos, h in enumerate(hdr_idx):
        s, e = int(line_starts[h]), int(line_ends[h])
        line = blob[s:e]
        bucket = None
        if line.startswith(b"#B "):
            hdr = line.split()
            if len(hdr) == 3:
                try:
                    bucket = int(hdr[2].decode())
                except ValueError:
                    bucket = None
        nxt = (int(hdr_idx[pos + 1]) if pos + 1 < len(hdr_idx)
               else len(line_starts))
        if bucket in by_bucket:
            runs.append((bucket, hdr[1], int(h) + 1, nxt))
    for bucket, block_key, lo, hi in runs:
        starts = line_starts[lo:hi] + 1  # skip the leading b" "
        ends = line_ends[lo:hi]
        keep = ends > starts  # empty documents are dropped, as before
        by_bucket[bucket].setdefault(block_key, []).append(
            (starts[keep], ends[keep]))
    out = {}
    for b, blocks in by_bucket.items():
        if not blocks:
            out[b] = DocSpans(blob, empty, empty)
            continue
        parts = [p for _, chunks in sorted(blocks.items()) for p in chunks]
        out[b] = DocSpans(blob,
                          np.concatenate([p[0] for p in parts]),
                          np.concatenate([p[1] for p in parts]))
    return out


class BertBucketProcessor:
    """Picklable per-bucket BERT pipeline stage: shuffle -> instances ->
    materialize -> shard sink. Pickles the HF tokenizer (fast tokenizers
    serialize to their JSON form); the TokenizerInfo tables and native
    engine are rebuilt lazily once per process."""

    def __init__(self, tokenizer, config, seed, out_dir, bin_size,
                 output_format, splitter_params=None, pack_seq_length=None,
                 pack_max_per_row=8):
        self.tokenizer = tokenizer
        self.config = config
        self.seed = seed
        self.out_dir = out_dir
        self.bin_size = bin_size
        self.output_format = output_format
        self.splitter_params = splitter_params  # picklable SplitterParams
        self.pack_seq_length = pack_seq_length  # offline FFD sink budget
        self.pack_max_per_row = pack_max_per_row
        self._tok_info = None

    def __getstate__(self):
        state = self.__dict__.copy()
        state["_tok_info"] = None  # rebuilt per process
        return state

    def __setstate__(self, state):
        self.__dict__.update(state)

    @property
    def tok_info(self):
        if self._tok_info is None:
            self._tok_info = TokenizerInfo(self.tokenizer)
        return self._tok_info

    def fingerprint(self):
        """Digest of everything that shapes this processor's output bytes,
        for the resume manifest: resuming with a different vocab, seed,
        bin width, masking config or sink format would silently mix shards
        from two incompatible runs (ADVICE round 3)."""
        # The digest hashes the id->token table the pipeline actually
        # tokenizes with (TokenizerInfo's construction-time snapshot), so
        # ANY vocab difference — including a same-size in-place token swap
        # — changes it. Memoized on the TokenizerInfo, which is rebuilt
        # per process/run, so the cache can never outlive the snapshot it
        # hashed (round-4 VERDICT: the old size-keyed cache could).
        vocab = self.tok_info.vocab_digest
        # schema_version leaves the digest when 1 so pre-upgrade v1 runs
        # (byte-identical output) stay resumable across the field's
        # introduction; v2 runs genuinely produce different bytes and
        # must fingerprint differently.
        import dataclasses
        cfg = dataclasses.asdict(self.config)
        if cfg.get("schema_version") == 1:
            del cfg["schema_version"]
        cfg = json.dumps(cfg, sort_keys=True, default=str)
        fields = [type(self).__name__, vocab, cfg, self.seed, self.bin_size,
                  self.output_format, splitter_digest(self.splitter_params),
                  "codec=" + binning_mod.DEFAULT_PARQUET_COMPRESSION]
        if (self.config.schema_version == 2
                and self.output_format == "parquet"):
            # The id-columnar (v2/packed) shards use the tuned parquet
            # layout (binning.SINK_PROFILE_V2): different bytes, so a v2
            # resume across the layout change must refuse — a deliberate
            # one-time fingerprint bump. v1 shards keep the legacy layout
            # byte-for-byte (golden-spool pins), so v1 digests — and
            # pre-upgrade crashed v1 runs — are untouched.
            fields.append("v2sink=" + binning_mod.SINK_PROFILE_V2)
        if self.pack_seq_length is not None:
            # Appended only when packing so every pre-existing (unpacked)
            # run's digest — and its resumability — is untouched.
            fields.append("pack={}x{}".format(self.pack_seq_length,
                                              self.pack_max_per_row))
        return processor_fingerprint(*fields)

    def prepare(self, texts, bucket):
        """Compute phase of the two-phase sink protocol: shuffle ->
        instances -> masking -> columns, all producer-side; returns a
        zero-argument *deferred publish closure* that performs only the
        durable write (sink.ShardWriter executes it on the writer
        thread, pipelined against the next bucket's compute).
        ``prepare(texts, b)()`` is exactly the old inline behavior."""
        config, seed = self.config, self.seed
        g = lrng.sample_rng(seed, 0x9A1A, bucket)
        lrng.shuffle(g, texts)
        if self.output_format == "txt":
            batch = instances_from_texts(
                texts, self.tok_info, config, seed, bucket,
                splitter_params=self.splitter_params)
            rows = materialize_rows(batch, config, self.tok_info, seed,
                                    (0x3A5C, bucket))
            return lambda: _write_txt_shard(rows, self.out_dir, bucket,
                                            config.masking, self.bin_size,
                                            config.max_seq_length)
        batch = None
        if config.masking:
            # Fused-masked rung: split + WordPiece + NSP + shuffle + the
            # Philox masking replay in ONE native call (no padded matrix
            # ever exists in Python). None = out of the replay contract;
            # fall through to the staged ladder.
            batch = masked_instances_from_texts(
                texts, self.tok_info, config, seed, bucket, (0x3A5C, bucket),
                splitter_params=self.splitter_params)
        if batch is None:
            batch = instances_from_texts(
                texts, self.tok_info, config, seed, bucket,
                splitter_params=self.splitter_params)
        columns, n = materialize_columns(batch, config, self.tok_info, seed,
                                         (0x3A5C, bucket))
        if obs.enabled() and "num_tokens" in columns:
            obs.inc("preprocess_tokens_total",
                    int(sum(int(t) for t in columns["num_tokens"])))
        out_dir, bin_size = self.out_dir, self.bin_size
        pack_seq_length = self.pack_seq_length
        pack_max_per_row = self.pack_max_per_row
        pack_special_ids = ((self.tok_info.cls_id, self.tok_info.sep_id)
                            if pack_seq_length is not None else None)

        def publish():
            return binning_mod.write_shard_columns(
                columns, n, out_dir, bucket, masking=config.masking,
                bin_size=bin_size,
                target_seq_length=config.max_seq_length,
                pack_seq_length=pack_seq_length,
                pack_max_per_row=pack_max_per_row,
                pack_special_ids=pack_special_ids)

        return publish

    def __call__(self, texts, bucket):
        return self.prepare(texts, bucket)()


def _write_txt_shard(rows, out_dir, part_id, masking, bin_size,
                     target_seq_length):
    """Human-readable debug sink (ref: pretrain.py:501-532 _save_txt)."""
    from ..utils.fs import deserialize_np_array
    os.makedirs(out_dir, exist_ok=True)

    def fmt(r):
        if masking:
            return ("is_random_next: {} - [CLS] {} [SEP] {} [SEP] - "
                    "masked_lm_positions: {} - masked_lm_labels: {} - {}".format(
                        r["is_random_next"], r["A"], r["B"],
                        # Human-readable debug sink only (never the
                        # parquet path). -- lddl: disable=python-hot-loop
                        deserialize_np_array(r["masked_lm_positions"]).tolist(),
                        r["masked_lm_labels"], r["num_tokens"]))
        return "is_random_next: {} - [CLS] {} [SEP] {} [SEP] - {}".format(
            r["is_random_next"], r["A"], r["B"], r["num_tokens"])

    written = {}
    if bin_size is None:
        path = os.path.join(out_dir, "{}.txt".format(part_id))
        rio.atomic_write(path, "".join(fmt(r) + "\n" for r in rows))
        written[path] = len(rows)
        return written
    nbins = binning_mod.num_bins(target_seq_length, bin_size)
    by_bin = {}
    for r in rows:
        b = binning_mod.bin_id_of_num_tokens(r["num_tokens"], bin_size, nbins)
        by_bin.setdefault(b, []).append(r)
    for b, bin_rows in sorted(by_bin.items()):
        path = os.path.join(out_dir, "{}.txt_{}".format(part_id, b))
        rio.atomic_write(path, "".join(fmt(r) + "\n" for r in bin_rows))
        written[path] = len(bin_rows)
    return written


# Worker-process globals for the intra-host pool (set by _pool_init).
_POOL = {}


def _pin_worker_core(spec):
    """Optional worker->core pinning (LDDL_TPU_PIN_CORES=1): each pool
    worker claims the next slot of ``native threads`` contiguous cores
    from the process affinity set, so the in-kernel thread pool of one
    worker never migrates onto another worker's cores. Slot assignment
    goes through a flock-appended file under out_dir (spawned workers
    share no other state); failure of any step leaves affinity alone —
    pinning is an optimization, never a correctness gate."""
    if os.environ.get("LDDL_TPU_PIN_CORES") != "1":
        return
    try:
        cores = sorted(os.sched_getaffinity(0))
        if len(cores) < 2:
            return
        import fcntl
        from .. import native
        path = os.path.join(spec.get("out_dir") or ".", ".pin_slots")
        # Coordination scratch, not shard data: a torn line at worst
        # skews one slot assignment, and pinning is best-effort anyway.
        with open(path, "a+") as f:  # lddl: disable=atomic-publish
            fcntl.flock(f, fcntl.LOCK_EX)
            f.seek(0)
            slot = len(f.read().splitlines())
            f.write("{}\n".format(os.getpid()))
            f.flush()
        width = native.resolve_threads()
        lo = (slot * width) % len(cores)
        os.sched_setaffinity(
            0, {cores[(lo + i) % len(cores)] for i in range(width)})
    except Exception:  # lddl: disable=swallowed-error (best-effort)
        pass


def _pool_init(process_bucket, spec):
    _POOL["process_bucket"] = process_bucket
    _POOL["spec"] = spec
    _pin_worker_core(spec)


def _record_bucket_written(written, bucket):
    """Per-bin sample accounting for one processed bucket: counter per
    bin (parsed off the part-file suffix — the one place bin identity
    already exists) + a histogram of bucket sizes (skew visibility)."""
    if not obs.enabled() or not isinstance(written, dict):
        return
    from ..utils.fs import get_bin_id_of_path
    total = 0
    for path, n in written.items():
        b = get_bin_id_of_path(path)
        obs.inc("preprocess_shards_total", bin="none" if b is None else b)
        obs.inc("preprocess_samples_total", n,
                bin="none" if b is None else b)
        total += n
    obs.observe("preprocess_bucket_samples", total)


def _run_block_bucket(spec, process_bucket, bucket, fence=None, writer=None):
    """No-global-shuffle unit: bucket == block; re-read the block directly
    (texts never cross the process boundary). ``fence`` (elastic mode):
    checked after reading and before writing — a holder whose lease was
    stolen self-terminates instead of publishing from possibly-stale
    state. ``writer`` (static serial path): the unit's durable write is
    deferred onto the shard-writer thread and the unit completes (and
    journals) when it drains — see sink.ShardWriter."""
    input_files = discover_source_files(spec["corpus_paths"])
    blocks = plan_blocks(input_files, spec["num_blocks"])
    texts = [text for _, text in read_documents(
        blocks[bucket], sample_ratio=spec["sample_ratio"],
        base_seed=spec["seed"])]
    if spec.get("clean_first"):
        _clean_bucket_outputs(spec["out_dir"], bucket)
    _check_fence(fence, bucket)
    prepare = getattr(process_bucket, "prepare", None)
    if writer is not None and prepare is not None:
        with obs.span("preprocess.process_block", bucket=bucket):
            publish = prepare(texts, bucket)
        writer.submit(bucket, _publish_task(publish, bucket), fence=fence)
        writer.end_unit(bucket)
        return sink_mod.DeferredUnit(bucket)
    with obs.span("preprocess.process_block", bucket=bucket):
        written = process_bucket(texts, bucket)
    _record_bucket_written(written, bucket)
    return written


def _check_fence(fence, unit):
    """Raise LeaseLost when an elastic unit's lease was stolen mid-run.
    Placed between a unit's read step and its writes: once a steal has
    happened, anything read afterwards may be concurrently swept or
    finalized away, so the loser must never publish bytes derived from
    it (the claim loop converts the raise into a fence-reject)."""
    if fence is not None and not fence():
        from ..resilience.leases import LeaseLost
        raise LeaseLost(
            "unit {} was stolen mid-run; self-terminating".format(unit))


def _pool_run_block_bucket(bucket):
    return _run_block_bucket(_POOL["spec"], _POOL["process_bucket"], bucket)


def _clean_bucket_outputs(out_dir, bucket):
    """Remove partial part/txt files a crashed attempt may have left for
    this bucket (resume-safety; exact-prefix globs cannot cross buckets)."""
    import glob
    for pattern in ("part.{}.parquet*".format(bucket),
                    "{}.txt*".format(bucket)):
        for path in sorted(glob.glob(os.path.join(out_dir, pattern))):
            os.remove(path)


def _publish_task(publish, bucket):
    """Wrap a processor's deferred publish closure with the per-bucket
    sample accounting (runs on the writer thread; obs is thread-safe)."""
    def task():
        written = publish()
        _record_bucket_written(written, bucket)
        return written
    return task


def _run_group(spec, process_bucket, group, fence=None, writer=None):
    """Gather unit: read one coarse spool group, process each fine bucket.
    ``fence`` (elastic mode) is checked after the spool read and before
    every bucket's compute — and re-checked by the shard writer
    immediately before every deferred publish (see `_check_fence` and
    sink.ShardWriter).

    The durable sink runs asynchronously whenever the processor exposes
    the two-phase ``prepare`` protocol: with a ``writer`` passed in (the
    static serial path) writes are deferred ACROSS units and the call
    returns ``sink.DeferredUnit``; otherwise (pool workers, the elastic
    claim loop) an own writer pipelines the buckets WITHIN the unit and
    drains before returning, so the unit's result — and any journal
    record derived from it — still strictly follows its bytes."""
    with obs.span("preprocess.gather_group", group=group):
        texts_by_bucket = _read_group_texts(spec["out_dir"], group,
                                            spec["nbuckets"], spec["ngroups"],
                                            accept=spec.get("spool_accept"))
        prepare = getattr(process_bucket, "prepare", None)
        if prepare is None:
            # Processors without the two-phase protocol (custom test
            # callables): the historical inline path, unchanged.
            written = {}
            for bucket in sorted(texts_by_bucket):
                if spec.get("clean_first"):
                    _clean_bucket_outputs(spec["out_dir"], bucket)
                _check_fence(fence, group)
                bucket_written = process_bucket(texts_by_bucket[bucket],
                                                bucket)
                _record_bucket_written(bucket_written, bucket)
                written.update(bucket_written)
            return written
        own = writer is None
        w = sink_mod.ShardWriter() if own else writer
        try:
            for bucket in sorted(texts_by_bucket):
                if spec.get("clean_first"):
                    _clean_bucket_outputs(spec["out_dir"], bucket)
                _check_fence(fence, group)
                publish = prepare(texts_by_bucket[bucket], bucket)
                w.submit(group, _publish_task(publish, bucket), fence=fence)
            w.end_unit(group)
            if not own:
                return sink_mod.DeferredUnit(group)
            done = w.drain()
        finally:
            if own:
                w.close()
        _, written, exc = done[0]
        if exc is not None:
            raise exc  # incl. LeaseLost: the claim loop fences the unit
    return written


def _pool_run_group(group):
    return _run_group(_POOL["spec"], _POOL["process_bucket"], group)


def _pool_scatter_block(block_id):
    spec = _POOL["spec"]
    input_files = discover_source_files(spec["corpus_paths"])
    blocks = plan_blocks(input_files, spec["num_blocks"])
    _spool_one_block(blocks[block_id], spec["out_dir"], spec["seed"],
                     spec["sample_ratio"], len(blocks), spec["ngroups"],
                     "w{}-{}.txt".format(spec["rank"], os.getpid()))
    return block_id


def run_sharded_pipeline(
    corpus_paths,
    out_dir,
    process_bucket,
    num_blocks=64,
    sample_ratio=0.9,
    seed=12345,
    global_shuffle=True,
    comm=None,
    log=None,
    num_workers=1,
    spool_groups=None,
    resume=False,
    progress_interval=5.0,
    elastic=False,
    lease_ttl=30.0,
    holder_id=None,
    scatter_units=None,
    emit_manifest=True,
):
    """Generic SPMD scaffolding shared by every preprocessor: dirty-dir
    guard -> block planning -> (optional) scatter shuffle -> strided bucket
    processing via ``process_bucket(texts, bucket) -> {path: n}`` ->
    cleanup + reduced totals. ``spool_groups`` overrides the coarse radix
    width (default min(nblocks, max(64, nblocks // 8))).

    ``elastic=True`` replaces the static rank->unit schedule with the
    lease-based work-stealing claim loop (:mod:`.steal`): launch the SAME
    invocation on N independent host processes sharing ``out_dir`` (no
    jax.distributed, no barriers — hosts may join late, die mid-unit, and
    be reclaimed by the survivors; the last host out runs the
    lease-guarded finalization). ``lease_ttl`` is the steal horizon in
    seconds (a dead host's units are reclaimed after at most one TTL),
    ``holder_id`` names this host in lease files (default: auto
    hostname-pid-nonce), ``scatter_units`` overrides the scatter
    work-unit count (block slices; default min(blocks, max(16,
    blocks/16))). Output bytes are identical to a static single-host run
    of the same plan — leases decide only WHO runs a unit, never what it
    produces.

    Fault model: a unit (spool group / block) whose processing raises is
    recorded and skipped; a dead pool worker rebuilds the pool and retries.
    Completed units are journaled to ``<out>/_done/group-<g>.json``, so a
    crashed or failed run re-invoked with ``resume=True`` (same arguments!)
    redoes only unfinished units; the scatter spool is reused when its
    completion marker exists, else rebuilt from scratch (appends from a
    half-dead scatter are not separable). Any unit failures raise
    RuntimeError at the end — after all healthy units finished, so the
    retry cost of the next resume is minimal. (Reference precedent for
    resume: common_crawl.py:251-260 --continue-process; the reference's
    Dask preprocess itself has no resume.)

    Returns {path: num_rows} for the shards written by THIS rank (ranks
    own disjoint buckets; the balancer performs the global census).
    SPMD: call on every host with the same arguments; hosts split the work
    by ``comm`` rank and meet at barriers.
    """
    comm = comm or LocalCommunicator()
    log = log or (lambda msg: None)
    if elastic and comm.world_size > 1:
        raise ValueError(
            "elastic mode replaces the static multihost schedule; launch "
            "independent processes sharing the output dir instead of "
            "initializing jax.distributed (--multihost)")
    # Top-level stage span (lint-enforced: tests/test_observability.py);
    # scatter/gather phases and per-unit worker spans nest under it in
    # the per-process trace files.
    with obs.span("preprocess.run", rank=comm.rank,
                  world_size=comm.world_size, elastic=bool(elastic)):
        try:
            return _run_pipeline_body(
                corpus_paths, out_dir, process_bucket, num_blocks,
                sample_ratio, seed, global_shuffle, comm, log, num_workers,
                spool_groups, resume, progress_interval, elastic,
                lease_ttl, holder_id, scatter_units, emit_manifest)
        finally:
            obs.flush()


def _run_pipeline_body(corpus_paths, out_dir, process_bucket, num_blocks,
                       sample_ratio, seed, global_shuffle, comm, log,
                       num_workers, spool_groups, resume, progress_interval,
                       elastic=False, lease_ttl=30.0, holder_id=None,
                       scatter_units=None, emit_manifest=True):
    # Refuse a dirty output dir (unless resuming): stale part files from a
    # previous run with a different block count would silently survive next
    # to fresh ones and duplicate data downstream. Elastic hosts joining a
    # run already in progress are the exception: the ledger manifest below
    # proves the directory belongs to THIS plan (a fingerprint mismatch
    # still refuses loudly).
    manifest_path = os.path.join(out_dir, _LEDGER_DIR, "manifest.json")
    joining = elastic and os.path.exists(manifest_path)
    if elastic and not joining and os.path.isdir(out_dir):
        # Simultaneous elastic starts race the first host's manifest
        # publish: its _done/_leases dirs can exist for a moment before
        # manifest.json lands. Wait briefly before judging the directory
        # dirty — a genuinely stale dir still refuses, just 10s later.
        from ..resilience.leases import LEASE_DIR
        if any(os.path.isdir(os.path.join(out_dir, d))
               for d in (_LEDGER_DIR, LEASE_DIR)):
            deadline = time.monotonic() + 10.0
            while time.monotonic() < deadline \
                    and not os.path.exists(manifest_path):
                time.sleep(0.1)
            joining = os.path.exists(manifest_path)
    if os.path.isdir(out_dir) and not resume and not joining:
        stale = [
            n for n in sorted(os.listdir(out_dir))
            if ".parquet" in n or (".txt" in n and not n.startswith("."))
            or n in (_SPOOL_DIR, _LEDGER_DIR)
        ]
        if stale:
            raise ValueError(
                "output dir {} already contains {} shard files (e.g. {}); "
                "remove them, choose a fresh directory, or pass "
                "resume=True/--resume to continue that run".format(
                    out_dir, len(stale), stale[0]))
    # No rank may start writing before every rank has passed the guard.
    comm.barrier()

    t0 = time.time()  # lddl: disable=wall-clock (log-only run rates)
    input_files = discover_source_files(corpus_paths)
    blocks = plan_blocks(input_files, num_blocks)
    nbuckets = len(blocks)
    if spool_groups is not None and int(spool_groups) < 1:
        raise ValueError(
            "spool_groups must be >= 1, got {}".format(spool_groups))
    ngroups = _num_spool_groups(nbuckets) if spool_groups is None else min(
        int(spool_groups), nbuckets)
    log("{} input files -> {} blocks ({} spool groups)".format(
        len(input_files), len(blocks), ngroups))
    proc_fp = getattr(process_bucket, "fingerprint", None)
    fingerprint = {
        "num_blocks": nbuckets, "spool_groups": ngroups, "seed": seed,
        "sample_ratio": sample_ratio, "global_shuffle": global_shuffle,
        # Unit identity is not enough: the corpus and the processor's
        # own parameters (vocab, binning, masking, sink format) also
        # define what a ledgered unit's bytes MEAN (ADVICE round 3).
        # Paths absolutize so a resume launched from a different cwd
        # (relative vs absolute spelling) is not spuriously refused.
        "corpus_paths": json.dumps(
            _canonical_paths(corpus_paths), sort_keys=True, default=str),
        "processor": proc_fp() if callable(proc_fp) else None,
    }
    n_scatter_units = None
    if elastic:
        # The elastic unit plan (scatter slices, per-slice records, fenced
        # spool file names) is incompatible with the static layout and
        # with a different slice count — both are part of unit identity,
        # so mixing them across a resume must refuse. The default is the
        # ADAPTIVE plan (probe slices + a journaled wall-informed split;
        # steal._ensure_plan): its sentinel string deliberately mismatches
        # any fixed integer count, so adaptive↔fixed resumes refuse too.
        # An explicit --scatter-units keeps the classic fixed stride.
        n_scatter_units = ("adaptive-v1" if scatter_units is None
                           else max(1, min(int(scatter_units), nbuckets)))
        fingerprint["elastic"] = True
        fingerprint["scatter_units"] = n_scatter_units
    # An elastic host joining an in-progress run verifies against the
    # existing manifest exactly like a resume would (hosts start at
    # different times by design; a misconfigured straggler must refuse,
    # not corrupt).
    _check_resume_manifest(out_dir, fingerprint,
                           resume or (elastic and joining), comm.rank)
    comm.barrier()  # manifest visible before anyone journals against it

    # Intra-host fan-out (the reference runs ~128 MPI ranks per node,
    # slurm_example.sub:72; our equivalent is one Communicator rank per
    # host times a local spawn pool). Workers re-read inputs themselves —
    # only unit ids cross the process boundary.
    # Work units: coarse spool groups under global shuffle, blocks without.
    all_units = list(range(comm.rank, ngroups if global_shuffle else nbuckets,
                           comm.world_size))
    workers = max(1, int(num_workers or 1))
    # Size the in-kernel thread pool so workers x native threads never
    # oversubscribes the usable cores; spawn children inherit this env and
    # resolve their own budget from it (native.resolve_threads). The
    # budget reserves the loader shard-I/O threads (prefetch/decode-ahead,
    # loader/shardcache.py) a colocated trainer's streams run. setdefault
    # only — an operator-set LDDL_TPU_NATIVE_THREADS always wins.
    from ..utils.cpus import loader_io_threads, pool_cpu_budget
    os.environ.setdefault(
        "LDDL_TPU_NATIVE_THREADS",
        str(max(1, pool_cpu_budget(reserve=loader_io_threads()) // workers)))
    spec = {
        "global_shuffle": global_shuffle,
        "out_dir": out_dir,
        "corpus_paths": corpus_paths,
        "num_blocks": num_blocks,
        "sample_ratio": sample_ratio,
        "seed": seed,
        "nbuckets": nbuckets,
        "ngroups": ngroups,
        "rank": comm.rank,
    }

    if elastic:
        spec["scatter_units"] = n_scatter_units
        spec["adaptive_scatter"] = n_scatter_units == "adaptive-v1"
        spec["emit_manifest"] = bool(emit_manifest)
        from . import steal
        return steal.run_elastic_pipeline(
            spec, process_bucket, log,
            holder_id=holder_id, lease_ttl=lease_ttl, workers=workers,
            progress_interval=progress_interval, t0=t0)

    def pool_factory_for(n_units):
        if workers <= 1 or n_units <= 1:
            return None

        def factory(max_workers=None):
            import concurrent.futures
            import multiprocessing
            return concurrent.futures.ProcessPoolExecutor(
                max_workers=max_workers or min(workers, n_units),
                mp_context=multiprocessing.get_context("spawn"),
                initializer=_pool_init,
                initargs=(process_bucket, spec))

        return factory

    # Resume bookkeeping: previously completed units (spool groups or, in
    # the no-shuffle case, blocks) are loaded from the ledger and skipped.
    written = {}
    my_units = []
    if resume:
        spec["clean_first"] = True  # wipe partial part files per redone unit
        for u in all_units:
            prior = _ledger_read(out_dir, u)
            if prior is None:
                my_units.append(u)
            else:
                written.update(prior)
        if len(my_units) < len(all_units):
            log("resume: {}/{} units already complete".format(
                len(all_units) - len(my_units), len(all_units)))
    else:
        my_units = all_units

    if global_shuffle:
        marker = os.path.join(out_dir, _SPOOL_DIR, _SCATTER_MARKER)
        scatter_ok = resume and os.path.exists(marker)
        # All ranks must agree on redoing the scatter (a lagging rank's
        # blocks may be missing even if THIS rank's units all completed).
        need_scatter = bool(comm.allreduce_sum(
            [int(bool(my_units) and not scatter_ok)])[0])
        if need_scatter:
            if comm.rank == 0 and os.path.isdir(
                    os.path.join(out_dir, _SPOOL_DIR)):
                # Partial spools are poison (appends are not separable).
                shutil.rmtree(os.path.join(out_dir, _SPOOL_DIR))
                log("resume: incomplete scatter spool wiped, redoing")
            comm.barrier()
            my_blocks = list(range(comm.rank, len(blocks), comm.world_size))
            factory = pool_factory_for(len(my_blocks))
            serial_name = "w{}-0.txt".format(comm.rank)
            # retry_deaths=False: a dead scatter worker leaves partial
            # appends that a re-run would duplicate; the only safe redo is
            # wiping the (unmarked) spool, which the next resume does.
            with obs.span("preprocess.scatter", rank=comm.rank,
                          blocks=len(my_blocks)):
                _, scatter_fail = _run_units(
                    _pool_scatter_block if factory else
                    (lambda b: _spool_one_block(
                        blocks[b], out_dir, seed, sample_ratio, nbuckets,
                        ngroups, serial_name)),
                    my_blocks, factory, log,
                    "rank {} scatter".format(comm.rank), retry_deaths=False,
                    progress_interval=progress_interval)
            n_failed = int(comm.allreduce_sum([len(scatter_fail)])[0])
            if n_failed:
                # A lost block poisons every bucket; the (incomplete,
                # unmarked) spool is redone from scratch on the next resume.
                raise RuntimeError(
                    "scatter failed for {} block(s) (this rank: {}); "
                    "re-run with resume to redo the scatter".format(
                        n_failed, sorted(scatter_fail)))
            comm.barrier()
            if comm.rank == 0:
                os.makedirs(os.path.dirname(marker), exist_ok=True)
                # Durable marker: a crash must not durably publish the
                # marker without the spool bytes it vouches for.
                rio.atomic_write(marker, "ok\n")
            comm.barrier()

        factory = pool_factory_for(len(my_units))
        # Cross-unit async sink (serial path only): one shard-writer
        # thread pipelines unit N's parquet encode + fsync + publish
        # against unit N+1's spool read / tokenize / mask. Pool workers
        # instead pipeline within each unit via an own writer inside
        # _run_group (results must drain before a future resolves, or
        # the parent would journal bytes still in flight).
        writer = (sink_mod.ShardWriter()
                  if factory is None and hasattr(process_bucket, "prepare")
                  else None)
        try:
            with obs.span("preprocess.gather", rank=comm.rank,
                          groups=len(my_units)):
                results, failures = _run_units(
                    _pool_run_group if factory else
                    (lambda g: _run_group(spec, process_bucket, g,
                                          writer=writer)),
                    my_units, factory, log,
                    "rank {} gather".format(comm.rank),
                    progress_interval=progress_interval,
                    on_result=lambda u, res: _ledger_write(out_dir, u, res),
                    writer=writer)
        finally:
            if writer is not None:
                writer.close()
    else:
        factory = pool_factory_for(len(my_units))
        writer = (sink_mod.ShardWriter()
                  if factory is None and hasattr(process_bucket, "prepare")
                  else None)
        try:
            results, failures = _run_units(
                _pool_run_block_bucket if factory else
                (lambda b: _run_block_bucket(spec, process_bucket, b,
                                             writer=writer)),
                my_units, factory, log, "rank {} process".format(comm.rank),
                progress_interval=progress_interval,
                on_result=lambda u, res: _ledger_write(out_dir, u, res),
                writer=writer)
        finally:
            if writer is not None:
                writer.close()

    for res in results.values():
        written.update(res)

    n_failed = int(comm.allreduce_sum([len(failures)])[0])
    comm.barrier()

    if n_failed:
        raise RuntimeError(
            "preprocess failed for {} unit(s) (this rank: {}); completed "
            "units are journaled — re-run with resume=True/--resume to "
            "redo only the failures".format(
                n_failed, failures or "none on this rank"))

    # Integrity manifest (per-shard byte length + CRC32) for the loader's
    # startup verification. Rank-strided like the census; no-op for txt
    # output or under LDDL_TPU_MANIFEST=0. The ingest service passes
    # emit_manifest=False: its work-dir part files are consumed by the
    # delta balancer immediately, and the published directories get
    # their manifests (with generation meta) from the ingest publisher.
    if emit_manifest:
        build_manifest(out_dir, comm=comm, log=log)

    if comm.rank == 0:
        if global_shuffle:
            shutil.rmtree(os.path.join(out_dir, _SPOOL_DIR),
                          ignore_errors=True)
        shutil.rmtree(os.path.join(out_dir, _LEDGER_DIR), ignore_errors=True)
        _sweep_tmp_debris(out_dir)
    totals = comm.allreduce_sum([len(written), sum(written.values())])
    elapsed = time.time() - t0  # lddl: disable=wall-clock (log-only rates)
    if obs.enabled():
        # Rates over the whole run (docs/sec comes out of the scatter
        # counters; sample/sec from the reduced census) — the summary's
        # throughput headline for this stage.
        obs.set_gauge("preprocess_samples_per_second",
                      int(totals[1]) / max(elapsed, 1e-9))
        docs = obs.registry().counter("preprocess_docs_total").total()
        if docs:
            obs.set_gauge("preprocess_docs_per_second",
                          docs / max(elapsed, 1e-9))
    log("preprocess done in {:.1f}s, {} shards, {} samples".format(
        elapsed, int(totals[0]), int(totals[1])))
    return written


def _sweep_tmp_debris(out_dir):
    """Sweep atomic-write temp files leaked by hard-killed writers: a
    worker terminated mid-write (its own SIGKILL, or the pool tearing
    down siblings after a break) never runs the unlink in
    write_table_atomic's finally, and if its unit was completed by a
    retry within the same run the ledger marks it done — so no resume
    ever redoes (and cleans) that bucket. Called only after every live
    write has published (post-barrier on the static path, inside the
    finalize lease on the elastic path); any remaining ``*.tmp.*`` is
    debris by construction."""
    import glob
    for stale in sorted(glob.glob(os.path.join(out_dir, "*.tmp.*"))):
        try:
            os.remove(stale)
            obs.inc("preprocess_stale_tmp_cleaned_total")
        # Best-effort sweep of dead writers' debris: a vanished or
        # unremovable temp file must not fail a completed run.
        except OSError:  # lddl: disable=swallowed-error
            pass


def train_splitter_params_from_corpus(corpus_paths, sample_bytes=1_500_000):
    """Deterministic corpus sample (file-discovery order, first documents
    up to ``sample_bytes``) -> punkt-trained SplitterParams. Every rank
    computes the identical sample, so no coordination is needed."""
    from .sentences import train_splitter_params
    from .readers import split_id_text
    texts = []
    total = 0
    for path in discover_source_files(corpus_paths):
        with open(path, encoding="utf-8", errors="replace") as f:
            for line in f:
                _, text = split_id_text(line.rstrip("\n"))
                if text.strip():
                    texts.append(text)
                    total += len(text)
                if total >= sample_bytes:
                    break
        if total >= sample_bytes:
            break
    if not texts:
        raise ValueError("splitter='learned': corpus sample is empty")
    return train_splitter_params(texts)


def run_bert_preprocess(
    corpus_paths,
    out_dir,
    tokenizer,
    config=None,
    num_blocks=64,
    sample_ratio=0.9,
    seed=12345,
    bin_size=None,
    global_shuffle=True,
    output_format="parquet",
    comm=None,
    log=None,
    num_workers=1,
    spool_groups=None,
    resume=False,
    progress_interval=5.0,
    elastic=False,
    lease_ttl=30.0,
    holder_id=None,
    scatter_units=None,
    emit_manifest=True,
    pack_seq_length=None,
    pack_max_per_row=8,
):
    """Run the full BERT preprocessing pipeline (see run_sharded_pipeline
    for the SPMD execution contract). ``num_workers`` > 1 fans the bucket
    work out over a local SPAWN process pool per host — when calling this
    from a script (rather than the CLI), guard the call with
    ``if __name__ == "__main__":`` or spawn re-executes your module
    (standard multiprocessing semantics). ``resume=True`` continues a
    crashed/failed run from its unit ledger. ``elastic=True`` runs the
    lease-based work-stealing schedule instead of the static one (see
    run_sharded_pipeline).

    ``pack_seq_length`` switches the shard sink to OFFLINE sequence
    packing (preprocess/packing.py): each bucket's instances are
    first-fit-decreasing-packed into fixed-``pack_seq_length`` rows of at
    most ``pack_max_per_row`` samples, and the emitted schema-v2 rows are
    already-packed training rows the loader streams zero-copy. Mutually
    exclusive with ``bin_size`` (packing subsumes binning); requires
    ``schema_version=2`` and parquet output, and the budget must hold the
    longest instance (``pack_seq_length >= config.max_seq_length``)."""
    config = config or BertPretrainConfig()
    if output_format not in ("parquet", "txt"):
        raise ValueError("output_format must be parquet|txt")
    if bin_size is not None:
        binning_mod.num_bins(config.max_seq_length, bin_size)  # validate
    if pack_seq_length is not None:
        if bin_size is not None:
            raise ValueError("pack_seq_length and bin_size are exclusive "
                             "(packing subsumes binning)")
        if output_format != "parquet":
            raise ValueError("offline packing requires parquet output")
        if config.schema_version != 2:
            raise ValueError("offline packing requires schema_version=2 "
                             "(packed rows are id-columnar)")
        if int(pack_seq_length) < config.max_seq_length:
            raise ValueError(
                "pack_seq_length {} cannot hold instances of up to "
                "max_seq_length {} tokens".format(pack_seq_length,
                                                  config.max_seq_length))
        if not (1 <= int(pack_max_per_row)):
            raise ValueError("pack_max_per_row must be >= 1")
        if int(pack_seq_length) >= 1 << 16:
            raise ValueError("pack_seq_length must fit uint16 row totals")
    splitter_params = (train_splitter_params_from_corpus(corpus_paths)
                       if config.splitter == "learned" else None)

    return run_sharded_pipeline(
        corpus_paths,
        out_dir,
        BertBucketProcessor(tokenizer, config, seed, out_dir, bin_size,
                            output_format,
                            splitter_params=splitter_params,
                            pack_seq_length=pack_seq_length,
                            pack_max_per_row=pack_max_per_row),
        num_blocks=num_blocks,
        sample_ratio=sample_ratio,
        seed=seed,
        global_shuffle=global_shuffle,
        comm=comm,
        log=log,
        num_workers=num_workers,
        spool_groups=spool_groups,
        resume=resume,
        progress_interval=progress_interval,
        elastic=elastic,
        lease_ttl=lease_ttl,
        holder_id=holder_id,
        scatter_units=scatter_units,
        emit_manifest=emit_manifest,
    )
