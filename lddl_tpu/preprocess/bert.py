"""BERT pretraining sample construction (NSP pairs + MLM masking).

Reference parity: lddl/dask/bert/pretrain.py:49-441 — an independent
reimplementation of Google BERT's ``create_pretraining_data`` distribution
(target-length sampling with ``short_seq_prob``, sentence-chunk
accumulation, random A/B split point, 50% random-next with segment
put-back, random front/back truncation, 80/10/10 masking).

TPU-first restructuring vs the reference: the whole pipeline is *token-id
based* — sentences tokenize straight to ids, pair creation concatenates int
lists, and static masking runs as ONE batched kernel per bucket
(lddl_tpu.ops.masking: numpy engine or jit'd JAX on TPU) instead of a
Python loop per row. Token strings are materialized only at the very end
for the parquet columns.

Output row schema (must match the reference sink,
lddl/dask/bert/pretrain.py:451-471):
    A: str                      whitespace-joined WordPiece tokens
    B: str
    is_random_next: bool
    num_tokens: int             len(A) + len(B) + 3 specials
    masked_lm_positions: bytes  (static masking) serialized np array of
                                positions into [CLS] A [SEP] B [SEP]
    masked_lm_labels: str       (static masking) original tokens, joined
"""

import dataclasses

import numpy as np

from ..ops.masking import mask_batch_numpy, make_jax_masker, plan_num_to_predict
from ..ops.packing import pad_to_bucket
from ..utils.fs import serialize_np_array
from ..utils import rng as lrng
from .sentences import split_sentences


@dataclasses.dataclass
class BertPretrainConfig:
    max_seq_length: int = 128
    short_seq_prob: float = 0.1
    masking: bool = False
    masked_lm_ratio: float = 0.15
    max_predictions_per_seq: int = None  # default: ceil(ratio * max_seq_len)
    whole_word_masking: bool = False
    duplicate_factor: int = 5
    engine: str = "numpy"  # masking kernel: "numpy" | "jax"
    # Sentence-split + tokenize engine: "native" = the C++ one-pass kernel
    # (lddl_tpu.native), "hf" = Python splitter + HF fast tokenizer,
    # "auto" = native when buildable + tokenizer-compatible, else hf.
    tokenizer_engine: str = "auto"

    def __post_init__(self):
        if self.max_seq_length < 8:
            raise ValueError("max_seq_length too small")
        if self.engine not in ("numpy", "jax"):
            raise ValueError("engine must be numpy|jax")
        if self.tokenizer_engine not in ("auto", "hf", "native"):
            raise ValueError("tokenizer_engine must be auto|hf|native")
        if self.max_predictions_per_seq is None:
            self.max_predictions_per_seq = int(
                np.ceil(self.masked_lm_ratio * self.max_seq_length))


class TokenizerInfo:
    """Pre-extracted tokenizer tables the id-based pipeline needs."""

    def __init__(self, tokenizer):
        self.tokenizer = tokenizer
        vocab = tokenizer.get_vocab()
        size = max(vocab.values()) + 1
        self.id_to_token = [None] * size
        for tok, i in vocab.items():
            self.id_to_token[i] = tok
        self.id_to_token = np.asarray(
            ["" if t is None else t for t in self.id_to_token], dtype=object)
        self.cls_id = vocab["[CLS]"]
        self.sep_id = vocab["[SEP]"]
        self.mask_id = vocab["[MASK]"]
        self.pad_id = vocab.get("[PAD]", 0)
        self.unk_id = vocab.get("[UNK]", 0)
        self.do_lower_case = bool(getattr(tokenizer, "do_lower_case", True))
        self.vocab_size = size
        self._native = None
        # Random-replacement masking draws from the full vocab (matching
        # Google's create_pretraining_data); the subword table supports
        # whole-word masking.
        self.is_subword = np.array(
            [t.startswith("##") for t in self.id_to_token], dtype=bool)

    def join(self, ids):
        return " ".join(self.id_to_token[np.asarray(ids, dtype=np.int64)])

    def native_tokenizer(self):
        """Cached C++ engine instance, or None when unavailable or the
        tokenizer's configuration differs from the semantics the native
        kernel implements (WordPiece + default BertNormalizer pipeline)."""
        if self._native is None:
            from .. import native
            backend = getattr(self.tokenizer, "_tokenizer", None)
            if (backend is not None
                    and _native_semantics_match(backend, self.do_lower_case)
                    and native.available()):
                unk = getattr(backend.model, "unk_token", "[UNK]")
                self._native = native.NativeTokenizer(
                    [str(t) for t in self.id_to_token],
                    unk_id=self.tokenizer.get_vocab().get(unk, self.unk_id),
                    do_lower_case=self.do_lower_case)
            else:
                self._native = False
        return self._native or None


def _native_semantics_match(backend, do_lower_case):
    """True iff the HF backend's configuration matches the exact pipeline
    the C++ kernel implements: clean_text + chinese-char spacing + NFD
    accent strip (tied to lowercasing) + lowercase, BertPreTokenizer, and
    '##'-prefixed WordPiece with the standard 100-char word cap. Any
    deviation (e.g. strip_accents=False with do_lower_case=True) must fall
    back to the HF engine rather than silently change token ids."""
    try:
        model = backend.model
        if type(model).__name__ != "WordPiece":
            return False
        if getattr(model, "continuing_subword_prefix", "##") != "##":
            return False
        if getattr(model, "max_input_chars_per_word", 100) != 100:
            return False
        norm = backend.normalizer
        if type(norm).__name__ != "BertNormalizer":
            return False
        if not getattr(norm, "clean_text", True):
            return False
        if not getattr(norm, "handle_chinese_chars", True):
            return False
        if bool(getattr(norm, "lowercase", do_lower_case)) != do_lower_case:
            return False
        strip = getattr(norm, "strip_accents", None)
        if strip is not None and bool(strip) != do_lower_case:
            return False
        if type(backend.pre_tokenizer).__name__ != "BertPreTokenizer":
            return False
        return True
    except Exception:
        return False


def documents_from_texts(texts, tokenizer, engine="auto"):
    """Raw document texts -> documents as lists of per-sentence id lists.

    engine "native": one C++ pass (sentence split + normalize + memoized
    WordPiece, lddl_tpu.native) over the whole block. engine "hf": Python
    splitter + one batched fast-tokenizer call (the reference tokenizes
    sentence-by-sentence, pretrain.py:77-97). "auto" prefers native.
    """
    tok_info = tokenizer if isinstance(tokenizer, TokenizerInfo) else None
    if tok_info is not None:
        tokenizer = tok_info.tokenizer
    if engine in ("auto", "native"):
        if tok_info is None:
            # Cache on the tokenizer object: TokenizerInfo holds the vocab
            # tables and the native engine's word->ids memo, both of which
            # must persist across per-block calls.
            tok_info = getattr(tokenizer, "_lddl_tpu_tok_info", None)
            if tok_info is None:
                tok_info = TokenizerInfo(tokenizer)
                try:
                    tokenizer._lddl_tpu_tok_info = tok_info
                except AttributeError:
                    pass
        nat = tok_info.native_tokenizer()
        if nat is not None:
            return _documents_from_texts_native(texts, nat)
        if engine == "native":
            raise RuntimeError("native tokenizer engine unavailable")
    doc_sentences = [split_sentences(t) for t in texts]
    flat = [s for sents in doc_sentences for s in sents]
    if not flat:
        return []
    backend = getattr(tokenizer, "_tokenizer", None)
    if backend is not None:
        # Rust fast path: skips transformers' per-encoding Python
        # conversion (offsets/attention masks we never use).
        try:
            encs = backend.encode_batch_fast(flat, add_special_tokens=False)
        except AttributeError:
            encs = backend.encode_batch(flat, add_special_tokens=False)
        all_ids = [e.ids for e in encs]
    else:
        enc = tokenizer(flat, add_special_tokens=False,
                        return_attention_mask=False)
        all_ids = enc["input_ids"]
    documents = []
    k = 0
    for sents in doc_sentences:
        doc = []
        for _ in sents:
            ids = all_ids[k]
            k += 1
            if ids:
                doc.append(ids)
        if doc:
            documents.append(doc)
    return documents


def _documents_from_texts_native(texts, nat):
    ids, sent_lens, doc_counts = nat.tokenize_docs(texts)
    flat = ids.tolist()
    ends = np.cumsum(sent_lens)
    documents = []
    k = 0
    pos = 0
    for d in range(len(texts)):
        doc = []
        for _ in range(int(doc_counts[d])):
            end = int(ends[k])
            doc.append(flat[pos:end])
            pos = end
            k += 1
        if doc:
            documents.append(doc)
    return documents


def _truncate_seq_pair(tokens_a, tokens_b, max_num_tokens, g):
    """Randomly truncate the longer of A/B from front or back until the pair
    fits. (standard BERT truncation; ref pretrain.py:161-178)"""
    while len(tokens_a) + len(tokens_b) > max_num_tokens:
        trunc = tokens_a if len(tokens_a) > len(tokens_b) else tokens_b
        if len(trunc) <= 1:
            trunc = tokens_b if trunc is tokens_a else tokens_a
            if len(trunc) <= 1:
                break
        if g.random() < 0.5:
            del trunc[0]
        else:
            trunc.pop()


def create_pairs_from_document(all_documents, document_index, config, g):
    """NSP pair instances (unmasked) from one document: list of
    (a_ids, b_ids, is_random_next)."""
    document = all_documents[document_index]
    max_num_tokens = config.max_seq_length - 3
    target_seq_length = max_num_tokens
    if g.random() < config.short_seq_prob:
        target_seq_length = int(g.integers(2, max_num_tokens + 1))

    instances = []
    current_chunk = []
    current_length = 0
    i = 0
    while i < len(document):
        segment = document[i]
        current_chunk.append(segment)
        current_length += len(segment)
        if i == len(document) - 1 or current_length >= target_seq_length:
            if current_chunk:
                a_end = 1
                if len(current_chunk) >= 2:
                    a_end = int(g.integers(1, len(current_chunk)))
                tokens_a = []
                for j in range(a_end):
                    tokens_a.extend(current_chunk[j])

                tokens_b = []
                if len(current_chunk) == 1 or g.random() < 0.5:
                    is_random_next = True
                    target_b_length = target_seq_length - len(tokens_a)
                    # Pick a different document (bounded retries mirror the
                    # standard algorithm; degenerate single-doc blocks fall
                    # back to self, kept well-formed by truncation).
                    random_document_index = document_index
                    if len(all_documents) > 1:
                        for _ in range(10):
                            cand = int(g.integers(0, len(all_documents)))
                            if cand != document_index:
                                random_document_index = cand
                                break
                    random_document = all_documents[random_document_index]
                    random_start = int(g.integers(0, len(random_document)))
                    for j in range(random_start, len(random_document)):
                        tokens_b.extend(random_document[j])
                        if len(tokens_b) >= target_b_length:
                            break
                    # Put back the unused tail of the chunk.
                    num_unused_segments = len(current_chunk) - a_end
                    i -= num_unused_segments
                else:
                    is_random_next = False
                    for j in range(a_end, len(current_chunk)):
                        tokens_b.extend(current_chunk[j])

                _truncate_seq_pair(tokens_a, tokens_b, max_num_tokens, g)
                if len(tokens_a) >= 1 and len(tokens_b) >= 1:
                    instances.append((tokens_a, tokens_b, is_random_next))
            current_chunk = []
            current_length = 0
        i += 1
    return instances


def pairs_from_documents(documents, config, g):
    """All (a_ids, b_ids, is_random_next) instances for a block:
    ``duplicate_factor`` passes, shuffled within the block."""
    instances = []
    for _ in range(config.duplicate_factor):
        for doc_idx in range(len(documents)):
            instances.extend(
                create_pairs_from_document(documents, doc_idx, config, g))
    lrng.shuffle(g, instances)
    return instances


def _build_sequences(instances, tok_info):
    """[CLS] a [SEP] b [SEP] id lists + per-row A lengths."""
    seqs = []
    a_lens = np.empty(len(instances), dtype=np.int32)
    for i, (a, b, _) in enumerate(instances):
        seqs.append([tok_info.cls_id] + a + [tok_info.sep_id] + b
                    + [tok_info.sep_id])
        a_lens[i] = len(a)
    return seqs, a_lens


def _candidate_mask(valid, a_lens, seq_lens):
    """Positions eligible for masking: valid, not [CLS]/[SEP]."""
    candidate = valid.copy()
    rows = np.arange(valid.shape[0])
    candidate[:, 0] = False
    candidate[rows, a_lens + 1] = False
    candidate[rows, seq_lens - 1] = False
    return candidate


def apply_static_masking(instances, config, tok_info, seed, scope):
    """Batch-mask all instances of a bucket; returns per-row
    (masked_seq_ids, positions, label_ids).

    Engine "numpy": vectorized host kernel on a Philox stream.
    Engine "jax": jit'd kernel (TPU when available), padded to lane-aligned
    buckets so compilations stay bounded.
    """
    seqs, a_lens = _build_sequences(instances, tok_info)
    seq_lens = np.asarray([len(s) for s in seqs], dtype=np.int32)
    width = min(128, config.max_seq_length)
    ids, valid = pad_to_bucket(seqs, pad_id=tok_info.pad_id,
                               length_multiple=width, min_length=width)
    candidate = _candidate_mask(valid, a_lens, seq_lens)
    num_to_predict = plan_num_to_predict(seq_lens, config.masked_lm_ratio,
                                         config.max_predictions_per_seq)

    if config.whole_word_masking:
        masked, selected = _mask_whole_word(ids, candidate, num_to_predict,
                                            tok_info,
                                            lrng.sample_rng(seed, *scope))
    elif config.engine == "jax":
        masker = _get_jax_masker(tok_info)
        # Pad the batch dim to a bucket as well: jit keys compilations on
        # the full shape, and every bucket has a different row count.
        n = ids.shape[0]
        n_pad = max(512, 1 << (n - 1).bit_length())
        if n_pad > n:
            pad_rows = n_pad - n
            ids_p = np.pad(ids, ((0, pad_rows), (0, 0)))
            cand_p = np.pad(candidate, ((0, pad_rows), (0, 0)))
            num_p = np.pad(num_to_predict, (0, pad_rows))
        else:
            ids_p, cand_p, num_p = ids, candidate, num_to_predict
        # Fold the scope into a 32-bit seed for jax.random.
        import hashlib
        h = hashlib.blake2b(
            ("{}:{}".format(seed, scope)).encode(), digest_size=4).digest()
        masked, selected = masker(ids_p, cand_p, num_p,
                                  int.from_bytes(h, "little"))
        masked, selected = masked[:n], selected[:n]
    else:
        masked, selected = mask_batch_numpy(
            ids, candidate, num_to_predict, lrng.sample_rng(seed, *scope),
            tok_info.mask_id, tok_info.vocab_size)

    out = []
    for i in range(len(seqs)):
        positions = np.nonzero(selected[i])[0].astype(np.uint16)
        labels = ids[i, positions]
        out.append((masked[i], positions, labels))
    return out, a_lens, seq_lens


_JAX_MASKERS = {}


def _get_jax_masker(tok_info):
    key = (tok_info.mask_id, tok_info.vocab_size)
    if key not in _JAX_MASKERS:
        _JAX_MASKERS[key] = make_jax_masker(tok_info.mask_id,
                                            tok_info.vocab_size)
    return _JAX_MASKERS[key]


def _mask_whole_word(ids, candidate, num_to_predict, tok_info, g):
    """Whole-word masking: subword continuations group with their word
    start; groups are selected atomically. Per-row loop (rarely used)."""
    out = ids.copy()
    selected = np.zeros_like(candidate)
    is_sub = tok_info.is_subword
    for r in range(ids.shape[0]):
        cols = np.nonzero(candidate[r])[0]
        groups = []
        for c in cols:
            if groups and is_sub[ids[r, c]] and groups[-1][-1] == c - 1:
                groups[-1].append(c)
            else:
                groups.append([c])
        # Stable argsort of raw uniforms (not Generator.permutation) keeps
        # the stream numpy-version-stable, matching utils.rng.shuffle.
        order = np.argsort(g.random(len(groups)), kind="stable")
        budget = int(num_to_predict[r])
        taken = 0
        for gi in order:
            group = groups[gi]
            if taken >= budget:
                break
            if taken + len(group) > budget:
                continue
            for c in group:
                r_act = g.random()
                if r_act < 0.8:
                    out[r, c] = tok_info.mask_id
                elif r_act < 0.9:
                    out[r, c] = int(g.integers(0, tok_info.vocab_size))
                selected[r, c] = True
                taken += 1
    return out, selected


def materialize_rows(instances, config, tok_info, seed, scope):
    """Instances -> parquet row dicts (strings), applying static masking
    batch-wise when configured."""
    if not config.masking:
        return [{
            "A": tok_info.join(a),
            "B": tok_info.join(b),
            "is_random_next": bool(rn),
            "num_tokens": len(a) + len(b) + 3,
        } for a, b, rn in instances]

    masked_rows, a_lens, seq_lens = apply_static_masking(
        instances, config, tok_info, seed, scope)
    rows = []
    for i, (inst, (masked_seq, positions, label_ids)) in enumerate(
            zip(instances, masked_rows)):
        la = int(a_lens[i])
        end = int(seq_lens[i])
        rows.append({
            "A": tok_info.join(masked_seq[1:1 + la]),
            "B": tok_info.join(masked_seq[2 + la:end - 1]),
            "is_random_next": bool(inst[2]),
            "num_tokens": end,
            "masked_lm_positions": serialize_np_array(
                positions.astype(np.uint16)),
            "masked_lm_labels": tok_info.join(label_ids),
        })
    return rows


# Backwards-compatible helper used by tests and docs: per-sequence masking
# on token strings via the batch kernel.
def create_masked_lm_predictions(tokens, vocab_words, g, masked_lm_ratio,
                                 max_predictions_per_seq,
                                 whole_word_masking=False):
    """Mask one token-string sequence in place; returns (positions, labels).

    Thin per-row wrapper over the batch kernels, kept for API parity with
    the reference's function of the same name (pretrain.py:182-238).
    """
    token_to_id = {t: i for i, t in enumerate(vocab_words)}
    # Specials (and any out-of-population token such as [UNK]) get reserved
    # ids beyond the random-draw range so they are never fabricated.
    extra = {}

    def id_of(t):
        if t in token_to_id:
            return token_to_id[t]
        if t not in extra:
            extra[t] = len(vocab_words) + len(extra)
        return extra[t]

    mask_reserved = id_of("[MASK]")
    ids = np.array([[id_of(t) for t in tokens]], dtype=np.int32)
    candidate = np.array(
        [[t not in ("[CLS]", "[SEP]") for t in tokens]], dtype=bool)
    num = plan_num_to_predict([len(tokens)], masked_lm_ratio,
                              max_predictions_per_seq)
    if whole_word_masking:
        class _Shim:
            pass
        shim = _Shim()
        shim.mask_id = mask_reserved
        shim.vocab_size = len(vocab_words)
        shim.is_subword = np.array(
            [t.startswith("##") for t in vocab_words]
            + [False] * len(extra), dtype=bool)
        masked, selected = _mask_whole_word(ids, candidate, num, shim, g)
    else:
        masked, selected = mask_batch_numpy(ids, candidate, num, g,
                                            mask_reserved, len(vocab_words))
    positions = np.nonzero(selected[0])[0]
    labels = [tokens[p] for p in positions]
    id_to_tok = {i: t for t, i in token_to_id.items()}
    id_to_tok.update({v: k for k, v in extra.items()})
    for p in positions:
        new_id = int(masked[0, p])
        if new_id != int(ids[0, p]):  # keep path: leave original verbatim
            tokens[p] = id_to_tok[new_id]
    return positions.tolist(), labels
