"""BERT pretraining sample construction (NSP pairs + MLM masking).

Reference parity: lddl/dask/bert/pretrain.py:49-441 — an independent
reimplementation of Google BERT's ``create_pretraining_data`` distribution
(target-length sampling with ``short_seq_prob``, sentence-chunk
accumulation, random A/B split point, 50% random-next with segment
put-back, random front/back truncation, 80/10/10 masking).

TPU-first restructuring vs the reference: the whole pipeline is *token-id
based* — sentences tokenize straight to ids, pair creation concatenates int
lists, and static masking runs as ONE batched kernel per bucket
(lddl_tpu.ops.masking: numpy engine or jit'd JAX on TPU) instead of a
Python loop per row. Token strings are materialized only at the very end
for the parquet columns.

Output row schema (must match the reference sink,
lddl/dask/bert/pretrain.py:451-471):
    A: str                      whitespace-joined WordPiece tokens
    B: str
    is_random_next: bool
    num_tokens: int             len(A) + len(B) + 3 specials
    masked_lm_positions: bytes  (static masking) serialized np array of
                                positions into [CLS] A [SEP] B [SEP]
    masked_lm_labels: str       (static masking) original tokens, joined
"""

import dataclasses
import hashlib
import json

import numpy as np

from .. import observability as obs
from ..ops.masking import (
    make_jax_masker,
    make_jax_whole_word_masker,
    mask_batch_numpy,
    mask_whole_word_batch_numpy,
    plan_num_to_predict,
)
from ..utils.fs import serialize_np_array
from ..utils import rng as lrng
from .sentences import split_sentences


@dataclasses.dataclass
class BertPretrainConfig:
    max_seq_length: int = 128
    short_seq_prob: float = 0.1
    masking: bool = False
    masked_lm_ratio: float = 0.15
    max_predictions_per_seq: int = None  # default: ceil(ratio * max_seq_len)
    whole_word_masking: bool = False
    duplicate_factor: int = 5
    # Masking kernel: "numpy" | "jax". numpy is the MEASURED default: on a
    # real TPU chip the jit'd kernel is 9-111x slower than the host numpy
    # kernel across bucket sizes 256..32k rows (dispatch latency + host<->device
    # transfer dominate this trivially-parallel int32 work; see
    # benchmarks/mask_engine_bench.py, recorded in MASK_ENGINE_BENCH.json).
    # The offline pipeline keeps the TPU free for training; the jax kernel
    # remains for device-resident data paths.
    engine: str = "numpy"
    # Sentence-split + tokenize engine: "native" = the C++ one-pass kernel
    # (lddl_tpu.native), "hf" = Python splitter + HF fast tokenizer,
    # "auto" = native when buildable + tokenizer-compatible, else hf.
    tokenizer_engine: str = "auto"
    # Sentence splitter: "rules" = the static rule-based splitter
    # (self-contained, F1 0.91 vs punkt); "learned" = corpus-trained punkt
    # parameters driving the punkt decision procedure (F1 0.99 vs an
    # identically-trained punkt, SPLITTER_DRIFT.json) — the runner trains
    # them on a deterministic corpus sample at run start (needs nltk at
    # TRAIN time only; the decision runs nltk-free in Python AND in the
    # C++ engine, fuzz-pinned to parity).
    splitter: str = "rules"
    # Shard schema: 2 (default) adds int32 token-id list columns (A_ids,
    # B_ids, masked_lm_*_ids) ALONGSIDE the text columns so the loader
    # decodes batches zero-copy instead of re-tokenizing every epoch;
    # 1 keeps the original text-only shards (byte-identical to previous
    # releases). Loaders auto-detect per shard; v1-vs-v2 batches are
    # byte-identical (tests/test_schema_v2.py).
    schema_version: int = 2

    def __post_init__(self):
        if self.max_seq_length < 8:
            raise ValueError("max_seq_length too small")
        if self.engine not in ("numpy", "jax"):
            raise ValueError("engine must be numpy|jax")
        if self.tokenizer_engine not in ("auto", "hf", "native"):
            raise ValueError("tokenizer_engine must be auto|hf|native")
        if self.splitter not in ("rules", "learned"):
            raise ValueError("splitter must be rules|learned")
        if self.schema_version not in (1, 2):
            raise ValueError("schema_version must be 1|2")
        if self.max_predictions_per_seq is None:
            self.max_predictions_per_seq = int(
                np.ceil(self.masked_lm_ratio * self.max_seq_length))


class _TokenByteTable:
    """Vocab byte tables: ``blob`` = all token UTF-8 bytes concatenated,
    ``starts``/``lens`` per id — consumed by the native memcpy join and by
    the numpy byte-gather fallback alike."""

    def __init__(self, enc, starts, lens):
        self.blob = b"".join(enc)
        self.starts = starts
        self.lens = lens


class TokenizerInfo:
    """Pre-extracted tokenizer tables the id-based pipeline needs."""

    def __init__(self, tokenizer):
        self.tokenizer = tokenizer
        vocab = tokenizer.get_vocab()
        size = max(vocab.values()) + 1
        self.id_to_token = [None] * size
        for tok, i in vocab.items():
            self.id_to_token[i] = tok
        self.id_to_token = np.asarray(
            ["" if t is None else t for t in self.id_to_token], dtype=object)
        # Once per process, vocab-sized (not corpus-sized): the plain-list
        # form feeds C-level joins downstream. -- lddl: disable=python-hot-loop
        self.token_list = self.id_to_token.tolist()
        self.cls_id = vocab["[CLS]"]
        self.sep_id = vocab["[SEP]"]
        self.mask_id = vocab["[MASK]"]
        self.pad_id = vocab.get("[PAD]", 0)
        self.unk_id = vocab.get("[UNK]", 0)
        self.do_lower_case = bool(getattr(tokenizer, "do_lower_case", True))
        self.vocab_size = size
        self._native = None
        self._token_bytes = None
        # Random-replacement masking draws from the full vocab (matching
        # Google's create_pretraining_data); the subword table supports
        # whole-word masking.
        self.is_subword = np.array(
            [t.startswith("##") for t in self.id_to_token], dtype=bool)

    def __getstate__(self):
        # The native engine holds a ctypes CDLL handle, which cannot cross
        # a pickle boundary (process pools pickle the tokenizer, and this
        # object may be cached on it). Every table here derives from the
        # tokenizer, so ship only that and re-derive on the other side —
        # otherwise each worker-spawn pickle would carry the vocab several
        # times over.
        return {"tokenizer": self.tokenizer}

    def __setstate__(self, state):
        # Re-derivation is deferred to first attribute use: this object may
        # sit in a reference cycle with the tokenizer (the
        # ``_lddl_tpu_tok_info`` cache), so the tokenizer is not fully
        # restored yet when __setstate__ runs.
        self.__dict__["_pickled_tokenizer"] = state["tokenizer"]

    def __getattr__(self, name):
        tok = self.__dict__.pop("_pickled_tokenizer", None)
        if tok is None:
            raise AttributeError(name)
        self.__init__(tok)
        return getattr(self, name)

    @property
    def vocab_digest(self):
        """Digest of the id->token snapshot this object tokenizes with.
        Cached on self: TokenizerInfo is rebuilt per process (and after
        every unpickle), so the cache cannot go stale against its own
        tables — unlike a digest cached on the mutable tokenizer (the
        round-4 size-keyed memo missed same-size in-place token swaps)."""
        d = self.__dict__.get("_vocab_digest")
        if d is None:
            h = hashlib.sha256()
            h.update(b"1" if self.do_lower_case else b"0")
            h.update(json.dumps(self.token_list,
                                separators=(",", ":")).encode())
            d = self._vocab_digest = h.hexdigest()[:16]
        return d

    def join(self, ids):
        return " ".join(self.id_to_token[np.asarray(ids, dtype=np.int64)])

    def token_byte_table(self):
        """Vocab byte tables for the Arrow column builders
        (preprocess.arrowcols): ``blob`` = all token UTF-8 bytes
        concatenated with per-id ``starts``/``lens`` — the gather tables
        of the native memcpy join AND the numpy byte-gather fallback."""
        if self._token_bytes is None:
            enc = [t.encode("utf-8") for t in self.token_list]
            lens = np.fromiter(map(len, enc), dtype=np.int64, count=len(enc))
            starts = np.zeros(len(enc), dtype=np.int64)
            np.cumsum(lens[:-1], out=starts[1:])
            self._token_bytes = _TokenByteTable(enc, starts, lens)
        return self._token_bytes

    def native_tokenizer(self):
        """Cached C++ engine instance, or None when unavailable or the
        tokenizer's configuration differs from the semantics the native
        kernel implements (WordPiece + default BertNormalizer pipeline)."""
        if self._native is None:
            from .. import native
            backend = getattr(self.tokenizer, "_tokenizer", None)
            if (backend is not None
                    and _native_semantics_match(backend, self.do_lower_case)
                    and native.available()):
                unk = getattr(backend.model, "unk_token", "[UNK]")
                self._native = native.NativeTokenizer(
                    [str(t) for t in self.id_to_token],
                    unk_id=self.tokenizer.get_vocab().get(unk, self.unk_id),
                    do_lower_case=self.do_lower_case)
            else:
                self._native = False
        return self._native or None


def _native_semantics_match(backend, do_lower_case):
    """True iff the HF backend's configuration matches the exact pipeline
    the C++ kernel implements: clean_text + chinese-char spacing + NFD
    accent strip (tied to lowercasing) + lowercase, BertPreTokenizer, and
    '##'-prefixed WordPiece with the standard 100-char word cap. Any
    deviation (e.g. strip_accents=False with do_lower_case=True) must fall
    back to the HF engine rather than silently change token ids."""
    try:
        model = backend.model
        if type(model).__name__ != "WordPiece":
            return False
        if getattr(model, "continuing_subword_prefix", "##") != "##":
            return False
        if getattr(model, "max_input_chars_per_word", 100) != 100:
            return False
        norm = backend.normalizer
        if type(norm).__name__ != "BertNormalizer":
            return False
        if not getattr(norm, "clean_text", True):
            return False
        if not getattr(norm, "handle_chinese_chars", True):
            return False
        if bool(getattr(norm, "lowercase", do_lower_case)) != do_lower_case:
            return False
        strip = getattr(norm, "strip_accents", None)
        if strip is not None and bool(strip) != do_lower_case:
            return False
        if type(backend.pre_tokenizer).__name__ != "BertPreTokenizer":
            return False
        return True
    except Exception:
        return False


def _apply_splitter_params(nat, splitter_params):
    """Attach (or clear) learned splitter params on the cached native
    engine, re-parsing only when the blob actually changed."""
    blob = splitter_params.serialize() if splitter_params else None
    if getattr(nat, "_args", (None,) * 5)[4] != blob:
        nat.set_splitter(blob)


def documents_from_texts(texts, tokenizer, engine="auto",
                         splitter_params=None):
    """Raw document texts -> documents as lists of per-sentence id
    sequences (Python lists on the hf engine, zero-copy int32 numpy views
    on the native engine — both iterate/slice identically for the pair
    engine).

    engine "native": one C++ pass (sentence split + normalize + memoized
    WordPiece, lddl_tpu.native) over the whole block. engine "hf": Python
    splitter + one batched fast-tokenizer call (the reference tokenizes
    sentence-by-sentence, pretrain.py:77-97). "auto" prefers native.
    ``splitter_params`` (sentences.SplitterParams) switches both engines
    to the corpus-learned punkt splitter.
    """
    tok_info = tokenizer if isinstance(tokenizer, TokenizerInfo) else None
    if tok_info is not None:
        tokenizer = tok_info.tokenizer
    if engine in ("auto", "native"):
        if tok_info is None:
            # Cache on the tokenizer object: TokenizerInfo holds the vocab
            # tables and the native engine's word->ids memo, both of which
            # must persist across per-block calls.
            tok_info = getattr(tokenizer, "_lddl_tpu_tok_info", None)
            if tok_info is None:
                tok_info = TokenizerInfo(tokenizer)
                try:
                    tokenizer._lddl_tpu_tok_info = tok_info
                except AttributeError:
                    pass
        nat = tok_info.native_tokenizer()
        if nat is not None:
            _apply_splitter_params(nat, splitter_params)
            return _documents_from_texts_native(texts, nat)
        if engine == "native":
            raise RuntimeError("native tokenizer engine unavailable")
    # Non-native path: the pipeline hands document text as raw bytes
    # (readers.read_block_lines); decode here, exactly as the old
    # str-everywhere pipeline did at read time.
    texts = [t.decode("utf-8", errors="replace") if isinstance(t, bytes)
             else t for t in texts]
    if splitter_params is not None:
        from .sentences import split_sentences_learned
        doc_sentences = [split_sentences_learned(t, splitter_params)
                         for t in texts]
    else:
        doc_sentences = [split_sentences(t) for t in texts]
    flat = [s for sents in doc_sentences for s in sents]
    if not flat:
        return []
    backend = getattr(tokenizer, "_tokenizer", None)
    if backend is not None:
        # Rust fast path: skips transformers' per-encoding Python
        # conversion (offsets/attention masks we never use).
        try:
            encs = backend.encode_batch_fast(flat, add_special_tokens=False)
        except AttributeError:
            encs = backend.encode_batch(flat, add_special_tokens=False)
        all_ids = [e.ids for e in encs]
    else:
        enc = tokenizer(flat, add_special_tokens=False,
                        return_attention_mask=False)
        all_ids = enc["input_ids"]
    documents = []
    k = 0
    for sents in doc_sentences:
        doc = []
        for _ in sents:
            ids = all_ids[k]
            k += 1
            if ids:
                doc.append(ids)
        if doc:
            documents.append(doc)
    return documents


def _emit_native_thread_metrics(nat):
    """Pool-attribution metrics after a native kernel call: the configured
    width (``native_threads`` gauge) plus per-thread busy-time deltas
    (``native_thread_busy_seconds_total{tid}``). Together they tell a
    starved pool (every tid busy but wall flat -> oversubscribed host)
    from a serial floor (tid 0 busy, the rest idle -> the bucket was too
    small to partition). Counters are cumulative on the kernel side; the
    previous reading is cached on the tokenizer and diffed here."""
    if not obs.enabled():
        return
    try:
        obs.set_gauge("native_threads", nat.get_threads())
        busy = nat.thread_busy_ns()
        prev = getattr(nat, "_busy_prev", [])
        for t, b in enumerate(busy):
            d = b - (prev[t] if t < len(prev) else 0)
            if d > 0:
                obs.inc("native_thread_busy_seconds_total", d / 1e9,
                        tid=str(t))
        nat._busy_prev = busy
    except Exception:  # lddl: disable=swallowed-error (metrics-only path)
        pass


def instances_from_texts(texts, tok_info, config, seed, bucket,
                         splitter_params=None):
    """Texts -> InstanceBatch via the configured engine (the whole bucket
    hot path: split + tokenize + pair creation). Both engines emit
    identical batches: tokenization parity plus the shared CounterRNG
    contract make the native path a bit-exact replay of the Python one.
    ``splitter_params`` is required when config.splitter == "learned"
    (the runner trains and passes it)."""
    if not isinstance(tok_info, TokenizerInfo):
        tok_info = TokenizerInfo(tok_info)
    if config.splitter == "learned" and splitter_params is None:
        raise ValueError(
            "config.splitter='learned' needs splitter_params (see "
            "sentences.train_splitter_params; run_bert_preprocess trains "
            "them automatically)")
    engine = config.tokenizer_engine
    nat = (tok_info.native_tokenizer()
           if engine in ("auto", "native") else None)
    if engine == "native" and nat is None:
        raise RuntimeError("native tokenizer engine unavailable")
    if nat is not None:
        from .. import native
        _apply_splitter_params(nat, splitter_params)
        if native.fused_enabled():
            # FUSED rung: raw document bytes (zero-copy when ``texts`` is
            # a readers.DocSpans spool view) -> packed instance buffers in
            # ONE native pass; the kernel also hands back the flat A/B id
            # segments on the unmasked path so the schema-v2 column
            # builders wrap them without re-gathering.
            seq_ids, seq_lens, a_lens, rn, a_ids, b_ids = \
                nat.bert_instances(
                    texts, config.max_seq_length, config.short_seq_prob,
                    config.duplicate_factor, seed, bucket, tok_info.cls_id,
                    tok_info.sep_id, want_ab=not config.masking)
            _emit_native_thread_metrics(nat)
            return InstanceBatch(seq_ids, seq_lens, a_lens, rn,
                                 a_ids=a_ids, b_ids=b_ids)
        # STAGED rung (LDDL_TPU_NATIVE_FUSED=0): two native calls with
        # ownership-transferred (still copy-free) result buffers.
        ids, sent_lens, doc_counts = nat.tokenize_docs(texts)
        seq_ids, seq_lens, a_lens, rn = native.bert_pairs(
            ids, sent_lens, doc_counts, config.max_seq_length,
            config.short_seq_prob, config.duplicate_factor, seed, bucket,
            tok_info.cls_id, tok_info.sep_id)
        _emit_native_thread_metrics(nat)
        return InstanceBatch(seq_ids, seq_lens, a_lens, rn)
    documents = documents_from_texts(texts, tok_info, engine="hf",
                                     splitter_params=splitter_params)
    instances = pairs_from_documents(documents, config, seed, bucket)
    return InstanceBatch.from_pairs(instances, tok_info.cls_id,
                                    tok_info.sep_id)


def _documents_from_texts_native(texts, nat):
    ids, sent_lens, doc_counts = nat.tokenize_docs(texts)
    # One vectorized split: per-sentence documents are zero-copy int32
    # views of the flat id buffer (no per-token Python objects). The
    # Python pair engine consumes them through iteration/len/slicing,
    # which numpy arrays serve exactly like lists.
    splits = np.split(ids, np.cumsum(sent_lens)[:-1])
    documents = []
    k = 0
    for d in range(len(texts)):
        doc = splits[k:k + int(doc_counts[d])]
        k += int(doc_counts[d])
        if doc:
            documents.append(doc)
    return documents


# Domain tags of the frozen pair-creation RNG streams (see utils/rng.py:
# CounterRNG — the cross-engine SplitMix64 contract shared with the C++
# engine). One stream per (seed, bucket, duplicate-pass, document); one
# shared stream for the in-bucket instance shuffle.
PAIR_TAG = 0x1DD1_0004
PAIR_SHUFFLE_TAG = 0x1DD1_0005


def _truncate_seq_pair(tokens_a, tokens_b, max_num_tokens, rng):
    """Randomly truncate the longer of A/B from front or back until the pair
    fits; returns the (possibly sliced) pair. One RNG draw per removed
    token, as in the standard algorithm (ref pretrain.py:161-178) — but
    tracked as front/back counters and applied as two slices instead of
    per-token list deletion."""
    la, lb = len(tokens_a), len(tokens_b)
    if la + lb <= max_num_tokens:
        return tokens_a, tokens_b
    fa = ba = fb = bb = 0  # front/back removals of a and b
    while la + lb > max_num_tokens:
        from_a = la > lb
        if (la if from_a else lb) <= 1:
            from_a = not from_a
            if (la if from_a else lb) <= 1:
                break
        if from_a:
            la -= 1
            if rng.uniform() < 0.5:
                fa += 1
            else:
                ba += 1
        else:
            lb -= 1
            if rng.uniform() < 0.5:
                fb += 1
            else:
                bb += 1
    return (tokens_a[fa:len(tokens_a) - ba],
            tokens_b[fb:len(tokens_b) - bb])


def create_pairs_from_document(all_documents, document_index, config, rng):
    """NSP pair instances (unmasked) from one document: list of
    (a_ids, b_ids, is_random_next). ``rng`` is a CounterRNG on the frozen
    cross-engine stream; the native engine replays the identical draw
    sequence (one uniform per decision, one randint per index pick)."""
    document = all_documents[document_index]
    max_num_tokens = config.max_seq_length - 3
    target_seq_length = max_num_tokens
    if rng.uniform() < config.short_seq_prob:
        target_seq_length = rng.randint(2, max_num_tokens + 1)

    instances = []
    current_chunk = []
    current_length = 0
    i = 0
    while i < len(document):
        segment = document[i]
        current_chunk.append(segment)
        current_length += len(segment)
        if i == len(document) - 1 or current_length >= target_seq_length:
            if current_chunk:
                a_end = 1
                if len(current_chunk) >= 2:
                    a_end = rng.randint(1, len(current_chunk))
                tokens_a = []
                for j in range(a_end):
                    tokens_a.extend(current_chunk[j])

                tokens_b = []
                if len(current_chunk) == 1 or rng.uniform() < 0.5:
                    is_random_next = True
                    target_b_length = target_seq_length - len(tokens_a)
                    # Pick a different document (bounded retries mirror the
                    # standard algorithm; degenerate single-doc blocks fall
                    # back to self, kept well-formed by truncation).
                    random_document_index = document_index
                    if len(all_documents) > 1:
                        for _ in range(10):
                            cand = rng.randint(0, len(all_documents))
                            if cand != document_index:
                                random_document_index = cand
                                break
                    random_document = all_documents[random_document_index]
                    random_start = rng.randint(0, len(random_document))
                    for j in range(random_start, len(random_document)):
                        tokens_b.extend(random_document[j])
                        if len(tokens_b) >= target_b_length:
                            break
                    # Put back the unused tail of the chunk.
                    num_unused_segments = len(current_chunk) - a_end
                    i -= num_unused_segments
                else:
                    is_random_next = False
                    for j in range(a_end, len(current_chunk)):
                        tokens_b.extend(current_chunk[j])

                tokens_a, tokens_b = _truncate_seq_pair(
                    tokens_a, tokens_b, max_num_tokens, rng)
                if len(tokens_a) >= 1 and len(tokens_b) >= 1:
                    instances.append((tokens_a, tokens_b, is_random_next))
            current_chunk = []
            current_length = 0
        i += 1
    return instances


def pairs_from_documents(documents, config, seed, bucket):
    """All (a_ids, b_ids, is_random_next) instances for a bucket:
    ``duplicate_factor`` passes over every document, then one in-bucket
    shuffle. Streams are keyed per (seed, bucket, pass, document) so the
    native engine can replay them in any order."""
    instances = []
    for dup in range(config.duplicate_factor):
        for doc_idx in range(len(documents)):
            rng = lrng.CounterRNG(PAIR_TAG, seed, bucket, dup, doc_idx)
            instances.extend(
                create_pairs_from_document(documents, doc_idx, config, rng))
    perm = lrng.stable_shuffle_perm(len(instances), PAIR_SHUFFLE_TAG, seed,
                                    bucket)
    return [instances[i] for i in perm]


@dataclasses.dataclass
class MaskedInstanceBatch:
    """One bucket's instances with static masking ALREADY applied — the
    fused-masked kernel's output format (lddl_bert_instances_masked):
    flat masked A/B id segments plus the row-relative mask selection
    (positions into [CLS] A [SEP] B [SEP], original label ids, per-row
    counts). Everything materialize_columns' masking branch derives from
    the padded matrix arrives precomputed, so no [n, width] array ever
    exists in Python. Bit-exact to apply_static_masking on the same
    Philox stream (pinned by tests/test_fused.py)."""

    a_lens: np.ndarray          # int32 [n]
    seq_lens: np.ndarray        # int32 [n]
    is_random_next: np.ndarray  # bool [n]
    flat_a: np.ndarray          # int32, masked A segments row-major
    flat_b: np.ndarray          # int32, masked B segments row-major
    sel_positions: np.ndarray   # int32, row-relative selected positions
    sel_lens: np.ndarray        # int32 [n] selected count per row
    label_ids: np.ndarray       # int32, original ids at selected positions

    def __len__(self):
        return len(self.seq_lens)


def masked_instances_from_texts(texts, tok_info, config, seed, bucket,
                                mask_scope, splitter_params=None):
    """FUSED-MASKED rung: raw document bytes -> masked instance arrays in
    ONE native call (split + WordPiece + NSP + shuffle + the numpy-Philox
    masking replay keyed by ``sample_key_bytes(seed, *mask_scope)``).

    Returns a MaskedInstanceBatch, or None when outside the frozen replay
    contract — numpy engine only, no whole-word masking, vocab size in
    [2, 2^32), native fused kernel available and semantics-matched, and
    not force-disabled (``LDDL_TPU_NATIVE_FUSED_MASK=0`` drops to the
    staged ladder: fused-unmasked + separate mask_batch). The caller MUST
    fall back on None — refusing into the numpy path is the contract,
    never a silent engine fork."""
    if not config.masking or config.whole_word_masking:
        return None
    if config.engine != "numpy":
        return None
    if not (2 <= tok_info.vocab_size < 0xFFFFFFFF):
        return None
    if config.tokenizer_engine not in ("auto", "native"):
        return None
    from .. import native
    if not native.fused_enabled() or not native.fused_mask_enabled():
        return None
    nat = tok_info.native_tokenizer()
    if nat is None:
        return None
    _apply_splitter_params(nat, splitter_params)
    res = nat.bert_instances_masked(
        texts, config.max_seq_length, config.short_seq_prob,
        config.duplicate_factor, seed, bucket, tok_info.cls_id,
        tok_info.sep_id, lrng.sample_key_bytes(seed, *mask_scope),
        tok_info.mask_id, tok_info.vocab_size, config.masked_lm_ratio,
        config.max_predictions_per_seq,
        min(128, config.max_seq_length))
    if res is None:
        return None
    _emit_native_thread_metrics(nat)
    return MaskedInstanceBatch(*res)


@dataclasses.dataclass
class InstanceBatch:
    """One bucket's pretraining instances in flat array form — the native
    engine's output format; the Python engine converts into it. Row i is
    ``seq_ids[off_i : off_i + seq_lens[i]]`` = [CLS] a [SEP] b [SEP] with
    ``a_lens[i]`` = len(a).

    ``a_ids``/``b_ids`` (optional): the flat A/B segments row-major — the
    fused kernel emits them directly on the unmasked path so the column
    builders skip the fancy-index re-gather; None means "derive from
    seq_ids"."""

    seq_ids: np.ndarray        # int32, all rows concatenated
    seq_lens: np.ndarray       # int32 [n]
    a_lens: np.ndarray         # int32 [n]
    is_random_next: np.ndarray  # bool [n]
    a_ids: np.ndarray = None   # int32, flat A segments (optional)
    b_ids: np.ndarray = None   # int32, flat B segments (optional)

    def __len__(self):
        return len(self.seq_lens)

    @classmethod
    def from_pairs(cls, instances, cls_id, sep_id):
        n = len(instances)
        seq_lens = np.empty(n, dtype=np.int32)
        a_lens = np.empty(n, dtype=np.int32)
        rn = np.empty(n, dtype=bool)
        flat = []
        for i, (a, b, r) in enumerate(instances):
            flat.append(cls_id)
            flat.extend(a)
            flat.append(sep_id)
            flat.extend(b)
            flat.append(sep_id)
            seq_lens[i] = len(a) + len(b) + 3
            a_lens[i] = len(a)
            rn[i] = r
        return cls(np.asarray(flat, dtype=np.int32), seq_lens, a_lens, rn)

    def padded(self, pad_id, length_multiple, min_length):
        """(ids, valid) 2-D arrays, width padded up to a lane-aligned
        bucket so jit compilations stay bounded."""
        from ..ops.packing import round_up
        n = len(self)
        width = max(min_length,
                    round_up(int(self.seq_lens.max()), length_multiple))
        valid = np.arange(width)[None, :] < self.seq_lens[:, None]
        ids = np.full((n, width), pad_id, dtype=np.int32)
        ids[valid] = self.seq_ids  # row-major fill matches flat order
        return ids, valid


def _candidate_mask(valid, a_lens, seq_lens):
    """Positions eligible for masking: valid, not [CLS]/[SEP]."""
    candidate = valid.copy()
    rows = np.arange(valid.shape[0])
    candidate[:, 0] = False
    candidate[rows, a_lens + 1] = False
    candidate[rows, seq_lens - 1] = False
    return candidate


def apply_static_masking(batch, config, tok_info, seed, scope):
    """Batch-mask all instances of a bucket (an InstanceBatch or a list of
    (a, b, is_random_next) pairs); returns batch arrays (masked ids,
    selected mask, original ids, a_lens, seq_lens) — callers slice rows
    out (positions of row i = nonzero(selected[i]), labels =
    ids[i, positions]).

    Engine "numpy": vectorized host kernel on a Philox stream.
    Engine "jax": jit'd kernel (TPU when available), padded to lane-aligned
    buckets so compilations stay bounded.
    """
    if isinstance(batch, list):
        batch = InstanceBatch.from_pairs(batch, tok_info.cls_id,
                                         tok_info.sep_id)
    a_lens, seq_lens = batch.a_lens, batch.seq_lens
    width = min(128, config.max_seq_length)
    ids, valid = batch.padded(tok_info.pad_id, width, width)
    candidate = _candidate_mask(valid, a_lens, seq_lens)
    num_to_predict = plan_num_to_predict(seq_lens, config.masked_lm_ratio,
                                         config.max_predictions_per_seq)

    if config.engine == "jax":
        masker = (_get_jax_wwm_masker(tok_info) if config.whole_word_masking
                  else _get_jax_masker(tok_info))
        masked, selected = _run_jax_chunked(masker, ids, candidate,
                                            num_to_predict, seed, scope)
    elif config.whole_word_masking:
        masked, selected = mask_whole_word_batch_numpy(
            ids, candidate, num_to_predict, lrng.sample_rng(seed, *scope),
            tok_info.mask_id, tok_info.vocab_size, tok_info.is_subword)
    else:
        # Native first: a bit-exact C++ replay of mask_batch_numpy on the
        # SAME Philox stream (utils.rng.sample_key_bytes hands the kernel
        # the stream key) — an implementation swap, not an engine fork,
        # so shard bytes cannot depend on which one ran (pinned by
        # tests/test_fused.py::test_native_mask_matches_numpy).
        from .. import native
        masked_selected = native.mask_batch(
            lrng.sample_key_bytes(seed, *scope), ids, candidate,
            num_to_predict, tok_info.mask_id, tok_info.vocab_size)
        if masked_selected is None:
            masked_selected = mask_batch_numpy(
                ids, candidate, num_to_predict,
                lrng.sample_rng(seed, *scope), tok_info.mask_id,
                tok_info.vocab_size)
        masked, selected = masked_selected

    return masked, selected, ids, a_lens, seq_lens


def _run_jax_chunked(masker, ids, candidate, num_to_predict, seed, scope):
    """Run a jit'd masker in fixed-size row chunks.

    jit keys compilations on the full shape and every bucket has a
    different row count, so run in fixed-size row chunks: all full chunks
    share ONE compiled shape per width bucket; only the last partial chunk
    pads up to a power of two (floor 64). Compilation count stays O(log
    chunk) per width, padding waste stays small."""
    n = ids.shape[0]
    chunk = 2048
    # Fold the scope into a 32-bit seed for jax.random; vary per chunk
    # so chunking does not correlate the streams.
    import hashlib

    def _seed_of(ci):
        h = hashlib.blake2b(
            ("{}:{}:{}".format(seed, scope, ci)).encode(),
            digest_size=4).digest()
        return int.from_bytes(h, "little")

    masked_parts, selected_parts = [], []
    for ci, start in enumerate(range(0, n, chunk)):
        ids_c = ids[start:start + chunk]
        cand_c = candidate[start:start + chunk]
        num_c = num_to_predict[start:start + chunk]
        nc = ids_c.shape[0]
        n_pad = min(chunk, 1 << max(6, (nc - 1).bit_length()))
        if n_pad > nc:
            ids_c = np.pad(ids_c, ((0, n_pad - nc), (0, 0)))
            cand_c = np.pad(cand_c, ((0, n_pad - nc), (0, 0)))
            num_c = np.pad(num_c, (0, n_pad - nc))
        m_c, s_c = masker(ids_c, cand_c, num_c, _seed_of(ci))
        masked_parts.append(np.asarray(m_c[:nc]))
        selected_parts.append(np.asarray(s_c[:nc]))
    masked = np.concatenate(masked_parts) if masked_parts else ids
    selected = (np.concatenate(selected_parts)
                if selected_parts else np.zeros_like(candidate))
    return masked, selected


_JAX_MASKERS = {}


def _get_jax_masker(tok_info):
    key = (tok_info.mask_id, tok_info.vocab_size)
    if key not in _JAX_MASKERS:
        _JAX_MASKERS[key] = make_jax_masker(tok_info.mask_id,
                                            tok_info.vocab_size)
    return _JAX_MASKERS[key]


_JAX_WWM_MASKERS = {}


def _get_jax_wwm_masker(tok_info):
    # is_subword must be part of the key: two vocabs of the same size and
    # mask_id can group words differently.
    key = (tok_info.mask_id, tok_info.vocab_size,
           tok_info.is_subword.tobytes())
    if key not in _JAX_WWM_MASKERS:
        _JAX_WWM_MASKERS[key] = make_jax_whole_word_masker(
            tok_info.mask_id, tok_info.vocab_size, tok_info.is_subword)
    return _JAX_WWM_MASKERS[key]


def materialize_columns(batch, config, tok_info, seed, scope):
    """Instances (InstanceBatch or list of (a, b, is_random_next)) ->
    parquet COLUMNS ({name: ndarray-or-pa.Array}, n), applying static
    masking batch-wise when configured.

    Columnar end-to-end: the string/binary columns are assembled as raw
    Arrow buffers with vectorized byte gathers (preprocess.arrowcols) —
    between pair construction and the parquet file, no per-row Python
    object exists at all."""
    from .arrowcols import (concat_aranges, int32_list_array,
                            joined_token_strings, serialized_u16_binary)
    if isinstance(batch, list):
        batch = InstanceBatch.from_pairs(batch, tok_info.cls_id,
                                         tok_info.sep_id)
    n = len(batch)
    if n == 0:
        return {}, 0
    if isinstance(batch, MaskedInstanceBatch):
        # Fused-masked fast path: the kernel already applied the Philox
        # masking replay and emitted exactly the flat arrays the column
        # builders consume — same values the padded-matrix branch below
        # would gather, so shard bytes are identical by construction.
        tok_table = tok_info.token_byte_table()
        a_lens = np.asarray(batch.a_lens, dtype=np.int64)
        b_lens = np.asarray(batch.seq_lens, dtype=np.int64) - a_lens - 3
        sel_lens = np.asarray(batch.sel_lens, dtype=np.int64)
        columns = {
            "A": joined_token_strings(batch.flat_a, a_lens, tok_table),
            "B": joined_token_strings(batch.flat_b, b_lens, tok_table),
            "is_random_next": np.asarray(batch.is_random_next, dtype=bool),
            "num_tokens": np.asarray(batch.seq_lens).astype(np.uint16),
            "masked_lm_positions": serialized_u16_binary(
                batch.sel_positions, sel_lens),
            "masked_lm_labels": joined_token_strings(
                batch.label_ids, sel_lens, tok_table),
        }
        if config.schema_version >= 2:
            columns["A_ids"] = int32_list_array(batch.flat_a, a_lens)
            columns["B_ids"] = int32_list_array(batch.flat_b, b_lens)
            columns["masked_lm_positions_ids"] = int32_list_array(
                batch.sel_positions, sel_lens)
            columns["masked_lm_label_ids"] = int32_list_array(
                batch.label_ids, sel_lens)
        return columns, n
    tok_table = tok_info.token_byte_table()
    a_lens = np.asarray(batch.a_lens, dtype=np.int64)
    seq_lens = np.asarray(batch.seq_lens, dtype=np.int64)
    b_lens = seq_lens - a_lens - 3
    rn = batch.is_random_next

    if not config.masking:
        if batch.a_ids is not None and batch.b_ids is not None:
            # Fused-kernel fast path: the flat A/B segments arrived as
            # ownership-transferred buffers — wrap, don't re-gather.
            flat_a, flat_b = batch.a_ids, batch.b_ids
        else:
            # Row i of seq_ids spans [off_i, off_i + seq_lens_i):
            # [CLS] A [SEP] B [SEP]. Gather A and B id segments flat.
            offsets = np.cumsum(seq_lens) - seq_lens
            flat_a = batch.seq_ids[np.repeat(offsets + 1, a_lens)
                                   + concat_aranges(a_lens)]
            flat_b = batch.seq_ids[np.repeat(offsets + 2 + a_lens, b_lens)
                                   + concat_aranges(b_lens)]
        columns = {
            "A": joined_token_strings(flat_a, a_lens, tok_table),
            "B": joined_token_strings(flat_b, b_lens, tok_table),
            "is_random_next": np.asarray(rn, dtype=bool),
            "num_tokens": seq_lens.astype(np.uint16),
        }
        if config.schema_version >= 2:
            columns["A_ids"] = int32_list_array(flat_a, a_lens)
            columns["B_ids"] = int32_list_array(flat_b, b_lens)
        return columns, n

    masked, selected, ids, a_lens, seq_lens = apply_static_masking(
        batch, config, tok_info, seed, scope)
    a_lens = np.asarray(a_lens, dtype=np.int64)
    seq_lens = np.asarray(seq_lens, dtype=np.int64)
    b_lens = seq_lens - a_lens - 3
    rows = np.arange(n, dtype=np.int64)
    flat_a = masked[np.repeat(rows, a_lens),
                    1 + concat_aranges(a_lens)]
    flat_b = masked[np.repeat(rows, b_lens),
                    np.repeat(2 + a_lens, b_lens) + concat_aranges(b_lens)]
    sel_rows, sel_cols = np.nonzero(selected)            # row-major: sorted
    sel_lens = np.bincount(sel_rows, minlength=n)
    columns = {
        "A": joined_token_strings(flat_a, a_lens, tok_table),
        "B": joined_token_strings(flat_b, b_lens, tok_table),
        "is_random_next": np.asarray(rn, dtype=bool),
        "num_tokens": seq_lens.astype(np.uint16),
        "masked_lm_positions": serialized_u16_binary(sel_cols, sel_lens),
        "masked_lm_labels": joined_token_strings(
            ids[sel_rows, sel_cols], sel_lens, tok_table),
    }
    if config.schema_version >= 2:
        columns["A_ids"] = int32_list_array(flat_a, a_lens)
        columns["B_ids"] = int32_list_array(flat_b, b_lens)
        columns["masked_lm_positions_ids"] = int32_list_array(sel_cols,
                                                              sel_lens)
        columns["masked_lm_label_ids"] = int32_list_array(
            ids[sel_rows, sel_cols], sel_lens)
    return columns, n


def materialize_rows(batch, config, tok_info, seed, scope):
    """Row-dict view of materialize_columns (debug/txt sink + tests; the
    parquet path consumes the columns directly)."""
    import pyarrow as pa
    # The schema-v2 id columns are a loader fast path, not part of the
    # human-readable row view (txt sink format is schema-stable) — don't
    # build them just to drop them.
    if config.schema_version != 1:
        config = dataclasses.replace(config, schema_version=1)
    columns, n = materialize_columns(batch, config, tok_info, seed, scope)
    plain = {
        # Debug/test row view only (see docstring): the parquet path
        # consumes the columns directly and never takes this branch.
        name: (col.to_pylist() if isinstance(col, pa.Array)  # lddl: disable=python-hot-loop
               else col.tolist())  # lddl: disable=python-hot-loop
        for name, col in columns.items()
    }
    names = list(plain)
    return [{name: plain[name][i] for name in names} for i in range(n)]


# Backwards-compatible helper used by tests and docs: per-sequence masking
# on token strings via the batch kernel.
def create_masked_lm_predictions(tokens, vocab_words, g, masked_lm_ratio,
                                 max_predictions_per_seq,
                                 whole_word_masking=False):
    """Mask one token-string sequence in place; returns (positions, labels).

    Thin per-row wrapper over the batch kernels, kept for API parity with
    the reference's function of the same name (pretrain.py:182-238).
    """
    token_to_id = {t: i for i, t in enumerate(vocab_words)}
    # Specials (and any out-of-population token such as [UNK]) get reserved
    # ids beyond the random-draw range so they are never fabricated.
    extra = {}

    def id_of(t):
        if t in token_to_id:
            return token_to_id[t]
        if t not in extra:
            extra[t] = len(vocab_words) + len(extra)
        return extra[t]

    mask_reserved = id_of("[MASK]")
    ids = np.array([[id_of(t) for t in tokens]], dtype=np.int32)
    candidate = np.array(
        [[t not in ("[CLS]", "[SEP]") for t in tokens]], dtype=bool)
    num = plan_num_to_predict([len(tokens)], masked_lm_ratio,
                              max_predictions_per_seq)
    if whole_word_masking:
        is_subword = np.array(
            [t.startswith("##") for t in vocab_words]
            + [False] * len(extra), dtype=bool)
        masked, selected = mask_whole_word_batch_numpy(
            ids, candidate, num, g, mask_reserved, len(vocab_words),
            is_subword)
    else:
        masked, selected = mask_batch_numpy(ids, candidate, num, g,
                                            mask_reserved, len(vocab_words))
    positions = np.nonzero(selected[0])[0]
    labels = [tokens[p] for p in positions]
    id_to_tok = {i: t for t, i in token_to_id.items()}
    id_to_tok.update({v: k for k, v in extra.items()})
    for p in positions:
        new_id = int(masked[0, p])
        if new_id != int(ids[0, p]):  # keep path: leave original verbatim
            tokens[p] = id_to_tok[new_id]
    # Per-row API-parity helper for tests/docs (see docstring); the batch
    # kernels above are the pipeline path. -- lddl: disable=python-hot-loop
    return positions.tolist(), labels
