"""BERT pretraining sample construction (NSP pairs + MLM masking).

Reference parity: lddl/dask/bert/pretrain.py:49-441 — itself a port of
Google BERT's ``create_pretraining_data``. This is an independent
reimplementation of that public algorithm on top of lddl_tpu's counter-based
RNG streams (lddl_tpu.utils.rng); the produced distribution matches the
reference (target-length sampling with ``short_seq_prob``, sentence-chunk
accumulation, random A/B split point, 50% random-next with segment
put-back, random front/back pair truncation, 80/10/10 masking), while the
exact random sequence follows our frozen RNG contract, not CPython's
Mersenne Twister (SURVEY.md §7 "Byte-identical shards vs TPU RNG").

Output row schema (must match the reference sink,
lddl/dask/bert/pretrain.py:451-471):
    A: str                      whitespace-joined WordPiece tokens
    B: str
    is_random_next: bool
    num_tokens: int             len(A) + len(B) + 3 specials
    masked_lm_positions: bytes  (static masking) serialized np array of
                                positions into [CLS] A [SEP] B [SEP]
    masked_lm_labels: str       (static masking) original tokens, joined
"""

import dataclasses

import numpy as np

from ..utils.fs import serialize_np_array
from ..utils import rng as lrng
from .sentences import split_sentences


@dataclasses.dataclass
class BertPretrainConfig:
    max_seq_length: int = 128
    short_seq_prob: float = 0.1
    masking: bool = False
    masked_lm_ratio: float = 0.15
    max_predictions_per_seq: int = None  # default: ceil(ratio * max_seq_len)
    whole_word_masking: bool = False
    duplicate_factor: int = 5

    def __post_init__(self):
        if self.max_seq_length < 8:
            raise ValueError("max_seq_length too small")
        if self.max_predictions_per_seq is None:
            self.max_predictions_per_seq = int(
                np.ceil(self.masked_lm_ratio * self.max_seq_length))


def documents_from_texts(texts, tokenizer):
    """Tokenize raw document texts into documents = lists of token-lists.

    Sentence-splits each text, then WordPiece-tokenizes all sentences in one
    batched fast-tokenizer call (the reference tokenizes sentence-by-
    sentence, pretrain.py:77-97; batching is the first of the hot-path wins).
    Documents that end up empty are dropped.
    """
    doc_sentences = [split_sentences(t) for t in texts]
    flat = [s for sents in doc_sentences for s in sents]
    if not flat:
        return []
    enc = tokenizer(flat, add_special_tokens=False, return_attention_mask=False)
    documents = []
    k = 0
    for sents in doc_sentences:
        doc = []
        for _ in sents:
            tokens = enc.tokens(k)
            k += 1
            if tokens:
                doc.append(tokens)
        if doc:
            documents.append(doc)
    return documents


def _truncate_seq_pair(tokens_a, tokens_b, max_num_tokens, g):
    """Randomly truncate the longer of A/B from front or back until the pair
    fits. (standard BERT truncation; ref pretrain.py:161-178)"""
    while len(tokens_a) + len(tokens_b) > max_num_tokens:
        trunc = tokens_a if len(tokens_a) > len(tokens_b) else tokens_b
        if len(trunc) <= 1:
            trunc = tokens_b if trunc is tokens_a else tokens_a
            if len(trunc) <= 1:
                break
        if g.random() < 0.5:
            del trunc[0]
        else:
            trunc.pop()


def create_masked_lm_predictions(tokens, vocab_words, g, masked_lm_ratio,
                                 max_predictions_per_seq,
                                 whole_word_masking=False):
    """Apply static 80/10/10 MLM masking in place.

    ``tokens`` is the full [CLS] A [SEP] B [SEP] list. Returns
    (positions, labels): sorted masked positions and their original tokens.
    """
    cand_indexes = []
    for i, token in enumerate(tokens):
        if token in ("[CLS]", "[SEP]"):
            continue
        if (whole_word_masking and cand_indexes
                and token.startswith("##")):
            cand_indexes[-1].append(i)
        else:
            cand_indexes.append([i])

    lrng.shuffle(g, cand_indexes)
    num_to_predict = min(max_predictions_per_seq,
                         max(1, int(round(len(tokens) * masked_lm_ratio))))

    masked = []  # (position, original_token)
    covered = set()
    for index_set in cand_indexes:
        if len(masked) >= num_to_predict:
            break
        if len(masked) + len(index_set) > num_to_predict:
            continue
        if any(i in covered for i in index_set):
            continue
        for i in index_set:
            covered.add(i)
            original = tokens[i]
            r = g.random()
            if r < 0.8:
                tokens[i] = "[MASK]"
            elif r < 0.9:
                tokens[i] = vocab_words[int(g.integers(0, len(vocab_words)))]
            # else: keep original
            masked.append((i, original))
    masked.sort(key=lambda x: x[0])
    positions = [p for p, _ in masked]
    labels = [t for _, t in masked]
    return positions, labels


def create_pairs_from_document(all_documents, document_index, config, g,
                               vocab_words=None):
    """Build NSP pair instances from one document.

    ``all_documents``: the block's documents (population for random-next
    sampling, like the reference's partition). Returns a list of row dicts.
    """
    document = all_documents[document_index]
    max_num_tokens = config.max_seq_length - 3
    target_seq_length = max_num_tokens
    if g.random() < config.short_seq_prob:
        target_seq_length = int(g.integers(2, max_num_tokens + 1))

    instances = []
    current_chunk = []
    current_length = 0
    i = 0
    while i < len(document):
        segment = document[i]
        current_chunk.append(segment)
        current_length += len(segment)
        if i == len(document) - 1 or current_length >= target_seq_length:
            if current_chunk:
                a_end = 1
                if len(current_chunk) >= 2:
                    a_end = int(g.integers(1, len(current_chunk)))
                tokens_a = []
                for j in range(a_end):
                    tokens_a.extend(current_chunk[j])

                tokens_b = []
                if len(current_chunk) == 1 or g.random() < 0.5:
                    is_random_next = True
                    target_b_length = target_seq_length - len(tokens_a)
                    # Pick a different document (bounded retries mirror the
                    # standard algorithm; degenerate single-doc blocks fall
                    # back to self, which truncation keeps well-formed).
                    random_document_index = document_index
                    if len(all_documents) > 1:
                        for _ in range(10):
                            cand = int(g.integers(0, len(all_documents)))
                            if cand != document_index:
                                random_document_index = cand
                                break
                    random_document = all_documents[random_document_index]
                    random_start = int(g.integers(0, len(random_document)))
                    for j in range(random_start, len(random_document)):
                        tokens_b.extend(random_document[j])
                        if len(tokens_b) >= target_b_length:
                            break
                    # Put back the unused tail of the chunk.
                    num_unused_segments = len(current_chunk) - a_end
                    i -= num_unused_segments
                else:
                    is_random_next = False
                    for j in range(a_end, len(current_chunk)):
                        tokens_b.extend(current_chunk[j])

                _truncate_seq_pair(tokens_a, tokens_b, max_num_tokens, g)
                if len(tokens_a) >= 1 and len(tokens_b) >= 1:
                    row = _make_row(tokens_a, tokens_b, is_random_next,
                                    config, g, vocab_words)
                    instances.append(row)
            current_chunk = []
            current_length = 0
        i += 1
    return instances


def _make_row(tokens_a, tokens_b, is_random_next, config, g, vocab_words):
    if config.masking:
        if not vocab_words:
            raise ValueError("masking requires vocab_words")
        tokens = ["[CLS]"] + tokens_a + ["[SEP]"] + tokens_b + ["[SEP]"]
        positions, labels = create_masked_lm_predictions(
            tokens, vocab_words, g, config.masked_lm_ratio,
            config.max_predictions_per_seq, config.whole_word_masking)
        # Read the (possibly masked) A/B back out of the full sequence.
        tokens_a = tokens[1:1 + len(tokens_a)]
        tokens_b = tokens[2 + len(tokens_a):-1]
        row = {
            "A": " ".join(tokens_a),
            "B": " ".join(tokens_b),
            "is_random_next": bool(is_random_next),
            "num_tokens": len(tokens_a) + len(tokens_b) + 3,
            "masked_lm_positions": serialize_np_array(
                np.asarray(positions, dtype=np.uint16)),
            "masked_lm_labels": " ".join(labels),
        }
    else:
        row = {
            "A": " ".join(tokens_a),
            "B": " ".join(tokens_b),
            "is_random_next": bool(is_random_next),
            "num_tokens": len(tokens_a) + len(tokens_b) + 3,
        }
    return row


def pairs_from_documents(documents, config, g, vocab_words=None):
    """All pair instances for a block: ``duplicate_factor`` passes over every
    document (each pass draws fresh randomness -> different pairs/masks,
    ref pretrain.py:386-402), shuffled within the block."""
    rows = []
    for _ in range(config.duplicate_factor):
        for doc_idx in range(len(documents)):
            rows.extend(
                create_pairs_from_document(documents, doc_idx, config, g,
                                           vocab_words=vocab_words))
    lrng.shuffle(g, rows)
    return rows
