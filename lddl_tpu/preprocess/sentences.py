"""Sentence segmentation.

Reference parity: the reference calls ``nltk.tokenize.sent_tokenize``
(lddl/dask/bert/pretrain.py:82, lddl/dask/bart/pretrain.py:82), which needs
the punkt model downloaded at image-build time. TPU pods are often
egress-restricted, so we ship a self-contained rule-based splitter and use
punkt only when its data is actually present on disk.

The rule-based splitter targets the same corpora (Wikipedia / books / news):
split at [.!?] + closing quotes/brackets + whitespace before anything but a
lowercase letter (lowercase continuations split only after ! / ?, punkt
behavior), with guards for common abbreviations, initials, decimal numbers
and ellipses on '.' boundaries, and punkt-style attachment of bare list
enumerators to the preceding sentence. Measured against a punkt oracle:
SPLITTER_DRIFT.json (F1 0.909, benchmarks/splitter_drift.py).
"""

import re

_ABBREVIATIONS = frozenset(
    s.lower() for s in (
        "Mr Mrs Ms Dr Prof Sr Jr St Lt Col Gen Rep Sen Gov Capt Cmdr Sgt "
        "Rev Hon Pres Supt Det Insp "
        "vs etc al eg ie cf ca approx "
        "Jan Feb Mar Apr Jun Jul Aug Sep Sept Oct Nov Dec "
        "Mon Tue Wed Thu Fri Sat Sun "
        "No Vol Fig Eq Sec Ch pp ed eds trans "
        "Inc Ltd Corp Co Dept Univ Assn Bros "
        "a.m p.m U.S U.K U.N E.U Ph.D M.D B.A M.A D.C").split())

# A boundary candidate: terminator + optional closing quotes/brackets
# (group 1), whitespace, then anything. What may FOLLOW a boundary is
# decided in code (see split_sentences): everything except a lowercase
# letter — matching punkt, which splits before bullets/quotes/digits —
# and lowercase too when the terminator is ! or ? (unambiguous enders).
_BOUNDARY = re.compile(r"([.!?][\"'\)\]”’]*)\s+(?=\S)")


def _use_nltk():
    # Opt-in only: merely importing nltk costs seconds of startup, so the
    # punkt path must be requested explicitly.
    import os
    if os.environ.get("LDDL_TPU_SENTENCE_SPLITTER", "") != "nltk":
        return False
    try:
        import nltk.data
        nltk.data.find("tokenizers/punkt")
        return True
    except Exception:
        return False


_NLTK_AVAILABLE = None


def _looks_like_abbreviation(left):
    """Is the text left of the boundary an abbreviation / initial / number
    that should NOT end a sentence?"""
    m = re.search(r"(\S+)$", left)
    if not m:
        return False
    word = m.group(1)
    core = word.rstrip(".").strip("\"'()[]“”‘’")
    if not core:
        return False
    # Single capital letter ("J. Smith") or dotted initials ("U.S.").
    if len(core) == 1 and core.isalpha():
        return True
    if re.fullmatch(r"(?:[A-Za-z]\.)+[A-Za-z]?", core):
        return True
    return core.lower() in _ABBREVIATIONS


# A bare list enumerator right after a boundary ("License. 2. Grant ..."):
# punkt glues it to the PRECEDING sentence ("... License. 2.") and splits
# after it, so we suppress the boundary before it and let the enumerator's
# own dot provide the boundary. <= 3 digits so a bare year still starts a
# sentence.
_ENUMERATOR_NEXT = re.compile(r"\d{1,3}\.[\"'\)\]”’]*\s")


def split_sentences(text):
    """Split ``text`` into sentences (non-empty, stripped)."""
    global _NLTK_AVAILABLE
    if _NLTK_AVAILABLE is None:
        _NLTK_AVAILABLE = _use_nltk()
    if _NLTK_AVAILABLE:
        from nltk.tokenize import sent_tokenize
        return [s.strip() for s in sent_tokenize(text) if s.strip()]

    sentences = []
    start = 0
    for m in _BOUNDARY.finditer(text):
        terminator = text[m.start(1)]
        nxt = text[m.end()]
        # A sentence may start with anything but a lowercase letter
        # (bullets, quotes, digits, uppercase); lowercase continuations
        # only split after the unambiguous enders ! and ?.
        if nxt.islower() and terminator == ".":
            continue
        if _ENUMERATOR_NEXT.match(text, m.end()):
            continue
        # Left context up to and including the terminator character.
        if terminator == "." and _looks_like_abbreviation(
                text[start:m.start(1) + 1]):
            continue
        piece = text[start:m.end(1)].strip()
        if piece:
            sentences.append(piece)
        start = m.end()
    tail = text[start:].strip()
    if tail:
        sentences.append(tail)
    return sentences
