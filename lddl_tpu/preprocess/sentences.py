"""Sentence segmentation.

Reference parity: the reference calls ``nltk.tokenize.sent_tokenize``
(lddl/dask/bert/pretrain.py:82, lddl/dask/bart/pretrain.py:82), which needs
the punkt model downloaded at image-build time. TPU pods are often
egress-restricted, so we ship a self-contained rule-based splitter and use
punkt only when its data is actually present on disk.

The rule-based splitter targets the same corpora (Wikipedia / books / news):
split at [.!?] + closing quotes/brackets + whitespace before anything but a
lowercase letter (lowercase continuations split only after ! / ?, punkt
behavior), with guards for common abbreviations, initials, decimal numbers
and ellipses on '.' boundaries, and punkt-style attachment of bare list
enumerators to the preceding sentence. Measured against a punkt oracle:
SPLITTER_DRIFT.json (F1 0.909, benchmarks/splitter_drift.py).

``--splitter learned`` upgrades to punkt-grade segmentation without the
runtime nltk dependency: ``train_splitter_params`` runs punkt's
unsupervised training (Kiss & Strunk 2006, via nltk's trainer) ONCE on a
deterministic corpus sample, and ``split_sentences_learned`` applies the
punkt decision procedure over the learned tables — in Python here and in
the C++ engine (lddl_native.cpp, fuzz-pinned parity). Measured F1 0.9905
vs an identically-trained punkt; ~11% split+tokenize throughput cost.
"""

import re

_ABBREVIATIONS = frozenset(
    s.lower() for s in (
        "Mr Mrs Ms Dr Prof Sr Jr St Lt Col Gen Rep Sen Gov Capt Cmdr Sgt "
        "Rev Hon Pres Supt Det Insp "
        "vs etc al eg ie cf ca approx "
        "Jan Feb Mar Apr Jun Jul Aug Sep Sept Oct Nov Dec "
        "Mon Tue Wed Thu Fri Sat Sun "
        "No Vol Fig Eq Sec Ch pp ed eds trans "
        "Inc Ltd Corp Co Dept Univ Assn Bros "
        "a.m p.m U.S U.K U.N E.U Ph.D M.D B.A M.A D.C").split())

# A boundary candidate: terminator + optional closing quotes/brackets
# (group 1), whitespace, then anything. What may FOLLOW a boundary is
# decided in code (see split_sentences): everything except a lowercase
# letter — matching punkt, which splits before bullets/quotes/digits —
# and lowercase too when the terminator is ! or ? (unambiguous enders).
_BOUNDARY = re.compile(r"([.!?][\"'\)\]”’]*)\s+(?=\S)")


def _use_nltk():
    # Opt-in only: merely importing nltk costs seconds of startup, so the
    # punkt path must be requested explicitly.
    import os
    if os.environ.get("LDDL_TPU_SENTENCE_SPLITTER", "") != "nltk":
        return False
    try:
        import nltk.data
        nltk.data.find("tokenizers/punkt")
        return True
    except Exception:
        return False


_NLTK_AVAILABLE = None


def _looks_like_abbreviation(left):
    """Is the text left of the boundary an abbreviation / initial / number
    that should NOT end a sentence?"""
    m = re.search(r"(\S+)$", left)
    if not m:
        return False
    word = m.group(1)
    core = word.rstrip(".").strip("\"'()[]“”‘’")
    if not core:
        return False
    # Single capital letter ("J. Smith") or dotted initials ("U.S.").
    if len(core) == 1 and core.isalpha():
        return True
    if re.fullmatch(r"(?:[A-Za-z]\.)+[A-Za-z]?", core):
        return True
    return core.lower() in _ABBREVIATIONS


# A bare list enumerator right after a boundary ("License. 2. Grant ..."):
# punkt glues it to the PRECEDING sentence ("... License. 2.") and splits
# after it, so we suppress the boundary before it and let the enumerator's
# own dot provide the boundary. <= 3 digits so a bare year still starts a
# sentence.
_ENUMERATOR_NEXT = re.compile(r"\d{1,3}\.[\"'\)\]”’]*\s")


class SplitterParams:
    """Corpus-learned punkt parameters driving ``split_sentences_learned``
    (VERDICT round-3 item 7: punkt's own trick is unsupervised training).

    Train once per run on a deterministic corpus sample with
    ``train_splitter_params``; the DECISION procedure then needs no nltk
    and runs at rule-based speed (hash lookups per boundary candidate) —
    mirrored exactly by the C++ engine (fuzz-pinned). Picklable, so pool
    workers receive the same parameters."""

    __slots__ = ("abbrev_types", "collocations", "sent_starters",
                 "ortho_context")

    def __init__(self, abbrev_types=(), collocations=(), sent_starters=(),
                 ortho_context=None):
        self.abbrev_types = frozenset(abbrev_types)
        self.collocations = frozenset(tuple(c) for c in collocations)
        self.sent_starters = frozenset(sent_starters)
        self.ortho_context = dict(ortho_context or {})

    def __reduce__(self):
        return (SplitterParams, (self.abbrev_types, self.collocations,
                                 self.sent_starters, self.ortho_context))

    def serialize(self):
        """Line-oriented UTF-8 blob for the native engine (and for the
        fingerprint): a 'P1' version header, then 'A <abbr>' /
        'C <t1> <t2>' / 'S <starter>' / 'O <type> <flags>' lines, sorted
        for determinism. The header makes even an empty-but-valid params
        object serialize non-empty, so the native engine cannot confuse
        it with "no params = rules splitter" (ADVICE r4); the C++ parser
        skips unknown tags, so 'P1' needs no native-side handling."""
        lines = ["P1"]
        for a in sorted(self.abbrev_types):
            lines.append("A " + a)
        for t1, t2 in sorted(self.collocations):
            lines.append("C {} {}".format(t1, t2))
        for s in sorted(self.sent_starters):
            lines.append("S " + s)
        for ty, flags in sorted(self.ortho_context.items()):
            if flags:
                lines.append("O {} {}".format(ty, int(flags)))
        return "\n".join(lines).encode("utf-8")


def train_splitter_params(texts, include_all_collocs=True):
    """Unsupervised punkt training (nltk's PunktTrainer — the library is
    the trainer; the decision procedure below is ours and nltk-free) on
    an in-memory corpus sample. Deterministic in the sample. The
    reference gets these statistics from the PRETRAINED punkt model
    (lddl/dask/bert/pretrain.py:82); on egress-restricted TPU pods we
    learn them from the corpus itself, which is how that model was built
    in the first place (Kiss & Strunk 2006)."""
    from nltk.tokenize.punkt import PunktTrainer
    trainer = PunktTrainer()
    trainer.INCLUDE_ALL_COLLOCS = include_all_collocs
    trainer.train("\n".join(texts), finalize=False)
    p = trainer.get_params()
    return SplitterParams(p.abbrev_types, p.collocations, p.sent_starters,
                          p.ortho_context)


# punkt orthographic-context flags (Kiss & Strunk 2006).
_ORTHO_BEG_UC = 1 << 1
_ORTHO_MID_UC = 1 << 2
_ORTHO_UNK_UC = 1 << 3
_ORTHO_BEG_LC = 1 << 4
_ORTHO_MID_LC = 1 << 5
_ORTHO_UNK_LC = 1 << 6
_ORTHO_UC = _ORTHO_BEG_UC | _ORTHO_MID_UC | _ORTHO_UNK_UC
_ORTHO_LC = _ORTHO_BEG_LC | _ORTHO_MID_LC | _ORTHO_UNK_LC

_NUM_TYPE = re.compile(r"^-?[\.,]?\d[\d,\.-]*\.?$")
_INITIAL = re.compile(r"^[^\W\d]\.$")
_ELLIPSIS = re.compile(r"^\.\.+$")
_PUNCT_TOK = re.compile(r"^[;,:.!?]$")
_WORD_RUN = re.compile(r"\S+")


def _punkt_type(tok):
    """punkt token type: lowercased, numbers collapsed to ##number##."""
    return _NUM_TYPE.sub("##number##", tok.lower())


def _first_case(tok):
    c = tok[:1]
    if c.isupper():
        return "upper"
    if c.islower():
        return "lower"
    return "none"


def _ortho_heuristic(params, tok2, ty2_nosent):
    """punkt 4.1.1: does ``tok2`` look like a sentence start?
    True | False | None (unknown)."""
    if _PUNCT_TOK.match(tok2):
        return False
    ortho = params.ortho_context.get(ty2_nosent, 0)
    case = _first_case(tok2)
    if case == "upper" and (ortho & _ORTHO_LC) \
            and not (ortho & _ORTHO_MID_UC):
        return True
    if case == "lower" and ((ortho & _ORTHO_UC)
                            or not (ortho & _ORTHO_BEG_LC)):
        return False
    return None


def _punkt_boundary(params, w1_tok, w2_tok):
    """Sentence boundary after period-final token ``w1_tok``? The punkt
    first-pass classification + second-pass annotation (4.1.1-4.1.3,
    4.2), decision only — all statistics come from ``params``."""
    ty1 = _punkt_type(w1_tok)
    ty1_nop = ty1[:-1] if ty1.endswith(".") else ty1
    is_ellipsis = bool(_ELLIPSIS.match(w1_tok))
    is_initial = bool(_INITIAL.match(w1_tok))
    abbr = (ty1_nop in params.abbrev_types
            or ("-" in ty1_nop
                and ty1_nop.rsplit("-", 1)[-1] in params.abbrev_types))
    sentbreak = not (abbr or is_ellipsis)  # first pass
    if not w2_tok:
        return sentbreak
    ty2 = _punkt_type(w2_tok)
    ty2_nosent = ty2[:-1] if ty2.endswith(".") else ty2
    if (ty1_nop, ty2_nosent) in params.collocations:     # 4.1.2
        return False
    if (abbr or is_ellipsis) and not is_initial:         # 4.2 + 4.1.1/3
        if _ortho_heuristic(params, w2_tok, ty2_nosent) is True:
            return True
        if _first_case(w2_tok) == "upper" \
                and ty2_nosent in params.sent_starters:
            return True
        return sentbreak
    if is_initial or ty1_nop == "##number##":            # 4.1.1 for these
        oh = _ortho_heuristic(params, w2_tok, ty2_nosent)
        if oh is False:
            return False
        if (oh is None and is_initial
                and _first_case(w2_tok) == "upper"
                and not (params.ortho_context.get(ty2_nosent, 0)
                         & _ORTHO_LC)):
            return False
    return sentbreak


def _punkt_word_before(left):
    """Last word-token of ``left`` the way punkt tokenizes it: closing
    wrappers split off, the terminating period kept on the token."""
    m = re.search(r"(\S+)$", left)
    if not m:
        return ""
    w = m.group(1).rstrip("\"')]}”’*")
    return w if w.endswith(".") else w + "."


def split_sentences_learned(text, params):
    """Split ``text`` with corpus-learned punkt parameters. Same boundary
    CANDIDATES as the rule-based splitter (terminator + closers +
    whitespace); every '.' candidate is decided by the punkt procedure,
    ! and ? always split (punkt sent_end_chars behavior). Measured
    F1 0.99 against an identically-trained nltk punkt
    (SPLITTER_DRIFT.json, learned entry)."""
    sentences = []
    start = 0
    for m in _BOUNDARY.finditer(text):
        if text[m.start(1)] == ".":
            w1 = _punkt_word_before(text[start:m.start(1) + 1])
            nxt = _WORD_RUN.match(text, m.end())
            w2_raw = nxt.group(0) if nxt else ""
            w2 = w2_raw.lstrip("\"'([{“‘*") or w2_raw
            if not _punkt_boundary(params, w1, w2):
                continue
        piece = text[start:m.end(1)].strip()
        if piece:
            sentences.append(piece)
        start = m.end()
    tail = text[start:].strip()
    if tail:
        sentences.append(tail)
    return sentences


def split_sentences(text):
    """Split ``text`` into sentences (non-empty, stripped)."""
    global _NLTK_AVAILABLE
    if _NLTK_AVAILABLE is None:
        _NLTK_AVAILABLE = _use_nltk()
    if _NLTK_AVAILABLE:
        from nltk.tokenize import sent_tokenize
        return [s.strip() for s in sent_tokenize(text) if s.strip()]

    sentences = []
    start = 0
    for m in _BOUNDARY.finditer(text):
        terminator = text[m.start(1)]
        nxt = text[m.end()]
        # A sentence may start with anything but a lowercase letter
        # (bullets, quotes, digits, uppercase); lowercase continuations
        # only split after the unambiguous enders ! and ?.
        if nxt.islower() and terminator == ".":
            continue
        if _ENUMERATOR_NEXT.match(text, m.end()):
            continue
        # Left context up to and including the terminator character.
        if terminator == "." and _looks_like_abbreviation(
                text[start:m.start(1) + 1]):
            continue
        piece = text[start:m.end(1)].strip()
        if piece:
            sentences.append(piece)
        start = m.end()
    tail = text[start:].strip()
    if tail:
        sentences.append(tail)
    return sentences
