"""Sequence-length binning math + binned parquet sink.

Reference parity: lddl/dask/bert/binning.py — the reference forks Dask's
to_parquet internals to write one file per (partition, bin) named
``part.N.parquet_<bin_id>``. We own our sink, so binning is ~40 lines
instead of a 509-line Dask fork: group rows by bin id, write one table per
bin with the same naming protocol.

Bin math (must match the loader and balancer):
    nbins  = target_seq_length // bin_size
    bin_id = min((num_tokens - 1) // bin_size, nbins - 1)
so bin k holds sequences of length (k*bin_size, (k+1)*bin_size], and the
last bin also absorbs any longer stragglers. On TPU this is the shape
story: pad bin k to (k+1)*bin_size and XLA compiles one program per bin,
bounded by nbins (SURVEY.md §5 "Long-context").
"""

import os

import numpy as np
import pyarrow as pa

from ..resilience.io import write_table_atomic

BASE_SCHEMA = {
    "A": pa.string(),
    "B": pa.string(),
    "is_random_next": pa.bool_(),
    "num_tokens": pa.uint16(),
}
MASKING_SCHEMA = {
    "masked_lm_positions": pa.binary(),
    "masked_lm_labels": pa.string(),
}
# Schema v2: token-id columnar twins of the text columns, ALONGSIDE them
# (a v2 shard is a strict superset of a v1 shard, so v1 readers keep
# working). The loader consumes these zero-copy instead of re-tokenizing
# the strings every epoch.
TOKEN_ID_SCHEMA = {
    "A_ids": pa.list_(pa.int32()),
    "B_ids": pa.list_(pa.int32()),
}
MASKING_TOKEN_ID_SCHEMA = {
    "masked_lm_positions_ids": pa.list_(pa.int32()),
    "masked_lm_label_ids": pa.list_(pa.int32()),
}
# Column names whose presence marks a schema-v2 shard (BERT / BART /
# offline-packed — packed shards are inherently id-columnar).
SCHEMA_V2_MARKERS = ("A_ids", "sentence_ids", "pack_a_lens")

# Offline-packed shards (preprocess/packing.py): every parquet row is one
# already-packed fixed-token-budget training row. ``input_ids`` stores
# the FULL interleaved content — [CLS] A [SEP] B [SEP] per sample,
# specials baked in at pack time — and the boundary columns carry
# per-sample segment lengths so the loader (and the model's
# block-diagonal attention masking) reconstructs per-sample segment ids
# without repacking or any tokenizer knowledge. Masking positions are
# stored ROW-relative. Packed shards are inherently schema v2 (id
# columns only — per-sample text columns have no row-level meaning).
PACKED_BASE_SCHEMA = {
    "input_ids": pa.list_(pa.int32()),
    "pack_a_lens": pa.list_(pa.int32()),
    "pack_b_lens": pa.list_(pa.int32()),
    "pack_nsp": pa.list_(pa.int32()),
    "num_tokens": pa.uint16(),
}
PACKED_MASKING_SCHEMA = {
    "masked_lm_positions_ids": pa.list_(pa.int32()),
    "masked_lm_label_ids": pa.list_(pa.int32()),
    "pack_mask_lens": pa.list_(pa.int32()),
}
# Column whose presence marks an offline-packed shard.
PACKED_MARKER = "pack_a_lens"


def schema_version_of_names(names):
    """1 or 2 from a parquet schema's column names (per-shard detection:
    the loader and the manifest's ``__meta__`` entry both use this)."""
    names = set(names)
    return 2 if any(m in names for m in SCHEMA_V2_MARKERS) else 1


def write_options_for_names(names):
    """The pq.write_table layout options for a shard with these column
    names: the tuned v2 layout for id-columnar schemas, the legacy
    (byte-pinned) layout for v1. One rule so the sink and the balancer's
    row-wise rewrites cannot drift."""
    return (dict(V2_PARQUET_WRITE_OPTIONS)
            if schema_version_of_names(names) == 2 else {})


def num_bins(target_seq_length, bin_size):
    if bin_size is None:
        return 1
    if bin_size <= 0 or target_seq_length % bin_size != 0:
        raise ValueError(
            "bin_size must divide target_seq_length ({} % {} != 0)".format(
                target_seq_length, bin_size))
    return target_seq_length // bin_size


def bin_id_of_num_tokens(num_tokens, bin_size, nbins):
    """Scalar or ndarray; the ONE definition of the bin formula (loader,
    balancer, and both sink paths must agree)."""
    return np.minimum(np.maximum(num_tokens - 1, 0) // bin_size, nbins - 1)


def make_packed_schema(masking=False, pack_seq_length=None,
                       max_per_row=None):
    """Schema of an offline-packed shard; the row shape is stamped into
    the schema metadata so it survives the balancer's row-wise
    concat/slice and the manifest can record it without guessing."""
    from .packing import PACK_META_MAX_PER_ROW, PACK_META_SEQ_LENGTH
    fields = dict(PACKED_BASE_SCHEMA)
    if masking:
        fields.update(PACKED_MASKING_SCHEMA)
    metadata = None
    if pack_seq_length is not None:
        metadata = {
            PACK_META_SEQ_LENGTH: str(int(pack_seq_length)).encode(),
            PACK_META_MAX_PER_ROW: str(int(max_per_row or 8)).encode(),
        }
    return pa.schema(list(fields.items()), metadata=metadata)


def make_schema(masking=False, binned=False, token_ids=False):
    fields = dict(BASE_SCHEMA)
    if masking:
        fields.update(MASKING_SCHEMA)
    if token_ids:
        fields.update(TOKEN_ID_SCHEMA)
        if masking:
            fields.update(MASKING_TOKEN_ID_SCHEMA)
    if binned:
        fields["bin_id"] = pa.int64()
    return pa.schema(list(fields.items()))


# One fact for the shard sink codec (binning, balancer, BART all import
# it; it also feeds the resume fingerprints): lz4 measured write -28% /
# read -66% vs snappy at +8% size — see the README attribution note.
DEFAULT_PARQUET_COMPRESSION = "lz4"

# Tuned page layout for the id-columnar shard schemas (v2 and packed).
# Measured on the bench corpus (see the README sink-architecture note):
# dictionary encoding buys little on mostly-unique joined-token strings
# and Zipf id lists but costs a dict-build pass per column chunk, page
# statistics are never consulted (shards are read whole), and the v2
# data-page header halves the per-page framing — together ~15% off the
# parquet encode step at a modest size cost. Applied ONLY when the
# schema is v2/packed: v1 shard bytes are pinned by the golden-spool
# tests and stay on the legacy layout, so v1 resume fingerprints (and
# pre-upgrade crashed v1 runs) are untouched. SINK_PROFILE_V2 feeds the
# v2 resume fingerprints — changing the layout is a deliberate one-time
# fingerprint bump.
V2_PARQUET_WRITE_OPTIONS = {
    "use_dictionary": False,
    "write_statistics": False,
    "data_page_version": "2.0",
}
SINK_PROFILE_V2 = "lz4.dpv2.nodict.nostats"


def write_shard_columns(columns, n, out_dir, part_id, masking=False,
                        bin_size=None, target_seq_length=128,
                        compression=DEFAULT_PARQUET_COMPRESSION,
                        pack_seq_length=None, pack_max_per_row=8,
                        pack_special_ids=None):
    """Write one block's COLUMNS ({name: list-or-ndarray}) as
    part.<part_id>.parquet[_<bin>] files — the columnar fast path (no
    per-row dicts anywhere between sample construction and arrow).

    Returns {written_path: num_rows}. With binning enabled, only non-empty
    bins produce a file (ref: binning.py:353-431); the balancer later
    equalizes the global per-bin file sets. With ``pack_seq_length`` set
    (mutually exclusive with binning — packing subsumes it), the sink
    first-fit-decreasing-packs the bucket into fixed-budget rows and the
    row count IS the packed row count (preprocess/packing.py).
    """
    if pack_seq_length is not None:
        if bin_size is not None:
            raise ValueError("pack_seq_length and bin_size are exclusive "
                             "(packing subsumes binning)")
        if pack_special_ids is None:
            raise ValueError("the packed sink needs pack_special_ids="
                             "(cls_id, sep_id) to interleave row content")
        from .packing import write_packed_shard
        return write_packed_shard(columns, n, out_dir, part_id,
                                  pack_seq_length, pack_max_per_row,
                                  pack_special_ids[0], pack_special_ids[1],
                                  masking=masking, compression=compression)
    os.makedirs(out_dir, exist_ok=True)
    written = {}
    token_ids = "A_ids" in columns  # schema v2 sniffed off the columns
    write_options = write_options_for_names(columns)
    if bin_size is None:
        schema = make_schema(masking=masking, binned=False,
                             token_ids=token_ids)
        path = os.path.join(out_dir, "part.{}.parquet".format(part_id))
        write_table_atomic(
            pa.table({name: columns.get(name, []) for name in schema.names},
                     schema=schema),
            path, compression=compression, **write_options)
        written[path] = n
        return written

    if n == 0:  # binned: empty buckets produce no files (like the old
        return written  # row path and ref binning.py:353-431)

    nbins = num_bins(target_seq_length, bin_size)
    schema = make_schema(masking=masking, binned=True, token_ids=token_ids)
    num_tokens = np.asarray(columns["num_tokens"], dtype=np.int64)
    bins = bin_id_of_num_tokens(num_tokens, bin_size, nbins)
    # ONE stable sort by bin + zero-copy slices per bin, instead of one
    # gather per (bin, column): row order within a bin is identical
    # (stable sort of equal keys == nonzero order), so shard bytes are
    # unchanged while Arrow takes drop from bins x columns to columns.
    order = np.argsort(bins, kind="stable")
    bins_sorted = bins[order]
    sorted_cols = {}
    for name in schema.names:
        if name == "bin_id":
            continue
        col = columns[name]
        if isinstance(col, pa.Array):
            sorted_cols[name] = col.take(order)
        elif isinstance(col, np.ndarray):
            sorted_cols[name] = col[order]
        else:
            # numpy integer indices subscript plain lists directly —
            # no need to materialize the order as a Python list first.
            sorted_cols[name] = [col[i] for i in order]
    boundaries = np.searchsorted(bins_sorted, np.arange(nbins + 1))
    for b in np.unique(bins):
        lo, hi = int(boundaries[b]), int(boundaries[b + 1])
        sub = {}
        for name in schema.names:
            if name == "bin_id":
                sub[name] = np.full(hi - lo, b, dtype=np.int64)
                continue
            col = sorted_cols[name]
            if isinstance(col, pa.Array):
                sub[name] = col.slice(lo, hi - lo)  # zero-copy
            else:
                sub[name] = col[lo:hi]
        path = os.path.join(out_dir,
                            "part.{}.parquet_{}".format(part_id, int(b)))
        # Atomic publish (tmp + fsync + replace): a SIGKILLed worker can
        # never leave a torn shard under its final name for the resume's
        # exact-prefix cleanup to miss.
        write_table_atomic(pa.table(sub, schema=schema), path,
                           compression=compression, **write_options)
        written[path] = hi - lo
    return written

