"""Sequence-length binning math + binned parquet sink.

Reference parity: lddl/dask/bert/binning.py — the reference forks Dask's
to_parquet internals to write one file per (partition, bin) named
``part.N.parquet_<bin_id>``. We own our sink, so binning is ~40 lines
instead of a 509-line Dask fork: group rows by bin id, write one table per
bin with the same naming protocol.

Bin math (must match the loader and balancer):
    nbins  = target_seq_length // bin_size
    bin_id = min((num_tokens - 1) // bin_size, nbins - 1)
so bin k holds sequences of length (k*bin_size, (k+1)*bin_size], and the
last bin also absorbs any longer stragglers. On TPU this is the shape
story: pad bin k to (k+1)*bin_size and XLA compiles one program per bin,
bounded by nbins (SURVEY.md §5 "Long-context").
"""

import os

import pyarrow as pa
import pyarrow.parquet as pq

BASE_SCHEMA = {
    "A": pa.string(),
    "B": pa.string(),
    "is_random_next": pa.bool_(),
    "num_tokens": pa.uint16(),
}
MASKING_SCHEMA = {
    "masked_lm_positions": pa.binary(),
    "masked_lm_labels": pa.string(),
}


def num_bins(target_seq_length, bin_size):
    if bin_size is None:
        return 1
    if bin_size <= 0 or target_seq_length % bin_size != 0:
        raise ValueError(
            "bin_size must divide target_seq_length ({} % {} != 0)".format(
                target_seq_length, bin_size))
    return target_seq_length // bin_size


def bin_id_of_num_tokens(num_tokens, bin_size, nbins):
    return min(max(num_tokens - 1, 0) // bin_size, nbins - 1)


def make_schema(masking=False, binned=False):
    fields = dict(BASE_SCHEMA)
    if masking:
        fields.update(MASKING_SCHEMA)
    if binned:
        fields["bin_id"] = pa.int64()
    return pa.schema(list(fields.items()))


def rows_to_table(rows, schema):
    columns = {
        name: [r.get(name) for r in rows] for name in schema.names
    }
    return pa.table(columns, schema=schema)


def write_shard(rows, out_dir, part_id, masking=False, bin_size=None,
                target_seq_length=128, compression="snappy"):
    """Write one block's rows as part.<part_id>.parquet[_<bin>] files.

    Returns {written_path: num_rows}. With binning enabled, only non-empty
    bins produce a file (ref: binning.py:353-431); the balancer later
    equalizes the global per-bin file sets.
    """
    os.makedirs(out_dir, exist_ok=True)
    written = {}
    if bin_size is None:
        schema = make_schema(masking=masking, binned=False)
        path = os.path.join(out_dir, "part.{}.parquet".format(part_id))
        pq.write_table(rows_to_table(rows, schema), path,
                       compression=compression)
        written[path] = len(rows)
        return written

    nbins = num_bins(target_seq_length, bin_size)
    schema = make_schema(masking=masking, binned=True)
    by_bin = {}
    for r in rows:
        b = bin_id_of_num_tokens(r["num_tokens"], bin_size, nbins)
        r = dict(r)
        r["bin_id"] = b
        by_bin.setdefault(b, []).append(r)
    for b, bin_rows in sorted(by_bin.items()):
        path = os.path.join(out_dir, "part.{}.parquet_{}".format(part_id, b))
        pq.write_table(rows_to_table(bin_rows, schema), path,
                       compression=compression)
        written[path] = len(bin_rows)
    return written
