from .readers import Block, plan_blocks, read_documents, split_id_text
from .sentences import (SplitterParams, split_sentences,
                        split_sentences_learned,
                        train_splitter_params)
from .tokenizer import get_tokenizer, build_wordpiece_vocab
from .bert import (
    BertPretrainConfig,
    create_pairs_from_document,
    create_masked_lm_predictions,
)
from .binning import bin_id_of_num_tokens, num_bins
from .runner import run_bert_preprocess, run_sharded_pipeline
from .bart import BartPretrainConfig, run_bart_preprocess

__all__ = [
    "Block",
    "plan_blocks",
    "read_documents",
    "split_id_text",
    "SplitterParams",
    "split_sentences",
    "split_sentences_learned",
    "train_splitter_params",
    "get_tokenizer",
    "build_wordpiece_vocab",
    "BertPretrainConfig",
    "create_pairs_from_document",
    "create_masked_lm_predictions",
    "bin_id_of_num_tokens",
    "num_bins",
    "run_bert_preprocess",
    "run_sharded_pipeline",
    "BartPretrainConfig",
    "run_bart_preprocess",
]
