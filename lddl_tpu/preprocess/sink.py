"""Asynchronous durable shard sink: the double-buffered writer thread.

PROFILE_PREPROCESS.json (post-PR 9) shows ~40% of single-worker preprocess
wall inside the durable sink — parquet encode + fsync + atomic publish +
spool IO — executed *serially between buckets*: tokenize bucket N+1 waits
for bucket N's bytes to hit stable storage. This module takes the sink off
the critical path: a :class:`ShardWriter` owns ONE writer thread and a
bounded queue (depth 2 by default — classic double buffering), and the
producer hands it *deferred publish closures* instead of writing inline.
While the writer encodes/fsyncs/publishes bucket N, the producer
tokenizes and masks bucket N+1; parquet encode, lz4, fsync and the file
writes all release the GIL, so the overlap is real even in one process.

Invariants (the writer is pure *deferred execution* of the existing
``resilience.io`` publish path — nothing about WHAT is written changes):

- **Byte identity.** Closures run in FIFO submit order on a single
  thread, so shard bytes, file names and manifests are identical to a
  serial run (pinned by tests/test_sink.py across binned / packed / BART
  / schema-v1 golden configs).
- **Atomic publish.** Closures call ``write_table_atomic`` /
  ``atomic_write`` like the inline path; the analyzer's publish-path-flow
  rule models the submit boundary (enqueue -> deferred call) so a raw
  ``pq.write_table`` laundered through :meth:`ShardWriter.submit` is
  still flagged (lddl_tpu/analysis/dataflow.py DEFERRED_CALL_MODULES).
- **Fencing.** In elastic mode every deferred closure carries the unit's
  lease fence; the writer re-checks it (``leases.verify_at`` via the
  fence closure) immediately before executing the deferred publish — not
  just at enqueue time — so a holder whose lease was stolen between
  tokenize and publish self-terminates instead of publishing.
- **Errors fail the unit loudly.** A closure that raises (injected
  ``eio``/``truncate`` faults included — ``resilience.faults`` sites fire
  on the writer thread) marks its unit failed, remaining closures of that
  unit are skipped, and the failure surfaces to the producer at the next
  ``completed()``/``drain()`` — always BEFORE the unit's ledger record is
  written, so a resume redoes the unit. Later units are unaffected
  (per-unit fault isolation, as in the inline path).
- **Journal ordering.** Unit ledger records (and the elastic claim
  loop's fence-checked journal publish) are written only after the
  writer drained that unit's closures: ``_run_group`` drains its own
  writer before returning, and the static serial path journals from
  ``completed()``/``drain()`` results only.

Knobs and telemetry::

    LDDL_TPU_SINK_DEPTH   queue depth (default 2; 0 disables the thread —
                          closures then run inline, byte-identical)
    preprocess_sink_queue_depth          gauge: queued tasks high-water
    preprocess_sink_stall_seconds_total  counter: producer seconds blocked
                                         on a full queue or final drain
    preprocess_sink_write_seconds_total  counter: writer seconds inside
                                         deferred publish closures
"""

import os
import queue
import threading
import time

from .. import observability as obs
from ..resilience import faults

_END = object()  # end-of-unit marker sentinel

DEFAULT_DEPTH = 2

# Process-local aggregate stats (monotonic-clock durations only — never
# shard bytes): read by benchmarks/profile_preprocess.py to embed the
# sink-overlap block in PROFILE_PREPROCESS.json even when the metrics
# registry is not armed.
_STATS_LOCK = threading.Lock()
_STATS = {"write_s": 0.0, "stall_s": 0.0, "tasks": 0, "units": 0,
          "failed_units": 0}


def stats_snapshot():
    """Copy of the process-cumulative sink stats (profiling aid), tagged
    with the storage backend the deferred publishes route through
    (resilience/backend.py — write_table_atomic/atomic_write inside each
    closure dispatch on it, so 'which store did these seconds go to' is
    part of the measurement's identity)."""
    from ..resilience import backend as storage
    with _STATS_LOCK:
        snap = dict(_STATS)
    snap["storage_backend"] = storage.active_name()
    return snap


def _stats_add(**deltas):
    with _STATS_LOCK:
        for k, v in deltas.items():
            _STATS[k] += v


def sink_depth():
    """The configured queue depth; 0 means "run closures inline" (the
    serial reference behavior — tests pin async == inline bytes)."""
    try:
        return max(0, int(os.environ.get("LDDL_TPU_SINK_DEPTH",
                                         DEFAULT_DEPTH)))
    except ValueError:
        return DEFAULT_DEPTH


class DeferredUnit:
    """Sentinel a unit function returns when its writes (and therefore
    its result dict) will materialize on the shard writer: the unit
    completes at a later ``completed()``/``drain()`` call."""

    __slots__ = ("unit",)

    def __init__(self, unit):
        self.unit = unit


class ShardWriter:
    """One writer thread + bounded FIFO queue of deferred publish tasks.

    Producer API (single producer thread):
        ``submit(unit, fn, fence=None)``  enqueue one deferred publish;
            ``fn() -> {path: rows}`` accumulates into the unit's result.
        ``end_unit(unit)``  mark the unit's last task as enqueued.
        ``completed()``  -> [(unit, written, exc)] units finished SO FAR.
        ``drain()``  block until the queue is empty, then ``completed()``.
        ``close()``  stop the thread (idempotent; call from ``finally``).
    """

    def __init__(self, depth=None, name="shard-sink"):
        self.depth = sink_depth() if depth is None else max(0, int(depth))
        self._inline = self.depth == 0
        self._queue = None
        self._thread = None
        self._lock = threading.Lock()
        self._open = {}   # unit -> {"written": dict, "exc": Exception|None}
        self._done = []   # [(unit, written, exc)] awaiting collection
        self._order = []  # units in end_unit order (completion order)
        if not self._inline:
            self._queue = queue.Queue(maxsize=self.depth)
            self._thread = threading.Thread(
                target=self._run, name=name, daemon=True)
            self._thread.start()

    # ------------------------------------------------------------ producer

    def submit(self, unit, fn, fence=None):
        state = self._open.setdefault(unit,
                                      {"written": {}, "exc": None})
        task = (unit, fn, fence)
        if self._inline:
            self._execute(state, task)
            return
        self._put(task)

    def end_unit(self, unit):
        state = self._open.setdefault(unit,
                                      {"written": {}, "exc": None})
        if self._inline:
            self._finish(unit, state)
            return
        self._put((unit, _END, None))

    def completed(self):
        """Units whose last task finished since the previous call, in
        completion (== submit) order. Thread-safe pop."""
        with self._lock:
            done, self._done = self._done, []
        return done

    def drain(self):
        """Block until every enqueued task ran; return ``completed()``.
        Producer stall time (the tail the overlap could not hide) is
        accounted to ``preprocess_sink_stall_seconds_total``."""
        if not self._inline:
            t0 = time.monotonic()
            self._queue.join()
            self._note_stall(time.monotonic() - t0)
        return self.completed()

    def close(self):
        if self._thread is not None:
            self._queue.join()
            self._queue.put(None)  # thread shutdown sentinel
            self._thread.join()
            self._thread = None

    def _put(self, task):
        q = self._queue
        if obs.enabled():
            obs.set_gauge("preprocess_sink_queue_depth", q.qsize() + 1)
        try:
            q.put_nowait(task)
            return
        except queue.Full:
            pass
        t0 = time.monotonic()
        q.put(task)  # blocks: this is the double-buffer back-pressure
        self._note_stall(time.monotonic() - t0)

    def _note_stall(self, seconds):
        if seconds <= 0:
            return
        _stats_add(stall_s=seconds)
        if obs.enabled():
            obs.inc("preprocess_sink_stall_seconds_total", seconds)

    # ------------------------------------------------------- writer thread

    def _run(self):
        while True:
            task = self._queue.get()
            if task is None:
                self._queue.task_done()
                return
            unit = task[0]
            state = self._open.get(unit)
            try:
                if task[1] is _END:
                    self._finish(unit, state)
                else:
                    self._execute(state, task)
            # Defense in depth: _execute/_finish catch their own errors,
            # but the writer thread must NEVER die with tasks queued —
            # queue.join() in drain()/close() would deadlock the
            # producer with no diagnostic. Anything unforeseen becomes a
            # completed-with-error unit instead.
            except Exception as e:  # noqa: BLE001
                with self._lock:
                    self._done.append((unit, {}, e))
            finally:
                self._queue.task_done()

    def _execute(self, state, task):
        unit, fn, fence = task
        if state["exc"] is not None:
            return  # unit already failed: skip its remaining publishes
        t0 = time.monotonic()
        try:
            # Chaos site for "mid-deferred-publish" fault placement
            # (tests park eio/stall/kill here); the closure's own
            # resilience.io calls carry the regular open/replace sites.
            faults.fault_point("sink-write", str(unit))
            if fence is not None and not fence():
                from ..resilience.leases import LeaseLost
                raise LeaseLost(
                    "unit {} was stolen before its deferred publish; "
                    "self-terminating".format(unit))
            res = fn()
            if res:
                state["written"].update(res)
        except Exception as e:  # noqa: BLE001 - surfaces at the producer
            state["exc"] = e
        finally:
            _stats_add(write_s=time.monotonic() - t0, tasks=1)
            if obs.enabled():
                obs.inc("preprocess_sink_write_seconds_total",
                        time.monotonic() - t0)

    def _finish(self, unit, state):
        if state is None:
            # Unmatched/duplicate end_unit: a caller bug, but it must
            # surface as a loud unit failure, not kill the writer thread
            # (which would deadlock the producer's queue.join()).
            state = {"written": {}, "exc": RuntimeError(
                "unmatched end_unit for {!r} (no open unit)".format(unit))}
        self._open.pop(unit, None)
        _stats_add(units=1,
                   failed_units=1 if state["exc"] is not None else 0)
        with self._lock:
            self._done.append((unit, state["written"], state["exc"]))


def collect_into(done, record, record_failure):
    """Route ``completed()`` tuples into the runner's per-unit result /
    failure recorders (the unit is journaled by ``record`` only here —
    i.e. only after its writes drained)."""
    for unit, written, exc in done:
        if exc is None:
            record(unit, written)
        else:
            record_failure(unit, "{}: {}".format(type(exc).__name__, exc))
