"""Offline corpus-level sequence packing: the preprocess-stage FFD sink.

The load-time packer (loader/bert.PackedBertLoader + ops/packing.
StreamPacker) repacks every epoch with a streaming first-fit — correct,
but it makes the packed path the SLOWEST loader configuration
(LOADER_BENCH.json: 29.8k samples/s packed vs 70.4k unbinned v2) because
per-sample Python packing runs on the hot path. This module moves the
packing OFFLINE: the preprocess sink sorts each bucket's instance
lengths descending and first-fit-decreasing-packs them into
fixed-token-budget rows, emitting schema-v2 shards whose parquet rows
ARE already-packed training rows. The loader then streams rows zero-copy
through the ordinary schema-v2 decode path (loader/bert.
BertPrepackedCollate) — no Python-side repacking at all, and pad_ratio
is the corpus-level FFD fill, at or below what the streaming packer
achieves.

Packed row schema (all id columns; packed shards are inherently
schema v2 — see binning.PACKED_BASE_SCHEMA):

    input_ids                  list<int32>  the row's FULL interleaved
                                            content: [CLS] A [SEP] B
                                            [SEP] per sample, specials
                                            baked in at pack time
    pack_a_lens / pack_b_lens  list<int32>  per-sample boundary columns
    pack_nsp                   list<int32>  per-sample is_random_next
    num_tokens                 uint16       used tokens in the row
    masked_lm_positions_ids    list<int32>  (static masking) ROW-relative
    masked_lm_label_ids        list<int32>  positions / label ids, concat
    pack_mask_lens             list<int32>  per-sample masking counts

The boundary columns let the loader (and the model's block-diagonal
attention masking) reconstruct per-sample segment ids without touching
token bytes or knowing the tokenizer, and the interleaved ``input_ids``
content means loading a row is one prefix scatter — no per-sample
assembly. The row shape ``(pack_seq_length, pack_max_per_row)`` is
stamped into the parquet schema metadata (PACK_META_* keys) so the
balancer's row-wise concat/slice carries it along and the manifest's
``__meta__`` can record it without guessing.

Determinism: FFD is pure sorting + first-fit (no RNG, no clock, no FS
order — lengths arrive in the bucket's canonical keyed-shuffle order and
ties break on that position), so packed shard bytes satisfy the same
resume/manifest invariants as every other sink; the pack parameters ride
the processor resume fingerprint.
"""

import os

import numpy as np
import pyarrow as pa

from .. import observability as obs
from ..resilience.io import write_table_atomic
from .arrowcols import gather_list_slices, int32_list_array

# Parquet schema-metadata keys stamping the packed row shape into every
# packed shard (strings; read back by pack_shape_of_schema).
PACK_META_SEQ_LENGTH = b"lddl_pack_seq_length"
PACK_META_MAX_PER_ROW = b"lddl_pack_max_per_row"


def ffd_pack(lengths, budget, max_per_row):
    """First-fit-decreasing bin packing of ``lengths`` into rows of
    capacity ``budget`` holding at most ``max_per_row`` samples.

    Deterministic: samples are visited in (length desc, original index)
    order and each drops into the FIRST open row with room (rows in
    creation order). Returns ``(sample_order, samples_per_row)`` —
    ``sample_order`` concatenates every row's sample indices in placement
    order, ``samples_per_row[r]`` counts row ``r``'s samples — the exact
    gather plan pack_columns consumes.

    The inner "first row that fits" scan is one vectorized numpy mask per
    sample (O(rows) bytes, not O(rows) Python), which keeps even a
    many-thousand-row bucket well under preprocess noise offline.
    """
    lengths = np.asarray(lengths, dtype=np.int64)
    n = len(lengths)
    if n == 0:
        return np.zeros(0, dtype=np.int64), np.zeros(0, dtype=np.int64)
    if int(lengths.max()) > budget:
        raise ValueError(
            "sample of {} tokens exceeds pack budget {}".format(
                int(lengths.max()), budget))
    if max_per_row < 1:
        raise ValueError("max_per_row must be >= 1")
    # Descending lengths, ties by original index: np.lexsort sorts by the
    # LAST key first, so (index, -length) gives the stable FFD order.
    order = np.lexsort((np.arange(n), -lengths))
    free = np.empty(n, dtype=np.int64)      # capacity left per open row
    counts = np.empty(n, dtype=np.int64)    # samples per open row
    rows = []                               # [[sample_idx, ...] per row]
    nrows = 0
    for idx in order:
        length = int(lengths[idx])
        fit = np.flatnonzero((free[:nrows] >= length)
                             & (counts[:nrows] < max_per_row))
        if len(fit):
            r = int(fit[0])
            rows[r].append(int(idx))
            free[r] -= length
            counts[r] += 1
        else:
            rows.append([int(idx)])
            free[nrows] = budget - length
            counts[nrows] = 1
            nrows += 1
    sample_order = np.concatenate(
        [np.asarray(row, dtype=np.int64) for row in rows])
    samples_per_row = counts[:nrows].copy()
    return sample_order, samples_per_row


def _column_views(col):
    """(flat_values, per_row_lens) of a ``list<int32>`` column the sink
    built (pa.Array via arrowcols.int32_list_array) — zero-copy."""
    lens = col.value_lengths().to_numpy(zero_copy_only=False).astype(
        np.int64)
    values = col.flatten().to_numpy(zero_copy_only=True)
    return values, lens


def pack_columns(columns, n, pack_seq_length, max_per_row, cls_id, sep_id,
                 masking=False):
    """Per-sample schema-v2 COLUMNS -> packed-row columns.

    ``columns`` is materialize_columns' output (the token-id columns are
    required: offline packing is a schema-v2 feature). The emitted
    ``input_ids`` column stores each row's FULLY INTERLEAVED content —
    ``[CLS] A [SEP] B [SEP]`` per sample, specials baked in at pack time
    (that is what lets the loader scatter whole rows instead of
    re-assembling per sample), and the masking positions are stored
    ROW-relative for the same reason. Returns
    ``(packed_columns, n_rows, stats)`` with ``stats`` carrying the
    placed-token / budget-slot accounting for the
    ``preprocess_pack_fill_ratio`` gauge."""
    if "A_ids" not in columns:
        raise ValueError(
            "offline packing requires the schema-v2 token-id columns "
            "(A_ids/B_ids); run with schema_version=2")
    from .arrowcols import concat_aranges
    num_tokens = np.asarray(columns["num_tokens"], dtype=np.int64)
    sample_order, samples_per_row = ffd_pack(num_tokens, pack_seq_length,
                                             max_per_row)
    n_rows = len(samples_per_row)
    row_starts = np.cumsum(samples_per_row) - samples_per_row

    def gathered(col):
        values, lens = _column_views(col)
        return gather_list_slices(values, lens, sample_order)

    flat_a, a_sel = gathered(columns["A_ids"])
    flat_b, b_sel = gathered(columns["B_ids"])
    tot_sel = a_sel + b_sel + 3
    assert np.array_equal(tot_sel, num_tokens[sample_order])
    # Rows tile their samples contiguously, so the concatenated row
    # contents ARE the samples laid out at their global offsets.
    global_off = np.cumsum(tot_sel) - tot_sel
    total = int(tot_sel.sum())
    content = np.empty(total, dtype=np.int32)
    content[global_off] = cls_id
    content[global_off + 1 + a_sel] = sep_id
    content[global_off + tot_sel - 1] = sep_id
    content[np.repeat(global_off + 1, a_sel)
            + concat_aranges(a_sel)] = flat_a
    content[np.repeat(global_off + 2 + a_sel, b_sel)
            + concat_aranges(b_sel)] = flat_b

    rn = np.asarray(columns["is_random_next"]).astype(np.int32)
    row_tokens = (np.add.reduceat(tot_sel, row_starts) if n_rows
                  else np.zeros(0, dtype=np.int64))
    assert not n_rows or int(row_tokens.max()) <= pack_seq_length
    packed = {
        "input_ids": int32_list_array(content, row_tokens),
        "pack_a_lens": int32_list_array(a_sel, samples_per_row),
        "pack_b_lens": int32_list_array(b_sel, samples_per_row),
        "pack_nsp": int32_list_array(rn[sample_order], samples_per_row),
        "num_tokens": row_tokens.astype(np.uint16),
    }
    if masking:
        flat_pos, m_sel = gathered(columns["masked_lm_positions_ids"])
        flat_lab, m_sel2 = gathered(columns["masked_lm_label_ids"])
        assert np.array_equal(m_sel, m_sel2)
        # Row-relative positions: the sample's offset inside its row is
        # its global offset minus the row's global base.
        row_base = np.cumsum(row_tokens) - row_tokens
        off_in_row = global_off - np.repeat(row_base, samples_per_row)
        pos_rowrel = flat_pos + np.repeat(off_in_row, m_sel)
        # Per-row masked counts via cumsum differences (np.add.reduceat
        # mishandles empty segments — a row of all-unmasked samples).
        cum_m = np.zeros(len(m_sel) + 1, dtype=np.int64)
        np.cumsum(m_sel, out=cum_m[1:])
        bounds = np.append(row_starts, len(m_sel))
        row_mask = cum_m[bounds[1:]] - cum_m[bounds[:-1]]
        packed["masked_lm_positions_ids"] = int32_list_array(pos_rowrel,
                                                             row_mask)
        packed["masked_lm_label_ids"] = int32_list_array(flat_lab, row_mask)
        packed["pack_mask_lens"] = int32_list_array(m_sel, samples_per_row)
    stats = {
        "tokens": int(tot_sel.sum()),
        "slots": int(n_rows) * int(pack_seq_length),
        "samples": int(n),
        "rows": int(n_rows),
    }
    return packed, n_rows, stats


def pack_meta_of(pack_seq_length, max_per_row):
    """The ``__meta__`` fragment recording the packed row shape — pure
    function of the shape (manifest content is resume-compared bytes)."""
    return {"pack_seq_length": int(pack_seq_length),
            "pack_max_per_row": int(max_per_row)}


def pack_shape_of_schema(schema):
    """(pack_seq_length, pack_max_per_row) off a parquet/arrow schema's
    metadata, or None for unpacked shards."""
    md = schema.metadata or {}
    if PACK_META_SEQ_LENGTH not in md:
        return None
    try:
        return (int(md[PACK_META_SEQ_LENGTH]),
                int(md.get(PACK_META_MAX_PER_ROW, b"8")))
    except (TypeError, ValueError):
        return None


def pack_shape_of_parquet(path):
    """Packed row shape off one shard's footer, or None (unreadable
    footers are the integrity verifier's problem, not the sniffer's).
    On a non-local storage backend the footer arrives via ranged reads
    (utils/fs), so the shape sniff never fetches a full object."""
    import pyarrow.parquet as pq
    from ..resilience.io import backend_if_nonlocal
    try:
        if backend_if_nonlocal() is not None:
            from ..utils.fs import read_footer_metadata
            return pack_shape_of_schema(read_footer_metadata(path).schema
                                        .to_arrow_schema())
        return pack_shape_of_schema(pq.read_schema(path))
    except (OSError, RuntimeError, pa.ArrowInvalid):
        return None


def _record_fill(stats):
    """Cumulative pack-fill telemetry: the gauge is placed tokens over
    budget slots across every bucket this process packed so far (the
    fleet aggregator recomputes the cluster-wide ratio from the two
    counters, so per-host and fleet numbers agree by construction)."""
    if not obs.enabled():
        return
    obs.inc("preprocess_pack_tokens_total", stats["tokens"])
    obs.inc("preprocess_pack_slot_tokens_total", stats["slots"])
    obs.inc("preprocess_pack_rows_total", stats["rows"])
    reg = obs.registry()
    slots = reg.counter("preprocess_pack_slot_tokens_total").total()
    if slots:
        obs.set_gauge(
            "preprocess_pack_fill_ratio",
            reg.counter("preprocess_pack_tokens_total").total() / slots)


def write_packed_shard(columns, n, out_dir, part_id, pack_seq_length,
                       max_per_row, cls_id, sep_id, masking=False,
                       compression=None):
    """Pack one bucket's columns and publish ``part.<id>.parquet`` whose
    rows are budget-sized packed sequences (schema metadata stamps the
    row shape). Empty buckets produce no file, like the binned sink.
    Returns {written_path: packed_row_count}."""
    from . import binning as binning_mod
    if compression is None:
        compression = binning_mod.DEFAULT_PARQUET_COMPRESSION
    if n == 0:
        return {}
    packed, n_rows, stats = pack_columns(
        columns, n, pack_seq_length, max_per_row, cls_id, sep_id,
        masking=masking)
    schema = binning_mod.make_packed_schema(
        masking=masking, pack_seq_length=pack_seq_length,
        max_per_row=max_per_row)
    os.makedirs(out_dir, exist_ok=True)
    path = os.path.join(out_dir, "part.{}.parquet".format(part_id))
    write_table_atomic(
        pa.table({name: packed[name] for name in schema.names},
                 schema=schema),
        path, compression=compression,
        **binning_mod.write_options_for_names(schema.names))
    _record_fill(stats)
    return {path: n_rows}


__all__ = [
    "PACK_META_MAX_PER_ROW",
    "PACK_META_SEQ_LENGTH",
    "ffd_pack",
    "pack_columns",
    "pack_meta_of",
    "pack_shape_of_parquet",
    "pack_shape_of_schema",
    "write_packed_shard",
]
