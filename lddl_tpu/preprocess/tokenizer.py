"""Tokenizer provisioning.

Reference parity: lddl/dask/bert/pretrain.py:584-587 (BertTokenizerFast from
a local vocab file or the HF hub). We add ``build_wordpiece_vocab`` so fully
offline environments (TPU pods commonly have no egress) can bootstrap a
working WordPiece vocab directly from a corpus sample.
"""

import collections
import os


def get_tokenizer(vocab_file=None, pretrained_model_name=None,
                  do_lower_case=True):
    """A HF fast WordPiece tokenizer from a vocab file or hub name."""
    from transformers import BertTokenizerFast
    if vocab_file is not None:
        if not os.path.isfile(vocab_file):
            raise FileNotFoundError("vocab file not found: {}".format(vocab_file))
        return BertTokenizerFast(vocab_file, do_lower_case=do_lower_case)
    if pretrained_model_name is not None:
        return BertTokenizerFast.from_pretrained(
            pretrained_model_name, do_lower_case=do_lower_case)
    raise ValueError("need vocab_file or pretrained_model_name")


SPECIAL_TOKENS = ("[PAD]", "[UNK]", "[CLS]", "[SEP]", "[MASK]")


def build_wordpiece_vocab(texts, out_path, vocab_size=30000,
                          do_lower_case=True, min_frequency=1):
    """Train a WordPiece vocab from an iterable of texts; write one token
    per line (BERT vocab format). Returns the path.

    Uses the HF ``tokenizers`` WordPiece trainer when available; falls back
    to specials + bytes-as-chars + frequent whole words, which is enough for
    tests and smoke runs.
    """
    texts = list(texts)
    try:
        from tokenizers import Tokenizer, models, trainers, normalizers, pre_tokenizers
        tok = Tokenizer(models.WordPiece(unk_token="[UNK]"))
        norms = [normalizers.NFD(), normalizers.StripAccents()]
        if do_lower_case:
            norms.insert(0, normalizers.Lowercase())
        tok.normalizer = normalizers.Sequence(norms)
        tok.pre_tokenizer = pre_tokenizers.BertPreTokenizer()
        trainer = trainers.WordPieceTrainer(
            vocab_size=vocab_size,
            min_frequency=min_frequency,
            special_tokens=list(SPECIAL_TOKENS),
            continuing_subword_prefix="##",
        )
        tok.train_from_iterator(texts, trainer)
        vocab = sorted(tok.get_vocab().items(), key=lambda kv: kv[1])
        tokens = [t for t, _ in vocab]
    except ImportError:
        counter = collections.Counter()
        chars = set()
        for t in texts:
            if do_lower_case:
                t = t.lower()
            for w in t.split():
                w = w.strip(".,;:!?\"'()[]")
                if w:
                    counter[w] += 1
                    chars.update(w)
        tokens = list(SPECIAL_TOKENS)
        tokens.extend(sorted(chars))
        tokens.extend(
            w for w, c in counter.most_common(vocab_size) if c >= min_frequency)
    with open(out_path, "w", encoding="utf-8") as f:
        for t in tokens:
            f.write(t + "\n")
    return out_path
