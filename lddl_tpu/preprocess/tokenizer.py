"""Tokenizer provisioning.

Reference parity: lddl/dask/bert/pretrain.py:584-587 (BertTokenizerFast from
a local vocab file or the HF hub). We add ``build_wordpiece_vocab`` so fully
offline environments (TPU pods commonly have no egress) can bootstrap a
working WordPiece vocab directly from a corpus sample.
"""

import collections
import os
import unicodedata


def get_tokenizer(vocab_file=None, pretrained_model_name=None,
                  do_lower_case=True):
    """A HF fast WordPiece tokenizer from a vocab file or hub name."""
    from transformers import BertTokenizerFast
    if vocab_file is not None:
        if not os.path.isfile(vocab_file):
            raise FileNotFoundError("vocab file not found: {}".format(vocab_file))
        return BertTokenizerFast(vocab_file, do_lower_case=do_lower_case)
    if pretrained_model_name is not None:
        return BertTokenizerFast.from_pretrained(
            pretrained_model_name, do_lower_case=do_lower_case)
    raise ValueError("need vocab_file or pretrained_model_name")


SPECIAL_TOKENS = ("[PAD]", "[UNK]", "[CLS]", "[SEP]", "[MASK]")


def _is_bert_punctuation(c):
    """BERT's punctuation predicate (category P plus the ASCII symbol
    ranges), matching the encode-time pre-tokenizer — both the HF
    BertTokenizerFast and the native engine's tables
    (native/gen_tables.py) isolate exactly this set."""
    cp = ord(c)
    if (33 <= cp <= 47 or 58 <= cp <= 64 or 91 <= cp <= 96
            or 123 <= cp <= 126):
        return True
    return unicodedata.category(c).startswith("P")


def _count_word_types(texts, do_lower_case):
    """Word-type frequencies after BERT-style pre-tokenization (whitespace
    split + punctuation isolation + lowercase/NFD-strip-accents normalize) —
    the same word boundary the WordPiece munch sees at encode time."""
    counter = collections.Counter()
    for t in texts:
        if do_lower_case:
            t = t.lower()
        t = unicodedata.normalize("NFD", t)
        t = "".join(c for c in t if unicodedata.category(c) != "Mn")
        for chunk in t.split():
            word = []
            for c in chunk:
                if _is_bert_punctuation(c):
                    if word:
                        counter["".join(word)] += 1
                        word = []
                    counter[c] += 1
                else:
                    word.append(c)
            if word:
                counter["".join(word)] += 1
    return counter


def build_wordpiece_vocab(texts, out_path, vocab_size=30000,
                          do_lower_case=True, min_frequency=1):
    """Train a WordPiece vocab from an iterable of texts; write one token
    per line (BERT vocab format). Returns the path.

    Fully deterministic by construction — unlike the HF ``tokenizers``
    WordPiece trainer, whose Rust hash-map iteration makes both the id
    order AND the selected token set vary run to run (observed; it broke
    byte-reproducibility of every downstream shard). Here: BPE-style
    greedy pair merging over word types, scored by pair frequency with
    lexicographic tie-break, alphabet and merges emitted in a canonical
    order. WordPiece encoding (greedy longest-match) only consumes the
    token *set*, so canonical ordering is free.
    """
    import heapq

    counter = _count_word_types(texts, do_lower_case)

    # Word types as symbol sequences: first char bare, continuations "##c".
    words = []  # [freq, [symbols...]]
    for word, freq in sorted(counter.items()):
        words.append([freq, [word[0]] + ["##" + c for c in word[1:]]])

    alphabet = sorted({s for _, syms in words for s in syms})
    vocab = list(SPECIAL_TOKENS) + alphabet
    seen = set(vocab)

    # Pair occurrence counts + posting lists (word indices; refreshed
    # lazily — a stale posting just re-derives the word's current pairs).
    pair_counts = collections.Counter()
    postings = collections.defaultdict(set)
    for wi, (freq, syms) in enumerate(words):
        for a, b in zip(syms, syms[1:]):
            pair_counts[(a, b)] += freq
            postings[(a, b)].add(wi)

    def merged_name(a, b):
        return a + b[2:] if b.startswith("##") else a + b

    heap = [(-c, p) for p, c in pair_counts.items()]
    heapq.heapify(heap)
    while len(vocab) < vocab_size and heap:
        neg, pair = heapq.heappop(heap)
        count = pair_counts.get(pair, 0)
        if count != -neg:  # stale heap entry
            if count >= min_frequency:
                heapq.heappush(heap, (-count, pair))
            continue
        if count < min_frequency:
            break
        new_sym = merged_name(*pair)
        if new_sym in seen:  # already produced via another merge path
            del pair_counts[pair]
            continue
        vocab.append(new_sym)
        seen.add(new_sym)
        a, b = pair
        touched = set()
        for wi in postings.pop(pair, ()):
            freq, syms = words[wi]
            out = []
            i = 0
            while i < len(syms):
                if i + 1 < len(syms) and syms[i] == a and syms[i + 1] == b:
                    out.append(new_sym)
                    i += 2
                else:
                    out.append(syms[i])
                    i += 1
            if len(out) == len(syms):  # stale posting: pair no longer here
                continue
            # Apply the pair-count delta by recount (clearer than in-place
            # neighborhood surgery, same asymptotics: O(len) per word).
            for p in zip(syms, syms[1:]):
                pair_counts[p] -= freq
                touched.add(p)
            for p in zip(out, out[1:]):
                pair_counts[p] += freq
                touched.add(p)
                postings[p].add(wi)
            words[wi][1] = out
        pair_counts.pop(pair, None)
        touched.discard(pair)
        for p in touched:
            c = pair_counts.get(p, 0)
            if c >= min_frequency:
                heapq.heappush(heap, (-c, p))
            elif c <= 0:
                pair_counts.pop(p, None)
                postings.pop(p, None)

    from ..resilience.io import atomic_write
    atomic_write(out_path, "".join(t + "\n" for t in vocab))
    return out_path
