"""Vectorized Arrow column builders: token-id arrays -> parquet columns.

The reference materializes parquet rows through Python strings (row dicts
of joined token lists, lddl/dask/bert/pretrain.py:444-498). Here the
string/binary columns are assembled as raw byte buffers with numpy gathers
— one fancy-index per column over a vocab byte blob — and handed to Arrow
via ``Array.from_buffers``: no per-row Python object is ever created on
the parquet path.
"""

import numpy as np
import pyarrow as pa


def concat_aranges(lens):
    """[arange(l) for l in lens] concatenated, without a Python loop."""
    lens = np.asarray(lens, dtype=np.int64)
    total = int(lens.sum())
    if total == 0:
        return np.zeros(0, dtype=np.int64)
    starts = np.cumsum(lens) - lens
    return np.arange(total, dtype=np.int64) - np.repeat(starts, lens)


def _offsets32(row_bytes):
    offsets = np.zeros(len(row_bytes) + 1, dtype=np.int64)
    np.cumsum(row_bytes, out=offsets[1:])
    if offsets[-1] >= 1 << 31:
        raise ValueError(
            "column exceeds 2GiB in one bucket; raise --num-blocks so "
            "buckets shrink")
    return offsets.astype(np.int32)


def joined_token_strings(flat_ids, row_lens, table):
    """StringArray: row i = space-joined tokens of its slice of
    ``flat_ids`` (row-major, ``row_lens[i]`` ids per row).

    ``table`` is TokenizerInfo.token_byte_table(). Fast path: the native
    C memcpy join fills the Arrow data+offsets buffers in one pass.
    Fallback: a pure-numpy byte gather over the vocab blob (no per-token
    Python at all). Either way no per-row Python string exists.
    """
    flat_ids = np.asarray(flat_ids, dtype=np.int64)
    row_lens = np.asarray(row_lens, dtype=np.int64)
    n = len(row_lens)
    tl = table.lens[flat_ids]
    n_nonempty = int(np.count_nonzero(row_lens))
    total = int(tl.sum()) + len(flat_ids) - n_nonempty
    if total >= 1 << 31:
        raise ValueError(
            "column exceeds 2GiB in one bucket; raise --num-blocks so "
            "buckets shrink")

    from .. import native
    joined = native.join_tokens(flat_ids, row_lens, table.blob,
                                table.starts, table.lens, total)
    if joined is not None:
        data, offsets = joined
        return pa.Array.from_buffers(
            pa.utf8(), n, [None, pa.py_buffer(offsets),
                           pa.py_buffer(data)])

    # A leading space before every token except each row's first.
    first = np.zeros(len(flat_ids), dtype=bool)
    row_tok_starts = np.cumsum(row_lens) - row_lens
    first[row_tok_starts[row_lens > 0]] = True
    has_space = (~first).astype(np.int64)
    emitted = tl + has_space

    cum = np.zeros(len(flat_ids) + 1, dtype=np.int64)
    np.cumsum(emitted, out=cum[1:])
    row_bytes = cum[row_tok_starts + row_lens] - cum[row_tok_starts]
    offsets = _offsets32(row_bytes)

    # Vectorized byte gather: copy every token's bytes from the vocab
    # blob straight into the Arrow data buffer via one fancy index, with
    # the inter-token spaces scattered first. (Replaces the old
    # tolist + b"".join-over-spaced-table path — the per-token list round
    # trip was this builder's last Python hot loop.)
    blob_arr = np.frombuffer(table.blob, dtype=np.uint8)
    data = np.empty(total, dtype=np.uint8)
    tok_dst = cum[:-1] + has_space  # first payload byte of each token
    data[cum[:-1][has_space == 1]] = 0x20  # the space precedes the token
    src = np.repeat(table.starts[flat_ids], tl) + concat_aranges(tl)
    dst = np.repeat(tok_dst, tl) + concat_aranges(tl)
    data[dst] = blob_arr[src]
    return pa.Array.from_buffers(
        pa.utf8(), n, [None, pa.py_buffer(offsets), pa.py_buffer(data)])


def int32_list_array(flat_vals, row_lens):
    """``list<int32>`` ListArray: row i = its slice of ``flat_vals``
    (row-major, ``row_lens[i]`` values per row) — the schema-v2 token-id
    columns, assembled from the SAME flat-id + offsets buffers the string
    builders consume, so emitting them is one extra buffer handoff, not a
    second materialization pass."""
    row_lens = np.asarray(row_lens, dtype=np.int64)
    n = len(row_lens)
    offsets = np.zeros(n + 1, dtype=np.int64)
    np.cumsum(row_lens, out=offsets[1:])
    if offsets[-1] >= 1 << 31:
        raise ValueError(
            "column exceeds 2^31 values in one bucket; raise --num-blocks "
            "so buckets shrink")
    offsets = offsets.astype(np.int32)
    values = np.ascontiguousarray(np.asarray(flat_vals, dtype=np.int32))
    child = pa.Array.from_buffers(pa.int32(), len(values),
                                  [None, pa.py_buffer(values)])
    return pa.Array.from_buffers(pa.list_(pa.int32()), n,
                                 [None, pa.py_buffer(offsets)],
                                 children=[child])


def gather_list_slices(values, lens, order):
    """Re-gather a flat-values + per-row-lens list column into a new row
    ``order``: returns ``(values_in_order, lens_in_order)`` where row
    ``order[i]``'s slice lands contiguously at position ``i``. One fancy
    index over the flat buffer — the offline packer's column permutation
    (no per-row Python, no intermediate list objects)."""
    values = np.asarray(values)
    lens = np.asarray(lens, dtype=np.int64)
    order = np.asarray(order, dtype=np.int64)
    starts = np.cumsum(lens) - lens
    sel = lens[order]
    src = np.repeat(starts[order], sel) + concat_aranges(sel)
    return values[src], sel


_U16_HEADER = np.frombuffer(b"R<u2", dtype=np.uint8)


def serialized_u16_binary(flat_vals, row_lens):
    """BinaryArray: row i = the serialize_np_array fast format (4-byte
    ``R<u2`` tag + raw little-endian uint16 payload, utils/fs.py) of its
    slice of ``flat_vals``."""
    row_lens = np.asarray(row_lens, dtype=np.int64)
    n = len(row_lens)
    payload = np.ascontiguousarray(
        np.asarray(flat_vals).astype("<u2")).view(np.uint8)
    row_bytes = 4 + 2 * row_lens
    offsets = _offsets32(row_bytes)
    out = np.empty(int(offsets[-1]), dtype=np.uint8)
    head_pos = (offsets[:-1].astype(np.int64)[:, None]
                + np.arange(4)[None, :]).reshape(-1)
    out[head_pos] = np.tile(_U16_HEADER, n)
    pl = 2 * row_lens
    dest = np.repeat(offsets[:-1].astype(np.int64) + 4, pl) + concat_aranges(pl)
    out[dest] = payload
    return pa.Array.from_buffers(
        pa.binary(), n, [None, pa.py_buffer(offsets), pa.py_buffer(out)])
