"""BART pretraining preprocessor: sentence chunks of ~target_seq_length.

Reference parity: lddl/dask/bart/pretrain.py:41-184. Documents sentence-
split, then sentences greedily accumulate (whitespace-token counted) into
chunks of at least ``target_seq_length - 3`` tokens; chunks are written as
single-column ``{sentences}`` parquet shards. No tokenizer, no masking, no
binning here — BART's denoising (text infilling, sentence permutation) is
applied at load time (lddl_tpu.loader.bart), which the reference leaves to
the training side and never shipped a loader for.

Improvement over the reference: ``short_seq_prob`` is honored (the
reference accepts the flag but never uses it, pretrain.py:47,108) — with
that probability a chunk targets a random shorter length, mirroring the
BERT pipeline's length diversity.
"""

import dataclasses
import os

import pyarrow as pa

from ..resilience.io import atomic_write, write_table_atomic
from ..utils import rng as lrng
from .binning import (DEFAULT_PARQUET_COMPRESSION, SINK_PROFILE_V2,
                      write_options_for_names)
from .sentences import split_sentences, split_sentences_learned
from .runner import run_sharded_pipeline


@dataclasses.dataclass
class BartPretrainConfig:
    target_seq_length: int = 128
    short_seq_prob: float = 0.1
    # Sentence splitter: "rules" | "learned" (see BertPretrainConfig).
    splitter: str = "rules"

    def __post_init__(self):
        if self.target_seq_length < 8:
            raise ValueError("target_seq_length too small")
        if self.splitter not in ("rules", "learned"):
            raise ValueError("splitter must be rules|learned")


def chunks_from_sentences(sentences, config, g):
    """One document's sentences -> list of chunk strings (leading-space
    joined, like the reference's ``chunk += " " + sentence``). The draw
    sequence depends only on chunk completions, so any splitter engine
    producing the same sentences yields byte-identical chunks."""
    base_target = config.target_seq_length - 3
    chunks = []
    chunk = ""
    num_tokens = 0
    target = base_target
    if config.short_seq_prob > 0 and g.random() < config.short_seq_prob:
        target = int(g.integers(2, base_target + 1))
    for sentence in sentences:
        chunk += " " + sentence
        num_tokens += len(sentence.split())
        if num_tokens >= target:
            chunks.append(chunk)
            chunk = ""
            num_tokens = 0
            target = base_target
            if (config.short_seq_prob > 0
                    and g.random() < config.short_seq_prob):
                target = int(g.integers(2, base_target + 1))
    if num_tokens > 0:
        chunks.append(chunk)
    return chunks


def chunks_from_text(text, config, g, splitter_params=None):
    """One document -> list of chunk strings (Python splitter path)."""
    sentences = (split_sentences_learned(text, splitter_params)
                 if splitter_params is not None else split_sentences(text))
    return chunks_from_sentences(sentences, config, g)


class BartBucketProcessor:
    """Picklable per-bucket BART pipeline stage (pool-friendly; see
    runner.BertBucketProcessor). With a ``tokenizer`` the parquet sink
    emits schema v2: ``sentence_ids``/``sentence_lens`` list<int32>
    columns precomputing EXACTLY what the BART loader's collate derives
    from the chunk text every epoch (``split_sentences`` + batched
    tokenize), so load-time noising starts from stored ids instead of
    re-tokenizing (byte-identical batches, tests/test_schema_v2.py)."""

    def __init__(self, config, seed, out_dir, output_format,
                 splitter_params=None, tokenizer=None):
        self.config = config
        self.seed = seed
        self.out_dir = out_dir
        self.output_format = output_format
        self.splitter_params = splitter_params
        self.tokenizer = tokenizer

    def fingerprint(self):
        """Resume-manifest digest (see BertBucketProcessor.fingerprint;
        vocab enters only when a tokenizer makes the sink schema-v2 —
        tokenizer-less runs keep the historical component list so
        pre-existing v1 runs stay resumable across this upgrade)."""
        from .runner import processor_fingerprint, splitter_digest
        parts = [type(self).__name__, self.config, self.seed,
                 self.output_format, splitter_digest(self.splitter_params)]
        if self.tokenizer is not None:
            parts.append("schema=" + self._schema_tag())
        parts.append("codec=" + DEFAULT_PARQUET_COMPRESSION)
        if self.tokenizer is not None and self.output_format == "parquet":
            # v2 BART shards use the tuned parquet layout (see
            # BertBucketProcessor.fingerprint): deliberate one-time
            # fingerprint bump; tokenizer-less (v1) runs untouched.
            parts.append("v2sink=" + SINK_PROFILE_V2)
        return processor_fingerprint(*parts)

    def _schema_tag(self):
        # ONE definition of "same vocab" across BERT and BART resume
        # fingerprints: TokenizerInfo.vocab_digest (memoized per rebuild,
        # folds in do_lower_case and the exact id->token snapshot).
        from .bert import TokenizerInfo
        return "v2:" + TokenizerInfo(self.tokenizer).vocab_digest

    def _id_columns(self, rows):
        """(sentence_ids, sentence_lens) ListArrays for the chunk strings,
        mirroring loader.bart.BartCollate's per-epoch computation: rules
        sentence split of the CHUNK (the collate never sees the learned
        splitter) + one batched add_special_tokens=False tokenize."""
        from .arrowcols import int32_list_array
        per_chunk = [split_sentences(r) for r in rows]
        flat = [s for sents in per_chunk for s in sents]
        enc = (self.tokenizer(flat, add_special_tokens=False,
                              return_attention_mask=False)["input_ids"]
               if flat else [])
        sent_lens = [len(e) for e in enc]
        flat_ids = [i for e in enc for i in e]
        k = 0
        chunk_tokens = []
        for sents in per_chunk:
            chunk_tokens.append(sum(sent_lens[k:k + len(sents)]))
            k += len(sents)
        return (int32_list_array(flat_ids, chunk_tokens),
                int32_list_array(sent_lens,
                                 [len(sents) for sents in per_chunk]))

    def _native_sentences(self, texts):
        """Whole-bucket native sentence split, or None to use the Python
        splitter. Zero-copy when ``texts`` is a readers.DocSpans spool
        view; boundaries are identical to the Python splitters (pinned by
        tests/test_native.py + test_fused.py), so chunk bytes cannot
        depend on the engine. ``LDDL_TPU_BART_NATIVE_SPLIT=0`` forces the
        Python path. The split kernel partitions documents across the
        LDDL_TPU_NATIVE_THREADS pool (the runner sizes that env so
        workers x threads never oversubscribes the host); output is
        byte-identical at any width (tests/test_native_threads.py)."""
        import os
        if os.environ.get("LDDL_TPU_BART_NATIVE_SPLIT") == "0":
            return None
        from .. import native
        if not native.available():
            return None
        blob = (self.splitter_params.serialize()
                if self.splitter_params is not None else None)
        return native.split_docs(texts, splitter_blob=blob)

    def prepare(self, texts, bucket):
        """Compute phase of the two-phase sink protocol (see
        runner.BertBucketProcessor.prepare): chunking and tokenization run
        producer-side; the returned closure performs only the durable
        write, deferred onto the shard-writer thread."""
        g = lrng.sample_rng(self.seed, 0xBA27, bucket)
        lrng.shuffle(g, texts)
        rows = []
        per_doc_sentences = self._native_sentences(texts)
        if per_doc_sentences is not None:
            for sentences in per_doc_sentences:
                rows.extend(chunks_from_sentences(sentences, self.config, g))
        else:
            for text in texts:
                # The runner hands raw document BYTES (zero-decode spool
                # path); BART chunking is str-based, so decode per
                # document here — after the shuffle, which is order-only.
                if isinstance(text, bytes):
                    text = text.decode("utf-8", errors="replace")
                rows.extend(chunks_from_text(
                    text, self.config, g,
                    splitter_params=self.splitter_params))
        out_dir = self.out_dir
        if self.output_format == "txt":
            path = os.path.join(out_dir, "{}.txt".format(bucket))

            def publish_txt():
                os.makedirs(out_dir, exist_ok=True)
                atomic_write(path, "".join(r + "\n" for r in rows))
                return {path: len(rows)}

            return publish_txt
        path = os.path.join(out_dir, "part.{}.parquet".format(bucket))
        fields = [("sentences", pa.string())]
        columns = {"sentences": rows}
        if self.tokenizer is not None:
            ids, lens = self._id_columns(rows)
            columns["sentence_ids"] = ids
            columns["sentence_lens"] = lens
            fields += [("sentence_ids", pa.list_(pa.int32())),
                       ("sentence_lens", pa.list_(pa.int32()))]
        write_options = write_options_for_names(columns)
        table = pa.table(columns, schema=pa.schema(fields))

        def publish():
            os.makedirs(out_dir, exist_ok=True)
            write_table_atomic(table, path,
                               compression=DEFAULT_PARQUET_COMPRESSION,
                               **write_options)
            return {path: len(rows)}

        return publish

    def __call__(self, texts, bucket):
        return self.prepare(texts, bucket)()


def run_bart_preprocess(
    corpus_paths,
    out_dir,
    config=None,
    num_blocks=64,
    sample_ratio=0.9,
    seed=12345,
    global_shuffle=True,
    output_format="parquet",
    comm=None,
    log=None,
    num_workers=1,
    spool_groups=None,
    resume=False,
    progress_interval=5.0,
    tokenizer=None,
    elastic=False,
    lease_ttl=30.0,
    holder_id=None,
    scatter_units=None,
):
    """Run the BART preprocessing pipeline (SPMD contract per
    run_sharded_pipeline). Output: part.<k>.parquet with a single
    ``sentences`` string column (ref: bart/pretrain.py:136-152); pass a
    ``tokenizer`` to add the schema-v2 ``sentence_ids``/``sentence_lens``
    token-id columns the loader consumes without re-tokenizing (the SAME
    tokenizer must then be used at load time, as with BERT shards)."""
    config = config or BartPretrainConfig()
    if output_format not in ("parquet", "txt"):
        raise ValueError("output_format must be parquet|txt")
    from .runner import train_splitter_params_from_corpus
    splitter_params = (train_splitter_params_from_corpus(corpus_paths)
                       if config.splitter == "learned" else None)
    return run_sharded_pipeline(
        corpus_paths,
        out_dir,
        BartBucketProcessor(config, seed, out_dir, output_format,
                            splitter_params=splitter_params,
                            tokenizer=tokenizer),
        num_blocks=num_blocks,
        sample_ratio=sample_ratio,
        seed=seed,
        global_shuffle=global_shuffle,
        comm=comm,
        log=log,
        num_workers=num_workers,
        spool_groups=spool_groups,
        resume=resume,
        progress_interval=progress_interval,
        elastic=elastic,
        lease_ttl=lease_ttl,
        holder_id=holder_id,
        scatter_units=scatter_units,
    )
