"""Input discovery and block planning for the preprocessors.

Reference parity: lddl/dask/readers.py. The reference builds a dask.bag with
``db.read_text(blocksize=total_bytes/num_blocks)``; our scheduling is static
and deterministic instead (SURVEY.md §7.4): the input corpus is planned into
an explicit list of byte-range Blocks once, identically on every host, and
hosts/workers pick blocks by striding — no task scheduler process needed.

Input contract (downloader output): UTF-8 text files where each line is
one document and the first ASCII-whitespace-separated token is the
document id (ref: lddl/dask/readers.py:131-136). The id/text split and
the empty-document filter are ASCII-whitespace-based (bytes semantics,
round 5): a document id separated from its text by a Unicode-only space
(e.g. NBSP) is treated as having empty text and dropped — the bundled
downloaders always emit ASCII separators; normalize external corpora to
this contract.
"""

import dataclasses
import os

import numpy as np

from ..utils.fs import get_all_files_paths_under
from ..utils import rng as lrng


@dataclasses.dataclass(frozen=True)
class Block:
    """A whole-line-aligned byte range of one input text file."""
    block_id: int
    path: str
    start: int
    end: int  # exclusive


class DocSpans:
    """Zero-copy document view: one contiguous bytes buffer + per-document
    (start, end) byte ranges. This is how a bucket's documents travel from
    the spool reader into the native engine — the kernel reads the raw
    buffer in place, so no per-document Python object (and no re-encoding
    pass) ever exists on the hot path.

    List-like for the fallback engines: ``len``, iteration and indexing
    yield each document's bytes (a copy, made only when actually
    consumed). ``take_`` permutes the view in place — the in-bucket
    shuffle reorders two int64 arrays instead of a list of objects
    (utils.rng.shuffle dispatches on it with the identical draw
    contract)."""

    __slots__ = ("buffer", "starts", "ends")

    def __init__(self, buffer, starts, ends):
        self.buffer = buffer
        self.starts = np.ascontiguousarray(starts, dtype=np.int64)
        self.ends = np.ascontiguousarray(ends, dtype=np.int64)

    @classmethod
    def from_texts(cls, texts):
        """Pack a sequence of bytes into one buffer (tests/adapters; the
        spool reader builds views directly over its merged read buffer)."""
        texts = [t if isinstance(t, bytes) else t.encode("utf-8")
                 for t in texts]
        lens = np.fromiter(map(len, texts), dtype=np.int64,
                           count=len(texts))
        ends = np.cumsum(lens)
        return cls(b"".join(texts), ends - lens, ends)

    def __len__(self):
        return len(self.starts)

    def __getitem__(self, i):
        if isinstance(i, slice):
            return [self[j] for j in range(*i.indices(len(self)))]
        return bytes(self.buffer[self.starts[i]:self.ends[i]])

    def __iter__(self):
        buf = self.buffer
        for s, e in zip(self.starts, self.ends):
            yield bytes(buf[int(s):int(e)])

    def take_(self, perm):
        """Reorder documents in place by ``perm`` (offset-array permute)."""
        self.starts = self.starts[perm]
        self.ends = self.ends[perm]
        return self


def _find_text_files_under(root):
    return [
        p for p in get_all_files_paths_under(root)
        if os.path.basename(p).endswith(".txt")
    ]


def discover_source_files(corpus_paths):
    """Flatten {corpus_name: path} into a sorted list of input text files.

    Each corpus path may point either at the corpus root (containing
    ``source/``), directly at a directory of ``.txt`` shards, or be an
    explicit list/tuple of text-file paths (the streaming-ingestion
    service hands landing files over this way) — explicit lists are
    sorted, so file order never depends on how the caller built them.
    """
    files = []
    for _, path in sorted(corpus_paths.items()):
        if path is None:
            continue
        if isinstance(path, (list, tuple)):
            explicit = sorted(str(p) for p in path)
            missing = [p for p in explicit if not os.path.isfile(p)]
            if missing:
                raise ValueError(
                    "explicit source file(s) missing: {}".format(missing))
            files.extend(explicit)
            continue
        source = os.path.join(path, "source")
        root = source if os.path.isdir(source) else path
        found = _find_text_files_under(root)
        if not found:
            raise ValueError("no .txt source shards under {}".format(root))
        files.extend(found)
    if not files:
        raise ValueError("no input corpora given")
    return files


def plan_blocks(input_files, target_num_blocks):
    """Deterministically split files into ~equal byte-range blocks.

    The block boundaries are provisional byte offsets; readers snap them to
    line boundaries (a block owns every line that *starts* inside it), so
    planning needs only file sizes — identical on every host.
    """
    sizes = [os.path.getsize(p) for p in input_files]
    total = sum(sizes)
    if total == 0:
        raise ValueError("input corpus is empty")
    target_num_blocks = max(1, int(target_num_blocks))
    block_size = max(1, total // target_num_blocks)
    blocks = []
    for path, size in zip(input_files, sizes):
        if size == 0:
            continue
        n = max(1, round(size / block_size))
        for i in range(n):
            start = size * i // n
            end = size * (i + 1) // n
            blocks.append(Block(len(blocks), path, start, end))
    return blocks


def read_block_lines(block):
    """Yield the RAW BYTES of the lines that start inside ``block`` (whole
    lines, no trailing \\n).

    Boundary rule: a line belongs to the block containing its first byte.
    A block whose start is mid-line skips forward to the next line start.

    Bytes, not str, on purpose: document text flows corpus -> spool ->
    gather -> C++ engine without ever paying a UTF-8 decode + re-encode
    round-trip over the whole corpus (the engine decodes once, in C; the
    HF/text fallback paths decode lazily at their entry points with
    errors="replace", the old behavior). Invalid UTF-8 is neutral either
    way: both the native normalizer and HF's clean_text drop U+FFFD.
    """
    with open(block.path, "rb") as f:
        if block.start == 0:
            f.seek(0)
        else:
            f.seek(block.start - 1)
            # If the previous byte is not a newline, our start is mid-line:
            # that line belongs to the previous block.
            prev = f.read(1)
            if prev != b"\n":
                f.readline()
        while f.tell() < block.end:
            line = f.readline()
            if not line:
                break
            yield line[:-1] if line.endswith(b"\n") else line


def split_id_text(raw_line):
    """'<doc id> <text...>' -> (doc_id, text); bytes in, bytes out (or str
    in, str out — the split-on-whitespace contract is ASCII whitespace,
    per the downloader output format). (ref: readers.py:131-136)"""
    parts = raw_line.split(None, 1)
    if len(parts) == 0:
        return None, raw_line[:0]
    if len(parts) == 1:
        return parts[0], raw_line[:0]
    return parts[0], parts[1]


def read_documents(block, sample_ratio=1.0, base_seed=12345):
    """Yield (doc_id, text) BYTES pairs for non-empty documents of a
    block, keeping each with probability ``sample_ratio`` (seeded per
    block, ref: readers.py:60-71 random_sample)."""
    g = lrng.sample_rng(base_seed, block.block_id) if sample_ratio < 1.0 else None
    for line in read_block_lines(block):
        if not line.strip():
            continue
        if g is not None and g.random() >= sample_ratio:
            continue
        doc_id, text = split_id_text(line)
        if not text.strip():
            continue
        yield doc_id, text
