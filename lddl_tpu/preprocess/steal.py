"""Elastic multi-host preprocessing: the lease-fenced work-stealing loop.

The static runner (:mod:`.runner`) schedules units by rank striding and
meets at barriers — one dead host wedges the phase (MPI semantics, exactly
what the reference inherits from dask-mpi). This module replaces the
schedule with a **claim loop** over the same units: N independent host
processes — no jax.distributed, no barriers, nothing shared but the
output directory — each repeatedly

    1. pick a unit whose completion record is absent,
    2. claim it via an atomic-rename lease (:mod:`..resilience.leases`),
    3. sweep any previous attempt's partial outputs,
    4. run it (serially or on the host's local spawn pool),
    5. fence-check the lease and, only if still held at the claimed
       epoch, journal the completion record,

until every unit is journaled. A host that dies mid-unit simply stops
renewing its lease; after one TTL any survivor steals the unit (epoch
bump), sweeps the debris, and redoes it. A host that *stalls* and
resurrects after a steal fails the fence check and discards its late
result (``lease_fence_rejects_total``) — the ledger only ever sees one
winner per unit.

Determinism contract: a unit's output bytes are a pure function of the
resume fingerprint and the unit id (PR 1/4 machinery). Leases decide WHO
runs a unit, never what it produces, so an elastic run of any host count,
with any sequence of host deaths, is byte-identical to a static
single-host run of the same plan (chaos-pinned in tests/test_chaos.py).

Unit kinds and their fencing:

- **scatter slices** (blocks ``unit, unit+S, ...`` of the plan): spool
  appends are not idempotent, so every claim attempt writes its own
  exclusively-owned files ``group-<g>/s<slice>.e<epoch>.<holder>.txt``
  and the completion record stores the winning ``(epoch, holder)``. The
  gather trusts ONLY the recorded file names — a fenced-off zombie's late
  appends land in files nothing ever reads.
- **gather groups** (coarse spool groups) / **blocks** (no-shuffle mode):
  outputs are whole shard files published atomically under deterministic
  names, so a zombie rewriting them is byte-identical by construction;
  the fence protects the ledger record itself.
- **finalize** (manifest + cleanup) is itself a lease-guarded unit — the
  last host out runs it, and if it dies mid-finalize a survivor steals
  that too. The lease directory is removed last: its disappearance is
  the "run complete" signal waiting hosts poll for.
"""

import concurrent.futures as cf
import hashlib
import json
import logging
import os
import shutil
import time

from .. import observability as obs
from ..parallel.distributed import LocalCommunicator
from ..resilience import io as rio
from ..resilience import leases
from . import runner as _runner

_FINALIZE_UNIT = "finalize"
_SCATTER_PREFIX = "scatter-"
_GROUP_PREFIX = "group-"
_BLOCK_PREFIX = "block-"

_log = logging.getLogger("lddl_tpu.preprocess.steal")


def _fence_for(out_dir, prefix, unit, epoch, holder):
    """A zero-state fence closure for unit bodies (works across the pool
    process boundary: everything needed to re-check the lease travels as
    plain values). False once the unit's lease stops naming exactly this
    (holder, epoch) attempt."""
    root = leases.lease_root(out_dir)
    key = "{}{}".format(prefix, unit)
    return lambda: leases.verify_at(root, key, holder, epoch)


# ------------------------------------------------------------ unit records


def _scatter_record_path(out_dir, unit):
    return os.path.join(out_dir, _runner._LEDGER_DIR,
                        "scatter-{}.json".format(unit))


def _read_scatter_record(out_dir, unit):
    """A scatter slice's completion record ({"epoch", "holder"}), or None.
    Torn bytes degrade to "not done" with a warning, like `_ledger_read`."""
    rec, status = rio.read_json(_scatter_record_path(out_dir, unit))
    if status == "torn":
        _log.warning("torn scatter record for unit %s; treating as not "
                     "done", unit)
        return None
    return rec if isinstance(rec, dict) else None


def _publish_scatter_record(out_dir, unit, lease):
    """Journal a completed scatter slice. The record IS the epoch fence
    for spool bytes: it names the one (epoch, holder) attempt whose files
    the gather may read — so lease state flowing into this _done record
    is the design, not a leak (it never reaches shard bytes or
    .manifest.json; the analyzer's lease-isolation rule guards those)."""
    path = _scatter_record_path(out_dir, unit)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    payload = json.dumps({"epoch": lease.epoch, "holder": lease.holder},
                         sort_keys=True)
    # Fence record by design (see docstring): epoch+holder, wall-clock-free.
    rio.atomic_write(path, payload)  # lddl: disable=lease-isolation,wall-clock-flow
    # Post-publish fence re-check: if the lease was stolen in the tiny
    # window between the pre-publish verify and this write, the thief may
    # ALREADY have journaled its own record — which our stale write just
    # clobbered with file names the thief swept. Re-read: if the record on
    # disk is ours but the lease is not, withdraw it so the unit is redone
    # rather than pointing at deleted spool files.
    if not leases.verify(lease):
        cur = _read_scatter_record(out_dir, unit)
        if cur == {"epoch": lease.epoch, "holder": lease.holder}:
            try:
                os.remove(path)
            except FileNotFoundError:
                pass
        _prune_empty_scaffolding(out_dir)
        return False
    return True


def _prune_empty_scaffolding(out_dir):
    """Best-effort removal of `_done`/`_leases` dirs a zombie's late write
    resurrected AFTER finalize retired them (os.makedirs inside the
    publish/acquire paths recreates the dir). rmdir only succeeds on
    empty dirs, so a live run's scaffolding is never touched."""
    for d in (os.path.join(out_dir, _runner._LEDGER_DIR),
              leases.lease_root(out_dir)):
        try:
            os.rmdir(d)
        # Non-empty (live run) or already gone: both fine by design.
        except OSError:  # lddl: disable=swallowed-error
            pass


def _publish_gather_record(out_dir, unit, result, lease):
    """Journal a completed gather unit, with the same post-publish fence
    re-check the scatter path has: if the lease was lost in the window
    between the claim loop's verify and this write, the record is
    withdrawn — a stalled zombie must not resurrect `_done/` inside an
    already-finalized output dir (and in the live-thief case a withdrawn
    record merely makes the unit's owner republish identical bytes)."""
    _runner._ledger_write(out_dir, unit, result)
    if not leases.verify(lease):
        try:
            os.remove(_runner._ledger_path(out_dir, unit))
        except FileNotFoundError:
            pass
        _prune_empty_scaffolding(out_dir)
        return False
    return True


def spool_name(unit, epoch, holder):
    """The exclusive spool file name of one scatter claim attempt (per
    coarse group). Epoch+holder make every attempt's files disjoint."""
    return "s{}.e{}.{}.txt".format(unit, epoch, holder)


def _stable_scatter_records(out_dir, scatter_units, lease_root, ttl, poll):
    """Read every scatter record until two consecutive sweeps agree.

    Returns ``("ok", {unit: record})``, ``("finalized", None)`` when
    another host already finalized the whole run, or ``("retry", None)``
    when a record is missing with no live lease — a fenced loser's
    clobber-then-withdraw transiently un-journaled the unit and the
    withdrawer died before redoing it, so the caller must re-enter the
    claim loop. The double read closes the window in which an accept set
    built from a loser's transient record would name spool files the
    winner's sweep deleted; what remains requires two suspensions at
    exactly the wrong microseconds AND is still bounded by this
    function's own re-read."""
    ledger_dir = os.path.join(out_dir, _runner._LEDGER_DIR)
    patience = max(2.0 * ttl, 3.0)
    deadline = time.monotonic() + patience
    prev = None
    while True:
        if not os.path.isdir(ledger_dir):
            return "finalized", None
        recs = {}
        missing = None
        for u in scatter_units:
            rec = _read_scatter_record(out_dir, u)
            if rec is None:
                missing = u
                break
            recs[u] = rec
        if missing is None:
            if recs == prev:
                return "ok", recs
            prev = recs
            time.sleep(min(poll, 0.05))
            continue
        prev = None
        if leases.is_live(lease_root,
                          "{}{}".format(_SCATTER_PREFIX, missing)):
            # Someone is actively republishing/redoing it: keep waiting.
            deadline = time.monotonic() + patience
        elif time.monotonic() >= deadline:
            return "retry", None
        time.sleep(poll)


# -------------------------------------------------------------- unit tasks
#
# Module-level so spawn pools can pickle them; serial mode calls them
# directly via closures built in run_elastic_pipeline. All take
# (unit, epoch, holder) so the claimed attempt's identity reaches the
# spool file names.


def _scatter_slice(spec, unit, epoch, holder):
    """Scatter all blocks of one slice (``unit, unit+S, ...``) into this
    attempt's exclusive spool files, self-terminating between blocks if
    the lease is stolen (appends after a steal would only be debris —
    fenced out by name — but stopping early keeps the thief's sweep
    meaningful and the host honest)."""
    input_files = _runner.discover_source_files(spec["corpus_paths"])
    blocks = _runner.plan_blocks(input_files, spec["num_blocks"])
    name = spool_name(unit, epoch, holder)
    fence = _fence_for(spec["out_dir"], _SCATTER_PREFIX, unit, epoch, holder)
    n = 0
    for b in range(unit, len(blocks), spec["scatter_units"]):
        _runner._check_fence(fence, unit)
        _runner._spool_one_block(blocks[b], spec["out_dir"], spec["seed"],
                                 spec["sample_ratio"], len(blocks),
                                 spec["ngroups"], name)
        n += 1
    return n


def _pool_scatter_slice(unit, epoch, holder):
    return _scatter_slice(_runner._POOL["spec"], unit, epoch, holder)


def _pool_gather_group(unit, epoch, holder):
    spec = _runner._POOL["spec"]
    return _runner._run_group(
        spec, _runner._POOL["process_bucket"], unit,
        fence=_fence_for(spec["out_dir"], _GROUP_PREFIX, unit, epoch,
                         holder))


def _pool_block_bucket(unit, epoch, holder):
    spec = _runner._POOL["spec"]
    return _runner._run_block_bucket(
        spec, _runner._POOL["process_bucket"], unit,
        fence=_fence_for(spec["out_dir"], _BLOCK_PREFIX, unit, epoch,
                         holder))


# ------------------------------------------------------------------ sweeps


def _sweep_scatter(spec, unit):
    """Remove EVERY attempt's spool files for a reclaimed scatter slice
    (all epochs/holders: only the attempt about to run may have files)."""
    import glob
    pattern = os.path.join(spec["out_dir"], _runner._SPOOL_DIR, "group-*",
                           "s{}.e*".format(unit))
    n = 0
    for path in sorted(glob.glob(pattern)):
        try:
            os.remove(path)
            n += 1
        except FileNotFoundError:
            pass
    if n:
        obs.inc("elastic_swept_files_total", int(n))
    return n


def _sweep_gather(spec, unit):
    """Remove a reclaimed gather group's partial bucket outputs (final
    part files AND ``*.tmp.*`` atomic-write debris — the exact-prefix
    globs in `_clean_bucket_outputs` cover both)."""
    for bucket in _runner._buckets_of_group(unit, spec["nbuckets"],
                                            spec["ngroups"]):
        _runner._clean_bucket_outputs(spec["out_dir"], bucket)


def _sweep_block(spec, unit):
    _runner._clean_bucket_outputs(spec["out_dir"], unit)


# -------------------------------------------------------------- claim loop


class _InlineExecutor(object):
    """Executor shim for serial hosts: submit() runs the task inline and
    returns an already-settled Future, so the claim loop has one shape."""

    def submit(self, fn, *args):
        fut = cf.Future()
        try:
            fut.set_result(fn(*args))
        except BaseException as e:  # noqa: BLE001 - future carries it
            fut.set_exception(e)
        return fut

    def shutdown(self, wait=True):
        pass


def _rotated(units, holder):
    """Deterministic per-holder rotation of the unit scan order, so N
    hosts starting together fan out across the unit space instead of
    racing for unit 0. Pure scheduling: never shapes output bytes."""
    order = sorted(units)
    if not order:
        return order
    start = int.from_bytes(
        hashlib.blake2b(holder.encode(), digest_size=4).digest(),
        "little") % len(order)
    return order[start:] + order[:start]


def claim_loop(spec, phase, unit_prefix, units, *, holder, ttl, keeper,
               is_done, sweep, task, publish, executor_factory, max_inflight,
               log, progress_interval=5.0, poll_s=None):
    """Run every unit to completion across all participating hosts.

    Returns a stats dict. Raises RuntimeError (with the standard
    "re-run with resume" message) if units failed on this host and no
    other host completed them within the patience window.

    - ``is_done(unit)`` — the unit's completion record, or None when not
      done. Done-ness is record EXISTENCE (``is not None``): an empty
      ``{}`` record from a zero-sample unit is still done.
    - ``sweep(unit)`` — remove a prior attempt's partial outputs; called
      on EVERY claim before running (cheap no-op on first attempts).
    - ``task(unit, epoch, holder)`` — the unit body; picklable when an
      ``executor_factory`` is given (spawn pool), else any callable.
    - ``publish(unit, result, lease)`` — journal completion; called only
      after the fence check passed. May return False to signal a
      post-publish fence loss (the unit stays pending).
    """
    from concurrent.futures.process import BrokenProcessPool

    lease_root = leases.lease_root(spec["out_dir"])
    ledger_dir = os.path.join(spec["out_dir"], _runner._LEDGER_DIR)

    def run_finalized():
        """True once another host's finalize has retired the ledger. The
        finalizer renames ``_done`` away atomically before deleting it, so
        "completion record missing AND ledger dir missing" unambiguously
        means "everything finished" — never "unit needs redoing". Without
        this, a host racing the finalize would reclaim a finished unit,
        sweep its FINAL outputs, and regenerate them from a spool that no
        longer exists."""
        return not os.path.isdir(ledger_dir)

    poll = poll_s if poll_s is not None else max(0.05, min(ttl / 4.0, 2.0))
    stats = {"units": len(units), "completed": 0, "stolen": 0,
             "fence_rejects": 0, "already_done": 0}
    # Done-ness is "a record EXISTS", never record truthiness: a gather
    # unit whose buckets produced zero samples journals a legitimately
    # empty {} record, and treating that as "not done" would make every
    # host redo empty units forever (the static resume path compares
    # `is None` for the same reason).
    remaining = set(u for u in units if is_done(u) is None)
    stats["already_done"] = len(units) - len(remaining)
    progress = _runner._Progress(log, phase, len(remaining),
                                 interval_s=progress_interval)
    order = _rotated(units, holder)
    failed = {}
    inflight = {}  # future -> (unit, lease)
    executor = None

    def ensure_executor():
        nonlocal executor
        if executor is None:
            executor = (executor_factory() if executor_factory is not None
                        else _InlineExecutor())
        return executor

    def drop_inflight(fut):
        unit, lease = inflight.pop(fut)
        keeper.remove(lease)
        return unit, lease

    def fence_reject(unit, lease, why):
        stats["fence_rejects"] += 1
        obs.inc("lease_fence_rejects_total")
        obs.event("lease.fence_reject", unit="{}{}".format(
            unit_prefix, unit), epoch=lease.epoch)
        obs.fleet.record("unit.fenced", unit="{}{}".format(
            unit_prefix, unit), epoch=lease.epoch, holder=holder, why=why)
        log("{}: unit {} {} at epoch {}; late result discarded "
            "(fence)".format(phase, unit, why, lease.epoch))

    def handle_completed(fut):
        unit, lease = drop_inflight(fut)
        try:
            result = fut.result()
        except BrokenProcessPool:
            # A dead pool worker breaks the whole pool and names no
            # culprit. Release so any host (us included) can reclaim
            # immediately; the per-claim sweep redoes partial outputs.
            leases.release(lease)
            raise
        except leases.LeaseLost:
            # The unit body self-terminated mid-run (the thief owns the
            # unit now). Not a failure: the winner's record will appear.
            fence_reject(unit, lease, "self-terminated (stolen)")
            return
        except Exception as e:  # noqa: BLE001 - isolate per unit
            if lease.lost or not leases.verify(lease):
                # An error on a unit we no longer own is zombie noise,
                # not a unit failure: a thief may have swept our spool
                # files mid-append, or a finalizer may already be
                # deleting the run's scaffolding under us.
                fence_reject(unit, lease,
                             "errored after losing its lease "
                             "({}: {})".format(type(e).__name__, e))
                return
            leases.release(lease)
            failed[unit] = "{}: {}".format(type(e).__name__, e)
            obs.fleet.record("unit.failed", unit="{}{}".format(
                unit_prefix, unit), epoch=lease.epoch, holder=holder,
                error=failed[unit][:200])
            remaining.discard(unit)
            log("{}: unit {} failed ({}); lease released for another "
                "host".format(phase, unit, failed[unit]))
            return
        if lease.lost or not leases.verify(lease):
            # Stolen while we ran (we stalled past the deadline): the
            # thief owns the unit now; discard our late result.
            fence_reject(unit, lease, "was stolen while this host ran it")
            return
        if publish(unit, result, lease) is False:
            fence_reject(unit, lease, "lost its lease during publish")
            return
        leases.release(lease)
        if lease.epoch > 0:
            stats["stolen"] += 1
        stats["completed"] += 1
        # Label = the phase word ("scatter"/"gather"/"process"), not the
        # constant "elastic" prefix of the display name.
        obs.inc("elastic_units_completed_total", phase=phase.split()[-1])
        obs.fleet.record("unit.journaled", unit="{}{}".format(
            unit_prefix, unit), epoch=lease.epoch, holder=holder,
            phase=phase.split()[-1])
        remaining.discard(unit)
        progress.tick(sum(result.values())
                      if isinstance(result, dict) else 0)

    def drain(timeout):
        if not inflight:
            return
        done, _ = cf.wait(list(inflight), timeout=timeout,
                          return_when=cf.FIRST_COMPLETED)
        for fut in done:
            if fut not in inflight:
                continue  # a pool reset already dropped it
            try:
                handle_completed(fut)
            except BrokenProcessPool:
                nonlocal_executor_reset()

    def nonlocal_executor_reset():
        nonlocal executor
        log("{}: pool worker died; releasing {} in-flight lease(s) and "
            "rebuilding the pool".format(phase, len(inflight)))
        for fut in list(inflight):
            _, lease = drop_inflight(fut)
            leases.release(lease)
        if executor is not None:
            executor.shutdown(wait=False)
            executor = None

    try:
        while remaining:
            claimed_any = False
            inflight_units = {u for u, _ in inflight.values()}
            for unit in order:
                if len(inflight) >= max_inflight:
                    break
                if unit not in remaining or unit in inflight_units \
                        or unit in failed:
                    continue
                if is_done(unit) is not None:
                    remaining.discard(unit)
                    progress.tick()
                    continue
                if run_finalized():
                    remaining.clear()
                    break
                lease = leases.try_acquire(
                    lease_root, "{}{}".format(unit_prefix, unit), holder,
                    ttl)
                if lease is None:
                    continue  # validly held elsewhere (or race lost)
                if is_done(unit) is not None:
                    # Completion records publish BEFORE leases release, so
                    # re-checking after the acquire closes the race where
                    # our pre-claim is_done read predated the winner's
                    # publish: without this, we would sweep (and redo) a
                    # unit whose outputs are already final.
                    leases.release(lease)
                    remaining.discard(unit)
                    progress.tick()
                    continue
                if run_finalized():
                    # Checked AFTER the missing-record read, never before:
                    # a finalize landing between the two checks makes a
                    # COMPLETED unit's record read as missing, and
                    # proceeding to sweep would delete final shards the
                    # (already-deleted) spool can't regenerate. Dir still
                    # present here ⇒ the None above was genuine; dir gone
                    # ⇒ everything (including this unit) finished.
                    # try_acquire's makedirs may also have resurrected
                    # _leases in the finalized dir: release and prune.
                    leases.release(lease)
                    _prune_empty_scaffolding(spec["out_dir"])
                    remaining.clear()
                    break
                sweep(unit)
                keeper.add(lease)
                try:
                    fut = ensure_executor().submit(task, unit, lease.epoch,
                                                   holder)
                except BrokenProcessPool:
                    # The pool broke while we were scanning (a worker died
                    # between drains): submit itself raises. Hand back the
                    # just-claimed lease, tear the pool down, rescan.
                    keeper.remove(lease)
                    leases.release(lease)
                    nonlocal_executor_reset()
                    continue
                inflight[fut] = (unit, lease)
                inflight_units.add(unit)
                claimed_any = True
            if inflight:
                drain(timeout=poll)
            elif not claimed_any and remaining:
                # Everything left is held by other live hosts (or just
                # journaled): wait for records to appear or leases to
                # expire, then rescan.
                time.sleep(poll)
    finally:
        if executor is not None:
            executor.shutdown(wait=False)

    if failed:
        # Another host may still complete what we could not (our failure
        # released the lease). Wait a patience window that resets on any
        # progress — a completed record OR a live lease on the unit
        # (another host actively redoing it renews at ttl/3; its unit may
        # legitimately take many TTLs, so a fixed countdown would raise a
        # spurious failure on a run that globally succeeds).
        patience = max(2.0 * ttl, 3.0)
        deadline = time.monotonic() + patience
        while failed and time.monotonic() < deadline:
            if run_finalized():
                failed.clear()  # everything completed (and was retired)
                break
            progressing = False
            for u in sorted(failed):
                if is_done(u) is not None:
                    failed.pop(u)
                    progressing = True
                elif leases.is_live(lease_root,
                                    "{}{}".format(unit_prefix, u)):
                    progressing = True
            if progressing:
                deadline = time.monotonic() + patience
            if failed:
                time.sleep(poll)
        if failed:
            raise RuntimeError(
                "{} failed for {} unit(s) (this host: {}); completed units "
                "are journaled — re-run with resume=True/--resume to redo "
                "only the failures".format(phase, len(failed), failed))
    return stats


# --------------------------------------------------------------- pipeline


def _pool_factory_for(process_bucket, spec, workers, n_units):
    if workers <= 1 or n_units <= 1:
        return None

    def factory():
        import multiprocessing
        return cf.ProcessPoolExecutor(
            max_workers=min(workers, n_units),
            mp_context=multiprocessing.get_context("spawn"),
            initializer=_runner._pool_init,
            initargs=(process_bucket, spec))

    return factory


def _census_from_disk(out_dir):
    """Recover the {path: rows} census from the output files themselves —
    the fallback when another host finalized (and deleted ``_done``)
    between our last unit and our merge. Parquet rows come from footers;
    txt shards count lines."""
    import glob
    written = {}
    for path in sorted(glob.glob(os.path.join(out_dir, "part.*"))):
        if ".tmp." in path:
            continue
        if ".parquet" in path:
            import pyarrow.parquet as pq
            written[path] = pq.read_metadata(path).num_rows
    for path in sorted(glob.glob(os.path.join(out_dir, "*.txt*"))):
        if ".tmp." in path or os.path.basename(path).startswith("."):
            continue
        written[path] = rio.read_bytes(path).count(b"\n")
    return written


def _merge_census(out_dir, gather_units):
    """Union of every gather unit's ledger record — the global census (in
    elastic mode hosts do not own disjoint buckets, so every host returns
    the merged totals). The claim loop observed every record before this
    runs; a record missing NOW means another host's finalize is already
    deleting the ledger, and the on-disk output files (all final at this
    point) are the authoritative fallback."""
    written = {}
    for g in gather_units:
        rec = _runner._ledger_read(out_dir, g)
        if rec is None:
            _log.info("ledger record for unit %s already cleaned up by "
                      "another host's finalize; recovering the census "
                      "from the output files", g)
            return _census_from_disk(out_dir)
        written.update(rec)
    return written


def _finalize(spec, holder, ttl, keeper, log, poll):
    """Lease-guarded gather-side finalization: integrity manifest, spool/
    ledger/debris cleanup. Exactly-once in the common case; crash-tolerant
    because a dead finalizer's lease expires and a survivor redoes it
    (every step is idempotent: the manifest is deterministic, the rmtrees
    tolerate absence). The lease directory is deleted LAST — waiting
    hosts treat its disappearance as "finalized"."""
    from ..resilience.integrity import build_manifest

    out_dir = spec["out_dir"]
    root = leases.lease_root(out_dir)
    while True:
        if not os.path.isdir(root):
            return False  # another host finished the whole run
        lease = leases.try_acquire(root, _FINALIZE_UNIT, holder,
                                   max(ttl, 5.0))
        if lease is None:
            time.sleep(poll)
            continue
        keeper.add(lease)
        try:
            with obs.span("preprocess.finalize", holder=holder):
                if spec.get("emit_manifest", True):
                    build_manifest(out_dir, comm=LocalCommunicator(),
                                   log=log)
                if not leases.verify(lease):
                    obs.inc("lease_fence_rejects_total")
                    log("finalize: lease stolen mid-manifest; yielding to "
                        "the new finalizer")
                    time.sleep(poll)
                    continue
                if spec["global_shuffle"]:
                    shutil.rmtree(os.path.join(out_dir, _runner._SPOOL_DIR),
                                  ignore_errors=True)
                # Retire the ledger ATOMICALLY (rename, then delete the
                # renamed dir): hosts still scanning must see either the
                # complete record set or no ledger dir at all — a
                # half-deleted ledger reads as "unit not done" and would
                # trigger a catastrophic reclaim of finished outputs.
                # Stale retired dirs (a finalizer that died between ITS
                # rename and rmtree) are swept FIRST: renaming onto an
                # existing non-empty dir would fail ENOTEMPTY, and a
                # same-holder resume must not mistake that for "already
                # retired" and leave _done/ behind forever.
                import glob
                ledger = os.path.join(out_dir, _runner._LEDGER_DIR)
                for stale in sorted(glob.glob(ledger + ".retired.*")):
                    shutil.rmtree(stale, ignore_errors=True)
                retired = "{}.retired.{}".format(ledger, holder)
                try:
                    os.replace(ledger, retired)  # lddl: disable=atomic-publish
                except FileNotFoundError:
                    retired = None  # already retired by someone else
                if retired is not None:
                    shutil.rmtree(retired, ignore_errors=True)
                _runner._sweep_tmp_debris(out_dir)
                shutil.rmtree(root, ignore_errors=True)
                return True
        finally:
            keeper.remove(lease)


def run_elastic_pipeline(spec, process_bucket, log, *, holder_id, lease_ttl,
                         workers, progress_interval, t0, poll_s=None):
    """The elastic replacement for the static scatter/gather schedule.
    Called from ``runner._run_pipeline_body`` after the dirty-dir guard
    and fingerprint manifest check; every participating host runs this
    with identical arguments (modulo ``holder_id``)."""
    out_dir = spec["out_dir"]
    holder = (leases.sanitize_holder(holder_id) if holder_id
              else leases.default_holder())
    ttl = float(lease_ttl)
    if ttl <= 0:
        raise ValueError("lease_ttl must be > 0, got {}".format(lease_ttl))
    poll = poll_s if poll_s is not None else max(0.05, min(ttl / 4.0, 2.0))
    keeper = leases.LeaseKeeper(ttl)
    # Fleet spools (when armed) should carry the LEASE holder name, so
    # the status report's "host h0 stalled" and the lease events' "stolen
    # from h0" name the same thing; the env pin makes pool workers
    # publish into the same spool.
    obs.fleet.adopt_holder(holder, ttl=ttl)
    log("elastic preprocess: holder={} ttl={}s".format(holder, ttl))
    totals = {"completed": 0, "stolen": 0, "fence_rejects": 0}

    def add_stats(stats):
        for k in totals:
            totals[k] += stats[k]

    try:
        if spec["global_shuffle"]:
            n_slices = spec["scatter_units"]
            scatter_units = list(range(n_slices))
            factory = _pool_factory_for(process_bucket, spec, workers,
                                        n_slices)
            # The accept set: exactly the winning attempt's spool files
            # per slice, read back STABLY after every slice is journaled
            # — identical on every host regardless of who ran what. A
            # "retry" (a record withdrawn by a fenced loser who then
            # died) re-enters the claim loop, which skips done units and
            # redoes only the un-journaled one.
            while True:
                with obs.span("preprocess.scatter", elastic=True,
                              holder=holder):
                    add_stats(claim_loop(
                        spec, "elastic scatter", _SCATTER_PREFIX,
                        scatter_units,
                        holder=holder, ttl=ttl, keeper=keeper,
                        is_done=lambda u: _read_scatter_record(out_dir, u),
                        sweep=lambda u: _sweep_scatter(spec, u),
                        task=(_pool_scatter_slice if factory else
                              (lambda u, e, h: _scatter_slice(
                                  spec, u, e, h))),
                        publish=lambda u, res, lease:
                            _publish_scatter_record(out_dir, u, lease),
                        executor_factory=factory,
                        max_inflight=max(1, workers),
                        log=log, progress_interval=progress_interval,
                        poll_s=poll_s))
                status, recs = _stable_scatter_records(
                    out_dir, scatter_units, leases.lease_root(out_dir),
                    ttl, poll)
                if status != "retry":
                    break
                log("elastic scatter: a completion record was withdrawn "
                    "with no live holder; re-entering the claim loop")
            if status == "ok":
                spec["spool_accept"] = sorted(
                    spool_name(u, recs[u]["epoch"], recs[u]["holder"])
                    for u in scatter_units)
            else:
                log("elastic: run already finalized by another host during "
                    "this host's scatter phase")
            gather_units = list(range(spec["ngroups"]))
            gather_prefix, gather_phase = _GROUP_PREFIX, "elastic gather"
            gather_task_pool, gather_sweep = _pool_gather_group, _sweep_gather

            def serial_gather(u, e, h):
                return _runner._run_group(
                    spec, process_bucket, u,
                    fence=_fence_for(out_dir, _GROUP_PREFIX, u, e, h))
        else:
            gather_units = list(range(spec["nbuckets"]))
            gather_prefix, gather_phase = _BLOCK_PREFIX, "elastic process"
            gather_task_pool, gather_sweep = _pool_block_bucket, _sweep_block

            def serial_gather(u, e, h):
                return _runner._run_block_bucket(
                    spec, process_bucket, u,
                    fence=_fence_for(out_dir, _BLOCK_PREFIX, u, e, h))

        factory = _pool_factory_for(process_bucket, spec, workers,
                                    len(gather_units))
        with obs.span("preprocess.gather", elastic=True, holder=holder):
            add_stats(claim_loop(
                spec, gather_phase, gather_prefix, gather_units,
                holder=holder, ttl=ttl, keeper=keeper,
                is_done=lambda u: _runner._ledger_read(out_dir, u),
                sweep=lambda u: gather_sweep(spec, u),
                task=gather_task_pool if factory else serial_gather,
                publish=lambda u, res, lease: _publish_gather_record(
                    out_dir, u, res, lease),
                executor_factory=factory, max_inflight=max(1, workers),
                log=log, progress_interval=progress_interval,
                poll_s=poll_s))

        # Merge the global census BEFORE finalize can delete the ledger.
        written = _merge_census(out_dir, gather_units)
        log("elastic summary: holder={} units={} steals={} "
            "fence_rejects={}".format(holder, totals["completed"],
                                      totals["stolen"],
                                      totals["fence_rejects"]))
        _finalize(spec, holder, ttl, keeper, log, poll)
    finally:
        keeper.stop()

    elapsed = time.time() - t0  # lddl: disable=wall-clock (log-only rates)
    if obs.enabled():
        obs.set_gauge("preprocess_samples_per_second",
                      sum(written.values()) / max(elapsed, 1e-9))
        docs = obs.registry().counter("preprocess_docs_total").total()
        if docs:
            obs.set_gauge("preprocess_docs_per_second",
                          docs / max(elapsed, 1e-9))
    log("preprocess done in {:.1f}s, {} shards, {} samples (elastic, "
        "global census)".format(elapsed, len(written),
                                sum(written.values())))
    return written
