"""Elastic multi-host preprocessing: the lease-fenced work-stealing loop.

The static runner (:mod:`.runner`) schedules units by rank striding and
meets at barriers — one dead host wedges the phase (MPI semantics, exactly
what the reference inherits from dask-mpi). This module replaces the
schedule with a **claim loop** over the same units: N independent host
processes — no jax.distributed, no barriers, nothing shared but the
output directory — each repeatedly

    1. pick a unit whose completion record is absent,
    2. claim it via an atomic-rename lease (:mod:`..resilience.leases`),
    3. sweep any previous attempt's partial outputs,
    4. run it (serially or on the host's local spawn pool),
    5. fence-check the lease and, only if still held at the claimed
       epoch, journal the completion record,

until every unit is journaled. A host that dies mid-unit simply stops
renewing its lease; after one TTL any survivor steals the unit (epoch
bump), sweeps the debris, and redoes it. A host that *stalls* and
resurrects after a steal fails the fence check and discards its late
result (``lease_fence_rejects_total``) — the ledger only ever sees one
winner per unit.

Determinism contract: a unit's output bytes are a pure function of the
resume fingerprint and the unit id (PR 1/4 machinery). Leases decide WHO
runs a unit, never what it produces, so an elastic run of any host count,
with any sequence of host deaths, is byte-identical to a static
single-host run of the same plan (chaos-pinned in tests/test_chaos.py).

Unit kinds and their fencing:

- **scatter slices** (blocks ``unit, unit+S, ...`` of the plan): spool
  appends are not idempotent, so every claim attempt writes its own
  exclusively-owned files ``group-<g>/s<slice>.e<epoch>.<holder>.txt``
  and the completion record stores the winning ``(epoch, holder)``. The
  gather trusts ONLY the recorded file names — a fenced-off zombie's late
  appends land in files nothing ever reads.
- **gather groups** (coarse spool groups) / **blocks** (no-shuffle mode):
  outputs are whole shard files published atomically under deterministic
  names, so a zombie rewriting them is byte-identical by construction;
  the fence protects the ledger record itself.
- **finalize** (manifest + cleanup) is itself a lease-guarded unit — the
  last host out runs it, and if it dies mid-finalize a survivor steals
  that too. The lease directory is removed last: its disappearance is
  the "run complete" signal waiting hosts poll for.
"""

import concurrent.futures as cf
import hashlib
import json
import logging
import os
import shutil
import time

from .. import observability as obs
from ..parallel.distributed import LocalCommunicator
from ..resilience import io as rio
from ..resilience import leases
from . import runner as _runner

_FINALIZE_UNIT = "finalize"
_SCATTER_PREFIX = "scatter-"
_GROUP_PREFIX = "group-"
_BLOCK_PREFIX = "block-"

_log = logging.getLogger("lddl_tpu.preprocess.steal")


def _fence_for(out_dir, prefix, unit, epoch, holder, deadline=0.0):
    """A fence closure for unit bodies (works across the pool process
    boundary: everything needed to re-check the lease travels as plain
    values and the closure is rebuilt inside the worker). Deadline-cached
    via :func:`leases.fence_at` — while the wall clock is inside the last
    deadline the fence read (seeded with the claim-time ``deadline`` when
    the submitter passes it), the check costs no filesystem op; past it,
    a real read refreshes from the keeper-renewed record. False once the
    unit's lease stops naming exactly this (holder, epoch) attempt."""
    root = leases.lease_root(out_dir)
    key = "{}{}".format(prefix, unit)
    return leases.fence_at(root, key, holder, epoch, deadline=deadline)


# ------------------------------------------------------------ unit records


def _scatter_record_path(out_dir, unit):
    return os.path.join(out_dir, _runner._LEDGER_DIR,
                        "scatter-{}.json".format(unit))


def _read_scatter_record(out_dir, unit):
    """A scatter slice's completion record ({"epoch", "holder"}), or None.
    Torn bytes degrade to "not done" with a warning, like `_ledger_read`."""
    rec, status = rio.read_json(_scatter_record_path(out_dir, unit))
    if status == "torn":
        _log.warning("torn scatter record for unit %s; treating as not "
                     "done", unit)
        return None
    return rec if isinstance(rec, dict) else None


def _publish_scatter_record(out_dir, unit, lease, wall=None):
    """Journal a completed scatter slice. The record IS the epoch fence
    for spool bytes: it names the one (epoch, holder) attempt whose files
    the gather may read — so lease state flowing into this _done record
    is the design, not a leak (it never reaches shard bytes or
    .manifest.json; the analyzer's lease-isolation rule guards those).
    ``wall`` (a monotonic duration, seconds — never a wall-clock instant)
    rides probe records so the adaptive plan can size the remaining units
    from observed throughput; like epoch/holder it stays scheduling
    state, retired with the ledger at finalize.

    Returns the journaled record dict on success (the claim loop feeds it
    to incremental consumers), False on a post-publish fence loss."""
    path = _scatter_record_path(out_dir, unit)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    record = {"epoch": lease.epoch, "holder": lease.holder}
    if wall is not None:
        record["wall"] = round(float(wall), 6)
    payload = json.dumps(record, sort_keys=True)
    # Fence record by design (see docstring): epoch+holder+probe wall,
    # never shard bytes.
    rio.atomic_write(path, payload)  # lddl: disable=lease-isolation,wall-clock-flow
    # Post-publish fence re-check: if the lease was stolen in the tiny
    # window between the pre-publish verify and this write, the thief may
    # ALREADY have journaled its own record — which our stale write just
    # clobbered with file names the thief swept. Re-read: if the record on
    # disk is ours but the lease is not, withdraw it so the unit is redone
    # rather than pointing at deleted spool files.
    if not leases.verify(lease):
        cur = _read_scatter_record(out_dir, unit)
        if cur == record:
            # Backend-routed withdrawal: on the mock store a raw unlink
            # would leave the record's commit records readable.
            rio.remove(path)
        _prune_empty_scaffolding(out_dir)
        return False
    return record


def _prune_empty_scaffolding(out_dir):
    """Best-effort removal of `_done`/`_leases` dirs a zombie's late write
    resurrected AFTER finalize retired them (os.makedirs inside the
    publish/acquire paths recreates the dir). rmdir only succeeds on
    empty dirs, so a live run's scaffolding is never touched."""
    for d in (os.path.join(out_dir, _runner._LEDGER_DIR),
              leases.lease_root(out_dir)):
        try:
            os.rmdir(d)
        # Non-empty (live run) or already gone: both fine by design.
        except OSError:  # lddl: disable=swallowed-error
            pass


def _publish_gather_record(out_dir, unit, result, lease):
    """Journal a completed gather unit, with the same post-publish fence
    re-check the scatter path has: if the lease was lost in the window
    between the claim loop's verify and this write, the record is
    withdrawn — a stalled zombie must not resurrect `_done/` inside an
    already-finalized output dir (and in the live-thief case a withdrawn
    record merely makes the unit's owner republish identical bytes).
    Returns the journaled record (= ``result``) on success so the
    incremental gather can consume it without re-reading the ledger."""
    _runner._ledger_write(out_dir, unit, result)
    if not leases.verify(lease):
        rio.remove(_runner._ledger_path(out_dir, unit))
        _prune_empty_scaffolding(out_dir)
        return False
    return result


def spool_name(unit, epoch, holder):
    """The exclusive spool file name of one scatter claim attempt (per
    coarse group). Epoch+holder make every attempt's files disjoint."""
    return "s{}.e{}.{}.txt".format(unit, epoch, holder)


# --------------------------------------------------- adaptive unit sizing
#
# Fixed scatter units make small corpora coordination-bound: the lease
# acquire/renew/fence cost per unit is flat regardless of how little work
# the unit holds. Adaptive mode probes first — a few small leading slices
# whose completion records carry their observed wall — then one
# lease-guarded PLAN unit sizes the remaining blocks into contiguous
# ranges targeting a wall of K × (measured lease round-trip). The plan is
# journaled in ``_done/scatter-plan.json`` so every host (and every
# resume) partitions identically; byte identity is untouched either way
# because the gather sorts blocks by block id across the whole accept set
# — unit boundaries only ever decide WHO spools a block, never where its
# text lands.

_PLAN_UNIT = "scatter-plan"
_PLAN_TARGET_K = 64.0


def _probe_layout(nblocks):
    """The fixed leading probe slices: up to 4 contiguous ranges covering
    at most ~1/8 of the blocks (1 block each on small plans). Deterministic
    in nblocks alone, so every host agrees on probe identity before any
    coordination happens."""
    n_probe = min(nblocks, 4)
    if n_probe <= 0:
        return []
    span = max(1, nblocks // (8 * n_probe))
    return [("p{}".format(i), i * span, (i + 1) * span)
            for i in range(n_probe)]


def _scatter_unit_blocks(spec, unit, nblocks):
    """The block indices one scatter unit owns. String units are probes
    (contiguous leading ranges); int units are plan ranges when an
    adaptive plan is loaded, else the classic ``unit, unit+S, ...``
    stride of fixed mode."""
    if isinstance(unit, str):
        for key, s, e in _probe_layout(nblocks):
            if key == unit:
                return range(s, min(e, nblocks))
        raise ValueError("unknown probe unit {!r}".format(unit))
    plan = spec.get("scatter_plan")
    if plan is not None:
        s, e = plan["main"][int(unit)]
        return range(s, min(e, nblocks))
    return range(unit, nblocks, spec["scatter_units"])


def _plan_record_path(out_dir):
    return os.path.join(out_dir, _runner._LEDGER_DIR,
                        "{}.json".format(_PLAN_UNIT))


def _read_plan_record(out_dir):
    rec, status = rio.read_json(_plan_record_path(out_dir))
    if status == "torn":
        _log.warning("torn scatter plan record; treating as absent")
        return None
    if isinstance(rec, dict) and isinstance(rec.get("main"), list):
        return rec
    return None


def _read_plan_stable(out_dir, poll):
    """Double-read the plan record (same clobber-then-withdraw window
    argument as :func:`_stable_scatter_records`): a plan must never be
    adopted from a fenced loser's transient record, because two hosts
    running DIFFERENT partitions under the same unit indices would journal
    ranges that don't line up."""
    first = _read_plan_record(out_dir)
    if first is None:
        return None
    time.sleep(min(poll, 0.05))
    second = _read_plan_record(out_dir)
    return second if second == first else None


def _lease_overhead_s(lease):
    """Measured lease round-trip (read + match), the unit-sizing yardstick.
    Monotonic durations only — the plan never sees a wall-clock instant."""
    t0 = time.monotonic()
    for _ in range(3):
        leases.verify_at(lease.root, lease.unit, lease.holder, lease.epoch)
    return max((time.monotonic() - t0) / 3.0, 1e-6)


def _compute_plan(out_dir, probes, nblocks, lease):
    """Size the post-probe blocks into contiguous ranges whose predicted
    wall is ~K× the measured lease overhead (clamped to [2s, 120s]), with
    at least min(rest, 8) units so a small corpus still fans out across
    hosts. Probe records missing a wall (fenced redo races) simply don't
    vote; with no votes at all the split degrades to the fixed-mode
    formula — the plan only ever shapes scheduling, never bytes."""
    import math
    walls, probed = [], 0
    for key, s, e in probes:
        rec = _read_scatter_record(out_dir, key)
        w = rec.get("wall") if isinstance(rec, dict) else None
        if isinstance(w, (int, float)) and w >= 0:
            walls.append(float(w))
            probed += max(1, min(e, nblocks) - s)
    covered = min(probes[-1][2], nblocks) if probes else 0
    rest = max(0, nblocks - covered)
    plan = {"epoch": lease.epoch, "holder": lease.holder, "main": []}
    if rest == 0:
        return plan
    if walls:
        per_block = max(sum(walls) / max(probed, 1), 1e-6)
        target = min(max(_PLAN_TARGET_K * _lease_overhead_s(lease), 2.0),
                     120.0)
        per_unit = max(1, int(target / per_block))
        n_units = min(rest, max(min(rest, 8),
                                int(math.ceil(rest / float(per_unit)))))
        plan["per_block_s"] = round(per_block, 6)
        plan["target_wall_s"] = round(target, 3)
    else:
        n_units = min(rest, max(8, rest // 16))
    base, extra = divmod(rest, n_units)
    start = covered
    for i in range(n_units):
        size = base + (1 if i < extra else 0)
        plan["main"].append([start, start + size])
        start += size
    return plan


def _ensure_plan(spec, probes, nblocks, holder, ttl, keeper, poll, log):
    """Read-or-compute the adaptive scatter plan, exactly-once via the
    ``scatter-plan`` lease (crash-tolerant like every other unit: a dead
    planner's lease expires and a survivor recomputes from the journaled
    probe walls). The plan is coordination metadata, not a work unit — it
    does not count toward ``elastic_units_completed_total`` and emits no
    ``unit.journaled`` event. Returns None when another host already
    finalized the whole run."""
    out_dir = spec["out_dir"]
    root = leases.lease_root(out_dir)
    ledger_dir = os.path.join(out_dir, _runner._LEDGER_DIR)
    while True:
        rec = _read_plan_stable(out_dir, poll)
        if rec is not None:
            return rec
        if not os.path.isdir(ledger_dir):
            return None  # finalized under us
        lease = leases.try_acquire(root, _PLAN_UNIT, holder, ttl)
        if lease is None:
            time.sleep(poll)
            continue
        keeper.add(lease)
        try:
            rec = _read_plan_record(out_dir)  # post-acquire re-check
            if rec is not None:
                return rec
            plan = _compute_plan(out_dir, probes, nblocks, lease)
            if not leases.verify(lease):
                continue
            path = _plan_record_path(out_dir)
            os.makedirs(os.path.dirname(path), exist_ok=True)
            payload = json.dumps(plan, sort_keys=True)
            # Scheduling metadata fenced like a scatter record (see
            # _publish_scatter_record): epoch/holder + monotonic durations.
            rio.atomic_write(path, payload)  # lddl: disable=lease-isolation,wall-clock-flow
            if not leases.verify(lease):
                cur = _read_plan_record(out_dir)
                if cur == plan:
                    rio.remove(path)
                _prune_empty_scaffolding(out_dir)
                continue
            log("elastic scatter: adaptive plan journaled ({} probe(s) + "
                "{} main unit(s) over {} blocks)".format(
                    len(probes), len(plan["main"]), nblocks))
            return plan
        finally:
            keeper.remove(lease)
            leases.release(lease)


def _stable_scatter_records(out_dir, scatter_units, lease_root, ttl, poll):
    """Read every scatter record until two consecutive sweeps agree.

    Returns ``("ok", {unit: record})``, ``("finalized", None)`` when
    another host already finalized the whole run, or ``("retry", None)``
    when a record is missing with no live lease — a fenced loser's
    clobber-then-withdraw transiently un-journaled the unit and the
    withdrawer died before redoing it, so the caller must re-enter the
    claim loop. The double read closes the window in which an accept set
    built from a loser's transient record would name spool files the
    winner's sweep deleted; what remains requires two suspensions at
    exactly the wrong microseconds AND is still bounded by this
    function's own re-read."""
    ledger_dir = os.path.join(out_dir, _runner._LEDGER_DIR)
    patience = max(2.0 * ttl, 3.0)
    deadline = time.monotonic() + patience
    prev = None
    while True:
        if not os.path.isdir(ledger_dir):
            return "finalized", None
        recs = {}
        missing = None
        for u in scatter_units:
            rec = _read_scatter_record(out_dir, u)
            if rec is None:
                missing = u
                break
            recs[u] = rec
        if missing is None:
            if recs == prev:
                return "ok", recs
            prev = recs
            time.sleep(min(poll, 0.05))
            continue
        prev = None
        if leases.is_live(lease_root,
                          "{}{}".format(_SCATTER_PREFIX, missing)):
            # Someone is actively republishing/redoing it: keep waiting.
            deadline = time.monotonic() + patience
        elif time.monotonic() >= deadline:
            return "retry", None
        time.sleep(poll)


# -------------------------------------------------------------- unit tasks
#
# Module-level so spawn pools can pickle them; serial mode calls them
# directly via closures built in run_elastic_pipeline. All take
# (unit, epoch, holder, deadline) so the claimed attempt's identity
# reaches the spool file names and the claim-time lease deadline seeds
# the worker-side fence cache.


def _scatter_slice(spec, unit, epoch, holder, deadline=0.0):
    """Scatter all blocks of one slice (:func:`_scatter_unit_blocks` —
    a fixed stride, a probe range, or a plan range) into this attempt's
    exclusive spool files, self-terminating between blocks if the lease
    is stolen (appends after a steal would only be debris — fenced out by
    name — but stopping early keeps the thief's sweep meaningful and the
    host honest)."""
    input_files = _runner.discover_source_files(spec["corpus_paths"])
    blocks = _runner.plan_blocks(input_files, spec["num_blocks"])
    name = spool_name(unit, epoch, holder)
    fence = _fence_for(spec["out_dir"], _SCATTER_PREFIX, unit, epoch, holder,
                       deadline=deadline)
    n = 0
    for b in _scatter_unit_blocks(spec, unit, len(blocks)):
        _runner._check_fence(fence, unit)
        _runner._spool_one_block(blocks[b], spec["out_dir"], spec["seed"],
                                 spec["sample_ratio"], len(blocks),
                                 spec["ngroups"], name)
        n += 1
    return n


def _pool_scatter_slice(unit, epoch, holder, deadline=0.0):
    return _scatter_slice(_runner._POOL["spec"], unit, epoch, holder,
                          deadline=deadline)


def _pool_gather_group(unit, epoch, holder, deadline=0.0):
    spec = _runner._POOL["spec"]
    return _runner._run_group(
        spec, _runner._POOL["process_bucket"], unit,
        fence=_fence_for(spec["out_dir"], _GROUP_PREFIX, unit, epoch,
                         holder, deadline=deadline))


def _pool_block_bucket(unit, epoch, holder, deadline=0.0):
    spec = _runner._POOL["spec"]
    return _runner._run_block_bucket(
        spec, _runner._POOL["process_bucket"], unit,
        fence=_fence_for(spec["out_dir"], _BLOCK_PREFIX, unit, epoch,
                         holder, deadline=deadline))


# ------------------------------------------------------------------ sweeps


def _sweep_scatter(spec, unit):
    """Remove EVERY attempt's spool files for a reclaimed scatter slice
    (all epochs/holders: only the attempt about to run may have files)."""
    import glob
    pattern = os.path.join(spec["out_dir"], _runner._SPOOL_DIR, "group-*",
                           "s{}.e*".format(unit))
    n = 0
    for path in sorted(glob.glob(pattern)):
        try:
            os.remove(path)
            n += 1
        except FileNotFoundError:
            pass
    if n:
        obs.inc("elastic_swept_files_total", int(n))
    return n


def _sweep_gather(spec, unit):
    """Remove a reclaimed gather group's partial bucket outputs (final
    part files AND ``*.tmp.*`` atomic-write debris — the exact-prefix
    globs in `_clean_bucket_outputs` cover both)."""
    for bucket in _runner._buckets_of_group(unit, spec["nbuckets"],
                                            spec["ngroups"]):
        _runner._clean_bucket_outputs(spec["out_dir"], bucket)


def _sweep_block(spec, unit):
    _runner._clean_bucket_outputs(spec["out_dir"], unit)


# -------------------------------------------------------------- claim loop


class _InlineExecutor(object):
    """Executor shim for serial hosts: submit() runs the task inline and
    returns an already-settled Future, so the claim loop has one shape."""

    def submit(self, fn, *args):
        fut = cf.Future()
        try:
            fut.set_result(fn(*args))
        except BaseException as e:  # noqa: BLE001 - future carries it
            fut.set_exception(e)
        return fut

    def shutdown(self, wait=True):
        pass


def _rotated(units, holder):
    """Deterministic per-holder rotation of the unit scan order, so N
    hosts starting together fan out across the unit space instead of
    racing for unit 0. Pure scheduling: never shapes output bytes."""
    order = sorted(units)
    if not order:
        return order
    start = int.from_bytes(
        hashlib.blake2b(holder.encode(), digest_size=4).digest(),
        "little") % len(order)
    return order[start:] + order[:start]


def claim_loop(spec, phase, unit_prefix, units, *, holder, ttl, keeper,
               is_done, sweep, task, publish, executor_factory, max_inflight,
               log, progress_interval=5.0, poll_s=None, ledger_name=None,
               on_record=None, unit_walls=None):
    """Run every unit to completion across all participating hosts.

    Returns a stats dict. Raises RuntimeError (with the standard
    "re-run with resume" message) if units failed on this host and no
    other host completed them within the patience window.

    - ``is_done(unit)`` — the unit's completion record, or None when not
      done. Done-ness is record EXISTENCE (``is not None``): an empty
      ``{}`` record from a zero-sample unit is still done.
    - ``sweep(unit)`` — remove a prior attempt's partial outputs; called
      on EVERY claim before running (cheap no-op on first attempts).
    - ``task(unit, epoch, holder, deadline)`` — the unit body; picklable
      when an ``executor_factory`` is given (spawn pool), else any
      callable. ``deadline`` is the claim-time lease deadline, seeding
      the body's deadline-cached fence (``leases.fence_at``).
    - ``publish(unit, result, lease)`` — journal completion; called only
      after the fence check passed. May return False to signal a
      post-publish fence loss (the unit stays pending); any other return
      value is treated as the journaled record.
    - ``ledger_name(unit)`` — the unit's completion-record FILE NAME.
      When given (and ``LDDL_TPU_COORD_LEGACY`` is unset), each scan pass
      snapshots the ledger dir and the lease dir ONCE and skips per-unit
      ``is_done``/lease reads that the snapshots already answer; every
      decision that matters (post-acquire re-check, fence, publish) still
      rides a real read, so a stale snapshot costs at most one extra pass.
    - ``on_record(unit, record)`` — fired once per unit the first time
      its completion record is observed (pre-done at entry, discovered
      mid-scan, found post-acquire, or journaled by this host). Lets the
      gather consume records incrementally instead of barriering.
    - ``unit_walls`` — optional dict filled with each locally-completed
      unit's monotonic task wall (seconds); probe publishes read it so
      observed throughput reaches the adaptive plan.
    """
    from concurrent.futures.process import BrokenProcessPool

    lease_root = leases.lease_root(spec["out_dir"])
    ledger_dir = os.path.join(spec["out_dir"], _runner._LEDGER_DIR)
    use_snapshot = ledger_name is not None and not leases.legacy_coordination()
    held_cache = {} if use_snapshot else None
    seen_records = set()

    def record_seen(unit, rec):
        if on_record is not None and rec is not None \
                and unit not in seen_records:
            seen_records.add(unit)
            on_record(unit, rec)

    def list_ledger():
        """One listing of ``_done`` per scan pass (backend-routed: on the
        mock store this is the ``list`` fault site, so chaos runs can
        serve a stale snapshot here): a name absent from the snapshot is
        definitely not journaled (records only ever appear; they are
        withdrawn so rarely the next pass absorbs it), so the per-unit
        is_done read is skipped for it. A STALE listing only delays
        discovery by one pass — record reads, not listings, are what the
        claim loop trusts for done-ness."""
        names = rio.list_dir(ledger_dir)
        return set() if names is None else set(names)

    def run_finalized():
        """True once another host's finalize has retired the ledger. The
        finalizer renames ``_done`` away atomically before deleting it, so
        "completion record missing AND ledger dir missing" unambiguously
        means "everything finished" — never "unit needs redoing". Without
        this, a host racing the finalize would reclaim a finished unit,
        sweep its FINAL outputs, and regenerate them from a spool that no
        longer exists."""
        return not os.path.isdir(ledger_dir)

    poll = poll_s if poll_s is not None else max(0.05, min(ttl / 4.0, 2.0))
    stats = {"units": len(units), "completed": 0, "stolen": 0,
             "fence_rejects": 0, "already_done": 0}
    # Done-ness is "a record EXISTS", never record truthiness: a gather
    # unit whose buckets produced zero samples journals a legitimately
    # empty {} record, and treating that as "not done" would make every
    # host redo empty units forever (the static resume path compares
    # `is None` for the same reason).
    remaining = set()
    entry_names = list_ledger() if use_snapshot else None
    for u in units:
        if entry_names is not None and ledger_name(u) not in entry_names:
            remaining.add(u)
            continue
        rec = is_done(u)
        if rec is None:
            remaining.add(u)
        else:
            record_seen(u, rec)
    stats["already_done"] = len(units) - len(remaining)
    progress = _runner._Progress(log, phase, len(remaining),
                                 interval_s=progress_interval)
    order = _rotated(units, holder)
    failed = {}
    inflight = {}  # future -> (unit, lease)
    executor = None

    def ensure_executor():
        nonlocal executor
        if executor is None:
            executor = (executor_factory() if executor_factory is not None
                        else _InlineExecutor())
        return executor

    start_times = {}  # future -> monotonic submit time (unit_walls only)

    def drop_inflight(fut):
        unit, lease = inflight.pop(fut)
        started = start_times.pop(fut, None)
        keeper.remove(lease)
        return unit, lease, started

    def fence_reject(unit, lease, why):
        stats["fence_rejects"] += 1
        obs.inc("lease_fence_rejects_total")
        obs.event("lease.fence_reject", unit="{}{}".format(
            unit_prefix, unit), epoch=lease.epoch)
        obs.fleet.record("unit.fenced", unit="{}{}".format(
            unit_prefix, unit), epoch=lease.epoch, holder=holder, why=why)
        log("{}: unit {} {} at epoch {}; late result discarded "
            "(fence)".format(phase, unit, why, lease.epoch))

    def handle_completed(fut):
        unit, lease, started = drop_inflight(fut)
        try:
            result = fut.result()
        except BrokenProcessPool:
            # A dead pool worker breaks the whole pool and names no
            # culprit. Release so any host (us included) can reclaim
            # immediately; the per-claim sweep redoes partial outputs.
            leases.release(lease)
            raise
        except leases.LeaseLost:
            # The unit body self-terminated mid-run (the thief owns the
            # unit now). Not a failure: the winner's record will appear.
            fence_reject(unit, lease, "self-terminated (stolen)")
            return
        except Exception as e:  # noqa: BLE001 - isolate per unit
            if not leases.still_held(lease):
                # An error on a unit we no longer own is zombie noise,
                # not a unit failure: a thief may have swept our spool
                # files mid-append, or a finalizer may already be
                # deleting the run's scaffolding under us.
                fence_reject(unit, lease,
                             "errored after losing its lease "
                             "({}: {})".format(type(e).__name__, e))
                return
            leases.release(lease)
            failed[unit] = "{}: {}".format(type(e).__name__, e)
            obs.fleet.record("unit.failed", unit="{}{}".format(
                unit_prefix, unit), epoch=lease.epoch, holder=holder,
                error=failed[unit][:200])
            remaining.discard(unit)
            log("{}: unit {} failed ({}); lease released for another "
                "host".format(phase, unit, failed[unit]))
            return
        if unit_walls is not None and started is not None:
            unit_walls[unit] = time.monotonic() - started
        if not leases.still_held(lease):
            # Stolen while we ran (we stalled past the deadline): the
            # thief owns the unit now; discard our late result. Inside
            # the deadline this look is free (leases.still_held); the
            # load-bearing fence is publish's post-publish re-verify.
            fence_reject(unit, lease, "was stolen while this host ran it")
            return
        pub = publish(unit, result, lease)
        if pub is False:
            fence_reject(unit, lease, "lost its lease during publish")
            return
        record_seen(unit, pub if isinstance(pub, dict) else result)
        leases.release(lease)
        if lease.epoch > 0:
            stats["stolen"] += 1
        stats["completed"] += 1
        # Label = the phase word ("scatter"/"gather"/"process"), not the
        # constant "elastic" prefix of the display name.
        obs.inc("elastic_units_completed_total", phase=phase.split()[-1])
        obs.fleet.record("unit.journaled", unit="{}{}".format(
            unit_prefix, unit), epoch=lease.epoch, holder=holder,
            phase=phase.split()[-1])
        remaining.discard(unit)
        progress.tick(sum(result.values())
                      if isinstance(result, dict) else 0)

    def drain(timeout):
        if not inflight:
            return
        done, _ = cf.wait(list(inflight), timeout=timeout,
                          return_when=cf.FIRST_COMPLETED)
        for fut in done:
            if fut not in inflight:
                continue  # a pool reset already dropped it
            try:
                handle_completed(fut)
            except BrokenProcessPool:
                nonlocal_executor_reset()

    def nonlocal_executor_reset():
        nonlocal executor
        log("{}: pool worker died; releasing {} in-flight lease(s) and "
            "rebuilding the pool".format(phase, len(inflight)))
        for fut in list(inflight):
            _, lease, _ = drop_inflight(fut)
            leases.release(lease)
        if executor is not None:
            executor.shutdown(wait=False)
            executor = None

    try:
        while remaining:
            claimed_any = False
            inflight_units = {u for u, _ in inflight.values()}
            # Per-pass snapshots (batched coordination): one _done listdir
            # answers "which units are journaled", one _leases scan feeds
            # try_acquire's known_missing fast path, and held_cache skips
            # re-reading leases whose observed deadline hasn't passed.
            pass_names = list_ledger() if use_snapshot else None
            pass_leases = (leases.scan_units(lease_root) if use_snapshot
                           else None)
            for unit in order:
                if len(inflight) >= max_inflight:
                    break
                if unit not in remaining or unit in inflight_units \
                        or unit in failed:
                    continue
                if pass_names is not None \
                        and ledger_name(unit) not in pass_names:
                    rec = None
                else:
                    rec = is_done(unit)
                if rec is not None:
                    record_seen(unit, rec)
                    remaining.discard(unit)
                    progress.tick()
                    continue
                if run_finalized():
                    remaining.clear()
                    break
                key = "{}{}".format(unit_prefix, unit)
                lease = leases.try_acquire(
                    lease_root, key, holder, ttl,
                    known_missing=(pass_leases is not None
                                   and key not in pass_leases),
                    held_cache=held_cache)
                if lease is None:
                    continue  # validly held elsewhere (or race lost)
                rec = is_done(unit)
                if rec is not None:
                    # Completion records publish BEFORE leases release, so
                    # re-checking after the acquire closes the race where
                    # our pre-claim is_done read predated the winner's
                    # publish: without this, we would sweep (and redo) a
                    # unit whose outputs are already final. Always a REAL
                    # read — never the snapshot.
                    record_seen(unit, rec)
                    leases.release(lease)
                    remaining.discard(unit)
                    progress.tick()
                    continue
                if run_finalized():
                    # Checked AFTER the missing-record read, never before:
                    # a finalize landing between the two checks makes a
                    # COMPLETED unit's record read as missing, and
                    # proceeding to sweep would delete final shards the
                    # (already-deleted) spool can't regenerate. Dir still
                    # present here ⇒ the None above was genuine; dir gone
                    # ⇒ everything (including this unit) finished.
                    # try_acquire's makedirs may also have resurrected
                    # _leases in the finalized dir: release and prune.
                    leases.release(lease)
                    _prune_empty_scaffolding(spec["out_dir"])
                    remaining.clear()
                    break
                sweep(unit)
                keeper.add(lease)
                try:
                    # Submit time taken BEFORE submit: the inline executor
                    # runs the task inside submit(), so an after-the-fact
                    # stamp would record a zero wall.
                    t_submit = time.monotonic()
                    fut = ensure_executor().submit(task, unit, lease.epoch,
                                                   holder, lease.deadline)
                except BrokenProcessPool:
                    # The pool broke while we were scanning (a worker died
                    # between drains): submit itself raises. Hand back the
                    # just-claimed lease, tear the pool down, rescan.
                    keeper.remove(lease)
                    leases.release(lease)
                    nonlocal_executor_reset()
                    continue
                if unit_walls is not None:
                    start_times[fut] = t_submit
                inflight[fut] = (unit, lease)
                inflight_units.add(unit)
                claimed_any = True
            if inflight:
                drain(timeout=poll)
            elif not claimed_any and remaining:
                # Everything left is held by other live hosts (or just
                # journaled): wait for records to appear or leases to
                # expire, then rescan.
                time.sleep(poll)
    finally:
        if executor is not None:
            executor.shutdown(wait=False)

    if failed:
        # Another host may still complete what we could not (our failure
        # released the lease). Wait a patience window that resets on any
        # progress — a completed record OR a live lease on the unit
        # (another host actively redoing it renews at ttl/3; its unit may
        # legitimately take many TTLs, so a fixed countdown would raise a
        # spurious failure on a run that globally succeeds).
        patience = max(2.0 * ttl, 3.0)
        deadline = time.monotonic() + patience
        while failed and time.monotonic() < deadline:
            if run_finalized():
                failed.clear()  # everything completed (and was retired)
                break
            progressing = False
            for u in sorted(failed):
                if is_done(u) is not None:
                    failed.pop(u)
                    progressing = True
                elif leases.is_live(lease_root,
                                    "{}{}".format(unit_prefix, u)):
                    progressing = True
            if progressing:
                deadline = time.monotonic() + patience
            if failed:
                time.sleep(poll)
        if failed:
            raise RuntimeError(
                "{} failed for {} unit(s) (this host: {}); completed units "
                "are journaled — re-run with resume=True/--resume to redo "
                "only the failures".format(phase, len(failed), failed))
    return stats


# --------------------------------------------------------------- pipeline


def _pool_factory_for(process_bucket, spec, workers, n_units):
    if workers <= 1 or n_units <= 1:
        return None

    def factory():
        import multiprocessing
        return cf.ProcessPoolExecutor(
            max_workers=min(workers, n_units),
            mp_context=multiprocessing.get_context("spawn"),
            initializer=_runner._pool_init,
            initargs=(process_bucket, spec))

    return factory


def _census_from_disk(out_dir):
    """Recover the {path: rows} census from the output files themselves —
    the fallback when another host finalized (and deleted ``_done``)
    between our last unit and our merge. Parquet rows come from footers;
    txt shards count lines."""
    import glob
    written = {}
    for path in sorted(glob.glob(os.path.join(out_dir, "part.*"))):
        if ".tmp." in path:
            continue
        if ".parquet" in path:
            import pyarrow.parquet as pq
            written[path] = pq.read_metadata(path).num_rows
    for path in sorted(glob.glob(os.path.join(out_dir, "*.txt*"))):
        if ".tmp." in path or os.path.basename(path).startswith("."):
            continue
        written[path] = rio.read_bytes(path).count(b"\n")
    return written


def _merge_census(out_dir, gather_units, census=None, consumed=()):
    """Union of every gather unit's ledger record — the global census (in
    elastic mode hosts do not own disjoint buckets, so every host returns
    the merged totals). Units already consumed incrementally (the
    overlapped gather's ``on_record`` hook) are not re-read: a gather
    unit's record content is a pure function of the plan, so the copy
    consumed in flight equals what a barrier read would see even if the
    record was withdrawn and republished in between. A record missing NOW
    means another host's finalize is already deleting the ledger, and the
    on-disk output files (all final at this point) are the authoritative
    fallback."""
    written = dict(census or {})
    for g in gather_units:
        if g in consumed:
            continue
        rec = _runner._ledger_read(out_dir, g)
        if rec is None:
            _log.info("ledger record for unit %s already cleaned up by "
                      "another host's finalize; recovering the census "
                      "from the output files", g)
            return _census_from_disk(out_dir)
        written.update(rec)
    return written


def _finalize(spec, holder, ttl, keeper, log, poll):
    """Lease-guarded gather-side finalization: integrity manifest, spool/
    ledger/debris cleanup. Exactly-once in the common case; crash-tolerant
    because a dead finalizer's lease expires and a survivor redoes it
    (every step is idempotent: the manifest is deterministic, the rmtrees
    tolerate absence). The lease directory is deleted LAST — waiting
    hosts treat its disappearance as "finalized"."""
    from ..resilience.integrity import build_manifest

    out_dir = spec["out_dir"]
    root = leases.lease_root(out_dir)
    while True:
        if not os.path.isdir(root):
            return False  # another host finished the whole run
        lease = leases.try_acquire(root, _FINALIZE_UNIT, holder,
                                   max(ttl, 5.0))
        if lease is None:
            time.sleep(poll)
            continue
        keeper.add(lease)
        try:
            with obs.span("preprocess.finalize", holder=holder):
                if spec.get("emit_manifest", True):
                    build_manifest(out_dir, comm=LocalCommunicator(),
                                   log=log)
                if not leases.verify(lease):
                    obs.inc("lease_fence_rejects_total")
                    log("finalize: lease stolen mid-manifest; yielding to "
                        "the new finalizer")
                    time.sleep(poll)
                    continue
                if spec["global_shuffle"]:
                    shutil.rmtree(os.path.join(out_dir, _runner._SPOOL_DIR),
                                  ignore_errors=True)
                # Retire the ledger ATOMICALLY (rename, then delete the
                # renamed dir): hosts still scanning must see either the
                # complete record set or no ledger dir at all — a
                # half-deleted ledger reads as "unit not done" and would
                # trigger a catastrophic reclaim of finished outputs.
                # Stale retired dirs (a finalizer that died between ITS
                # rename and rmtree) are swept FIRST: renaming onto an
                # existing non-empty dir would fail ENOTEMPTY, and a
                # same-holder resume must not mistake that for "already
                # retired" and leave _done/ behind forever.
                import glob
                ledger = os.path.join(out_dir, _runner._LEDGER_DIR)
                for stale in sorted(glob.glob(ledger + ".retired.*")):
                    shutil.rmtree(stale, ignore_errors=True)
                retired = "{}.retired.{}".format(ledger, holder)
                try:
                    os.replace(ledger, retired)  # lddl: disable=atomic-publish
                except FileNotFoundError:
                    retired = None  # already retired by someone else
                if retired is not None:
                    shutil.rmtree(retired, ignore_errors=True)
                _runner._sweep_tmp_debris(out_dir)
                shutil.rmtree(root, ignore_errors=True)
                return True
        finally:
            keeper.remove(lease)


def run_elastic_pipeline(spec, process_bucket, log, *, holder_id, lease_ttl,
                         workers, progress_interval, t0, poll_s=None):
    """The elastic replacement for the static scatter/gather schedule.
    Called from ``runner._run_pipeline_body`` after the dirty-dir guard
    and fingerprint manifest check; every participating host runs this
    with identical arguments (modulo ``holder_id``)."""
    out_dir = spec["out_dir"]
    holder = (leases.sanitize_holder(holder_id) if holder_id
              else leases.default_holder())
    ttl = float(lease_ttl)
    if ttl <= 0:
        raise ValueError("lease_ttl must be > 0, got {}".format(lease_ttl))
    poll = poll_s if poll_s is not None else max(0.05, min(ttl / 4.0, 2.0))
    keeper = leases.LeaseKeeper(ttl)
    # Fleet spools (when armed) should carry the LEASE holder name, so
    # the status report's "host h0 stalled" and the lease events' "stolen
    # from h0" name the same thing; the env pin makes pool workers
    # publish into the same spool.
    obs.fleet.adopt_holder(holder, ttl=ttl)
    log("elastic preprocess: holder={} ttl={}s".format(holder, ttl))
    totals = {"completed": 0, "stolen": 0, "fence_rejects": 0}

    def add_stats(stats):
        for k in totals:
            totals[k] += stats[k]

    try:
        if spec["global_shuffle"]:
            adaptive = bool(spec.get("adaptive_scatter"))
            nblocks = len(_runner.plan_blocks(
                _runner.discover_source_files(spec["corpus_paths"]),
                spec["num_blocks"]))
            scatter_walls = {}

            def scatter_loop(unit_list):
                factory = _pool_factory_for(process_bucket, spec, workers,
                                            len(unit_list))
                return claim_loop(
                    spec, "elastic scatter", _SCATTER_PREFIX, unit_list,
                    holder=holder, ttl=ttl, keeper=keeper,
                    is_done=lambda u: _read_scatter_record(out_dir, u),
                    ledger_name=lambda u: "scatter-{}.json".format(u),
                    sweep=lambda u: _sweep_scatter(spec, u),
                    task=(_pool_scatter_slice if factory else
                          (lambda u, e, h, d=0.0: _scatter_slice(
                              spec, u, e, h, deadline=d))),
                    publish=lambda u, res, lease: _publish_scatter_record(
                        out_dir, u, lease,
                        wall=scatter_walls.get(u) if adaptive else None),
                    unit_walls=scatter_walls,
                    executor_factory=factory,
                    max_inflight=max(1, workers),
                    log=log, progress_interval=progress_interval,
                    poll_s=poll_s)

            # The accept set: exactly the winning attempt's spool files
            # per slice, read back STABLY after every slice is journaled
            # — identical on every host regardless of who ran what. A
            # "retry" (a record withdrawn by a fenced loser who then
            # died) re-enters the claim loop, which skips done units and
            # redoes only the un-journaled one.
            while True:
                if adaptive:
                    # Probes first (fixed identity), then the lease-guarded
                    # plan sizes the remaining blocks; the main loop's pool
                    # factory is built AFTER the plan lands in spec, so a
                    # spawn pool's spec snapshot carries it.
                    probes = _probe_layout(nblocks)
                    with obs.span("preprocess.scatter", elastic=True,
                                  holder=holder, adaptive=True):
                        add_stats(scatter_loop([k for k, _, _ in probes]))
                        # The plan record carries epoch/holder ON PURPOSE
                        # (fencing audit trail, like every _done record);
                        # it is a journaled-once shared fact, not shard
                        # content — byte identity is pinned by tests.
                        plan = _ensure_plan(spec, probes, nblocks, holder,  # lddl: disable=lease-isolation,wall-clock-flow
                                            ttl, keeper, poll, log)
                        if plan is None:
                            status, recs = "finalized", None
                            break
                        spec["scatter_plan"] = {"main": plan["main"]}
                        add_stats(scatter_loop(
                            list(range(len(plan["main"])))))
                    scatter_units = ([k for k, _, _ in probes]
                                     + list(range(len(plan["main"]))))
                else:
                    scatter_units = list(range(spec["scatter_units"]))
                    with obs.span("preprocess.scatter", elastic=True,
                                  holder=holder):
                        add_stats(scatter_loop(scatter_units))
                status, recs = _stable_scatter_records(
                    out_dir, scatter_units, leases.lease_root(out_dir),
                    ttl, poll)
                if status != "retry":
                    break
                log("elastic scatter: a completion record was withdrawn "
                    "with no live holder; re-entering the claim loop")
            if status == "ok":
                spec["spool_accept"] = sorted(
                    spool_name(u, recs[u]["epoch"], recs[u]["holder"])
                    for u in scatter_units)
            else:
                log("elastic: run already finalized by another host during "
                    "this host's scatter phase")
            gather_units = list(range(spec["ngroups"]))
            gather_prefix, gather_phase = _GROUP_PREFIX, "elastic gather"
            gather_task_pool, gather_sweep = _pool_gather_group, _sweep_gather

            def serial_gather(u, e, h, d=0.0):
                return _runner._run_group(
                    spec, process_bucket, u,
                    fence=_fence_for(out_dir, _GROUP_PREFIX, u, e, h,
                                     deadline=d))
        else:
            gather_units = list(range(spec["nbuckets"]))
            gather_prefix, gather_phase = _BLOCK_PREFIX, "elastic process"
            gather_task_pool, gather_sweep = _pool_block_bucket, _sweep_block

            def serial_gather(u, e, h, d=0.0):
                return _runner._run_block_bucket(
                    spec, process_bucket, u,
                    fence=_fence_for(out_dir, _BLOCK_PREFIX, u, e, h,
                                     deadline=d))

        # Overlapped gather: consume each unit's census record the moment
        # it is observed (journaled by us, or discovered on disk from
        # another host) instead of re-reading every record at a barrier
        # after the loop. Record content is plan-deterministic, so the
        # in-flight copy is what a barrier read would return; byte
        # identity is untouched. Disabled (empty hook) under
        # LDDL_TPU_COORD_LEGACY=1.
        census, consumed_at = {}, {}

        def on_gather_record(u, rec):
            consumed_at[u] = time.monotonic()
            if isinstance(rec, dict):
                census.update(rec)

        legacy = leases.legacy_coordination()
        factory = _pool_factory_for(process_bucket, spec, workers,
                                    len(gather_units))
        with obs.span("preprocess.gather", elastic=True, holder=holder):
            add_stats(claim_loop(
                spec, gather_phase, gather_prefix, gather_units,
                holder=holder, ttl=ttl, keeper=keeper,
                is_done=lambda u: _runner._ledger_read(out_dir, u),
                ledger_name=lambda u: "group-{}.json".format(u),
                on_record=None if legacy else on_gather_record,
                sweep=lambda u: gather_sweep(spec, u),
                task=gather_task_pool if factory else serial_gather,
                publish=lambda u, res, lease: _publish_gather_record(
                    out_dir, u, res, lease),
                executor_factory=factory, max_inflight=max(1, workers),
                log=log, progress_interval=progress_interval,
                poll_s=poll_s))

        # Merge the global census BEFORE finalize can delete the ledger;
        # only units the overlapped consume missed are read here. The
        # saved wall = how long each consumed record would have sat
        # waiting for this barrier.
        barrier_t = time.monotonic()
        # Gather census records are pure instance counts; the lease taint
        # the flow engine sees rides claim_loop's shared record plumbing
        # (scatter records DO carry epoch/holder), never gather content.
        written = _merge_census(out_dir, gather_units, census=census,  # lddl: disable=lease-isolation,wall-clock-flow
                                consumed=set(consumed_at))
        if consumed_at:
            obs.inc("gather_overlap_seconds_total",
                    sum(barrier_t - t for t in consumed_at.values()))
        log("elastic summary: holder={} units={} steals={} "
            "fence_rejects={}".format(holder, totals["completed"],
                                      totals["stolen"],
                                      totals["fence_rejects"]))
        # spec carries the adopted plan's block ranges (journaled-once
        # shared fact); the manifest lists shards whose bytes are
        # partition-independent — identity pinned across fixed/adaptive.
        _finalize(spec, holder, ttl, keeper, log, poll)  # lddl: disable=lease-isolation,wall-clock-flow
    finally:
        keeper.stop()

    elapsed = time.time() - t0  # lddl: disable=wall-clock (log-only rates)
    if obs.enabled():
        obs.set_gauge("preprocess_samples_per_second",
                      sum(written.values()) / max(elapsed, 1e-9))
        docs = obs.registry().counter("preprocess_docs_total").total()
        if docs:
            obs.set_gauge("preprocess_docs_per_second",
                          docs / max(elapsed, 1e-9))
    log("preprocess done in {:.1f}s, {} shards, {} samples (elastic, "
        "global census)".format(elapsed, len(written),
                                sum(written.values())))
    return written
