"""Generate unicode_tables.h for the native engine.

The C++ engine must match two Python-side semantics exactly:

1. the rule-based sentence splitter (lddl_tpu.preprocess.sentences), which
   uses Python ``re`` \\s, ``str.strip()`` whitespace, and ``str.isalpha``;
2. the HF BertNormalizer pipeline (clean_text -> chinese-char spacing ->
   NFD accent strip -> lowercase) used by BertTokenizerFast, whose helper
   predicates (is_whitespace / is_control / is_punctuation) follow the
   definitions in tokenizers' Rust BertNormalizer / BertPreTokenizer.

Rather than hand-porting Unicode behavior, we *evaluate the Python
semantics per BMP codepoint* here and bake the answers into lookup tables,
so the C++ side is table-driven and exact on the BMP. Codepoints above the
BMP use sparse binary-searched tables (flag runs + non-identity folds)
generated the same way, so astral Cf/Cc removal, astral punctuation
isolation, and cased astral scripts (e.g. Deseret) also match
BertTokenizerFast exactly.
"""

import re
import sys
import unicodedata

F_RE_SPACE = 1    # Python re \s (str patterns)
F_STR_SPACE = 2   # str.isspace() — what str.strip() removes
F_HF_WS = 4       # HF fast is_whitespace: \t\n\r or Unicode White_Space
F_HF_CTRL = 8     # HF is_control: category C*, except \t\n\r
F_HF_PUNCT = 16   # HF is_punctuation: ASCII punct ranges or category P*
F_CJK = 32        # HF chinese-char ranges (BMP part)
F_ALPHA = 64      # str.isalpha()
F_LOWER = 128     # str.islower() (single char)
F_RE_DIGIT = 256  # Python re \d (str patterns) == category Nd
F_UPPER = 512     # str.isupper() (single char)
F_RE_WORD = 1024  # Python re \w (str patterns)
# CPython str.lower()'s ONLY context-sensitive case is Final_Sigma
# (U+03A3 -> ς when preceded by a cased char, skipping case-ignorables,
# and not followed by one). The two predicates are probed from CPython
# itself rather than hand-ported property tables.
F_PY_CASED = 2048
F_PY_CASE_IGNORABLE = 4096

_RE_SPACE = re.compile(r"\s")
_RE_DIGIT = re.compile(r"\d")
_RE_WORD = re.compile(r"\w")

# HF is_chinese_char ranges (BMP + astral extension blocks).
_CJK = ((0x4E00, 0x9FFF), (0x3400, 0x4DBF), (0xF900, 0xFAFF),
        (0x20000, 0x2A6DF), (0x2A700, 0x2B73F), (0x2B740, 0x2B81F),
        (0x2B820, 0x2CEAF), (0x2F800, 0x2FA1F))

# Unicode White_Space property (what Rust's char::is_whitespace — used by
# the HF fast BertNormalizer — matches). NOTE: several of these are also
# category C (U+0085, U+000B, U+000C); the normalizer checks is_control
# FIRST, so those are removed, not spaced — the C++ engine mirrors that
# order. Empirically verified against BertTokenizerFast.
_WHITE_SPACE = frozenset(
    [0x09, 0x0A, 0x0B, 0x0C, 0x0D, 0x20, 0x85, 0xA0, 0x1680]
    + list(range(0x2000, 0x200B)) + [0x2028, 0x2029, 0x202F, 0x205F, 0x3000])


def _flags(cp):
    c = chr(cp)
    f = 0
    if _RE_SPACE.match(c):
        f |= F_RE_SPACE
    if c.isspace():
        f |= F_STR_SPACE
    cat = unicodedata.category(c)
    if cp in _WHITE_SPACE:
        f |= F_HF_WS
    # The HF fast normalizer (Rust) removes Cc/Cf/Co/Cs but KEEPS Cn
    # (unassigned codepoints survive and join words — empirically probed:
    # U+0378/U+FDD0/U+3FFFD stay, U+E000/U+100001/U+00AD are removed).
    if c not in "\t\n\r" and cat in ("Cc", "Cf", "Co", "Cs"):
        f |= F_HF_CTRL
    if (33 <= cp <= 47 or 58 <= cp <= 64 or 91 <= cp <= 96
            or 123 <= cp <= 126 or cat.startswith("P")):
        f |= F_HF_PUNCT
    if any(lo <= cp <= hi for lo, hi in _CJK):
        f |= F_CJK
    if c.isalpha():
        f |= F_ALPHA
    if c.islower():
        f |= F_LOWER
    if _RE_DIGIT.match(c):
        f |= F_RE_DIGIT
    if c.isupper():
        f |= F_UPPER
    if _RE_WORD.match(c):
        f |= F_RE_WORD
    # Probes against CPython's own Final_Sigma scan: cp is "cased" iff a
    # sigma directly after it takes the final form; "case-ignorable" iff
    # it is transparent to that backward scan (and not itself cased).
    if (c + "Σ").lower().endswith("ς"):
        f |= F_PY_CASED
    elif ("A" + c + "Σ").lower().endswith("ς"):
        f |= F_PY_CASE_IGNORABLE
    return f


def _py_lower(cp):
    """Python str.lower() per codepoint (full case mapping; may expand,
    e.g. U+0130 -> 2 codepoints). The learned splitter's punkt types are
    built with str.lower, so the C++ port needs the exact mapping."""
    return [ord(c) for c in chr(cp).lower()]


def _fold_lower_strip(cp):
    """NFD -> drop Mn -> lowercase, per codepoint (the BertNormalizer
    composition for do_lower_case=True). Returns output codepoints."""
    s = unicodedata.normalize("NFD", chr(cp))
    s = "".join(ch for ch in s if unicodedata.category(ch) != "Mn")
    s = s.lower()
    return [ord(ch) for ch in s]


_HF_BITS = F_HF_WS | F_HF_CTRL | F_HF_PUNCT | F_CJK


def calibration_tag():
    """Identifies the environment the HF-side tables were calibrated
    against. Stamped into the generated header; build.py regenerates when
    it no longer matches (e.g. tokenizers installed/upgraded after a
    fallback build), so cached tables cannot silently lose parity."""
    # unicodedata always contributes (splitter bits F_RE_SPACE/F_STR_SPACE/
    # F_ALPHA are baked from it), so a Python/Unicode upgrade regenerates
    # even when the tokenizers version is unchanged.
    tag = "unicodedata=" + unicodedata.unidata_version
    try:
        import tokenizers
        return "tokenizers=" + tokenizers.__version__ + ";" + tag
    except Exception:
        return tag

# Codepoints never probed: surrogates (not valid scalars) and the probe
# guard digits (digits are flag-free identity in every Unicode version).
def _probe_skip(cp):
    return 0xD800 <= cp <= 0xDFFF or 0x30 <= cp <= 0x39


def _probe_rust_tables():
    """Calibrate the HF-side behaviors (clean_text removal, whitespace,
    CJK spacing, punctuation, fold output) against the INSTALLED Rust
    ``tokenizers`` pipeline, per codepoint. Python's unicodedata and the
    Rust crates can disagree by several Unicode versions (e.g. U+10EFD is
    Mn in Unicode 15 but unknown to older Rust tables; Cn codepoints are
    kept while Co are removed) — parity is defined against
    BertTokenizerFast, so the installed Rust behavior wins. Returns
    (flags: {cp: hf_bits}, folds: {cp: [out_cps]}) or None when the
    ``tokenizers`` package is unavailable (unicodedata approximation is
    used instead)."""
    try:
        from tokenizers.normalizers import BertNormalizer
        from tokenizers.pre_tokenizers import BertPreTokenizer
    except Exception:
        return None
    norm_plain = BertNormalizer(clean_text=True, handle_chinese_chars=True,
                                strip_accents=False, lowercase=False)
    norm_lower = BertNormalizer(clean_text=True, handle_chinese_chars=True,
                                strip_accents=True, lowercase=True)
    pre = BertPreTokenizer()
    cps = [cp for cp in range(0x110000) if not _probe_skip(cp)]

    def norm_probe(norm):
        # "5<cp>7" groups: '5'/'7' survive every normalizer unchanged and
        # no codepoint normalizes to a digit, so the segment between the
        # guards is exactly cp's normalized expansion.
        out = {}
        chunk_size = 4096
        for i in range(0, len(cps), chunk_size):
            chunk = cps[i:i + chunk_size]
            t = norm.normalize_str(
                "".join("5" + chr(cp) + "7" for cp in chunk))
            pos = 0
            for cp in chunk:
                assert t[pos] == "5", hex(cp)
                nxt = t.index("7", pos + 1)
                out[cp] = t[pos + 1:nxt]
                pos = nxt + 1
            assert pos == len(t)
        return out

    plain = norm_probe(norm_plain)
    lower = norm_probe(norm_lower)

    # Punctuation probe: BertPreTokenizer isolates punct codepoints.
    # "5<cp>7." groups; '.' always splits, so each group parses to
    # ["5<cp>7"] (not punct), ["5", <cp>, "7"] (punct), or ["5", "7"]
    # (whitespace).
    punct = {}
    chunk_size = 4096
    for i in range(0, len(cps), chunk_size):
        chunk = cps[i:i + chunk_size]
        toks = [t for t, _ in pre.pre_tokenize_str(
            "".join("5" + chr(cp) + "7." for cp in chunk))]
        j = 0
        for cp in chunk:
            c = chr(cp)
            if toks[j] == "5" + c + "7":
                punct[cp] = False
                j += 1
            elif toks[j] == "5" and toks[j + 1] == c and toks[j + 2] == "7":
                punct[cp] = True
                j += 3
            elif toks[j] == "5" and toks[j + 1] == "7":
                punct[cp] = False  # whitespace-split, not punctuation
                j += 2
            else:
                raise AssertionError("unparseable punct probe at "
                                     + hex(cp))
            assert toks[j] == ".", hex(cp)
            j += 1
        assert j == len(toks)

    flags = {}
    folds = {}
    for cp in cps:
        nl = plain[cp]
        f = 0
        if nl == "":
            f |= F_HF_CTRL
        elif nl == " ":
            f |= F_HF_WS
        elif len(nl) >= 3 and nl[0] == " " and nl[-1] == " ":
            assert nl == " " + chr(cp) + " ", hex(cp)
            f |= F_CJK
        if punct[cp]:
            f |= F_HF_PUNCT
        flags[cp] = f
        if not (f & (F_HF_CTRL | F_HF_WS)):
            lo = lower[cp]
            if f & F_CJK:
                assert lo[0] == " " and lo[-1] == " ", hex(cp)
                lo = lo[1:-1]
            fold = [ord(ch) for ch in lo]
            if fold != [cp]:
                assert len(fold) <= 3, hex(cp)
                folds[cp] = fold
    return flags, folds


def _make_flags_fn():
    """flags(cp): splitter bits (F_RE_SPACE/F_STR_SPACE/F_ALPHA) always
    follow Python semantics (they mirror the Python splitter); HF bits
    come from the Rust probe when available."""
    probed = _probe_rust_tables()
    if probed is None:
        sys.stderr.write("gen_tables: tokenizers unavailable — using "
                         "unicodedata approximation for HF semantics\n")
        return _flags, _fold_lower_strip
    pflags, pfolds = probed

    def flags(cp):
        f = _flags(cp)
        if cp in pflags:
            f = (f & ~_HF_BITS) | pflags[cp]
        return f

    def fold(cp):
        return pfolds.get(cp, [cp])

    return flags, fold


def _astral_tables(flags_fn, fold_fn):
    """Sparse tables for cp >= 0x10000: contiguous same-flag runs (binary
    search by start) and non-identity fold entries (binary search by cp)."""
    run_starts, run_flags = [], []
    prev = None
    for cp in range(0x10000, 0x110000):
        f = flags_fn(cp)
        if f != prev:
            run_starts.append(cp)
            run_flags.append(f)
            prev = f
    folds = []
    for cp in range(0x10000, 0x110000):
        out = fold_fn(cp)
        if out != [cp]:
            assert len(out) <= 3
            padded = out + [0] * (3 - len(out))
            folds.append((cp, len(out), padded[0], padded[1], padded[2]))
    return run_starts, run_flags, folds


def _astral_fold_entries(fold_fn):
    """Non-identity astral fold entries only (no flag-run recompute)."""
    folds = []
    for cp in range(0x10000, 0x110000):
        out = fold_fn(cp)
        if out != [cp]:
            assert len(out) <= 3
            padded = out + [0] * (3 - len(out))
            folds.append((cp, len(out), padded[0], padded[1], padded[2]))
    return folds


def generate(out_path):
    flags_fn, fold_fn = _make_flags_fn()
    flags = [flags_fn(cp) for cp in range(0x10000)]
    astral_starts, astral_flags, astral_folds = _astral_tables(flags_fn,
                                                               fold_fn)
    astral_lowers = _astral_fold_entries(_py_lower)

    # str.lower() table (BMP): only non-identity entries materialized.
    lower_idx = [0xFFFF] * 0x10000
    lower_entries = []
    for cp in range(0x10000):
        if 0xD800 <= cp <= 0xDFFF:
            continue
        out = _py_lower(cp)
        if out == [cp]:
            continue
        assert len(out) <= 3
        if len(lower_entries) >= 0xFFFF:
            raise RuntimeError("lower entry overflow")
        lower_idx[cp] = len(lower_entries)
        padded = out + [0] * (3 - len(out))
        lower_entries.append((len(out), padded[0], padded[1], padded[2]))

    # Fold table: only non-identity entries are materialized.
    fold_idx = [0xFFFF] * 0x10000
    entries = []  # list of (n, out0, out1, out2)
    for cp in range(0x10000):
        if 0xD800 <= cp <= 0xDFFF:  # surrogates: not valid scalar values
            continue
        out = fold_fn(cp)
        if out == [cp]:
            continue
        if len(out) > 3:  # no BMP codepoint folds to >3 under this pipeline
            out = out[:3]
        if len(entries) >= 0xFFFF:
            raise RuntimeError("fold entry overflow")
        fold_idx[cp] = len(entries)
        padded = out + [0] * (3 - len(out))
        entries.append((len(out), padded[0], padded[1], padded[2]))

    def dump(name, ctype, values, per_line=16):
        lines = ["static const {} {}[{}] = {{".format(ctype, name, len(values))]
        for i in range(0, len(values), per_line):
            lines.append("  " + ",".join(str(v) for v in values[i:i + per_line])
                         + ",")
        lines.append("};")
        return "\n".join(lines)

    parts = [
        "// Auto-generated by gen_tables.py — do not edit.",
        "// calibration: " + calibration_tag(),
        "#pragma once",
        "#include <cstdint>",
        "#define F_RE_SPACE {}".format(F_RE_SPACE),
        "#define F_STR_SPACE {}".format(F_STR_SPACE),
        "#define F_HF_WS {}".format(F_HF_WS),
        "#define F_HF_CTRL {}".format(F_HF_CTRL),
        "#define F_HF_PUNCT {}".format(F_HF_PUNCT),
        "#define F_CJK {}".format(F_CJK),
        "#define F_ALPHA {}".format(F_ALPHA),
        "#define F_LOWER {}".format(F_LOWER),
        "#define F_RE_DIGIT {}".format(F_RE_DIGIT),
        "#define F_UPPER {}".format(F_UPPER),
        "#define F_RE_WORD {}".format(F_RE_WORD),
        "#define F_PY_CASED {}".format(F_PY_CASED),
        "#define F_PY_CASE_IGNORABLE {}".format(F_PY_CASE_IGNORABLE),
        dump("UFLAGS", "uint16_t", flags),
        dump("LOWER_IDX", "uint16_t", lower_idx),
        dump("LOWER_N", "uint8_t", [e[0] for e in lower_entries]),
        dump("LOWER_OUT", "uint32_t",
             [v for e in lower_entries for v in (e[1], e[2], e[3])]),
        dump("ALOWER_CP", "uint32_t", [e[0] for e in astral_lowers]),
        dump("ALOWER_N", "uint8_t", [e[1] for e in astral_lowers]),
        dump("ALOWER_OUT", "uint32_t",
             [v for e in astral_lowers for v in (e[2], e[3], e[4])]),
        dump("FOLD_IDX", "uint16_t", fold_idx),
        dump("FOLD_N", "uint8_t", [e[0] for e in entries]),
        dump("FOLD_OUT", "uint32_t",
             [v for e in entries for v in (e[1], e[2], e[3])]),
        dump("AFLAG_START", "uint32_t", astral_starts),
        dump("AFLAG_VALUE", "uint16_t", astral_flags),
        dump("AFOLD_CP", "uint32_t", [e[0] for e in astral_folds]),
        dump("AFOLD_N", "uint8_t", [e[1] for e in astral_folds]),
        dump("AFOLD_OUT", "uint32_t",
             [v for e in astral_folds for v in (e[2], e[3], e[4])]),
    ]
    with open(out_path, "w") as f:
        f.write("\n".join(parts) + "\n")
    return out_path


if __name__ == "__main__":
    generate(sys.argv[1] if len(sys.argv) > 1 else "unicode_tables.h")
