"""ctypes binding for the native preprocessing engine.

Public surface:
    available() -> bool
    NativeTokenizer(id_to_token, unk_id, do_lower_case)
        .tokenize_docs(texts) -> (ids, sent_lens, doc_sent_counts) np arrays
    split_docs(texts) -> list[list[str]]   (sentence split only; BART path)

The engine replaces the reference's per-partition sentence-split + HF
tokenize hot loop (lddl/dask/bert/pretrain.py:77-97) with one native pass;
semantics parity with the Python/HF path is enforced by tests/test_native.py.
"""

import ctypes
import os
import threading

import numpy as np

_lock = threading.Lock()
_lib = None
_lib_tried = False


class _TokResult(ctypes.Structure):
    _fields_ = [
        ("ids", ctypes.POINTER(ctypes.c_int32)),
        ("n_ids", ctypes.c_int64),
        ("sent_lens", ctypes.POINTER(ctypes.c_int32)),
        ("n_sents", ctypes.c_int64),
        ("doc_sent_counts", ctypes.POINTER(ctypes.c_int32)),
        ("n_docs", ctypes.c_int64),
    ]


class _PairResult(ctypes.Structure):
    _fields_ = [
        ("seq_ids", ctypes.POINTER(ctypes.c_int32)),
        ("n_seq_ids", ctypes.c_int64),
        ("seq_lens", ctypes.POINTER(ctypes.c_int32)),
        ("a_lens", ctypes.POINTER(ctypes.c_int32)),
        ("is_random_next", ctypes.POINTER(ctypes.c_uint8)),
        ("n_instances", ctypes.c_int64),
    ]


class _SplitResult(ctypes.Structure):
    _fields_ = [
        ("starts", ctypes.POINTER(ctypes.c_int64)),
        ("ends", ctypes.POINTER(ctypes.c_int64)),
        ("n_sents", ctypes.c_int64),
        ("doc_sent_counts", ctypes.POINTER(ctypes.c_int32)),
        ("n_docs", ctypes.c_int64),
    ]


def _load():
    global _lib, _lib_tried
    with _lock:
        if _lib_tried:
            return _lib
        _lib_tried = True
        if os.environ.get("LDDL_TPU_DISABLE_NATIVE"):
            return None
        from .build import ensure_built
        path = ensure_built()
        if path is None:
            return None
        try:
            lib = ctypes.CDLL(path)
        except OSError:
            return None
        # Version-gate BEFORE binding symbols: a cached .so from an older
        # ABI must degrade to "unavailable", not raise AttributeError.
        try:
            if lib.lddl_native_abi_version() != 5:
                return None
        except AttributeError:
            return None
        lib.lddl_tok_create.restype = ctypes.c_void_p
        lib.lddl_tok_create.argtypes = [ctypes.c_char_p, ctypes.c_int64,
                                        ctypes.c_int32, ctypes.c_int]
        lib.lddl_tok_free.argtypes = [ctypes.c_void_p]
        lib.lddl_tok_set_memo_cap.argtypes = [ctypes.c_void_p,
                                              ctypes.c_int64]
        lib.lddl_tok_set_splitter.restype = None
        lib.lddl_tok_set_splitter.argtypes = [ctypes.c_void_p,
                                              ctypes.c_char_p,
                                              ctypes.c_int64]
        lib.lddl_split_docs2.restype = ctypes.POINTER(_SplitResult)
        lib.lddl_split_docs2.argtypes = [
            ctypes.c_char_p, ctypes.POINTER(ctypes.c_int64), ctypes.c_int64,
            ctypes.c_char_p, ctypes.c_int64]
        lib.lddl_join_tokens.restype = None
        lib.lddl_join_tokens.argtypes = [
            ctypes.POINTER(ctypes.c_int32), ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int64), ctypes.c_int64,
            ctypes.c_char_p, ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int64), ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_int32)]
        lib.lddl_tok_docs.restype = ctypes.POINTER(_TokResult)
        lib.lddl_tok_docs.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_int64), ctypes.c_int64]
        lib.lddl_tok_result_free.argtypes = [ctypes.POINTER(_TokResult)]
        lib.lddl_split_docs.restype = ctypes.POINTER(_SplitResult)
        lib.lddl_split_docs.argtypes = [
            ctypes.c_char_p, ctypes.POINTER(ctypes.c_int64), ctypes.c_int64]
        lib.lddl_split_result_free.argtypes = [ctypes.POINTER(_SplitResult)]
        lib.lddl_bert_pairs.restype = ctypes.POINTER(_PairResult)
        lib.lddl_bert_pairs.argtypes = [
            ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int32),
            ctypes.c_int64, ctypes.POINTER(ctypes.c_int32), ctypes.c_int64,
            ctypes.c_int32, ctypes.c_double, ctypes.c_int32,
            ctypes.c_uint64, ctypes.c_uint64, ctypes.c_int32, ctypes.c_int32]
        lib.lddl_pairs_free.argtypes = [ctypes.POINTER(_PairResult)]
        _lib = lib
        return _lib


def available():
    return _load() is not None


def join_tokens(flat_ids, row_lens, blob, tok_starts, tok_lens,
                total_bytes):
    """Space-join token ids into one contiguous UTF-8 buffer + int32 value
    offsets (the Arrow StringArray layout) with the C memcpy kernel.
    Returns (data uint8[total_bytes], offsets int32[n_rows+1]) or None
    when the native library is unavailable."""
    lib = _load()
    if lib is None:
        return None
    flat_ids = np.ascontiguousarray(flat_ids, dtype=np.int32)
    row_lens = np.ascontiguousarray(row_lens, dtype=np.int64)
    tok_starts = np.ascontiguousarray(tok_starts, dtype=np.int64)
    tok_lens = np.ascontiguousarray(tok_lens, dtype=np.int64)
    out = np.empty(int(total_bytes), dtype=np.uint8)
    offsets = np.empty(len(row_lens) + 1, dtype=np.int32)
    p_i32 = ctypes.POINTER(ctypes.c_int32)
    p_i64 = ctypes.POINTER(ctypes.c_int64)
    lib.lddl_join_tokens(
        flat_ids.ctypes.data_as(p_i32), len(flat_ids),
        row_lens.ctypes.data_as(p_i64), len(row_lens),
        blob,
        tok_starts.ctypes.data_as(p_i64),
        tok_lens.ctypes.data_as(p_i64),
        out.ctypes.data_as(ctypes.c_char_p),
        offsets.ctypes.data_as(p_i32))
    return out, offsets


def _pack_docs(texts):
    """Concatenate texts into one UTF-8 buffer + int64 offsets array.
    Accepts bytes (the preprocess pipeline's zero-decode path — the C++
    engine is the first and only UTF-8 decoder) or str."""
    encoded = [t if isinstance(t, bytes) else t.encode("utf-8")
               for t in texts]
    offsets = np.zeros(len(encoded) + 1, dtype=np.int64)
    np.cumsum([len(e) for e in encoded], out=offsets[1:])
    return b"".join(encoded), offsets


class NativeTokenizer:
    """Native split+normalize+WordPiece over documents.

    One instance holds the vocab hash table and the word->ids memo cache;
    reuse it across buckets (the memo is what makes Zipf-distributed text
    fast). Not thread-safe; use one instance per worker process.
    """

    def __init__(self, id_to_token, unk_id, do_lower_case=True,
                 memo_cap=None, splitter_blob=None):
        lib = _load()
        if lib is None:
            raise RuntimeError("native engine unavailable")
        self._args = (list(id_to_token), int(unk_id), bool(do_lower_case),
                      memo_cap, splitter_blob)
        self._lib = lib
        buf = "\n".join(id_to_token).encode("utf-8")
        self._handle = lib.lddl_tok_create(buf, len(buf), int(unk_id),
                                           1 if do_lower_case else 0)
        if memo_cap is not None:
            lib.lddl_tok_set_memo_cap(self._handle, int(memo_cap))
        if splitter_blob:
            lib.lddl_tok_set_splitter(self._handle, splitter_blob,
                                      len(splitter_blob))

    def set_splitter(self, blob):
        """Attach (or clear, blob=None) corpus-learned punkt splitter
        params — the SplitterParams.serialize() blob (never empty: it
        carries a 'P1' header line, so clear-vs-params is unambiguous).
        tokenize_docs then splits with the learned decision procedure."""
        self._lib.lddl_tok_set_splitter(self._handle, blob or b"",
                                        len(blob or b""))
        self._args = self._args[:4] + (blob,)

    def __reduce__(self):
        # ctypes handles cannot cross pickle boundaries; rebuild from the
        # constructor args in the receiving process (fresh memo cache).
        return (NativeTokenizer, self._args)

    def __del__(self):
        if getattr(self, "_handle", None):
            self._lib.lddl_tok_free(self._handle)
            self._handle = None

    def tokenize_docs(self, texts):
        """-> (ids int32[], sent_lens int32[], doc_sent_counts int32[]).

        Sentences are concatenated in document order; empty sentences are
        dropped; doc_sent_counts[d] = number of non-empty sentences of
        document d.
        """
        if not texts:
            z = np.zeros(0, dtype=np.int32)
            return z, z.copy(), z.copy()
        buf, offsets = _pack_docs(texts)
        res = self._lib.lddl_tok_docs(
            self._handle, buf,
            offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            len(texts))
        try:
            r = res.contents
            ids = np.ctypeslib.as_array(r.ids, shape=(r.n_ids,)).copy()
            sent_lens = np.ctypeslib.as_array(
                r.sent_lens, shape=(r.n_sents,)).copy()
            doc_counts = np.ctypeslib.as_array(
                r.doc_sent_counts, shape=(r.n_docs,)).copy()
        finally:
            self._lib.lddl_tok_result_free(res)
        return ids, sent_lens, doc_counts


def bert_pairs(ids, sent_lens, doc_sent_counts, max_seq_length,
               short_seq_prob, duplicate_factor, seed, bucket, cls_id,
               sep_id):
    """NSP pair creation over a tokenized bucket (lddl_tok_docs output),
    replaying the frozen CounterRNG streams of the Python engine
    (preprocess.bert.pairs_from_documents). Returns flat instance arrays
    (seq_ids, seq_lens, a_lens, is_random_next)."""
    lib = _load()
    if lib is None:
        raise RuntimeError("native engine unavailable")
    ids = np.ascontiguousarray(ids, dtype=np.int32)
    sent_lens = np.ascontiguousarray(sent_lens, dtype=np.int32)
    doc_sent_counts = np.ascontiguousarray(doc_sent_counts, dtype=np.int32)
    p_i32 = ctypes.POINTER(ctypes.c_int32)
    res = lib.lddl_bert_pairs(
        ids.ctypes.data_as(p_i32), sent_lens.ctypes.data_as(p_i32),
        len(sent_lens), doc_sent_counts.ctypes.data_as(p_i32),
        len(doc_sent_counts), int(max_seq_length), float(short_seq_prob),
        int(duplicate_factor), int(seed) & (2**64 - 1),
        int(bucket) & (2**64 - 1), int(cls_id), int(sep_id))
    try:
        r = res.contents
        n = r.n_instances
        if n == 0:
            z32 = np.zeros(0, dtype=np.int32)
            return (z32, z32.copy(), z32.copy(), np.zeros(0, dtype=bool))
        seq_ids = np.ctypeslib.as_array(r.seq_ids, shape=(r.n_seq_ids,)).copy()
        seq_lens_o = np.ctypeslib.as_array(r.seq_lens, shape=(n,)).copy()
        a_lens = np.ctypeslib.as_array(r.a_lens, shape=(n,)).copy()
        rn = np.ctypeslib.as_array(r.is_random_next,
                                   shape=(n,)).astype(bool)
    finally:
        lib.lddl_pairs_free(res)
    return seq_ids, seq_lens_o, a_lens, rn


def split_docs(texts, splitter_blob=None):
    """Sentence-split documents natively -> list of sentence lists.

    Same boundaries as preprocess.sentences.split_sentences — or, with
    ``splitter_blob`` (SplitterParams.serialize()), as
    split_sentences_learned (enforced by tests); raises RuntimeError when
    the native engine is unavailable.
    """
    lib = _load()
    if lib is None:
        raise RuntimeError("native engine unavailable")
    if not texts:
        return []
    buf, offsets = _pack_docs(texts)
    res = lib.lddl_split_docs2(
        buf, offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        len(texts), splitter_blob, len(splitter_blob or b""))
    try:
        r = res.contents
        starts = np.ctypeslib.as_array(r.starts, shape=(r.n_sents,)).copy()
        ends = np.ctypeslib.as_array(r.ends, shape=(r.n_sents,)).copy()
        counts = np.ctypeslib.as_array(
            r.doc_sent_counts, shape=(r.n_docs,)).copy()
    finally:
        lib.lddl_split_result_free(res)
    out = []
    k = 0
    for d in range(len(texts)):
        sents = []
        for _ in range(int(counts[d])):
            sents.append(buf[starts[k]:ends[k]].decode("utf-8"))
            k += 1
        out.append(sents)
    return out
