"""ctypes binding for the native preprocessing engine.

Public surface:
    available() -> bool
    fused_enabled() -> bool     (the one-pass instances kernel; env-gated)
    NativeTokenizer(id_to_token, unk_id, do_lower_case)
        .tokenize_docs(texts) -> (ids, sent_lens, doc_sent_counts) np arrays
        .bert_instances(docs, ...) -> packed instance arrays in ONE pass
    mask_batch(key, ids, candidate, ...) -> numpy-Philox-replay masking
    split_docs(texts) -> list[list[str]]   (sentence split only; BART path)

The engine replaces the reference's per-partition sentence-split + HF
tokenize hot loop (lddl/dask/bert/pretrain.py:77-97) with one native pass;
semantics parity with the Python/HF path is enforced by tests/test_native.py
and tests/test_fused.py.

Zero-copy result contract: the kernels malloc exactly-sized output buffers
and transfer ownership — the binding wraps each buffer as a numpy array
whose finalizer (weakref.finalize -> lddl_buf_free) frees it when the last
view dies. No ``.copy()`` ever happens at the boundary.
"""

import ctypes
import os
import threading
import weakref

import numpy as np

_lock = threading.Lock()
_lib = None
_lib_tried = False


class _TokResult(ctypes.Structure):
    _fields_ = [
        ("ids", ctypes.POINTER(ctypes.c_int32)),
        ("n_ids", ctypes.c_int64),
        ("sent_lens", ctypes.POINTER(ctypes.c_int32)),
        ("n_sents", ctypes.c_int64),
        ("doc_sent_counts", ctypes.POINTER(ctypes.c_int32)),
        ("n_docs", ctypes.c_int64),
    ]


class _PairResult(ctypes.Structure):
    _fields_ = [
        ("seq_ids", ctypes.POINTER(ctypes.c_int32)),
        ("n_seq_ids", ctypes.c_int64),
        ("seq_lens", ctypes.POINTER(ctypes.c_int32)),
        ("a_lens", ctypes.POINTER(ctypes.c_int32)),
        ("is_random_next", ctypes.POINTER(ctypes.c_uint8)),
        ("n_instances", ctypes.c_int64),
    ]


class _SplitResult(ctypes.Structure):
    _fields_ = [
        ("starts", ctypes.POINTER(ctypes.c_int64)),
        ("ends", ctypes.POINTER(ctypes.c_int64)),
        ("n_sents", ctypes.c_int64),
        ("doc_sent_counts", ctypes.POINTER(ctypes.c_int32)),
        ("n_docs", ctypes.c_int64),
    ]


class _InstResult(ctypes.Structure):
    _fields_ = [
        ("seq_ids", ctypes.POINTER(ctypes.c_int32)),
        ("n_seq_ids", ctypes.c_int64),
        ("seq_lens", ctypes.POINTER(ctypes.c_int32)),
        ("a_lens", ctypes.POINTER(ctypes.c_int32)),
        ("is_random_next", ctypes.POINTER(ctypes.c_uint8)),
        ("n_instances", ctypes.c_int64),
        ("a_ids", ctypes.POINTER(ctypes.c_int32)),
        ("n_a_ids", ctypes.c_int64),
        ("b_ids", ctypes.POINTER(ctypes.c_int32)),
        ("n_b_ids", ctypes.c_int64),
    ]


class _MaskedInstResult(ctypes.Structure):
    _fields_ = [
        ("a_lens", ctypes.POINTER(ctypes.c_int32)),
        ("seq_lens", ctypes.POINTER(ctypes.c_int32)),
        ("is_random_next", ctypes.POINTER(ctypes.c_uint8)),
        ("n_instances", ctypes.c_int64),
        ("a_ids", ctypes.POINTER(ctypes.c_int32)),
        ("n_a_ids", ctypes.c_int64),
        ("b_ids", ctypes.POINTER(ctypes.c_int32)),
        ("n_b_ids", ctypes.c_int64),
        ("mlm_pos", ctypes.POINTER(ctypes.c_int32)),
        ("mlm_labels", ctypes.POINTER(ctypes.c_int32)),
        ("mlm_lens", ctypes.POINTER(ctypes.c_int32)),
        ("n_mlm", ctypes.c_int64),
    ]


def _load():
    global _lib, _lib_tried
    with _lock:
        if _lib_tried:
            return _lib
        _lib_tried = True
        if os.environ.get("LDDL_TPU_DISABLE_NATIVE"):
            return None
        from .build import ensure_built
        # ensure_built routes by LDDL_TPU_NATIVE_SANITIZE: a sanitized
        # build lives under its own mode-suffixed .so and never collides
        # with the normal cache.
        path = ensure_built()
        if path is None:
            return None
        try:
            lib = ctypes.CDLL(path)
        except OSError:
            # Includes the sanitized-build case where the sanitizer
            # runtime is not preloaded: dlopen'ing a TSan/ASan .so into
            # plain CPython requires LD_PRELOAD=libtsan.so/libasan.so
            # (benchmarks/sanitize_smoke.py sets this up). Degrading to
            # "unavailable" here is correct — the smoke separately
            # asserts availability so it can never pass vacuously.
            return None
        # Version-gate BEFORE binding symbols: a cached .so from an older
        # ABI must degrade to "unavailable", not raise AttributeError.
        try:
            if lib.lddl_native_abi_version() != 8:
                return None
        except AttributeError:
            return None
        lib.lddl_tok_create.restype = ctypes.c_void_p
        lib.lddl_tok_create.argtypes = [ctypes.c_char_p, ctypes.c_int64,
                                        ctypes.c_int32, ctypes.c_int]
        lib.lddl_tok_free.argtypes = [ctypes.c_void_p]
        lib.lddl_tok_set_memo_cap.argtypes = [ctypes.c_void_p,
                                              ctypes.c_int64]
        lib.lddl_tok_set_threads.restype = None
        lib.lddl_tok_set_threads.argtypes = [ctypes.c_void_p,
                                             ctypes.c_int32]
        lib.lddl_tok_get_threads.restype = ctypes.c_int32
        lib.lddl_tok_get_threads.argtypes = [ctypes.c_void_p]
        lib.lddl_tok_thread_busy_ns.restype = ctypes.c_int32
        lib.lddl_tok_thread_busy_ns.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_int64),
            ctypes.c_int32]
        lib.lddl_tok_set_splitter.restype = None
        lib.lddl_tok_set_splitter.argtypes = [ctypes.c_void_p,
                                              ctypes.c_char_p,
                                              ctypes.c_int64]
        lib.lddl_split_docs2.restype = ctypes.POINTER(_SplitResult)
        lib.lddl_split_docs2.argtypes = [
            ctypes.c_char_p, ctypes.POINTER(ctypes.c_int64), ctypes.c_int64,
            ctypes.c_char_p, ctypes.c_int64]
        lib.lddl_join_tokens.restype = None
        lib.lddl_join_tokens.argtypes = [
            ctypes.POINTER(ctypes.c_int32), ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int64), ctypes.c_int64,
            ctypes.c_char_p, ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int64), ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_int32)]
        lib.lddl_tok_docs.restype = ctypes.POINTER(_TokResult)
        lib.lddl_tok_docs.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_int64), ctypes.c_int64]
        lib.lddl_tok_result_free.argtypes = [ctypes.POINTER(_TokResult)]
        lib.lddl_split_docs.restype = ctypes.POINTER(_SplitResult)
        lib.lddl_split_docs.argtypes = [
            ctypes.c_char_p, ctypes.POINTER(ctypes.c_int64), ctypes.c_int64]
        lib.lddl_split_result_free.argtypes = [ctypes.POINTER(_SplitResult)]
        lib.lddl_bert_pairs.restype = ctypes.POINTER(_PairResult)
        lib.lddl_bert_pairs.argtypes = [
            ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int32),
            ctypes.c_int64, ctypes.POINTER(ctypes.c_int32), ctypes.c_int64,
            ctypes.c_int32, ctypes.c_double, ctypes.c_int32,
            ctypes.c_uint64, ctypes.c_uint64, ctypes.c_int32, ctypes.c_int32,
            ctypes.c_int32]
        lib.lddl_pairs_free.argtypes = [ctypes.POINTER(_PairResult)]
        lib.lddl_pairs_release.argtypes = [ctypes.POINTER(_PairResult)]
        lib.lddl_tok_result_release.argtypes = [ctypes.POINTER(_TokResult)]
        lib.lddl_buf_free.argtypes = [ctypes.c_void_p]
        lib.lddl_bert_instances.restype = ctypes.POINTER(_InstResult)
        lib.lddl_bert_instances.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64),
            ctypes.c_int64, ctypes.c_int32, ctypes.c_double, ctypes.c_int32,
            ctypes.c_uint64, ctypes.c_uint64, ctypes.c_int32, ctypes.c_int32,
            ctypes.c_int32]
        lib.lddl_inst_free.argtypes = [ctypes.POINTER(_InstResult)]
        lib.lddl_inst_release.argtypes = [ctypes.POINTER(_InstResult)]
        lib.lddl_mask_batch.restype = None
        lib.lddl_mask_batch.argtypes = [
            ctypes.c_uint64, ctypes.c_uint64,
            ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_uint8),
            ctypes.POINTER(ctypes.c_int64), ctypes.c_int64, ctypes.c_int64,
            ctypes.c_int32, ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_uint8),
            ctypes.c_int32]
        lib.lddl_split_docs_spans.restype = ctypes.POINTER(_SplitResult)
        lib.lddl_split_docs_spans.argtypes = [
            ctypes.c_char_p, ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int64), ctypes.c_int64,
            ctypes.c_char_p, ctypes.c_int64, ctypes.c_int32]
        lib.lddl_bert_instances_masked.restype = \
            ctypes.POINTER(_MaskedInstResult)
        lib.lddl_bert_instances_masked.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64),
            ctypes.c_int64, ctypes.c_int32, ctypes.c_double, ctypes.c_int32,
            ctypes.c_uint64, ctypes.c_uint64, ctypes.c_int32, ctypes.c_int32,
            ctypes.c_uint64, ctypes.c_uint64, ctypes.c_int32, ctypes.c_int64,
            ctypes.c_double, ctypes.c_int32, ctypes.c_int32]
        lib.lddl_masked_inst_free.argtypes = [
            ctypes.POINTER(_MaskedInstResult)]
        _lib = lib
        return _lib


def available():
    return _load() is not None


def fused_enabled():
    """True when the one-pass fused instances kernel should be used.
    ``LDDL_TPU_NATIVE_FUSED=0`` drops to the staged native engine
    (tokenize + pairs as two calls) — the first rung of the runtime
    fallback ladder fused -> staged -> hf. Read per call so tests (and
    spawned pool workers, which inherit the environment) can flip it."""
    return (_load() is not None
            and os.environ.get("LDDL_TPU_NATIVE_FUSED", "1") != "0")


def fused_mask_enabled():
    """True when the fused-masked instances kernel may run (the top rung
    of the masking ladder: split + WordPiece + NSP + shuffle + Philox
    masking replay in one call, no separate lddl_mask_batch pass).
    ``LDDL_TPU_NATIVE_FUSED_MASK=0`` forces the staged rungs so tests —
    and production triage — can pin byte identity across the ladder.
    ``LDDL_TPU_NATIVE_MASK=0`` (the "no C++ masking anywhere" triage
    knob) also drops this rung: an operator forcing numpy masking must
    actually get numpy masking, not the fused replay of it."""
    return (fused_enabled()
            and os.environ.get("LDDL_TPU_NATIVE_FUSED_MASK", "1") != "0"
            and os.environ.get("LDDL_TPU_NATIVE_MASK") != "0")


_MAX_THREADS = 64  # kMaxThreads in lddl_native.cpp


def resolve_threads(requested=None):
    """Effective in-kernel thread count (the v8 thread pool).

    Precedence: explicit ``requested`` > ``LDDL_TPU_NATIVE_THREADS`` env
    (``0`` or ``auto`` -> the process's usable-CPU count; unset/empty/
    unparsable -> 1). Clamped to [1, 64] (the kernel's kMaxThreads). Read
    per call so spawned pool workers — which inherit the environment the
    runner sized for them — resolve their own budget."""
    if requested is None:
        raw = os.environ.get("LDDL_TPU_NATIVE_THREADS", "").strip().lower()
        if raw in ("0", "auto"):
            from ..utils.cpus import usable_cpu_count
            requested = usable_cpu_count()
        else:
            try:
                requested = int(raw) if raw else 1
            except ValueError:
                requested = 1
    return max(1, min(_MAX_THREADS, int(requested)))


def thread_plan(requested, n_items):
    """Refusal ladder for a partition request: -> (effective, reason).

    The kernel never splits finer than one item per thread, so a bucket
    with fewer documents than the configured pool silently runs narrower;
    this mirrors that clamp on the Python side so callers (and the
    observability gauge) report the thread count that actually ran.
    ``reason`` is None when the request was honored, else a short tag
    (``"n_items"``, ``"cap"``, ``"floor"``) naming the clamp that fired."""
    requested = int(requested)
    eff = max(1, min(_MAX_THREADS, requested, max(1, int(n_items))))
    if eff == requested:
        return eff, None
    if requested < 1:
        return eff, "floor"
    if requested > _MAX_THREADS and eff == _MAX_THREADS:
        return eff, "cap"
    return eff, "n_items"


def _owned_array(lib, ptr, n, ctype, dtype):
    """Wrap a malloc'd kernel buffer as a numpy array WITHOUT copying;
    ownership transfers to the array — a finalizer frees the buffer when
    the array (and every view holding a base reference to it) is gone.

    Exception-safety contract with the result structs: the caller nulls
    the struct field right after this returns and always calls the
    kernel's ``*_free`` in a ``finally`` — so a failure mid-wrap frees
    exactly the not-yet-transferred buffers (free(NULL) is a no-op for
    the transferred ones) and the struct itself, never double-freeing."""
    addr = ctypes.cast(ptr, ctypes.c_void_p).value
    if not n or not addr:
        if addr:
            lib.lddl_buf_free(addr)
        return np.zeros(0, dtype=dtype)
    arr = np.ctypeslib.as_array(ctypes.cast(addr, ctypes.POINTER(ctype)),
                                shape=(int(n),))
    weakref.finalize(arr, lib.lddl_buf_free, addr)
    return arr


def _doc_ranges(docs):
    """(buf, starts, ends, n, keepalive) for the native kernels.

    ``docs`` is either a zero-copy span view (readers.DocSpans duck type:
    ``.buffer``/``.starts``/``.ends``) — no bytes are touched — or any
    sequence of bytes/str, which packs into one contiguous buffer."""
    buffer = getattr(docs, "buffer", None)
    if buffer is not None:
        starts = np.ascontiguousarray(docs.starts, dtype=np.int64)
        ends = np.ascontiguousarray(docs.ends, dtype=np.int64)
        return buffer, starts, ends, len(starts), (starts, ends)
    buf, offsets = _pack_docs(docs)
    return buf, offsets[:-1], offsets[1:], len(docs), (offsets,)


def join_tokens(flat_ids, row_lens, blob, tok_starts, tok_lens,
                total_bytes):
    """Space-join token ids into one contiguous UTF-8 buffer + int32 value
    offsets (the Arrow StringArray layout) with the C memcpy kernel.
    Returns (data uint8[total_bytes], offsets int32[n_rows+1]) or None
    when the native library is unavailable."""
    lib = _load()
    if lib is None:
        return None
    flat_ids = np.ascontiguousarray(flat_ids, dtype=np.int32)
    row_lens = np.ascontiguousarray(row_lens, dtype=np.int64)
    tok_starts = np.ascontiguousarray(tok_starts, dtype=np.int64)
    tok_lens = np.ascontiguousarray(tok_lens, dtype=np.int64)
    out = np.empty(int(total_bytes), dtype=np.uint8)
    offsets = np.empty(len(row_lens) + 1, dtype=np.int32)
    p_i32 = ctypes.POINTER(ctypes.c_int32)
    p_i64 = ctypes.POINTER(ctypes.c_int64)
    lib.lddl_join_tokens(
        flat_ids.ctypes.data_as(p_i32), len(flat_ids),
        row_lens.ctypes.data_as(p_i64), len(row_lens),
        blob,
        tok_starts.ctypes.data_as(p_i64),
        tok_lens.ctypes.data_as(p_i64),
        out.ctypes.data_as(ctypes.c_char_p),
        offsets.ctypes.data_as(p_i32))
    return out, offsets


def _pack_docs(texts):
    """Concatenate texts into one UTF-8 buffer + int64 offsets array.
    Accepts bytes (the preprocess pipeline's zero-decode path — the C++
    engine is the first and only UTF-8 decoder) or str."""
    encoded = [t if isinstance(t, bytes) else t.encode("utf-8")
               for t in texts]
    offsets = np.zeros(len(encoded) + 1, dtype=np.int64)
    np.cumsum([len(e) for e in encoded], out=offsets[1:])
    return b"".join(encoded), offsets


class NativeTokenizer:
    """Native split+normalize+WordPiece over documents.

    One instance holds the vocab hash table and the word->ids memo cache;
    reuse it across buckets (the memo is what makes Zipf-distributed text
    fast). Not thread-safe; use one instance per worker process.
    """

    def __init__(self, id_to_token, unk_id, do_lower_case=True,
                 memo_cap=None, splitter_blob=None):
        lib = _load()
        if lib is None:
            raise RuntimeError("native engine unavailable")
        self._args = (list(id_to_token), int(unk_id), bool(do_lower_case),
                      memo_cap, splitter_blob)
        self._lib = lib
        buf = "\n".join(id_to_token).encode("utf-8")
        self._handle = lib.lddl_tok_create(buf, len(buf), int(unk_id),
                                           1 if do_lower_case else 0)
        if memo_cap is not None:
            lib.lddl_tok_set_memo_cap(self._handle, int(memo_cap))
        if splitter_blob:
            lib.lddl_tok_set_splitter(self._handle, splitter_blob,
                                      len(splitter_blob))
        # Thread budget is resolved from the environment, NOT pickled in
        # _args: a pool worker rebuilding the tokenizer sizes itself from
        # the env the runner set for it, not from the parent's budget.
        lib.lddl_tok_set_threads(self._handle, resolve_threads())

    def set_threads(self, n):
        """Resize the in-kernel thread pool (clamped to [1, 64])."""
        self._lib.lddl_tok_set_threads(self._handle, int(n))

    def get_threads(self):
        """Configured pool width (a bucket with fewer docs runs narrower)."""
        return int(self._lib.lddl_tok_get_threads(self._handle))

    def thread_busy_ns(self):
        """Cumulative per-thread busy nanoseconds since construction, one
        entry per configured thread slot. Callers diff successive reads to
        attribute wall time (native_thread_busy_seconds_total{tid})."""
        out = (ctypes.c_int64 * _MAX_THREADS)()
        n = self._lib.lddl_tok_thread_busy_ns(self._handle, out,
                                              _MAX_THREADS)
        return [int(out[i]) for i in range(max(0, n))]

    def set_splitter(self, blob):
        """Attach (or clear, blob=None) corpus-learned punkt splitter
        params — the SplitterParams.serialize() blob (never empty: it
        carries a 'P1' header line, so clear-vs-params is unambiguous).
        tokenize_docs then splits with the learned decision procedure."""
        self._lib.lddl_tok_set_splitter(self._handle, blob or b"",
                                        len(blob or b""))
        self._args = self._args[:4] + (blob,)

    def __reduce__(self):
        # ctypes handles cannot cross pickle boundaries; rebuild from the
        # constructor args in the receiving process (fresh memo cache).
        return (NativeTokenizer, self._args)

    def __del__(self):
        if getattr(self, "_handle", None):
            self._lib.lddl_tok_free(self._handle)
            self._handle = None

    def tokenize_docs(self, texts):
        """-> (ids int32[], sent_lens int32[], doc_sent_counts int32[]).

        Sentences are concatenated in document order; empty sentences are
        dropped; doc_sent_counts[d] = number of non-empty sentences of
        document d. The returned arrays wrap the kernel's buffers without
        copying (ownership transfers; a finalizer frees each buffer).
        """
        if not len(texts):
            z = np.zeros(0, dtype=np.int32)
            return z, z.copy(), z.copy()
        lib = self._lib
        buf, offsets = _pack_docs(texts)
        res = lib.lddl_tok_docs(
            self._handle, buf,
            offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            len(texts))
        try:
            r = res.contents
            ids = _owned_array(lib, r.ids, r.n_ids, ctypes.c_int32,
                               np.int32)
            r.ids = None
            sent_lens = _owned_array(lib, r.sent_lens, r.n_sents,
                                     ctypes.c_int32, np.int32)
            r.sent_lens = None
            doc_counts = _owned_array(lib, r.doc_sent_counts, r.n_docs,
                                      ctypes.c_int32, np.int32)
            r.doc_sent_counts = None
        finally:
            # Frees whatever was NOT transferred (nulled fields are
            # free(NULL) no-ops) plus the struct — leak-free even when a
            # wrap raises mid-way.
            lib.lddl_tok_result_free(res)
        return ids, sent_lens, doc_counts

    def bert_instances(self, docs, max_seq_length, short_seq_prob,
                       duplicate_factor, seed, bucket, cls_id, sep_id,
                       want_ab=False):
        """FUSED hot path: documents -> packed NSP instance arrays in one
        native pass (split + normalize + WordPiece + pair creation +
        in-bucket shuffle), bit-identical to tokenize_docs + bert_pairs.

        ``docs`` is a readers.DocSpans view (zero-copy: the kernel reads
        the spool buffer in place) or a sequence of bytes/str. Returns
        (seq_ids, seq_lens, a_lens, is_random_next, a_ids, b_ids) numpy
        arrays wrapping the kernel's buffers without copying; a_ids/b_ids
        are None unless ``want_ab``.
        """
        lib = self._lib
        if not len(docs):
            z = np.zeros(0, dtype=np.int32)
            empty_ab = z.copy() if want_ab else None
            return (z, z.copy(), z.copy(), np.zeros(0, dtype=bool),
                    empty_ab, z.copy() if want_ab else None)
        buf, starts, ends, n, _keep = _doc_ranges(docs)
        p_i64 = ctypes.POINTER(ctypes.c_int64)
        res = lib.lddl_bert_instances(
            self._handle, buf,
            starts.ctypes.data_as(p_i64), ends.ctypes.data_as(p_i64),
            n, int(max_seq_length), float(short_seq_prob),
            int(duplicate_factor), int(seed) & (2**64 - 1),
            int(bucket) & (2**64 - 1), int(cls_id), int(sep_id),
            1 if want_ab else 0)
        try:
            r = res.contents
            n_inst = r.n_instances
            seq_ids = _owned_array(lib, r.seq_ids, r.n_seq_ids,
                                   ctypes.c_int32, np.int32)
            r.seq_ids = None
            seq_lens = _owned_array(lib, r.seq_lens, n_inst,
                                    ctypes.c_int32, np.int32)
            r.seq_lens = None
            a_lens = _owned_array(lib, r.a_lens, n_inst,
                                  ctypes.c_int32, np.int32)
            r.a_lens = None
            rn = _owned_array(lib, r.is_random_next, n_inst,
                              ctypes.c_uint8, np.uint8).view(np.bool_)
            r.is_random_next = None
            a_ids = b_ids = None
            if want_ab:
                a_ids = _owned_array(lib, r.a_ids, r.n_a_ids,
                                     ctypes.c_int32, np.int32)
                r.a_ids = None
                b_ids = _owned_array(lib, r.b_ids, r.n_b_ids,
                                     ctypes.c_int32, np.int32)
                r.b_ids = None
        finally:
            lib.lddl_inst_free(res)  # see tokenize_docs: leak-free
        return seq_ids, seq_lens, a_lens, rn, a_ids, b_ids

    def bert_instances_masked(self, docs, max_seq_length, short_seq_prob,
                              duplicate_factor, seed, bucket, cls_id,
                              sep_id, key_bytes, mask_id, vocab_size,
                              masked_lm_ratio, max_predictions, width_min):
        """FUSED-MASKED hot path: documents -> MASKED instance arrays in
        one native pass — everything bert_instances does PLUS the
        bit-exact numpy-Philox masking replay over the (virtual) padded
        matrix the staged path would build (key = ``key_bytes`` from
        utils.rng.sample_key_bytes; same draw-order contract as
        mask_batch). Returns (a_lens, seq_lens, is_random_next, flat_a,
        flat_b, sel_positions, sel_lens, label_ids) — masked A/B id
        segments plus the row-relative mask selection — or None when the
        parameters fall outside the frozen replay contract (vocab size
        must be in [2, 2^32))."""
        vocab_size = int(vocab_size)
        if not (2 <= vocab_size < 0xFFFFFFFF):
            return None
        lib = self._lib
        z = np.zeros(0, dtype=np.int32)
        if not len(docs):
            return (z, z.copy(), np.zeros(0, dtype=bool), z.copy(),
                    z.copy(), z.copy(), z.copy(), z.copy())
        buf, starts, ends, n, _keep = _doc_ranges(docs)
        k0, k1 = np.frombuffer(key_bytes, dtype="<u8")
        p_i64 = ctypes.POINTER(ctypes.c_int64)
        res = lib.lddl_bert_instances_masked(
            self._handle, buf,
            starts.ctypes.data_as(p_i64), ends.ctypes.data_as(p_i64),
            n, int(max_seq_length), float(short_seq_prob),
            int(duplicate_factor), int(seed) & (2**64 - 1),
            int(bucket) & (2**64 - 1), int(cls_id), int(sep_id),
            int(k0), int(k1), int(mask_id), vocab_size,
            float(masked_lm_ratio), int(max_predictions), int(width_min))
        try:
            r = res.contents
            n_inst = r.n_instances
            a_lens = _owned_array(lib, r.a_lens, n_inst,
                                  ctypes.c_int32, np.int32)
            r.a_lens = None
            seq_lens = _owned_array(lib, r.seq_lens, n_inst,
                                    ctypes.c_int32, np.int32)
            r.seq_lens = None
            rn = _owned_array(lib, r.is_random_next, n_inst,
                              ctypes.c_uint8, np.uint8).view(np.bool_)
            r.is_random_next = None
            flat_a = _owned_array(lib, r.a_ids, r.n_a_ids,
                                  ctypes.c_int32, np.int32)
            r.a_ids = None
            flat_b = _owned_array(lib, r.b_ids, r.n_b_ids,
                                  ctypes.c_int32, np.int32)
            r.b_ids = None
            sel_pos = _owned_array(lib, r.mlm_pos, r.n_mlm,
                                   ctypes.c_int32, np.int32)
            r.mlm_pos = None
            label_ids = _owned_array(lib, r.mlm_labels, r.n_mlm,
                                     ctypes.c_int32, np.int32)
            r.mlm_labels = None
            sel_lens = _owned_array(lib, r.mlm_lens, n_inst,
                                    ctypes.c_int32, np.int32)
            r.mlm_lens = None
        finally:
            lib.lddl_masked_inst_free(res)  # see tokenize_docs: leak-free
        return (a_lens, seq_lens, rn, flat_a, flat_b, sel_pos, sel_lens,
                label_ids)


def bert_pairs(ids, sent_lens, doc_sent_counts, max_seq_length,
               short_seq_prob, duplicate_factor, seed, bucket, cls_id,
               sep_id, threads=None):
    """NSP pair creation over a tokenized bucket (lddl_tok_docs output),
    replaying the frozen CounterRNG streams of the Python engine
    (preprocess.bert.pairs_from_documents). Returns flat instance arrays
    (seq_ids, seq_lens, a_lens, is_random_next). ``threads=None`` resolves
    the pool width from LDDL_TPU_NATIVE_THREADS; output is byte-identical
    at every width (the pair streams are per-document-keyed)."""
    lib = _load()
    if lib is None:
        raise RuntimeError("native engine unavailable")
    ids = np.ascontiguousarray(ids, dtype=np.int32)
    sent_lens = np.ascontiguousarray(sent_lens, dtype=np.int32)
    doc_sent_counts = np.ascontiguousarray(doc_sent_counts, dtype=np.int32)
    p_i32 = ctypes.POINTER(ctypes.c_int32)
    res = lib.lddl_bert_pairs(
        ids.ctypes.data_as(p_i32), sent_lens.ctypes.data_as(p_i32),
        len(sent_lens), doc_sent_counts.ctypes.data_as(p_i32),
        len(doc_sent_counts), int(max_seq_length), float(short_seq_prob),
        int(duplicate_factor), int(seed) & (2**64 - 1),
        int(bucket) & (2**64 - 1), int(cls_id), int(sep_id),
        resolve_threads(threads))
    try:
        r = res.contents
        n = r.n_instances
        seq_ids = _owned_array(lib, r.seq_ids, r.n_seq_ids,
                               ctypes.c_int32, np.int32)
        r.seq_ids = None
        seq_lens_o = _owned_array(lib, r.seq_lens, n, ctypes.c_int32,
                                  np.int32)
        r.seq_lens = None
        a_lens = _owned_array(lib, r.a_lens, n, ctypes.c_int32, np.int32)
        r.a_lens = None
        rn = _owned_array(lib, r.is_random_next, n,
                          ctypes.c_uint8, np.uint8).view(np.bool_)
        r.is_random_next = None
    finally:
        lib.lddl_pairs_free(res)  # see tokenize_docs: leak-free
    return seq_ids, seq_lens_o, a_lens, rn


def mask_batch(key_bytes, ids, candidate, num_to_predict, mask_id,
               vocab_size, threads=None):
    """Static MLM masking — a bit-exact native replay of
    ops.masking.mask_batch_numpy on the numpy-Philox stream keyed by
    ``key_bytes`` (utils.rng.sample_key_bytes). Returns (masked_ids,
    selected) or None when the native engine is unavailable, disabled
    (``LDDL_TPU_NATIVE_MASK=0``), or the parameters fall outside the
    frozen replay contract (vocab size must be in [2, 2^32))."""
    lib = _load()
    if lib is None or os.environ.get("LDDL_TPU_NATIVE_MASK") == "0":
        return None
    vocab_size = int(vocab_size)
    if not (2 <= vocab_size < 0xFFFFFFFF):
        return None
    ids = np.ascontiguousarray(ids, dtype=np.int32)
    candidate = np.ascontiguousarray(candidate, dtype=np.uint8)
    num_to_predict = np.ascontiguousarray(num_to_predict, dtype=np.int64)
    n, width = ids.shape
    out = np.empty_like(ids)
    selected = np.empty((n, width), dtype=np.uint8)
    k0, k1 = np.frombuffer(key_bytes, dtype="<u8")
    p_i32 = ctypes.POINTER(ctypes.c_int32)
    p_u8 = ctypes.POINTER(ctypes.c_uint8)
    lib.lddl_mask_batch(
        int(k0), int(k1),
        ids.ctypes.data_as(p_i32), candidate.ctypes.data_as(p_u8),
        num_to_predict.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        n, width, int(mask_id), vocab_size,
        out.ctypes.data_as(p_i32), selected.ctypes.data_as(p_u8),
        resolve_threads(threads))
    return out, selected.view(np.bool_)


def split_docs(texts, splitter_blob=None, threads=None):
    """Sentence-split documents natively -> list of sentence lists.

    Same boundaries as preprocess.sentences.split_sentences — or, with
    ``splitter_blob`` (SplitterParams.serialize()), as
    split_sentences_learned (enforced by tests); raises RuntimeError when
    the native engine is unavailable. ``texts`` may be a readers.DocSpans
    view (zero-copy: the kernel scans the spool buffer in place) or any
    sequence of str/bytes.
    """
    lib = _load()
    if lib is None:
        raise RuntimeError("native engine unavailable")
    if not len(texts):
        return []
    buf, starts, ends, n, _keep = _doc_ranges(texts)
    p_i64 = ctypes.POINTER(ctypes.c_int64)
    res = lib.lddl_split_docs_spans(
        buf, starts.ctypes.data_as(p_i64), ends.ctypes.data_as(p_i64),
        n, splitter_blob, len(splitter_blob or b""),
        resolve_threads(threads))
    try:
        r = res.contents
        starts_o = np.ctypeslib.as_array(r.starts, shape=(r.n_sents,)).copy()
        ends_o = np.ctypeslib.as_array(r.ends, shape=(r.n_sents,)).copy()
        counts = np.ctypeslib.as_array(
            r.doc_sent_counts, shape=(r.n_docs,)).copy()
    finally:
        lib.lddl_split_result_free(res)
    out = []
    k = 0
    # errors="replace" mirrors the Python path's document decode; sentence
    # ranges of valid UTF-8 round-trip identically either way.
    for d in range(n):
        sents = []
        for _ in range(int(counts[d])):
            sents.append(bytes(buf[starts_o[k]:ends_o[k]])
                         .decode("utf-8", errors="replace"))
            k += 1
        out.append(sents)
    return out
