"""Build the native engine shared library.

Reference parity note: the reference obtains native performance by
delegation (pyarrow C++, jemalloc, MPI — SURVEY.md §0); our runtime's own
hot loop is native C++ compiled here. The library is built lazily on first
use with the system g++ (baked into TPU images) and cached next to the
sources; rebuilds trigger only when a source file is newer than the cached
.so. Everything degrades gracefully: callers treat a failed build as
"native engine unavailable" and fall back to the HF/Python path.
"""

import os
import subprocess
import tempfile

from ..resilience.io import atomic_publish, atomic_write

_DIR = os.path.dirname(os.path.abspath(__file__))
SRC = os.path.join(_DIR, "lddl_native.cpp")
TABLES = os.path.join(_DIR, "unicode_tables.h")
# Paths of the NORMAL (unsanitized) build; sanitized builds live under
# mode-suffixed names (lib_path) so the two can never collide.
LIB = os.path.join(_DIR, "_lddl_native.so")
LIB_META = LIB + ".meta"

_SANITIZE_FLAGS = {
    "tsan": ("-fsanitize=thread",),
    "asan": ("-fsanitize=address",),
    "ubsan": ("-fsanitize=undefined",),
}


def _march():
    return os.environ.get("LDDL_TPU_NATIVE_MARCH", "native")


def sanitize_modes():
    """Sanitizer modes requested via LDDL_TPU_NATIVE_SANITIZE (comma
    separated subset of tsan/asan/ubsan), as a sorted tuple. () means a
    normal build. Invalid values raise — a typo silently building an
    uninstrumented kernel would make the CI sanitize smoke vacuous."""
    raw = os.environ.get("LDDL_TPU_NATIVE_SANITIZE", "").strip()
    if not raw:
        return ()
    modes = sorted({m.strip() for m in raw.split(",") if m.strip()})
    bad = [m for m in modes if m not in _SANITIZE_FLAGS]
    if bad:
        raise ValueError(
            "LDDL_TPU_NATIVE_SANITIZE={!r}: unknown mode(s) {}; expected "
            "a comma-separated subset of {}".format(
                raw, bad, "/".join(sorted(_SANITIZE_FLAGS))))
    if "tsan" in modes and "asan" in modes:
        raise ValueError(
            "LDDL_TPU_NATIVE_SANITIZE: tsan and asan are mutually "
            "exclusive (gcc cannot combine their runtimes)")
    return tuple(modes)


def lib_path(modes=None):
    """The .so path for the requested sanitizer modes. Sanitized builds
    get their own cache key (filename) so toggling
    LDDL_TPU_NATIVE_SANITIZE can never serve a binary built for the
    other mode."""
    modes = sanitize_modes() if modes is None else tuple(modes)
    if not modes:
        return LIB
    return os.path.join(_DIR, "_lddl_native.san-{}.so".format(
        "-".join(modes)))


def compile_flags(modes=None):
    """The exact g++ flags for this build (part of the staleness meta
    tag, so a flag change — including the sanitizer set — rebuilds even
    against an mtime-equal cached .so).

    -march=native: the engine builds lazily on the machine that runs it
    (2x on the WordPiece/UTF-8 hot loops vs plain -O3); heterogeneous
    fleets sharing one prebuilt image pin LDDL_TPU_NATIVE_MARCH.
    -pthread: the v8 engine runs an in-kernel thread pool. Sanitized
    builds trade -O3 for -O1 -g -fno-omit-frame-pointer so TSan/ASan
    reports carry usable stacks and races are not optimized away."""
    modes = sanitize_modes() if modes is None else tuple(modes)
    flags = ["-march=" + _march(), "-std=c++17",
             "-shared", "-fPIC", "-pthread"]
    if modes:
        flags = ["-O1", "-g", "-fno-omit-frame-pointer"] + flags
        for m in modes:
            flags.extend(_SANITIZE_FLAGS[m])
    else:
        flags = ["-O3"] + flags
    return flags


def source_digest():
    """Digest of the kernel sources the .so must have been built from
    (lddl_native.cpp + unicode_tables.h). Part of the meta tag so a stale
    binary — mtime-equal but content-different sources, e.g. a git
    checkout that preserves timestamps, or a partially synced tree —
    fails the staleness check LOUDLY and rebuilds instead of silently
    serving old kernels (tests/test_fused.py pins this)."""
    import hashlib
    h = hashlib.sha256()
    for path in (SRC, TABLES):
        try:
            with open(path, "rb") as f:
                h.update(f.read())
        except OSError:
            h.update(b"missing:" + path.encode())
    return h.hexdigest()[:16]


def _lib_meta_tag():
    """Identifies what the cached .so was built FOR. -march=native bakes
    the build host's ISA into a .so cached in the package directory; on a
    shared tree (NFS, prebuilt image) a different host must rebuild
    instead of SIGILL-ing, so the march setting joins the staleness
    check. 'native' is intentionally not resolved to a concrete ISA: two
    heterogeneous hosts sharing a tree should pin LDDL_TPU_NATIVE_MARCH.
    The tag also embeds a digest of the kernel sources (source_digest),
    so content drift rebuilds even when mtimes lie, PLUS the sanitizer
    mode set and the full compiler flag list, so toggling
    LDDL_TPU_NATIVE_SANITIZE (or any flag change) can never serve a
    stale cached .so."""
    import platform
    modes = sanitize_modes()
    tag = ("march=" + _march() + ";src=" + source_digest()
           + ";sanitize=" + (",".join(modes) or "off")
           + ";flags=" + " ".join(compile_flags(modes)))
    if _march() == "native":
        tag += ";host=" + platform.machine()
        # A concrete per-microarch signal where available (x86 flags set
        # differs across generations; cheap single read).
        try:
            with open("/proc/cpuinfo") as f:
                for line in f:
                    if line.startswith("flags"):
                        import hashlib
                        tag += ";cpuflags=" + hashlib.sha256(
                            line.encode()).hexdigest()[:12]
                        break
        # /proc/cpuinfo probe is a cache-tag refinement; absent (non-Linux)
        # just means a coarser tag.
        except OSError:  # lddl: disable=swallowed-error
            pass
    return tag


def _stale(target, sources):
    if not os.path.exists(target):
        return True
    t = os.path.getmtime(target)
    return any(os.path.getmtime(s) > t for s in sources if os.path.exists(s))


def _lib_stale():
    lib = lib_path()
    if _stale(lib, [SRC, TABLES]):
        return True
    try:
        with open(lib + ".meta") as f:
            return f.read().strip() != _lib_meta_tag()
    except OSError:
        return True


def _tables_stale():
    """Tables are stale on mtime (generator changed) OR when the
    calibration tag no longer matches this environment — the HF-side
    semantics are probed from the installed ``tokenizers`` package, so a
    header calibrated elsewhere (or via the unicodedata fallback) must be
    regenerated to keep exact parity."""
    if _stale(TABLES, [os.path.join(_DIR, "gen_tables.py")]):
        return True
    from . import gen_tables
    want = "// calibration: " + gen_tables.calibration_tag()
    try:
        with open(TABLES) as f:
            head = [next(f, "").strip() for _ in range(3)]
    except OSError:
        return True
    return want not in head


import contextlib


@contextlib.contextmanager
def _build_lock():
    """Cross-process exclusive lock: table generation is a multi-second
    full-Unicode probe, so an N-worker pool must generate once, not N
    times concurrently. Blocks until the winner finishes; losers then see
    fresh tables and skip regeneration."""
    path = os.path.join(_DIR, ".build.lock")
    try:
        import fcntl
        with open(path, "w") as f:
            fcntl.flock(f, fcntl.LOCK_EX)
            try:
                yield
            finally:
                fcntl.flock(f, fcntl.LOCK_UN)
    except ImportError:  # non-POSIX: fall back to the atomic os.replace race
        yield


def ensure_built(verbose=False):
    """Build (if stale) and return the .so path for the current
    LDDL_TPU_NATIVE_SANITIZE mode set, or None on failure."""
    try:
        if not _tables_stale() and not _lib_stale():
            return lib_path()
        with _build_lock():
            # Re-check under the lock: another process may have finished.
            if _tables_stale():
                from . import gen_tables
                fd, tmp = tempfile.mkstemp(dir=_DIR, suffix=".h.tmp")
                os.close(fd)
                try:
                    gen_tables.generate(tmp)
                    atomic_publish(tmp, TABLES)
                finally:
                    if os.path.exists(tmp):
                        os.unlink(tmp)
            if _lib_stale():
                fd, tmp = tempfile.mkstemp(dir=_DIR, suffix=".so.tmp")
                os.close(fd)
                try:
                    cmd = ["g++"] + compile_flags() + [SRC, "-o", tmp]
                    proc = subprocess.run(cmd, capture_output=True, text=True)
                    if proc.returncode != 0:
                        if verbose:
                            print("native build failed:\n" + proc.stderr)
                        return None
                    # Durable atomic publish: on a shared tree (NFS,
                    # prebuilt image) a torn .so would SIGBUS every host.
                    lib = lib_path()
                    atomic_publish(tmp, lib)
                    atomic_write(lib + ".meta", _lib_meta_tag() + "\n")
                finally:
                    if os.path.exists(tmp):
                        os.unlink(tmp)
        return lib_path()
    except Exception as e:  # missing g++, read-only fs, ...
        if verbose:
            print("native build unavailable: {}".format(e))
        return None


if __name__ == "__main__":
    import sys
    path = ensure_built(verbose=True)
    print(path or "BUILD FAILED")
    sys.exit(0 if path else 1)
