"""SPMD load balancer: equalize per-shard sample counts to ±1.

Reference parity: lddl/dask/load_balance.py. Same guarantees and output
contract (``shard-<i>.parquet[_<bin>]`` with every shard holding ``base`` or
``base+1`` samples, plus a ``.num_samples.json`` cache), with the MPI
collectives replaced by the lddl_tpu Communicator (jax.distributed on pods,
no MPI dependency).

Why balancing matters: the loader shards *files* across data-parallel
groups; equal per-file counts are what keep rank-sharded epochs from
diverging (ref: lddl/torch/datasets.py:142-156).

Design (ref: load_balance.py:321-369): SPMD-replicated deterministic control
flow. Every rank computes the identical transfer plan over shard *metadata*;
exactly one rank — the transfer's owner — performs the parquet I/O for each
transfer. Row custody always lives on the shared filesystem: every mutation
is immediately persisted by its owner, so any rank can own the next transfer
touching that shard after the per-iteration barrier. Communication is one
sum-allreduce (census) plus one barrier per iteration; rows never ride the
network directly.

Differences from the reference (improvements, not drift):
- Transfers move ``min(surplus, deficit)`` against exact per-shard targets
  instead of halving pair differences, so convergence takes O(1) iterations
  for typical skew rather than O(log skew).
- Empty-input edge cases raise clean errors instead of asserting deep in
  pyarrow.
"""

import os

import pyarrow as pa
import pyarrow.parquet as pq

from ..parallel.distributed import LocalCommunicator
from ..utils.fs import (
    get_all_bin_ids,
    get_all_parquets_under,
    get_file_paths_for_bin_id,
    get_num_samples_of_parquet,
    write_num_samples_cache,
)
from ..utils.types import File


class _Shard:
    """One output shard: the input Files still feeding it plus an output
    file accumulating rows it has taken custody of. All ranks track the
    same metadata; only transfer owners move actual rows."""

    def __init__(self, idx, input_files, out_dir, postfix=""):
        self.idx = idx
        self.input_files = list(input_files)
        self.out_path = os.path.join(
            out_dir, "shard-{}.parquet{}".format(idx, postfix))
        self.output_file = None  # File once any rows land in out_path

    @property
    def num_samples(self):
        n = sum(f.num_samples for f in self.input_files)
        if self.output_file is not None:
            n += self.output_file.num_samples
        return n

    def _store(self, num_samples, table=None):
        """Append rows to the output file. ``table`` is given only on the
        rank doing real I/O; all other ranks mirror the count."""
        if table is not None:
            assert table.num_rows == num_samples
        if self.output_file is None:
            self.output_file = File(self.out_path, 0)
        elif table is not None and self.output_file.num_samples > 0:
            table = pa.concat_tables([pq.read_table(self.out_path), table])
        self.output_file.num_samples += num_samples
        if table is not None:
            assert table.num_rows == self.output_file.num_samples
            pq.write_table(table, self.out_path)

    def _load(self, num_samples, with_table):
        """Remove rows, consuming input files from the end first, then the
        output file. Leftovers of a partially-consumed file are re-stored
        to the output file (persisted immediately when ``with_table``)."""
        assert num_samples <= self.num_samples
        tables = [] if with_table else None
        while num_samples > 0:
            from_output = not self.input_files
            if from_output:
                src = self.output_file
                self.output_file = None
            else:
                src = self.input_files.pop()
            take = min(src.num_samples, num_samples)
            src_table = None
            if with_table:
                src_table = pq.read_table(src.path)
                assert src_table.num_rows == src.num_samples
                tables.append(src_table.slice(0, take))
            if take < src.num_samples:
                self._store(
                    src.num_samples - take,
                    table=src_table.slice(take) if with_table else None)
            elif from_output and with_table:
                # Output file fully drained: delete it so stale rows cannot
                # be rediscovered by directory globbing. (A later _store for
                # this shard recreates the file fresh.)
                os.remove(src.path)
            num_samples -= take
        if with_table:
            return pa.concat_tables(tables)
        return None

    def transfer_to(self, other, num_samples, i_am_owner):
        other._store(num_samples,
                     table=self._load(num_samples, with_table=i_am_owner))

    def flush(self, i_am_owner):
        """Fold any remaining input files into the output shard file.

        ``_load`` always pops whole input files (a partially-consumed file's
        leftover moves to the output file immediately), so everything still
        listed here is an intact original.
        """
        remaining = [f for f in self.input_files if f.num_samples > 0]
        self.input_files = []
        if not remaining:
            return
        n = sum(f.num_samples for f in remaining)
        table = None
        if i_am_owner:
            table = pa.concat_tables([pq.read_table(f.path) for f in remaining])
        self._store(n, table=table)


def _census(file_paths, comm):
    """Per-file sample counts: rank-strided footer reads + sum-allreduce.
    (ref: load_balance.py:226-242)"""
    counts = [0] * len(file_paths)
    for i in range(comm.rank, len(file_paths), comm.world_size):
        counts[i] = get_num_samples_of_parquet(file_paths[i])
    counts = comm.allreduce_sum(counts)
    return [File(p, int(n)) for p, n in zip(file_paths, counts)]


def _balance_one_set(file_paths, out_dir, num_shards, comm, postfix=""):
    """Balance one (possibly per-bin) file set into num_shards outputs."""
    files = _census(file_paths, comm)
    total = sum(f.num_samples for f in files)
    if total < num_shards:
        raise ValueError(
            "cannot balance {} samples into {} shards; every shard must "
            "receive at least one sample".format(total, num_shards))
    base = total // num_shards
    num_plus_one = total - base * num_shards
    targets = [base + (1 if i < num_plus_one else 0) for i in range(num_shards)]

    shards = [
        _Shard(i, files[i::num_shards], out_dir, postfix=postfix)
        for i in range(num_shards)
    ]

    transfer_idx = 0
    for _ in range(num_shards + 2):
        large = [s for s in shards if s.num_samples > targets[s.idx]]
        small = [s for s in shards if s.num_samples < targets[s.idx]]
        if not large and not small:
            break
        large.sort(key=lambda s: s.num_samples - targets[s.idx], reverse=True)
        small.sort(key=lambda s: targets[s.idx] - s.num_samples, reverse=True)
        for ls, ss in zip(large, small):
            n = min(ls.num_samples - targets[ls.idx],
                    targets[ss.idx] - ss.num_samples)
            if n <= 0:
                continue
            ls.transfer_to(
                ss, n, i_am_owner=(transfer_idx % comm.world_size == comm.rank))
            transfer_idx += 1
        comm.barrier()
    else:
        raise RuntimeError("balancer failed to converge")

    for s in shards:
        assert s.num_samples == targets[s.idx], (
            "shard {} has {} != target {}".format(
                s.idx, s.num_samples, targets[s.idx]))

    for s in shards:
        s.flush(i_am_owner=(s.idx % comm.world_size == comm.rank))
    comm.barrier()
    return {os.path.basename(s.out_path): int(s.num_samples) for s in shards}


def balance_shards(in_dir, out_dir, num_shards, comm=None, log=None):
    """Balance preprocessor output into ``num_shards`` equal shards (per bin
    when the input is binned). SPMD: call on every host with identical args.

    Returns {shard_basename: num_samples}; writes .num_samples.json.
    """
    comm = comm or LocalCommunicator()
    log = log or (lambda msg: None)
    if num_shards < 1:
        raise ValueError("num_shards must be >= 1")
    if os.path.isdir(out_dir):
        stale = [n for n in os.listdir(out_dir) if ".parquet" in n]
        if stale:
            raise ValueError(
                "output dir {} already contains {} shard files (e.g. {}); "
                "remove them or choose a fresh directory".format(
                    out_dir, len(stale), stale[0]))
    os.makedirs(out_dir, exist_ok=True)
    file_paths = get_all_parquets_under(in_dir)
    if not file_paths:
        raise ValueError("no parquet shards under {}".format(in_dir))
    bin_ids = get_all_bin_ids(file_paths)
    counts = {}
    if bin_ids:
        from ..utils.fs import get_bin_id_of_path
        unbinned = [p for p in file_paths if get_bin_id_of_path(p) is None]
        if unbinned:
            raise ValueError(
                "input mixes binned and unbinned shards ({} unbinned, e.g. "
                "{}); balance them separately".format(
                    len(unbinned), os.path.basename(unbinned[0])))
        for b in bin_ids:
            bin_paths = get_file_paths_for_bin_id(file_paths, b)
            counts.update(
                _balance_one_set(bin_paths, out_dir, num_shards, comm,
                                 postfix="_{}".format(b)))
            log("balanced bin {}: {} files -> {} shards".format(
                b, len(bin_paths), num_shards))
    else:
        counts.update(_balance_one_set(file_paths, out_dir, num_shards, comm))
        log("balanced {} files -> {} shards".format(
            len(file_paths), num_shards))
    if comm.rank == 0:
        write_num_samples_cache(out_dir, counts)
    comm.barrier()
    return counts


def generate_num_samples_cache(path, comm=None):
    """(Re)build .num_samples.json for a directory of parquet shards.
    (ref: load_balance.py:428-455)"""
    comm = comm or LocalCommunicator()
    file_paths = get_all_parquets_under(path)
    if not file_paths:
        raise ValueError("no parquet shards under {}".format(path))
    files = _census(file_paths, comm)
    counts = {os.path.basename(f.path): int(f.num_samples) for f in files}
    if comm.rank == 0:
        write_num_samples_cache(path, counts)
    comm.barrier()
    return counts
