"""SPMD load balancer: equalize per-shard sample counts to ±1.

Reference parity: lddl/dask/load_balance.py. Same guarantees and output
contract (``shard-<i>.parquet[_<bin>]`` with every shard holding ``base`` or
``base+1`` samples, plus a ``.num_samples.json`` cache), with the MPI
collectives replaced by the lddl_tpu Communicator (jax.distributed on pods,
no MPI dependency).

Why balancing matters: the loader shards *files* across data-parallel
groups; equal per-file counts are what keep rank-sharded epochs from
diverging (ref: lddl/torch/datasets.py:142-156).

Design (ref: load_balance.py:321-369): SPMD-replicated deterministic control
flow. Every rank computes the identical transfer plan over shard *metadata*;
exactly one rank — the transfer's owner — performs the parquet I/O for each
transfer. Row custody always lives on the shared filesystem: every mutation
is immediately persisted by its owner, so any rank can own the next transfer
touching that shard after the per-iteration barrier. Communication is one
sum-allreduce (census) plus one barrier per iteration; rows never ride the
network directly.

Differences from the reference (improvements, not drift):
- Transfers move exact ``min(surplus, deficit)`` amounts in one two-pointer
  sweep instead of halving pair differences: convergence is a single
  iteration for ANY skew (the reference's scheme is O(log skew) iterations,
  each a barrier + re-read). All transfers out of one source shard are
  grouped into a single load (``transfer_to_many``), so a giant input
  feeding many shards is read once, not once per destination — the
  ``stats`` dict quantifies this (rows_read <= total rows, property-tested
  in tests/test_balance.py).
- Empty-input edge cases raise clean errors instead of asserting deep in
  pyarrow.
"""

import os

import pyarrow as pa

from .. import observability as obs
from ..preprocess.binning import (DEFAULT_PARQUET_COMPRESSION,
                                  write_options_for_names)

from ..parallel.distributed import LocalCommunicator
from ..resilience.integrity import build_manifest
from ..resilience.io import read_table, write_table_atomic
from ..utils.fs import (
    get_all_bin_ids,
    get_all_parquets_under,
    get_file_paths_for_bin_id,
    get_num_samples_of_parquet,
    write_num_samples_cache,
)
from ..utils.types import File


class _Shard:
    """One output shard: the input Files still feeding it plus *part files*
    holding rows it has taken custody of. All ranks track the same
    metadata; only transfer owners move actual rows.

    Custody is write-once part files (``<out>.partK``, K a metadata-
    replicated sequence number): every store persists a FRESH file, never a
    read-modify-write — so two transfers owned by different ranks can land
    rows on the same destination within one barrier window without racing,
    and appending never re-reads accumulated rows. ``flush`` merges the
    remaining inputs + parts into the final shard file after the
    convergence barrier.

    ``stats`` (optional dict) accumulates the I/O the plan implies, in
    rows, identically on every rank (the plan is SPMD-replicated):
    ``rows_read`` counts source-file reads, ``rows_reread`` part-file
    drain re-reads, ``rows_written`` rows persisted (leftovers, landed
    transfers, and the final merge). A minimal pass costs total_rows read
    + total_rows written; everything above that is the balancing overhead
    being quantified."""

    def __init__(self, idx, input_files, out_dir, postfix="", stats=None):
        self.idx = idx
        self.input_files = list(input_files)
        self.out_path = os.path.join(
            out_dir, "shard-{}.parquet{}".format(idx, postfix))
        self.output_parts = []  # custody Files, deterministic paths
        self._part_seq = 0
        self.stats = stats

    def _count(self, key, n):
        if self.stats is not None:
            self.stats[key] = self.stats.get(key, 0) + int(n)

    @property
    def num_samples(self):
        return (sum(f.num_samples for f in self.input_files)
                + sum(f.num_samples for f in self.output_parts))

    def _store(self, num_samples, table=None):
        """Take custody of rows in a fresh part file. ``table`` is given
        only on the rank doing real I/O; all other ranks mirror the
        metadata (including the part sequence number)."""
        assert num_samples > 0
        path = "{}.part{}".format(self.out_path, self._part_seq)
        self._part_seq += 1
        self.output_parts.append(File(path, num_samples))
        self._count("rows_written", num_samples)
        if table is not None:
            assert table.num_rows == num_samples
            write_table_atomic(table, path,
                               compression=DEFAULT_PARQUET_COMPRESSION,
                               **write_options_for_names(table.schema.names))
            _count_bytes_rewritten(path)

    def _load(self, num_samples, with_table):
        """Remove rows, consuming input files from the end first, then
        custody parts. The leftover of a partially-consumed source becomes
        a fresh part (persisted immediately when ``with_table``)."""
        assert num_samples <= self.num_samples
        tables = [] if with_table else None
        while num_samples > 0:
            from_output = not self.input_files
            src = (self.output_parts.pop() if from_output
                   else self.input_files.pop())
            take = min(src.num_samples, num_samples)
            self._count("rows_reread" if from_output else "rows_read",
                        src.num_samples)
            src_table = None
            if with_table:
                src_table = read_table(src.path)
                assert src_table.num_rows == src.num_samples
                tables.append(src_table.slice(0, take))
            if take < src.num_samples:
                self._store(
                    src.num_samples - take,
                    table=src_table.slice(take) if with_table else None)
            if from_output and with_table:
                # The popped part is dead (its leftover, if any, moved to a
                # fresh part above): delete so stale rows cannot linger.
                os.remove(src.path)
            num_samples -= take
        if with_table:
            return pa.concat_tables(tables)
        return None

    def transfer_to_many(self, assignments, i_am_owner):
        """Move rows to several shards with ONE load of this shard:
        ``assignments`` is [(shard, num_samples), ...]. Grouping all
        transfers out of a source avoids re-reading its leftover once per
        destination (the dominant I/O cost when one giant file feeds many
        shards)."""
        total = sum(n for _, n in assignments)
        if i_am_owner:
            # Owner-side count: every rank mirrors the plan metadata, but
            # only the owner moves rows — counting there keeps the counter
            # exact per process in multi-rank (thread-comm) layouts too.
            obs.inc("balance_samples_moved_total", total)
        table = self._load(total, with_table=i_am_owner)
        offset = 0
        for other, n in assignments:
            other._store(n, table=table.slice(offset, n) if i_am_owner
                         else None)
            offset += n

    def flush(self, i_am_owner):
        """Merge remaining input files + custody parts into the final
        shard file. Must run after a barrier so every part written by any
        owner is visible; every shard flushes exactly once."""
        inputs = [f for f in self.input_files if f.num_samples > 0]
        sources = inputs + self.output_parts
        self.input_files = []
        parts, self.output_parts = self.output_parts, []
        n = sum(f.num_samples for f in sources)
        assert n > 0, "shard {} would be empty".format(self.idx)
        self._count("rows_read", sum(f.num_samples for f in inputs))
        self._count("rows_reread", sum(f.num_samples for f in parts))
        self._count("rows_written", n)
        if i_am_owner:
            table = pa.concat_tables([read_table(f.path) for f in sources])
            assert table.num_rows == n
            write_table_atomic(table, self.out_path,
                               compression=DEFAULT_PARQUET_COMPRESSION,
                               **write_options_for_names(table.schema.names))
            _count_bytes_rewritten(self.out_path)
            for f in parts:
                os.remove(f.path)
        self.final_file = File(self.out_path, n)


def _count_bytes_rewritten(path):
    """Bytes this rank physically wrote while balancing (custody parts +
    final merges) — the I/O cost the ``stats`` row counts only imply."""
    if not obs.enabled():
        return
    try:
        obs.inc("balance_bytes_rewritten_total", os.stat(path).st_size)
    # Telemetry-only stat of a file this rank just wrote; a racing stat
    # failure must not fail the balance.
    except OSError:  # lddl: disable=swallowed-error
        pass


def _census(file_paths, comm):
    """Per-file sample counts: rank-strided footer reads + sum-allreduce.
    (ref: load_balance.py:226-242)"""
    counts = [0] * len(file_paths)
    for i in range(comm.rank, len(file_paths), comm.world_size):
        counts[i] = get_num_samples_of_parquet(file_paths[i])
    counts = comm.allreduce_sum(counts)
    return [File(p, int(n)) for p, n in zip(file_paths, counts)]


def compute_targets(total, num_shards):
    """Per-shard target counts: base everywhere, +1 on the first
    ``total % num_shards`` shards."""
    base = total // num_shards
    num_plus_one = total - base * num_shards
    return [base + (1 if i < num_plus_one else 0) for i in range(num_shards)]


def _converge(shards, targets, comm):
    """Drive shards to exact targets via owner-striped transfers.

    One sweep suffices: surpluses and deficits sum to zero by construction,
    and the two-pointer walk pairs them off exactly, grouping every
    transfer out of one source shard into a single load. Deterministic SPMD
    control flow; the iteration bound is a safety net, not an expectation.
    Exposed separately so the plan can be property-tested metadata-only
    (no rank ever owning a transfer)."""
    group_idx = 0
    iterations = 0
    for _ in range(len(shards) + 2):
        large = [s for s in shards if s.num_samples > targets[s.idx]]
        small = [s for s in shards if s.num_samples < targets[s.idx]]
        if not large and not small:
            break
        iterations += 1
        large.sort(key=lambda s: s.num_samples - targets[s.idx], reverse=True)
        small.sort(key=lambda s: targets[s.idx] - s.num_samples, reverse=True)
        deficits = {s.idx: targets[s.idx] - s.num_samples for s in small}
        si = 0
        for ls in large:
            surplus = ls.num_samples - targets[ls.idx]
            assignments = []
            while surplus > 0 and si < len(small):
                ss = small[si]
                n = min(surplus, deficits[ss.idx])
                assignments.append((ss, n))
                surplus -= n
                deficits[ss.idx] -= n
                if deficits[ss.idx] == 0:
                    si += 1
            if assignments:
                ls.transfer_to_many(
                    assignments,
                    i_am_owner=(group_idx % comm.world_size == comm.rank))
                group_idx += 1
        comm.barrier()
    else:
        raise RuntimeError("balancer failed to converge")

    for s in shards:
        assert s.num_samples == targets[s.idx], (
            "shard {} has {} != target {}".format(
                s.idx, s.num_samples, targets[s.idx]))
    return iterations


def _balance_one_set(file_paths, out_dir, num_shards, comm, postfix="",
                     stats=None):
    """Balance one (possibly per-bin) file set into num_shards outputs."""
    files = _census(file_paths, comm)
    total = sum(f.num_samples for f in files)
    if total < num_shards:
        raise ValueError(
            "cannot balance {} samples into {} shards; every shard must "
            "receive at least one sample".format(total, num_shards))
    targets = compute_targets(total, num_shards)

    shards = [
        _Shard(i, files[i::num_shards], out_dir, postfix=postfix, stats=stats)
        for i in range(num_shards)
    ]
    _converge(shards, targets, comm)

    for s in shards:
        s.flush(i_am_owner=(s.idx % comm.world_size == comm.rank))
    comm.barrier()
    return {os.path.basename(s.out_path): int(s.final_file.num_samples)
            for s in shards}


def balance_shards(in_dir, out_dir, num_shards, comm=None, log=None,
                   stats=None):
    """Balance preprocessor output into ``num_shards`` equal shards (per bin
    when the input is binned). SPMD: call on every host with identical args.

    Returns {shard_basename: num_samples}; writes .num_samples.json.
    Pass ``stats={}`` to collect the plan's I/O cost in rows (see _Shard).
    """
    comm = comm or LocalCommunicator()
    log = log or (lambda msg: None)
    if num_shards < 1:
        raise ValueError("num_shards must be >= 1")
    # Top-level stage span (lint-enforced: tests/test_observability.py).
    with obs.span("balance.run", rank=comm.rank, num_shards=num_shards):
        return _balance_shards_body(in_dir, out_dir, num_shards, comm, log,
                                    stats)


def _balance_shards_body(in_dir, out_dir, num_shards, comm, log, stats):
    if os.path.isdir(out_dir):
        stale = [n for n in sorted(os.listdir(out_dir)) if ".parquet" in n]
        if stale:
            raise ValueError(
                "output dir {} already contains {} shard files (e.g. {}); "
                "remove them or choose a fresh directory".format(
                    out_dir, len(stale), stale[0]))
    os.makedirs(out_dir, exist_ok=True)
    file_paths = get_all_parquets_under(in_dir)
    if not file_paths:
        raise ValueError("no parquet shards under {}".format(in_dir))
    bin_ids = get_all_bin_ids(file_paths)
    counts = {}
    if bin_ids:
        from ..utils.fs import get_bin_id_of_path
        unbinned = [p for p in file_paths if get_bin_id_of_path(p) is None]
        if unbinned:
            raise ValueError(
                "input mixes binned and unbinned shards ({} unbinned, e.g. "
                "{}); balance them separately".format(
                    len(unbinned), os.path.basename(unbinned[0])))
        for b in bin_ids:
            bin_paths = get_file_paths_for_bin_id(file_paths, b)
            with obs.span("balance.bin", bin=b, files=len(bin_paths)):
                counts.update(
                    _balance_one_set(bin_paths, out_dir, num_shards, comm,
                                     postfix="_{}".format(b), stats=stats))
            log("balanced bin {}: {} files -> {} shards".format(
                b, len(bin_paths), num_shards))
    else:
        counts.update(_balance_one_set(file_paths, out_dir, num_shards, comm,
                                       stats=stats))
        log("balanced {} files -> {} shards".format(
            len(file_paths), num_shards))
    if stats is not None:
        log("balance I/O (rows): {}".format(
            {k: stats[k] for k in sorted(stats)}))
    if comm.rank == 0:
        write_num_samples_cache(out_dir, counts)
    comm.barrier()
    # Integrity manifest next to .num_samples.json: per-shard byte length
    # + CRC32, verified by the loader at startup (rank-strided checksums).
    build_manifest(out_dir, comm=comm, log=log)
    return counts


def generate_num_samples_cache(path, comm=None):
    """(Re)build .num_samples.json for a directory of parquet shards.
    (ref: load_balance.py:428-455)"""
    comm = comm or LocalCommunicator()
    file_paths = get_all_parquets_under(path)
    if not file_paths:
        raise ValueError("no parquet shards under {}".format(path))
    files = _census(file_paths, comm)
    counts = {os.path.basename(f.path): int(f.num_samples) for f in files}
    if comm.rank == 0:
        write_num_samples_cache(path, counts)
    comm.barrier()
    return counts
