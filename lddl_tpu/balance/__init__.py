from .balancer import balance_shards, generate_num_samples_cache

__all__ = ["balance_shards", "generate_num_samples_cache"]
