"""Delta balancer: extend a balanced shard directory without rewriting it.

The classic balancer (:mod:`.balancer`) rewrites every shard of the
output directory — correct for an offline batch run, catastrophic for a
streaming service where a small delta would pay a full-corpus rewrite.
This module rebalances **only the tail**: the new generation's rows plus,
at most, the minimum set of prior-tail shards needed to keep the
directory-wide ±1 sample-count invariant.

Key idea — the **row budget**: generation 0 fixes each bin's per-shard
count at ``m`` (every prior shard holds ``m`` or ``m+1`` rows, the ±1
invariant the loader depends on). A delta of ``T1`` rows is cut into
``G = T1 // m`` new shards of ``m`` rows (the first ``min(T1 mod m, G)``
of them take one extra row), and the remainder — always fewer than ``m``
rows — becomes **carryover**: rows already journaled as ingested, parked
in ``.ingest/carry/`` and prepended to the NEXT generation's input. In
this steady state no prior shard is ever touched: untouched shards stay
byte-identical across arbitrarily many incremental rounds, which is what
makes mid-service generation pickup safe (a loader may be streaming them
while the new generation publishes).

``flush=True`` trades that for zero carry latency: the remainder is
folded into the prior tail by whichever of two moves touches fewer
shards — *absorb-up* (append one row to ``r`` prior shards currently at
``m``) or *pull-down* (build one more full shard from the remainder plus
the last row of ``m - r`` prior shards currently at ``m+1``). Both
preserve the ±1 invariant exactly; both rewrite the touched shards
in place (atomic replace), so flushing is for maintenance windows, not
for directories being streamed mid-epoch.

Crash safety is two-phase: everything is staged under the generation's
work dir first, a ``plan.json`` marker is published atomically once the
staging is complete, and only then does the publish phase copy staged
bytes into the dataset (idempotent — a crashed publish re-runs from the
staged bytes, never from recomputation). Nothing in the dataset root
mutates before the marker exists.
"""

import json
import logging
import os

import pyarrow as pa

from .. import observability as obs
from ..preprocess.binning import (DEFAULT_PARQUET_COMPRESSION,
                                  write_options_for_names)
from ..resilience import io as rio
from ..utils.fs import (
    GENERATION_DIR_RE,
    generation_dir_name,
    get_bin_id_of_path,
    get_num_samples_of_parquet,
)

PLAN_NAME = "plan.json"

_log = logging.getLogger("lddl_tpu.balance.delta")

# Bin key used in plans/carry maps for unbinned data (bin ids are ints).
UNBINNED_KEY = "unbinned"


def bin_key_of(bin_id):
    return UNBINNED_KEY if bin_id is None else str(bin_id)


def shard_suffix(bin_id):
    return ".parquet" if bin_id is None else ".parquet_{}".format(bin_id)


def carry_basename(generation, bin_id):
    # The bin id rides the standard .parquet_<b> extension so carry files
    # re-enter the next round's bin grouping like any other input.
    return "gen-{:04d}.carry{}".format(generation, shard_suffix(bin_id))


def plan_bin_delta(prior_counts, new_total):
    """The pure per-bin arithmetic: given the prior shard counts (all
    ``m`` or ``m+1`` — the invariant) and ``new_total`` delta rows,
    return ``(m, G, plus_new, carry)``: ``G`` new shards, the first
    ``plus_new`` of them at ``m+1`` rows, ``carry`` rows (< m) deferred.

    Zero prior shards are touched by construction: new shards only ever
    take counts already in {m, m+1}, so the directory-wide spread stays
    ≤ 1 without moving a single prior row."""
    if not prior_counts:
        raise ValueError("plan_bin_delta needs at least one prior shard")
    m, hi = min(prior_counts), max(prior_counts)
    if hi - m > 1:
        raise ValueError(
            "prior shards are not balanced (counts range {}..{}); run the "
            "full balancer before ingesting incrementally".format(m, hi))
    G = new_total // m
    r = new_total - G * m
    plus_new = min(r, G)
    return m, G, plus_new, r - plus_new


def plan_flush(prior_counts, m, carry):
    """How to fold ``carry`` (< m) leftover rows into the prior tail while
    keeping every count in {m, m+1}. Returns ``("absorb", k)`` — append
    one row to each of the last ``k = carry`` prior shards currently at
    ``m`` — or ``("pull", k)`` — build one more full shard from the carry
    plus the last row of each of the last ``k = m - carry`` prior shards
    currently at ``m+1`` — whichever touches fewer shards. Raises when
    neither move is feasible (degenerate tiny directories)."""
    at_m = sum(1 for c in prior_counts if c == m)
    at_m1 = len(prior_counts) - at_m
    absorb_ok = carry <= at_m
    pull_ok = (m - carry) <= at_m1
    if not absorb_ok and not pull_ok:
        raise ValueError(
            "cannot flush {} leftover row(s): only {} shard(s) at {} and "
            "{} at {}; ingest more data or re-run the full balancer".format(
                carry, at_m, m, at_m1, m + 1))
    if absorb_ok and (not pull_ok or carry <= m - carry):
        return "absorb", carry
    return "pull", m - carry


def _read_concat(paths):
    tables = [rio.read_table(p) for p in paths]
    return tables[0] if len(tables) == 1 else pa.concat_tables(tables)


def _stage_table(table, path):
    os.makedirs(os.path.dirname(path), exist_ok=True)
    rio.write_table_atomic(table, path,
                           compression=DEFAULT_PARQUET_COMPRESSION,
                           **write_options_for_names(table.schema.names))


def _bin_inputs(part_paths, carry_in_paths):
    """Group delta inputs by bin id: carryover first (oldest rows flush
    into shards first — FIFO), then the preprocess part files in sorted
    order. Pure name-based grouping, deterministic."""
    by_bin = {}
    for path in sorted(carry_in_paths):
        b = get_bin_id_of_path(path)
        by_bin.setdefault(b, []).append(path)
    for path in sorted(part_paths):
        b = get_bin_id_of_path(path)
        by_bin.setdefault(b, []).append(path)
    return by_bin


def _generation_of_relpath(rel):
    m = GENERATION_DIR_RE.match(rel.split(os.sep, 1)[0])
    return int(m.group(1)) if m else 0


def _pack_shape_of_first(paths):
    """Packed row shape of the first (sorted) shard, or None — one footer
    read is enough: within one producer run the shape is schema-level
    constant, and cross-run drift is what the caller refuses on."""
    from ..preprocess.packing import pack_shape_of_parquet
    for p in sorted(paths):
        return pack_shape_of_parquet(p)
    return None


def _check_packed_shape(root, inputs, prior_bins):
    """The delta balancer learns the packed row shape: delta part files
    must be packed against the SAME (budget, max_per_row) the prior
    generations fixed — mixing packed and unpacked rows (or two budgets)
    in one directory would give the loader rows of two incompatible
    shapes. The ingest fingerprint already freezes this for the service;
    this guard catches manual misuse of the balancer API."""
    in_paths = [p for paths in inputs.values() for p in paths]
    prior_rels = [os.path.join(root, rel)
                  for bins in prior_bins.values() for rel, _ in bins]
    if not in_paths or not prior_rels:
        return
    delta_shape = _pack_shape_of_first(in_paths)
    prior_shape = _pack_shape_of_first(prior_rels)
    if delta_shape != prior_shape:
        raise ValueError(
            "delta and prior shards disagree on the packed row shape "
            "(delta {}, prior {}); the ingest configuration drifted — "
            "packed corpora must append deltas packed against the same "
            "pack_seq_length/pack_max_per_row".format(
                delta_shape or "unpacked", prior_shape or "unpacked"))


def _prior_by_bin(prior):
    """{bin_id: [(relpath, count)]} from the prior snapshot, each bin's
    shards ordered by (generation, relpath) — so the deterministic
    'tail' the flush moves index from the end of is the NEWEST
    generation's shards, and generation 0's bulk is the last thing a
    flush would ever touch."""
    by_bin = {}
    for rel in sorted(prior, key=lambda r: (_generation_of_relpath(r), r)):
        by_bin.setdefault(get_bin_id_of_path(rel), []).append(
            (rel, int(prior[rel])))
    return by_bin


def stage_delta_balance(root, generation, part_paths, stage_dir, *,
                        prior, carry_in_paths=(), num_shards=8,
                        flush=False, log=None):
    """Phase 1: compute the delta plan and stage every output file under
    ``stage_dir``; publish the ``plan.json`` marker last. Nothing in the
    dataset root is touched. Returns the plan dict.

    - ``prior``: {relpath: count} snapshot of the existing shards (empty
      for generation 0, which becomes a classic full balance of the delta
      into the root).
    - ``carry_in_paths``: the previous generation's carryover shards,
      consumed ahead of the new part files.
    - ``num_shards``: shard count for generation 0 and for bins the prior
      generations have never seen.
    """
    log = log or (lambda msg: None)
    inputs = _bin_inputs(part_paths, carry_in_paths)
    prior_bins = _prior_by_bin(prior)
    _check_packed_shape(root, inputs, prior_bins)
    if inputs and prior_bins:
        in_binned = set(inputs) != {None}
        prior_binned = set(prior_bins) != {None}
        if in_binned != prior_binned:
            raise ValueError(
                "delta and prior shards disagree on binning (delta bins "
                "{}, prior bins {}); the ingest configuration drifted".format(
                    sorted(map(bin_key_of, inputs)),
                    sorted(map(bin_key_of, prior_bins))))
    plan = {"generation": generation, "bins": {}, "flush": bool(flush),
            "target": "" if generation == 0
                      else generation_dir_name(generation)}
    visible_bins = {b for b in prior_bins if b is not None}

    for b in sorted(inputs, key=lambda x: (-1 if x is None else x)):
        paths = inputs[b]
        counts = [get_num_samples_of_parquet(p) for p in paths]
        total = sum(counts)
        if total == 0:
            continue
        key = bin_key_of(b)
        bin_plan = {"new": {}, "touched": {}, "carry": {}, "consumed": 0,
                    "inputs": total}
        plan["bins"][key] = bin_plan
        prior_bin = prior_bins.get(b, [])

        if not prior_bin:
            defer = None
            if prior_bins and b is not None and visible_bins and not (
                    min(visible_bins) - 1 <= b <= max(visible_bins) + 1):
                # The loader requires a gap-free bin range; a delta-only
                # bin far from the existing range would poison the whole
                # directory, so its rows wait in carryover until the
                # range grows to meet it. (Generation 0 accepts whatever
                # bins the corpus produces — classic-pipeline parity.)
                defer = ("bin {} would leave a gap next to the existing "
                         "bins {}..{}".format(b, min(visible_bins),
                                              max(visible_bins)))
            elif prior_bins and total < num_shards:
                defer = ("new bin {} has {} row(s), fewer than {} "
                         "shards".format(key, total, num_shards))
            if defer is not None:
                log("delta balance: deferring {} row(s) to carryover "
                    "({})".format(total, defer))
                table = _read_concat(paths)
                name = carry_basename(generation, b)
                _stage_table(table, os.path.join(stage_dir, "carry", name))
                bin_plan["carry"][name] = total
                continue
            # Generation 0 (or a brand-new contiguous bin): classic full
            # balance of the delta itself — this FIXES the bin's row
            # budget m for every later generation.
            if total < num_shards:
                raise ValueError(
                    "cannot balance {} samples into {} shards; every "
                    "shard must receive at least one sample".format(
                        total, num_shards))
            from .balancer import compute_targets
            sizes = compute_targets(total, num_shards)
            table = _read_concat(paths)
            offset = 0
            for i, n in enumerate(sizes):
                name = "shard-{}{}".format(i, shard_suffix(b))
                _stage_table(table.slice(offset, n),
                             os.path.join(stage_dir, "new", name))
                bin_plan["new"][name] = n
                offset += n
            bin_plan["consumed"] = total
            if b is not None:
                visible_bins.add(b)
            continue

        prior_counts = [c for _, c in prior_bin]
        m, G, plus_new, carry = plan_bin_delta(prior_counts, total)
        if carry and flush:
            try:
                plan_flush(prior_counts, m, carry)
            except ValueError:
                # Neither a ±1 absorb nor a ±1 pull can place the
                # remainder (few shards, large leftover): the "minimum
                # set of prior shards to touch" degenerates to the whole
                # bin, so rebalance it outright — still staged and
                # published like every other delta, just with every
                # prior shard of this bin in the touched set.
                _stage_full_bin_rebalance(root, stage_dir, b, prior_bin,
                                          paths, total, G, bin_plan, log)
                continue
        sizes = [m + 1] * plus_new + [m] * (G - plus_new)
        table = _read_concat(paths)
        offset = 0
        for i, n in enumerate(sizes):
            name = "shard-{}{}".format(i, shard_suffix(b))
            _stage_table(table.slice(offset, n),
                         os.path.join(stage_dir, "new", name))
            bin_plan["new"][name] = n
            offset += n
        bin_plan["consumed"] = offset
        remainder = table.slice(offset)

        if carry and flush:
            move, k = plan_flush(prior_counts, m, carry)
            if move == "absorb":
                # Append one remainder row to each of the last k prior
                # shards currently at m (tail-first, deterministic).
                targets = [rc for rc in prior_bin if rc[1] == m][-k:]
                for j, (rel, c) in enumerate(targets):
                    prior_table = rio.read_table(os.path.join(root, rel))
                    merged = pa.concat_tables(
                        [prior_table, remainder.slice(j, 1)])
                    _stage_table(merged, _touched_stage_path(stage_dir, rel))
                    bin_plan["touched"][rel] = c + 1
            else:
                # One more full shard: remainder + the last row of each of
                # the last k prior shards currently at m+1.
                donors = [rc for rc in prior_bin if rc[1] == m + 1][-k:]
                donated = []
                for rel, c in donors:
                    prior_table = rio.read_table(os.path.join(root, rel))
                    donated.append(prior_table.slice(c - 1, 1))
                    _stage_table(prior_table.slice(0, c - 1),
                                 _touched_stage_path(stage_dir, rel))
                    bin_plan["touched"][rel] = c - 1
                extra = pa.concat_tables([remainder] + donated)
                name = "shard-{}{}".format(G, shard_suffix(b))
                _stage_table(extra, os.path.join(stage_dir, "new", name))
                bin_plan["new"][name] = extra.num_rows
            bin_plan["consumed"] = total
        elif carry:
            name = carry_basename(generation, b)
            _stage_table(remainder, os.path.join(stage_dir, "carry", name))
            bin_plan["carry"][name] = carry

    rio.atomic_write(os.path.join(stage_dir, PLAN_NAME),
                     json.dumps(plan, sort_keys=True))
    return plan


def _stage_full_bin_rebalance(root, stage_dir, bin_id, prior_bin, paths,
                              delta_total, G, bin_plan, log):
    """Flush fallback: re-slice one whole bin (prior shards in tail order,
    then the delta stream) into ``len(prior) + G`` exactly-balanced
    shards. Every prior shard of the bin is rewritten in place; the ``G``
    new shards still land in the generation directory."""
    from .balancer import compute_targets
    prior_tables = [rio.read_table(os.path.join(root, rel))
                    for rel, _ in prior_bin]
    table = pa.concat_tables(prior_tables + [_read_concat(paths)])
    total = table.num_rows
    s_new = len(prior_bin) + G
    targets = compute_targets(total, s_new)
    log("delta balance: flush rebalances whole bin {} ({} prior "
        "shard(s) rewritten)".format(bin_key_of(bin_id), len(prior_bin)))
    offset = 0
    for (rel, _), n in zip(prior_bin, targets[:len(prior_bin)]):
        _stage_table(table.slice(offset, n),
                     _touched_stage_path(stage_dir, rel))
        bin_plan["touched"][rel] = n
        offset += n
    for i, n in enumerate(targets[len(prior_bin):]):
        name = "shard-{}{}".format(i, shard_suffix(bin_id))
        _stage_table(table.slice(offset, n),
                     os.path.join(stage_dir, "new", name))
        bin_plan["new"][name] = n
        offset += n
    bin_plan["consumed"] = delta_total


def _touched_stage_path(stage_dir, relpath):
    return os.path.join(stage_dir, "touched", relpath.replace(os.sep, "__"))


def read_plan(stage_dir):
    """The staged plan, or None when staging never completed (the marker
    is published only after every staged file exists)."""
    rec, status = rio.read_json(os.path.join(stage_dir, PLAN_NAME))
    if status != "ok" or not isinstance(rec, dict):
        return None
    return rec


def publish_delta_balance(root, stage_dir, plan, *, carry_dir, log=None):
    """Phase 2: copy staged bytes into the dataset. Idempotent — staged
    files survive until the caller's final cleanup, so a crashed publish
    simply re-runs (byte-identically: the plan is frozen). New-generation
    shards land under the plan's target dir (stale non-plan names are
    removed first, file by file — never an rmtree, so a reader mid-epoch
    never sees a published path vanish), touched prior shards are
    atomically replaced in the root, carryover lands under ``carry_dir``.
    All copies are zero-memory ``atomic_copy`` (hard-link + rename).
    Returns {"new": {relpath: count}, "touched": {...},
    "carry": {bin_key: basename}}."""
    log = log or (lambda msg: None)
    target = os.path.join(root, plan["target"]) if plan["target"] else root
    if plan["target"] and os.path.isdir(target):
        # Remove stale NAMES a crashed attempt may have left, but never
        # rmtree the directory: a plan resumed from its intake record is
        # deterministic, so re-published files are byte-identical and
        # land via atomic replace — a follow-mode loader that (behind a
        # prematurely advanced gate, e.g. a crash between the gate write
        # and the journal commit) is already streaming these shards
        # never sees a vanished path. Bookkeeping dotfiles stay; they
        # are refreshed after publish.
        expected = {name for key in plan["bins"]
                    for name in plan["bins"][key]["new"]}
        for name in sorted(os.listdir(target)):
            if name in expected or name.startswith("."):
                continue
            try:
                os.remove(os.path.join(target, name))
            except FileNotFoundError:
                pass
    published = {"new": {}, "touched": {}, "carry": {}}
    bytes_new = bytes_rewritten = 0
    for key in sorted(plan["bins"]):
        bin_plan = plan["bins"][key]
        for name in sorted(bin_plan["new"]):
            staged = os.path.join(stage_dir, "new", name)
            os.makedirs(target, exist_ok=True)
            rio.atomic_copy(staged, os.path.join(target, name))
            rel = os.path.join(plan["target"], name) if plan["target"] \
                else name
            published["new"][rel] = bin_plan["new"][name]
            bytes_new += os.path.getsize(staged)
        for rel in sorted(bin_plan["touched"]):
            staged = _touched_stage_path(stage_dir, rel)
            rio.atomic_copy(staged, os.path.join(root, rel))
            published["touched"][rel] = bin_plan["touched"][rel]
            bytes_rewritten += os.path.getsize(staged)
        for name in sorted(bin_plan["carry"]):
            staged = os.path.join(stage_dir, "carry", name)
            os.makedirs(carry_dir, exist_ok=True)
            rio.atomic_copy(staged, os.path.join(carry_dir, name))
            published["carry"][key] = name
    if obs.enabled():
        obs.inc("ingest_shard_bytes_appended_total", bytes_new)
        if bytes_rewritten:
            obs.inc("ingest_shard_bytes_rewritten_total", bytes_rewritten)
    log("delta balance: published {} new shard(s), {} touched prior "
        "shard(s), {} carry file(s)".format(
            len(published["new"]), len(published["touched"]),
            len(published["carry"])))
    return published
