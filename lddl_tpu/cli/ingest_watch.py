"""CLI: streaming ingestion service — watch a landing directory and
incrementally preprocess + delta-balance new documents into a growing,
generation-structured shard directory (see lddl_tpu/ingest/).

One-shot mode (``--once``) diffs and ingests a single generation — the
building block for cron-style scheduling; the default is a polling watch
loop. Safe to kill at any point and re-run: an in-flight generation
resumes from its intake record, and the journal commit is atomic.
"""

from ..preprocess import BertPretrainConfig, get_tokenizer
from ..utils.args import attach_bool_arg
from .common import (apply_storage_backend, arm_fleet_if_requested,
                     attach_elastic_args, attach_fleet_arg,
                     attach_storage_arg, elastic_kwargs_of, make_parser)


def attach_args(parser=None):
    parser = parser or make_parser(__doc__)
    parser.add_argument("--landing", required=True,
                        help="landing directory of downloader-contract "
                             ".txt files (or a dir containing source/); "
                             "scanned every round and diffed against the "
                             "journal by document content hash")
    parser.add_argument("--sink", "--outdir", dest="sink", required=True,
                        help="dataset root: generation 0 lands here as "
                             "classic balanced shards, later generations "
                             "under gen-<NNNN>/; service state lives in "
                             "<sink>/.ingest/")
    parser.add_argument("--vocab-file", default=None)
    parser.add_argument("--tokenizer", default=None,
                        help="HF hub tokenizer name (alternative to "
                             "--vocab-file)")
    parser.add_argument("--num-shards", type=int, default=8,
                        help="generation-0 shard count per bin — this "
                             "fixes the per-shard row budget every later "
                             "generation appends at")
    parser.add_argument("--target-seq-length", type=int, default=128)
    parser.add_argument("--short-seq-prob", type=float, default=0.1)
    attach_bool_arg(parser, "masking", default=False,
                    help_str="static masking (default: dynamic at load "
                             "time)")
    parser.add_argument("--masked-lm-ratio", type=float, default=0.15)
    parser.add_argument("--duplicate-factor", type=int, default=5)
    parser.add_argument("--seed", type=int, default=12345)
    parser.add_argument("--bin-size", type=int, default=None)
    parser.add_argument("--pack-seq-length", type=int, default=None,
                        help="grow an OFFLINE-PACKED corpus: every "
                             "delta's instances are FFD-packed into "
                             "fixed-budget schema-v2 rows (exclusive "
                             "with --bin-size; the shape rides the "
                             "journal fingerprint, so drift refuses)")
    parser.add_argument("--pack-max-per-row", type=int, default=8,
                        help="samples-per-row cap of the offline packer")
    parser.add_argument("--num-blocks", type=int, default=None,
                        help="blocks per delta preprocess (default: "
                             "scaled to the delta's document count)")
    parser.add_argument("--local-workers", type=int, default=1,
                        help="process-pool size for the delta preprocess")
    parser.add_argument("--schema-version", type=int, choices=(1, 2),
                        default=2)
    parser.add_argument("--tokenizer-engine",
                        choices=("auto", "hf", "native"), default="auto")
    attach_bool_arg(parser, "once", default=False,
                    help_str="diff-and-ingest a single round, then exit "
                             "(default: poll forever)")
    parser.add_argument("--interval", type=float, default=30.0,
                        metavar="SECONDS",
                        help="watch-loop poll interval")
    parser.add_argument("--max-rounds", type=int, default=0,
                        help="stop the watch loop after this many rounds "
                             "(0 = forever)")
    attach_bool_arg(parser, "flush-tail", default=False,
                    help_str="fold the carryover remainder into the "
                             "prior tail instead of deferring it; "
                             "touches prior shards, so only for "
                             "maintenance windows — not while a loader "
                             "streams the directory mid-epoch")
    attach_bool_arg(parser, "autoscale", default=False,
                    help_str="telemetry-driven autoscaling: a control "
                             "thread reads the fleet aggregate every "
                             "half interval and spawns/retires local "
                             "helper processes (--join-pending mode) to "
                             "hold --backlog-slo-docs; requires "
                             "--elastic and --fleet-telemetry")
    parser.add_argument("--backlog-slo-docs", type=int, default=512,
                        help="autoscale SLO: spawn a helper while the "
                             "fleet's ingest backlog gauge is at/above "
                             "this many documents (or the service is "
                             "wedged)")
    parser.add_argument("--max-helpers", type=int, default=2,
                        help="autoscale ceiling on concurrently running "
                             "helper processes")
    parser.add_argument("--drain-rounds", type=int, default=2,
                        help="consecutive calm control rounds (no "
                             "backlog, no pending work) before one "
                             "helper is retired")
    attach_bool_arg(parser, "join-pending", default=False,
                    help_str="helper mode (what --autoscale spawns): "
                             "join the in-flight generation's elastic "
                             "preprocess from its frozen intake record, "
                             "then poll for the next one; never scans "
                             "the landing dir or commits the journal")
    attach_elastic_args(parser)
    attach_fleet_arg(parser)
    attach_storage_arg(parser)
    return parser


def _helper_argv(args):
    """The command line --autoscale spawns: this same CLI in
    --join-pending mode, carrying every processor-config flag (the
    helper recomputes the intake fingerprint and refuses on drift) but
    none of the landing-scan knobs (frozen in the intake record)."""
    import sys
    argv = [sys.executable, "-m", "lddl_tpu.cli.ingest_watch",
            "--landing", args.landing, "--sink", args.sink,
            "--join-pending", "--elastic",
            "--local-workers", str(args.local_workers),
            "--lease-ttl", str(args.lease_ttl),
            "--interval", str(args.interval),
            "--num-shards", str(args.num_shards),
            "--target-seq-length", str(args.target_seq_length),
            "--short-seq-prob", str(args.short_seq_prob),
            "--masked-lm-ratio", str(args.masked_lm_ratio),
            "--duplicate-factor", str(args.duplicate_factor),
            "--seed", str(args.seed),
            "--schema-version", str(args.schema_version),
            "--tokenizer-engine", args.tokenizer_engine]
    if args.vocab_file:
        argv += ["--vocab-file", args.vocab_file]
    if args.tokenizer:
        argv += ["--tokenizer", args.tokenizer]
    if args.masking:
        argv += ["--masking"]
    if args.scatter_units is not None:
        argv += ["--scatter-units", str(args.scatter_units)]
    if args.fleet_telemetry:
        argv += ["--fleet-telemetry"]
    return argv


def main(args=None):
    args = args if args is not None else attach_args().parse_args()
    if args.vocab_file is None and args.tokenizer is None:
        raise SystemExit("need --vocab-file or --tokenizer")
    # Pin the storage backend into the env first (workers and helper
    # subprocesses inherit it), then arm fleet BEFORE snapshotting the
    # elastic kwargs (see the bert CLI).
    apply_storage_backend(args)
    arm_fleet_if_requested(args, args.sink)
    elastic_kwargs = elastic_kwargs_of(args)
    tokenizer = get_tokenizer(vocab_file=args.vocab_file,
                              pretrained_model_name=args.tokenizer)
    config = BertPretrainConfig(
        max_seq_length=args.target_seq_length,
        short_seq_prob=args.short_seq_prob,
        masking=args.masking,
        masked_lm_ratio=args.masked_lm_ratio,
        duplicate_factor=args.duplicate_factor,
        tokenizer_engine=args.tokenizer_engine,
        schema_version=args.schema_version,
    )
    from ..ingest import ingest_once, join_pending_generation, watch
    if args.join_pending:
        # Helper mode: poll the journal for an in-flight generation and
        # join its elastic claim loop. Retirement is a plain SIGTERM from
        # the autoscaler — converted to a normal exit so the atexit hook
        # closes the telemetry spool (pipeline_status then reads a clean
        # shutdown, not a stalled host). A helper that dies mid-unit
        # anyway just stops renewing its leases and the survivors steal.
        import signal
        import time

        def _retired(signum, frame):
            raise SystemExit(0)

        signal.signal(signal.SIGTERM, _retired)
        while True:
            report = join_pending_generation(
                args.sink, tokenizer, config=config,
                num_workers=args.local_workers,
                lease_ttl=args.lease_ttl,
                holder_id=args.elastic_host_id,
                scatter_units=args.scatter_units,
                log=print)
            print("ingest helper: {}".format(report))
            if args.once:
                return
            time.sleep(max(1.0, args.interval / 3.0))
    kwargs = dict(
        config=config,
        num_shards=args.num_shards,
        bin_size=args.bin_size,
        seed=args.seed,
        num_blocks=args.num_blocks,
        num_workers=args.local_workers,
        flush_tail=args.flush_tail,
        pack_seq_length=args.pack_seq_length,
        pack_max_per_row=args.pack_max_per_row,
        **elastic_kwargs,
    )
    if args.autoscale:
        if args.once:
            raise SystemExit("--autoscale requires the watch loop (it "
                             "decides across rounds); drop --once")
        if not args.elastic:
            raise SystemExit("--autoscale needs --elastic: helpers join "
                             "the preprocess through the lease claim loop")
        if not args.fleet_telemetry:
            raise SystemExit("--autoscale needs --fleet-telemetry: scale "
                             "decisions read the fleet aggregate")
        import subprocess
        import threading
        from ..observability.autoscale import Autoscaler

        def spawn():
            return subprocess.Popen(_helper_argv(args))

        def retire(proc):
            proc.terminate()
            try:
                proc.wait(timeout=30)
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait(timeout=10)

        scaler = Autoscaler(args.sink, spawn, retire,
                            backlog_slo_docs=args.backlog_slo_docs,
                            max_helpers=args.max_helpers,
                            drain_rounds=args.drain_rounds,
                            stall_ttl=args.lease_ttl, log=print)
        stop = threading.Event()

        def control_loop():
            # Half the watch interval so a backlog spike seen at scan
            # time scales up while the round's preprocess is still
            # running — when a helper is actually useful.
            while not stop.wait(max(1.0, args.interval / 2.0)):
                try:
                    scaler.step()
                except Exception as e:  # noqa: BLE001 - keep controlling
                    print("autoscale: control round failed ({}: {})".format(
                        type(e).__name__, e))

        thread = threading.Thread(target=control_loop, name="autoscale",
                                  daemon=True)
        thread.start()
        try:
            watch(args.sink, tokenizer, args.landing,
                  interval_s=args.interval, max_rounds=args.max_rounds,
                  log=print, **kwargs)
        finally:
            stop.set()
            thread.join(timeout=5.0)
            scaler.shutdown()
        return
    if args.once:
        report = ingest_once(args.sink, tokenizer, landing=args.landing,
                             log=print, **kwargs)
        print("ingest report: {}".format(report))
        return
    watch(args.sink, tokenizer, args.landing, interval_s=args.interval,
          max_rounds=args.max_rounds, log=print, **kwargs)


if __name__ == "__main__":
    main()
