"""CLI: BERT pretraining preprocessor.

Reference parity: the ``preprocess_bert_pretrain`` console script
(lddl/dask/bert/pretrain.py:618-883), with dask/mpi flags replaced by
the static-scheduled runner's (--num-blocks, --multihost) and the new
--engine flag selecting the masking kernel backend.
"""

from ..preprocess import BertPretrainConfig, get_tokenizer, run_bert_preprocess
from ..utils.args import attach_bool_arg
from .common import (apply_storage_backend, arm_fleet_if_requested,
                     attach_corpus_args, attach_elastic_args,
                     attach_fleet_arg, attach_multihost_arg,
                     attach_storage_arg, communicator_of,
                     corpus_paths_of, elastic_kwargs_of, make_parser)


def attach_args(parser=None):
    parser = parser or make_parser(__doc__)
    attach_corpus_args(parser)
    attach_multihost_arg(parser)
    attach_elastic_args(parser)
    attach_fleet_arg(parser)
    attach_storage_arg(parser)
    parser.add_argument("--sink", "--outdir", dest="sink", required=True,
                        help="output directory for the parquet shards")
    parser.add_argument("--vocab-file", default=None)
    parser.add_argument("--tokenizer", default=None,
                        help="HF hub tokenizer name (alternative to "
                             "--vocab-file)")
    parser.add_argument("--target-seq-length", type=int, default=128)
    parser.add_argument("--short-seq-prob", type=float, default=0.1)
    attach_bool_arg(parser, "masking", default=False,
                    help_str="static masking (default: dynamic at load time)")
    parser.add_argument("--masked-lm-ratio", type=float, default=0.15)
    parser.add_argument("--max-predictions-per-seq", type=int, default=None)
    attach_bool_arg(parser, "whole-word-masking", default=False)
    parser.add_argument("--duplicate-factor", type=int, default=5)
    parser.add_argument("--sample-ratio", type=float, default=0.9)
    parser.add_argument("--seed", type=int, default=12345)
    parser.add_argument("--bin-size", type=int, default=None)
    parser.add_argument("--pack-seq-length", type=int, default=None,
                        help="OFFLINE sequence packing: FFD-pack each "
                             "bucket's instances into fixed-budget "
                             "schema-v2 rows the loader streams zero-"
                             "copy (exclusive with --bin-size; requires "
                             "--schema-version 2)")
    parser.add_argument("--pack-max-per-row", type=int, default=8,
                        help="samples-per-row cap of the offline packer "
                             "(the loader's cls_positions width)")
    parser.add_argument("--num-blocks", type=int, default=64)
    parser.add_argument("--spool-groups", type=int, default=None,
                        help="coarse radix width of the shuffle spool "
                             "(default min(blocks, max(64, blocks/8)); "
                             "spool files = groups x writers)")
    parser.add_argument("--local-workers", type=int, default=0,
                        help="process-pool size per host for bucket "
                             "processing (0 = one per CPU core; the "
                             "reference runs ~128 MPI ranks per node)")
    parser.add_argument("--engine", choices=("numpy", "jax"), default="numpy",
                        help="masking kernel backend (jax = jit on TPU)")
    parser.add_argument("--tokenizer-engine",
                        choices=("auto", "hf", "native"), default="auto",
                        help="sentence-split + tokenize backend (native = "
                             "the C++ one-pass kernel)")
    parser.add_argument("--splitter", choices=("rules", "learned"),
                        default="rules",
                        help="sentence splitter: rules = self-contained "
                             "static rules; learned = corpus-trained punkt "
                             "parameters (F1 0.99 vs punkt, needs nltk at "
                             "train time only)")
    parser.add_argument("--output-format", choices=("parquet", "txt"),
                        default="parquet")
    parser.add_argument("--schema-version", type=int, choices=(1, 2),
                        default=2,
                        help="parquet shard schema: 2 (default) adds the "
                             "token-id list columns the loader decodes "
                             "zero-copy; 1 = original text-only shards")
    attach_bool_arg(parser, "resume", default=False,
                    help_str="continue a crashed/failed run from its unit "
                             "ledger (skips completed spool groups)")
    attach_bool_arg(parser, "global-shuffle", default=True,
                    help_str="two-pass global document shuffle")
    return parser


def main(args=None):
    args = args if args is not None else attach_args().parse_args()
    if args.vocab_file is None and args.tokenizer is None:
        raise SystemExit("need --vocab-file or --tokenizer")
    # Pin the storage backend into the env first (workers inherit it),
    # then arm fleet BEFORE snapshotting the elastic kwargs: on an
    # elastic run with no --elastic-host-id this pins the auto-generated
    # lease holder into args so spool and lease files share a name.
    apply_storage_backend(args)
    arm_fleet_if_requested(args, args.sink)
    elastic_kwargs = elastic_kwargs_of(args)
    comm = communicator_of(args)
    tokenizer = get_tokenizer(vocab_file=args.vocab_file,
                              pretrained_model_name=args.tokenizer)
    config = BertPretrainConfig(
        max_seq_length=args.target_seq_length,
        short_seq_prob=args.short_seq_prob,
        masking=args.masking,
        masked_lm_ratio=args.masked_lm_ratio,
        max_predictions_per_seq=args.max_predictions_per_seq,
        whole_word_masking=args.whole_word_masking,
        duplicate_factor=args.duplicate_factor,
        engine=args.engine,
        tokenizer_engine=args.tokenizer_engine,
        splitter=args.splitter,
        schema_version=args.schema_version,
    )
    from ..utils.cpus import usable_cpu_count
    run_bert_preprocess(
        corpus_paths_of(args),
        args.sink,
        tokenizer,
        config=config,
        num_workers=args.local_workers or usable_cpu_count(),
        num_blocks=args.num_blocks,
        sample_ratio=args.sample_ratio,
        seed=args.seed,
        bin_size=args.bin_size,
        pack_seq_length=args.pack_seq_length,
        pack_max_per_row=args.pack_max_per_row,
        global_shuffle=args.global_shuffle,
        output_format=args.output_format,
        comm=comm,
        log=print,
        spool_groups=args.spool_groups,
        resume=args.resume,
        **elastic_kwargs,
    )


if __name__ == "__main__":
    main()
