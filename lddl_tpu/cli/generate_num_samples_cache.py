"""CLI: (re)generate the .num_samples.json cache for a shard directory.

Reference parity: the ``generate_num_samples_cache`` console script
(lddl/dask/load_balance.py:428-455).
"""

from ..balance import generate_num_samples_cache
from .common import attach_multihost_arg, communicator_of, make_parser


def attach_args(parser=None):
    parser = parser or make_parser(__doc__)
    parser.add_argument("--path", required=True)
    attach_multihost_arg(parser)
    return parser


def main(args=None):
    args = args if args is not None else attach_args().parse_args()
    comm = communicator_of(args)
    counts = generate_num_samples_cache(args.path, comm=comm)
    print("cached counts for {} shards".format(len(counts)))


if __name__ == "__main__":
    main()
