"""CLI: balance preprocessor output into equal-count shards.

Reference parity: the ``balance_dask_output`` console script
(lddl/dask/load_balance.py:381-426), MPI replaced by --multihost
(jax.distributed).
"""

from ..balance import balance_shards
from .common import (apply_storage_backend, arm_fleet_if_requested,
                     attach_fleet_arg, attach_multihost_arg,
                     attach_storage_arg, communicator_of, make_parser)


def attach_args(parser=None):
    parser = parser or make_parser(__doc__)
    parser.add_argument("--indir", required=True,
                        help="preprocessor output directory")
    parser.add_argument("--outdir", required=True)
    parser.add_argument("--num-shards", type=int, required=True,
                        help="shard count; choose a multiple of "
                             "(num data-parallel groups x loader workers)")
    attach_multihost_arg(parser)
    attach_storage_arg(parser)
    attach_fleet_arg(parser)
    return parser


def main(args=None):
    args = args if args is not None else attach_args().parse_args()
    apply_storage_backend(args)
    arm_fleet_if_requested(args, args.outdir)
    comm = communicator_of(args)
    counts = balance_shards(args.indir, args.outdir, args.num_shards,
                            comm=comm, log=print)
    print("balanced {} shards, {} samples total".format(
        len(counts), sum(counts.values())))


if __name__ == "__main__":
    main()
