"""CLI: BART pretraining preprocessor.

Reference parity: the ``preprocess_bart_pretrain`` console script
(lddl/dask/bart/pretrain.py:155-290).
"""

from ..preprocess import BartPretrainConfig, run_bart_preprocess
from ..utils.args import attach_bool_arg
from ..utils.cpus import usable_cpu_count
from .common import (apply_storage_backend, arm_fleet_if_requested,
                     attach_corpus_args, attach_elastic_args,
                     attach_fleet_arg, attach_multihost_arg,
                     attach_storage_arg, communicator_of,
                     corpus_paths_of, elastic_kwargs_of, make_parser)


def attach_args(parser=None):
    parser = parser or make_parser(__doc__)
    attach_corpus_args(parser)
    attach_multihost_arg(parser)
    attach_elastic_args(parser)
    attach_fleet_arg(parser)
    attach_storage_arg(parser)
    parser.add_argument("--sink", "--outdir", dest="sink", required=True)
    parser.add_argument("--vocab-file", default=None,
                        help="emit schema-v2 token-id columns "
                             "(sentence_ids/sentence_lens) tokenized with "
                             "this vocab; the loader must use the same "
                             "vocab (default: text-only v1 shards)")
    parser.add_argument("--tokenizer", default=None,
                        help="HF hub tokenizer name (alternative to "
                             "--vocab-file) for schema-v2 shards")
    parser.add_argument("--target-seq-length", type=int, default=128)
    parser.add_argument("--short-seq-prob", type=float, default=0.1)
    parser.add_argument("--sample-ratio", type=float, default=0.9)
    parser.add_argument("--seed", type=int, default=12345)
    parser.add_argument("--num-blocks", type=int, default=64)
    parser.add_argument("--spool-groups", type=int, default=None,
                        help="coarse radix width of the shuffle spool")
    parser.add_argument("--local-workers", type=int, default=0,
                        help="process-pool size per host "
                             "(0 = one per CPU core)")
    parser.add_argument("--splitter", choices=("rules", "learned"),
                        default="rules",
                        help="sentence splitter (see preprocess_bert_"
                             "pretrain --splitter)")
    parser.add_argument("--output-format", choices=("parquet", "txt"),
                        default="parquet")
    attach_bool_arg(parser, "resume", default=False,
                    help_str="continue a crashed/failed run from its unit "
                             "ledger (skips completed spool groups)")
    attach_bool_arg(parser, "global-shuffle", default=True)
    return parser


def main(args=None):
    import os
    args = args if args is not None else attach_args().parse_args()
    # Pin the storage backend into the env first (workers inherit it),
    # then arm fleet BEFORE snapshotting the elastic kwargs (see the
    # bert CLI).
    apply_storage_backend(args)
    arm_fleet_if_requested(args, args.sink)
    elastic_kwargs = elastic_kwargs_of(args)
    comm = communicator_of(args)
    tokenizer = None
    if args.vocab_file or args.tokenizer:
        from ..preprocess import get_tokenizer
        tokenizer = get_tokenizer(vocab_file=args.vocab_file,
                                  pretrained_model_name=args.tokenizer)
    run_bart_preprocess(
        corpus_paths_of(args),
        args.sink,
        config=BartPretrainConfig(
            target_seq_length=args.target_seq_length,
            short_seq_prob=args.short_seq_prob,
            splitter=args.splitter,
        ),
        num_workers=args.local_workers or usable_cpu_count(),
        num_blocks=args.num_blocks,
        sample_ratio=args.sample_ratio,
        seed=args.seed,
        global_shuffle=args.global_shuffle,
        output_format=args.output_format,
        comm=comm,
        log=print,
        spool_groups=args.spool_groups,
        resume=args.resume,
        tokenizer=tokenizer,
        **elastic_kwargs,
    )


if __name__ == "__main__":
    main()
