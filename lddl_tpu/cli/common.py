"""Shared CLI plumbing: corpus path flags, multihost init, logging."""

import argparse


def attach_corpus_args(parser):
    parser.add_argument("--wikipedia", default=None,
                        help="path to the wikipedia corpus (dir with "
                             "source/*.txt)")
    parser.add_argument("--books", default=None)
    parser.add_argument("--common-crawl", default=None)
    parser.add_argument("--open-webtext", default=None)


def corpus_paths_of(args):
    paths = {
        "wikipedia": args.wikipedia,
        "books": args.books,
        "common_crawl": args.common_crawl,
        "open_webtext": args.open_webtext,
    }
    if all(v is None for v in paths.values()):
        raise SystemExit(
            "give at least one corpus: --wikipedia/--books/--common-crawl/"
            "--open-webtext")
    return paths


def attach_multihost_arg(parser):
    parser.add_argument(
        "--multihost", action="store_true",
        help="initialize jax.distributed and split work across hosts; "
             "without the flags below, coordinator/rank come from the "
             "cluster environment (TPU metadata, SLURM, ...)")
    parser.add_argument(
        "--coordinator-address", default=None, metavar="HOST:PORT",
        help="rank-0 coordinator address when no cluster env provides it "
             "(the jax.distributed equivalent of mpirun's wiring)")
    parser.add_argument("--num-processes", type=int, default=None,
                        help="world size (with --coordinator-address)")
    parser.add_argument("--process-id", type=int, default=None,
                        help="this host's rank (with --coordinator-address)")


def attach_elastic_args(parser):
    parser.add_argument(
        "--elastic", action="store_true",
        help="lease-based work-stealing multi-host mode: launch this SAME "
             "command on N independent hosts sharing --sink (no "
             "coordinator, no barriers); hosts claim scatter/gather units "
             "via lease files, any host may die mid-unit and be reclaimed "
             "by the survivors, output is byte-identical to a single-host "
             "run. Mutually exclusive with --multihost")
    parser.add_argument(
        "--lease-ttl", type=float, default=30.0, metavar="SECONDS",
        help="elastic lease TTL: a dead host's in-flight unit is stolen "
             "after at most this long; must exceed the renewal round-trip "
             "on your shared filesystem (renewals run at ttl/3)")
    parser.add_argument(
        "--elastic-host-id", default=None,
        help="stable holder id for lease files (default: auto "
             "hostname-pid-nonce)")
    parser.add_argument(
        "--scatter-units", type=int, default=None,
        help="fixed elastic scatter work-unit count (block slices). "
             "Default: ADAPTIVE — a few probe slices measure per-block "
             "wall, then a journaled plan sizes the remaining units "
             "toward a target wall of ~64x the measured lease overhead; "
             "give an explicit count to pin the classic fixed stride "
             "(the unit plan rides the resume fingerprint either way)")


def attach_storage_arg(parser):
    parser.add_argument(
        "--storage-backend", choices=("local", "mock"), default=None,
        help="durable-IO/coordination backend (resilience/backend.py): "
             "'local' = the POSIX shared filesystem (default; atomic-"
             "rename leases, rename publishes), 'mock' = the in-process "
             "object store with CAS leases and multipart-upload-then-"
             "commit publishes (chaos/CI validation only). Equivalent to "
             "LDDL_TPU_STORAGE_BACKEND; inherited by worker processes")


def apply_storage_backend(args):
    """Pin the selected backend into the environment BEFORE any run
    kwargs are snapshotted or workers spawn (env-based, so pool/loader
    children inherit it — same pattern as fault arming)."""
    name = getattr(args, "storage_backend", None)
    if name:
        from ..resilience import backend as storage
        storage.set_backend(name)


def attach_fleet_arg(parser):
    parser.add_argument(
        "--fleet-telemetry", action="store_true",
        help="publish per-host telemetry spools (registry snapshots + "
             "unit/generation lifecycle event logs + traces) under "
             "<sink>/.telemetry/<holder>/ for cross-host aggregation; "
             "inspect with `python -m tools.pipeline_status <sink>` "
             "(equivalent to LDDL_TPU_FLEET_DIR=<sink>)")


def arm_fleet_if_requested(args, sink):
    """Arm fleet telemetry into the run's output dir when requested
    (--fleet-telemetry, or the env var set by the operator). The elastic
    holder id doubles as the spool name so lease events and spool dirs
    name the same host — and when the operator gave no --elastic-host-id
    on an elastic run, ONE auto-generated lease holder is pinned into
    args here so the spool and the lease files still share a name
    (configure() would otherwise pin a hostname-pid default that the
    runner's later adopt_holder() could no longer override)."""
    if not getattr(args, "fleet_telemetry", False):
        return
    holder = getattr(args, "elastic_host_id", None)
    if holder is None and getattr(args, "elastic", False):
        from ..resilience import leases
        holder = leases.default_holder()
        args.elastic_host_id = holder
    from ..observability import fleet
    fleet.configure(sink, holder_id=holder,
                    ttl=getattr(args, "lease_ttl", None))


def elastic_kwargs_of(args):
    if getattr(args, "elastic", False) and getattr(args, "multihost", False):
        raise SystemExit(
            "--elastic and --multihost are mutually exclusive: elastic "
            "hosts coordinate through lease files in the output dir, not "
            "jax.distributed")
    return {
        "elastic": getattr(args, "elastic", False),
        "lease_ttl": args.lease_ttl,
        "holder_id": args.elastic_host_id,
        "scatter_units": args.scatter_units,
    }


def communicator_of(args):
    from ..parallel.distributed import get_communicator
    if getattr(args, "multihost", False):
        import os

        import jax
        plats = os.environ.get("JAX_PLATFORMS", "")
        if plats:
            # Re-assert the env var through the config: if anything imported
            # jax and touched a backend before us (e.g. a site hook), the
            # env var alone no longer takes effect, and a half-initialized
            # accelerator backend would silently break collective semantics.
            jax.config.update("jax_platforms", plats)
        if plats.startswith("cpu"):
            # CPU-only preprocess clusters (no TPUs attached) need an
            # explicit cross-process collectives backend; TPU pods get
            # collectives from the platform itself.
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
        wiring = (args.coordinator_address, args.num_processes,
                  args.process_id)
        if any(v is not None for v in wiring) and None in wiring:
            raise SystemExit(
                "--coordinator-address, --num-processes and --process-id "
                "must be given together (or none, for cluster "
                "auto-detection)")
        kwargs = {}
        if args.coordinator_address is not None:
            kwargs = dict(coordinator_address=args.coordinator_address,
                          num_processes=args.num_processes,
                          process_id=args.process_id)
        jax.distributed.initialize(**kwargs)
    return get_communicator()


def make_parser(description):
    return argparse.ArgumentParser(
        description=description,
        formatter_class=argparse.ArgumentDefaultsHelpFormatter)
