"""Shared CLI plumbing: corpus path flags, multihost init, logging."""

import argparse


def attach_corpus_args(parser):
    parser.add_argument("--wikipedia", default=None,
                        help="path to the wikipedia corpus (dir with "
                             "source/*.txt)")
    parser.add_argument("--books", default=None)
    parser.add_argument("--common-crawl", default=None)
    parser.add_argument("--open-webtext", default=None)


def corpus_paths_of(args):
    paths = {
        "wikipedia": args.wikipedia,
        "books": args.books,
        "common_crawl": args.common_crawl,
        "open_webtext": args.open_webtext,
    }
    if all(v is None for v in paths.values()):
        raise SystemExit(
            "give at least one corpus: --wikipedia/--books/--common-crawl/"
            "--open-webtext")
    return paths


def attach_multihost_arg(parser):
    parser.add_argument(
        "--multihost", action="store_true",
        help="initialize jax.distributed (reads the standard "
             "JAX coordinator env vars / TPU metadata) and split work "
             "across hosts")


def communicator_of(args):
    from ..parallel.distributed import get_communicator
    if getattr(args, "multihost", False):
        import jax
        jax.distributed.initialize()
    return get_communicator()


def make_parser(description):
    return argparse.ArgumentParser(
        description=description,
        formatter_class=argparse.ArgumentDefaultsHelpFormatter)
