"""lddl_tpu — a TPU-native (JAX/XLA/pjit) language-dataset pipeline.

A ground-up rebuild of the capabilities of NVIDIA LDDL
(reference: /root/reference, wdykas/LDDL) designed TPU-first:

- Downloaders normalize public corpora into one-document-per-line text shards.
- A distributed preprocessor sentence-splits, tokenizes, builds BERT/BART
  pretraining samples, applies static or dynamic MLM masking, and writes
  sequence-length-binned Parquet shards. Hot per-partition kernels
  (masking / binning / packing) run as jit+vmap'd JAX on TPU.
- A deterministic SPMD load balancer equalizes per-shard sample counts.
- A streaming, epoch-seeded data loader yields globally-sharded ``jax.Array``
  batches for an arbitrary ``jax.sharding.Mesh`` (data-parallel-group-aware:
  tensor/pipeline-parallel peers receive identical data) with synchronized
  per-iteration sequence-bin selection and zero communication.

Layer map (mirrors reference lddl/ layering, see SURVEY.md):

    download/    -> text shards        (ref: lddl/download/)
    preprocess/  -> parquet shards     (ref: lddl/dask/)
    balance/     -> balanced shards    (ref: lddl/dask/load_balance.py)
    loader/      -> device batches     (ref: lddl/torch*, lddl/paddle)
    ops/         -> TPU kernels for the hot paths (new; TPU-native)
    models/      -> reference BERT/BART models + train steps (new; the
                    mock-training harness the reference keeps in benchmarks/)
    parallel/    -> mesh + multihost coordination (ref: MPI/NCCL usage)
    resilience/  -> retries, atomic publish, integrity, fault injection
    observability/ -> metrics registry + span tracing + exporters (inert
                    by contract; armed via LDDL_TPU_METRICS_DIR — see
                    README "Observability" for the stable metric names)
"""

__version__ = "0.1.0"
