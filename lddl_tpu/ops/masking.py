"""Batched static MLM masking kernels (numpy + jit'd JAX/TPU).

Replaces the reference's per-row Python loop
(lddl/dask/bert/pretrain.py:182-238) with whole-bucket batch kernels — the
"tokenize/mask/bin as jit+vmap'd JAX" hot path from BASELINE.json.

Algorithm (identical semantics across engines):
- per row: num_to_predict = min(max_pred, max(1, round(seq_len * ratio)))
- selection: uniform random subset of the non-special valid positions
  (smallest-k of iid uniform scores == uniform subset without replacement)
- per selected position: 80% -> [MASK], 10% -> uniform random vocab id,
  10% -> keep original.

The two engines consume different RNG streams (numpy Philox vs
jax.random), so masks differ between engines but are each fully
deterministic in (seed, bucket). Shard parity is defined per engine.
"""

import numpy as np


def plan_num_to_predict(seq_lens, masked_lm_ratio, max_predictions_per_seq):
    seq_lens = np.asarray(seq_lens)
    return np.minimum(
        max_predictions_per_seq,
        np.maximum(1, np.round(seq_lens * masked_lm_ratio)),
    ).astype(np.int32)


def _ranks_from_scores(scores):
    """Per-row rank of each column under ascending score order."""
    order = np.argsort(scores, axis=1, kind="stable")
    ranks = np.empty_like(order)
    rows = np.arange(scores.shape[0])[:, None]
    ranks[rows, order] = np.arange(scores.shape[1])[None, :]
    return ranks


def mask_batch_numpy(ids, candidate, num_to_predict, g, mask_id, vocab_size,
                     random_token_low=0):
    """Vectorized masking over a padded id matrix.

    ids: [N, L] int32; candidate: [N, L] bool (valid AND non-special);
    num_to_predict: [N] int. Returns (masked_ids, selected_mask).
    """
    scores = g.random(ids.shape)
    scores[~candidate] = np.inf
    ranks = _ranks_from_scores(scores)
    selected = (ranks < num_to_predict[:, None]) & candidate

    action = g.random(ids.shape)
    random_ids = g.integers(random_token_low, vocab_size, ids.shape,
                            dtype=np.int64).astype(np.int32)
    out = np.where(selected & (action < 0.8), mask_id, ids)
    out = np.where(selected & (action >= 0.8) & (action < 0.9), random_ids,
                   out)
    return out, selected


def _mask_batch_jax_impl(ids, candidate, num_to_predict, key, mask_id,
                         vocab_size, random_token_low):
    import jax
    import jax.numpy as jnp

    k_sel, k_act, k_rand = jax.random.split(key, 3)
    scores = jax.random.uniform(k_sel, ids.shape)
    scores = jnp.where(candidate, scores, jnp.inf)
    order = jnp.argsort(scores, axis=1)
    ranks = jnp.zeros_like(order).at[
        jnp.arange(ids.shape[0])[:, None], order].set(
            jnp.arange(ids.shape[1])[None, :])
    selected = (ranks < num_to_predict[:, None]) & candidate

    action = jax.random.uniform(k_act, ids.shape)
    random_ids = jax.random.randint(k_rand, ids.shape, random_token_low,
                                    vocab_size, dtype=jnp.int32)
    out = jnp.where(selected & (action < 0.8), mask_id, ids)
    out = jnp.where(selected & (action >= 0.8) & (action < 0.9), random_ids,
                    out)
    return out, selected


def make_jax_masker(mask_id, vocab_size, random_token_low=0):
    """jit'd masking kernel; call with padded-to-bucket shapes so the
    number of compilations stays bounded (see ops.packing.pad_to_bucket)."""
    import jax
    import functools

    impl = functools.partial(
        _mask_batch_jax_impl,
        mask_id=mask_id,
        vocab_size=vocab_size,
        random_token_low=random_token_low,
    )
    jitted = jax.jit(impl)

    def run(ids, candidate, num_to_predict, seed):
        key = jax.random.key(np.uint32(seed))
        out, selected = jitted(ids, candidate,
                               np.asarray(num_to_predict, np.int32), key)
        return np.asarray(out), np.asarray(selected)

    return run


def mask_batch_jax(ids, candidate, num_to_predict, seed, mask_id, vocab_size,
                   random_token_low=0):
    """One-shot convenience wrapper around make_jax_masker."""
    run = make_jax_masker(mask_id, vocab_size, random_token_low)
    return run(ids, candidate, num_to_predict, seed)
