"""Batched static MLM masking kernels (numpy + jit'd JAX/TPU).

Replaces the reference's per-row Python loop
(lddl/dask/bert/pretrain.py:182-238) with whole-bucket batch kernels — the
"tokenize/mask/bin as jit+vmap'd JAX" hot path from BASELINE.json.

Algorithm (identical semantics across engines):
- per row: num_to_predict = min(max_pred, max(1, round(seq_len * ratio)))
- selection: uniform random subset of the non-special valid positions
  (smallest-k of iid uniform scores == uniform subset without replacement)
- per selected position: 80% -> [MASK], 10% -> uniform random vocab id,
  10% -> keep original.

The two engines consume different RNG streams (numpy Philox vs
jax.random), so masks differ between engines but are each fully
deterministic in (seed, bucket). Shard parity is defined per engine.

Engine choice is a measured decision, not a guess: on a real TPU chip the
jax kernel loses to host numpy by 9-111x across bucket sizes 256..32k rows (dispatch
latency + host<->device transfer dominate; benchmarks/mask_engine_bench.py
-> MASK_ENGINE_BENCH.json), so "numpy" is the preprocessing default and
the jit kernels serve device-resident data paths.
"""

import numpy as np


def plan_num_to_predict(seq_lens, masked_lm_ratio, max_predictions_per_seq):
    seq_lens = np.asarray(seq_lens)
    return np.minimum(
        max_predictions_per_seq,
        np.maximum(1, np.round(seq_lens * masked_lm_ratio)),
    ).astype(np.int32)


def mask_batch_numpy(ids, candidate, num_to_predict, g, mask_id, vocab_size,
                     random_token_low=0):
    """Vectorized masking over a padded id matrix.

    ids: [N, L] int32; candidate: [N, L] bool (valid AND non-special);
    num_to_predict: [N] int. Returns (masked_ids, selected_mask).
    """
    scores = g.random(ids.shape)
    scores[~candidate] = np.inf
    # Smallest-k selection via partition + per-row threshold: O(N*L)
    # instead of a full argsort. Equivalent to rank-based selection for
    # distinct scores (iid float64 uniforms: ties have probability ~2^-53;
    # non-candidates sit at +inf and the candidate guard excludes them
    # even when a short row's threshold is inf).
    num_to_predict = np.asarray(num_to_predict)
    if ids.shape[0] == 0:
        return ids.copy(), np.zeros_like(candidate)
    # num_to_predict values beyond the row width clamp to "take every
    # candidate" (the rank-based behavior).
    k_max = min(max(int(num_to_predict.max()), 1), ids.shape[1])
    # Single partition at k_max-1 + a sort of the k_max-wide slice. (A
    # multi-kth np.partition at every distinct k was measured 20x slower:
    # numpy runs one introselect pass per listed kth.)
    smallest = np.partition(scores, k_max - 1, axis=1)[:, :k_max]
    smallest.sort(axis=1)
    thresh = smallest[np.arange(ids.shape[0]),
                      np.clip(num_to_predict, 1, k_max) - 1]
    selected = (scores <= thresh[:, None]) & candidate
    selected[num_to_predict <= 0] = False

    action = g.random(ids.shape)
    random_ids = g.integers(random_token_low, vocab_size, ids.shape,
                            dtype=np.int64).astype(np.int32)
    out = np.where(selected & (action < 0.8), mask_id, ids)
    out = np.where(selected & (action >= 0.8) & (action < 0.9), random_ids,
                   out)
    return out, selected


def _word_groups(ids, candidate, is_subword):
    """Group candidate columns into whole words: a candidate column joins
    the previous column's group when its token is a subword continuation
    and the previous column is also a candidate. Returns (start, gid):
    start[r,c] marks group heads; gid[r,c] numbers groups left-to-right
    (meaningful only where candidate)."""
    cont = np.zeros_like(candidate)
    cont[:, 1:] = (candidate[:, 1:] & candidate[:, :-1]
                   & is_subword[ids[:, 1:]])
    start = candidate & ~cont
    gid = np.cumsum(start, axis=1) - 1
    return start, gid


def mask_whole_word_batch_numpy(ids, candidate, num_to_predict, g, mask_id,
                                vocab_size, is_subword, random_token_low=0):
    """Vectorized whole-word masking: subword continuations group with
    their word start and groups are selected atomically — a group is taken,
    in random score order, iff it fits the remaining per-row budget
    (Google-BERT wwm semantics: oversized groups are skipped, not split).

    Frozen draw contract (identical to mask_batch_numpy's shapes, so the
    stream is engine-checkable): scores [N,L], action [N,L], random ids
    [N,L] — selection order is the stable ascending argsort of each
    group's head-column score.

    NOTE: this batched selection consumes a different draw stream than the
    removed round-1 per-row loop, so wwm static masks for a given
    (seed, bucket) differ from round-1 outputs — regenerate any round-1
    wwm datasets rather than mixing them with current ones.
    """
    n, width = ids.shape
    scores = g.random(ids.shape)
    start, gid = _word_groups(ids, candidate, is_subword)
    ngroups = start.sum(axis=1)
    max_groups = max(int(ngroups.max()) if n else 0, 1)

    sizes = np.zeros((n, max_groups), dtype=np.int64)
    cand_r, cand_c = np.nonzero(candidate)
    np.add.at(sizes, (cand_r, gid[cand_r, cand_c]), 1)
    gscores = np.full((n, max_groups), np.inf)
    head_r, head_c = np.nonzero(start)
    gscores[head_r, gid[head_r, head_c]] = scores[head_r, head_c]

    order = np.argsort(gscores, axis=1, kind="stable")
    size_sorted = np.take_along_axis(sizes, order, axis=1)
    valid_rank = np.arange(max_groups)[None, :] < ngroups[:, None]
    taken = np.zeros(n, dtype=np.int64)
    budget = np.asarray(num_to_predict, dtype=np.int64)
    accept = np.zeros((n, max_groups), dtype=bool)
    rows = np.arange(n)
    # Greedy scan, vectorized over rows, sequential only over score rank.
    for k in range(max_groups):
        sz = size_sorted[:, k]
        ok = valid_rank[:, k] & (taken < budget) & (taken + sz <= budget)
        taken = np.where(ok, taken + sz, taken)
        accept[rows[ok], order[ok, k]] = True

    selected = np.zeros_like(candidate)
    selected[cand_r, cand_c] = accept[cand_r, gid[cand_r, cand_c]]

    action = g.random(ids.shape)
    random_ids = g.integers(random_token_low, vocab_size, ids.shape,
                            dtype=np.int64).astype(np.int32)
    out = np.where(selected & (action < 0.8), mask_id, ids)
    out = np.where(selected & (action >= 0.8) & (action < 0.9), random_ids,
                   out)
    return out, selected


def _mask_whole_word_jax_impl(ids, candidate, num_to_predict, key,
                              is_subword, mask_id, vocab_size,
                              random_token_low):
    import jax
    import jax.numpy as jnp

    n, width = ids.shape
    k_sel, k_act, k_rand = jax.random.split(key, 3)
    scores = jax.random.uniform(k_sel, ids.shape)

    cont = jnp.zeros_like(candidate)
    cont = cont.at[:, 1:].set(candidate[:, 1:] & candidate[:, :-1]
                              & is_subword[ids[:, 1:]])
    start = candidate & ~cont
    gid = jnp.cumsum(start, axis=1) - 1
    ngroups = start.sum(axis=1)

    rows = jnp.arange(n)
    # Per-(row, gid) aggregates via segment ids r*width + gid; gid < width
    # always, so segments never collide across rows.
    seg = (rows[:, None] * width + jnp.clip(gid, 0)).reshape(-1)
    sizes = jax.ops.segment_sum(candidate.reshape(-1).astype(jnp.int32), seg,
                                num_segments=n * width).reshape(n, width)
    gscores = jnp.full((n, width), jnp.inf).at[
        rows[:, None], jnp.clip(gid, 0)].min(
            jnp.where(start, scores, jnp.inf))

    order = jnp.argsort(gscores, axis=1)
    size_sorted = jnp.take_along_axis(sizes, order, axis=1)
    valid_rank = jnp.arange(width)[None, :] < ngroups[:, None]
    budget = num_to_predict.astype(jnp.int32)

    def step(carry, k):
        taken, accept = carry
        sz = size_sorted[:, k].astype(jnp.int32)
        ok = valid_rank[:, k] & (taken < budget) & (taken + sz <= budget)
        taken = jnp.where(ok, taken + sz, taken)
        accept = accept.at[rows, order[:, k]].set(
            accept[rows, order[:, k]] | ok)
        return (taken, accept), None

    (_, accept), _ = jax.lax.scan(
        step, (jnp.zeros(n, jnp.int32), jnp.zeros((n, width), bool)),
        jnp.arange(width))
    selected = candidate & accept[rows[:, None], jnp.clip(gid, 0)]

    action = jax.random.uniform(k_act, ids.shape)
    random_ids = jax.random.randint(k_rand, ids.shape, random_token_low,
                                    vocab_size, dtype=jnp.int32)
    out = jnp.where(selected & (action < 0.8), mask_id, ids)
    out = jnp.where(selected & (action >= 0.8) & (action < 0.9), random_ids,
                    out)
    return out, selected


def make_jax_whole_word_masker(mask_id, vocab_size, is_subword,
                               random_token_low=0):
    """jit'd whole-word masking kernel (same call shape as
    make_jax_masker's runner)."""
    import jax
    import jax.numpy as jnp
    import functools

    impl = functools.partial(
        _mask_whole_word_jax_impl,
        mask_id=mask_id,
        vocab_size=vocab_size,
        random_token_low=random_token_low,
    )
    jitted = jax.jit(impl)
    is_subword = jnp.asarray(is_subword)

    def run(ids, candidate, num_to_predict, seed):
        key = jax.random.key(np.uint32(seed))
        out, selected = jitted(ids, candidate,
                               np.asarray(num_to_predict, np.int32), key,
                               is_subword)
        return np.asarray(out), np.asarray(selected)

    return run


def _mask_batch_jax_impl(ids, candidate, num_to_predict, key, mask_id,
                         vocab_size, random_token_low):
    import jax
    import jax.numpy as jnp

    k_sel, k_act, k_rand = jax.random.split(key, 3)
    scores = jax.random.uniform(k_sel, ids.shape)
    scores = jnp.where(candidate, scores, jnp.inf)
    order = jnp.argsort(scores, axis=1)
    ranks = jnp.zeros_like(order).at[
        jnp.arange(ids.shape[0])[:, None], order].set(
            jnp.arange(ids.shape[1])[None, :])
    selected = (ranks < num_to_predict[:, None]) & candidate

    action = jax.random.uniform(k_act, ids.shape)
    random_ids = jax.random.randint(k_rand, ids.shape, random_token_low,
                                    vocab_size, dtype=jnp.int32)
    out = jnp.where(selected & (action < 0.8), mask_id, ids)
    out = jnp.where(selected & (action >= 0.8) & (action < 0.9), random_ids,
                    out)
    return out, selected


def make_jax_masker(mask_id, vocab_size, random_token_low=0):
    """jit'd masking kernel; call with padded-to-bucket shapes so the
    number of compilations stays bounded (see ops.packing.pad_to_bucket)."""
    import jax
    import functools

    impl = functools.partial(
        _mask_batch_jax_impl,
        mask_id=mask_id,
        vocab_size=vocab_size,
        random_token_low=random_token_low,
    )
    jitted = jax.jit(impl)

    def run(ids, candidate, num_to_predict, seed):
        key = jax.random.key(np.uint32(seed))
        out, selected = jitted(ids, candidate,
                               np.asarray(num_to_predict, np.int32), key)
        return np.asarray(out), np.asarray(selected)

    return run


def mask_batch_jax(ids, candidate, num_to_predict, seed, mask_id, vocab_size,
                   random_token_low=0):
    """One-shot convenience wrapper around make_jax_masker."""
    run = make_jax_masker(mask_id, vocab_size, random_token_low)
    return run(ids, candidate, num_to_predict, seed)
