"""Pallas fused attention (flash-style online softmax) for TPU.

The hot op of the model stack as a hand-written TPU kernel: per
(batch, head), Q blocks stream through VMEM while the kernel walks K/V
in blocks under a running-max/denominator softmax — the L x L score
matrix never exists in HBM, scores accumulate in fp32 on the MXU
(``preferred_element_type``), and the output is written once per Q
block.

Scope (documented, tested):
- forward: the pallas kernel (grid (B*H, L/TQ), K/V resident in VMEM per
  (batch, head) — the right regime for L up to a few thousand; VMEM is
  ~16 MiB/core).
- backward: jax.custom_vjp recomputing through the XLA dense reference
  (bit-compatible semantics, standard recompute fallback); a pallas
  backward kernel is future work.
- numerics match ops.ring_attention.dense_attention_reference (same
  finite -1e9 padding bias), pinned by interpret-mode tests on CPU; the
  kernel compiles and runs on a real TPU chip via the same entry point.

``interpret=None`` auto-selects: real pallas lowering on TPU, interpret
mode elsewhere (CPU CI).
"""

import functools

# jax imported inside functions: the offline pipeline stages must stay
# importable (via lddl_tpu.ops) on machines where jax is absent/broken.

_TQ = 128   # Q rows per program (8x128-aligned for fp32 tiles)
_TK = 128   # K/V rows per inner step


def _fwd_kernel(q_ref, k_ref, v_ref, mask_ref, o_ref, *, scale, n_kv):
    import jax
    import jax.numpy as jnp

    q = q_ref[0].astype(jnp.float32)            # [TQ, D]
    tq, d = q.shape

    def body(j, carry):
        m, l, acc = carry
        import jax.experimental.pallas as pl
        k_blk = k_ref[0, pl.ds(j * _TK, _TK), :].astype(jnp.float32)
        v_blk = v_ref[0, pl.ds(j * _TK, _TK), :].astype(jnp.float32)
        msk = mask_ref[0, 0, pl.ds(j * _TK, _TK)]
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale   # [TQ, TK]
        s = s + jnp.where(msk[None, :] > 0, 0.0, -1e9)
        m_new = jnp.maximum(m, s.max(axis=1, keepdims=True))
        p = jnp.exp(s - m_new)                            # [TQ, TK]
        corr = jnp.exp(m - m_new)                         # [TQ, 1]
        l_new = l * corr + p.sum(axis=1, keepdims=True)
        acc_new = acc * corr + jax.lax.dot_general(
            p, v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return m_new, l_new, acc_new

    m0 = jnp.full((tq, 1), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((tq, 1), jnp.float32)
    acc0 = jnp.zeros((tq, d), jnp.float32)
    import jax.lax as lax
    _, l, acc = lax.fori_loop(0, n_kv, body, (m0, l0, acc0))
    o_ref[0] = (acc / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)


def flash_attention_fwd(q, k, v, kv_mask, interpret=None):
    """Fused attention forward: q/k/v [B, L, H, D], kv_mask [B, L]
    (1 = attend). Returns [B, L, H, D]; fp32 accumulation, output in
    q.dtype. L is padded to a 128 multiple internally (padded keys are
    masked; padded query rows are dropped on return)."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    b, l, h, d = q.shape
    l_pad = -(-l // _TQ) * _TQ
    if l_pad != l:
        pad = ((0, 0), (0, l_pad - l), (0, 0), (0, 0))
        q = jnp.pad(q, pad)
        k = jnp.pad(k, pad)
        v = jnp.pad(v, pad)
        kv_mask = jnp.pad(kv_mask, ((0, 0), (0, l_pad - l)))

    # [B, L, H, D] -> [B*H, L, D]; mask tiled per head.
    def to_bh(t):
        return t.transpose(0, 2, 1, 3).reshape(b * h, l_pad, d)

    qb, kb, vb = to_bh(q), to_bh(k), to_bh(v)
    # [B, 1, L]: the trailing (1, L) block satisfies the TPU tiling rule
    # (last two dims equal to the array's); the index map shares one mask
    # copy across the H head-programs instead of materializing B*H copies.
    maskb = kv_mask.astype(jnp.int32).reshape(b, 1, l_pad)

    scale = 1.0 / (d ** 0.5)
    n_kv = l_pad // _TK
    kernel = functools.partial(_fwd_kernel, scale=scale, n_kv=n_kv)
    out = pl.pallas_call(
        kernel,
        grid=(b * h, l_pad // _TQ),
        in_specs=[
            pl.BlockSpec((1, _TQ, d), lambda bh, qi: (bh, qi, 0)),
            pl.BlockSpec((1, l_pad, d), lambda bh, qi: (bh, 0, 0)),
            pl.BlockSpec((1, l_pad, d), lambda bh, qi: (bh, 0, 0)),
            pl.BlockSpec((1, 1, l_pad),
                         lambda bh, qi: (bh // h, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, _TQ, d), lambda bh, qi: (bh, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((b * h, l_pad, d), q.dtype),
        interpret=interpret,
    )(qb, kb, vb, maskb)
    out = out.reshape(b, h, l_pad, d).transpose(0, 2, 1, 3)
    return out[:, :l]


_FLASH_VJP = None


def _build_vjp():
    """custom_vjp built on first use (keeps this module importable
    without jax)."""
    global _FLASH_VJP
    if _FLASH_VJP is not None:
        return _FLASH_VJP
    import jax

    @functools.partial(jax.custom_vjp, nondiff_argnums=(4,))
    def fa(q, k, v, kv_mask, interpret):
        return flash_attention_fwd(q, k, v, kv_mask, interpret=interpret)

    def fa_fwd(q, k, v, kv_mask, interpret):
        out = flash_attention_fwd(q, k, v, kv_mask, interpret=interpret)
        return out, (q, k, v, kv_mask)

    def fa_bwd(interpret, residuals, ct):
        from .ring_attention import dense_attention_reference
        q, k, v, kv_mask = residuals
        _, vjp = jax.vjp(
            lambda q_, k_, v_: dense_attention_reference(q_, k_, v_,
                                                         kv_mask),
            q, k, v)
        dq, dk, dv = vjp(ct)
        return dq, dk, dv, None

    fa.defvjp(fa_fwd, fa_bwd)
    _FLASH_VJP = fa
    return fa


def flash_attention(q, k, v, kv_mask, interpret=None):
    """Differentiable fused attention: pallas forward, recompute-through-
    dense backward (see module docstring)."""
    return _build_vjp()(q, k, v, kv_mask, interpret)
