"""Pallas fused attention for TPU — two regimes behind one entry point.

Short sequences (L_pad <= 896 at the standard head_dim 64; the
reference's headline pretraining regime L=512 —
/root/reference/lddl/dask/bert/pretrain.py:627-637 — sits here): the
"single-block" kernels. The whole L x L score matrix for one (batch,
head) row fits VMEM, so the forward computes an ordinary (not online)
softmax in one pass, and the backward is ONE fused kernel that
recomputes P once and emits dQ, dK, dV together (5 matmuls vs the
two-kernel online recipe's 7). Cells are fat: ``nbh`` (batch, head)
rows per grid cell (same batch row, so the mask/allowed matrix is built
once per cell; one row above L_pad 512, where the temporaries grow),
which amortizes per-cell overheads that dominate at short L — this is
what makes the pallas kernel BEAT XLA's fused dense attention from
L_pad 256 through 896 (FLASH_ATTENTION_BENCH.json +
MODEL_BENCH.json), where rounds 3-4 lost to it at 512 and below.

Long sequences: the flash-style online-softmax kernels. Per (batch,
head), Q blocks stream through VMEM while the kernel walks K/V blocks
under a running-max/denominator softmax — the L x L score matrix never
exists anywhere. Backward recomputes P blockwise from (Q, K, LSE): dQ
in a Q-block kernel, dK/dV in a KV-block kernel.

Both regimes: matmul operands stay in the stored dtype (bf16 in
training) with fp32 accumulation on the MXU
(``preferred_element_type``); the forward saves per-row log-sum-exp for
the backward; numerics match ops.ring_attention.dense_attention_reference
pinned by interpret-mode tests on CPU for forward AND gradients, and
the same entry point compiles and runs on a real TPU chip
(FLASH_ATTENTION_BENCH.json, MODEL_BENCH.json).

``interpret=None`` auto-selects: real pallas lowering on TPU, interpret
mode elsewhere (CPU CI).
"""

import functools

# jax imported inside functions: the offline pipeline stages must stay
# importable (via lddl_tpu.ops) on machines where jax is absent/broken.

def _block_sizes(l_pad):
    """(TQ, TK) tuned on a real v5e chip (round-3 sweep, fwd+bwd wall):
    128x128 blocks leave 2.5-3.6x on the table vs the MXU-filling sizes
    below — the inner dots must be big enough to amortize per-step
    overhead. l_pad is a multiple of 128, so the fallbacks always divide."""
    tq = 256 if l_pad <= 1024 else 512
    while l_pad % tq:
        tq //= 2
    tk = 512
    while l_pad % tk:
        tk //= 2
    return min(tq, l_pad), min(tk, l_pad)


def _dot(a, b, transpose_b=False):
    import jax
    import jax.numpy as jnp
    dims = (((1,), (1,)), ((), ())) if transpose_b else (((1,), (0,)),
                                                         ((), ()))
    return jax.lax.dot_general(a, b, dims,
                               preferred_element_type=jnp.float32)


def _fwd_kernel(q_ref, k_ref, v_ref, mask_ref, qmask_ref, o_ref, lse_ref,
                *, scale, n_kv, tk):
    import jax.numpy as jnp
    import jax.lax as lax
    from jax.experimental import pallas as pl

    # Matmul OPERANDS stay in the stored dtype (bf16 in training): the MXU
    # runs bf16 x bf16 -> fp32 at full rate but fp32 x fp32 at ~1/4 rate —
    # casting inputs up was measured to cost the whole kernel its lead
    # (MODEL_BENCH round-3 tuning). Softmax statistics stay fp32.
    q = q_ref[0]                                # [TQ, D], stored dtype
    qm = qmask_ref[0, 0]                        # [TQ] segment ids
    tq, d = q.shape

    def body(j, carry):
        m, l, acc = carry
        k_blk = k_ref[0, pl.ds(j * tk, tk), :]
        v_blk = v_ref[0, pl.ds(j * tk, tk), :]
        msk = mask_ref[0, 0, pl.ds(j * tk, tk)]
        s = _dot(q, k_blk, transpose_b=True) * scale      # fp32 [TQ, TK]
        # Attend iff the key is valid AND in the query's segment (plain
        # padding masks are the one-segment special case: q side all 1s).
        allowed = (msk[None, :] > 0) & (msk[None, :] == qm[:, None])
        s = s + jnp.where(allowed, 0.0, -1e9)
        m_new = jnp.maximum(m, s.max(axis=1, keepdims=True))
        p = jnp.exp(s - m_new)                            # fp32 [TQ, TK]
        corr = jnp.exp(m - m_new)                         # [TQ, 1]
        l_new = l * corr + p.sum(axis=1, keepdims=True)
        acc_new = acc * corr + _dot(p.astype(v_blk.dtype), v_blk)
        return m_new, l_new, acc_new

    m0 = jnp.full((tq, 1), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((tq, 1), jnp.float32)
    acc0 = jnp.zeros((tq, d), jnp.float32)
    m, l, acc = lax.fori_loop(0, n_kv, body, (m0, l0, acc0))
    l = jnp.maximum(l, 1e-30)
    o_ref[0] = (acc / l).astype(o_ref.dtype)
    lse_ref[0, 0] = (m[:, 0] + jnp.log(l[:, 0])).astype(lse_ref.dtype)


def _bwd_dq_kernel(q_ref, k_ref, v_ref, mask_ref, qmask_ref, do_ref,
                   lse_ref, delta_ref, dq_ref, *, scale, n_kv, tk):
    import jax.numpy as jnp
    import jax.lax as lax
    from jax.experimental import pallas as pl

    q = q_ref[0]                                 # [TQ, D], stored dtype
    qm = qmask_ref[0, 0]                         # [TQ] segment ids
    do = do_ref[0]                               # [TQ, D]
    lse = lse_ref[0, 0][:, None]                 # [TQ, 1]
    delta = delta_ref[0, 0][:, None]             # [TQ, 1]
    tq, d = q.shape

    def body(j, dq_acc):
        k_blk = k_ref[0, pl.ds(j * tk, tk), :]
        v_blk = v_ref[0, pl.ds(j * tk, tk), :]
        msk = mask_ref[0, 0, pl.ds(j * tk, tk)]
        s = _dot(q, k_blk, transpose_b=True) * scale
        allowed = (msk[None, :] > 0) & (msk[None, :] == qm[:, None])
        s = s + jnp.where(allowed, 0.0, -1e9)
        p = jnp.exp(s - lse)                     # fp32 [TQ, TK]
        dp = _dot(do, v_blk, transpose_b=True)   # fp32 [TQ, TK]
        ds = (p * (dp - delta) * scale).astype(k_blk.dtype)
        return dq_acc + _dot(ds, k_blk)

    dq = lax.fori_loop(0, n_kv, body, jnp.zeros((tq, d), jnp.float32))
    dq_ref[0] = dq.astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, mask_ref, qmask_ref, do_ref,
                    lse_ref, delta_ref, dk_ref, dv_ref, *, scale, n_q, tq):
    import jax.numpy as jnp
    import jax.lax as lax
    from jax.experimental import pallas as pl

    k = k_ref[0]                                 # [TK, D], stored dtype
    v = v_ref[0]                                 # [TK, D]
    msk = mask_ref[0, 0]                         # [TK] (this KV block)
    tk, d = k.shape

    def body(i, carry):
        dk_acc, dv_acc = carry
        q_blk = q_ref[0, pl.ds(i * tq, tq), :]
        do_blk = do_ref[0, pl.ds(i * tq, tq), :]
        qm = qmask_ref[0, 0, pl.ds(i * tq, tq)]            # [TQ]
        lse = lse_ref[0, 0, pl.ds(i * tq, tq)][None, :]    # [1, TQ]
        delta = delta_ref[0, 0, pl.ds(i * tq, tq)][None, :]
        allowed = (msk[:, None] > 0) & (msk[:, None] == qm[None, :])
        bias = jnp.where(allowed, 0.0, -1e9)               # [TK, TQ]
        # s^T layout: [TK, TQ]
        st = _dot(k, q_blk, transpose_b=True) * scale + bias
        pt = jnp.exp(st - lse)                   # fp32 [TK, TQ]
        dv_acc = dv_acc + _dot(pt.astype(do_blk.dtype), do_blk)  # [TK, D]
        dpt = _dot(v, do_blk, transpose_b=True)  # fp32 [TK, TQ]
        dst = (pt * (dpt - delta) * scale).astype(q_blk.dtype)
        dk_acc = dk_acc + _dot(dst, q_blk)       # [TK, D]
        return dk_acc, dv_acc

    zero = jnp.zeros((tk, d), jnp.float32)
    dk, dv = lax.fori_loop(0, n_q, body, (zero, zero))
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _prep_one(t, l_pad):
    """Pad one [B, L, H, D] tensor to l_pad rows and move it to the
    [B*H, L, D] kernel layout."""
    import jax.numpy as jnp
    b, l, h, d = t.shape
    if l_pad != l:
        t = jnp.pad(t, ((0, 0), (0, l_pad - l), (0, 0), (0, 0)))
    return t.transpose(0, 2, 1, 3).reshape(b * h, l_pad, d)


def _prep_mask(m, l_pad):
    import jax.numpy as jnp
    b, l = m.shape
    if l_pad != l:
        m = jnp.pad(m, ((0, 0), (0, l_pad - l)))
    return m.astype(jnp.int32).reshape(b, 1, l_pad)


def _prep(q, k, v, kv_mask, q_mask):
    """Pad L to a block multiple and move to the [B*H, L, D] kernel
    layout. Masks may be binary validity or per-token segment ids (packed
    rows); q_mask defaults to all-ones = "every query in segment 1".
    Returns (qb, kb, vb, maskb[B,1,Lp], qmaskb[B,1,Lp], shapes)."""
    import jax.numpy as jnp
    b, l, h, d = q.shape
    l_pad = pad_seq_len(l)
    if q_mask is None:
        # Plain padding mask: the kernel's test is (msk > 0) & (msk == qm),
        # so a truthy value other than 1 (int mask from a sum, bool*2, ...)
        # must normalize to 1 or it would mask EVERYTHING against the
        # all-ones q side (ADVICE round 3). NOTE: segment ids passed as
        # kv_mask WITHOUT the matching q_mask also collapse to all-1s here
        # — packed callers must pass the segment array as BOTH masks (as
        # models/bert.py does); values are invisible at trace time, so
        # this cannot be asserted.
        kv_mask = (kv_mask != 0).astype(jnp.int32)
        q_mask = jnp.ones((b, l), jnp.int32)
    return (_prep_one(q, l_pad), _prep_one(k, l_pad), _prep_one(v, l_pad),
            _prep_mask(kv_mask, l_pad), _prep_mask(q_mask, l_pad),
            (b, l, h, d, l_pad))


def _from_bh(t, b, l, h, d):
    return t.reshape(b, h, -1, d).transpose(0, 2, 1, 3)[:, :l]


def flash_attention_fwd(q, k, v, kv_mask, interpret=None, q_mask=None):
    """Fused attention forward: q/k/v [B, L, H, D], kv_mask [B, L]
    (1 = attend, or per-token segment ids for packed rows — pass the same
    array as q_mask and attention becomes block-diagonal within rows).
    Returns (out [B, L, H, D], lse [B*H, 1, L_pad]); fp32 accumulation,
    output in q.dtype. L pads to a 128 multiple internally (padded keys
    are masked; padded query rows are dropped on return)."""
    import jax
    from jax.experimental import pallas as pl
    import jax.numpy as jnp

    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    qb, kb, vb, maskb, qmaskb, (b, l, h, d, l_pad) = _prep(
        q, k, v, kv_mask, q_mask)
    scale = 1.0 / (d ** 0.5)
    if _use_onekv(l_pad, d):
        nbh = _nbh_for(h, l_pad)
        spec, spec_mask, spec_row = _onekv_specs(nbh, l_pad, d, h)
        out, lse = pl.pallas_call(
            functools.partial(_onekv_fwd_kernel, scale=scale, nbh=nbh),
            grid=(b * h // nbh,),
            in_specs=[spec, spec, spec, spec_mask, spec_mask],
            out_specs=[spec, spec_row],
            out_shape=[
                jax.ShapeDtypeStruct((b * h, l_pad, d), q.dtype),
                jax.ShapeDtypeStruct((b * h, 1, l_pad), jnp.float32),
            ],
            interpret=interpret,
        )(qb, kb, vb, maskb, qmaskb)
        return _from_bh(out, b, l, h, d), lse
    tq, tk = _block_sizes(l_pad)
    assert l_pad % tq == 0 and l_pad % tk == 0, (l_pad, tq, tk)
    kernel = functools.partial(_fwd_kernel, scale=scale,
                               n_kv=l_pad // tk, tk=tk)
    out, lse = pl.pallas_call(
        kernel,
        grid=(b * h, l_pad // tq),
        in_specs=[
            pl.BlockSpec((1, tq, d), lambda bh, qi: (bh, qi, 0)),
            pl.BlockSpec((1, l_pad, d), lambda bh, qi: (bh, 0, 0)),
            pl.BlockSpec((1, l_pad, d), lambda bh, qi: (bh, 0, 0)),
            pl.BlockSpec((1, 1, l_pad),
                         lambda bh, qi: (bh // h, 0, 0)),
            pl.BlockSpec((1, 1, tq), lambda bh, qi: (bh // h, 0, qi)),
        ],
        out_specs=[
            pl.BlockSpec((1, tq, d), lambda bh, qi: (bh, qi, 0)),
            pl.BlockSpec((1, 1, tq), lambda bh, qi: (bh, 0, qi)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, l_pad, d), q.dtype),
            jax.ShapeDtypeStruct((b * h, 1, l_pad), jnp.float32),
        ],
        interpret=interpret,
    )(qb, kb, vb, maskb, qmaskb)
    return _from_bh(out, b, l, h, d), lse


def flash_attention_bwd(q, k, v, kv_mask, out, lse, ct, interpret=None,
                        q_mask=None):
    """Pallas backward. Single-block regime: ONE fused kernel recomputes P
    once and emits dQ, dK, dV together. Online regime: P recomputed
    blockwise from (Q, K, LSE); dQ from a Q-block kernel, dK/dV from a
    KV-block kernel."""
    import jax
    from jax.experimental import pallas as pl
    import jax.numpy as jnp

    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    qb, kb, vb, maskb, qmaskb, (b, l, h, d, l_pad) = _prep(
        q, k, v, kv_mask, q_mask)
    dob = _prep_one(ct, l_pad)
    ob = _prep_one(out, l_pad)
    scale = 1.0 / (d ** 0.5)
    # delta_i = sum_d dO_id * O_id, per query row.
    delta = (dob.astype(jnp.float32) * ob.astype(jnp.float32)).sum(
        axis=-1).reshape(b * h, 1, l_pad)

    if _use_onekv(l_pad, d):
        nbh = _nbh_for(h, l_pad)
        spec, spec_mask, spec_row = _onekv_specs(nbh, l_pad, d, h)
        dq, dk, dv = pl.pallas_call(
            functools.partial(_onekv_bwd_kernel, scale=scale, nbh=nbh),
            grid=(b * h // nbh,),
            in_specs=[spec, spec, spec, spec_mask, spec_mask, spec,
                      spec_row, spec_row],
            out_specs=[spec, spec, spec],
            out_shape=[jax.ShapeDtypeStruct((b * h, l_pad, d), t.dtype)
                       for t in (q, k, v)],
            interpret=interpret,
        )(qb, kb, vb, maskb, qmaskb, dob, lse, delta)
        return (_from_bh(dq, b, l, h, d), _from_bh(dk, b, l, h, d),
                _from_bh(dv, b, l, h, d))

    tq, tk = _block_sizes(l_pad)
    assert l_pad % tq == 0 and l_pad % tk == 0, (l_pad, tq, tk)
    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale,
                          n_kv=l_pad // tk, tk=tk),
        grid=(b * h, l_pad // tq),
        in_specs=[
            pl.BlockSpec((1, tq, d), lambda bh, qi: (bh, qi, 0)),   # q
            pl.BlockSpec((1, l_pad, d), lambda bh, qi: (bh, 0, 0)),  # k
            pl.BlockSpec((1, l_pad, d), lambda bh, qi: (bh, 0, 0)),  # v
            pl.BlockSpec((1, 1, l_pad),
                         lambda bh, qi: (bh // h, 0, 0)),            # mask
            pl.BlockSpec((1, 1, tq),
                         lambda bh, qi: (bh // h, 0, qi)),           # qmask
            pl.BlockSpec((1, tq, d), lambda bh, qi: (bh, qi, 0)),   # do
            pl.BlockSpec((1, 1, tq), lambda bh, qi: (bh, 0, qi)),   # lse
            pl.BlockSpec((1, 1, tq), lambda bh, qi: (bh, 0, qi)),   # delta
        ],
        out_specs=pl.BlockSpec((1, tq, d), lambda bh, qi: (bh, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((b * h, l_pad, d), q.dtype),
        interpret=interpret,
    )(qb, kb, vb, maskb, qmaskb, dob, lse, delta)

    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale=scale,
                          n_q=l_pad // tq, tq=tq),
        grid=(b * h, l_pad // tk),
        in_specs=[
            pl.BlockSpec((1, l_pad, d), lambda bh, ki: (bh, 0, 0)),  # q
            pl.BlockSpec((1, tk, d), lambda bh, ki: (bh, ki, 0)),   # k
            pl.BlockSpec((1, tk, d), lambda bh, ki: (bh, ki, 0)),   # v
            pl.BlockSpec((1, 1, tk),
                         lambda bh, ki: (bh // h, 0, ki)),           # mask
            pl.BlockSpec((1, 1, l_pad),
                         lambda bh, ki: (bh // h, 0, 0)),            # qmask
            pl.BlockSpec((1, l_pad, d), lambda bh, ki: (bh, 0, 0)),  # do
            pl.BlockSpec((1, 1, l_pad), lambda bh, ki: (bh, 0, 0)),  # lse
            pl.BlockSpec((1, 1, l_pad), lambda bh, ki: (bh, 0, 0)),  # delta
        ],
        out_specs=[
            pl.BlockSpec((1, tk, d), lambda bh, ki: (bh, ki, 0)),
            pl.BlockSpec((1, tk, d), lambda bh, ki: (bh, ki, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, l_pad, d), k.dtype),
            jax.ShapeDtypeStruct((b * h, l_pad, d), v.dtype),
        ],
        interpret=interpret,
    )(qb, kb, vb, maskb, qmaskb, dob, lse, delta)
    return (_from_bh(dq, b, l, h, d), _from_bh(dk, b, l, h, d),
            _from_bh(dv, b, l, h, d))


# ---------------------------------------------------------------------------
# Single-block ("onekv") kernels: the L_pad <= ONEKV_MAX_L_PAD regime.
#
# Per grid cell, ``nbh`` consecutive (batch, head) rows — all of the SAME
# batch row (dispatch guarantees nbh divides num_heads) — are processed with
# whole-row [L, L] score matrices in VMEM. The -1e9 additive mask (the same
# finite-bias convention as the online kernels and the dense reference —
# never multiply-after-exp, whose raw-score row max lets one disallowed
# outlier key underflow every allowed probability) is built ONCE per cell
# and reused by all nbh rows; the 1/l normalization is folded into the
# [L, D] output instead of the [L, L] probabilities. The backward is one
# fused kernel: P is recomputed once and dQ, dK, dV all emitted from it.
# ---------------------------------------------------------------------------


ONEKV_MAX_L_PAD = 896


def pad_seq_len(l):
    """_prep's padding rule: L pads to the next multiple of 128."""
    return -(-l // 128) * 128


def _use_onekv(l_pad, d):
    """Single-block dispatch: the [L, L] per-row score matrix and the fused
    backward's temporaries must fit VMEM alongside nbh rows of blocks
    (nbh drops to 1 above 512 — see _nbh_for; 896 is the largest l_pad
    whose fused-backward temporaries, ~3 fp32 [L, L] + one bf16 [L, L],
    still compile at nbh=1; 1024 does not fit). The extended 640-896
    range is compile-validated at head_dim 64 only (every BERT/BART
    preset) — wider heads double the per-row blocks on top of the ~11 MB
    of [896, 896] temporaries, so they keep the conservative 512 bound."""
    max_l = ONEKV_MAX_L_PAD if d <= 64 else 512
    return l_pad <= max_l and d <= 128


def single_block_serves(seq_len, head_dim):
    """True when flash_attention will dispatch the single-block kernels for
    this shape AND they are in their measured winning range (l_pad >= 256 —
    dense keeps the shortest bins, MODEL_BENCH.json). The ONE predicate
    models/attention.resolve_auto_impl consults, so the selector can never
    drift from the dispatcher."""
    l_pad = pad_seq_len(seq_len)
    return l_pad >= 256 and _use_onekv(l_pad, head_dim)


def _nbh_for(h, l_pad):
    """Rows per cell: largest of 4/2/1 dividing num_heads so every cell's
    rows share one batch row (mask built once per cell) — but 1 above
    l_pad 512, where a single row's [L, L] fp32 temporaries already take
    ~2.5 MB each and unrolled multi-row cells blow VMEM."""
    if l_pad > 512:
        return 1
    return 4 if h % 4 == 0 else (2 if h % 2 == 0 else 1)


def _dot0(a, b):
    """Contract over axis 0 of both: a [M, N], b [M, D] -> [N, D]."""
    import jax
    import jax.numpy as jnp
    return jax.lax.dot_general(a, b, (((0,), (0,)), ((), ())),
                               preferred_element_type=jnp.float32)


def _cell_bias(mask_ref, qmask_ref):
    """[L, L] additive mask for one cell (rows share the batch row):
    0 where the key is valid AND in the query's segment, -1e9 elsewhere —
    the same finite-bias convention as the online kernels (fp32 min would
    overflow in bf16; exp(-1e9 - m) underflows to an exact 0 probability,
    and an all-masked row softmaxes to the uniform average, matching the
    dense reference)."""
    import jax.numpy as jnp
    msk = mask_ref[0, 0]
    qm = qmask_ref[0, 0]
    allowed = (msk[None, :] > 0) & (msk[None, :] == qm[:, None])
    return jnp.where(allowed, 0.0, -1e9)


def _onekv_fwd_kernel(q_ref, k_ref, v_ref, mask_ref, qmask_ref, o_ref,
                      lse_ref, *, scale, nbh):
    import jax.numpy as jnp

    bias = _cell_bias(mask_ref, qmask_ref)
    for i in range(nbh):
        q = q_ref[i]                             # [L, D], stored dtype
        k = k_ref[i]
        v = v_ref[i]
        s = _dot(q, k, transpose_b=True) * scale + bias  # fp32 [L, L]
        m = s.max(axis=1, keepdims=True)
        p = jnp.exp(s - m)
        l = jnp.maximum(p.sum(axis=1, keepdims=True), 1e-30)
        o = _dot(p.astype(v.dtype), v)            # [L, D] fp32, unnormalized
        o_ref[i] = (o * (1.0 / l)).astype(o_ref.dtype)
        lse_ref[i, 0] = m[:, 0] + jnp.log(l[:, 0])


def _onekv_bwd_kernel(q_ref, k_ref, v_ref, mask_ref, qmask_ref, do_ref,
                      lse_ref, delta_ref, dq_ref, dk_ref, dv_ref, *, scale,
                      nbh):
    import jax.numpy as jnp

    bias = _cell_bias(mask_ref, qmask_ref)
    for i in range(nbh):
        q = q_ref[i]
        k = k_ref[i]
        v = v_ref[i]
        do = do_ref[i]
        lse = lse_ref[i, 0][:, None]             # [L, 1]
        delta = delta_ref[i, 0][:, None]
        s = _dot(q, k, transpose_b=True) * scale + bias
        p = jnp.exp(s - lse)                     # fp32 [Lq, Lk]
        dp = _dot(do, v, transpose_b=True)       # fp32 [Lq, Lk]
        dv_ref[i] = _dot0(p.astype(do.dtype), do).astype(dv_ref.dtype)
        ds = (p * (dp - delta) * scale).astype(k.dtype)
        dq_ref[i] = _dot(ds, k).astype(dq_ref.dtype)
        dk_ref[i] = _dot0(ds, q).astype(dk_ref.dtype)


def _onekv_specs(nbh, l_pad, d, h):
    """(row spec, mask spec) for grid (b*h // nbh,): cell g covers bh rows
    [g*nbh, (g+1)*nbh), all in batch row (g*nbh)//h."""
    from jax.experimental import pallas as pl
    spec = pl.BlockSpec((nbh, l_pad, d), lambda g: (g, 0, 0))
    spec_mask = pl.BlockSpec((1, 1, l_pad), lambda g: (g * nbh // h, 0, 0))
    spec_row = pl.BlockSpec((nbh, 1, l_pad), lambda g: (g, 0, 0))
    return spec, spec_mask, spec_row


_FLASH_VJP = None


def _build_vjp():
    """custom_vjp built on first use (keeps this module importable
    without jax)."""
    global _FLASH_VJP
    if _FLASH_VJP is not None:
        return _FLASH_VJP
    import jax

    @functools.partial(jax.custom_vjp, nondiff_argnums=(5,))
    def fa(q, k, v, kv_mask, q_mask, interpret):
        out, _ = flash_attention_fwd(q, k, v, kv_mask, interpret=interpret,
                                     q_mask=q_mask)
        return out

    def fa_fwd(q, k, v, kv_mask, q_mask, interpret):
        out, lse = flash_attention_fwd(q, k, v, kv_mask,
                                       interpret=interpret, q_mask=q_mask)
        return out, (q, k, v, kv_mask, q_mask, out, lse)

    def fa_bwd(interpret, residuals, ct):
        q, k, v, kv_mask, q_mask, out, lse = residuals
        dq, dk, dv = flash_attention_bwd(q, k, v, kv_mask, out, lse, ct,
                                         interpret=interpret, q_mask=q_mask)
        return dq, dk, dv, None, None

    fa.defvjp(fa_fwd, fa_bwd)
    _FLASH_VJP = fa
    return fa


def flash_attention(q, k, v, kv_mask=None, interpret=None, q_mask=None,
                    segments=None):
    """Differentiable fused attention: pallas forward AND backward (see
    module docstring).

    ``kv_mask`` [B, L] is strictly a BINARY key-padding mask (1 = attend);
    any nonzero value normalizes to 1. For packed rows pass per-token
    segment ids as ``segments=`` — it sets both sides and attention
    becomes block-diagonal per segment (0 = padding). Passing segment ids
    as ``kv_mask`` alone would silently attend across packed segments
    (ADVICE r4), which is why the packed path has its own keyword;
    ``q_mask`` stays for callers composing the two sides explicitly."""
    if segments is not None:
        if kv_mask is not None or q_mask is not None:
            raise ValueError(
                "segments= is exclusive with kv_mask/q_mask: it defines "
                "both sides of the block-diagonal mask")
        kv_mask, q_mask = segments, segments
    elif kv_mask is None:
        raise ValueError("flash_attention needs kv_mask or segments")
    return _build_vjp()(q, k, v, kv_mask, q_mask, interpret)
