"""Shape utilities for TPU-friendly batching of ragged token sequences."""

import numpy as np


def round_up(n, multiple):
    return ((n - 1) // multiple + 1) * multiple


def pad_to_bucket(id_lists, pad_id=0, length_multiple=128, min_length=128):
    """Ragged int lists -> (ids [N, L], valid [N, L]) with L rounded up to
    ``length_multiple`` (TPU lane width) so the jit'd masking kernel sees a
    bounded set of shapes."""
    n = len(id_lists)
    longest = max((len(x) for x in id_lists), default=1)
    L = max(min_length, round_up(longest, length_multiple))
    ids = np.full((n, L), pad_id, dtype=np.int32)
    valid = np.zeros((n, L), dtype=bool)
    for i, x in enumerate(id_lists):
        ids[i, :len(x)] = x
        valid[i, :len(x)] = True
    return ids, valid
