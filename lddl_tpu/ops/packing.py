"""Sequence packing + shape utilities for TPU-friendly batching.

Packing puts several short samples in one fixed-length row, separated by
nothing but their own [CLS]/[SEP] structure, with a *segment id* per token;
attention is restricted to same-segment tokens (block-diagonal mask), so
samples cannot see each other. This reclaims the padding FLOPs that
binning alone leaves behind (3.8% pad at bin-size 64 in LOADER_BENCH) —
the idiomatic fixed-shape TPU move; the reference's Tensor-Core alignment
trick (lddl/torch/bert.py:91-96) is the nearest, much weaker, analogue.

The packer is a *streaming first-fit*: samples arrive in loader order and
drop into the first open row with room; a batch closes when a sample fits
no row. Deterministic (no sort, no RNG), O(rows) per sample, and with
binned shards (similar lengths per batch) it fills rows as tightly as
first-fit-decreasing in practice.

WHEN TO USE (measured on a real v5e chip, PACKING_BENCH.json): packing
beats naive fixed-length padding (+10% useful tokens/s) but LOSES ~10%
to tight per-bin shapes, because block-diagonal attention still computes
the full L^2 score matmuls — rows 4x longer than the samples cost 4x the
attention FLOPs per token, more than the 3-4% pad it reclaims. Default
to binned shards for throughput; pick packing when a SINGLE static shape
is required (pipeline-parallel stages, fixed-shape serving) or when
shards are unbinned.
"""

import numpy as np


def round_up(n, multiple):
    return ((n - 1) // multiple + 1) * multiple


def pad_to_bucket(id_lists, pad_id=0, length_multiple=128, min_length=128):
    """Ragged int lists -> (ids [N, L], valid [N, L]) with L rounded up to
    ``length_multiple`` (TPU lane width) so the jit'd masking kernel sees a
    bounded set of shapes."""
    n = len(id_lists)
    longest = max((len(x) for x in id_lists), default=1)
    L = max(min_length, round_up(longest, length_multiple))
    ids = np.full((n, L), pad_id, dtype=np.int32)
    valid = np.zeros((n, L), dtype=bool)
    for i, x in enumerate(id_lists):
        ids[i, :len(x)] = x
        valid[i, :len(x)] = True
    return ids, valid


class StreamPacker:
    """First-fit packing of a sample stream into fixed-capacity rows, with
    a look-ahead *horizon*: up to ``horizon`` rows stay open at once, and
    when the stream stalls (next sample fits nowhere and the horizon is
    full) only the ``emit_rows`` FULLEST rows are emitted — nearly-empty
    rows stay open to catch later short samples. On the bench length
    distribution this cuts pad from ~5% (close-everything) to ~1.1-1.5%,
    near the distribution's fillability floor.

    ``add(length) -> ordinal or None``: the sample's global stream ordinal
    if placed; None means "emit_fullest() first, then re-add".
    ``emit_fullest()`` / ``flush()`` return layouts
    ``[[(ordinal, length), ...] per row]``; ordinals are global, the
    caller maps them back to its sample store. Deterministic throughout:
    first-fit in creation order, fullest selection ties broken by
    creation order.
    """

    def __init__(self, capacity, emit_rows, max_per_row, horizon=None):
        if max_per_row < 1 or emit_rows < 1:
            raise ValueError("emit_rows and max_per_row must be >= 1")
        self.capacity = capacity
        self.emit_rows = emit_rows
        self.max_per_row = max_per_row
        self.horizon = max(emit_rows, horizon if horizon is not None
                           else 4 * emit_rows)
        self._rows = []       # [[(ordinal, length), ...]]
        self._free = []       # remaining capacity per row
        self._born = []       # creation index per row (tie-break)
        self._next_born = 0
        self._count = 0       # global stream ordinal

    def add(self, length):
        if length > self.capacity:
            raise ValueError(
                "sample of {} tokens exceeds pack capacity {}".format(
                    length, self.capacity))
        for i, free in enumerate(self._free):
            if free >= length and len(self._rows[i]) < self.max_per_row:
                self._rows[i].append((self._count, length))
                self._free[i] -= length
                self._count += 1
                return self._count - 1
        if len(self._rows) < self.horizon:
            self._rows.append([(self._count, length)])
            self._free.append(self.capacity - length)
            self._born.append(self._next_born)
            self._next_born += 1
            self._count += 1
            return self._count - 1
        return None

    def _take(self, indices):
        taken = [self._rows[i] for i in indices]
        keep = [i for i in range(len(self._rows)) if i not in set(indices)]
        self._rows = [self._rows[i] for i in keep]
        self._free = [self._free[i] for i in keep]
        self._born = [self._born[i] for i in keep]
        return taken

    def emit_fullest(self):
        """Remove and return the emit_rows fullest rows (<= emit_rows when
        fewer are open)."""
        order = sorted(range(len(self._rows)),
                       key=lambda i: (self._free[i], self._born[i]))
        return self._take(order[:self.emit_rows])

    def flush(self):
        """Remove and return ALL open rows (end of stream)."""
        return self._take(list(range(len(self._rows))))

    @property
    def open_rows(self):
        return len(self._rows)

    @property
    def sample_count(self):
        return self._count


def packed_layout_arrays(rows, capacity, max_per_row):
    """Packed layout -> numpy index arrays for the collate scatter.

    Returns a dict:
      row_of[s], slot_of[s], offset_of[s]  — per sample (stream order),
      n_rows, and pad_tokens (free capacity summed over rows).
    """
    n_samples = sum(len(r) for r in rows)
    row_of = np.zeros(n_samples, dtype=np.int64)
    slot_of = np.zeros(n_samples, dtype=np.int64)
    offset_of = np.zeros(n_samples, dtype=np.int64)
    pad_tokens = 0
    for ri, row in enumerate(rows):
        off = 0
        if len(row) > max_per_row:
            raise ValueError("row {} holds {} > max_per_row {}".format(
                ri, len(row), max_per_row))
        for si, (ordinal, length) in enumerate(row):
            row_of[ordinal] = ri
            slot_of[ordinal] = si
            offset_of[ordinal] = off
            off += length
        if off > capacity:
            raise ValueError("row {} overflows: {} > {}".format(
                ri, off, capacity))
        pad_tokens += capacity - off
    return {
        "row_of": row_of,
        "slot_of": slot_of,
        "offset_of": offset_of,
        "n_rows": len(rows),
        "pad_tokens": pad_tokens,
    }
