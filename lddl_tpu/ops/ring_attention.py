"""Ring attention: sequence-parallel exact attention over the ``sp`` axis.

Long-context story (SURVEY.md §5): the reference has no attention code at
all; lddl_tpu's model stack ships two sequence-parallel schemes —

- Megatron-SP (models/bert.py default): activations are sequence-sharded
  between blocks and all-gathered into attention. Memory per device for
  the attention inputs is O(L), fine for BERT-scale lengths.
- Ring attention (this module): Q stays sequence-sharded and K/V blocks
  rotate around the ``sp`` ring via ``lax.ppermute`` while an online
  (flash-style) softmax accumulates exact results block by block. No
  device ever materializes the full sequence — O(L/sp) activations and
  O(L^2/sp) score work per device — so max context scales linearly with
  the ring size. Collectives ride ICI; the rotation overlaps with each
  block's compute under XLA's async collectives.

The implementation is XLA-level (shard_map + ppermute + scan), exact (not
an approximation), and reverse-differentiable (scan with static length;
the transpose of ppermute is ppermute). Numerical parity with dense
attention is pinned by tests on a virtual 8-device mesh.

Semantics match models/bert.py's dense path: softmax(QK^T/sqrt(D) + bias)
with bias 0 for valid keys and -1e9 for padding (finite: an all-padded
block must not NaN the online max). Attention-probability dropout is not
applied under ring (the standard choice for blockwise attention kernels);
hidden dropout elsewhere is unaffected.
"""

import functools

# jax imported inside functions: the offline pipeline stages must stay
# importable (via lddl_tpu.ops) on machines where jax is absent/broken.


def _ring_attention_local(q, k, v, kv_mask, axis_name):
    """Per-device body under shard_map.

    q: [B, Lq, H, D] local query block (sequence-sharded)
    k, v: [B, Lk, H, D] local key/value blocks (sequence-sharded)
    kv_mask: [B, Lk] validity of the local keys (1 = attend)
    Returns [B, Lq, H, D].
    """
    import jax
    import jax.numpy as jnp
    ring_size = jax.lax.psum(1, axis_name)
    scale = jnp.float32(1.0) / jnp.sqrt(q.shape[-1]).astype(jnp.float32)

    perm = [(i, (i + 1) % ring_size) for i in range(ring_size)]

    def one_block(carry, is_last):
        k_blk, v_blk, mask_blk, m, l, acc = carry
        # Matmul OPERANDS stay in the stored dtype (bf16 in training) with
        # fp32 ACCUMULATION (preferred_element_type): the MXU runs
        # bf16 x bf16 -> fp32 at full rate but fp32 x fp32 at ~1/4 rate —
        # the same measured fix as ops/flash_attention.py. The running
        # max/denominator arithmetic stays fp32 (flash recipe).
        scores = jnp.einsum("bqhd,bkhd->bhqk", q, k_blk,
                            preferred_element_type=jnp.float32) * scale
        bias = jnp.where(mask_blk[:, None, None, :] > 0, 0.0, -1e9)
        s = scores + bias
        m_new = jnp.maximum(m, s.max(axis=-1))
        p = jnp.exp(s - m_new[..., None])
        corr = jnp.exp(m - m_new)
        l_new = l * corr + p.sum(axis=-1)
        acc_new = (acc * corr[..., None]
                   + jnp.einsum("bhqk,bkhd->bhqd", p.astype(v_blk.dtype),
                                v_blk,
                                preferred_element_type=jnp.float32))
        # The last block's rotation would only be discarded: skip it
        # (1/ring_size of the ring traffic).
        k_nxt, v_nxt, mask_nxt = jax.lax.cond(
            is_last,
            lambda ops: ops,
            lambda ops: tuple(jax.lax.ppermute(o, axis_name, perm)
                              for o in ops),
            (k_blk, v_blk, mask_blk))
        return (k_nxt, v_nxt, mask_nxt, m_new, l_new, acc_new), None

    b, lq, h, d = q.shape
    m0 = jnp.full((b, h, lq), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((b, h, lq), jnp.float32)
    acc0 = jnp.zeros((b, h, lq, d), jnp.float32)
    is_last = jnp.arange(ring_size) == ring_size - 1
    (_, _, _, _, l, acc), _ = jax.lax.scan(
        one_block, (k, v, kv_mask, m0, l0, acc0), is_last)
    out = acc / jnp.maximum(l, 1e-30)[..., None]
    return out.transpose(0, 2, 1, 3).astype(q.dtype)


def ring_attention(q, k, v, kv_mask, mesh, seq_axis="sp", batch_axes=None,
                   heads_axis="tp"):
    """Exact attention with Q/K/V sequence-sharded over ``seq_axis``.

    q/k/v: [B, L, H, D] (global); kv_mask: [B, L] (1 = attend). The
    arrays' layout is constrained to (batch, seq-sharded, heads, :) and
    the ring runs under shard_map; XLA never gathers the full sequence.
    """
    import jax
    from jax.sharding import PartitionSpec as P
    if batch_axes is None:
        batch_axes = tuple(a for a in ("dp", "fsdp") if a in mesh.axis_names)
    heads = heads_axis if heads_axis in mesh.axis_names else None
    qkv_spec = P(batch_axes if batch_axes else None, seq_axis, heads, None)
    mask_spec = P(batch_axes if batch_axes else None, seq_axis)
    from ..parallel import compat
    fn = compat.shard_map(
        functools.partial(_ring_attention_local, axis_name=seq_axis),
        mesh=mesh,
        in_specs=(qkv_spec, qkv_spec, qkv_spec, mask_spec),
        out_specs=qkv_spec,
        check_vma=False,
    )
    return fn(q, k, v, kv_mask)


def dense_attention_reference(q, k, v, kv_mask):
    """The unsharded computation ring_attention must reproduce (same bias
    semantics as models/bert.py)."""
    import jax
    import jax.numpy as jnp
    scale = 1.0 / jnp.sqrt(q.shape[-1]).astype(jnp.float32)
    scores = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                        k.astype(jnp.float32)) * scale
    bias = jnp.where(kv_mask[:, None, None, :] > 0, 0.0, -1e9)
    probs = jax.nn.softmax(scores + bias, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", probs, v.astype(jnp.float32))
    return out.astype(q.dtype)
