from .masking import (
    plan_num_to_predict,
    mask_batch_numpy,
    mask_batch_jax,
    make_jax_masker,
)
from .packing import pad_to_bucket, round_up

__all__ = [
    "plan_num_to_predict",
    "mask_batch_numpy",
    "mask_batch_jax",
    "make_jax_masker",
    "pad_to_bucket",
    "round_up",
]
