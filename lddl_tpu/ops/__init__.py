from .masking import (
    plan_num_to_predict,
    mask_batch_numpy,
    mask_batch_jax,
    mask_whole_word_batch_numpy,
    make_jax_masker,
    make_jax_whole_word_masker,
)
from .packing import pad_to_bucket, round_up
from .flash_attention import flash_attention
from .ring_attention import dense_attention_reference, ring_attention

__all__ = [
    "plan_num_to_predict",
    "mask_batch_numpy",
    "mask_batch_jax",
    "mask_whole_word_batch_numpy",
    "make_jax_masker",
    "make_jax_whole_word_masker",
    "pad_to_bucket",
    "round_up",
    "ring_attention",
    "flash_attention",
    "dense_attention_reference",
]
