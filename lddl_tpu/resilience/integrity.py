"""Shard integrity manifests: per-shard byte length + CRC32.

The preprocessor and balancer publish a ``.manifest.json`` next to
``.num_samples.json`` in every shard directory; the loader verifies it at
startup. A truncated shard (torn GCS-fuse write, partial copy) is then a
loud, *named* startup decision — ``on_corrupt="fail"`` (default) or
``"quarantine"`` (exclude the shard, recompute counts from the survivors,
log the exclusion) — instead of an opaque pyarrow error mid-epoch or a
silently short epoch.

Manifest construction is SPMD like everything else: ranks checksum a
strided subset of shards, one sum-allreduce merges (each entry is computed
by exactly one rank, so the sum IS the value), rank 0 atomically publishes.

Env knobs::

    LDDL_TPU_MANIFEST=0     skip manifest emission (saves the extra read
                            pass on very large outputs)
    LDDL_TPU_VERIFY_CRC=1   loader startup re-hashes every shard (full
                            read) instead of only checking byte lengths
"""

import json
import os
import zlib

from . import faults
from .io import atomic_write, with_retries

MANIFEST_NAME = ".manifest.json"

_CHUNK = 1 << 20


class ShardIntegrityError(RuntimeError):
    pass


def shard_checksum(path):
    """(byte_length, crc32) of a file, streamed in 1 MiB chunks with
    transient-error retries (a retry restarts the whole checksum — CRC
    state cannot survive a torn read)."""

    def _sum():
        faults.fault_point("open", path)
        crc = 0
        nbytes = 0
        with open(path, "rb") as f:
            while True:
                action = faults.fault_point("read", path)
                chunk = f.read(_CHUNK)
                if action == "truncate":
                    # Injected torn read: checksum a chopped stream.
                    chunk = chunk[:max(0, len(chunk) // 2 - 1)]
                    crc = zlib.crc32(chunk, crc)
                    nbytes += len(chunk)
                    break
                if not chunk:
                    break
                crc = zlib.crc32(chunk, crc)
                nbytes += len(chunk)
        return nbytes, crc & 0xFFFFFFFF

    return with_retries(_sum, desc="checksum {}".format(path))


def _parquet_basenames(dir_path):
    from ..utils.fs import _is_parquet_path
    try:
        names = sorted(os.listdir(dir_path))
    except OSError:
        return []
    return [n for n in names if _is_parquet_path(n)]


def read_manifest(dir_path):
    """The {basename: {"bytes": n, "crc32": c}} manifest of a shard
    directory, or None when absent/unreadable (older data has none)."""
    path = os.path.join(dir_path, MANIFEST_NAME)
    try:
        with open(path, "r") as f:
            manifest = json.load(f)
    except (OSError, ValueError):
        return None
    return manifest if isinstance(manifest, dict) else None


def build_manifest(dir_path, comm=None, log=None, extra_meta=None):
    """Checksum every parquet shard directly in ``dir_path`` (rank-strided)
    and publish the manifest from rank 0.

    ``LDDL_TPU_MANIFEST`` modes: ``full`` (default; stat sizes + one CRC32
    read pass over this rank's stride), ``size`` (stat only — zero extra
    reads, for multi-TB outputs where the CRC pass is too expensive; the
    loader then verifies lengths only), ``0`` (skip entirely).

    ``extra_meta`` merges additional keys into the reserved ``__meta__``
    entry — the streaming-ingestion publisher records the latest
    generation number and per-generation shard lists there (the loader's
    generation-pickup gate). The caller must pass deterministic content
    only; manifest bytes are resume-compared."""
    mode = os.environ.get("LDDL_TPU_MANIFEST", "full")
    if mode == "0":
        return None
    if mode not in ("full", "size", "1"):
        mode = "full"
    from ..observability import span as obs_span
    from ..parallel.distributed import LocalCommunicator
    comm = comm or LocalCommunicator()
    names = _parquet_basenames(dir_path)
    if not names and not extra_meta:
        return None
    with obs_span("resilience.build_manifest", mode=mode, shards=len(names)):
        return _build_manifest(dir_path, comm, names, mode, log, extra_meta)


def _shard_schema_info(path):
    """(token-id schema version 1|2, packed row shape or None) off one
    shard's parquet footer, or (None, None) when the footer is unreadable
    (the verifier's problem to report, not the meta sniffer's)."""
    import pyarrow as pa
    import pyarrow.parquet as pq
    from ..preprocess.binning import schema_version_of_names
    from ..preprocess.packing import pack_shape_of_schema
    try:
        schema = pq.read_schema(path)
    except (OSError, pa.ArrowInvalid):
        return None, None
    return schema_version_of_names(schema.names), pack_shape_of_schema(schema)


def _build_manifest(dir_path, comm, names, mode, log, extra_meta=None):
    sizes = [0] * len(names)
    crcs = [0] * len(names)
    vflags = [0, 0]  # token-id schema v1 / v2 seen on this rank's stride
    # Packed-shape homogeneity accumulators, allreduce-sum friendly:
    # [sum L, sum L^2, sum P, sum P^2, packed shards, unpacked shards].
    # After the allreduce, the shape is recorded iff every readable shard
    # is packed AND the (L, P) variance is zero — each index is
    # contributed by exactly one stride owner, so the sums are exact.
    pstats = [0, 0, 0, 0, 0, 0]
    for i in range(comm.rank, len(names), comm.world_size):
        path = os.path.join(dir_path, names[i])
        if mode == "size":
            sizes[i] = with_retries(
                lambda p=path: os.stat(p).st_size, desc="stat " + path)
        else:
            # Sizes come from the checksum pass's byte count so a file
            # mutated mid-pass can't record a size/crc from two versions.
            sizes[i], crcs[i] = shard_checksum(path)
        if mode != "size":
            # Schema sniff rides the same stride (one footer read per
            # shard across the whole pod, not per rank). size mode's
            # contract is stat-only / zero extra reads, so it skips the
            # sniff and publishes no __meta__ — like it skips the CRC.
            v, pack_shape = _shard_schema_info(path)
            if v is not None:
                vflags[v - 1] = 1
                if pack_shape is not None:
                    L, P = pack_shape
                    pstats[0] += L
                    pstats[1] += L * L
                    pstats[2] += P
                    pstats[3] += P * P
                    pstats[4] += 1
                else:
                    pstats[5] += 1
    sizes = comm.allreduce_sum(sizes)
    crcs = comm.allreduce_sum(crcs)
    vflags = comm.allreduce_sum(vflags)
    pstats = [int(x) for x in comm.allreduce_sum(pstats)]
    manifest = {
        n: ({"bytes": int(s), "crc32": int(c)} if mode != "size"
            else {"bytes": int(s)})
        for n, s, c in zip(names, sizes, crcs)
    }
    # Reserved __meta__ entry (never a parquet basename, so shard lookups
    # skip it): {"schema_version": 1|2} when every readable shard agrees,
    # {"schema_versions": [1, 2]} for a mixed directory (supported: the
    # loader selects its decode path per shard).
    versions = [v for v, flag in zip((1, 2), vflags) if flag]
    if len(versions) == 1:
        manifest["__meta__"] = {"schema_version": versions[0]}
    elif versions:
        manifest["__meta__"] = {"schema_versions": versions}
    n_packed = pstats[4]
    if n_packed and not pstats[5] \
            and pstats[1] * n_packed == pstats[0] * pstats[0] \
            and pstats[3] * n_packed == pstats[2] * pstats[2]:
        # Every readable shard is packed with one (L, P): record the row
        # shape — the loader's zero-copy packed-path detection gate.
        from ..preprocess.packing import pack_meta_of
        manifest.setdefault("__meta__", {})["packed"] = pack_meta_of(
            pstats[0] // n_packed, pstats[2] // n_packed)
    if extra_meta:
        manifest.setdefault("__meta__", {}).update(extra_meta)
    if comm.rank == 0:
        atomic_write(os.path.join(dir_path, MANIFEST_NAME),
                     json.dumps(manifest, sort_keys=True))
    comm.barrier()
    if log is not None:
        log("integrity manifest: {} shard(s) in {}".format(
            len(names), dir_path))
    return manifest


def _check_one_shard(path, entry, check_crc):
    """None if the shard matches its manifest entry, else the reason.
    Transient storage errors retry (a startup blip must not read as
    corruption); a shard that stays unreadable past the deadline IS
    flagged — with the OSError as the reason."""

    def _stat():
        faults.fault_point("open", path)
        return os.stat(path).st_size

    try:
        actual_bytes = with_retries(_stat, desc="stat {}".format(path))
    except OSError as e:
        return "unreadable: {}".format(e)
    if actual_bytes != entry.get("bytes"):
        return "size mismatch: manifest says {} bytes, found {}".format(
            entry.get("bytes"), actual_bytes)
    if check_crc and entry.get("crc32") is not None:
        # (size-mode manifests carry no crc32 — nothing to re-hash.)
        _, crc = shard_checksum(path)
        if crc != entry.get("crc32"):
            return ("crc32 mismatch: manifest says {:#010x}, "
                    "found {:#010x}".format(entry.get("crc32"), crc))
    return None


def verify_shards(file_paths, on_corrupt="fail", check_crc=None, log=None,
                  comm=None):
    """Verify shards against their directories' manifests at startup.

    Returns ``(good_paths, excluded)`` where ``excluded`` is a list of
    ``(path, reason)``. Shards without a manifest entry (or in a directory
    with no manifest at all — older data) are trusted as-is. Byte lengths
    are always checked (one retried ``stat`` per shard); full CRC
    re-hashing is opt-in via ``check_crc=True`` or ``LDDL_TPU_VERIFY_CRC=1``.

    With a multi-rank ``comm``, checks stripe across ranks (a pod does one
    collective read pass, not world_size of them) and the verdict bitmap
    is allreduced, so every rank excludes the IDENTICAL shard set even if
    only one rank observed the corruption — rank-divergent shard lists
    would desync the SPMD epoch.

    ``on_corrupt="fail"`` raises ShardIntegrityError naming every corrupt
    shard; ``"quarantine"`` excludes them (loudly) so startup proceeds on
    the survivors and the caller's balance accounting stays explicit.
    """
    if on_corrupt not in ("fail", "quarantine"):
        raise ValueError(
            "on_corrupt must be 'fail' or 'quarantine', got {!r}".format(
                on_corrupt))
    if check_crc is None:
        check_crc = os.environ.get("LDDL_TPU_VERIFY_CRC", "0") == "1"
    from ..observability import span as obs_span
    from ..parallel.distributed import LocalCommunicator
    comm = comm or LocalCommunicator()
    with obs_span("resilience.verify_shards", shards=len(file_paths),
                  check_crc=check_crc):
        return _verify_shards(file_paths, on_corrupt, check_crc, log, comm)


def _verify_shards(file_paths, on_corrupt, check_crc, log, comm):
    manifests = {}
    for d in {os.path.dirname(p) for p in file_paths}:
        manifests[d] = read_manifest(d)

    flags = [0] * len(file_paths)
    reasons = {}
    for i in range(comm.rank, len(file_paths), comm.world_size):
        path = file_paths[i]
        manifest = manifests[os.path.dirname(path)]
        entry = manifest.get(os.path.basename(path)) if manifest else None
        if not entry:
            continue
        reason = _check_one_shard(path, entry, check_crc)
        if reason is not None:
            flags[i] = 1
            reasons[i] = reason
    if comm.world_size > 1:
        flags = [int(f) for f in comm.allreduce_sum(flags)]

    good, excluded = [], []
    for i, path in enumerate(file_paths):
        if flags[i]:
            excluded.append((path, reasons.get(
                i, "flagged corrupt by another rank's strided check")))
        else:
            good.append(path)

    if excluded:
        from ..observability import event as obs_event
        from ..observability import inc as obs_inc
        obs_inc("resilience_corrupt_shards_total", len(excluded))
        for p, r in excluded:
            obs_event("resilience.corrupt_shard", path=p, reason=r[:200],
                      policy=on_corrupt)
        lines = ["  {} -- {}".format(p, r) for p, r in excluded]
        if on_corrupt == "fail":
            raise ShardIntegrityError(
                "{} corrupt shard(s) detected (on_corrupt=fail):\n{}\n"
                "Re-run the producing stage, or start with "
                "on_corrupt='quarantine' to exclude them.".format(
                    len(excluded), "\n".join(lines)))
        obs_inc("resilience_quarantined_shards_total", len(excluded))
        msg = ("QUARANTINED {} corrupt shard(s); continuing on {} "
               "surviving shard(s):\n{}".format(
                   len(excluded), len(good), "\n".join(lines)))
        if log is not None:
            log(msg)
        import warnings
        warnings.warn(msg, stacklevel=2)
    return good, excluded
