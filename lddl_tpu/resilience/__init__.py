"""Fault-injection harness + resilient I/O + shard integrity.

Three small layers the whole pipeline rides:

- ``faults``    — env-armed fault injector (EIO/ESTALE/truncate/slow/kill)
                  with fault points inside every guarded I/O primitive.
- ``io``        — ``with_retries`` (backoff + jitter + deadline, transient
                  OSErrors only) and the sanctioned atomic/durable write
                  and resilient read primitives.
- ``integrity`` — per-shard byte-length + CRC32 manifests written by the
                  preprocessor and balancer, verified by the loader with a
                  fail/quarantine policy. (Imported lazily by consumers —
                  it depends on utils/ and parallel/, unlike faults/io
                  which are stdlib-only.)
- ``leases``    — atomic-rename lease files with epoch fencing: the
                  coordination layer the elastic work-stealing preprocess
                  runner claims its units through (any host may die
                  mid-unit and be reclaimed by the survivors).
"""

from . import faults
from . import leases
from .io import (
    TRANSIENT_ERRNOS,
    atomic_publish,
    atomic_write,
    is_transient,
    open_append,
    read_bytes,
    read_table,
    retry_policy,
    with_retries,
    write_table_atomic,
)

__all__ = [
    "faults",
    "leases",
    "TRANSIENT_ERRNOS",
    "atomic_publish",
    "atomic_write",
    "is_transient",
    "open_append",
    "read_bytes",
    "read_table",
    "retry_policy",
    "with_retries",
    "write_table_atomic",
]
