"""Atomic-rename lease protocol for multi-host work stealing.

N independent host processes share nothing but the output directory
(GCS-fuse/NFS — the same medium the shards ride). Each work unit gets one
lease file ``<out>/_leases/<unit>.json`` carrying ``(holder, epoch,
deadline)``:

- **acquire**: a missing lease is claimed by writing a holder-unique temp
  file and hard-linking it into place (``os.link`` fails with EEXIST if
  someone else got there first — the classic NFS-safe exclusive create;
  filesystems without link support fall back to ``O_CREAT|O_EXCL``).
- **renew**: the holder republishes the lease with a pushed-out deadline
  via tmp + ``os.replace`` (:func:`resilience.io.atomic_publish`), then
  reads it back; a mismatch means the lease was stolen (`LeaseLost`).
- **steal**: anyone may replace an EXPIRED lease, bumping the **epoch**.
  Replace + read-back does not serialize concurrent stealers perfectly —
  two may transiently both believe they won — and that is fine *by
  design*: mutual exclusion here is an efficiency lever, never the
  correctness mechanism.
- **fence**: correctness comes from epoch fencing at publish time. Before
  journaling a completed unit, the holder re-reads the lease and publishes
  ONLY if ``(holder, epoch)`` still match; a stalled-then-resurrected
  holder sees the bumped epoch, discards its late result, and
  self-terminates the unit (``lease_fence_rejects_total``). Unit outputs
  that cannot be replaced idempotently (scatter spool appends) additionally
  carry ``(epoch, holder)`` in their file names, so a loser's debris is
  never read and never collides with the winner's files.

Lease files are scheduling state, never data: nothing in them (holder id,
epoch, wall-clock deadline) may flow into shard bytes or
``.manifest.json`` content — machine-checked by the analyzer's
``lease-isolation`` flow rule. Deadlines are wall-clock on purpose (the
one cross-host time base a shared filesystem gives us); this module is
the single place the pipeline reads the wall clock for control flow, and
it is allowlisted for exactly that.

Chaos sites: ``lease-acquire`` / ``lease-renew`` / ``lease-release`` fault
points fire at the guarded operations; the ``stall`` fault kind freezes a
renewal past the deadline to force a steal (see ``faults.py``).

**CAS backends** (``LDDL_TPU_STORAGE_BACKEND=mock`` — resilience/
backend.py): on a store with conditional put, acquire/renew/steal become
compare-and-swap on the lease object's generation instead of replace +
read-back — create is ``put_if_match(..., None)``, renew/steal are
``put_if_match(..., gen_read)``, release is ``delete_if_match``. The
fence stays a precondition check, so the exactly-once proof carries: a
fenced loser's conditional put FAILS (``CASConflict`` → ``LeaseLost``)
instead of a read-back mismatching after the fact, and concurrent
stealers are perfectly serialized (exactly one conditional put per
generation wins — strictly stronger than the replace race the local
protocol tolerates by design). Epoch semantics, counters, fleet events,
the deadline cache, and every fault site are identical across backends.
"""

import json
import logging
import os
import re
import socket
import threading
import time
import uuid

from . import backend as storage
from . import faults
from . import io as rio
from ..observability import event as obs_event
from ..observability import fleet
from ..observability import inc as obs_inc

LEASE_DIR = "_leases"

_log = logging.getLogger("lddl_tpu.resilience.leases")

_SAFE_RE = re.compile(r"[^A-Za-z0-9_.-]+")


def legacy_coordination():
    """True when ``LDDL_TPU_COORD_LEGACY=1`` pins the pre-batched
    coordination paths (per-lease keeper renewals with read-back,
    unsnapshotted claim-loop scans, barrier gather). Kept so benchmarks
    can measure the batched protocol against its ancestor honestly and
    tests can compare the two for byte identity."""
    return os.environ.get("LDDL_TPU_COORD_LEGACY", "") == "1"


def _op(kind):
    """Count one lease-file filesystem operation. ``lease_ops_total`` is
    the coordination-cost headline (ISSUE 15): every lease read, publish,
    exclusive create, unlink, and directory scan increments it exactly
    once, on the legacy and batched paths alike, so the ratio between the
    two is an apples-to-apples count of FS round trips."""
    obs_inc("lease_ops_total", op=kind)


class LeaseLost(RuntimeError):
    """The lease was stolen (epoch bumped / holder replaced) out from
    under its holder; the unit in flight must be self-terminated."""


class Lease(object):
    """One held lease. ``lost`` is flipped by the keeper thread when a
    renewal discovers the lease was stolen; the claim loop checks it (and
    re-verifies on disk) before publishing the unit. ``gen`` is the lease
    object's storage generation on CAS backends (None on the local
    atomic-rename protocol): every conditional renew chains off the
    generation the previous operation returned."""

    __slots__ = ("root", "unit", "holder", "epoch", "deadline", "lost",
                 "gen")

    def __init__(self, root, unit, holder, epoch, deadline, gen=None):
        self.root = root
        self.unit = unit
        self.holder = holder
        self.epoch = epoch
        self.deadline = deadline
        self.lost = False
        self.gen = gen

    @property
    def path(self):
        return lease_path(self.root, self.unit)

    def __repr__(self):
        return "Lease({}@{} epoch={})".format(self.unit, self.holder,
                                              self.epoch)


def default_holder():
    """Unique-per-process holder id: hostname + pid + a random tag (a
    respawned process recycling a pid must not mistake its dead
    predecessor's lease for its own). Lease-file state only — never data."""
    return sanitize_holder("{}-{}-{}".format(
        socket.gethostname(), os.getpid(), uuid.uuid4().hex[:6]))


def sanitize_holder(holder):
    """Holder ids land in file names (lease temps, scatter spool files);
    restrict them to a safe charset."""
    safe = _SAFE_RE.sub("-", str(holder)).strip("-")
    if not safe:
        raise ValueError("holder id {!r} is empty after sanitizing".format(
            holder))
    return safe


def lease_root(out_dir):
    return os.path.join(out_dir, LEASE_DIR)


def lease_path(root, unit):
    return os.path.join(root, "{}.json".format(unit))


def read_lease(root, unit):
    """The current lease record for ``unit``, or None when absent.

    Reads ride :func:`resilience.io.read_bytes` (transient-error retries +
    fault injection). A torn/empty record — possible only through storage
    misbehaviour, every writer publishes complete temp files — reads as an
    expired epoch-0 lease with a warning, so a flaky byte never wedges the
    scheduler; the fence still protects the ledger."""
    path = lease_path(root, unit)
    _op("read")
    rec, status = rio.read_json(path)
    if status == "missing":
        return None
    if status == "ok" and isinstance(rec, dict):
        return rec
    _log.warning("torn/unparseable lease file %s; treating as expired",
                 path)
    obs_inc("lease_torn_reads_total")
    return {"unit": unit, "holder": "", "epoch": 0, "deadline": 0.0,
            "torn": True}


def _record(unit, holder, epoch, deadline):
    return {"unit": unit, "holder": holder, "epoch": int(epoch),
            "deadline": float(deadline)}


def _write_tmp(path, rec, holder):
    """Fully write a holder-unique temp next to ``path`` (unique name: two
    hosts — or two threads — publishing the same lease can never interleave
    bytes in a shared temp the way a pid-keyed name could)."""
    tmp = "{}.tmp.{}".format(path, holder)
    # Pre-publish scratch with a holder-unique name, promoted only via
    # os.link / atomic_publish below; a torn temp is never trusted.
    with open(tmp, "wb") as f:  # lddl: disable=atomic-publish
        f.write(json.dumps(rec, sort_keys=True).encode("utf-8"))
        f.flush()
        os.fsync(f.fileno())
    return tmp


def _cleanup_tmp(tmp):
    try:
        os.unlink(tmp)
    except FileNotFoundError:
        pass


def _matches(rec, holder, epoch):
    return (rec is not None and rec.get("holder") == holder
            and rec.get("epoch") == epoch)


def _try_create(path, rec, holder):
    """Exclusive create of a fresh lease file. ``os.link`` is atomic and
    fails loudly on EEXIST even on NFS; filesystems that refuse hard links
    fall back to O_CREAT|O_EXCL (fine everywhere the fallback runs: a FUSE
    mount without link support is also not an NFSv2 mount)."""
    _op("create")
    tmp = _write_tmp(path, rec, holder)
    try:
        try:
            os.link(tmp, path)
            return True
        except FileExistsError:
            return False
        # Deliberate fallthrough, not a swallow: EPERM/ENOTSUP here means
        # the mount refuses hard links; the O_EXCL path below performs the
        # same exclusive create. -- lddl: disable=swallowed-error
        except OSError:
            pass
        try:
            fd = os.open(path, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
        except FileExistsError:
            return False
        try:
            with open(tmp, "rb") as f:
                os.write(fd, f.read())
        finally:
            os.close(fd)
        return True
    finally:
        _cleanup_tmp(tmp)


def _publish(path, rec, holder):
    """Replace the lease file with a fully-written record (tmp + fsync +
    ``os.replace`` + dir fsync via resilience.io)."""
    _op("publish")
    tmp = _write_tmp(path, rec, holder)
    try:
        rio.atomic_publish(tmp, path)
    finally:
        _cleanup_tmp(tmp)


# ------------------------------------------------ CAS-backend primitives

def _cas_backend():
    """The active CAS-capable storage backend, or None when the default
    LocalBackend is active (the atomic-rename protocol below is the
    local path — unchanged, byte for byte)."""
    bk = storage.get_backend()
    return bk if bk.is_cas else None


def _read_lease_versioned(bk, root, unit):
    """CAS read: ``(record, generation)`` for the unit's lease object, or
    ``(None, None)`` when absent. Torn bytes map to the same expired
    epoch-0 record as :func:`read_lease` — but keep their generation, so
    the subsequent steal is still a conditional put."""
    path = lease_path(root, unit)
    _op("read")
    data, gen = rio.with_retries(lambda: bk.get_versioned(path),
                                 desc="lease get {}".format(path))
    if data is None:
        return None, None
    try:
        rec = json.loads(data)
    except ValueError:
        rec = None
    if isinstance(rec, dict):
        return rec, gen
    _log.warning("torn/unparseable lease object %s; treating as expired",
                 path)
    obs_inc("lease_torn_reads_total")
    return {"unit": unit, "holder": "", "epoch": 0, "deadline": 0.0,
            "torn": True}, gen


def _cas_put(bk, path, rec, expected_gen, kind):
    """One conditional lease put (create when ``expected_gen`` is None).
    Transient store errors retry through the classifier; a
    :class:`backend.CASConflict` propagates — precondition loss is the
    protocol signal, never a retry candidate."""
    _op(kind)
    data = json.dumps(rec, sort_keys=True).encode("utf-8")
    return rio.with_retries(
        lambda: bk.put_if_match(path, data, expected_gen),
        desc="lease cas-put {}".format(path))


def _try_acquire_cas(bk, root, unit, holder, ttl_s, now, held_cache,
                     known_missing):
    """CAS-backend claim: the same state machine as the local path below,
    with conditional puts serializing what replace + read-back only
    narrows. A conflict anywhere means another claimant won — count it
    and stand down (the next pass re-reads)."""
    path = lease_path(root, unit)
    if known_missing:
        cur, gen = None, None
    else:
        cur, gen = _read_lease_versioned(bk, root, unit)
    if cur is None:
        rec = _record(unit, holder, 0, now + ttl_s)
        try:
            g = _cas_put(bk, path, rec, None, "create")
        except storage.CASConflict:
            obs_inc("lease_acquire_conflicts_total")
            return None
        obs_inc("lease_acquires_total")
        fleet.record("unit.claimed", unit=str(unit), epoch=0,
                     holder=holder)
        return Lease(root, unit, holder, 0, rec["deadline"], gen=g)
    if float(cur.get("deadline", 0.0)) > now and not cur.get("torn"):
        if held_cache is not None:
            held_cache[unit] = float(cur.get("deadline", 0.0))
        obs_inc("lease_acquire_conflicts_total")
        return None
    new_epoch = int(cur.get("epoch", 0)) + 1
    rec = _record(unit, holder, new_epoch, now + ttl_s)
    try:
        g = _cas_put(bk, path, rec, gen, "publish")
    except storage.CASConflict:
        obs_inc("lease_acquire_conflicts_total")
        return None
    obs_inc("lease_acquires_total")
    obs_inc("lease_steals_total")
    obs_event("lease.steal", unit=str(unit), epoch=new_epoch,
              prev_holder=str(cur.get("holder", "")))
    fleet.record("unit.stolen", unit=str(unit), epoch=new_epoch,
                 holder=holder, prev_holder=str(cur.get("holder", "")))
    return Lease(root, unit, holder, new_epoch, rec["deadline"], gen=g)


def _renew_cas(bk, lease, ttl_s, now_fn):
    """CAS-backend renewal: read → fence-match → conditional put. No
    read-back on any path — the conditional put IS the read-back: a
    concurrent replace between our read and our put surfaces as
    :class:`backend.CASConflict`, i.e. the fence tripping as a
    precondition instead of after the fact."""
    cur, gen = _read_lease_versioned(bk, lease.root, lease.unit)
    if not _matches(cur, lease.holder, lease.epoch):
        lease.lost = True
        raise LeaseLost("lease for unit {} was stolen (now {})".format(
            lease.unit, cur))
    rec = _record(lease.unit, lease.holder, lease.epoch,
                  now_fn() + ttl_s)
    try:
        lease.gen = _cas_put(bk, lease.path, rec, gen, "publish")
    except storage.CASConflict:
        lease.lost = True
        raise LeaseLost("lease for unit {} lost during renewal "
                        "(CAS precondition)".format(lease.unit))
    lease.deadline = rec["deadline"]
    obs_inc("lease_renews_total")
    fleet.record("unit.renewed", unit=str(lease.unit), epoch=lease.epoch,
                 holder=lease.holder)
    return lease


def scan_units(root):
    """One directory scan of the lease root: the set of unit keys that
    currently have a lease file (tmp debris excluded), or None when the
    root itself is gone (finalized/absent). A single scan stands in for
    per-unit existence reads — the amortization both the batched keeper
    pass and the claim loop's per-pass snapshot ride."""
    _op("scan")
    bk = _cas_backend()
    if bk is not None:
        names = bk.list(root)
        if names is None:
            return None
        return {n[:-len(".json")] for n in names if n.endswith(".json")}
    try:
        names = sorted(os.listdir(root))
    except (FileNotFoundError, NotADirectoryError):
        return None
    return {n[:-len(".json")] for n in names
            if n.endswith(".json") and ".tmp." not in n}


def try_acquire(root, unit, holder, ttl_s, now_fn=time.time,
                known_missing=False, held_cache=None):
    """Claim ``unit``: returns a :class:`Lease` on success, None when the
    unit is validly held by someone else (or a race was lost).

    A missing lease is created exclusively at epoch 0. An expired (or
    torn) lease is **stolen**: the epoch is bumped and the record
    replaced, then read back — only the claimant whose bytes survived the
    replace race proceeds. The read-back does not make concurrent steals
    perfectly exclusive; the publish-time fence does (module docstring).

    Two amortization knobs (both safe to omit):

    - ``known_missing=True`` — the caller's per-pass :func:`scan_units`
      snapshot showed no lease file, so skip the initial read and go
      straight to the exclusive create; a racer who created one since the
      scan just fails the create and falls back to the read path.
    - ``held_cache`` — a ``{unit: deadline}`` dict the caller threads
      through its passes. A valid-held conflict records the observed
      deadline; later calls for the same unit return None without any
      filesystem read until that deadline has passed. The wall-clock
      comparison stays inside this module (the one allowlisted clock
      consumer); a cached skip is not an acquire attempt, so it counts
      neither ops nor conflicts.
    """
    now = now_fn()
    if held_cache is not None:
        cached = held_cache.get(unit)
        if cached is not None:
            if cached > now:
                return None
            held_cache.pop(unit, None)
    os.makedirs(root, exist_ok=True)
    path = lease_path(root, unit)
    faults.fault_point("lease-acquire", path)
    bk = _cas_backend()
    if bk is not None:
        return _try_acquire_cas(bk, root, unit, holder, ttl_s, now,
                                held_cache, known_missing)
    cur = None if known_missing else read_lease(root, unit)
    if cur is None:
        rec = _record(unit, holder, 0, now + ttl_s)
        if _try_create(path, rec, holder):
            if not legacy_coordination():
                # The exclusive create succeeded, so the bytes on disk are
                # ours and nobody may validly steal them before the fresh
                # deadline: the legacy read-back can only confirm that.
                # The one race it narrowed — a thief who read a stale
                # EXPIRED record, lost it to a release-unlink, and then
                # replaces our newborn file — leaves two hosts transiently
                # believing they won, which the module docstring already
                # declares fine by design: the publish-time fence picks
                # one winner, the loser's work is the only cost.
                obs_inc("lease_acquires_total")
                fleet.record("unit.claimed", unit=str(unit), epoch=0,
                             holder=holder)
                return Lease(root, unit, holder, 0, rec["deadline"])
            got = read_lease(root, unit)
            if _matches(got, holder, 0):
                obs_inc("lease_acquires_total")
                fleet.record("unit.claimed", unit=str(unit), epoch=0,
                             holder=holder)
                return Lease(root, unit, holder, 0, rec["deadline"])
            obs_inc("lease_acquire_conflicts_total")
            return None
        if not known_missing:
            obs_inc("lease_acquire_conflicts_total")
            return None
        # The snapshot was stale (someone created the lease since the
        # scan): re-enter through the normal read path.
        cur = read_lease(root, unit)
        if cur is None:
            # Created then already released/swept between our two looks;
            # treat as a lost race rather than spinning here.
            obs_inc("lease_acquire_conflicts_total")
            return None
    if float(cur.get("deadline", 0.0)) > now and not cur.get("torn"):
        # Validly held (possibly by a past incarnation of ourselves — a
        # claim loop never double-claims, so "held by my id" is equally
        # a conflict here).
        if held_cache is not None:
            held_cache[unit] = float(cur.get("deadline", 0.0))
        obs_inc("lease_acquire_conflicts_total")
        return None
    new_epoch = int(cur.get("epoch", 0)) + 1
    rec = _record(unit, holder, new_epoch, now + ttl_s)
    _publish(path, rec, holder)
    got = read_lease(root, unit)
    if _matches(got, holder, new_epoch):
        obs_inc("lease_acquires_total")
        obs_inc("lease_steals_total")
        obs_event("lease.steal", unit=str(unit), epoch=new_epoch,
                  prev_holder=str(cur.get("holder", "")))
        fleet.record("unit.stolen", unit=str(unit), epoch=new_epoch,
                     holder=holder, prev_holder=str(cur.get("holder", "")))
        return Lease(root, unit, holder, new_epoch, rec["deadline"])
    obs_inc("lease_acquire_conflicts_total")
    return None


def renew(lease, ttl_s, now_fn=time.time):
    """Push the deadline out by ``ttl_s``. Raises :class:`LeaseLost` when
    the on-disk record no longer names this holder+epoch (stolen while we
    stalled). The ``lease-renew`` fault site fires BEFORE the read, so an
    injected ``stall`` freezes the renewal long enough for the deadline to
    pass and a steal to land — exactly the scenario the fence exists for."""
    path = lease.path
    faults.fault_point("lease-renew", path)
    bk = _cas_backend()
    if bk is not None:
        return _renew_cas(bk, lease, ttl_s, now_fn)
    cur = read_lease(lease.root, lease.unit)
    if not _matches(cur, lease.holder, lease.epoch):
        lease.lost = True
        raise LeaseLost("lease for unit {} was stolen (now {})".format(
            lease.unit, cur))
    rec = _record(lease.unit, lease.holder, lease.epoch, now_fn() + ttl_s)
    _publish(path, rec, lease.holder)
    got = read_lease(lease.root, lease.unit)
    if not _matches(got, lease.holder, lease.epoch):
        lease.lost = True
        raise LeaseLost("lease for unit {} lost during renewal".format(
            lease.unit))
    lease.deadline = rec["deadline"]
    obs_inc("lease_renews_total")
    fleet.record("unit.renewed", unit=str(lease.unit), epoch=lease.epoch,
                 holder=lease.holder)
    return lease


def renew_fast(lease, ttl_s, now_fn=time.time):
    """Batched-keeper renewal: read → fence-match → publish, with NO
    read-back. The read-back in :func:`renew` only narrows (never closes)
    the replace race — the publish-time fence plus the next keeper pass's
    read give the same guarantee one FS round trip cheaper, which is the
    point of the batched pass. Counters and fleet events are identical to
    :func:`renew`; the ``lease-renew`` fault site still fires first, so
    the chaos suite's forced-stall steal scenario is unchanged. On a CAS
    backend renew and renew_fast are the same operation — the conditional
    put already carries the read-back's guarantee for free."""
    path = lease.path
    faults.fault_point("lease-renew", path)
    bk = _cas_backend()
    if bk is not None:
        return _renew_cas(bk, lease, ttl_s, now_fn)
    cur = read_lease(lease.root, lease.unit)
    if not _matches(cur, lease.holder, lease.epoch):
        lease.lost = True
        raise LeaseLost("lease for unit {} was stolen (now {})".format(
            lease.unit, cur))
    rec = _record(lease.unit, lease.holder, lease.epoch, now_fn() + ttl_s)
    _publish(path, rec, lease.holder)
    lease.deadline = rec["deadline"]
    obs_inc("lease_renews_total")
    fleet.record("unit.renewed", unit=str(lease.unit), epoch=lease.epoch,
                 holder=lease.holder)
    return lease


def verify(lease):
    """Fence check: True iff the on-disk lease still names this holder AND
    epoch. Run immediately before journaling a completed unit; False means
    the unit was reclaimed and this result must be discarded."""
    if lease.lost:
        return False
    return verify_at(lease.root, lease.unit, lease.holder, lease.epoch)


def is_live(root, unit, now_fn=time.time):
    """True while SOME host validly holds ``unit`` (unexpired, untorn
    lease) — i.e. the unit is actively being worked on. Used by the
    claim loop's failure-patience logic: a host must not declare the run
    failed while another live host is still redoing the unit (the
    wall-clock comparison lives here so steal.py stays clock-free)."""
    rec = read_lease(root, unit)
    return (rec is not None and not rec.get("torn")
            and float(rec.get("deadline", 0.0)) > now_fn())


def verify_at(root, unit, holder, epoch):
    """Stateless fence check for code that cannot carry a Lease object
    across a process boundary (pool workers): True iff the on-disk lease
    for ``unit`` names exactly (holder, epoch). Workers call this between
    sub-steps to self-terminate a stolen unit early instead of wasting
    work (and, crucially, instead of writing outputs derived from state a
    finalizer may already be deleting)."""
    return _matches(read_lease(root, unit), holder, epoch)


def fence_at(root, unit, holder, epoch, deadline=0.0, now_fn=time.time):
    """A deadline-cached fence closure over :func:`verify_at`, for unit
    bodies that re-check their lease between sub-steps.

    The protocol forbids stealing an unexpired lease (:func:`try_acquire`
    refuses a record whose deadline is ahead), so while the wall clock is
    strictly inside the last deadline this fence READ — seeded with the
    claim-time ``deadline`` when the caller knows it — the on-disk record
    provably still names ``(holder, epoch)`` and the closure answers True
    with no filesystem op. At/past the cached deadline it re-reads,
    refreshing the cache from the record the keeper's renewals have been
    pushing out; a mismatch is final (epochs never revert). A stall long
    enough to let a thief in necessarily carries the wall past the cached
    deadline too, so the first post-stall call is a real read and the
    fence trips exactly where an every-call read would have tripped it.
    Legacy coordination pins every call to a real read. The wall-clock
    comparison stays in this module (the allowlisted clock consumer)."""
    state = {"deadline": float(deadline), "ok": True}
    legacy = legacy_coordination()

    def check():
        if not state["ok"]:
            return False
        if not legacy and now_fn() < state["deadline"]:
            return True
        rec = read_lease(root, unit)
        if not _matches(rec, holder, epoch):
            state["ok"] = False
            return False
        state["deadline"] = float(rec.get("deadline", 0.0))
        return True

    return check


def still_held(lease, now_fn=time.time):
    """Deadline-aware pre-publish look at a held lease: False when the
    keeper already flagged it lost; True WITHOUT a filesystem read while
    the wall clock is strictly inside the last deadline this process
    acquired/renewed to (an unexpired lease cannot be validly stolen, so
    a read could only confirm ownership); a real :func:`verify` read past
    the deadline or under legacy coordination. Advisory only — the
    correctness fence is the post-publish re-verify inside the unit
    record publishers, which always reads."""
    if lease.lost:
        return False
    if not legacy_coordination() and now_fn() < lease.deadline:
        return True
    return verify(lease)


def release(lease, now_fn=time.time):
    """Drop a completed unit's lease (verified unlink; inside the deadline
    the verify read is skipped). Best-effort: the unit's ledger record is
    the durable completion signal — claim loops check the ledger before
    the lease — so a leftover lease file is inert and gets swept with the
    rest of ``_leases/`` at finalize."""
    faults.fault_point("lease-release", lease.path)
    if lease.lost:
        return
    bk = _cas_backend()
    if bk is not None:
        # Conditional delete chained off our last-known generation; a
        # conflict means a keeper renewal advanced it concurrently —
        # re-read once and retry, then give up (a leftover lease object
        # is inert, same as a leftover lease file).
        for _ in range(2):
            cur, gen = _read_lease_versioned(bk, lease.root, lease.unit)
            if not _matches(cur, lease.holder, lease.epoch):
                return
            _op("unlink")
            try:
                rio.with_retries(
                    lambda g=gen: bk.delete_if_match(lease.path, g),
                    desc="lease delete {}".format(lease.path))
            except storage.CASConflict:
                continue
            obs_inc("lease_releases_total")
            return
        return
    if not legacy_coordination() and now_fn() < lease.deadline:
        # An unexpired lease cannot have been validly stolen, so the
        # pre-unlink verify read could only confirm the record is ours.
        # Should a clock-skewed early thief have replaced it anyway, the
        # unlink drops the thief's lease: for a journaled unit (ledger
        # publishes BEFORE release) the thief's own post-acquire ledger
        # re-check retires the duplicate attempt; otherwise the thief
        # merely loses the efficiency lever and the publish-time fence
        # picks one winner, as for any concurrent-claim race.
        _op("unlink")
        try:
            os.unlink(lease.path)
        except FileNotFoundError:
            pass
        obs_inc("lease_releases_total")
        return
    if verify(lease):
        _op("unlink")
        try:
            os.unlink(lease.path)
        except FileNotFoundError:
            pass
        obs_inc("lease_releases_total")


class LeaseKeeper(object):
    """One background thread renewing every lease this host holds, at
    ``ttl/3``. A renewal that discovers a steal marks ``lease.lost`` (and
    stops renewing it); the claim loop's fence does the rest. Transient
    storage errors are retried inside the lease I/O; anything else is
    conservatively treated as lost — without renewals the lease expires
    anyway, and redoing a unit is always safe."""

    def __init__(self, ttl_s):
        self.ttl_s = ttl_s
        self._leases = set()
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread = None

    def add(self, lease):
        with self._lock:
            self._leases.add(lease)
            if self._thread is None:
                self._thread = threading.Thread(
                    target=self._run, name="lease-keeper", daemon=True)
                self._thread.start()

    def remove(self, lease):
        with self._lock:
            self._leases.discard(lease)

    def stop(self):
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5.0)

    def _run(self):
        period = max(self.ttl_s / 3.0, 0.05)
        legacy = legacy_coordination()
        while not self._stop.wait(period):
            with self._lock:
                held = list(self._leases)
            if legacy:
                for lease in held:
                    if lease.lost:
                        continue
                    self._renew_one(lease, renew)
                continue
            # Batched pass: one directory scan per lease root answers
            # "does my file still exist" for every held lease at once; a
            # lease missing from the scan was stolen-then-released (or the
            # run finalized) — the same on-disk states a legacy renew()
            # would discover one read at a time. Survivors renew via
            # renew_fast (read + publish, no read-back): 1 + 2n FS ops per
            # pass instead of 3n.
            by_root = {}
            for lease in held:
                if not lease.lost:
                    by_root.setdefault(lease.root, []).append(lease)
            for root, group in by_root.items():
                try:
                    present = scan_units(root)
                except Exception as e:  # noqa: BLE001 - class docstring
                    _log.warning("lease scan of %s failed (%s: %s); "
                                 "renewing individually", root,
                                 type(e).__name__, e)
                    present = None
                    scan_failed = True
                else:
                    scan_failed = False
                for lease in group:
                    if (not scan_failed and (
                            present is None
                            or str(lease.unit) not in present)):
                        lease.lost = True
                        self._mark_lost(lease)
                        continue
                    self._renew_one(lease, renew_fast)

    def _renew_one(self, lease, renew_fn):
        try:
            renew_fn(lease, self.ttl_s)
        except LeaseLost:
            self._mark_lost(lease)
        except Exception as e:  # noqa: BLE001 - see class docstring
            lease.lost = True
            _log.warning("lease renewal for unit %s failed (%s: %s); "
                         "treating as lost", lease.unit,
                         type(e).__name__, e)

    @staticmethod
    def _mark_lost(lease):
        obs_event("lease.lost", unit=str(lease.unit), epoch=lease.epoch)
        fleet.record("unit.lost", unit=str(lease.unit), epoch=lease.epoch,
                     holder=lease.holder)
        _log.warning("lease for unit %s stolen at epoch %s; in-flight "
                     "result will be fenced off", lease.unit, lease.epoch)
