"""Atomic-rename lease protocol for multi-host work stealing.

N independent host processes share nothing but the output directory
(GCS-fuse/NFS — the same medium the shards ride). Each work unit gets one
lease file ``<out>/_leases/<unit>.json`` carrying ``(holder, epoch,
deadline)``:

- **acquire**: a missing lease is claimed by writing a holder-unique temp
  file and hard-linking it into place (``os.link`` fails with EEXIST if
  someone else got there first — the classic NFS-safe exclusive create;
  filesystems without link support fall back to ``O_CREAT|O_EXCL``).
- **renew**: the holder republishes the lease with a pushed-out deadline
  via tmp + ``os.replace`` (:func:`resilience.io.atomic_publish`), then
  reads it back; a mismatch means the lease was stolen (`LeaseLost`).
- **steal**: anyone may replace an EXPIRED lease, bumping the **epoch**.
  Replace + read-back does not serialize concurrent stealers perfectly —
  two may transiently both believe they won — and that is fine *by
  design*: mutual exclusion here is an efficiency lever, never the
  correctness mechanism.
- **fence**: correctness comes from epoch fencing at publish time. Before
  journaling a completed unit, the holder re-reads the lease and publishes
  ONLY if ``(holder, epoch)`` still match; a stalled-then-resurrected
  holder sees the bumped epoch, discards its late result, and
  self-terminates the unit (``lease_fence_rejects_total``). Unit outputs
  that cannot be replaced idempotently (scatter spool appends) additionally
  carry ``(epoch, holder)`` in their file names, so a loser's debris is
  never read and never collides with the winner's files.

Lease files are scheduling state, never data: nothing in them (holder id,
epoch, wall-clock deadline) may flow into shard bytes or
``.manifest.json`` content — machine-checked by the analyzer's
``lease-isolation`` flow rule. Deadlines are wall-clock on purpose (the
one cross-host time base a shared filesystem gives us); this module is
the single place the pipeline reads the wall clock for control flow, and
it is allowlisted for exactly that.

Chaos sites: ``lease-acquire`` / ``lease-renew`` / ``lease-release`` fault
points fire at the guarded operations; the ``stall`` fault kind freezes a
renewal past the deadline to force a steal (see ``faults.py``).
"""

import json
import logging
import os
import re
import socket
import threading
import time
import uuid

from . import faults
from . import io as rio
from ..observability import event as obs_event
from ..observability import fleet
from ..observability import inc as obs_inc

LEASE_DIR = "_leases"

_log = logging.getLogger("lddl_tpu.resilience.leases")

_SAFE_RE = re.compile(r"[^A-Za-z0-9_.-]+")


class LeaseLost(RuntimeError):
    """The lease was stolen (epoch bumped / holder replaced) out from
    under its holder; the unit in flight must be self-terminated."""


class Lease(object):
    """One held lease. ``lost`` is flipped by the keeper thread when a
    renewal discovers the lease was stolen; the claim loop checks it (and
    re-verifies on disk) before publishing the unit."""

    __slots__ = ("root", "unit", "holder", "epoch", "deadline", "lost")

    def __init__(self, root, unit, holder, epoch, deadline):
        self.root = root
        self.unit = unit
        self.holder = holder
        self.epoch = epoch
        self.deadline = deadline
        self.lost = False

    @property
    def path(self):
        return lease_path(self.root, self.unit)

    def __repr__(self):
        return "Lease({}@{} epoch={})".format(self.unit, self.holder,
                                              self.epoch)


def default_holder():
    """Unique-per-process holder id: hostname + pid + a random tag (a
    respawned process recycling a pid must not mistake its dead
    predecessor's lease for its own). Lease-file state only — never data."""
    return sanitize_holder("{}-{}-{}".format(
        socket.gethostname(), os.getpid(), uuid.uuid4().hex[:6]))


def sanitize_holder(holder):
    """Holder ids land in file names (lease temps, scatter spool files);
    restrict them to a safe charset."""
    safe = _SAFE_RE.sub("-", str(holder)).strip("-")
    if not safe:
        raise ValueError("holder id {!r} is empty after sanitizing".format(
            holder))
    return safe


def lease_root(out_dir):
    return os.path.join(out_dir, LEASE_DIR)


def lease_path(root, unit):
    return os.path.join(root, "{}.json".format(unit))


def read_lease(root, unit):
    """The current lease record for ``unit``, or None when absent.

    Reads ride :func:`resilience.io.read_bytes` (transient-error retries +
    fault injection). A torn/empty record — possible only through storage
    misbehaviour, every writer publishes complete temp files — reads as an
    expired epoch-0 lease with a warning, so a flaky byte never wedges the
    scheduler; the fence still protects the ledger."""
    path = lease_path(root, unit)
    rec, status = rio.read_json(path)
    if status == "missing":
        return None
    if status == "ok" and isinstance(rec, dict):
        return rec
    _log.warning("torn/unparseable lease file %s; treating as expired",
                 path)
    obs_inc("lease_torn_reads_total")
    return {"unit": unit, "holder": "", "epoch": 0, "deadline": 0.0,
            "torn": True}


def _record(unit, holder, epoch, deadline):
    return {"unit": unit, "holder": holder, "epoch": int(epoch),
            "deadline": float(deadline)}


def _write_tmp(path, rec, holder):
    """Fully write a holder-unique temp next to ``path`` (unique name: two
    hosts — or two threads — publishing the same lease can never interleave
    bytes in a shared temp the way a pid-keyed name could)."""
    tmp = "{}.tmp.{}".format(path, holder)
    # Pre-publish scratch with a holder-unique name, promoted only via
    # os.link / atomic_publish below; a torn temp is never trusted.
    with open(tmp, "wb") as f:  # lddl: disable=atomic-publish
        f.write(json.dumps(rec, sort_keys=True).encode("utf-8"))
        f.flush()
        os.fsync(f.fileno())
    return tmp


def _cleanup_tmp(tmp):
    try:
        os.unlink(tmp)
    except FileNotFoundError:
        pass


def _matches(rec, holder, epoch):
    return (rec is not None and rec.get("holder") == holder
            and rec.get("epoch") == epoch)


def _try_create(path, rec, holder):
    """Exclusive create of a fresh lease file. ``os.link`` is atomic and
    fails loudly on EEXIST even on NFS; filesystems that refuse hard links
    fall back to O_CREAT|O_EXCL (fine everywhere the fallback runs: a FUSE
    mount without link support is also not an NFSv2 mount)."""
    tmp = _write_tmp(path, rec, holder)
    try:
        try:
            os.link(tmp, path)
            return True
        except FileExistsError:
            return False
        # Deliberate fallthrough, not a swallow: EPERM/ENOTSUP here means
        # the mount refuses hard links; the O_EXCL path below performs the
        # same exclusive create. -- lddl: disable=swallowed-error
        except OSError:
            pass
        try:
            fd = os.open(path, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
        except FileExistsError:
            return False
        try:
            with open(tmp, "rb") as f:
                os.write(fd, f.read())
        finally:
            os.close(fd)
        return True
    finally:
        _cleanup_tmp(tmp)


def _publish(path, rec, holder):
    """Replace the lease file with a fully-written record (tmp + fsync +
    ``os.replace`` + dir fsync via resilience.io)."""
    tmp = _write_tmp(path, rec, holder)
    try:
        rio.atomic_publish(tmp, path)
    finally:
        _cleanup_tmp(tmp)


def try_acquire(root, unit, holder, ttl_s, now_fn=time.time):
    """Claim ``unit``: returns a :class:`Lease` on success, None when the
    unit is validly held by someone else (or a race was lost).

    A missing lease is created exclusively at epoch 0. An expired (or
    torn) lease is **stolen**: the epoch is bumped and the record
    replaced, then read back — only the claimant whose bytes survived the
    replace race proceeds. The read-back does not make concurrent steals
    perfectly exclusive; the publish-time fence does (module docstring)."""
    os.makedirs(root, exist_ok=True)
    path = lease_path(root, unit)
    faults.fault_point("lease-acquire", path)
    cur = read_lease(root, unit)
    now = now_fn()
    if cur is None:
        rec = _record(unit, holder, 0, now + ttl_s)
        if _try_create(path, rec, holder):
            got = read_lease(root, unit)
            if _matches(got, holder, 0):
                obs_inc("lease_acquires_total")
                fleet.record("unit.claimed", unit=str(unit), epoch=0,
                             holder=holder)
                return Lease(root, unit, holder, 0, rec["deadline"])
        obs_inc("lease_acquire_conflicts_total")
        return None
    if float(cur.get("deadline", 0.0)) > now and not cur.get("torn"):
        # Validly held (possibly by a past incarnation of ourselves — a
        # claim loop never double-claims, so "held by my id" is equally
        # a conflict here).
        obs_inc("lease_acquire_conflicts_total")
        return None
    new_epoch = int(cur.get("epoch", 0)) + 1
    rec = _record(unit, holder, new_epoch, now + ttl_s)
    _publish(path, rec, holder)
    got = read_lease(root, unit)
    if _matches(got, holder, new_epoch):
        obs_inc("lease_acquires_total")
        obs_inc("lease_steals_total")
        obs_event("lease.steal", unit=str(unit), epoch=new_epoch,
                  prev_holder=str(cur.get("holder", "")))
        fleet.record("unit.stolen", unit=str(unit), epoch=new_epoch,
                     holder=holder, prev_holder=str(cur.get("holder", "")))
        return Lease(root, unit, holder, new_epoch, rec["deadline"])
    obs_inc("lease_acquire_conflicts_total")
    return None


def renew(lease, ttl_s, now_fn=time.time):
    """Push the deadline out by ``ttl_s``. Raises :class:`LeaseLost` when
    the on-disk record no longer names this holder+epoch (stolen while we
    stalled). The ``lease-renew`` fault site fires BEFORE the read, so an
    injected ``stall`` freezes the renewal long enough for the deadline to
    pass and a steal to land — exactly the scenario the fence exists for."""
    path = lease.path
    faults.fault_point("lease-renew", path)
    cur = read_lease(lease.root, lease.unit)
    if not _matches(cur, lease.holder, lease.epoch):
        lease.lost = True
        raise LeaseLost("lease for unit {} was stolen (now {})".format(
            lease.unit, cur))
    rec = _record(lease.unit, lease.holder, lease.epoch, now_fn() + ttl_s)
    _publish(path, rec, lease.holder)
    got = read_lease(lease.root, lease.unit)
    if not _matches(got, lease.holder, lease.epoch):
        lease.lost = True
        raise LeaseLost("lease for unit {} lost during renewal".format(
            lease.unit))
    lease.deadline = rec["deadline"]
    obs_inc("lease_renews_total")
    fleet.record("unit.renewed", unit=str(lease.unit), epoch=lease.epoch,
                 holder=lease.holder)
    return lease


def verify(lease):
    """Fence check: True iff the on-disk lease still names this holder AND
    epoch. Run immediately before journaling a completed unit; False means
    the unit was reclaimed and this result must be discarded."""
    if lease.lost:
        return False
    return verify_at(lease.root, lease.unit, lease.holder, lease.epoch)


def is_live(root, unit, now_fn=time.time):
    """True while SOME host validly holds ``unit`` (unexpired, untorn
    lease) — i.e. the unit is actively being worked on. Used by the
    claim loop's failure-patience logic: a host must not declare the run
    failed while another live host is still redoing the unit (the
    wall-clock comparison lives here so steal.py stays clock-free)."""
    rec = read_lease(root, unit)
    return (rec is not None and not rec.get("torn")
            and float(rec.get("deadline", 0.0)) > now_fn())


def verify_at(root, unit, holder, epoch):
    """Stateless fence check for code that cannot carry a Lease object
    across a process boundary (pool workers): True iff the on-disk lease
    for ``unit`` names exactly (holder, epoch). Workers call this between
    sub-steps to self-terminate a stolen unit early instead of wasting
    work (and, crucially, instead of writing outputs derived from state a
    finalizer may already be deleting)."""
    return _matches(read_lease(root, unit), holder, epoch)


def release(lease):
    """Drop a completed unit's lease (verified unlink). Best-effort: the
    unit's ledger record is the durable completion signal — claim loops
    check the ledger before the lease — so a leftover lease file is inert
    and gets swept with the rest of ``_leases/`` at finalize."""
    faults.fault_point("lease-release", lease.path)
    if verify(lease):
        try:
            os.unlink(lease.path)
        except FileNotFoundError:
            pass
        obs_inc("lease_releases_total")


class LeaseKeeper(object):
    """One background thread renewing every lease this host holds, at
    ``ttl/3``. A renewal that discovers a steal marks ``lease.lost`` (and
    stops renewing it); the claim loop's fence does the rest. Transient
    storage errors are retried inside the lease I/O; anything else is
    conservatively treated as lost — without renewals the lease expires
    anyway, and redoing a unit is always safe."""

    def __init__(self, ttl_s):
        self.ttl_s = ttl_s
        self._leases = set()
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread = None

    def add(self, lease):
        with self._lock:
            self._leases.add(lease)
            if self._thread is None:
                self._thread = threading.Thread(
                    target=self._run, name="lease-keeper", daemon=True)
                self._thread.start()

    def remove(self, lease):
        with self._lock:
            self._leases.discard(lease)

    def stop(self):
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5.0)

    def _run(self):
        period = max(self.ttl_s / 3.0, 0.05)
        while not self._stop.wait(period):
            with self._lock:
                held = list(self._leases)
            for lease in held:
                if lease.lost:
                    continue
                try:
                    renew(lease, self.ttl_s)
                except LeaseLost:
                    obs_event("lease.lost", unit=str(lease.unit),
                              epoch=lease.epoch)
                    fleet.record("unit.lost", unit=str(lease.unit),
                                 epoch=lease.epoch, holder=lease.holder)
                    _log.warning("lease for unit %s stolen at epoch %s; "
                                 "in-flight result will be fenced off",
                                 lease.unit, lease.epoch)
                except Exception as e:  # noqa: BLE001 - see class docstring
                    lease.lost = True
                    _log.warning("lease renewal for unit %s failed (%s: "
                                 "%s); treating as lost", lease.unit,
                                 type(e).__name__, e)
