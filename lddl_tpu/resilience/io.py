"""Resilient I/O primitives: transient-error retries + durable atomic writes.

TPU pods mount their shards over GCS-fuse/NFS (see preprocess/runner.py),
where transient ``EIO``/``ESTALE``/timeout errors are a fact of life and a
crash between ``write()`` and ``rename()`` can durably publish a torn file.
This module is the single place the pipeline does either of:

- retrying: ``with_retries`` wraps an operation in exponential backoff +
  jitter + a total deadline, retrying ONLY transient OSErrors — a
  ``FileNotFoundError`` or ``PermissionError`` fails immediately.
- publishing: ``atomic_write``/``atomic_publish`` are the only sanctioned
  ways to place a file into a shard directory (tmp in the same directory,
  fsync the file, ``os.replace``, fsync the directory) — enforced by a
  lint-style test over the whole package (tests/test_resilience.py).

Every primitive calls ``faults.fault_point`` at its guarded operations, so
the chaos harness can inject failures into real pipeline runs.

These primitives are also the pipeline's storage-backend seam
(``resilience/backend.py``): under the default LocalBackend every branch
below is the pre-backend POSIX code verbatim (zero new syscalls — the
dispatch check is one env-dict lookup); under ``LDDL_TPU_STORAGE_BACKEND=
mock`` publishes become multipart-upload-then-commit against the mock
object store and reads resolve through its versioned commit records.

Env knobs (all optional)::

    LDDL_TPU_RETRY_ATTEMPTS      max attempts per operation (default 5)
    LDDL_TPU_RETRY_DEADLINE_S    total time budget per operation (default 60)
    LDDL_TPU_RETRY_BASE_DELAY_S  first backoff delay (default 0.05)
    LDDL_TPU_RETRY_MAX_DELAY_S   backoff cap (default 2.0)
"""

import errno
import json
import os
import random
import time

from . import backend as _backend
from . import faults
from ..observability import enabled as obs_enabled
from ..observability import event as obs_event
from ..observability import fleet
from ..observability import inc as obs_inc

# OSError errnos considered transient on shared storage: worth retrying.
TRANSIENT_ERRNOS = frozenset(
    getattr(errno, name) for name in (
        "EIO", "ESTALE", "EAGAIN", "EINTR", "EBUSY", "ETIMEDOUT",
        "ECONNRESET", "ECONNABORTED", "ENETRESET", "EHOSTUNREACH",
        "ENOBUFS", "EREMOTEIO",
    ) if hasattr(errno, name))


def is_transient(exc):
    """True for OSErrors that plausibly heal on retry (flaky NFS/GCS-fuse),
    False for everything else (missing file, permissions, logic bugs)."""
    return isinstance(exc, OSError) and exc.errno in TRANSIENT_ERRNOS


def _env_float(name, default):
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default


def retry_policy():
    """The active retry knobs as a dict (also documented in README)."""
    return {
        "attempts": int(_env_float("LDDL_TPU_RETRY_ATTEMPTS", 5)),
        "deadline_s": _env_float("LDDL_TPU_RETRY_DEADLINE_S", 60.0),
        "base_delay_s": _env_float("LDDL_TPU_RETRY_BASE_DELAY_S", 0.05),
        "max_delay_s": _env_float("LDDL_TPU_RETRY_MAX_DELAY_S", 2.0),
    }


def _mock_backend():
    """The active non-POSIX backend, or None under the default
    LocalBackend (whose hot path is the inline pre-backend code below,
    not a dispatch — the check costs one env-dict lookup)."""
    if (os.environ.get(_backend.ENV_VAR) or "local") == "local":
        return None
    return _backend.get_backend()


def _lat_start():
    """Latency-timing start marker, or None with telemetry disarmed (the
    disabled hot path stays one env lookup — no clock read)."""
    return time.perf_counter() if obs_enabled() else None


def _lat_end(t0, op):
    """Close a latency interval into the per-{backend,op} histogram."""
    if t0 is not None:
        _backend.observe_latency(_backend.active_name(), op,
                                 time.perf_counter() - t0)


_jitter_rng = random.Random()


def with_retries(fn, desc="operation", attempts=None, deadline_s=None,
                 base_delay_s=None, max_delay_s=None, retryable=is_transient):
    """Run ``fn()`` with exponential backoff + jitter + a total deadline.

    Retries only exceptions for which ``retryable(exc)`` is true (by
    default: transient OSErrors). The final failure re-raises the LAST
    error with the attempt history attached to its message via
    ``raise ... from`` chaining.

    Every retry reports to the observability registry
    (``resilience_retry_attempts_total{op=...}`` + a trace instant event;
    exhaustion increments ``resilience_retry_exhausted_total``) — the
    previously invisible retry traffic is the telemetry, the named OSError
    below stays the failure contract.
    """
    policy = retry_policy()
    attempts = attempts if attempts is not None else policy["attempts"]
    deadline_s = (deadline_s if deadline_s is not None
                  else policy["deadline_s"])
    base = (base_delay_s if base_delay_s is not None
            else policy["base_delay_s"])
    cap = max_delay_s if max_delay_s is not None else policy["max_delay_s"]
    t0 = time.monotonic()
    attempt = 0
    while True:
        attempt += 1
        try:
            return fn()
        except Exception as e:  # noqa: BLE001 - filtered by retryable()
            if not retryable(e):
                raise
            elapsed = time.monotonic() - t0
            op = desc.split(" ", 1)[0]
            if attempt >= attempts or elapsed >= deadline_s:
                obs_inc("resilience_retry_exhausted_total", op=op)
                fleet.record("io.retry_exhausted", op=op,
                             error="{}: {}".format(type(e).__name__,
                                                   e)[:200])
                raise OSError(
                    getattr(e, "errno", None) or errno.EIO,
                    "{} failed after {} attempt(s) over {:.1f}s: {}".format(
                        desc, attempt, elapsed, e),
                    getattr(e, "filename", None)) from e
            delay = min(cap, base * (2 ** (attempt - 1)))
            # Backoff jitter only shapes WHEN a retry runs, never what any
            # rank writes or reads — an unkeyed stream is the point here
            # (keyed jitter would synchronize retry storms across ranks).
            delay *= _jitter_rng.uniform(0.5, 1.5)  # lddl: disable=rng-flow
            delay = min(delay, max(0.0, deadline_s - elapsed))
            obs_inc("resilience_retry_attempts_total", op=op)
            obs_event("resilience.retry", op=op, attempt=attempt,
                      error="{}: {}".format(type(e).__name__, e)[:200])
            time.sleep(delay)


def _fsync_dir(path):
    """Flush a directory entry (the rename itself) to stable storage.
    Transient errors (flaky NFS/GCS-fuse EIO) retry through the
    classifier like every neighboring durable-path op — previously a
    single transient EIO silently SKIPPED the dir fsync, a durability
    hole where the completed replace could evaporate on power loss.
    Terminal refusals stay best-effort: some filesystems (FAT, some FUSE
    mounts) refuse directory fsync, and a refusal must not undo a
    completed replace."""
    dirname = os.path.dirname(os.path.abspath(path)) or "."

    def _sync():
        fd = os.open(dirname, os.O_RDONLY)
        try:
            os.fsync(fd)
        finally:
            os.close(fd)

    try:
        with_retries(_sync, desc="fsync dir {}".format(dirname))
    # Deliberate best-effort swallow (see docstring): only non-transient
    # refusals and exhausted transients land here, after the classifier
    # has already retried everything retryable. -- lddl: disable=swallowed-error
    except OSError:
        pass


def atomic_publish(tmp_path, path, fsync_file=True):
    """Atomically move a fully-written temp file into place: fsync the
    file's bytes, ``os.replace`` into the target name, fsync the directory
    so the rename itself is durable. The ONLY sanctioned publish primitive
    (with atomic_write) for files in shard directories.

    On the mock object store there is no rename: the temp's bytes are
    published via multipart-upload-then-commit and the temp is consumed
    (unlinked) to keep the caller contract identical."""
    bk = _mock_backend()
    t0 = _lat_start()
    if bk is not None:
        bk.put_file(tmp_path, path)
        try:
            os.unlink(tmp_path)
        except OSError:
            pass
        _lat_end(t0, "put")
        return
    if fsync_file:
        fd = os.open(tmp_path, os.O_RDONLY)
        try:
            os.fsync(fd)
        finally:
            os.close(fd)
    faults.fault_point("replace", path)
    os.replace(tmp_path, path)
    _fsync_dir(path)
    _backend.count("local", "put", "ok")
    _lat_end(t0, "put")


def atomic_write(path, data, retries=True):
    """Durably and atomically write ``data`` (bytes or str) to ``path``.

    A crash at any point leaves either the complete old file or the
    complete new file — never a torn or empty one (tmp + fsync file +
    ``os.replace`` + fsync dir). Transient storage errors are retried;
    the temp file is always cleaned up on failure.
    """
    if isinstance(data, str):
        data = data.encode("utf-8")
    tmp = "{}.tmp.{}".format(path, os.getpid())

    def _write():
        faults.fault_point("open", path)
        try:
            with open(tmp, "wb") as f:
                f.write(data)
                f.flush()
                os.fsync(f.fileno())
            atomic_publish(tmp, path, fsync_file=False)
        finally:
            if os.path.exists(tmp):
                try:
                    os.unlink(tmp)
                except OSError:
                    pass

    if retries:
        return with_retries(_write, desc="atomic_write {}".format(path))
    return _write()


def atomic_copy(src, path, retries=True):
    """Atomically publish an existing durable file at ``path`` without
    loading it into memory.

    Fast path: hard-link ``src`` to a temp name and ``os.replace`` it in
    (zero data copy; ``src`` must already be durable — e.g. produced by
    atomic_write/write_table_atomic, which fsync). Filesystems without
    hard links fall back to a chunked copy + fsync. Either way ``src`` is
    left in place, so a crashed publish re-runs idempotently. Same crash
    contract as atomic_write: the target is never torn."""
    tmp = "{}.tmp.{}".format(path, os.getpid())

    def _copy():
        bk = _mock_backend()
        if bk is not None:
            # No hard links on an object store: multipart-upload the
            # source's bytes (src stays in place, same idempotence
            # contract as the link path).
            bk.put_file(src, path)
            return
        faults.fault_point("open", path)
        try:
            try:
                os.link(src, tmp)
                atomic_publish(tmp, path, fsync_file=False)
            except OSError:
                # No hard links here (or a stale tmp): chunked fallback.
                if os.path.exists(tmp):
                    os.unlink(tmp)
                with open(src, "rb") as fin, open(tmp, "wb") as fout:
                    while True:
                        chunk = fin.read(1 << 20)
                        if not chunk:
                            break
                        fout.write(chunk)
                    fout.flush()
                    os.fsync(fout.fileno())
                atomic_publish(tmp, path, fsync_file=False)
        finally:
            if os.path.exists(tmp):
                try:
                    os.unlink(tmp)
                except OSError:
                    pass

    if retries:
        return with_retries(_copy, desc="atomic_copy {}".format(path))
    return _copy()


def read_bytes(path, retries=True):
    """Read a whole file with transient-error retries and fault injection
    (``truncate`` faults chop the returned payload, simulating a torn
    read on flaky storage)."""

    def _read():
        bk = _mock_backend()
        t0 = _lat_start()
        if bk is not None:
            # The store fires its own open/read(/range-read) fault
            # points and resolves the newest committed generation.
            data = bk.get(path)
            _lat_end(t0, "get")
            return data
        faults.fault_point("open", path)
        with open(path, "rb") as f:
            data = f.read()
        action = faults.fault_point("read", path)
        if action == "truncate":
            data = data[:max(0, len(data) // 2 - 1)]
        _backend.count("local", "get", "ok")
        _lat_end(t0, "get")
        return data

    if retries:
        return with_retries(_read, desc="read {}".format(path))
    return _read()


def backend_if_nonlocal():
    """Public alias of the hot-path dispatch check: the active non-POSIX
    backend instance, or None under the default LocalBackend (the loader
    shard pipeline uses it to keep the local+disabled path byte- and
    syscall-identical to the pre-pipeline code)."""
    return _mock_backend()


def object_head(path):
    """(size_bytes, version) of ``path`` through the active backend
    WITHOUT reading data bytes — the loader shard cache's version/ETag
    probe. The mock store answers from the newest commit record (the
    generation IS the version); the local path answers from ``os.stat``
    with the (size, mtime_ns) pair standing in as a change-detecting
    version. (None, None) when absent."""
    bk = _mock_backend()
    if bk is not None:
        return bk.head(path)
    try:
        st = os.stat(path)
    except FileNotFoundError:
        return None, None
    return st.st_size, (st.st_size, st.st_mtime_ns)


def read_range(path, start, length, retries=True):
    """Ranged read of ``[start, start+length)`` through the active
    backend — the ``range-read`` fault site on both. Exists for
    footer-first parquet census reads: counting samples must never
    fetch full objects."""

    def _read():
        bk = _mock_backend()
        t0 = _lat_start()
        data = (bk if bk is not None else _backend.get_backend()).get(
            path, start=start, length=length)
        _lat_end(t0, "range-read")
        return data

    if retries:
        return with_retries(_read, desc="range read {}".format(path))
    return _read()


def read_shard_bytes(path, retries=True):
    """(bytes, version) of a whole parquet shard through the active
    backend — the loader shard cache's fetch primitive. The version
    pairs with :func:`object_head` so generation-following can never
    serve stale cached bytes: mock-store objects carry their commit
    generation (the ETag), POSIX files a (size, mtime_ns) stat version.

    Same failure contract as :func:`read_table`: torn shard bytes — an
    injected ``truncate`` or a genuinely chopped object (the parquet
    magic is checked at both ends) — surface as a permanent ValueError
    naming the shard, and can never be silently decoded or cached."""

    def _read():
        bk = _mock_backend()
        t0 = _lat_start()
        if bk is not None:
            data, version = bk.get_versioned(path)
            if data is None:
                # External (never-committed) plain file: generation-less.
                # Fall back to the raw object with the same stat version
                # shape head() reports for it.
                st = os.stat(path)
                data = bk.get(path)
                version = ("stat", st.st_size, st.st_mtime_ns)
        else:
            faults.fault_point("open", path)
            st = os.stat(path)
            with open(path, "rb") as f:
                data = f.read()
            version = (st.st_size, st.st_mtime_ns)
            if faults.fault_point("read", path) == "truncate":
                data = data[:max(0, len(data) // 2 - 1)]
            _backend.count("local", "get", "ok")
        _lat_end(t0, "get")
        if len(data) < 12 or data[:4] != b"PAR1" or data[-4:] != b"PAR1":
            raise ValueError(
                "injected truncated parquet read: {}".format(path)
                if faults.armed() else
                "torn parquet shard read ({} byte(s)): {}".format(
                    len(data), path))
        return data, version

    if retries:
        return with_retries(_read, desc="read shard {}".format(path))
    return _read()


def read_json(path, retries=True):
    """Read a small JSON record with transient-error retries: returns
    ``(value, "ok")``, ``(None, "missing")`` on ENOENT, or
    ``(raw_bytes, "torn")`` when the bytes don't parse (flaky storage
    serving a torn read). The one reader behind every ledger / scatter
    record / lease file, so torn-record semantics cannot drift between
    them: callers decide what "torn" means for their record type (always
    some flavor of "not done"/"expired" — records are written atomically,
    so torn bytes implicate the medium, not the writer)."""
    try:
        data = read_bytes(path, retries=retries)
    except FileNotFoundError:
        return None, "missing"
    try:
        return json.loads(data), "ok"
    except ValueError:
        return data, "torn"


def open_append(path, retries=True):
    """Open a spool file for append, retrying transient open errors.
    Only the OPEN retries: retrying a failed append could duplicate
    bytes, so write errors propagate to the unit-level fault handling.
    Spool appends stay POSIX on every backend — scatter spools are
    holder-keyed local scratch, never objects (object stores have no
    append); their bytes reach the store only via the publish
    primitives above."""

    def _open():
        faults.fault_point("open", path)
        return open(path, "ab")

    if retries:
        return with_retries(_open, desc="open append {}".format(path))
    return _open()


def read_table(path, retries=True):
    """pyarrow ``read_table`` with transient-error retries + fault
    injection — the sanctioned way every stage reads a parquet shard."""
    import pyarrow.parquet as pq

    def _read():
        faults.fault_point("open", path)
        if faults.fault_point("read", path) == "truncate":
            # A torn parquet read cannot be emulated by chopping (pyarrow
            # owns the file handle), so surface it the way a real torn
            # read does: a permanent parse error, not a retried blip.
            raise ValueError(
                "injected truncated parquet read: {}".format(path))
        return pq.read_table(path)

    if retries:
        return with_retries(_read, desc="read parquet {}".format(path))
    return _read()


def write_table_atomic(table, path, compression=None, retries=True,
                       **write_options):
    """Write a pyarrow table via tmp + fsync + replace, so a crashed or
    preempted writer can never publish a torn shard under its final name
    (half-written ``part.*.parquet`` files were previously possible and
    poisoned downstream stages). ``write_options`` pass through to
    ``pq.write_table`` (the v2/packed sinks pin their page layout via
    binning.V2_PARQUET_WRITE_OPTIONS)."""
    import pyarrow.parquet as pq

    tmp = "{}.tmp.{}".format(path, os.getpid())

    def _write():
        faults.fault_point("open", path)
        try:
            pq.write_table(table, tmp, compression=compression,
                           **write_options)
            atomic_publish(tmp, path)
        finally:
            if os.path.exists(tmp):
                try:
                    os.unlink(tmp)
                except OSError:
                    pass

    if retries:
        return with_retries(_write, desc="write parquet {}".format(path))
    return _write()


def list_dir(path):
    """Sorted directory listing through the active backend (publish
    scratch excluded), or None when the directory is absent. On the mock
    store this is the ``list`` fault site — an injected ``stale`` kind
    serves a pre-put snapshot, which callers must treat as a discovery
    hint, never as record truth."""
    bk = _mock_backend()
    t0 = _lat_start()
    if bk is not None:
        names = bk.list(path)
        _lat_end(t0, "list")
        return names
    try:
        names = sorted(os.listdir(path))
    except (FileNotFoundError, NotADirectoryError):
        return None
    _backend.count("local", "list", "ok")
    _lat_end(t0, "list")
    return [n for n in names if ".tmp." not in n]


def remove(path):
    """Delete one published record through the active backend (missing is
    fine — removals race sweeps by design). On the mock store this drops
    the authoritative commit records too: a raw ``os.remove`` there would
    leave the object readable through the backend, silently resurrecting
    a withdrawn record."""
    bk = _mock_backend()
    t0 = _lat_start()
    if bk is not None:
        bk.delete(path)
        _lat_end(t0, "delete")
        return
    try:
        os.remove(path)
    except FileNotFoundError:
        pass
    _backend.count("local", "delete", "ok")
    _lat_end(t0, "delete")


def put_exclusive(path, data):
    """Create-only publish: ``"ok"`` when this caller's bytes committed,
    ``"conflict"`` when the object already exists (mock store CAS
    create). On the local backend this is today's ``atomic_write`` —
    the POSIX journal-commit contract is unchanged (single in-sequence
    writer; the segment hole/torn checks stay the guard), while the mock
    store upgrades the commit point to a real conditional create so a
    raced commit surfaces as a conflict instead of a silent overwrite."""
    bk = _mock_backend()
    if bk is not None:
        if isinstance(data, str):
            data = data.encode("utf-8")
        t0 = _lat_start()
        try:
            with_retries(lambda: bk.put_if_match(path, data, None),
                         desc="put_exclusive {}".format(path))
        except _backend.CASConflict:
            _lat_end(t0, "cas-put")
            return "conflict"
        _lat_end(t0, "cas-put")
        return "ok"
    atomic_write(path, data)
    return "ok"
