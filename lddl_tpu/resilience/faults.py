"""Process-wide fault injector for chaos tests and resilience validation.

The pipeline's resilient I/O layer (resilience/io.py) calls
``fault_point(op, path)`` at every guarded operation; when the injector is
armed, matching calls raise transient ``OSError``s, truncate reads, sleep,
or SIGKILL the calling process. Disarmed (the default), a fault point is a
single dict lookup — effectively free on the hot path.

Arming is ENV-VAR based (``LDDL_TPU_FAULTS``) so spawned pool/loader worker
processes inherit the configuration automatically; ``arm()``/``disarm()``
are conveniences that set/clear the env var and re-parse in-process.

Spec grammar — comma-separated clauses of colon-separated fields::

    <op>:<kind>[:p=<float>][:nth=<int>][:max=<int>][:seed=<int>]
               [:path=<substr>][:delay=<float>][:flag=<file>]

    op    site name: open | read | replace | worker | lease-acquire |
          lease-renew | lease-release | journal-read | journal-publish |
          sink-write | cas-put | range-read | multipart-commit | list
          (or * for any site; the last four fire only on the mock
          object-store backend — see resilience/backend.py)
    kind  eio | estale | truncate | slow | stall | kill | conflict | stale
    p     per-call injection probability (seeded per process)
    nth   inject on exactly the Nth matching call of this process
    max   cap on injections per process (default: 1 for nth, unlimited for p)
    path  only calls whose path/tag contains this substring match
    delay sleep seconds for kind=slow (default 0.2) and kind=stall
          (default 30; set it past the lease TTL at a lease-renew — or,
          on the mock store, a cas-put — site to freeze the renewal and
          force a steal)
    flag  cross-process once-latch: inject only while <file> does not
          exist, and create it upon injection (survives respawned workers)

Examples::

    LDDL_TPU_FAULTS="read:eio:p=0.2:seed=7"        # flaky shard reads
    LDDL_TPU_FAULTS="open:kill:nth=5:path=_shuffle:flag=/tmp/k1"
    LDDL_TPU_FAULTS="worker:kill:nth=2:flag=/tmp/k2"  # loader worker death
    LDDL_TPU_FAULTS="lease-renew:stall:nth=1:delay=20"  # freeze renewal,
                                                        # force a steal
    LDDL_TPU_FAULTS="journal-read:truncate:nth=1"  # torn ingest-journal
                                                   # cache -> segment rescan
    LDDL_TPU_FAULTS="sink-write:kill:nth=2"  # SIGKILL on the shard-writer
                                             # thread mid-deferred-publish

Mock-object-store kinds: ``conflict`` (returned as an action at
``cas-put`` / ``multipart-commit``; the store raises an injected
``CASConflict`` — a lost precondition) and ``stale`` (returned at
``list``; the store serves its previous listing snapshot — a
list-after-put staleness window). Both are no-ops on the POSIX paths,
which never ask for them.

The ``sink-write`` site fires on the async shard-writer THREAD
(preprocess/sink.py), immediately before each deferred publish closure
runs — chaos coverage for the enqueue->publish window the double
buffer opens (an eio there must fail the unit loudly at the producer; a
kill must leave only ``*.tmp.*`` debris + an unjournaled unit).
"""

import errno
import os
import random
import threading
import time

ENV_VAR = "LDDL_TPU_FAULTS"

_ERRNO_OF = {
    "eio": errno.EIO,
    "estale": getattr(errno, "ESTALE", errno.EIO),
}

# Parsed state: (raw_spec, [clause dicts]); counters are per-process and
# per-clause. Re-parsed whenever the env var changes.
_state = {"raw": None, "clauses": []}
# The injector hooks run on whatever thread hits them (heartbeat
# sampler, sink writer, main); reentrant so a signal interrupting a
# frame mid-refresh cannot deadlock its own hook.
_state_lock = threading.RLock()


class FaultSpecError(ValueError):
    pass


def _parse_clause(text, index):
    fields = text.strip().split(":")
    if len(fields) < 2:
        raise FaultSpecError(
            "fault clause {!r} needs at least <op>:<kind>".format(text))
    op, kind = fields[0].strip(), fields[1].strip()
    if kind not in ("eio", "estale", "truncate", "slow", "stall", "kill",
                    "conflict", "stale"):
        raise FaultSpecError("unknown fault kind {!r} in {!r}".format(
            kind, text))
    clause = {"op": op, "kind": kind, "p": None, "nth": None, "max": None,
              "seed": 0, "path": None,
              "delay": 30.0 if kind == "stall" else 0.2, "flag": None,
              "index": index}
    for field in fields[2:]:
        if "=" not in field:
            raise FaultSpecError("malformed option {!r} in {!r}".format(
                field, text))
        key, value = field.split("=", 1)
        if key == "p":
            clause["p"] = float(value)
        elif key == "nth":
            clause["nth"] = int(value)
        elif key == "max":
            clause["max"] = int(value)
        elif key == "seed":
            clause["seed"] = int(value)
        elif key == "path":
            clause["path"] = value
        elif key == "delay":
            clause["delay"] = float(value)
        elif key == "flag":
            clause["flag"] = value
        else:
            raise FaultSpecError("unknown option {!r} in {!r}".format(
                key, text))
    if (clause["p"] is None) == (clause["nth"] is None):
        raise FaultSpecError(
            "fault clause {!r} needs exactly one of p= or nth=".format(text))
    if clause["max"] is None and clause["nth"] is not None:
        clause["max"] = 1
    return clause


def _parse(raw):
    if not raw:
        return []
    return [_parse_clause(part, i)
            for i, part in enumerate(raw.split(",")) if part.strip()]


def _refresh():
    raw = os.environ.get(ENV_VAR) or None
    with _state_lock:
        if raw != _state["raw"]:
            _state["raw"] = raw
            _state["clauses"] = _parse(raw)
            for c in _state["clauses"]:
                c["_calls"] = 0
                c["_injected"] = 0
                c["_rng"] = random.Random(
                    c["seed"] * 1000003 + os.getpid())
        return _state["clauses"]


def arm(spec):
    """Arm the injector for this process AND future child processes.
    Re-arming (even with an identical spec) resets the call counters."""
    os.environ[ENV_VAR] = spec
    with _state_lock:
        _state["raw"] = None  # force a re-parse so counters start fresh
        _refresh()


def disarm():
    os.environ.pop(ENV_VAR, None)
    _refresh()


def armed():
    return bool(_refresh())


def _should_inject(clause, op, path):
    if clause["op"] not in ("*", op):
        return False
    if clause["path"] is not None and clause["path"] not in (path or ""):
        return False
    if clause["flag"] is not None and os.path.exists(clause["flag"]):
        return False
    if clause["max"] is not None and clause["_injected"] >= clause["max"]:
        return False
    clause["_calls"] += 1
    if clause["nth"] is not None:
        return clause["_calls"] == clause["nth"]
    return clause["_rng"].random() < clause["p"]


def _latch(clause, op):
    clause["_injected"] += 1
    # Telemetry (imported lazily: observability must stay import-light for
    # the disarmed hot path, and this only runs when a fault actually
    # fires): injections were previously invisible outside test asserts.
    from ..observability import event as obs_event
    from ..observability import inc as obs_inc
    obs_inc("resilience_faults_injected_total", op=op, kind=clause["kind"])
    obs_event("resilience.fault_injected", op=op, kind=clause["kind"])
    if clause["flag"] is not None:
        try:
            with open(clause["flag"], "x") as f:
                f.write("injected\n")
        except OSError:
            pass


def fault_point(op, path=None):
    """Guarded-operation hook. Returns None (no fault) or an action
    string the caller must honor — ``"truncate"`` (chop the bytes just
    read), ``"conflict"`` (mock store: raise an injected CASConflict),
    ``"stale"`` (mock store: serve the previous listing snapshot).
    Raises OSError / sleeps / SIGKILLs the process for the other
    kinds."""
    clauses = _refresh()  # one env-dict lookup when disarmed
    if not clauses:
        return None
    action = None
    for clause in clauses:
        if not _should_inject(clause, op, path):
            continue
        kind = clause["kind"]
        if kind in ("slow", "stall"):
            # "stall" is "slow" with a freeze-scale default: parked at a
            # lease-renew site it outlives the lease TTL, so the deadline
            # passes mid-renewal and another host steals the unit.
            _latch(clause, op)
            time.sleep(clause["delay"])
        elif kind == "kill":
            _latch(clause, op)
            # SIGKILL destroys the process before any atexit export runs;
            # flush the injection record NOW or the kill is invisible in
            # the telemetry it exists to make visible.
            try:
                from ..observability import exporters, fleet, tracing
                tracing.flush()
                exporters.export_jsonl()
                # Fleet spool too (snapshot left UN-closed: the host is
                # dying abnormally, and the aggregator's stall verdict
                # keys on exactly that).
                fleet.heartbeat(closed=False)
            except Exception:  # noqa: BLE001 - the kill must still fire
                pass
            import signal
            os.kill(os.getpid(), signal.SIGKILL)
        elif kind in ("truncate", "conflict", "stale"):
            # Action kinds: the caller interprets the string (chop the
            # read bytes / raise CASConflict / serve a stale listing).
            _latch(clause, op)
            action = kind
        else:
            _latch(clause, op)
            err = _ERRNO_OF[kind]
            raise OSError(err, "injected fault [{}] at {}".format(
                kind, op), path)
    return action
