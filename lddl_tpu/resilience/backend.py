"""Pluggable storage backend: POSIX shared filesystem vs mock object store.

Every coordination mechanism in the pipeline — atomic-rename leases, the
``.ingest/`` journal, two-phase shard publish — historically assumed a
POSIX shared filesystem (the one LDDL deployment constraint PAPER.md
inherits). Production fleets increasingly mount object stores instead,
where rename is not atomic, reads can be stale, and puts fail in ways NFS
never does. This module is the seam between those worlds:

- :class:`LocalBackend` — the default. A thin veneer over
  ``resilience/io``'s existing primitives; the hot path in ``io.py`` does
  NOT dispatch through it (the local branches there are the pre-backend
  code verbatim — zero new syscalls), so selecting ``local`` is
  byte-identical and cost-identical to the pre-backend pipeline.
- :class:`MockObjectStore` — an in-process object store with object-store
  semantics: **no rename** (objects appear only via
  multipart-upload-then-commit), **versioned objects** (every commit is a
  new immutable generation; conditional ops compare generations, the moral
  equivalent of an ETag), and a fault program driven by the existing
  ``LDDL_TPU_FAULTS`` injector (``cas-put`` / ``range-read`` /
  ``multipart-commit`` / ``list`` sites) so the chaos suite can replay
  the SIGKILL matrix against CAS conflicts, torn multipart uploads,
  list-after-put staleness, and 5xx-shaped transients.

Mock store on-disk layout (disk-backed so the 3-host chaos runs — real
processes sharing only the output directory — coordinate through it
exactly like they would through a real store; "in-process" means no
external server, not no disk)::

    <dir>/.obj.<name>/u<pid>-<seq>.p<k>   uploaded parts (staging;
                                          orphans = abandoned multipart)
    <dir>/.obj.<name>/g<00000042>.json    commit record for generation 42
                                          (atomic exclusive create: the
                                          ONE winner per generation)
    <dir>/<name>                          materialized read view of the
                                          newest committed generation

The commit record is the linearization point: it is hard-linked into
place from a fully-written temp (``os.link`` fails loudly on EEXIST even
on NFS), so exactly one writer wins each generation — that exclusive
create IS the store's compare-and-swap. The materialized view exists so
unchanged data-plane readers (loader, balancer, integrity checks) keep
reading plain files; coordination reads (leases, CAS chains) always
resolve through the commit records, which are authoritative. A crash
between commit and materialize leaves a committed-but-unmirrored object:
readers through the backend see the commit, raw existence checks lag one
step — the same window a real store's list-after-put staleness opens, and
the pipeline's redo-idempotence absorbs both.

Selection is ENV-VAR based (``LDDL_TPU_STORAGE_BACKEND``: ``local`` |
``mock``) so spawned pool/loader workers inherit the backend
automatically; CLIs expose it as ``--storage-backend``.

Counters: ``backend_ops_total{backend,op,outcome}`` for every backend
operation and ``backend_cas_conflicts_total`` for every conditional-put /
conditional-delete precondition loss (injected or real).
"""

import errno
import json
import os
import shutil
import threading
import time

from . import faults
from ..observability import inc as obs_inc
from ..observability import observe as obs_observe

ENV_VAR = "LDDL_TPU_STORAGE_BACKEND"
BACKENDS = ("local", "mock")

OBJ_PREFIX = ".obj."

# "No precondition" sentinel for internal put plumbing (None already means
# "object must not exist", so a third state needs its own marker).
_ANY = object()


class CASConflict(RuntimeError):
    """A conditional put/delete lost its precondition: the object's
    current generation no longer matches what the caller read. Loud by
    design — precondition loss means another writer won (a steal, a
    concurrent commit) and blind retry would overwrite its work; callers
    translate it into their protocol's loss path (``LeaseLost``, a lost
    claim race, an idempotent re-read). Deliberately NOT an OSError:
    the transient-error classifier must never auto-retry it."""


def count(backend, op, outcome):
    """One backend operation outcome into ``backend_ops_total`` — the
    cross-backend cost/outcome headline (labels documented in README)."""
    obs_inc("backend_ops_total", backend=backend, op=op, outcome=outcome)


def observe_latency(backend, op, seconds):
    """One backend operation latency into
    ``backend_op_latency_seconds{backend,op}`` — the per-op cost
    distribution the status CLI reads back out of the fleet rollup."""
    obs_observe("backend_op_latency_seconds", seconds,
                backend=backend, op=op)


def _conflict(backend, path, op):
    count(backend, op, "conflict")
    obs_inc("backend_cas_conflicts_total")
    raise CASConflict("{} precondition lost at {} ({})".format(
        op, path, backend))


def active_name():
    """The selected backend name (``local`` unless the env var says
    otherwise) — cheap enough for hot-path dispatch checks."""
    return os.environ.get(ENV_VAR) or "local"


_instances = {}
# Reentrant: get_backend sits on the SIGTERM flush path (final_flush ->
# spool writes -> backend), and a signal interrupting a frame that holds
# a non-reentrant lock here would deadlock the handler.
_instances_lock = threading.RLock()


def get_backend():
    """The active backend instance (one per name per process)."""
    name = active_name()
    inst = _instances.get(name)
    if inst is None:
        with _instances_lock:
            inst = _instances.get(name)
            if inst is None:
                if name == "local":
                    inst = LocalBackend()
                elif name == "mock":
                    inst = MockObjectStore()
                else:
                    raise ValueError(
                        "unknown storage backend {!r} (LDDL_TPU_STORAGE_"
                        "BACKEND); expected one of {}".format(
                            name, "/".join(BACKENDS)))
                _instances[name] = inst
    return inst


def set_backend(name):
    """Select the backend for this process AND future child processes
    (env-var based, like ``faults.arm``)."""
    if name not in BACKENDS:
        raise ValueError("unknown storage backend {!r}; expected one of "
                         "{}".format(name, "/".join(BACKENDS)))
    os.environ[ENV_VAR] = name


class LocalBackend(object):
    """The POSIX shared-filesystem backend: delegates to the battle-tested
    primitives in ``resilience/io``. ``is_cas`` is False — the lease
    protocol keeps its atomic-rename + read-back shape here, because a
    POSIX filesystem offers no conditional put (replace + read-back plus
    the publish-time fence is the protocol *designed* for that medium).
    ``put_if_match`` therefore supports only the create case (generation
    None), which maps onto the same NFS-safe exclusive create the lease
    acquire path uses."""

    name = "local"
    is_cas = False

    def put_atomic(self, path, data):
        from . import io as rio
        rio.atomic_write(path, data)

    def put_file(self, src, path):
        from . import io as rio
        rio.atomic_copy(src, path)

    def put_if_match(self, path, data, expected_gen):
        if expected_gen is not None:
            raise NotImplementedError(
                "LocalBackend has no conditional replace: POSIX offers no "
                "CAS — the lease protocol uses atomic rename + read-back "
                "plus publish-time fencing here by design")
        if isinstance(data, str):
            data = data.encode("utf-8")
        try:
            fd = os.open(path, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
        except FileExistsError:
            _conflict(self.name, path, "cas-put")
        try:
            os.write(fd, data)
            os.fsync(fd)
        finally:
            os.close(fd)
        count(self.name, "cas-put", "ok")
        return 1

    def get(self, path, start=None, length=None):
        from . import io as rio
        if start is None and length is None:
            return rio.read_bytes(path)
        # Ranged read: seek + pread ONLY the requested window. The loader
        # census reads parquet footers this way; slurping the whole shard
        # and slicing (the old behavior) defeats the point of a ranged
        # API on multi-MB objects.
        faults.fault_point("open", path)
        lo = start or 0
        fd = os.open(path, os.O_RDONLY)
        try:
            if length is None:
                os.lseek(fd, lo, os.SEEK_SET)
                chunks = []
                while True:
                    c = os.read(fd, 1 << 20)
                    if not c:
                        break
                    chunks.append(c)
                data = b"".join(chunks)
            else:
                data = os.pread(fd, length, lo)
                # Short preads are legal (signals, NFS): keep reading.
                while len(data) < length:
                    more = os.pread(fd, length - len(data),
                                    lo + len(data))
                    if not more:
                        break
                    data += more
        finally:
            os.close(fd)
        if faults.fault_point("range-read", path) == "truncate":
            data = data[:max(0, len(data) // 2 - 1)]
        count(self.name, "range-read", "ok")
        return data

    def get_versioned(self, path):
        """(bytes, version) of the current object, or (None, None) when
        absent. POSIX files carry no generation; the (size, mtime_ns)
        stat pair stands in as a change-detecting version — the same one
        ``head`` reports, so the loader shard cache's probe/fetch keys
        agree. The local protocol never CAS-chains off it."""
        from . import io as rio
        try:
            st = os.stat(path)
            return rio.read_bytes(path), (st.st_size, st.st_mtime_ns)
        except FileNotFoundError:
            return None, None

    def head(self, path):
        """(size_bytes, version) metadata probe without reading data
        bytes — the loader shard cache's cheap version check. Returns
        (None, None) when absent."""
        try:
            st = os.stat(path)
        except FileNotFoundError:
            return None, None
        count(self.name, "head", "ok")
        return st.st_size, (st.st_size, st.st_mtime_ns)

    def list(self, dirpath):
        try:
            names = sorted(os.listdir(dirpath))
        except (FileNotFoundError, NotADirectoryError):
            return None
        count(self.name, "list", "ok")
        return [n for n in names if ".tmp." not in n]

    def delete(self, path):
        try:
            os.remove(path)
        except FileNotFoundError:
            pass
        count(self.name, "delete", "ok")

    def delete_if_match(self, path, expected_gen):
        """Advisory on POSIX (no versions to compare): plain unlink. The
        lease protocol's local release path does its own verified
        unlink and never calls this."""
        self.delete(path)
        return True


class MockObjectStore(object):
    """In-process object store with object-store semantics (module
    docstring has the layout). Thread-safe and multi-process-safe: all
    coordination state is the exclusive-create commit records on disk, so
    the 3-host chaos subprocesses race through it exactly like concurrent
    clients race a real store."""

    name = "mock"
    is_cas = True

    # Commit records of the newest two generations (and their parts) are
    # kept; older ones are garbage-collected so renew-heavy lease objects
    # don't grow without bound. Keeping one superseded generation lets an
    # in-flight reader that already resolved it finish against intact
    # parts (its NEXT read resolves the newer commit).
    _KEEP_GENS = 2

    def __init__(self):
        self._lock = threading.Lock()
        self._upload_seq = 0
        self._list_cache = {}
        try:
            self._part_bytes = int(os.environ.get(
                "LDDL_TPU_MOCK_PART_BYTES", 1 << 18))
        except ValueError:
            self._part_bytes = 1 << 18
        self._part_bytes = max(1, self._part_bytes)
        # Uniform per-operation latency (LDDL_TPU_MOCK_LATENCY_MS),
        # modeling a remote store's round trip on every DATA op —
        # get/put/list/delete, NOT head (metadata probes are HEAD-class
        # requests, orders of magnitude cheaper than GETs on real
        # stores). First-class knob for loader_bench's prefetch/cache
        # headline, replacing hand-built LDDL_TPU_FAULTS slow specs.
        try:
            self._latency_s = max(0.0, float(os.environ.get(
                "LDDL_TPU_MOCK_LATENCY_MS", 0)) / 1e3)
        except ValueError:
            self._latency_s = 0.0

    def _lat(self):
        if self._latency_s > 0.0:
            time.sleep(self._latency_s)

    # ------------------------------------------------------------ layout

    @staticmethod
    def _obj_dir(path):
        d, b = os.path.split(os.path.abspath(path))
        return os.path.join(d, OBJ_PREFIX + b)

    @staticmethod
    def _gen_name(gen):
        return "g{:08d}.json".format(gen)

    @classmethod
    def _current_gen(cls, odir):
        try:
            names = sorted(os.listdir(odir))
        except (FileNotFoundError, NotADirectoryError):
            return None
        best = None
        for n in names:
            if n.startswith("g") and n.endswith(".json"):
                try:
                    g = int(n[1:-5])
                except ValueError:
                    continue
                if best is None or g > best:
                    best = g
        return best

    @staticmethod
    def _read_meta(odir, gen):
        with open(os.path.join(
                odir, MockObjectStore._gen_name(gen)), "rb") as f:
            return json.loads(f.read())

    def _next_upload_id(self):
        # pid + per-process sequence: unique across the racing hosts AND
        # the writer thread vs main thread of one host. Identity of
        # staging scratch only — committed object content never includes
        # it (the commit record does, as provenance, and commit records
        # are coordination state that no shard/manifest byte derives
        # from).
        with self._lock:
            self._upload_seq += 1
            return "{}-{}".format(os.getpid(), self._upload_seq)

    def _chunks_of(self, data):
        for off in range(0, len(data), self._part_bytes):
            yield data[off:off + self._part_bytes]

    # ------------------------------------------------------------- write

    def _upload_parts(self, odir, chunks):
        """Phase 1 of multipart-upload-then-commit: stream parts into the
        object's staging namespace. A crash or injected fault here leaves
        orphaned parts — an abandoned multipart upload, invisible to every
        reader because no commit record references them."""
        os.makedirs(odir, exist_ok=True)
        uid = self._next_upload_id()
        parts, total = [], 0
        for k, chunk in enumerate(chunks):
            pname = "u{}.p{:04d}".format(uid, k)
            ppath = os.path.join(odir, pname)
            faults.fault_point("open", ppath)
            # Part staging, fsynced in full; promoted ONLY by the commit
            # record below — a torn part is never referenced. (A
            # zero-byte object is simply a commit record with no parts.)
            with open(ppath, "wb") as f:
                f.write(chunk)
                f.flush()
                os.fsync(f.fileno())
            parts.append(pname)
            total += len(chunk)
        return uid, parts, total

    def _commit(self, path, odir, uid, parts, size, expected_gen):
        """Phase 2: linearize via exclusive create of the generation's
        commit record. Exactly one writer per generation wins the
        ``os.link``; everyone else gets a CAS conflict and their parts
        stay behind as an abandoned upload."""
        action = faults.fault_point("multipart-commit", path)
        if action == "conflict":
            _conflict(self.name, path, "multipart-commit")
        cur = self._current_gen(odir)
        if expected_gen is not _ANY and cur != expected_gen:
            _conflict(self.name, path, "cas-put")
        target = 1 if cur is None else cur + 1
        meta = {"parts": parts, "size": size, "upload": uid}
        tmp = os.path.join(odir, "commit.{}.tmp".format(uid))
        # Commit-record staging, promoted only via the exclusive link.
        with open(tmp, "wb") as f:
            f.write(json.dumps(meta, sort_keys=True).encode("utf-8"))
            f.flush()
            os.fsync(f.fileno())
        gpath = os.path.join(odir, self._gen_name(target))
        try:
            try:
                os.link(tmp, gpath)
            except FileExistsError:
                _conflict(self.name, path,
                          "cas-put" if expected_gen is not _ANY else "put")
            # Mounts without hard links: O_EXCL performs the same
            # exclusive create (mirrors leases._try_create).
            except OSError:
                try:
                    fd = os.open(gpath,
                                 os.O_CREAT | os.O_EXCL | os.O_WRONLY)
                except FileExistsError:
                    _conflict(self.name, path,
                              "cas-put" if expected_gen is not _ANY
                              else "put")
                try:
                    with open(tmp, "rb") as f:
                        os.write(fd, f.read())
                    os.fsync(fd)
                finally:
                    os.close(fd)
        finally:
            try:
                os.unlink(tmp)
            except FileNotFoundError:
                pass
        self._gc(odir, target)
        self._materialize(path, odir, meta)
        return target

    def _gc(self, odir, newest):
        """Drop commit records (and their parts) older than the kept
        window. Races between concurrent collectors are benign: every
        step tolerates already-gone files."""
        keep = set()
        try:
            names = sorted(os.listdir(odir))
        except (FileNotFoundError, NotADirectoryError):
            return
        gens = []
        for n in names:
            if n.startswith("g") and n.endswith(".json"):
                try:
                    gens.append(int(n[1:-5]))
                except ValueError:
                    continue
        for g in gens:
            if g > newest - self._KEEP_GENS:
                try:
                    keep.update(self._read_meta(odir, g)["parts"])
                except (OSError, ValueError, KeyError):
                    continue
        for g in gens:
            if g <= newest - self._KEEP_GENS:
                try:
                    meta = self._read_meta(odir, g)
                except (OSError, ValueError):
                    meta = {"parts": ()}
                for pname in meta.get("parts", ()):
                    if pname in keep:
                        continue
                    try:
                        os.unlink(os.path.join(odir, pname))
                    except OSError:
                        pass
                try:
                    os.unlink(os.path.join(odir, self._gen_name(g)))
                except OSError:
                    pass

    def _materialize(self, path, odir, meta):
        """Mirror the committed object at its real POSIX path so the
        unchanged data-plane readers (loader, balancer, raw existence
        checks) keep working. Internal mirror maintenance, not part of
        the store API — the API exposes no rename. The ``replace`` fault
        site fires with the REAL path, so existing chaos specs keyed on
        publish targets hit the same window here."""
        uid = self._next_upload_id()
        tmp = "{}.tmp.{}".format(path, uid)
        with open(tmp, "wb") as f:
            for pname in meta["parts"]:
                with open(os.path.join(odir, pname), "rb") as pf:
                    shutil.copyfileobj(pf, f)
            f.flush()
            os.fsync(f.fileno())
        faults.fault_point("replace", path)
        os.replace(tmp, path)
        from . import io as rio
        rio._fsync_dir(path)

    def _put_once(self, path, chunks, expected_gen):
        self._lat()
        action = faults.fault_point("cas-put", path)
        if action == "conflict":
            _conflict(self.name, path, "cas-put")
        odir = self._obj_dir(path)
        uid, parts, size = self._upload_parts(odir, chunks)
        return self._commit(path, odir, uid, parts, size, expected_gen)

    def put_if_match(self, path, data, expected_gen):
        """Conditional put: commit succeeds only while the object's
        current generation equals ``expected_gen`` (None = must not
        exist). Returns the new generation; raises :class:`CASConflict`
        on precondition loss. The store's compare-and-swap — what the
        lease protocol's acquire/renew/steal become here."""
        if isinstance(data, str):
            data = data.encode("utf-8")
        gen = self._put_once(path, self._chunks_of(data), expected_gen)
        count(self.name, "cas-put", "ok")
        return gen

    def _put_retry_races(self, path, chunks_fn):
        """Unconditional last-writer-wins put: re-reads the current
        generation and retries lost CAS races (bounded — sustained loss
        against a determinism-pinned pipeline would mean a protocol bug,
        so it eventually surfaces loudly)."""
        last = None
        for _ in range(32):
            cur = self._current_gen(self._obj_dir(path))
            try:
                return self._put_once(
                    path, chunks_fn(), cur if cur is not None else None)
            except CASConflict as e:
                last = e
        raise OSError(
            errno.EIO, "mock put of {} lost 32 consecutive CAS "
            "races".format(path)) from last

    def put_atomic(self, path, data):
        if isinstance(data, str):
            data = data.encode("utf-8")
        self._put_retry_races(path, lambda: self._chunks_of(data))
        count(self.name, "put", "ok")

    def put_file(self, src, path):
        """Multipart-upload-then-commit from a fully-written local
        staging file (how shard publishes arrive: parquet bytes are
        staged locally, then uploaded in parts)."""

        def chunks():
            with open(src, "rb") as f:
                while True:
                    c = f.read(self._part_bytes)
                    if not c:
                        return
                    yield c

        self._put_retry_races(path, chunks)
        count(self.name, "put", "ok")

    # -------------------------------------------------------------- read

    def _read_committed(self, path, odir, gen, start=None, length=None):
        meta = self._read_meta(odir, gen)
        buf = []
        for pname in meta["parts"]:
            with open(os.path.join(odir, pname), "rb") as f:
                buf.append(f.read())
        data = b"".join(buf)
        if start is not None or length is not None:
            lo = start or 0
            data = data[lo:] if length is None else data[lo:lo + length]
        return data

    def get(self, path, start=None, length=None):
        """Read the newest committed generation (ranged when
        ``start``/``length`` given — the ``range-read`` fault site).
        Paths never written through the store (source corpora, spool
        scratch) fall back to the plain file: they are external,
        generation-less objects."""
        self._lat()
        faults.fault_point("open", path)
        odir = self._obj_dir(path)
        cur = self._current_gen(odir)
        if cur is None:
            if os.path.isfile(path):
                with open(path, "rb") as f:
                    if start:
                        f.seek(start)
                    data = f.read(-1 if length is None else length)
            else:
                raise FileNotFoundError(
                    errno.ENOENT, "no such object", path)
        else:
            data = self._read_committed(path, odir, cur, start, length)
        ranged = start is not None or length is not None
        action = faults.fault_point(
            "range-read" if ranged else "read", path)
        if action == "truncate":
            data = data[:max(0, len(data) // 2 - 1)]
        count(self.name, "range-read" if ranged else "get", "ok")
        return data

    def get_versioned(self, path):
        """(bytes, generation) of the current committed object, or
        (None, None) when the path has never been committed — the read
        half of every CAS chain. External plain files are NOT versioned
        reads: the CAS namespace is store-managed objects only."""
        self._lat()
        faults.fault_point("open", path)
        odir = self._obj_dir(path)
        cur = self._current_gen(odir)
        if cur is None:
            return None, None
        data = self._read_committed(path, odir, cur)
        if faults.fault_point("read", path) == "truncate":
            data = data[:max(0, len(data) // 2 - 1)]
        count(self.name, "get", "ok")
        return data, cur

    def head(self, path):
        """(size_bytes, generation) of the current committed object from
        its commit record alone — no part reads, no data bytes; the
        loader shard cache's cheap version/ETag probe. External
        (never-committed) plain files report a stat version like
        LocalBackend; (None, None) when absent. Deliberately NOT a
        latency-modeled data op (see ``_lat``)."""
        odir = self._obj_dir(path)
        cur = self._current_gen(odir)
        if cur is None:
            try:
                st = os.stat(path)
            except FileNotFoundError:
                return None, None
            count(self.name, "head", "ok")
            return st.st_size, ("stat", st.st_size, st.st_mtime_ns)
        try:
            meta = self._read_meta(odir, cur)
        except (OSError, ValueError):
            return None, None
        count(self.name, "head", "ok")
        return int(meta.get("size", 0)), cur

    def list(self, dirpath):
        """Sorted names of the directory's objects: committed store
        objects plus external plain files (hidden names and publish
        scratch excluded). The ``list`` fault site's ``stale`` kind
        serves the PREVIOUS snapshot this process took — a
        list-after-put staleness window, which callers must (and do)
        tolerate: listings are discovery hints, record reads are the
        truth."""
        self._lat()
        try:
            names = sorted(os.listdir(dirpath))
        except (FileNotFoundError, NotADirectoryError):
            return None
        out = set()
        for n in names:
            if n.startswith(OBJ_PREFIX):
                odir = os.path.join(dirpath, n)
                if self._current_gen(odir) is not None:
                    out.add(n[len(OBJ_PREFIX):])
            elif n.startswith(".") or ".tmp." in n:
                continue
            else:
                out.add(n)
        result = sorted(out)
        if faults.fault_point("list", dirpath) == "stale":
            prev = self._list_cache.get(dirpath)
            if prev is not None:
                count(self.name, "list", "stale")
                return list(prev)
        self._list_cache[dirpath] = tuple(result)
        count(self.name, "list", "ok")
        return result

    # ------------------------------------------------------------ delete

    def delete(self, path):
        """Unconditional delete: drop the commit records (authoritative)
        then the materialized view. Immediately consistent in the mock —
        real-store delete lag is modeled by the ``list`` staleness fault
        instead, which is where the pipeline would feel it."""
        self._lat()
        odir = self._obj_dir(path)
        shutil.rmtree(odir, ignore_errors=True)
        try:
            os.remove(path)
        except (FileNotFoundError, IsADirectoryError):
            pass
        count(self.name, "delete", "ok")

    def delete_if_match(self, path, expected_gen):
        """Conditional delete (lease release): succeeds only while the
        current generation matches. The check-then-delete pair is not
        atomic — a writer landing in between loses its commit records;
        acceptable here because the only conditional deleter is the
        lease release path, whose worst case (dropping a clock-skewed
        thief's lease) the protocol already tolerates on the local
        path."""
        cur = self._current_gen(self._obj_dir(path))
        if cur != expected_gen:
            _conflict(self.name, path, "delete")
        self.delete(path)
        return True
