"""OpenWebText downloader: Drive archive -> nested .xz subsets -> shards.

Reference parity: lddl/download/openwebtext.py (gdown fetch, nested .xz
extraction, round-robin sharding with the page filename as the doc id).
The Google-Drive fetch needs the optional ``gdown`` package; offline
environments pass ``--local-archive`` or ``--extracted-dir``.
"""

import argparse
import lzma
import os
import tarfile

from ..utils.fs import expand_outdir_and_mkdir, get_all_files_paths_under
from .utils import safe_extractall, shard_files_parallel

_DRIVE_ID = "1EA5V0oetDCOke7afsktL_JDQ-ETtNOvx"


def fetch_from_drive(outdir):
    try:
        import gdown
    except ImportError as e:
        raise RuntimeError(
            "the 'gdown' package is required to fetch OpenWebText from "
            "Google Drive (pip install gdown), or pass --local-archive") from e
    path = os.path.join(outdir, "openwebtext.tar.xz")
    gdown.download(id=_DRIVE_ID, output=path)
    return path


def extract_archive(archive, outdir):
    """openwebtext.tar.xz contains openwebtext/*.xz subset archives, each a
    tar of per-page .txt files."""
    top = os.path.join(outdir, "openwebtext")
    with tarfile.open(archive, "r:*") as tf:
        safe_extractall(tf, outdir)
    extracted = os.path.join(outdir, "extracted")
    os.makedirs(extracted, exist_ok=True)
    for subset in sorted(os.listdir(top)):
        if not subset.endswith(".xz"):
            continue
        sub_path = os.path.join(top, subset)
        with lzma.open(sub_path) as xz:
            with tarfile.open(fileobj=xz, mode="r:") as tf:
                safe_extractall(
                    tf, os.path.join(extracted, subset[:-len(".xz")]))
    return extracted


def parse_page_file(path):
    """One page file -> one (doc_id, text); the doc id is the page
    filename without extension."""
    with open(path, encoding="utf-8", errors="replace") as f:
        text = f.read()
    yield os.path.basename(path)[:-len(".txt")], text


def shard_pages(extracted_dir, outdir, num_shards, num_processes=None):
    """Page files round-robin to shards, one pool worker per shard
    (ref: openwebtext.py:127-160)."""
    paths = [p for p in get_all_files_paths_under(extracted_dir)
             if p.endswith(".txt")]
    return shard_files_parallel(paths, outdir, num_shards, parse_page_file,
                                num_processes=num_processes)


def attach_args(parser=None):
    parser = parser or argparse.ArgumentParser(
        description="Download OpenWebText and make one-page-per-line shards")
    parser.add_argument("--outdir", required=True)
    parser.add_argument("--num-shards", type=int, default=256)
    parser.add_argument("--local-archive", default=None)
    parser.add_argument("--extracted-dir", default=None)
    parser.add_argument("--number-of-sharding-processes", type=int, default=0,
                        help="process-pool size for the sharding step "
                             "(0 = cpu count)")
    return parser


def main(args=None):
    args = args if args is not None else attach_args().parse_args()
    outdir = expand_outdir_and_mkdir(args.outdir)
    extracted = args.extracted_dir
    if extracted is None:
        archive = args.local_archive or fetch_from_drive(outdir)
        extracted = extract_archive(archive, outdir)
    n = shard_pages(extracted, outdir, args.num_shards,
                    num_processes=args.number_of_sharding_processes)
    print("openwebtext: {} pages -> {} shards".format(n, args.num_shards))


if __name__ == "__main__":
    main()
