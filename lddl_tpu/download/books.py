"""BookCorpus downloader: books1.tar.gz -> one-book-per-line shards.

Reference parity: lddl/download/books.py (download from the-eye.eu, untar,
round-robin book files into shards, book filename as the doc id).
"""

import argparse
import os
import tarfile

from ..utils.args import attach_bool_arg
from ..utils.fs import expand_outdir_and_mkdir, get_all_files_paths_under
from .utils import download, safe_extractall, shard_files_parallel

_URL = "https://the-eye.eu/public/AI/pile_preliminary_components/books1.tar.gz"


def untar(archive, outdir):
    with tarfile.open(archive, "r:gz") as tf:
        safe_extractall(tf, outdir)


def parse_book_file(path):
    """One book file -> one (doc_id, text); the doc id is the book's
    filename (whitespace replaced)."""
    with open(path, encoding="utf-8", errors="replace") as f:
        text = f.read()
    yield os.path.basename(path).replace(" ", "-"), text


def shard_books(books_dir, outdir, num_shards, num_processes=None):
    """Book files round-robin to shards, one pool worker per shard
    (ref: books.py:177-187)."""
    paths = [p for p in get_all_files_paths_under(books_dir)
             if p.endswith(".txt")]
    return shard_files_parallel(paths, outdir, num_shards, parse_book_file,
                                num_processes=num_processes)


def attach_args(parser=None):
    parser = parser or argparse.ArgumentParser(
        description="Download BookCorpus and make one-book-per-line shards")
    parser.add_argument("--outdir", required=True)
    parser.add_argument("--num-shards", type=int, default=256)
    parser.add_argument("--local-archive", default=None,
                        help="pre-downloaded books1.tar.gz")
    parser.add_argument("--books-dir", default=None,
                        help="already-extracted books directory "
                             "(skips download+untar)")
    attach_bool_arg(parser, "download", default=True,
                    help_str="run the download step")
    parser.add_argument("--number-of-sharding-processes", type=int, default=0,
                        help="process-pool size for the sharding step "
                             "(0 = cpu count)")
    return parser


def main(args=None):
    args = args if args is not None else attach_args().parse_args()
    outdir = expand_outdir_and_mkdir(args.outdir)
    books_dir = args.books_dir
    if books_dir is None:
        archive = args.local_archive or os.path.join(outdir, "books1.tar.gz")
        if args.download and args.local_archive is None:
            download(_URL, archive)
        books_dir = os.path.join(outdir, "books1")
        untar(archive, outdir)
    n = shard_books(books_dir, outdir, args.num_shards,
                    num_processes=args.number_of_sharding_processes)
    print("books: {} books -> {} shards".format(n, args.num_shards))


if __name__ == "__main__":
    main()
