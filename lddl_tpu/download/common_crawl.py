"""Common Crawl news downloader.

Reference parity: lddl/download/common_crawl.py, which wraps
``news-please``'s commoncrawl crawler with language/date filters, streams
articles into per-(pid, tid) buffer files flushed every
``--articles-per-write``, and finally aggregates+shards. We keep the same
architecture with the crawler gated behind the optional ``news-please``
package, and support the same resumable multi-node prefix naming so
several hosts can download concurrently into one directory and shard once
at the end (ref: common_crawl.py:114-122,336-344).
"""

import argparse
import os
import threading
import time

from ..utils.fs import expand_outdir_and_mkdir, get_all_files_paths_under
from .utils import _ShardWriter


class ArticleBuffer:
    """Thread-local article buffering with periodic flush, mirroring the
    reference's streaming callback design (common_crawl.py:310-381)."""

    def __init__(self, txt_dir, prefix, articles_per_write=1000):
        self._txt_dir = txt_dir
        self._prefix = prefix
        self._articles_per_write = articles_per_write
        self._local = threading.local()
        os.makedirs(txt_dir, exist_ok=True)

    def _state(self):
        if not hasattr(self._local, "articles"):
            self._local.articles = []
            self._local.warc_count = 0
        return self._local

    def add(self, doc_id, text):
        state = self._state()
        state.articles.append((doc_id, text))
        if len(state.articles) >= self._articles_per_write:
            self.flush()

    def flush(self):
        state = self._state()
        if not state.articles:
            return
        # Unique name per (prefix, pid, tid, counter, time) so many hosts /
        # threads never collide on a shared filesystem.
        name = "{}-{}-{}-{}-{}.txt".format(
            self._prefix, os.getpid(), threading.get_ident(),
            state.warc_count, time.time_ns())
        with open(os.path.join(self._txt_dir, name), "w",
                  encoding="utf-8") as f:
            for doc_id, text in state.articles:
                f.write(doc_id + " " + " ".join(text.split()) + "\n")
        state.articles = []
        state.warc_count += 1


def crawl(outdir, prefix, start_date=None, end_date=None, language="en",
          articles_per_write=1000, continue_process=True):
    try:
        from newsplease.crawler import commoncrawl_crawler
    except ImportError as e:
        raise RuntimeError(
            "the 'news-please' package is required to crawl Common Crawl "
            "(pip install news-please); alternatively aggregate "
            "pre-downloaded article files with --txt-dir") from e
    buffer = ArticleBuffer(os.path.join(outdir, "txt"), prefix,
                           articles_per_write)

    def on_article(article):
        if article.language is not None and article.language != language:
            return
        text = article.maintext or ""
        if not text.strip():
            return
        buffer.add("cc-" + (article.url or "unknown").replace(" ", ""), text)

    def on_warc(*_args, **_kw):
        buffer.flush()

    commoncrawl_crawler.crawl_from_commoncrawl(
        valid_hosts=[],
        warc_files_start_date=start_date,
        warc_files_end_date=end_date,
        callback_on_article_extracted=on_article,
        callback_on_warc_completed=on_warc,
        continue_process=continue_process,
        local_download_dir_warc=os.path.join(outdir, "warc"),
        number_of_extraction_processes=1,
    )
    buffer.flush()


def aggregate_txt(txt_dir, outdir, num_shards):
    """Merge the streamed buffer files (one doc per line already) into the
    standard round-robin source shards."""
    writer = _ShardWriter(outdir, num_shards)
    try:
        for path in sorted(get_all_files_paths_under(txt_dir)):
            with open(path, encoding="utf-8") as f:
                for line in f:
                    parts = line.rstrip("\n").split(None, 1)
                    if len(parts) == 2:
                        writer.write(parts[0], parts[1])
    finally:
        writer.close()
    return writer.num_documents


def attach_args(parser=None):
    parser = parser or argparse.ArgumentParser(
        description="Download Common Crawl news and make source shards")
    parser.add_argument("--outdir", required=True)
    parser.add_argument("--prefix", default="cc",
                        help="unique per host for multi-node downloads")
    parser.add_argument("--num-shards", type=int, default=256)
    parser.add_argument("--start-date", default=None, help="YYYY-MM-DD")
    parser.add_argument("--end-date", default=None, help="YYYY-MM-DD")
    parser.add_argument("--language", default="en")
    parser.add_argument("--articles-per-write", type=int, default=1000)
    parser.add_argument("--txt-dir", default=None,
                        help="skip crawling; aggregate these buffer files")
    parser.add_argument("--crawl-only", action="store_true",
                        help="crawl without the final sharding (for "
                             "multi-node: shard once after all hosts finish)")
    return parser


def _parse_date(s):
    import datetime
    return None if s is None else datetime.datetime.strptime(s, "%Y-%m-%d")


def main(args=None):
    args = args if args is not None else attach_args().parse_args()
    outdir = expand_outdir_and_mkdir(args.outdir)
    txt_dir = args.txt_dir
    if txt_dir is None:
        crawl(outdir, args.prefix,
              start_date=_parse_date(args.start_date),
              end_date=_parse_date(args.end_date),
              language=args.language,
              articles_per_write=args.articles_per_write)
        txt_dir = os.path.join(outdir, "txt")
    if not args.crawl_only:
        n = aggregate_txt(txt_dir, outdir, args.num_shards)
        print("common_crawl: {} articles -> {} shards".format(
            n, args.num_shards))


if __name__ == "__main__":
    main()
