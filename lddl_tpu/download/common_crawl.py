"""Common Crawl news downloader.

Reference parity: lddl/download/common_crawl.py, which wraps
``news-please``'s commoncrawl crawler with language/date/host filters,
streams articles into per-(pid, tid) buffer files flushed every
``--articles-per-write``, and finally aggregates+shards with a process
pool. We keep the same architecture with the crawler gated behind the
optional ``news-please`` package, and support the same resumable
multi-node prefix naming so several hosts can download concurrently into
one directory and shard once at the end
(ref: common_crawl.py:114-122,336-344).

Flag parity with the reference CLI (common_crawl.py:100-260): article and
WARC date windows with custom formats, --valid-hosts, --strict-date,
--reuse-previously-downloaded-files, --continue-after-error,
--show-download-progress, --delete-warc-after-extraction,
--continue-process, --number-of-extraction-processes,
--number-of-sharding-processes, and the skippable --newsplease/--shard
steps.
"""

import argparse
import os
import threading
import time

from ..utils.args import attach_bool_arg
from ..utils.cpus import usable_cpu_count
from ..utils.fs import expand_outdir_and_mkdir, get_all_files_paths_under
from .utils import shard_files_parallel


class ArticleBuffer:
    """Thread-local article buffering with periodic flush, mirroring the
    reference's streaming callback design (common_crawl.py:310-381)."""

    def __init__(self, txt_dir, prefix, articles_per_write=1000):
        self._txt_dir = txt_dir
        self._prefix = prefix
        self._articles_per_write = articles_per_write
        self._local = threading.local()
        os.makedirs(txt_dir, exist_ok=True)

    def _state(self):
        if not hasattr(self._local, "articles"):
            self._local.articles = []
            self._local.warc_count = 0
        return self._local

    def add(self, doc_id, text):
        state = self._state()
        state.articles.append((doc_id, text))
        if len(state.articles) >= self._articles_per_write:
            self.flush()

    def flush(self):
        state = self._state()
        if not state.articles:
            return
        # Unique name per (prefix, pid, tid, counter, time) so many hosts /
        # threads never collide on a shared filesystem.
        name = "{}-{}-{}-{}-{}.txt".format(
            self._prefix, os.getpid(), threading.get_ident(),
            state.warc_count, time.time_ns())
        with open(os.path.join(self._txt_dir, name), "w",
                  encoding="utf-8") as f:
            for doc_id, text in state.articles:
                f.write(doc_id + " " + " ".join(text.split()) + "\n")
        state.articles = []
        state.warc_count += 1


def crawl(outdir, prefix, valid_hosts=(), start_date=None, end_date=None,
          warc_files_start_date=None, warc_files_end_date=None,
          strict_date=True, langs=("en",), articles_per_write=1000,
          reuse_previously_downloaded_files=True, continue_after_error=True,
          show_download_progress=False, delete_warc_after_extraction=True,
          continue_process=True, number_of_extraction_processes=1):
    """Stream Common Crawl news articles into buffer files under
    ``<outdir>/txt`` (ref: common_crawl.py:454-483 for the kwargs)."""
    try:
        from newsplease.crawler import commoncrawl_crawler
    except ImportError as e:
        raise RuntimeError(
            "the 'news-please' package is required to crawl Common Crawl "
            "(pip install news-please); alternatively aggregate "
            "pre-downloaded article files with --txt-dir") from e
    buffer = ArticleBuffer(os.path.join(outdir, "txt"), prefix,
                           articles_per_write)
    langs = set(langs)

    def on_article(article):
        if article.language is not None and article.language not in langs:
            return
        text = article.maintext or ""
        if not text.strip():
            return
        buffer.add("cc-" + (article.url or "unknown").replace(" ", ""), text)

    def on_warc(*_args, **_kw):
        buffer.flush()

    commoncrawl_crawler.crawl_from_commoncrawl(
        on_article,
        callback_on_warc_completed=on_warc,
        valid_hosts=list(valid_hosts),
        start_date=start_date,
        end_date=end_date,
        warc_files_start_date=warc_files_start_date,
        warc_files_end_date=warc_files_end_date,
        strict_date=strict_date,
        reuse_previously_downloaded_files=reuse_previously_downloaded_files,
        local_download_dir_warc=os.path.join(outdir, "warc"),
        continue_after_error=continue_after_error,
        show_download_progress=show_download_progress,
        number_of_extraction_processes=number_of_extraction_processes,
        delete_warc_after_extraction=delete_warc_after_extraction,
        continue_process=continue_process,
        fetch_images=False,
    )
    buffer.flush()


def parse_buffer_file(path):
    """One streamed buffer file (one doc per line already) ->
    (doc_id, text) pairs."""
    with open(path, encoding="utf-8") as f:
        for line in f:
            parts = line.rstrip("\n").split(None, 1)
            if len(parts) == 2:
                yield parts[0], parts[1]


def aggregate_txt(txt_dir, outdir, num_shards, num_processes=None):
    """Merge the streamed buffer files into the standard source shards,
    one pool worker per shard (ref: common_crawl.py:406-427). Every file
    under ``txt_dir`` is aggregated regardless of extension — the
    --txt-dir workflow accepts externally-produced buffer files."""
    return shard_files_parallel(
        get_all_files_paths_under(txt_dir), outdir, num_shards,
        parse_buffer_file, num_processes=num_processes)


def attach_args(parser=None):
    parser = parser or argparse.ArgumentParser(
        description="Download Common Crawl news and make source shards")
    parser.add_argument("--outdir", required=True)
    parser.add_argument("--prefix", default="cc",
                        help="unique per host for multi-node downloads")
    parser.add_argument("--num-shards", type=int, default=256)
    parser.add_argument("--valid-hosts", nargs="*", default=[],
                        help="keep only articles from these hosts "
                             "(default: any host)")
    parser.add_argument("--start-date", default=None,
                        help="keep only articles published after this date")
    parser.add_argument("--start-date-format", default="%Y-%m-%d")
    parser.add_argument("--end-date", default=None,
                        help="keep only articles published before this date")
    parser.add_argument("--end-date-format", default="%Y-%m-%d")
    parser.add_argument("--warc-files-start-date", default=None,
                        help="download only .warc files published after "
                             "this date (controls download volume)")
    parser.add_argument("--warc-files-start-date-format", default="%Y-%m-%d")
    parser.add_argument("--warc-files-end-date", default=None,
                        help="download only .warc files published before "
                             "this date")
    parser.add_argument("--warc-files-end-date-format", default="%Y-%m-%d")
    parser.add_argument("--langs", nargs="+", default=["en"],
                        help="keep only articles in these languages")
    parser.add_argument("--articles-per-write", type=int, default=1000)
    parser.add_argument("--number-of-extraction-processes", type=int,
                        default=usable_cpu_count(),
                        help="newsplease extraction process count")
    parser.add_argument("--number-of-sharding-processes", type=int,
                        default=0,
                        help="process-pool size for the sharding step "
                             "(0 = cpu count)")
    attach_bool_arg(parser, "strict-date", default=True,
                    help_str="discard articles whose published date could "
                             "not be detected when date-filtering")
    attach_bool_arg(parser, "reuse-previously-downloaded-files", default=True,
                    help_str="skip .warc files already on disk (no "
                             "completeness check)")
    attach_bool_arg(parser, "continue-after-error", default=True,
                    help_str="keep downloading when newsplease errors")
    attach_bool_arg(parser, "show-download-progress", default=False,
                    help_str="show .warc download progress")
    attach_bool_arg(parser, "delete-warc-after-extraction", default=True,
                    help_str="delete each .warc once extracted")
    attach_bool_arg(parser, "continue-process", default=True,
                    help_str="resume from fully-downloaded but not fully "
                             "extracted .warc files (filters must not have "
                             "changed)")
    attach_bool_arg(parser, "newsplease", default=True,
                    help_str="run the crawl step")
    attach_bool_arg(parser, "shard", default=True,
                    help_str="run the sharding step (multi-node: shard once "
                             "after all hosts finish crawling)")
    parser.add_argument("--txt-dir", default=None,
                        help="aggregate these buffer files instead of "
                             "<outdir>/txt (implies --no-newsplease)")
    return parser


def _parse_date(s, fmt):
    import datetime
    return None if s is None else datetime.datetime.strptime(s, fmt)


def main(args=None):
    args = args if args is not None else attach_args().parse_args()
    outdir = expand_outdir_and_mkdir(args.outdir)
    txt_dir = args.txt_dir
    if txt_dir is None:
        txt_dir = os.path.join(outdir, "txt")
        if args.newsplease:
            crawl(
                outdir, args.prefix,
                valid_hosts=args.valid_hosts,
                start_date=_parse_date(args.start_date,
                                       args.start_date_format),
                end_date=_parse_date(args.end_date, args.end_date_format),
                warc_files_start_date=_parse_date(
                    args.warc_files_start_date,
                    args.warc_files_start_date_format),
                warc_files_end_date=_parse_date(
                    args.warc_files_end_date,
                    args.warc_files_end_date_format),
                strict_date=args.strict_date,
                langs=args.langs,
                articles_per_write=args.articles_per_write,
                reuse_previously_downloaded_files=(
                    args.reuse_previously_downloaded_files),
                continue_after_error=args.continue_after_error,
                show_download_progress=args.show_download_progress,
                delete_warc_after_extraction=(
                    args.delete_warc_after_extraction),
                continue_process=args.continue_process,
                number_of_extraction_processes=(
                    args.number_of_extraction_processes),
            )
    if args.shard:
        n = aggregate_txt(txt_dir, outdir, args.num_shards,
                          num_processes=args.number_of_sharding_processes)
        print("common_crawl: {} articles -> {} shards".format(
            n, args.num_shards))


if __name__ == "__main__":
    main()
